#!/usr/bin/env python
"""Equivalents of the reference's criterion benches
(/root/reference/benches/bench.rs): advance_and_{load,save} over 1000
single-type components and 3000 disjoint (3 types x 1000 entities),
using the snapshot layer without any session — the reference's
SnapshotPlugin-standalone pattern (bench.rs:49).

Prints one JSON line per benchmark.  Run on any backend:
    python benches/criterion_equiv.py [--iters N]
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

import numpy as np


def build_app(n_types: int, n_entities: int):
    import dataclasses

    import jax.numpy as jnp

    from bevy_ggrs_tpu import App
    from bevy_ggrs_tpu.snapshot import active_mask, spawn_many

    names = ["c%d" % i for i in range(n_types)]
    app = App(num_players=1, capacity=n_types * n_entities,
              input_shape=(), input_dtype=np.uint8)
    for n in names:
        app.rollback_component(n, (), jnp.int32, checksum=True)

    def step(world, ctx):
        comps = dict(world.comps)
        m = active_mask(world)
        for n in names:
            comps[n] = jnp.where(m & world.has[n], comps[n] + 1, comps[n])
        return dataclasses.replace(world, comps=comps)

    def setup(world):
        for i, n in enumerate(names):
            world = spawn_many(
                app.reg, world,
                {n: jnp.zeros((n_entities,), jnp.int32)}, count=n_entities,
            )
        return world

    app.set_step(step)
    app.set_setup(setup)
    return app


def bench(label, fn, iters, passes=3):
    """Median-of-`passes` timed loops (criterion-style; spread in the JSON)."""
    import statistics

    import jax

    jax.block_until_ready(fn())  # warmup/compile
    samples = []
    for _ in range(passes):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn()
        jax.block_until_ready(out)
        samples.append((time.perf_counter() - t0) / iters)
    dt = statistics.median(samples)
    spread = (max(samples) - min(samples)) / dt if dt else 0.0
    print(json.dumps({"metric": label, "value": round(dt * 1e6, 2),
                      "unit": "us/iter", "spread": round(spread, 3)}))
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=100)
    args = ap.parse_args()

    import jax

    from bevy_ggrs_tpu.session.events import InputStatus

    platform = jax.devices()[0].platform
    print(json.dumps({"metric": "platform", "value": platform, "unit": ""}))

    # hierarchy rollback: 1k parent/child chains (BASELINE config 4)
    from bevy_ggrs_tpu.models import box_game  # noqa: F401 (import warms jax)
    import dataclasses
    import jax.numpy as jnp
    from bevy_ggrs_tpu import App
    from bevy_ggrs_tpu.snapshot import Registry, active_mask, spawn_many

    happ = App(num_players=1, capacity=2048, input_shape=(), input_dtype=np.uint8)
    happ.register_hierarchy()
    happ.rollback_component("v", (), jnp.int32, checksum=True)

    def hstep(world, ctx):
        m = active_mask(world) & world.has["v"]
        return dataclasses.replace(
            world,
            comps={**world.comps,
                   "v": jnp.where(m, world.comps["v"] + 1, world.comps["v"])},
        )

    def hsetup(world):
        parents = jnp.full((1024,), -1, jnp.int32)
        world = spawn_many(happ.reg, world,
                           {Registry.PARENT: parents,
                            "v": jnp.zeros((1024,), jnp.int32)}, count=1024)
        children_parents = jnp.arange(1024, dtype=jnp.int32)
        world = spawn_many(happ.reg, world,
                           {Registry.PARENT: children_parents,
                            "v": jnp.zeros((1024,), jnp.int32)}, count=1024)
        return world

    happ.set_step(hstep)
    happ.set_setup(hsetup)
    hworld = happ.init_state()
    hin = np.zeros((8, 1), np.uint8)
    hst = np.zeros((8, 1), np.int8)

    def hier_resim():
        return happ.resim_fn(hworld, hin, hst, 0)[2]

    bench("hierarchy_rollback_1k_chains_8frames", hier_resim, args.iters)

    for n_types, n_entities, tag in ((1, 1000, "1000_components"),
                                     (3, 1000, "3000_disjoint_components")):
        app = build_app(n_types, n_entities)
        world = app.init_state()
        inputs = np.zeros((1, 1), np.uint8)
        status = np.zeros((1, 1), np.int8)

        # advance_and_save: one AdvanceWorld + SaveWorld (state retain is
        # free; the measured cost is the advance + checksum, as one call)
        def adv_save():
            final, stacked, checks = app.resim_fn(world, inputs, status, 0, -1)
            return checks

        bench(f"advance_and_save_{tag}", adv_save, args.iters)

        # advance_and_load: one AdvanceWorld + snapshot restore.  Restore is
        # a host-side pytree rebind; we measure advance + a checksum read of
        # the restored (original) state to keep the device honest.
        final, stacked, checks = app.resim_fn(world, inputs, status, 0, -1)

        def adv_load():
            app.resim_fn(world, inputs, status, 0, -1)
            restored = world  # O(1) rollback: rebind the retained pytree
            return app.checksum_fn(restored)

        bench(f"advance_and_load_{tag}", adv_load, args.iters)


if __name__ == "__main__":
    main()
