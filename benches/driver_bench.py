#!/usr/bin/env python
"""End-to-end DRIVER throughput: full ticks/sec (session + protocol + fused
dispatch) for the synctest oracle and a 2-peer channel-network P2P game.
Complements bench.py (raw resim throughput).  One JSON line per config."""

import json
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

import numpy as np


def bench_synctest(n_entities=2000, ticks=150, check_distance=7):
    from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
    from bevy_ggrs_tpu.models import stress

    app = stress.make_app(n_entities, capacity=n_entities)
    session = SyncTestSession(num_players=2, input_shape=(),
                              input_dtype=np.uint8,
                              check_distance=check_distance)
    runner = GgrsRunner(app, session)
    for _ in range(5):
        runner.tick()  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(ticks):
        runner.tick()
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": f"driver_synctest_ticks_per_sec_{n_entities}ent_cd{check_distance}",
        "value": round(ticks / dt, 1), "unit": "ticks/s",
    }))


def bench_p2p_channel(n_entities=2000, ticks=300):
    from bevy_ggrs_tpu import GgrsRunner, PlayerType, SessionBuilder, SessionState
    from bevy_ggrs_tpu.models import stress
    from bevy_ggrs_tpu.session.channel import ChannelNetwork

    net = ChannelNetwork(latency_hops=2)
    socks = [net.endpoint("a"), net.endpoint("b")]
    runners = []
    for i in range(2):
        app = stress.make_app(n_entities, capacity=n_entities)
        b = (SessionBuilder.for_app(app).with_input_delay(1)
             .with_disconnect_timeout(60.0).with_disconnect_notify_delay(30.0)
             .add_player(PlayerType.LOCAL, i)
             .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a"))
        runners.append(GgrsRunner(app, b.start_p2p_session(socks[i])))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    for _ in range(10):  # warmup
        net.deliver()
        for r in runners:
            r.update(1 / 60)
    t0 = time.perf_counter()
    for _ in range(ticks):
        net.deliver()
        for r in runners:
            r.update(1 / 60)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": f"driver_p2p_pair_ticks_per_sec_{n_entities}ent",
        "value": round(ticks / dt, 1), "unit": "ticks/s",
        "rollbacks": runners[0].stats()["rollbacks"],
    }))


if __name__ == "__main__":
    import jax

    print(json.dumps({"metric": "platform",
                      "value": jax.devices()[0].platform, "unit": ""}))
    bench_synctest()
    bench_p2p_channel()
