#!/usr/bin/env python
"""End-to-end DRIVER throughput: full ticks/sec (session + protocol + fused
dispatch) for the synctest oracle and a 2-peer channel-network P2P game.
Complements bench.py (raw resim throughput).  One JSON line per config."""

import json
import statistics
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

import numpy as np

PASSES = 3  # timed passes per config; median + spread reported


def _timed_passes(fn, ticks):
    """Run `fn(ticks)` PASSES times -> (median ticks/s, spread)."""
    samples = []
    for _ in range(PASSES):
        t0 = time.perf_counter()
        fn(ticks)
        samples.append(ticks / (time.perf_counter() - t0))
    med = statistics.median(samples)
    return med, (max(samples) - min(samples)) / med if med else 0.0


def bench_synctest(n_entities=2000, ticks=150, check_distance=7):
    """Full synctest driver ticks/s.

    Run at two scales: the reference-equivalent small world (2k entities,
    where flat per-transfer link latency dominates on remote-attached
    accelerators) and a game-scale world (100k entities, where device compute
    dominates and the TPU driver pulls ahead of CPU)."""
    from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
    from bevy_ggrs_tpu.models import stress

    app = stress.make_app(n_entities, capacity=n_entities)
    session = SyncTestSession(num_players=2, input_shape=(),
                              input_dtype=np.uint8,
                              check_distance=check_distance)
    runner = GgrsRunner(app, session)
    # warmup must cover the rollback ramp (the full check_distance-deep resim
    # program only compiles once _age reaches check_distance) AND one full
    # deferred-comparison cycle (the batched checksum pull compiles a fused
    # concat program on its first run)
    for _ in range(check_distance + session.compare_interval() + 10):
        runner.tick()

    def run(n):
        for _ in range(n):
            runner.tick()

    d0, u0 = runner.device_dispatches, runner.stats()["host_uploads"]
    med, spread = _timed_passes(run, ticks)
    st = runner.stats()
    print(json.dumps({
        "metric": f"driver_synctest_ticks_per_sec_{n_entities}ent_cd{check_distance}",
        "value": round(med, 1), "unit": "ticks/s",
        "spread": round(spread, 3), "passes": PASSES,
        # timed-region upload census: the packed path holds this at one
        # upload per dispatch (the pre-packing driver issued three)
        "dispatches": runner.device_dispatches - d0,
        "host_uploads": st["host_uploads"] - u0,
        "packed": st["packed"],
    }))


def bench_p2p_channel(n_entities=2000, ticks=300):
    from bevy_ggrs_tpu import GgrsRunner, PlayerType, SessionBuilder, SessionState
    from bevy_ggrs_tpu.models import stress
    from bevy_ggrs_tpu.session.channel import ChannelNetwork

    net = ChannelNetwork(latency_hops=2)
    socks = [net.endpoint("a"), net.endpoint("b")]
    runners = []
    for i in range(2):
        app = stress.make_app(n_entities, capacity=n_entities)
        b = (SessionBuilder.for_app(app).with_input_delay(1)
             .with_disconnect_timeout(60.0).with_disconnect_notify_delay(30.0)
             .add_player(PlayerType.LOCAL, i)
             .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a"))
        runners.append(GgrsRunner(app, b.start_p2p_session(socks[i])))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    for _ in range(30):  # warmup (first ticks compile the advance program)
        net.deliver()
        for r in runners:
            r.update(1 / 60)

    def run(n):
        for _ in range(n):
            net.deliver()
            for r in runners:
                r.update(1 / 60)

    d0, u0 = (runners[0].device_dispatches,
              runners[0].stats()["host_uploads"])
    med, spread = _timed_passes(run, ticks)
    st = runners[0].stats()
    print(json.dumps({
        "metric": f"driver_p2p_pair_ticks_per_sec_{n_entities}ent",
        "value": round(med, 1), "unit": "ticks/s",
        "spread": round(spread, 3), "passes": PASSES,
        "rollbacks": st["rollbacks"],
        "dispatches": runners[0].device_dispatches - d0,
        "host_uploads": st["host_uploads"] - u0,
        "packed": st["packed"],
    }))


def bench_batched_lobbies(m=16, n_entities=2000, ticks=60, check_distance=3):
    """Many-worlds server: M synctest lobbies through ONE BatchedRunner vs
    M serial GgrsRunners.  Metric = aggregate lobby-ticks/s (every lobby
    advances one frame per server tick).  The batched driver issues ~2
    dispatches per server tick regardless of M; the serial baseline issues
    ~2M — the submission-amortization the reference's one-session-per-
    process model (/root/reference/src/lib.rs:79-88) cannot express."""
    import numpy as np

    from bevy_ggrs_tpu import BatchedRunner, GgrsRunner, SyncTestSession
    from bevy_ggrs_tpu.models import stress

    def session():
        return SyncTestSession(num_players=2, input_shape=(),
                               input_dtype=np.uint8,
                               check_distance=check_distance)

    def read_b(lobby, handles):
        return {h: np.uint8((lobby * 5 + h) & 0xF) for h in handles}

    app = stress.make_app(n_entities, capacity=n_entities)
    br = BatchedRunner(app, [session() for _ in range(m)],
                       read_inputs=read_b)
    warm = check_distance + 34
    for _ in range(warm):
        br.tick()

    def run_batched(n):
        for _ in range(n):
            br.tick()

    med_b, spread_b = _timed_passes(run_batched, ticks)
    br.finish()

    serial = [
        GgrsRunner(
            stress.make_app(n_entities, capacity=n_entities),
            session(),
            read_inputs=lambda hs, b=b: read_b(b, hs),
        )
        for b in range(m)
    ]
    for _ in range(warm):
        for r in serial:
            r.tick()

    def run_serial(n):
        for _ in range(n):
            for r in serial:
                r.tick()

    med_s, spread_s = _timed_passes(run_serial, ticks)
    for r in serial:
        r.finish()
    print(json.dumps({
        "metric": f"batched_lobbies_{m}x{n_entities}ent_lobby_ticks_per_sec",
        "value": round(med_b * m, 1), "unit": "lobby-ticks/s",
        "spread": round(spread_b, 3),
        "serial_lobby_ticks_per_sec": round(med_s * m, 1),
        "serial_spread": round(spread_s, 3),
        "batched_vs_serial": round(med_b / med_s, 2) if med_s else None,
        "lobbies": m, "passes": PASSES,
    }))


def bench_speculation_payoff(n_entities=2000, ticks=240):
    """Does speculation pay under jitter?  2-peer box_game-shaped pad over a
    lossy/jittery channel (BASELINE config 5 territory), three driver
    configurations: speculation off / on (per-length programs) / canonical-
    branched (the bit-determinism + hedging shape).  Reports ticks/s plus
    rollback + hit-rate counters so break-even is visible either way."""
    import numpy as np

    from bevy_ggrs_tpu import (
        GgrsRunner,
        PlayerType,
        SessionBuilder,
        SessionState,
        SpeculationConfig,
    )
    from bevy_ggrs_tpu.models import stress
    from bevy_ggrs_tpu.ops.speculation import pad_candidates
    from bevy_ggrs_tpu.session.channel import ChannelNetwork

    def make_pair(mode):
        net = ChannelNetwork(latency_hops=2, jitter_hops=3, loss=0.05, seed=9)
        runners = []
        for i in range(2):
            if mode == "canonical_branched":
                app = stress.make_app(n_entities, capacity=n_entities)
                app.canonical_depth = 16
                app.canonical_branches = 17  # lane 0 + 16 pad hedges
            else:
                app = stress.make_app(n_entities, capacity=n_entities)
            b = (SessionBuilder(input_shape=(), input_dtype=np.uint8)
                 .with_num_players(2).with_input_delay(1)
                 .with_max_prediction_window(8)
                 .with_disconnect_timeout(60.0)
                 .with_disconnect_notify_delay(30.0)
                 .add_player(PlayerType.LOCAL, i)
                 .add_player(PlayerType.REMOTE, 1 - i,
                             "b" if i == 0 else "a"))
            sess = b.start_p2p_session(net.endpoint("a" if i == 0 else "b"))
            spec = None
            if mode in ("on", "canonical_branched"):
                spec = SpeculationConfig(
                    candidates_fn=pad_candidates(2, [1 - i], range(16)),
                    depth=4,
                )
            rng = np.random.default_rng(21 + i)
            runners.append(GgrsRunner(
                app, sess,
                read_inputs=lambda hs, r=rng: {
                    h: np.uint8(r.integers(0, 16)) for h in hs
                },
                speculation=spec,
            ))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            net.deliver()
            for r in runners:
                r.update(0.0)
            if all(r.session.current_state() == SessionState.RUNNING
                   for r in runners):
                break
            time.sleep(0.001)
        for _ in range(40):  # warmup/compile
            net.deliver()
            for r in runners:
                r.update(1 / 60)
        return net, runners

    for mode in ("off", "on", "canonical_branched"):
        net, runners = make_pair(mode)

        def run(n):
            for _ in range(n):
                net.deliver()
                for r in runners:
                    r.update(1 / 60)

        med, spread = _timed_passes(run, ticks)
        s = runners[0].stats()
        print(json.dumps({
            "metric": f"speculation_payoff_{mode}_ticks_per_sec_{n_entities}ent",
            "value": round(med, 1), "unit": "ticks/s",
            "spread": round(spread, 3), "passes": PASSES,
            "rollbacks": s["rollbacks"],
            "resimulated_frames": s["resimulated_frames"],
            "speculation_hits": s["speculation_hits"],
            "speculation_misses": s["speculation_misses"],
            "dispatches": s["device_dispatches"],
        }))


def bench_coalescing(n_entities=2000, frames=240, chunk=4):
    """Catch-up shape: each host update owes `chunk` sim frames.  Measures
    the same frame budget with coalesce_frames=1 (chunk dispatches per
    update) vs coalesce_frames=chunk (one fused k=chunk dispatch) — the
    tick-coalescing lever (docs/dispatch_floor.md).  On CPU the dispatch
    overhead is small so the delta is modest; on a remote-attached device
    each saved dispatch saves ~3 uploads x flat link latency."""
    import numpy as np

    from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
    from bevy_ggrs_tpu.models import stress

    for coalesce in (1, chunk):
        app = stress.make_app(n_entities, capacity=n_entities)
        session = SyncTestSession(
            num_players=2, input_shape=(), input_dtype=np.uint8,
            check_distance=3,
        )
        runner = GgrsRunner(app, session, coalesce_frames=coalesce)
        for _ in range(20):
            runner.update(chunk / 60.0)  # warmup/compile both k shapes
        warm_dispatches, warm_ticks = runner.device_dispatches, runner.ticks

        def run(n, runner=runner):
            for _ in range(n // chunk):
                runner.update(chunk / 60.0)

        med, spread = _timed_passes(run, frames)
        print(json.dumps({
            "metric": (
                f"coalesce_{coalesce}_catchup_frames_per_sec_"
                f"{n_entities}ent_chunk{chunk}"
            ),
            "value": round(med, 1), "unit": "frames/s",
            "spread": round(spread, 3), "passes": PASSES,
            # timed-passes-only counters (warmup excluded): THE dispatch
            # reduction the feature exists to show
            "dispatches": runner.device_dispatches - warm_dispatches,
            "ticks": runner.ticks - warm_ticks,
        }))


def bench_megastep(n_entities=2000, flushes=30, n=8):
    """Run-behind/headless cadence: each host update owes `n` frames over a
    steady predicted p2p pair (constant inputs, no rollbacks).  Measures
    megastep=False (one fused k=n dispatch + per-flush staging) against
    megastep=True (the device-resident N-tick program: one dispatch fed by
    ONE packed upload, snapshot ring resident on device) — the lever that
    kills the dispatch floor for catch-up (docs/architecture.md
    "Megastep")."""
    import numpy as np

    from bevy_ggrs_tpu import (
        GgrsRunner, PlayerType, SessionBuilder, SessionState,
    )
    from bevy_ggrs_tpu.models import stress
    from bevy_ggrs_tpu.session.channel import ChannelNetwork

    for megastep in (False, True):
        net = ChannelNetwork(seed=13)
        socks = [net.endpoint("a"), net.endpoint("b")]
        runners = []
        for i in range(2):
            app = stress.make_app(n_entities, capacity=n_entities)
            b = (SessionBuilder.for_app(app).with_input_delay(2)
                 .with_disconnect_timeout(60.0)
                 .with_disconnect_notify_delay(30.0)
                 .add_player(PlayerType.LOCAL, i)
                 .add_player(PlayerType.REMOTE, 1 - i,
                             "b" if i == 0 else "a"))
            runners.append(GgrsRunner(
                app, b.start_p2p_session(socks[i]),
                read_inputs=lambda hs: {h: np.uint8(0) for h in hs},
                coalesce_frames=n, megastep=megastep,
            ))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            net.deliver()
            for r in runners:
                r.update(0.0)
            if all(r.session.current_state() == SessionState.RUNNING
                   for r in runners):
                break
            time.sleep(0.001)
        for _ in range(8):  # warmup: compile + settle the flush cadence
            net.deliver()
            for r in runners:
                r.update(n / 60.0)

        def run(m, runners=runners, net=net):
            for _ in range(m // n):
                net.deliver()
                for r in runners:
                    r.update(n / 60.0)

        r0 = runners[0]
        d0, u0, f0 = (r0.device_dispatches, r0.stats()["host_uploads"],
                      r0.frame)
        med, spread = _timed_passes(run, flushes * n)
        st = r0.stats()
        print(json.dumps({
            "metric": (
                f"megastep_{'on' if megastep else 'off'}_catchup_"
                f"frames_per_sec_{n_entities}ent_n{n}"
            ),
            "value": round(med, 1), "unit": "frames/s",
            "spread": round(spread, 3), "passes": PASSES,
            "dispatches": r0.device_dispatches - d0,
            "host_uploads": st["host_uploads"] - u0,
            "frames": r0.frame - f0,
            "rollbacks": st["rollbacks"],
        }))
        for r in runners:
            r.finish()


if __name__ == "__main__":
    import argparse

    import jax

    ap = argparse.ArgumentParser()
    ap.add_argument("--speculation-payoff", action="store_true",
                    help="run only the speculation payoff matrix")
    ap.add_argument("--batched-only", action="store_true",
                    help="run only the batched-lobbies comparison")
    ap.add_argument("--coalesce-only", action="store_true",
                    help="run only the tick-coalescing comparison")
    ap.add_argument("--megastep-only", action="store_true",
                    help="run only the megastep on/off comparison")
    args = ap.parse_args()

    print(json.dumps({"metric": "platform",
                      "value": jax.devices()[0].platform, "unit": ""}))
    if args.speculation_payoff:
        bench_speculation_payoff()
    elif args.batched_only:
        bench_batched_lobbies(m=16, n_entities=2000)
        bench_batched_lobbies(m=16, n_entities=10_000, ticks=30)
    elif args.coalesce_only:
        bench_coalescing()
    elif args.megastep_only:
        bench_megastep()
    else:
        bench_synctest()
        bench_synctest(n_entities=100_000, ticks=100)
        bench_p2p_channel()
        bench_p2p_channel(n_entities=100_000, ticks=200)
        bench_batched_lobbies(m=16, n_entities=2000)
        bench_batched_lobbies(m=16, n_entities=10_000, ticks=30)
        bench_coalescing()
        bench_megastep()
