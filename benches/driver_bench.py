#!/usr/bin/env python
"""End-to-end DRIVER throughput: full ticks/sec (session + protocol + fused
dispatch) for the synctest oracle and a 2-peer channel-network P2P game.
Complements bench.py (raw resim throughput).  One JSON line per config."""

import json
import statistics
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

import numpy as np

PASSES = 3  # timed passes per config; median + spread reported


def _timed_passes(fn, ticks):
    """Run `fn(ticks)` PASSES times -> (median ticks/s, spread)."""
    samples = []
    for _ in range(PASSES):
        t0 = time.perf_counter()
        fn(ticks)
        samples.append(ticks / (time.perf_counter() - t0))
    med = statistics.median(samples)
    return med, (max(samples) - min(samples)) / med if med else 0.0


def bench_synctest(n_entities=2000, ticks=150, check_distance=7):
    """Full synctest driver ticks/s.

    Run at two scales: the reference-equivalent small world (2k entities,
    where flat per-transfer link latency dominates on remote-attached
    accelerators) and a game-scale world (100k entities, where device compute
    dominates and the TPU driver pulls ahead of CPU)."""
    from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
    from bevy_ggrs_tpu.models import stress

    app = stress.make_app(n_entities, capacity=n_entities)
    session = SyncTestSession(num_players=2, input_shape=(),
                              input_dtype=np.uint8,
                              check_distance=check_distance)
    runner = GgrsRunner(app, session)
    # warmup must cover the rollback ramp (the full check_distance-deep resim
    # program only compiles once _age reaches check_distance) AND one full
    # deferred-comparison cycle (the batched checksum pull compiles a fused
    # concat program on its first run)
    for _ in range(check_distance + session.compare_interval() + 10):
        runner.tick()

    def run(n):
        for _ in range(n):
            runner.tick()

    med, spread = _timed_passes(run, ticks)
    print(json.dumps({
        "metric": f"driver_synctest_ticks_per_sec_{n_entities}ent_cd{check_distance}",
        "value": round(med, 1), "unit": "ticks/s",
        "spread": round(spread, 3), "passes": PASSES,
    }))


def bench_p2p_channel(n_entities=2000, ticks=300):
    from bevy_ggrs_tpu import GgrsRunner, PlayerType, SessionBuilder, SessionState
    from bevy_ggrs_tpu.models import stress
    from bevy_ggrs_tpu.session.channel import ChannelNetwork

    net = ChannelNetwork(latency_hops=2)
    socks = [net.endpoint("a"), net.endpoint("b")]
    runners = []
    for i in range(2):
        app = stress.make_app(n_entities, capacity=n_entities)
        b = (SessionBuilder.for_app(app).with_input_delay(1)
             .with_disconnect_timeout(60.0).with_disconnect_notify_delay(30.0)
             .add_player(PlayerType.LOCAL, i)
             .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a"))
        runners.append(GgrsRunner(app, b.start_p2p_session(socks[i])))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    for _ in range(30):  # warmup (first ticks compile the advance program)
        net.deliver()
        for r in runners:
            r.update(1 / 60)

    def run(n):
        for _ in range(n):
            net.deliver()
            for r in runners:
                r.update(1 / 60)

    med, spread = _timed_passes(run, ticks)
    print(json.dumps({
        "metric": f"driver_p2p_pair_ticks_per_sec_{n_entities}ent",
        "value": round(med, 1), "unit": "ticks/s",
        "spread": round(spread, 3), "passes": PASSES,
        "rollbacks": runners[0].stats()["rollbacks"],
    }))


if __name__ == "__main__":
    import jax

    print(json.dumps({"metric": "platform",
                      "value": jax.devices()[0].platform, "unit": ""}))
    bench_synctest()
    bench_synctest(n_entities=100_000, ticks=100)
    bench_p2p_channel()
    bench_p2p_channel(n_entities=100_000, ticks=200)
