"""CPU baselines for bench.py — the same rollback-resim semantics as the
device path, implemented in strong vectorized numpy (a stricter baseline than
the reference's per-entity HashMap save/load path, SURVEY §3.6)."""

import numpy as np

GRAVITY = np.float32(-9.8)
BOUND = np.float32(50.0)
DT = np.float32(1.0 / 60.0)

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl(x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _mix32(h, k):
    with np.errstate(over="ignore"):
        k = k * _C1
        k = _rotl(k, 15)
        k = k * _C2
        h = h ^ k
        h = _rotl(h, 13)
        return h * np.uint32(5) + np.uint32(0xE6546B64)


def _fmix32(h):
    with np.errstate(over="ignore"):
        h = h ^ (h >> np.uint32(16))
        h = h * np.uint32(0x85EBCA6B)
        h = h ^ (h >> np.uint32(13))
        h = h * np.uint32(0xC2B2AE35)
        return h ^ (h >> np.uint32(16))


class NumpyStressSim:
    """10k-entity Transform+Velocity sim: advance + checksum + snapshot/frame."""

    def __init__(self, n, seed=0):
        rng = np.random.default_rng(seed)
        self.pos = rng.uniform(-40, 40, (n, 3)).astype(np.float32)
        self.vel = rng.uniform(-5, 5, (n, 3)).astype(np.float32)
        self.ids = np.arange(n, dtype=np.uint32)

    def advance(self):
        self.vel = self.vel + np.array([0, GRAVITY, 0], np.float32) * DT
        self.pos = self.pos + self.vel * DT
        over = np.abs(self.pos) > BOUND
        self.vel = np.where(over, -self.vel, self.vel)
        self.pos = np.clip(self.pos, -BOUND, BOUND)

    def checksum(self):
        parts = []
        for col in (self.pos, self.vel):
            lanes = col.view(np.uint32)
            h = np.full(col.shape[0], 0x9E3779B9, np.uint32)
            for i in range(lanes.shape[1]):
                h = _mix32(h, lanes[:, i])
            h = _fmix32(_mix32(_fmix32(h), self.ids))
            with np.errstate(over="ignore"):
                parts.append(_fmix32(np.sum(h, dtype=np.uint32)))
        return parts[0] ^ parts[1]

    def resim(self, depth):
        """One rollback batch: depth x (advance + save(state copy + checksum))."""
        out = 0
        snapshots = []
        for _ in range(depth):
            self.advance()
            snapshots.append((self.pos.copy(), self.vel.copy()))
            out ^= int(self.checksum())
        return out
