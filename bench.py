#!/usr/bin/env python
"""Benchmark: resimulation throughput at 8-frame rollback x 10k entities.

Headline metric (BASELINE.md): resim frames/sec — one "resim frame" is a full
AdvanceWorld + SaveWorld (state + checksum) of the stress workload (10k
entities, Transform+Velocity).  The device path runs the whole 8-frame
rollback as ONE jit(lax.scan(step)) call emitting every intermediate state
and checksum (what the driver actually dispatches on a rollback request).

Baseline: the same semantics implemented as strong vectorized numpy on the
host CPU — per frame: integrate, bounce, per-entity murmur-fold checksum,
snapshot copy.  This is a *stronger* baseline than the reference's
per-entity-HashMap data path (SURVEY §3.6), implemented in
bench_baselines.py.  vs_baseline = device_fps / numpy_cpu_fps.

Also reported: speculative fan-out throughput (16 branches x 8 frames per
dispatch — the jit(vmap(scan)) north-star shape).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

N_ENTITIES = 10_000
DEPTH = 8
ITERS = 30
SPEC_BRANCHES = 16


def _device_backend_usable(timeout_s: int = 90) -> bool:
    """Probe the default JAX backend in a subprocess (a wedged TPU tunnel can
    hang jax.devices() indefinitely; don't let it take the benchmark down)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _bench_layout(app):
    import jax
    import jax.numpy as jnp
    from bevy_ggrs_tpu.session.events import InputStatus

    world = app.init_state()
    inputs = jax.device_put(jnp.zeros((DEPTH, 2), jnp.uint8))
    status = jax.device_put(
        jnp.full((DEPTH, 2), InputStatus.CONFIRMED, jnp.int8)
    )
    fn = app.resim_fn
    final, stacked, checks = fn(world, inputs, status, 0)
    jax.block_until_ready((final, stacked, checks))
    t0 = time.perf_counter()
    w = world
    for i in range(ITERS):
        w, stacked, checks = fn(w, inputs, status, i * DEPTH)
    jax.block_until_ready(w)
    return DEPTH * ITERS / (time.perf_counter() - t0)


def bench_device():
    import jax
    import jax.numpy as jnp
    from bevy_ggrs_tpu.models import stress, stress_soa
    from bevy_ggrs_tpu.session.events import InputStatus

    # two layouts of the same workload: [N,3] matrices vs per-coordinate [N]
    # scalar columns (lane-friendly on TPU, docs/tpu_notes.md §2)
    fps_mat = _bench_layout(stress.make_app(N_ENTITIES))
    fps_soa = _bench_layout(stress_soa.make_app(N_ENTITIES))
    fps = max(fps_mat, fps_soa)
    layout = "scalar_columns" if fps_soa >= fps_mat else "vec3_columns"

    # speculative fan-out (BASELINE config 5: 4 players x 16 branches x
    # 8 frames over the 10k-entity world) via the CANONICAL branched program
    # — the shipped bit-determinism + hedging dispatch shape
    app = stress.make_app(N_ENTITIES, num_players=4)
    app.canonical_depth = DEPTH
    app.canonical_branches = SPEC_BRANCHES
    world = app.init_state()
    spec = app.branched_fn
    bi = jax.device_put(jnp.zeros((SPEC_BRANCHES, DEPTH, 4), jnp.uint8))
    bs = jax.device_put(jnp.zeros((SPEC_BRANCHES, DEPTH, 4), jnp.int8))
    nr = jax.device_put(jnp.full((SPEC_BRANCHES,), DEPTH, jnp.int32))
    out = spec(world, bi, bs, 0, nr)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for i in range(ITERS):
        out = spec(world, bi, bs, i, nr)
    jax.block_until_ready(out)
    sdt = time.perf_counter() - t0
    spec_fps = SPEC_BRANCHES * DEPTH * ITERS / sdt

    # canonical bit-determinism mode (fixed k=16 program): the safe float
    # configuration's throughput, reported alongside the fast path
    capp = stress.make_app(N_ENTITIES)
    capp.canonical_depth = 16
    fps_canon = _bench_layout(capp)

    platform = jax.devices()[0].platform
    return fps, spec_fps, platform, layout, fps_mat, fps_soa, fps_canon


def bench_numpy_baseline():
    from bench_baselines import NumpyStressSim

    sim = NumpyStressSim(N_ENTITIES, seed=0)
    sim.resim(DEPTH)  # warmup
    t0 = time.perf_counter()
    for _ in range(ITERS):
        sim.resim(DEPTH)
    dt = time.perf_counter() - t0
    return DEPTH * ITERS / dt


def main():
    fallback = False
    if not _device_backend_usable():
        fallback = True
        import jax

        jax.config.update("jax_platforms", "cpu")
    device_fps, spec_fps, platform, layout, fps_mat, fps_soa, fps_canon = bench_device()
    cpu_fps = bench_numpy_baseline()
    result = {
        "metric": f"resim_frames_per_sec_{N_ENTITIES}ent_{DEPTH}frame_rollback",
        "value": round(device_fps, 1),
        "unit": "frames/s",
        "vs_baseline": round(device_fps / cpu_fps, 2),
        "baseline_numpy_cpu_fps": round(cpu_fps, 1),
        "speculative_16branch_resim_fps": round(spec_fps, 1),
        "best_layout": layout,
        "vec3_layout_fps": round(fps_mat, 1),
        "scalar_columns_fps": round(fps_soa, 1),
        "canonical_mode_fps": round(fps_canon, 1),
        "platform": platform,
        "entities": N_ENTITIES,
        "rollback_depth": DEPTH,
        "tpu_fallback_to_cpu": fallback,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
