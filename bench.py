#!/usr/bin/env python
"""Benchmark: resimulation throughput at 8-frame rollback x 10k entities.

Headline metric (BASELINE.md): resim frames/sec — one "resim frame" is a full
AdvanceWorld + SaveWorld (state + checksum) of the stress workload (10k
entities, Transform+Velocity).  The device path runs the whole 8-frame
rollback as ONE jit(lax.scan(step)) call emitting every intermediate state
and checksum (what the driver actually dispatches on a rollback request).

Baseline: the same semantics implemented as strong vectorized numpy on the
host CPU — per frame: integrate, bounce, per-entity murmur-fold checksum,
snapshot copy.  This is a *stronger* baseline than the reference's
per-entity-HashMap data path (SURVEY §3.6), implemented in
bench_baselines.py.  vs_baseline = device_fps / numpy_cpu_fps.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import time

import numpy as np

N_ENTITIES = 10_000
DEPTH = 8
ITERS = 30


def bench_device():
    import jax
    from bevy_ggrs_tpu.models import stress
    from bevy_ggrs_tpu.session.events import InputStatus

    app = stress.make_app(N_ENTITIES)
    world = app.init_state()
    inputs = np.zeros((DEPTH, 2), np.uint8)
    status = np.full((DEPTH, 2), InputStatus.CONFIRMED, np.int8)

    fn = app.resim_fn
    # warmup/compile
    final, stacked, checks = fn(world, inputs, status, 0, -1)
    jax.block_until_ready((final, stacked, checks))

    t0 = time.perf_counter()
    for i in range(ITERS):
        final, stacked, checks = fn(world, inputs, status, i, -1)
    jax.block_until_ready((final, stacked, checks))
    dt = time.perf_counter() - t0
    fps = DEPTH * ITERS / dt
    platform = jax.devices()[0].platform
    return fps, platform


def bench_numpy_baseline():
    from bench_baselines import NumpyStressSim

    sim = NumpyStressSim(N_ENTITIES, seed=0)
    sim.resim(DEPTH)  # warmup
    t0 = time.perf_counter()
    for _ in range(ITERS):
        sim.resim(DEPTH)
    dt = time.perf_counter() - t0
    return DEPTH * ITERS / dt


def main():
    device_fps, platform = bench_device()
    cpu_fps = bench_numpy_baseline()
    result = {
        "metric": f"resim_frames_per_sec_{N_ENTITIES}ent_{DEPTH}frame_rollback",
        "value": round(device_fps, 1),
        "unit": "frames/s",
        "vs_baseline": round(device_fps / cpu_fps, 2),
        "baseline_numpy_cpu_fps": round(cpu_fps, 1),
        "platform": platform,
        "entities": N_ENTITIES,
        "rollback_depth": DEPTH,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
