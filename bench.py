#!/usr/bin/env python
"""Benchmark: resimulation throughput at 8-frame rollback x 10k entities.

Headline metric (BASELINE.md): resim frames/sec — one "resim frame" is a full
AdvanceWorld + SaveWorld (state + checksum) of the stress workload (10k
entities, Transform+Velocity).  The device path runs the whole 8-frame
rollback as ONE jit(lax.scan(step)) call emitting every intermediate state
and checksum (what the driver actually dispatches on a rollback request).

Baseline: the same semantics implemented as strong vectorized numpy on the
host CPU — per frame: integrate, bounce, per-entity murmur-fold checksum,
snapshot copy.  This is a *stronger* baseline than the reference's
per-entity-HashMap data path (SURVEY §3.6), implemented in
bench_baselines.py.  vs_baseline = device_fps / numpy_cpu_fps.

Rigor (criterion-equivalent, /root/reference/benches/bench.rs:47-95): every
timed loop runs REPS times; the reported value is the MEDIAN and the spread
(max-min)/median ships in the JSON so an unstable link shows up as a wide
spread instead of a silently wrong point estimate.

Speculation is reported as lane-0 USEFUL frames/s (one authoritative lane out
of the 16-branch canonical dispatch); raw lane-frames/s (x16) is a secondary
field.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import statistics
import subprocess
import sys
import time

import numpy as np

N_ENTITIES = 10_000
N_ENTITIES_BIG = 100_000
DEPTH = 8
ITERS = 30
REPS = 5
SPEC_BRANCHES = 16

# v5e-class HBM bandwidth for the %BW context figure (the workload is
# bandwidth-bound: elementwise integrate + hash, no matmuls -> MXU ~idle)
HBM_BYTES_PER_SEC = 819e9


def _device_backend_usable(timeout_s: int = 90) -> bool:
    """Probe the default JAX backend in a subprocess (a wedged TPU tunnel can
    hang jax.devices() indefinitely; don't let it take the benchmark down)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _median_spread(samples):
    med = statistics.median(samples)
    spread = (max(samples) - min(samples)) / med if med else 0.0
    return med, spread


def _bench_layout(app, n_players=2):
    """Median-of-REPS resim frames/s for one app; returns (median, spread)."""
    import jax
    from bevy_ggrs_tpu.session.events import InputStatus

    world = app.init_state()
    # host numpy inputs — what the driver actually passes per dispatch
    inputs = np.zeros((DEPTH, n_players), np.uint8)
    status = np.full((DEPTH, n_players), InputStatus.CONFIRMED, np.int8)
    fn = app.resim_fn
    final, stacked, checks = fn(world, inputs, status, 0)
    jax.block_until_ready((final, stacked, checks))
    samples = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        w = world
        for i in range(ITERS):
            w, stacked, checks = fn(w, inputs, status, i * DEPTH)
        jax.block_until_ready(w)
        samples.append(DEPTH * ITERS / (time.perf_counter() - t0))
    return _median_spread(samples)


def _state_bytes(app):
    """Total bytes of the registered component columns (one world copy)."""
    import jax

    world = app.init_state()
    return sum(
        a.size * a.dtype.itemsize for a in jax.tree.leaves(world.comps)
    )


def bench_device():
    import jax
    import jax.numpy as jnp
    from bevy_ggrs_tpu.models import stress, stress_soa

    # two layouts of the same workload: [N,3] matrices vs per-coordinate [N]
    # scalar columns (lane-friendly on TPU, docs/tpu_notes.md §2)
    fps_mat, spread_mat = _bench_layout(stress.make_app(N_ENTITIES))
    fps_soa, spread_soa = _bench_layout(stress_soa.make_app(N_ENTITIES))
    if fps_soa >= fps_mat:
        fps, spread, layout = fps_soa, spread_soa, "scalar_columns"
    else:
        fps, spread, layout = fps_mat, spread_mat, "vec3_columns"

    # game-scale secondary config
    fps_big, spread_big = _bench_layout(
        stress.make_app(N_ENTITIES_BIG, capacity=N_ENTITIES_BIG)
    )

    # bandwidth context: per resim frame the step reads+writes every column
    # and the checksum re-reads them (~3 passes over the world).  Only
    # meaningful against real TPU HBM — null on other platforms.
    sb = _state_bytes(stress.make_app(N_ENTITIES))
    bytes_per_frame = 3 * sb
    platform = jax.devices()[0].platform
    hbm_pct = (
        100.0 * fps * bytes_per_frame / HBM_BYTES_PER_SEC
        if platform == "tpu"
        else None
    )

    # speculative fan-out (BASELINE config 5: 4 players x 16 branches x
    # 8 frames over the 10k-entity world) via the CANONICAL branched program
    # — the shipped bit-determinism + hedging dispatch shape
    app = stress.make_app(N_ENTITIES, num_players=4)
    app.canonical_depth = DEPTH
    app.canonical_branches = SPEC_BRANCHES
    world = app.init_state()
    spec = app.branched_fn
    bi = jax.device_put(jnp.zeros((SPEC_BRANCHES, DEPTH, 4), jnp.uint8))
    bs = jax.device_put(jnp.zeros((SPEC_BRANCHES, DEPTH, 4), jnp.int8))
    nr = jax.device_put(jnp.full((SPEC_BRANCHES,), DEPTH, jnp.int32))
    out = spec(world, bi, bs, 0, nr)
    jax.block_until_ready(out)
    spec_samples = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        for i in range(ITERS):
            out = spec(world, bi, bs, i, nr)
        jax.block_until_ready(out)
        spec_samples.append(DEPTH * ITERS / (time.perf_counter() - t0))
    spec_fps, spec_spread = _median_spread(spec_samples)  # lane-0 useful

    # canonical bit-determinism mode (fixed k=16 program): the safe float
    # configuration's throughput, reported alongside the fast path
    capp = stress.make_app(N_ENTITIES)
    capp.canonical_depth = 16
    fps_canon, spread_canon = _bench_layout(capp)

    return {
        "fps": fps, "spread": spread, "layout": layout,
        "fps_mat": fps_mat, "fps_soa": fps_soa,
        "fps_big": fps_big, "spread_big": spread_big,
        "spec_fps": spec_fps, "spec_spread": spec_spread,
        "fps_canon": fps_canon, "spread_canon": spread_canon,
        "platform": platform, "hbm_pct": hbm_pct,
        "bytes_per_frame": bytes_per_frame,
    }


def bench_numpy_baseline(n_entities=N_ENTITIES, iters=ITERS):
    from bench_baselines import NumpyStressSim

    sim = NumpyStressSim(n_entities, seed=0)
    sim.resim(DEPTH)  # warmup
    samples = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        for _ in range(iters):
            sim.resim(DEPTH)
        samples.append(DEPTH * iters / (time.perf_counter() - t0))
    return _median_spread(samples)


def main():
    fallback = False
    if not _device_backend_usable():
        fallback = True
        import jax

        jax.config.update("jax_platforms", "cpu")
    d = bench_device()
    cpu_fps, cpu_spread = bench_numpy_baseline()
    cpu_fps_big, _ = bench_numpy_baseline(N_ENTITIES_BIG, iters=5)
    result = {
        "metric": f"resim_frames_per_sec_{N_ENTITIES}ent_{DEPTH}frame_rollback",
        "value": round(d["fps"], 1),
        "unit": "frames/s",
        "vs_baseline": round(d["fps"] / cpu_fps, 2),
        "spread": round(d["spread"], 3),
        "reps": REPS,
        "baseline_numpy_cpu_fps": round(cpu_fps, 1),
        "baseline_spread": round(cpu_spread, 3),
        "resim_fps_100k_entities": round(d["fps_big"], 1),
        "resim_fps_100k_spread": round(d["spread_big"], 3),
        "vs_baseline_100k": round(d["fps_big"] / cpu_fps_big, 2),
        "baseline_numpy_cpu_fps_100k": round(cpu_fps_big, 1),
        "speculative_lane0_useful_fps": round(d["spec_fps"], 1),
        "speculative_lane_frames_per_sec": round(
            d["spec_fps"] * SPEC_BRANCHES, 1
        ),
        "speculative_spread": round(d["spec_spread"], 3),
        "best_layout": d["layout"],
        "vec3_layout_fps": round(d["fps_mat"], 1),
        "scalar_columns_fps": round(d["fps_soa"], 1),
        "canonical_mode_fps": round(d["fps_canon"], 1),
        "canonical_spread": round(d["spread_canon"], 3),
        "approx_hbm_bw_util_pct": (
            round(d["hbm_pct"], 2) if d["hbm_pct"] is not None else None
        ),
        "bytes_per_resim_frame": d["bytes_per_frame"],
        "platform": d["platform"],
        "entities": N_ENTITIES,
        "rollback_depth": DEPTH,
        "tpu_fallback_to_cpu": fallback,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
