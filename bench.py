#!/usr/bin/env python
"""Benchmark: resimulation throughput at 8-frame rollback x 10k entities.

Headline metric (BASELINE.md): resim frames/sec — one "resim frame" is a full
AdvanceWorld + SaveWorld (state + checksum) of the stress workload (10k
entities, Transform+Velocity).  The device path runs the whole 8-frame
rollback as ONE jit(lax.scan(step)) call emitting every intermediate state
and checksum (what the driver actually dispatches on a rollback request).

Baseline: the same semantics implemented as strong vectorized numpy on the
host CPU — per frame: integrate, bounce, per-entity murmur-fold checksum,
snapshot copy (bench_baselines.py).  This is a *stronger* baseline than the
reference's per-entity-HashMap data path (SURVEY §3.6).
vs_baseline = device_fps / numpy_cpu_fps, with the exact denominator and the
host it was measured on carried in the JSON (``baseline_host``).

Crash-resilience (the round-3 lesson: a mid-suite tunnel death voided the
round's TPU evidence): the suite is STAGED.  Each metric runs in its own
subprocess with a timeout; every stage result is appended to
``BENCH_PROGRESS.jsonl`` the moment it lands, so a later wedge cannot void
earlier numbers.  Stages are ordered headline-first.  Between stages the
orchestrator re-probes the backend (subprocess probe — a wedged tunnel hangs
``jax.devices()`` indefinitely) and retries once after a cooldown before
falling back to CPU for the REMAINING stages only; ``tpu_fallback_to_cpu``
is true only if the HEADLINE stage itself ran on CPU.

Rigor (criterion-equivalent, /root/reference/benches/bench.rs:47-95): every
timed loop runs REPS times; the reported value is the MEDIAN and the spread
(max-min)/median ships in the JSON so an unstable link shows up as a wide
spread instead of a silently wrong point estimate.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import argparse
import json
import os
import platform as _platform
import statistics
import subprocess
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.abspath(__file__))
PROGRESS_PATH = os.path.join(ROOT, "BENCH_PROGRESS.jsonl")

N_ENTITIES = 10_000
N_BIG = 100_000
N_HUGE = 1_000_000
DEPTH = 8
ITERS = 30
REPS = 5
SPEC_BRANCHES = 16
LOBBIES = 16

# v5e-class HBM bandwidth for the %BW context figure (the workload is
# bandwidth-bound: elementwise integrate + hash, no matmuls -> MXU ~idle)
HBM_BYTES_PER_SEC = 819e9


def _host_tag() -> str:
    """Machine identity for baseline provenance (VERDICT r3 'pin the
    baselines': the numpy denominator varies 3x across hosts)."""
    model = "?"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return f"{_platform.node()}|{model}|ncpu={os.cpu_count()}"


def _median_spread(samples):
    med = statistics.median(samples)
    spread = (max(samples) - min(samples)) / med if med else 0.0
    return med, spread


def _trimmed_mean_spread(samples):
    """Noise-robust rep aggregation: mean over the samples with the single
    min and max dropped (>= 4 reps; below that there is nothing to trim).
    Returns ``(value, spread, spread_raw)`` — ``spread`` over the trimmed
    set (what the vs_baseline ratio rides on), ``spread_raw`` over all reps
    (so a noisy host is still visible in the JSON).  Motivation: one outlier
    rep put ``spread_10k`` at 0.258 vs baseline 0.083 in BENCH_r05, jittering
    the round-to-round ratio; (max-min)/median over all reps amplifies
    exactly the outliers a robust stat should shrug off."""
    _, spread_raw = _median_spread(samples)
    trimmed = sorted(samples)[1:-1] if len(samples) >= 4 else list(samples)
    val = statistics.fmean(trimmed)
    spread = (max(trimmed) - min(trimmed)) / val if val else 0.0
    return val, spread, spread_raw


# --------------------------------------------------------------------------
# stage bodies (run inside `bench.py --stage NAME` subprocesses)
# --------------------------------------------------------------------------

def _stage_setup():
    """Per-stage jax setup: persistent compile cache (stages are separate
    processes; without it each pays the full 20-40s TPU compile)."""
    import jax

    try:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(ROOT, ".jax_cache")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:
        pass  # cache is an optimization; never fail the stage over it
    return jax


def _bench_resim(app, n_players=2, iters=ITERS, reps=REPS, depth=DEPTH,
                 warmup_reps=1):
    """Trimmed-mean-of-reps resim frames/s for one app; returns
    ``(value, spread, spread_raw)`` (see :func:`_trimmed_mean_spread`).

    Uses the DONATING dispatch (what the driver issues): the carried state's
    buffers are reused in place by XLA, so each rep starts from a fresh
    world (the previous rep's was consumed).

    ``warmup_reps`` full UNTIMED reps run first (beyond the compile call):
    the first timed windows used to absorb allocator/cache warmup, which was
    the dominant term of ``spread_10k`` (0.258 in BENCH_r05) — the policy is
    recorded in the stage JSON as ``rep_policy``."""
    import jax
    from bevy_ggrs_tpu.session.events import InputStatus

    fn = getattr(app, "resim_fn_donated", None) or app.resim_fn
    # host numpy inputs — what the driver actually passes per dispatch
    inputs = np.zeros((depth, n_players), np.uint8)
    status = np.full((depth, n_players), InputStatus.CONFIRMED, np.int8)
    warm = app.init_state()
    final, stacked, checks = fn(warm, inputs, status, 0)
    jax.block_until_ready((final, stacked, checks))
    for _ in range(warmup_reps):
        w = app.init_state()
        jax.block_until_ready(w)
        for i in range(iters):
            w, stacked, checks = fn(w, inputs, status, i * depth)
        jax.block_until_ready(w)
    samples = []
    for _ in range(reps):
        w = app.init_state()
        jax.block_until_ready(w)
        t0 = time.perf_counter()
        for i in range(iters):
            w, stacked, checks = fn(w, inputs, status, i * depth)
        jax.block_until_ready(w)
        samples.append(depth * iters / (time.perf_counter() - t0))
    return _trimmed_mean_spread(samples)


def _rep_policy(reps, warmup_reps, iters):
    return {"reps": reps, "warmup_reps": warmup_reps, "iters": iters,
            "stat": "trimmed_mean(drop 1 min + 1 max when reps >= 4)",
            "spread": "(max-min)/mean over the trimmed set",
            "spread_raw": "(max-min)/median over ALL reps"}


def _state_bytes(app):
    """Total bytes of the registered component columns (one world copy)."""
    import jax

    world = app.init_state()
    return sum(a.size * a.dtype.itemsize for a in jax.tree.leaves(world.comps))


def _hbm_pct(fps, bytes_per_frame, plat):
    if plat != "tpu":
        return None
    return round(100.0 * fps * bytes_per_frame / HBM_BYTES_PER_SEC, 2)


def stage_resim10k():
    jax = _stage_setup()
    from bevy_ggrs_tpu.models import stress_soa

    app = stress_soa.make_app(N_ENTITIES)
    fps, spread, spread_raw = _bench_resim(app, warmup_reps=2)
    plat = jax.devices()[0].platform
    bpf = 3 * _state_bytes(app)  # step reads+writes + checksum re-read
    return {
        "fps_10k": round(fps, 1), "spread_10k": round(spread, 3),
        "spread_raw_10k": round(spread_raw, 3),
        "layout_10k": "scalar_columns",
        "rep_policy_10k": _rep_policy(REPS, 2, ITERS),
        "bytes_per_resim_frame": bpf,
        "hbm_pct_10k": _hbm_pct(fps, bpf, plat),
        "platform": plat,
    }


def stage_resim100k():
    jax = _stage_setup()
    from bevy_ggrs_tpu.models import stress_soa

    app = stress_soa.make_app(N_BIG, capacity=N_BIG)
    fps, spread, spread_raw = _bench_resim(app, iters=10)
    plat = jax.devices()[0].platform
    bpf = 3 * _state_bytes(app)
    return {
        "fps_100k": round(fps, 1), "spread_100k": round(spread, 3),
        "spread_raw_100k": round(spread_raw, 3),
        "hbm_pct_100k": _hbm_pct(fps, bpf, plat), "platform": plat,
    }


def stage_resim1m():
    jax = _stage_setup()
    from bevy_ggrs_tpu.models import stress_soa

    app = stress_soa.make_app(N_HUGE, capacity=N_HUGE)
    fps, spread, spread_raw = _bench_resim(app, iters=5, reps=3)
    plat = jax.devices()[0].platform
    bpf = 3 * _state_bytes(app)
    return {
        "fps_1m": round(fps, 1), "spread_1m": round(spread, 3),
        "spread_raw_1m": round(spread_raw, 3),
        "hbm_pct_1m": _hbm_pct(fps, bpf, plat), "platform": plat,
    }


def stage_batched():
    """Many-worlds: M independent 10k-entity lobbies through the shape-
    bucketed wave executor (the server shape that supersedes the reference's
    one-session-per-process model, /root/reference/src/lib.rs:79-88).

    Two parts:

    1. THROUGHPUT — the same 16-lobby x 8-frame x 10k-entity workload as
       BENCH_r05, dispatched through ``BucketedWaveExecutor`` exactly as the
       server does for a full wave: the exact (unmasked) ``unroll=2`` program
       with hoisted checksums and output recycling (previous wave's
       stacked/checks buffers donated back to XLA).  Reports aggregate
       lobby-frames/s (``batched_agg_fps_10k``).
    2. DISPATCH GATE — a real ``BatchedRunner`` drives M lockstep SyncTest
       lobbies at M=4 and M=16 with telemetry on; the stage HARD-FAILS
       (raises -> nonzero exit) unless the steady-state device-dispatch
       count per tick is identical at both lobby counts (O(1) in M).
       Reports ``device_dispatches_per_tick``, the bucket histogram and the
       executor compile count.

    ``BGT_BENCH_SMOKE=1`` shrinks both parts to a seconds-long CI smoke run
    (1 rep; the gate is unchanged — it is the point of the smoke)."""
    jax = _stage_setup()
    from bevy_ggrs_tpu.models import stress_soa
    from bevy_ggrs_tpu.ops.batch import BucketedWaveExecutor, stack_worlds
    from bevy_ggrs_tpu.session.events import InputStatus

    smoke = os.environ.get("BGT_BENCH_SMOKE", "") == "1"
    reps = 1 if smoke else REPS
    iters = 5 if smoke else ITERS
    warmup_reps = 1 if smoke else 2

    app = stress_soa.make_app(N_ENTITIES)
    ex = BucketedWaveExecutor(app, DEPTH, recycle_outputs=True)
    worlds = stack_worlds([app.init_state() for _ in range(LOBBIES)])
    inputs = np.zeros((LOBBIES, DEPTH, 2), np.uint8)
    status = np.full((LOBBIES, DEPTH, 2), InputStatus.CONFIRMED, np.int8)
    frames = np.zeros((LOBBIES,), np.int32)
    ks = [DEPTH] * LOBBIES

    def run_reps(n, timed):
        nonlocal worlds
        out = []
        for _ in range(n):
            t0 = time.perf_counter()
            w = worlds
            for i in range(iters):
                _bkt, w, _stacked, _checks = ex.run_wave(
                    w, inputs, status, frames + i * DEPTH, ks
                )
            jax.block_until_ready(w)
            if timed:
                out.append(
                    LOBBIES * DEPTH * iters / (time.perf_counter() - t0)
                )
        return out

    run_reps(warmup_reps, timed=False)  # compiles + allocator warmup
    agg, spread, spread_raw = _trimmed_mean_spread(run_reps(reps, timed=True))

    gate = _dispatch_flatness_gate(smoke)
    plat = jax.devices()[0].platform
    return {
        "batched_lobbies": LOBBIES,
        "batched_agg_fps_10k": round(agg, 1),
        "batched_per_lobby_fps_10k": round(agg / LOBBIES, 1),
        "batched_spread": round(spread, 3),
        "batched_spread_raw": round(spread_raw, 3),
        "batched_rep_policy": _rep_policy(reps, warmup_reps, iters),
        "batched_executor": {
            "unroll": ex.unroll, "fused_checksums": ex.fused_checksums,
            "recycle_outputs": ex.recycle_outputs,
            "buckets": list(ex.buckets),
        },
        **gate,
        "platform": plat,
    }


def _dispatch_flatness_gate(smoke: bool) -> dict:
    """Drive a real BatchedRunner at M=4 and M=16 lockstep SyncTest lobbies
    and HARD-FAIL unless device dispatches per steady-state tick are equal
    (the O(1)-in-M acceptance gate).  Telemetry is enabled so the reported
    dispatch/compile counts come from the registry, not ad-hoc ints."""
    from bevy_ggrs_tpu import BatchedRunner, SyncTestSession, telemetry
    from bevy_ggrs_tpu.models import stress

    telemetry.disable()
    telemetry.reset()
    telemetry.enable()
    warm, meas = (2, 4) if smoke else (4, 8)
    per_tick = {}
    hist = compiles = jit_entries = None
    for m in (4, 16):
        app = stress.make_app(64, capacity=64)
        sessions = [
            SyncTestSession(num_players=2, input_shape=(),
                            input_dtype=np.uint8, check_distance=2,
                            compare_interval=1)
            for _ in range(m)
        ]
        br = BatchedRunner(
            app, sessions,
            read_inputs=lambda lobby, handles: {
                h: np.uint8((lobby + h) & 0xF) for h in handles
            },
        )
        for _ in range(warm):
            br.tick()
        d0 = br.device_dispatches
        for _ in range(meas):
            br.tick()
        br.finish()
        per_tick[m] = (br.device_dispatches - d0) / meas
        if m == 16:
            s = br.stats()
            hist = s["bucket_hist"]
            compiles = s["program_compiles"]
            jit_entries = s["jit_entries"]
    reg = telemetry.registry()
    tel = {
        "wave_dispatches_total": reg.counter(
            "batched_wave_dispatches_total").value(),
        "program_compiles_total": reg.counter(
            "batched_program_compiles_total").value(),
        "device_dispatches_total": reg.counter(
            "device_dispatches_total").value(),
        "fused_load_dispatches_total": reg.counter(
            "fused_load_dispatches_total").value(),
        "fallback_load_rows_total": reg.counter(
            "fallback_load_rows_total").value(),
    }
    telemetry.disable()
    telemetry.reset()
    if per_tick[4] != per_tick[16]:
        raise RuntimeError(
            "O(1)-dispatch gate FAILED: device dispatches per tick scale "
            f"with lobby count: {per_tick}"
        )
    return {
        "device_dispatches_per_tick": {str(m): v for m, v in per_tick.items()},
        "batched_bucket_hist": {str(k): v for k, v in (hist or {}).items()},
        "batched_program_compiles": compiles,
        "batched_jit_entries": jit_entries,
        "batched_telemetry": tel,
    }


SHARDED_LOBBIES = 16


def stage_sharded():
    """Device-sharded many-worlds executor: lobbies across the mesh.

    Two parts (mirroring :func:`stage_batched`, which this stage extends to
    a ``"lobby"`` device mesh — docs/architecture.md "Many-worlds
    sharding"):

    1. THROUGHPUT — the 16-lobby x 8-frame wave dispatched through a
       1-device ``BucketedWaveExecutor`` (the D=1 arm) and through a
       ``ShardedWaveExecutor`` over every visible device (D=8 virtual CPU
       devices in CI; real chips on a pod slice).  Reports aggregate
       lobby-frames/s per arm with the trimmed-mean rep aggregation, the
       D-speedup ratio, and the per-device buffer residency from the
       executor's ``harvest_shards`` probe (REAL per-device metrics — the
       multichip harness records these, scripts/multichip_bench.py).  On a
       1-core CPU host the D=8 arm measures dispatch overhead, not
       parallel speedup — the ratio is reported, never gated.
    2. FLATNESS GATE — ``BatchedRunner(mesh=...)`` drives M=8 and M=32
       lockstep SyncTest lobbies; the stage HARD-FAILS unless the
       steady-state per-device dispatch count per tick is identical at
       both lobby counts (each SPMD wave is exactly one dispatch per
       device, so runner dispatches == per-device dispatches).

    Needs >= 2 devices; single-device backends report
    ``sharded_skipped`` (the multichip harness marks that run ``skipped``,
    never ``ok``).  ``BGT_BENCH_SMOKE=1`` shrinks to a seconds-long CI run
    with the gate fully armed."""
    # must precede backend init: split the CPU platform into 8 virtual
    # devices (ignored by real TPU backends — the flag only affects the
    # host platform)
    if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    jax = _stage_setup()
    from bevy_ggrs_tpu.models import stress_soa
    from bevy_ggrs_tpu.ops.batch import (
        BucketedWaveExecutor, ShardedWaveExecutor, stack_worlds,
    )
    from bevy_ggrs_tpu.parallel import make_lobby_mesh
    from bevy_ggrs_tpu.session.events import InputStatus

    n_dev = len(jax.devices())
    plat = jax.devices()[0].platform
    if n_dev < 2:
        return {
            "sharded_skipped": f"single-device backend ({plat})",
            "platform": plat,
        }
    smoke = os.environ.get("BGT_BENCH_SMOKE", "") == "1"
    reps = 1 if smoke else REPS
    iters = 5 if smoke else ITERS
    warmup_reps = 1 if smoke else 2
    n_ent = 2000 if smoke else N_ENTITIES

    app = stress_soa.make_app(n_ent)
    mesh = make_lobby_mesh(n_dev)
    inputs = np.zeros((SHARDED_LOBBIES, DEPTH, 2), np.uint8)
    status = np.full((SHARDED_LOBBIES, DEPTH, 2), InputStatus.CONFIRMED,
                     np.int8)
    ks = [DEPTH] * SHARDED_LOBBIES
    frames = np.zeros((SHARDED_LOBBIES,), np.int32)
    arms = {}
    per_device = None
    for d, ex in ((1, BucketedWaveExecutor(app, DEPTH)),
                  (n_dev, ShardedWaveExecutor(app, DEPTH, mesh))):
        worlds = stack_worlds(
            [app.init_state() for _ in range(SHARDED_LOBBIES)]
        )
        samples = []
        for rep in range(warmup_reps + reps):
            t0 = time.perf_counter()
            w = worlds
            for i in range(iters):
                _bkt, w, _stk, _chk = ex.run_wave(
                    w, inputs, status, frames + i * DEPTH, ks
                )
            jax.block_until_ready(w)
            if rep >= warmup_reps:
                samples.append(
                    SHARDED_LOBBIES * DEPTH * iters
                    / (time.perf_counter() - t0)
                )
        agg, spread, spread_raw = _trimmed_mean_spread(samples)
        arms[d] = {"agg_fps": round(agg, 1), "spread": round(spread, 3),
                   "spread_raw": round(spread_raw, 3)}
        if d > 1:
            per_device = ex.harvest_shards(w)

    gate = _sharded_flatness_gate(smoke, mesh)
    return {
        "sharded_lobbies": SHARDED_LOBBIES,
        "sharded_entities": n_ent,
        "sharded_devices": n_dev,
        "sharded_agg_fps_d1": arms[1]["agg_fps"],
        "sharded_agg_fps_dN": arms[n_dev]["agg_fps"],
        "sharded_speedup_dN_vs_d1": round(
            arms[n_dev]["agg_fps"] / arms[1]["agg_fps"], 3
        ),
        "sharded_spread": arms[n_dev]["spread"],
        "sharded_spread_raw": arms[n_dev]["spread_raw"],
        "sharded_rep_policy": _rep_policy(reps, warmup_reps, iters),
        "sharded_per_device": per_device,
        **gate,
        "platform": plat,
    }


def _sharded_flatness_gate(smoke: bool, mesh) -> dict:
    """Drive BatchedRunner(mesh=...) at M=8 and M=32 lockstep SyncTest
    lobbies and HARD-FAIL unless per-device dispatches per steady-state
    tick are equal — the sharded O(1)-in-M acceptance gate (each SPMD wave
    costs exactly one dispatch on every device, so the runner's dispatch
    count IS the per-device count)."""
    from bevy_ggrs_tpu import BatchedRunner, SyncTestSession, telemetry
    from bevy_ggrs_tpu.models import stress

    telemetry.disable()
    telemetry.reset()
    telemetry.enable()
    warm, meas = (2, 4) if smoke else (4, 8)
    per_tick = {}
    sharded_stats = None
    for m in (8, 32):
        app = stress.make_app(64, capacity=64)
        sessions = [
            SyncTestSession(num_players=2, input_shape=(),
                            input_dtype=np.uint8, check_distance=2,
                            compare_interval=1)
            for _ in range(m)
        ]
        br = BatchedRunner(
            app, sessions,
            read_inputs=lambda lobby, handles: {
                h: np.uint8((lobby + h) & 0xF) for h in handles
            },
            mesh=mesh,
        )
        for _ in range(warm):
            br.tick()
        d0 = br.device_dispatches
        for _ in range(meas):
            br.tick()
        br.finish()
        per_tick[m] = (br.device_dispatches - d0) / meas
        if m == 32:
            sharded_stats = br.stats().get("sharded")
    reg = telemetry.registry()
    tel = {
        "sharded_wave_dispatches_total": reg.counter(
            "sharded_wave_dispatches_total").value(),
        "shard_program_compiles_total": reg.counter(
            "shard_program_compiles_total").value(),
        "shard_imbalance_ratio": reg.gauge("shard_imbalance_ratio").value(),
    }
    telemetry.disable()
    telemetry.reset()
    if per_tick[8] != per_tick[32]:
        raise RuntimeError(
            "sharded O(1)-dispatch gate FAILED: per-device dispatches per "
            f"tick scale with lobby count: {per_tick}"
        )
    return {
        "sharded_dispatches_per_device_tick": {
            str(m): v for m, v in per_tick.items()
        },
        "sharded_runner_stats": sharded_stats,
        "sharded_telemetry": tel,
    }


def stage_canonical():
    """Bit-determinism mode (fixed k=16 padded program) throughput."""
    jax = _stage_setup()
    from bevy_ggrs_tpu.models import stress

    app = stress.make_app(N_ENTITIES)
    app.canonical_depth = 16
    fps, spread, spread_raw = _bench_resim(app)
    return {
        "fps_canon": round(fps, 1), "spread_canon": round(spread, 3),
        "spread_raw_canon": round(spread_raw, 3),
        "platform": jax.devices()[0].platform,
    }


SVC_ENTITIES = 65536
SVC_TICKS = 150
SVC_WARM = 60
SVC_MIN_P99_SPEEDUP = 5.0
SVC_MIN_HIT_RATE = 0.5


def _speculation_service_arm(jax, smoke):
    """Speculation 2.0 rollback-servicing comparison (HARD gates).

    Two pipelined p2p pairs run the induced-late-input workload from
    ``stage_netstats`` (``latency_hops=6 > input_delay=1``, inputs flipping
    every 7 ticks): every flip forces a genuine misprediction rollback.
    The MISS pair runs speculation-less, so each of its rollbacks pays the
    full ring-materialize + resim servicing (``rollback_service_ms{path=
    miss}``).  The HIT pair hedges both pads over the flip alphabet
    ({0,1} x {0,1}); its rollbacks are served from the branch cache — a
    bookkeeping ring pop plus device-side selects, zero resim frames
    (``path=hit``).  Both pairs run ``measure_rollback_service=True`` so
    the serviced device work retires inside the timed span (JAX dispatch
    is async; without the block, p99 would time queue insertion, not
    servicing).

    HARD GATES (raise -> nonzero exit):

    1. hit-path p99 is >= 5x lower than miss-path p99;
    2. cache hit rate > 50% with the hold-last+hedged candidate set;
    3. steady census unchanged — the HIT pair's runner-level uploads still
       equal its dispatches (1+1 per fused advance), and every draft
       dispatch rode exactly ONE packed upload;
    4. zero steady-state recompiles — both pairs' measured windows run
       under the armed ``BGT_COMPILE_GUARD`` sentinel, so a fresh program
       compile after warmup raises ``RecompileError`` naming the owner
       and variant kind (the runtime twin of lint rules BGT070/BGT071)."""
    from bevy_ggrs_tpu import telemetry
    from bevy_ggrs_tpu.ops.speculation import (
        SpeculationConfig, pad_candidates,
    )
    from bevy_ggrs_tpu.utils.compile_guard import set_compile_guard

    ticks = 60 if smoke else SVC_TICKS
    warm = 40 if smoke else SVC_WARM
    entities = 65536 if smoke else SVC_ENTITIES

    telemetry.disable()
    telemetry.reset()

    def flipping_inputs(i):
        count = [0]

        def read(handles):
            count[0] += 1
            return {h: np.uint8((count[0] // 7) % 2) for h in handles}

        return read

    def run_pair(tag, **runner_kw):
        # warm runs with telemetry OFF: the warm slice's rollbacks carry
        # the bucket-program compile stalls, which would otherwise land in
        # the servicing histogram and clamp both paths' p99 at compile time
        telemetry.disable()
        net, runners = _make_p2p_pair(
            True, tag, inputs=flipping_inputs, latency_hops=6,
            input_delay=1, entities=entities,
            measure_rollback_service=True, **runner_kw,
        )
        dt = 1.0 / runners[0].app.fps
        _slice_ticks(jax, net, runners, warm, dt)
        telemetry.enable()
        # the warm slice compiled every variant this workload can reach;
        # a fresh compile in the measured window would both skew p99 and
        # betray an unstable cache key — hard-fail via the armed guard
        guard = set_compile_guard(True)
        runners[0].arm_compile_guard()
        try:
            _slice_ticks(jax, net, runners, ticks, dt)
        finally:
            guard.disarm()
            set_compile_guard(False)
        return runners

    # miss pair FIRST: its rollbacks populate path="miss" before the hit
    # pair's (rare) unhedged corrections add theirs
    miss_runners = run_pair("svcm")
    miss_rollbacks = sum(r.rollbacks for r in miss_runners)
    for r in miss_runners:
        r.finish()
    spec = SpeculationConfig(
        candidates_fn=pad_candidates(2, [0, 1], [0, 1]),
        depth=8, max_cached_frames=16,
    )
    hit_runners = run_pair("svch", speculation=spec)
    hits = sum(r.spec_cache.hits for r in hit_runners)
    misses = sum(r.spec_cache.misses for r in hit_runners)
    drafts = sum(r.spec_cache.draft_dispatches for r in hit_runners)
    draft_uploads = sum(r.spec_cache.host_uploads for r in hit_runners)
    served = sum(r.stats()["cache_served_frames"] for r in hit_runners)
    census = [(r.stats()["host_uploads"], r.device_dispatches)
              for r in hit_runners]
    for r in hit_runners:
        r.finish()

    h = telemetry.registry().histogram("rollback_service_ms")
    p99_hit = h.percentile(0.99, path="hit")
    p99_miss = h.percentile(0.99, path="miss")
    p50_hit = h.percentile(0.5, path="hit")
    p50_miss = h.percentile(0.5, path="miss")
    telemetry.disable()
    telemetry.reset()

    if miss_rollbacks == 0 or p99_miss is None:
        raise RuntimeError(
            "speculation gate: the induced-late-input pair forced no "
            "miss-path rollbacks — the comparison is void"
        )
    if hits == 0 or p99_hit is None:
        raise RuntimeError(
            "speculation gate: the hedged pair served no cache hits "
            f"(hits={hits} misses={misses}) — drafts never verified"
        )
    hit_rate = hits / max(hits + misses, 1)
    if hit_rate <= SVC_MIN_HIT_RATE:
        raise RuntimeError(
            f"speculation gate: hit rate {hit_rate:.2f} <= "
            f"{SVC_MIN_HIT_RATE} with hold-last hedged drafts "
            f"(hits={hits} misses={misses})"
        )
    if p99_miss < SVC_MIN_P99_SPEEDUP * p99_hit:
        raise RuntimeError(
            "speculation gate: hit-path rollback servicing p99 "
            f"{p99_hit:.3f}ms is not >= {SVC_MIN_P99_SPEEDUP}x lower than "
            f"miss-path p99 {p99_miss:.3f}ms"
        )
    for u, d in census:
        if u != d:
            raise RuntimeError(
                "speculation gate: the hedged pair broke the steady packed "
                f"census — {u} uploads for {d} dispatches (required 1+1; "
                "drafts must ride their own packed staging)"
            )
    if drafts == 0 or draft_uploads != drafts:
        raise RuntimeError(
            f"speculation gate: {drafts} draft dispatches took "
            f"{draft_uploads} uploads (required: exactly one packed upload "
            "per draft)"
        )
    return {
        "speculation_rollback_service_p99_ms_hit": round(p99_hit, 3),
        "speculation_rollback_service_p99_ms_miss": round(p99_miss, 3),
        "speculation_rollback_service_p50_ms_hit": round(p50_hit, 3),
        "speculation_rollback_service_p50_ms_miss": round(p50_miss, 3),
        "speculation_service_p99_speedup": round(p99_miss / p99_hit, 2),
        "speculation_hit_rate": round(hit_rate, 3),
        "speculation_hits": hits,
        "speculation_misses": misses,
        "speculation_cache_served_frames": served,
        "speculation_draft_dispatches": drafts,
        "speculation_service_entities": entities,
        "speculation_rep_policy": (
            f"two p2p pairs (latency_hops=6, input_delay=1, inputs flip "
            f"every 7 ticks, {entities} entities), {ticks} measured ticks "
            f"after {warm} warm; p99 from rollback_service_ms{{path}} with "
            "in-span block_until_ready (measure_rollback_service)"),
    }


def stage_speculation():
    """BASELINE config 5 (canonical branched throughput: 4 players x 16
    branches x 8 frames over the 10k-entity world, value = lane-0 USEFUL
    frames/s) plus the Speculation 2.0 rollback-servicing arm — hit-path
    vs miss-path ``rollback_service_ms`` p99 under an induced-late-input
    p2p workload, with the >=5x / >50%-hit-rate / census HARD gates
    (:func:`_speculation_service_arm`).  ``BGT_BENCH_SMOKE=1`` skips the
    throughput arm and shrinks the servicing windows; every gate stays
    armed."""
    jax = _stage_setup()
    smoke = os.environ.get("BGT_BENCH_SMOKE", "") == "1"
    out = {}
    if not smoke:
        import jax.numpy as jnp
        from bevy_ggrs_tpu.models import stress

        app = stress.make_app(N_ENTITIES, num_players=4)
        app.canonical_depth = DEPTH
        app.canonical_branches = SPEC_BRANCHES
        world = app.init_state()
        spec = app.branched_fn
        bi = jax.device_put(jnp.zeros((SPEC_BRANCHES, DEPTH, 4), jnp.uint8))
        bs = jax.device_put(jnp.zeros((SPEC_BRANCHES, DEPTH, 4), jnp.int8))
        nr = jax.device_put(jnp.full((SPEC_BRANCHES,), DEPTH, jnp.int32))
        o = spec(world, bi, bs, 0, nr)
        jax.block_until_ready(o)
        samples = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            for i in range(ITERS):
                o = spec(world, bi, bs, i, nr)
            jax.block_until_ready(o)
            samples.append(DEPTH * ITERS / (time.perf_counter() - t0))
        fps, spread = _median_spread(samples)
        out.update({
            "spec_fps": round(fps, 1), "spec_spread": round(spread, 3),
        })
    out.update(_speculation_service_arm(jax, smoke))
    out["platform"] = jax.devices()[0].platform
    return out


def stage_layouts():
    """[N,3] matrix layout at 10k, for the layout-comparison field."""
    jax = _stage_setup()
    from bevy_ggrs_tpu.models import stress

    fps, spread, spread_raw = _bench_resim(stress.make_app(N_ENTITIES))
    return {
        "fps_vec3": round(fps, 1), "spread_vec3": round(spread, 3),
        "spread_raw_vec3": round(spread_raw, 3),
        "platform": jax.devices()[0].platform,
    }


def stage_telemetry():
    """Telemetry cost + content: synctest driver tick throughput with the
    registry disabled vs enabled (the disabled number guards the <2%
    overhead budget — every hot-path call site is one attribute check),
    plus the enabled run's ``telemetry.summary()`` so BENCH output carries
    rollback/resim/speculation counters."""
    jax = _stage_setup()
    from bevy_ggrs_tpu import GgrsRunner, SyncTestSession, telemetry
    from bevy_ggrs_tpu.models import stress

    def run(ticks=200, reps=3):
        # small world + check_distance rollbacks every tick: driver-overhead
        # dominated, the worst case for per-site instrumentation cost
        samples = []
        for _ in range(reps):
            app = stress.make_app(512, capacity=512)
            r = GgrsRunner(app, SyncTestSession(
                num_players=2, check_distance=2, compare_interval=1,
            ))
            for _ in range(10):
                r.tick()  # compile outside the timed window
            t0 = time.perf_counter()
            for _ in range(ticks):
                r.tick()
            jax.block_until_ready(r.world)
            samples.append(ticks / (time.perf_counter() - t0))
            r.finish()
        return _median_spread(samples)[0]

    telemetry.disable()
    telemetry.reset()
    fps_off = run()
    telemetry.enable()
    fps_on = run()
    summ = telemetry.summary()
    telemetry.disable()
    telemetry.reset()
    return {
        "telemetry_fps_disabled": round(fps_off, 1),
        "telemetry_fps_enabled": round(fps_on, 1),
        "telemetry_overhead_enabled_pct": round(
            100.0 * (1.0 - fps_on / fps_off), 2
        ),
        "telemetry_summary": {
            "derived": summ["derived"],
            "timeline_events": summ["timeline_events"],
        },
        "platform": jax.devices()[0].platform,
    }


# small world on purpose: the pipelining win is a fixed per-tick host cost
# (forced checksum device_get + block) that the async harvest removes, so
# the ratio gate needs a tick short enough for that cost to stay visible —
# and small lobbies are exactly where per-tick engine overhead dominates
PIPELINE_ENTITIES = 64
PIPELINE_ROUNDS = 12
PIPELINE_SLICE = 25
PIPELINE_WARM = 50
PIPELINE_MIN_SPEEDUP = 1.15


def _make_p2p_pair(pipelined, tag, inputs=None, latency_hops=None,
                   input_delay=2, entities=PIPELINE_ENTITIES,
                   **runner_kw):
    """Build a two-runner p2p loopback pair over ``ChannelNetwork``.

    Shared by :func:`stage_pipeline` and :func:`stage_netstats`.  ``inputs``
    is an optional factory: ``inputs(i)`` returns the ``read_inputs``
    callable for runner ``i``; the default is constant zeros — the
    misprediction-free workload the pipeline comparison wants.  Pass
    ``latency_hops`` > ``input_delay`` plus varying inputs to make served
    predictions genuinely wrong (rollbacks with attributable blame)."""
    import numpy as np

    from bevy_ggrs_tpu import (
        DesyncDetection, GgrsRunner, PlayerType, SessionBuilder,
    )
    from bevy_ggrs_tpu.models import stress_soa
    from bevy_ggrs_tpu.session.channel import ChannelNetwork
    from bevy_ggrs_tpu.session.events import SessionState

    kw = {} if latency_hops is None else {"latency_hops": latency_hops}
    net = ChannelNetwork(seed=7, **kw)
    socks = [net.endpoint(f"{tag}{i}") for i in range(2)]
    runners = []
    for i in range(2):
        app = stress_soa.make_app(entities)
        builder = (
            SessionBuilder.for_app(app)
            .with_input_delay(input_delay)
            .with_desync_detection_mode(DesyncDetection.on(1))
            .with_eager_checksums(not pipelined)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"{tag}{1 - i}")
        )
        session = builder.start_p2p_session(socks[i])
        read = (inputs(i) if inputs is not None
                else (lambda handles: {h: np.uint8(0) for h in handles}))
        runners.append(GgrsRunner(
            app, session, read_inputs=read, pipeline=pipelined,
            **runner_kw,
        ))
    for _ in range(500):
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING
               for r in runners):
            break
    else:
        raise RuntimeError(f"{tag} pair never reached RUNNING")
    return net, runners


def _slice_ticks(jax, net, runners, ticks, dt):
    """Run one timed slice of ``ticks`` updates over a p2p pair.

    Device work raised by a slice is retired inside it, so the elapsed
    time is attributable: the sync arm already blocks per update, the
    pipelined arm settles its in-flight window here."""
    t0 = time.perf_counter()
    for _ in range(ticks):
        net.deliver()
        for r in runners:
            r.update(dt)
    for r in runners:
        jax.block_until_ready(r._world.comps)
    return time.perf_counter() - t0


def stage_pipeline():
    """Pipelined vs synchronous tick engine over a p2p loopback pair.

    Two two-runner p2p sessions (per-frame desync detection) run over the
    in-memory deterministic ``ChannelNetwork`` — UDP loopback adds scheduler
    jitter that swamps the structural signal on a 1-core host.  The sync arm
    is ``pipeline=False``: a zero-deep in-flight window, every ``update()``
    force-reads the tick checksum and blocks on the world before returning.
    The pipelined arm is the default engine: ahead-of-tick dispatch with
    async checksum readback harvested on a later tick.  The arms alternate
    25-tick timed slices so host-wide drift cancels pairwise; the speedup
    is the median of per-round pipelined/sync ratios — each ratio compares
    adjacent-in-time slices, and the median is immune to the occasional
    contention-mauled round this shared host produces.

    HARD GATES: (1) forced readbacks per steady-state pipelined tick == 0;
    (2) pipelined >= 1.15x sync ticks/sec on CPU.  Both raise."""
    jax = _stage_setup()
    from bevy_ggrs_tpu.snapshot.lazy import readback_stats

    net_s, sync_runners = _make_p2p_pair(False, "sync")
    net_p, pipe_runners = _make_p2p_pair(True, "pipe")
    dt = 1.0 / sync_runners[0].app.fps
    _slice_ticks(jax, net_s, sync_runners, PIPELINE_WARM, dt)
    _slice_ticks(jax, net_p, pipe_runners, PIPELINE_WARM, dt)

    sync_tps, pipe_tps = [], []
    forced_pipe = harvested_pipe = forced_sync = 0
    blocked_sync = 0.0
    for _ in range(PIPELINE_ROUNDS):
        s0 = readback_stats()
        elapsed = _slice_ticks(jax, net_s, sync_runners, PIPELINE_SLICE, dt)
        s1 = readback_stats()
        sync_tps.append(PIPELINE_SLICE / elapsed)
        forced_sync += s1["forced"] - s0["forced"]
        blocked_sync += s1["blocked_seconds"] - s0["blocked_seconds"]
        elapsed = _slice_ticks(jax, net_p, pipe_runners, PIPELINE_SLICE, dt)
        s2 = readback_stats()
        pipe_tps.append(PIPELINE_SLICE / elapsed)
        forced_pipe += s2["forced"] - s1["forced"]
        harvested_pipe += s2["harvested"] - s1["harvested"]

    degrades = sum(r.stats()["pipeline_degrades"] for r in pipe_runners)
    netstats_attached = all(r._netstats is not None
                            for r in (*sync_runners, *pipe_runners))
    netstats_every = (pipe_runners[0]._netstats.every
                      if pipe_runners[0]._netstats is not None else 0)
    for r in (*sync_runners, *pipe_runners):
        r.finish()

    # tick-phase reconciliation over the pipelined arm: the phase timers'
    # cumulative attribution must cover the wall tick time (a phase missing
    # from the catalog would show up here as unattributed residual)
    phase_tot = {}
    phase_ticks = 0
    wall_s = unattr_s = 0.0
    for r in pipe_runners:
        t = r.stats()["phases"]
        phase_ticks += t["ticks"]
        wall_s += t["wall_seconds"]
        unattr_s += t["unattributed_seconds"]
        for k, v in t["phase_seconds"].items():
            phase_tot[k] = phase_tot.get(k, 0.0) + v
    unattr_pct = round(100.0 * unattr_s / wall_s, 2) if wall_s else 0.0

    agg_sync, _, spread_sync_raw = _trimmed_mean_spread(sync_tps)
    agg_pipe, spread_pipe, spread_pipe_raw = _trimmed_mean_spread(pipe_tps)
    ratios = [p / s for p, s in zip(pipe_tps, sync_tps)]
    speedup = statistics.median(ratios)
    platform = jax.devices()[0].platform
    if forced_pipe:
        raise RuntimeError(
            f"pipeline gate: {forced_pipe} forced checksum readbacks in "
            f"{PIPELINE_ROUNDS * PIPELINE_SLICE} steady-state pipelined "
            "ticks (required: 0)"
        )
    if forced_sync == 0:
        raise RuntimeError(
            "pipeline gate: sync arm forced no readbacks — the arms are "
            "not differentiated, the comparison is void"
        )
    if platform == "cpu" and speedup < PIPELINE_MIN_SPEEDUP:
        raise RuntimeError(
            f"pipeline gate: pipelined/sync speedup {speedup:.3f} < "
            f"{PIPELINE_MIN_SPEEDUP} on cpu "
            f"(sync {agg_sync:.1f} vs pipelined {agg_pipe:.1f} ticks/s)"
        )
    if phase_ticks and unattr_pct > 10.0:
        raise RuntimeError(
            f"pipeline gate: {unattr_pct}% of wall tick time is not "
            "attributed to any phase timer (required: <= 10%) — a hot-loop "
            "phase is missing from the telemetry.phases catalog"
        )
    return {
        "pipeline_ticks_per_sec_sync": round(agg_sync, 1),
        "pipeline_ticks_per_sec_pipelined": round(agg_pipe, 1),
        "pipeline_speedup": round(speedup, 3),
        "pipeline_spread": round(spread_pipe, 3),
        "pipeline_spread_raw": round(
            max(spread_sync_raw, spread_pipe_raw), 3),
        "pipeline_forced_steady_state": forced_pipe,
        "pipeline_harvested": harvested_pipe,
        "pipeline_sync_forced": forced_sync,
        "pipeline_sync_blocked_seconds": round(blocked_sync, 4),
        "pipeline_degrades": degrades,
        "pipeline_phase_ms": {
            k: round(v * 1e3, 1) for k, v in phase_tot.items()
        },
        "pipeline_unattributed_pct": unattr_pct,
        "pipeline_netstats": {
            # the per-peer sampler rides the same net_poll phase these
            # arms time; stage_netstats gates its cost, this just records
            # that both arms carried it at the env-resolved cadence
            "sampler_attached": netstats_attached,
            "every": netstats_every,
        },
        "pipeline_entities": PIPELINE_ENTITIES,
        "pipeline_rep_policy": (
            f"paired alternating {PIPELINE_SLICE}-tick slices x "
            f"{PIPELINE_ROUNDS} rounds over ChannelNetwork; speedup = "
            "median of per-round pipe/sync ratios; per-arm ticks/s = "
            "trimmed mean over rounds (drop 1 min + 1 max)"),
        "platform": platform,
    }


UPLOADS_TICKS = 150
UPLOADS_WARM = 40
MEGASTEP_N = 8
MEGASTEP_FLUSHES = 16
SANITIZER_CALLS = 20_000
SANITIZER_MAX_OVERHEAD_PCT = 2.0
SANITIZER_MAX_OFF_US = 1.5
GUARD_MAX_OFF_US = 1.5


def stage_uploads():
    """Host->device upload census: the packed single-upload tick and the
    megastep N-tick flush (docs/dispatch_floor.md "Packed uploads" /
    docs/architecture.md "Megastep").

    Arm 1 is the steady predicted p2p pair from ``stage_pipeline``: with
    constant inputs every tick is one fused advance, so the packed staging
    path must feed it with exactly ONE upload (prefix row + payload rows in
    one int8 buffer) — the pre-packing driver issued three (inputs, status,
    start-frame scalar).  Arm 2 is the same pair with
    ``coalesce_frames=8, megastep=True``: a flush owing exactly 8 frames
    must retire as ONE dispatch fed by ONE upload (the device-resident
    snapshot ring absorbs the loads).  Frame-advantage throttling makes a
    few flushes owe 7 or 9; those are excluded from the gate but counted.
    Arm 3 is the arm-1 pair with ``input_queue=True`` — the rotating
    device-resident staging queue (utils/staging.StagingQueue) that moves
    the transfer-safety block off the tick's critical path; its census must
    stay EXACTLY 1 upload + 1 dispatch per frame, the rotation only changes
    WHEN the block happens.  Arm 4 prices the ``BGT_SANITIZE`` transfer
    sanitizer (utils/staging.TransferSanitizer): a packed tick's whole
    ledger transaction is 4 hook calls (pack_prefix guard_write + commit's
    guard_write/begin/land), microbenchmarked armed and disarmed against
    arm 1's measured tick wall.  Arm 5 prices the ``BGT_COMPILE_GUARD``
    steady-state recompile sentinel (utils/compile_guard) the same way:
    disarmed, a ``notify()`` hook must collapse to one attribute check.

    The arm-1 and arm-3 measured windows additionally run with the compile
    guard ARMED: post-warmup the engine's variant set is closed, so any
    fresh program compile inside the window raises ``RecompileError``
    (naming the owning runner and variant kind — the runtime twin of lint
    rules BGT070/BGT071) straight through the stage.  The megastep arm
    stays unguarded: frame-advantage throttling legitimately compiles
    fresh owed-count programs when the cadence jitters.

    HARD GATES (raise -> nonzero exit):

    1. packed steady state — host uploads == device dispatches == frames
       advanced over the measured window (1 upload + 1 dispatch per tick);
    2. megastep — every flush owing exactly N frames cost exactly 1
       dispatch + 1 upload, and at least half the flushes were exact;
    3. input queue — same 1+1 census as arm 1 over the rotating buffers;
    4. sanitizer — armed, the per-tick transaction is < 2% of the packed
       tick wall; disarmed (the default), < 1.5us per tick (the hooks
       collapse to one attribute check each);
    5. compile guard — zero steady-state recompiles in the guarded
       windows; disarmed, notify() costs < 1.5us (one attribute check).

    ``BGT_BENCH_SMOKE=1`` shrinks the windows; all gates stay armed."""
    jax = _stage_setup()
    from bevy_ggrs_tpu.utils import compile_guard
    from bevy_ggrs_tpu.utils.compile_guard import set_compile_guard

    smoke = os.environ.get("BGT_BENCH_SMOKE", "") == "1"
    ticks = 50 if smoke else UPLOADS_TICKS
    flushes = 8 if smoke else MEGASTEP_FLUSHES

    # -- arm 1: packed per-tick census -----------------------------------
    net, runners = _make_p2p_pair(True, "upl")
    dt = 1.0 / runners[0].app.fps
    _slice_ticks(jax, net, runners, UPLOADS_WARM, dt)
    r0 = runners[0]
    if not r0.stats()["packed"]:
        raise RuntimeError("uploads gate: driver did not take the packed "
                           "staging path")
    d0, u0, f0 = (r0.device_dispatches, r0.stats()["host_uploads"], r0.frame)
    b0 = r0.stats()["packed_upload_bytes"]
    # post-warmup the variant set is closed: a fresh compile inside the
    # measured window is a steady-state recompile and fails the stage
    guard = set_compile_guard(True)
    r0.arm_compile_guard()
    try:
        packed_wall = _slice_ticks(jax, net, runners, ticks, dt)
    finally:
        guard.disarm()
    st = r0.stats()
    packed_d = r0.device_dispatches - d0
    packed_u = st["host_uploads"] - u0
    packed_f = r0.frame - f0
    bytes_per_tick = (st["packed_upload_bytes"] - b0) / max(packed_f, 1)
    for r in runners:
        r.finish()
    if not (packed_d == packed_u == packed_f and packed_f > 0):
        raise RuntimeError(
            f"uploads gate: steady packed tick census broke — {packed_f} "
            f"frames took {packed_d} dispatches and {packed_u} uploads "
            "(required: 1 + 1 per frame)"
        )

    # -- arm 2: megastep flush census -------------------------------------
    net_m, ms_runners = _make_p2p_pair(
        True, "ms", coalesce_frames=MEGASTEP_N, megastep=True,
    )
    m0 = ms_runners[0]
    for _ in range(6):  # settle: predictions confirmed, rings warm
        _slice_ticks(jax, net_m, ms_runners, 1, MEGASTEP_N * dt)
    exact = 0
    total_d = total_u = total_f = 0
    for _ in range(flushes):
        d0, u0, f0 = (m0.device_dispatches, m0.stats()["host_uploads"],
                      m0.frame)
        _slice_ticks(jax, net_m, ms_runners, 1, MEGASTEP_N * dt)
        fd = m0.frame - f0
        dd = m0.device_dispatches - d0
        ud = m0.stats()["host_uploads"] - u0
        total_d += dd
        total_u += ud
        total_f += fd
        if fd == MEGASTEP_N:
            exact += 1
            if dd != 1 or ud != 1:
                raise RuntimeError(
                    f"uploads gate: a megastep flush owing exactly "
                    f"{MEGASTEP_N} frames cost {dd} dispatches and {ud} "
                    "uploads (required: 1 + 1)"
                )
    ms_stats = m0.stats()
    for r in ms_runners:
        r.finish()
    if exact < flushes // 2:
        raise RuntimeError(
            f"uploads gate: only {exact}/{flushes} megastep flushes owed "
            f"exactly {MEGASTEP_N} frames — the cadence never settled, the "
            "census is void"
        )

    # -- arm 3: device-resident input queue census ------------------------
    net_q, q_runners = _make_p2p_pair(True, "upq", input_queue=True)
    _slice_ticks(jax, net_q, q_runners, UPLOADS_WARM, dt)
    q0 = q_runners[0]
    d0, u0, f0 = (q0.device_dispatches, q0.stats()["host_uploads"], q0.frame)
    q0.arm_compile_guard()
    try:
        _slice_ticks(jax, net_q, q_runners, ticks, dt)
    finally:
        guard.disarm()
    stq = q0.stats()
    queue_d = q0.device_dispatches - d0
    queue_u = stq["host_uploads"] - u0
    queue_f = q0.frame - f0
    for r in q_runners:
        r.finish()
    if not (queue_d == queue_u == queue_f and queue_f > 0):
        raise RuntimeError(
            f"uploads gate: input-queue tick census broke — {queue_f} "
            f"frames took {queue_d} dispatches and {queue_u} uploads "
            "(required: 1 + 1 per frame; the rotation must not add or "
            "drop uploads)"
        )

    # -- arm 4: transfer-sanitizer overhead -------------------------------
    from bevy_ggrs_tpu.utils.staging import TransferSanitizer

    calls = 2_000 if smoke else SANITIZER_CALLS
    buf = np.zeros((MEGASTEP_N + 1, 64), np.int8)
    tick_us = packed_wall / (2 * ticks) * 1e6  # both runners share a tick

    def _transaction_us(san):
        t0 = time.perf_counter()
        for _ in range(calls):
            # one packed tick's ledger traffic: pack_prefix's guard, then
            # commit's guard/begin/land
            san.guard_write(buf)
            san.guard_write(buf)
            san.begin(buf)
            san.land(buf)
        return (time.perf_counter() - t0) / calls * 1e6

    san_off_us = _transaction_us(TransferSanitizer(enabled=False))
    san_on_us = _transaction_us(TransferSanitizer(enabled=True))
    san_pct = 100.0 * san_on_us / tick_us if tick_us else 0.0
    if san_pct >= SANITIZER_MAX_OVERHEAD_PCT:
        raise RuntimeError(
            f"uploads gate: BGT_SANITIZE=1 costs {san_on_us:.2f}us per "
            f"packed tick = {san_pct:.3f}% of the {tick_us:.1f}us tick "
            f"(required: < {SANITIZER_MAX_OVERHEAD_PCT}%)"
        )
    if san_off_us >= SANITIZER_MAX_OFF_US:
        raise RuntimeError(
            f"uploads gate: DISABLED sanitizer costs {san_off_us:.2f}us "
            "per packed tick — the default path must stay one attribute "
            f"check per hook (< {SANITIZER_MAX_OFF_US}us)"
        )

    # -- arm 5: compile-guard disarmed overhead ---------------------------
    steady_recompiles = len(guard.steady_compiles)
    set_compile_guard(False)
    t0 = time.perf_counter()
    for _ in range(calls):
        compile_guard.notify("bench", "exact:k1", 0.0)
    guard_off_us = (time.perf_counter() - t0) / calls * 1e6
    if guard_off_us >= GUARD_MAX_OFF_US:
        raise RuntimeError(
            f"uploads gate: DISABLED compile guard costs "
            f"{guard_off_us:.2f}us per notify — the default path must stay "
            f"one attribute check (< {GUARD_MAX_OFF_US}us)"
        )

    return {
        "uploads_per_tick_packed": round(packed_u / packed_f, 3),
        "dispatches_per_tick_packed": round(packed_d / packed_f, 3),
        "packed_upload_bytes_per_tick": round(bytes_per_tick, 1),
        "megastep_frames_per_dispatch": round(total_f / max(total_d, 1), 2),
        "megastep_uploads_per_flush": round(total_u / flushes, 2),
        "megastep_exact_flushes": exact,
        "megastep_flushes": flushes,
        "megastep_n": MEGASTEP_N,
        "megastep_fused_ring_loads": ms_stats["fused_ring_loads"],
        "uploads_per_tick_input_queue": round(queue_u / queue_f, 3),
        "input_queue_landed_free": stq["staging_landed_free"],
        "input_queue_deferred_blocks": stq["staging_deferred_blocks"],
        "sanitizer_on_us_per_tick": round(san_on_us, 3),
        "sanitizer_off_us_per_tick": round(san_off_us, 3),
        "sanitizer_overhead_pct": round(san_pct, 3),
        "compile_guard_steady_recompiles": steady_recompiles,
        "compile_guard_off_us_per_notify": round(guard_off_us, 3),
        "uploads_rep_policy": (
            f"steady p2p census over {ticks} ticks after {UPLOADS_WARM} "
            f"warm; megastep census over {flushes} x {MEGASTEP_N}-frame "
            "flushes, gate on exactly-N flushes only; input-queue census "
            f"over the same {ticks}-tick window with rotating staging"),
        "platform": jax.devices()[0].platform,
    }


NETSTATS_TICKS = 200
NETSTATS_EVERY = 8
NETSTATS_POLL_CALLS = 20_000
NETSTATS_MAX_OVERHEAD_PCT = 1.0


def stage_netstats():
    """Network observability: rollback-cause attribution + per-peer sampler.

    A two-runner p2p pair runs over ``ChannelNetwork(latency_hops=3)`` with
    ``input_delay=1`` and inputs flipping every 7 ticks, so served
    predictions genuinely mispredict: every rollback the drivers execute
    must carry a blamed handle (docs/observability.md "Network & QoS").
    Two timed slices run — sampler disabled, then sampler at ``every=8`` —
    and the sampler's per-call cost is additionally measured by a direct
    ``poll()`` microbenchmark so the overhead gate does not ride on two
    noisy wall-clock slices alone.

    HARD GATES (raise -> nonzero exit):

    1. attribution completeness — sum over handles of
       ``rollback_cause_total`` == ``rollbacks_total``, with > 0 rollbacks
       observed and no ``handle=unknown`` on this fully-attributed path;
    2. sampler cost — the amortized enabled ``poll()`` is < 1% of the
       measured tick wall time, and the disabled ``poll()`` (the
       ``BGT_NETSTATS_EVERY=0`` path) is a sub-microsecond boolean check;
    3. ``/qos`` — an exporter on an ephemeral port serves JSON whose
       ``lobby_qos_score`` values are finite and within [0, 100].

    Reports sampler-off vs sampler-on ticks/s, per-handle cause counts,
    lateness p95, sweep counts and the QoS snapshot.  ``BGT_BENCH_SMOKE=1``
    shrinks the slices; every gate stays armed."""
    jax = _stage_setup()
    import json as _json
    import urllib.request

    from bevy_ggrs_tpu import telemetry
    from bevy_ggrs_tpu.telemetry.netstats import NetStatsSampler

    smoke = os.environ.get("BGT_BENCH_SMOKE", "") == "1"
    ticks = 60 if smoke else NETSTATS_TICKS

    telemetry.disable()
    telemetry.reset()
    telemetry.enable()

    def flipping_inputs(i):
        count = [0]

        def read(handles):
            count[0] += 1
            return {h: np.uint8((count[0] // 7) % 2) for h in handles}

        return read

    net, runners = _make_p2p_pair(
        False, "net", inputs=flipping_inputs, latency_hops=3, input_delay=1,
    )
    dt = 1.0 / runners[0].app.fps
    _slice_ticks(jax, net, runners, ticks, dt)  # warmup (compile + sync)

    for r in runners:
        r._netstats = NetStatsSampler(r.session, every=0)
    wall_off = _slice_ticks(jax, net, runners, ticks, dt)
    for r in runners:
        r._netstats = NetStatsSampler(r.session, every=NETSTATS_EVERY)
    wall_on = _slice_ticks(jax, net, runners, ticks, dt)
    sweeps = sum(r._netstats.samples for r in runners)
    if sweeps == 0:
        raise RuntimeError(
            f"netstats gate: sampler took no sweeps in {ticks} ticks at "
            f"every={NETSTATS_EVERY}"
        )

    # snapshot before the poll() microbenchmark below so the reported
    # sweep/sample counts reflect the timed slices, not the 20k-call loop
    snap = telemetry.registry().snapshot()

    # poll() microbenchmark: disabled must be a boolean-check no-op,
    # enabled amortizes one sweep per `every` calls
    off_sampler = NetStatsSampler(runners[0].session, every=0)
    t0 = time.perf_counter()
    for _ in range(NETSTATS_POLL_CALLS):
        off_sampler.poll()
    poll_off_us = (time.perf_counter() - t0) / NETSTATS_POLL_CALLS * 1e6
    on_sampler = NetStatsSampler(runners[0].session, every=NETSTATS_EVERY)
    t0 = time.perf_counter()
    for _ in range(NETSTATS_POLL_CALLS):
        on_sampler.poll()
    poll_on_us = (time.perf_counter() - t0) / NETSTATS_POLL_CALLS * 1e6
    tick_ms = wall_on / (2 * ticks)  # two runners share each slice tick
    tick_ms *= 1e3
    overhead_pct = 100.0 * poll_on_us / 1e3 / tick_ms if tick_ms else 0.0
    if overhead_pct >= NETSTATS_MAX_OVERHEAD_PCT:
        raise RuntimeError(
            f"netstats gate: enabled sampler poll() costs {poll_on_us:.2f}"
            f"us/call = {overhead_pct:.3f}% of the {tick_ms:.3f}ms tick "
            f"(required: < {NETSTATS_MAX_OVERHEAD_PCT}%)"
        )
    if poll_off_us >= 1.0:
        raise RuntimeError(
            f"netstats gate: DISABLED sampler poll() costs "
            f"{poll_off_us:.2f}us/call — the BGT_NETSTATS_EVERY=0 path "
            "must stay a single boolean check (< 1us)"
        )

    rollbacks = sum(snap.get("rollbacks_total", {}).get(
        "series", {}).values())
    causes = snap.get("rollback_cause_total", {}).get("series", {})
    if rollbacks == 0:
        raise RuntimeError(
            "netstats gate: latency_hops=3 + flipping inputs forced no "
            "rollbacks — the attribution path was never exercised"
        )
    if sum(causes.values()) != rollbacks:
        raise RuntimeError(
            "netstats gate: attribution is incomplete: "
            f"sum(rollback_cause_total)={sum(causes.values())} != "
            f"rollbacks_total={rollbacks} ({causes})"
        )
    if "handle=unknown" in causes:
        raise RuntimeError(
            "netstats gate: p2p mispredictions produced "
            f"handle=unknown blame: {causes}"
        )
    lat = telemetry.registry().histogram("input_lateness_frames")
    lateness_p95 = max(
        (lat.percentile(0.95, handle=h) or 0.0) for h in (0, 1)
    )

    exporter = telemetry.start_http_exporter(port=0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/qos", timeout=10
        ) as resp:
            qos = _json.loads(resp.read().decode("utf-8"))
    finally:
        exporter.close()
    scores = qos.get("lobby_qos_score") or {}
    if not scores or not all(0.0 <= v <= 100.0 for v in scores.values()):
        raise RuntimeError(
            f"netstats gate: /qos served no usable lobby_qos_score: {qos!r}"
        )

    samples_total = sum(snap.get("netstats_samples_total", {}).get(
        "series", {}).values())
    for r in runners:
        r.finish()
    plat = jax.devices()[0].platform
    telemetry.disable()
    telemetry.reset()
    return {
        "netstats_ticks_per_sec_off": round(2 * ticks / wall_off, 1),
        "netstats_ticks_per_sec_on": round(2 * ticks / wall_on, 1),
        "netstats_poll_disabled_us": round(poll_off_us, 3),
        "netstats_poll_enabled_us": round(poll_on_us, 3),
        "netstats_overhead_pct_of_tick": round(overhead_pct, 4),
        "netstats_sweeps": sweeps,
        "netstats_samples_total": samples_total,
        "netstats_every": NETSTATS_EVERY,
        "netstats_rollbacks_total": rollbacks,
        "netstats_rollback_causes": causes,
        "netstats_lateness_p95_frames": round(lateness_p95, 2),
        "netstats_qos": {
            "lobby_qos_score": scores,
            "inputs": {k: v.get("inputs") for k, v in
                       (qos.get("lobbies") or {}).items()},
        },
        "platform": plat,
    }


TRACE_TICKS = 120
TRACE_WARM = 40
TRACE_ROUNDS = 6
TRACE_MAX_OVERHEAD_PCT = 2.0


def stage_trace():
    """Chrome-trace export: recording overhead + well-formedness gates.

    The steady packed p2p pair from ``stage_uploads`` alternates
    recording-OFF slices (flight recorder AND telemetry disabled — the
    one-boolean tick path) with recording-ON slices (both enabled: phase
    timers, timeline instants, per-tick devmem/pipeline counters); the
    final ON window is exported through ``telemetry.chrome_trace()`` and
    structurally validated.  The on/off wall ratios are REPORTED but not
    gated: on this class of shared host the per-round ratio noise
    (±10-15%) dwarfs a 2% budget, so — exactly as ``stage_netstats`` gates
    its sampler on a direct ``poll()`` microbenchmark rather than wall
    slices — the overhead gate here rides on a microbenchmark of the
    per-tick trace-recording transaction: one ``input_send`` timeline
    record x the observed send rate, one ``devmem.note`` x the note rate
    counted live during the final ON slice, plus the ``devmem.total()``
    flight-extras read, divided by the measured ON-slice tick wall.

    HARD GATES (raise -> nonzero exit):

    1. recording overhead — microbenched per-tick trace-recording cost
       <= 2% of the steady packed tick;
    2. disabled path — the same transaction with recording off (no-op
       record + dict-store note) must stay < 1.5us/tick;
    3. census intact — the traced window still ticks at 1 host upload +
       1 device dispatch per frame (recording must not perturb the packed
       steady state);
    4. well-formedness — ``validate_chrome_trace`` returns no problems
       (required keys per event type, non-negative durations, monotonic
       ``ts`` per track, paired flow ids) and the trace carries tick
       slices, phase child slices and the ``device_resident_bytes``
       counter track.

    ``BGT_TRACE_OUT=path`` additionally writes the validated trace
    (``bench.py --trace-out`` sets it).  ``BGT_BENCH_SMOKE=1`` shrinks the
    slices; every gate stays armed."""
    jax = _stage_setup()
    from bevy_ggrs_tpu import telemetry

    smoke = os.environ.get("BGT_BENCH_SMOKE", "") == "1"
    ticks = 30 if smoke else TRACE_TICKS
    rounds = 4 if smoke else TRACE_ROUNDS

    def record(on: bool):
        telemetry.configure_flight(enabled=on)
        if on:
            telemetry.enable()
        else:
            telemetry.disable()

    record(False)
    telemetry.reset()
    # one ring slot per traced tick of the final ON window (2 runners)
    telemetry.configure_flight(maxlen=max(2 * ticks + 64, 256))

    net, runners = _make_p2p_pair(True, "trc")
    dt = 1.0 / runners[0].app.fps
    _slice_ticks(jax, net, runners, TRACE_WARM, dt)
    r0 = runners[0]
    if not r0.stats()["packed"]:
        raise RuntimeError("trace gate: driver did not take the packed "
                           "staging path")

    from bevy_ggrs_tpu.telemetry import devmem

    ratios = []
    census = None
    wall_on = 0.0
    note_calls = 0
    for rnd in range(rounds):
        record(False)
        wall_off = _slice_ticks(jax, net, runners, ticks, dt)
        record(True)
        telemetry.timeline().clear()
        telemetry.flight_recorder().clear()
        d0, u0, f0 = (r0.device_dispatches, r0.stats()["host_uploads"],
                      r0.frame)
        if rnd == rounds - 1:
            # count the per-tick devmem.note rate live during the final
            # ON slice (ring re-notes + staging commits vary by path)
            real_note = devmem.note
            counted = [0]

            def _counting_note(owner, nbytes):
                counted[0] += 1
                real_note(owner, nbytes)

            devmem.note = _counting_note
            try:
                wall_on = _slice_ticks(jax, net, runners, ticks, dt)
            finally:
                devmem.note = real_note
            note_calls = counted[0]
        else:
            wall_on = _slice_ticks(jax, net, runners, ticks, dt)
        census = (r0.device_dispatches - d0,
                  r0.stats()["host_uploads"] - u0, r0.frame - f0)
        ratios.append(wall_on / wall_off)
    wall_ratio = statistics.median(ratios)

    runner_ticks = 2 * ticks  # two runners share each slice tick
    tick_us = wall_on / runner_ticks * 1e6
    sends = sum(1 for e in telemetry.timeline().events()
                if e.get("kind") == "input_send")
    sends_per_tick = sends / runner_ticks
    notes_per_tick = note_calls / runner_ticks

    # the trace itself: the last ON window, validated structurally (built
    # BEFORE the microbenchmark below floods the timeline with probes)
    trace = telemetry.chrome_trace()
    problems = telemetry.validate_chrome_trace(trace)
    evs = trace["traceEvents"]
    tick_slices = [e for e in evs
                   if e.get("ph") == "X" and e.get("name") == "tick"]
    phase_slices = [e for e in evs
                    if e.get("ph") == "X" and e.get("name") == "wave_dispatch"]
    counters = {e["name"] for e in evs if e.get("ph") == "C"}

    # microbenchmark the per-tick trace-recording transaction (recording
    # still ON from the final slice): the stage_netstats poll() pattern
    MICRO = 20000
    t0 = time.perf_counter()
    for i in range(MICRO):
        telemetry.record("input_send", frame=i, handle=0, size_bytes=8)
    rec_us = (time.perf_counter() - t0) / MICRO * 1e6
    t0 = time.perf_counter()
    for i in range(MICRO):
        devmem.note("trcbench/probe", i)
    note_us = (time.perf_counter() - t0) / MICRO * 1e6
    t0 = time.perf_counter()
    for _ in range(MICRO):
        devmem.total()
    total_us = (time.perf_counter() - t0) / MICRO * 1e6
    marginal_us = (rec_us * sends_per_tick + note_us * notes_per_tick
                   + total_us)
    overhead_pct = 100.0 * marginal_us / tick_us if tick_us else 0.0

    # disabled path: record() must be a boolean no-op, note() a dict store
    record(False)
    t0 = time.perf_counter()
    for i in range(MICRO):
        telemetry.record("input_send", frame=i, handle=0, size_bytes=8)
        devmem.note("trcbench/probe", i)
        devmem.total()
    off_us = (time.perf_counter() - t0) / MICRO * 1e6

    for r in runners:
        r.finish()
    record(False)
    telemetry.reset()

    if problems:
        raise RuntimeError(
            f"trace gate: chrome trace is malformed: {problems[:5]}"
        )
    if not tick_slices or not phase_slices:
        raise RuntimeError(
            f"trace gate: traced window exported {len(tick_slices)} tick "
            f"slices and {len(phase_slices)} wave_dispatch slices "
            "(required: > 0 each)"
        )
    if "device_resident_bytes" not in counters:
        raise RuntimeError(
            f"trace gate: no device_resident_bytes counter track "
            f"(counters: {sorted(counters)})"
        )
    dd, ud, fd = census
    if not (dd == ud == fd and fd > 0):
        raise RuntimeError(
            f"trace gate: recording perturbed the packed census — {fd} "
            f"frames took {dd} dispatches and {ud} uploads "
            "(required: 1 + 1 per frame)"
        )
    if overhead_pct > TRACE_MAX_OVERHEAD_PCT:
        raise RuntimeError(
            f"trace gate: per-tick trace-recording transaction costs "
            f"{marginal_us:.2f}us = {overhead_pct:.2f}% of the "
            f"{tick_us:.0f}us steady packed tick (required: <= "
            f"{TRACE_MAX_OVERHEAD_PCT}%; record {rec_us:.2f}us x "
            f"{sends_per_tick:.2f} + note {note_us:.2f}us x "
            f"{notes_per_tick:.2f} + total {total_us:.2f}us)"
        )
    if off_us >= 1.5:
        raise RuntimeError(
            f"trace gate: DISABLED recording transaction costs "
            f"{off_us:.2f}us/tick — the recording-off path must stay a "
            "boolean no-op plus one dict store (< 1.5us)"
        )

    out_path = os.environ.get("BGT_TRACE_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(trace, f, default=repr)

    return {
        "trace_overhead_pct": round(overhead_pct, 3),
        "trace_marginal_us_per_tick": round(marginal_us, 3),
        "trace_record_us": round(rec_us, 3),
        "trace_note_us": round(note_us, 3),
        "trace_disabled_us_per_tick": round(off_us, 3),
        "trace_sends_per_tick": round(sends_per_tick, 2),
        "trace_notes_per_tick": round(notes_per_tick, 2),
        "trace_tick_us": round(tick_us, 1),
        "trace_wall_ratio_on_off": round(wall_ratio, 4),
        "trace_rounds": rounds,
        "trace_events": len(evs),
        "trace_tick_slices": len(tick_slices),
        "trace_counter_tracks": sorted(counters),
        "trace_census_1plus1_frames": fd,
        "trace_rep_policy": (
            f"alternating {ticks}-tick off/on slices x {rounds} rounds; "
            "overhead = microbenched recording transaction / ON tick "
            "wall; wall ratio reported informationally"),
        "platform": jax.devices()[0].platform,
    }


FLEET_TARGET = 1500
FLEET_ENTITIES = 256
FLEET_LOBBIES = 3
FLEET_CAPACITY = 3  # per worker; 2 workers


def stage_fleet():
    """Fleet control plane end-to-end: a real 2-worker fleet (separate
    processes, loopback UDP), synthetic lobbies, a live migration, a
    SIGKILL failover, and a wire admission probe.

    The stage runs a :class:`FleetScheduler` in-process and spawns two
    ``scripts/fleet_worker.py`` subprocesses.  It places ``FLEET_LOBBIES``
    synthetic stress_soa lobbies, fills the remaining slots with inert
    external-mode lobbies to probe admission, live-migrates one lobby
    between workers at ~1/3 of its run, then SIGKILLs the busiest worker at
    mid-game and lets the scheduler fail its lobbies over from their last
    confirmed shipped checkpoints.  Afterwards every lobby's final checksum
    is compared against an in-process control run of the same spec (whole
    stage pinned to CPU: a checksum comparison across different backends
    would compare different float programs, not the fleet).

    HARD GATES (raise -> nonzero exit):

    1. zero desyncs — every lobby's wire-reported final checksum equals
       its unmigrated in-process control, bit for bit, despite one lobby
       migrating live and others failing over from checkpoints;
    2. >= 1 live migration completed (``outcome=ok``) with its downtime
       measured into ``migration_downtime_ms``;
    3. >= 1 failover resumed from the last CONFIRMED shipped frame after
       the SIGKILL (``outcome=failover``, resume frame > 0);
    4. admission control is wire-visible — a SUBMIT into a full fleet
       comes back as a REJECT datagram with reason ``capacity``;
    5. SLO burn semantics under an induced stall — SIGSTOPping a worker
       fires EXACTLY ONE deduplicated ``heartbeat_liveness`` alert
       (``fleet_alert_latency_ms`` = stall to fire; bench-history floor
       metric), which resolves after SIGCONT;
    6. the federated HTTP surface serves under load — ``/fleet``
       (``fleet/v1`` with non-empty series + the active alert), ``/qos``
       (``fleet-qos/v1``) and ``/metrics`` with ``worker=`` labels;
    7. observer ingest stays amortized-free — one heartbeat fold +
       evaluation costs < 1% of the heartbeat cadence;
    8. the 3-participant merged trace (in-process scheduler + both
       workers' ``--trace-out`` dumps, one of them SIGKILLed) passes
       ``validate_chrome_trace`` and carries ``fleet_wire`` instants, a
       ``fleet_alert`` instant, and a cross-pid ``migration`` flow arrow
       whose span matches the measured downtime.

    ``BGT_BENCH_SMOKE=1`` shrinks frames/entities; every gate stays
    armed."""
    # pin the WHOLE stage (scheduler, workers, control resims) to CPU
    # before any jax import: gate 1 compares bits across processes, which
    # is only meaningful when both sides run the same backend
    os.environ["BGT_PLATFORM"] = "cpu"
    from bevy_ggrs_tpu.utils.platform import apply_platform_env

    apply_platform_env()
    jax = _stage_setup()
    import shutil
    import signal
    import tempfile
    import threading
    import urllib.request

    from bevy_ggrs_tpu import telemetry
    from bevy_ggrs_tpu.fleet import (
        FleetClient, FleetScheduler, LobbySim, LobbySpec, checksum_hex,
        FleetObserver, start_fleet_exporter,
    )
    from bevy_ggrs_tpu.fleet.worker import HEARTBEAT_S

    smoke = os.environ.get("BGT_BENCH_SMOKE", "") == "1"
    target = 300 if smoke else FLEET_TARGET
    entities = 32 if smoke else FLEET_ENTITIES
    wait_s = 180 if smoke else 420

    telemetry.enable()
    # generous timeout: even with interleaved heartbeats, one first-step
    # canonical compile on a loaded CI host can stall a worker for seconds.
    # The liveness SLO (1.5 s gap) pages far below this, so the SIGSTOP
    # phase fires an alert without ever tripping a spurious failover.
    sched = FleetScheduler(worker_timeout_s=8.0)
    port = sched.local_addr[1]
    exporter = start_fleet_exporter(sched.observer, port=0)
    trace_dir = tempfile.mkdtemp(prefix="bgt_fleet_trace_")
    procs = {}

    def spawn(wid):
        env = dict(os.environ)
        env["BGT_PLATFORM"] = "cpu"
        # paced to realtime cadence: an unpaced CPU sim clears the whole
        # horizon between two heartbeats, and every phase below (migrate at
        # ~1/3, SIGKILL mid-game) depends on lobbies actually being mid-game
        procs[wid] = subprocess.Popen(
            [sys.executable, os.path.join(ROOT, "scripts", "fleet_worker.py"),
             "--scheduler", f"127.0.0.1:{port}", "--worker-id", wid,
             "--capacity", str(FLEET_CAPACITY), "--ckpt-every", "40",
             "--pace-fps", "240",
             "--trace-out", os.path.join(trace_dir, f"{wid}.trace.json"),
             "--trace-every", "0.5"],
            cwd=ROOT, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def pump_until(cond, timeout_s, what):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            sched.poll()
            if cond():
                return
            time.sleep(0.002)
        raise RuntimeError(f"fleet gate: timed out waiting for {what} "
                           f"(snapshot: {sched.snapshot()['lobbies']})")

    try:
        spawn("wA")
        spawn("wB")
        pump_until(lambda: len(sched.workers) == 2, wait_s,
                   "2 workers to register")

        # the last lobby runs 4x longer so it is provably still mid-game
        # when its worker gets SIGKILLed — otherwise fast CPU sims could
        # finish everything before the kill and the failover gate would
        # pass vacuously
        specs = [
            LobbySpec(lobby_id=f"fl{i}", app="stress_soa",
                      entities=entities, seed=i,
                      target_frames=target * (4 if i == FLEET_LOBBIES - 1
                                              else 1))
            for i in range(FLEET_LOBBIES)
        ]
        for spec in specs:
            ok, who = sched.submit(spec)
            if not ok:
                raise RuntimeError(
                    f"fleet gate: lobby {spec.lobby_id} rejected at "
                    f"placement time ({who})"
                )
        pump_until(
            lambda: all(sched.lobbies[s.lobby_id].state in ("running",
                                                            "done")
                        for s in specs),
            wait_s, "all lobbies placed and running",
        )

        # admission probe: fill every remaining slot with inert external-
        # mode lobbies (no queued inputs -> zero sim cost), then a SUBMIT
        # over the wire must come back REJECT(capacity)
        fleet_slots = 2 * FLEET_CAPACITY
        fillers = [f"fill{i}" for i in range(fleet_slots - FLEET_LOBBIES)]
        for fid in fillers:
            ok, who = sched.submit(LobbySpec(
                lobby_id=fid, app="stress_soa", entities=16,
                target_frames=1_000_000, input_mode="external",
            ))
            if not ok:
                raise RuntimeError(f"fleet gate: filler {fid} rejected "
                                   f"({who}) before the fleet was full")
        cli = FleetClient(sched.local_addr)
        verdict = {}

        def ask():
            verdict["worker"] = cli.submit(
                LobbySpec(lobby_id="overflow", app="stress_soa",
                          entities=16), timeout_s=30,
            )
            verdict["reason"] = cli.last_reject

        t = threading.Thread(target=ask)
        t.start()
        while t.is_alive():
            sched.poll()
            time.sleep(0.002)
        t.join()
        cli.close()
        if verdict["worker"] is not None or verdict["reason"] != "capacity":
            raise RuntimeError(
                "fleet gate: overflow SUBMIT into a full fleet must be "
                "rejected on the wire with reason 'capacity'; got "
                f"worker={verdict['worker']!r} reason={verdict['reason']!r}"
            )
        for fid in fillers:
            sched.drop(fid)

        # live migration at ~1/3 of the short horizon.  The LONG lobby is
        # the one migrated: scheduler-side frame knowledge is heartbeat-
        # lagged, and a post-compile CPU sim can clear a short lobby's
        # whole horizon between two heartbeats — the 4x runway guarantees
        # the migration lands mid-game
        mig = specs[-1].lobby_id
        rec = sched.lobbies[mig]
        pump_until(lambda: rec.frame >= target // 3, wait_s,
                   f"{mig} to reach frame {target // 3}")
        src = rec.worker_id
        if not sched.migrate(mig):
            raise RuntimeError("fleet gate: migrate() found no destination")
        pump_until(
            lambda: rec.state == "running" and rec.worker_id != src,
            wait_s, f"{mig} to finish migrating off {src}",
        )
        if not any(e["event"] == "migrate_ok" for e in sched.events):
            raise RuntimeError(
                "fleet gate: no completed live migration (migrate_ok); "
                f"events: {[e['event'] for e in sched.events]}"
            )

        # SLO burn: SIGSTOP the migration SOURCE (it no longer hosts the
        # long lobby, so it is disjoint from the failover victim below)
        # and require exactly one deduplicated heartbeat_liveness fire,
        # then a resolve after SIGCONT.  The scrapes below run while the
        # alert is active so /fleet provably serves under load.
        stopped = src
        t_stop = time.monotonic()
        os.kill(procs[stopped].pid, signal.SIGSTOP)

        def _liveness_fires():
            return [a for a in sched.observer.alert_history()
                    if a["slo_id"] == "heartbeat_liveness"
                    and a["subject"] == stopped
                    and a["state"] == "fire" and a["t"] >= t_stop]

        pump_until(lambda: bool(_liveness_fires()), wait_s,
                   f"liveness SLO alert on stalled worker {stopped}")
        fleet_alert_latency_ms = (
            (_liveness_fires()[0]["t"] - t_stop) * 1000.0)

        base_url = f"http://127.0.0.1:{exporter.port}"
        with urllib.request.urlopen(base_url + "/fleet", timeout=10) as r:
            fleet_json = json.load(r)
        with urllib.request.urlopen(base_url + "/qos", timeout=10) as r:
            qos_json = json.load(r)
        with urllib.request.urlopen(base_url + "/metrics", timeout=10) as r:
            metrics_text = r.read().decode("utf-8")
        if fleet_json.get("schema") != "fleet/v1":
            raise RuntimeError(
                f"fleet gate: /fleet schema {fleet_json.get('schema')!r} "
                "(required: 'fleet/v1')")
        for wid in ("wA", "wB"):
            series = (fleet_json.get("workers", {}).get(wid) or {}
                      ).get("series") or {}
            if not series.get("qos_floor"):
                raise RuntimeError(
                    f"fleet gate: /fleet carries no qos_floor series for "
                    f"{wid} (workers: {sorted(fleet_json.get('workers', {}))})"
                )
        if not any(a["slo_id"] == "heartbeat_liveness"
                   and a["subject"] == stopped
                   for a in fleet_json.get("alerts", {}).get("active", [])):
            raise RuntimeError(
                "fleet gate: the firing liveness alert is missing from "
                f"/fleet active alerts: {fleet_json.get('alerts')}")
        if qos_json.get("schema") != "fleet-qos/v1":
            raise RuntimeError(
                f"fleet gate: /qos schema {qos_json.get('schema')!r} "
                "(required: 'fleet-qos/v1')")
        if 'worker="wA"' not in metrics_text:
            raise RuntimeError(
                "fleet gate: federated /metrics lacks worker=\"wA\" "
                "labeled series")

        os.kill(procs[stopped].pid, signal.SIGCONT)
        pump_until(
            lambda: not any(a["slo_id"] == "heartbeat_liveness"
                            and a["subject"] == stopped
                            for a in sched.observer.active_alerts()),
            wait_s, f"liveness alert on {stopped} to resolve")
        fires = _liveness_fires()
        if len(fires) != 1:
            raise RuntimeError(
                "fleet gate: SLO dedup broken — expected exactly one "
                f"liveness fire for {stopped} across the stall, got "
                f"{len(fires)}")

        # failover: SIGKILL the worker hosting the long lobby once a
        # confirmed checkpoint for it is in scheduler hands and the game
        # is provably still in progress
        long_rec = sched.lobbies[specs[-1].lobby_id]
        pump_until(
            lambda: long_rec.state == "running"
            and long_rec.ckpt_blob is not None,
            wait_s, "a confirmed checkpoint for the long lobby",
        )
        if long_rec.frame >= specs[-1].target_frames:
            raise RuntimeError(
                "fleet gate: the long lobby finished before the kill — "
                "failover was never exercised"
            )
        victim = long_rec.worker_id
        procs[victim].kill()
        procs[victim].wait()

        pump_until(
            lambda: all(sched.lobbies[s.lobby_id].state == "done"
                        for s in specs),
            wait_s, "all lobbies to finish",
        )

        failovers = [e for e in sched.events if e["event"] == "failover"]
        if not failovers:
            raise RuntimeError(
                "fleet gate: worker was SIGKILLed but no lobby failed "
                f"over; events: {[e['event'] for e in sched.events]}"
            )
        bad = [e for e in failovers if e.get("frame", 0) <= 0]
        if bad:
            raise RuntimeError(
                "fleet gate: failover resumed from frame 0 — the "
                f"confirmed-checkpoint path was not used: {bad}"
            )

        # 3-participant merged trace: capture BEFORE the control resims
        # below so the scheduler-process trace holds no tick frames of its
        # own (workers align to it via fleet_wire send/completion pairs).
        # The victim's file is its last periodic dump — the SIGSTOP phase
        # between the migration and the kill guarantees it spans RESUME_OK.
        sched_trace = telemetry.chrome_trace(process_name="scheduler")
        worker_traces = []
        for wid in ("wA", "wB"):
            with open(os.path.join(trace_dir, f"{wid}.trace.json")) as f:
                worker_traces.append(json.load(f))
        merged = telemetry.merge_traces(sched_trace, *worker_traces)
        errs = telemetry.validate_chrome_trace(merged)
        if errs:
            raise RuntimeError(
                f"fleet gate: merged 3-way trace invalid: {errs[:5]}")
        evs = merged["traceEvents"]
        wire_instants = [e for e in evs if e.get("ph") == "i"
                         and e.get("name") == "fleet_wire"]
        if not wire_instants:
            raise RuntimeError(
                "fleet gate: merged trace carries no fleet_wire instants")
        if not any(e.get("ph") == "i" and e.get("name") == "fleet_alert"
                   for e in evs):
            raise RuntimeError(
                "fleet gate: merged trace carries no fleet_alert instant "
                "(the liveness fire/resolve must land on the scheduler "
                "track)")
        mig_events = [e for e in sched.events if e["event"] == "migrate_ok"]
        downtime = mig_events[-1]["downtime_ms"] if mig_events else None
        flow_starts = {e["id"]: e for e in evs
                       if e.get("cat") == "fleet_flow"
                       and e.get("name") == "migration" and e["ph"] == "s"}
        span_ms = [
            (e["ts"] - flow_starts[e["id"]]["ts"]) / 1000.0
            for e in evs
            if e.get("cat") == "fleet_flow" and e.get("name") == "migration"
            and e["ph"] == "f" and e["id"] in flow_starts
            and e["pid"] != flow_starts[e["id"]]["pid"]
        ]
        if not span_ms:
            raise RuntimeError(
                "fleet gate: no cross-pid CKPT->RESUME_OK migration flow "
                "arrow in the merged trace")
        # the arrow must SPAN the measured downtime: same two endpoints,
        # so agreement is bounded by wire-pair clock-alignment error
        if downtime is None or not any(
                s > 0 and abs(s - downtime) <= 500.0 for s in span_ms):
            raise RuntimeError(
                f"fleet gate: migration arrow span {span_ms} ms does not "
                f"match the measured downtime {downtime} ms (+/- 500 ms)")
        out_path = os.environ.get("BGT_FLEET_TRACE_OUT")
        if out_path:
            with open(out_path, "w") as f:
                json.dump(merged, f, default=repr)

        # observer ingest cost: folding one heartbeat + an SLO evaluation
        # must stay under 1% of the heartbeat cadence (a fleet of hundreds
        # of workers cannot make the scheduler's poll loop miss beats)
        probe = FleetObserver()
        synth = {
            "lobbies": {f"L{i}": {"frame": 0, "state": "running"}
                        for i in range(4)},
            "lobby_qos_score": {f"L{i}": 90.0 - i for i in range(4)},
            "shard_imbalance_ratio": 1.1,
            "device_resident_bytes": 1 << 20,
        }
        n_beats = 50 if smoke else 200
        t0 = time.perf_counter()
        for k in range(n_beats):
            synth["lobbies"]["L0"]["frame"] = k
            probe.ingest_heartbeat(f"w{k % 4}", synth, assigned_slots=3)
            probe.evaluate()
        ingest_ms = (time.perf_counter() - t0) * 1000.0 / n_beats
        ingest_budget_ms = HEARTBEAT_S * 1000.0 * 0.01
        if ingest_ms >= ingest_budget_ms:
            raise RuntimeError(
                f"fleet gate: observer ingest+evaluate costs "
                f"{ingest_ms:.3f} ms/heartbeat (required: < "
                f"{ingest_budget_ms:.2f} ms = 1% of the {HEARTBEAT_S}s "
                "heartbeat cadence)")

        # gate 1: zero desyncs vs in-process controls
        desyncs = []
        for spec in specs:
            control = LobbySim(spec)
            control.run_to(spec.target_frames)
            want = checksum_hex(control.checksum())
            got = sched.lobbies[spec.lobby_id].final_checksum
            if got != want:
                desyncs.append((spec.lobby_id, got, want))
        if desyncs:
            raise RuntimeError(
                f"fleet gate: DESYNC — migrated/failed-over lobbies do not "
                f"match their unmigrated controls: {desyncs}"
            )

        reject_series = (telemetry.summary()["metrics"]
                         .get("admission_rejects_total", {})
                         .get("series", {}))
        alert_series = (telemetry.summary()["metrics"]
                        .get("fleet_alerts_total", {})
                        .get("series", {}))
        return {
            "fleet_workers_spawned": 2,
            "fleet_lobbies": FLEET_LOBBIES,
            "fleet_target_frames": target,
            "fleet_entities": entities,
            "fleet_migrations_ok": len(mig_events),
            "fleet_migration_downtime_ms": downtime,
            "fleet_failovers": len(failovers),
            "fleet_failover_frames": [e.get("frame") for e in failovers],
            "fleet_admission_rejects": reject_series,
            "fleet_desyncs": 0,
            "fleet_alert_latency_ms": round(fleet_alert_latency_ms, 1),
            "fleet_alerts_total": alert_series,
            "fleet_observer_ingest_ms": round(ingest_ms, 4),
            "fleet_observer_ingest_budget_ms": round(ingest_budget_ms, 3),
            "fleet_merged_trace_events": len(evs),
            "fleet_merged_trace_pids": len({e.get("pid") for e in evs
                                            if e.get("pid") is not None}),
            "fleet_wire_instants": len(wire_instants),
            "fleet_migration_arrow_span_ms": [round(s, 1) for s in span_ms],
            "fleet_qos_worst": qos_json.get("worst_lobbies", [])[:3],
            "fleet_events": [e["event"] for e in sched.events],
            "platform": jax.devices()[0].platform,
        }
    finally:
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.kill(p.pid, signal.SIGCONT)  # a stopped child
                except OSError:                     # ignores SIGKILL
                    pass
                p.kill()
                p.wait()
        sched.close()
        exporter.close()
        shutil.rmtree(trace_dir, ignore_errors=True)


STAGES = {
    # headline-first order — a tunnel death after stage k voids nothing
    # before it (round-3 postmortem, VERDICT "what's weak" #1)
    "resim10k": (stage_resim10k, 420),
    "resim100k": (stage_resim100k, 420),
    "resim1m": (stage_resim1m, 600),
    "batched": (stage_batched, 600),
    "sharded": (stage_sharded, 600),
    "canonical": (stage_canonical, 420),
    "speculation": (stage_speculation, 420),
    "layouts": (stage_layouts, 420),
    "telemetry": (stage_telemetry, 420),
    "pipeline": (stage_pipeline, 600),
    "uploads": (stage_uploads, 420),
    "netstats": (stage_netstats, 420),
    "trace": (stage_trace, 420),
    "fleet": (stage_fleet, 900),
}


# --------------------------------------------------------------------------
# numpy baselines (orchestrator process; no device backend involved)
# --------------------------------------------------------------------------

def bench_numpy_baseline(n_entities, iters, reps=REPS):
    from bench_baselines import NumpyStressSim

    sim = NumpyStressSim(n_entities, seed=0)
    sim.resim(DEPTH)  # warmup
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            sim.resim(DEPTH)
        samples.append(DEPTH * iters / (time.perf_counter() - t0))
    return _median_spread(samples)


# --------------------------------------------------------------------------
# orchestrator
# --------------------------------------------------------------------------

def _append_progress(record: dict) -> None:
    record = dict(record, ts=round(time.time(), 1))
    with open(PROGRESS_PATH, "a") as f:
        f.write(json.dumps(record) + "\n")


def _probe_backend(timeout_s: int = 120) -> bool:
    """Probe the default JAX backend in a subprocess (a wedged TPU tunnel can
    hang jax.devices() indefinitely; don't let it take the benchmark down)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True, cwd=ROOT,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


# glibc malloc tuning for the stage subprocesses: the stacked resim outputs
# are tens-of-MB buffers that default-malloc serves via mmap and returns to
# the kernel every free — page-fault churn worth ~8% of batched agg fps on
# the 1-CPU bench host.  Keeping them on the heap (1 GB thresholds) lets
# XLA's allocator actually reuse them.  Recorded in the suite JSON as
# ``bench_env``.
BENCH_MALLOC_ENV = {
    "MALLOC_MMAP_THRESHOLD_": str(1 << 30),
    "MALLOC_TRIM_THRESHOLD_": str(1 << 30),
}


def _run_stage(name: str, timeout_s: int, force_cpu: bool, extra_env=None):
    """Run one stage subprocess; returns (result_dict | None, error | None)."""
    env = dict(os.environ)
    for k, v in BENCH_MALLOC_ENV.items():
        env.setdefault(k, v)
    if extra_env:
        env.update(extra_env)
    if force_cpu:
        env["BGT_PLATFORM"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--stage", name],
            timeout=timeout_s, capture_output=True, text=True, cwd=ROOT,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None, "timeout"
    if r.returncode != 0:
        return None, (r.stderr or "nonzero-exit").strip()[-400:]
    try:
        return json.loads(r.stdout.strip().splitlines()[-1]), None
    except (json.JSONDecodeError, IndexError):
        return None, f"unparseable stage output: {r.stdout[-200:]!r}"


def orchestrate():
    _append_progress({"stage": "suite_start", "host": _host_tag()})
    merged: dict = {}
    stage_platforms: dict = {}
    errors: dict = {}
    force_cpu = False

    if not _probe_backend():
        print("initial backend probe failed; retrying in 45s", file=sys.stderr)
        time.sleep(45)
        if not _probe_backend():
            force_cpu = True
            _append_progress({"stage": "probe", "result": "dead->cpu"})

    for name, (_, timeout_s) in STAGES.items():
        if force_cpu and _probe_backend(60):
            # the tunnel came back mid-suite: reclaim it for the rest
            force_cpu = False
            _append_progress({"stage": "probe", "result": "recovered->tpu"})
        t0 = time.time()
        result, err = _run_stage(name, timeout_s, force_cpu)
        if result is None and not force_cpu:
            # recovery path — distinguish "tunnel died" (finish remaining
            # stages on CPU) from "this stage is broken on a healthy
            # backend" (CPU-fallback THIS stage only, keep TPU for the rest)
            print(f"stage {name} failed ({err}); probing tunnel",
                  file=sys.stderr)
            _append_progress({"stage": name, "error": err})
            if _probe_backend():
                result, err = _run_stage(name, timeout_s, force_cpu=False)
            else:
                time.sleep(45)
                if _probe_backend():
                    result, err = _run_stage(name, timeout_s, force_cpu=False)
            if result is None:
                if _probe_backend(60):
                    _append_progress(
                        {"stage": name, "note": "stage-only cpu fallback"}
                    )
                    result, err = _run_stage(name, timeout_s, force_cpu=True)
                else:
                    force_cpu = True
                    _append_progress({"stage": "probe", "result": "dead->cpu"})
                    result, err = _run_stage(name, timeout_s, force_cpu=True)
        elif result is None and force_cpu:
            _append_progress({"stage": name, "error": err})
        if result is None:
            errors[name] = err
            continue
        stage_platforms[name] = result.pop("platform", "cpu")
        merged.update(result)
        _append_progress({
            "stage": name, "platform": stage_platforms[name],
            "secs": round(time.time() - t0, 1), **result,
        })
        print(f"stage {name} [{stage_platforms[name]}] "
              f"({time.time() - t0:.0f}s): {result}", file=sys.stderr)

    # numpy baselines — host CPU, no tunnel exposure, machine-tagged
    base10k, base10k_sp = bench_numpy_baseline(N_ENTITIES, iters=ITERS)
    base100k, _ = bench_numpy_baseline(N_BIG, iters=5, reps=3)
    base1m, _ = bench_numpy_baseline(N_HUGE, iters=1, reps=2)
    _append_progress({
        "stage": "baselines", "host": _host_tag(),
        "numpy_fps_10k": round(base10k, 1),
        "numpy_fps_100k": round(base100k, 1),
        "numpy_fps_1m": round(base1m, 1),
    })

    fps10k = merged.get("fps_10k")
    fpsvec3 = merged.get("fps_vec3")
    if fps10k is not None and fpsvec3 is not None and fpsvec3 > fps10k:
        value, spread, layout = fpsvec3, merged["spread_vec3"], "vec3_columns"
    else:
        value = fps10k
        spread = merged.get("spread_10k")
        layout = merged.get("layout_10k", "scalar_columns")

    headline_platform = stage_platforms.get("resim10k", "none")
    rnd = lambda x, n=1: (round(x, n) if x is not None else None)
    div = lambda a, b: (round(a / b, 2) if a and b else None)
    result = {
        "metric": f"resim_frames_per_sec_{N_ENTITIES}ent_{DEPTH}frame_rollback",
        "value": rnd(value),
        "unit": "frames/s",
        "vs_baseline": div(value, base10k),
        "spread": rnd(spread, 3),
        "reps": REPS,
        "baseline_numpy_cpu_fps": round(base10k, 1),
        "baseline_spread": round(base10k_sp, 3),
        "baseline_host": _host_tag(),
        "resim_fps_100k_entities": merged.get("fps_100k"),
        "resim_fps_100k_spread": merged.get("spread_100k"),
        "vs_baseline_100k": div(merged.get("fps_100k"), base100k),
        "baseline_numpy_cpu_fps_100k": round(base100k, 1),
        "resim_fps_1m_entities": merged.get("fps_1m"),
        "resim_fps_1m_spread": merged.get("spread_1m"),
        "vs_baseline_1m": div(merged.get("fps_1m"), base1m),
        "baseline_numpy_cpu_fps_1m": round(base1m, 1),
        "batched_lobbies": merged.get("batched_lobbies"),
        "batched_agg_fps_10k": merged.get("batched_agg_fps_10k"),
        "batched_per_lobby_fps_10k": merged.get("batched_per_lobby_fps_10k"),
        "batched_agg_vs_baseline": div(merged.get("batched_agg_fps_10k"),
                                       base10k),
        "batched_spread": merged.get("batched_spread"),
        "batched_rep_policy": merged.get("batched_rep_policy"),
        "batched_executor": merged.get("batched_executor"),
        "device_dispatches_per_tick": merged.get("device_dispatches_per_tick"),
        "batched_bucket_hist": merged.get("batched_bucket_hist"),
        "batched_program_compiles": merged.get("batched_program_compiles"),
        "batched_jit_entries": merged.get("batched_jit_entries"),
        "batched_telemetry": merged.get("batched_telemetry"),
        "sharded": {
            "devices": merged.get("sharded_devices"),
            "lobbies": merged.get("sharded_lobbies"),
            "agg_fps_d1": merged.get("sharded_agg_fps_d1"),
            "agg_fps_dN": merged.get("sharded_agg_fps_dN"),
            "speedup_dN_vs_d1": merged.get("sharded_speedup_dN_vs_d1"),
            "spread": merged.get("sharded_spread"),
            "spread_raw": merged.get("sharded_spread_raw"),
            "rep_policy": merged.get("sharded_rep_policy"),
            "per_device": merged.get("sharded_per_device"),
            "dispatches_per_device_tick": merged.get(
                "sharded_dispatches_per_device_tick"),
            "runner_stats": merged.get("sharded_runner_stats"),
            "telemetry": merged.get("sharded_telemetry"),
            "skipped": merged.get("sharded_skipped"),
        },
        "rep_policy_10k": merged.get("rep_policy_10k"),
        "bench_env": BENCH_MALLOC_ENV,
        "speculative_lane0_useful_fps": merged.get("spec_fps"),
        "speculative_lane_frames_per_sec": rnd(
            (merged.get("spec_fps") or 0) * SPEC_BRANCHES or None),
        "speculative_spread": merged.get("spec_spread"),
        "best_layout": layout,
        "vec3_layout_fps": merged.get("fps_vec3"),
        "scalar_columns_fps": merged.get("fps_10k"),
        "canonical_mode_fps": merged.get("fps_canon"),
        "canonical_spread": merged.get("spread_canon"),
        "approx_hbm_bw_util_pct": merged.get("hbm_pct_10k"),
        "approx_hbm_bw_util_pct_100k": merged.get("hbm_pct_100k"),
        "approx_hbm_bw_util_pct_1m": merged.get("hbm_pct_1m"),
        "bytes_per_resim_frame": merged.get("bytes_per_resim_frame"),
        "telemetry": {
            "ticks_per_sec_disabled": merged.get("telemetry_fps_disabled"),
            "ticks_per_sec_enabled": merged.get("telemetry_fps_enabled"),
            "overhead_enabled_pct": merged.get(
                "telemetry_overhead_enabled_pct"
            ),
            "enabled_summary": merged.get("telemetry_summary"),
        },
        "pipeline": {
            "ticks_per_sec_sync": merged.get("pipeline_ticks_per_sec_sync"),
            "ticks_per_sec_pipelined": merged.get(
                "pipeline_ticks_per_sec_pipelined"),
            "speedup_vs_sync": merged.get("pipeline_speedup"),
            "forced_readbacks_steady_state": merged.get(
                "pipeline_forced_steady_state"),
            "harvested_readbacks": merged.get("pipeline_harvested"),
            "sync_forced_readbacks": merged.get("pipeline_sync_forced"),
            "sync_blocked_seconds": merged.get(
                "pipeline_sync_blocked_seconds"),
            "degrades": merged.get("pipeline_degrades"),
            "spread": merged.get("pipeline_spread"),
            "spread_raw": merged.get("pipeline_spread_raw"),
            "entities": merged.get("pipeline_entities"),
            "netstats": merged.get("pipeline_netstats"),
            "rep_policy": merged.get("pipeline_rep_policy"),
        },
        "netstats": {
            "ticks_per_sec_sampler_off": merged.get(
                "netstats_ticks_per_sec_off"),
            "ticks_per_sec_sampler_on": merged.get(
                "netstats_ticks_per_sec_on"),
            "poll_disabled_us": merged.get("netstats_poll_disabled_us"),
            "poll_enabled_us": merged.get("netstats_poll_enabled_us"),
            "overhead_pct_of_tick": merged.get(
                "netstats_overhead_pct_of_tick"),
            "sweeps": merged.get("netstats_sweeps"),
            "every": merged.get("netstats_every"),
            "rollbacks_total": merged.get("netstats_rollbacks_total"),
            "rollback_causes": merged.get("netstats_rollback_causes"),
            "lateness_p95_frames": merged.get(
                "netstats_lateness_p95_frames"),
            "qos": merged.get("netstats_qos"),
        },
        "fleet": {
            "workers_spawned": merged.get("fleet_workers_spawned"),
            "lobbies": merged.get("fleet_lobbies"),
            "target_frames": merged.get("fleet_target_frames"),
            "entities": merged.get("fleet_entities"),
            "migrations_ok": merged.get("fleet_migrations_ok"),
            "migration_downtime_ms": merged.get(
                "fleet_migration_downtime_ms"),
            "failovers": merged.get("fleet_failovers"),
            "failover_frames": merged.get("fleet_failover_frames"),
            "admission_rejects": merged.get("fleet_admission_rejects"),
            "desyncs": merged.get("fleet_desyncs"),
            "events": merged.get("fleet_events"),
        },
        # every per-stage spread in one place so the history gate (and a
        # human reading BENCH_rXX.json) can tell CPU-fallback run-to-run
        # noise from a real regression: a delta inside the larger of the
        # two runs' spreads is noise, not signal
        "stage_spreads": {
            k: v for k, v in merged.items()
            if "spread" in k and v is not None
        },
        "platform": headline_platform,
        "stage_platforms": stage_platforms,
        "stage_errors": errors or None,
        "entities": N_ENTITIES,
        "rollback_depth": DEPTH,
        "tpu_fallback_to_cpu": headline_platform != "tpu",
    }
    _append_progress({"stage": "suite_done", **result})
    print(json.dumps(result))


def smoke():
    """CI smoke: the batched + sharded + netstats + uploads + speculation +
    trace + fleet stages only, 1 rep, small iter counts — seconds, not
    minutes — with every hard gate fully armed (a dispatch-count regression
    in either executor, a broken rollback-cause invariant, a sampler-cost
    regression, an extra host->device upload on the packed/megastep/
    input-queue paths, a hit-path rollback-servicing p99 that is not >=5x
    below the miss path, a malformed Chrome trace, trace-recording overhead
    past 2%, a fleet desync after live migration or SIGKILL failover, or a
    non-wire-visible admission reject fails this run).
    The sharded stage runs under forced 8-virtual-device CPU so the mesh
    path is exercised even on single-chip hosts; netstats runs on CPU (its
    gates are host-loop properties, not device throughput).  Wired into
    scripts/check.sh."""
    result, err = _run_stage(
        "batched", timeout_s=300, force_cpu=False,
        extra_env={"BGT_BENCH_SMOKE": "1"},
    )
    if result is None:
        print(f"bench smoke FAILED: {err}", file=sys.stderr)
        sys.exit(1)
    sharded, err = _run_stage(
        "sharded", timeout_s=300, force_cpu=True,
        extra_env={"BGT_BENCH_SMOKE": "1", "BGT_CPU_DEVICES": "8"},
    )
    if sharded is None:
        print(f"bench smoke FAILED (sharded stage): {err}", file=sys.stderr)
        sys.exit(1)
    if sharded.get("sharded_skipped"):
        print(f"bench smoke FAILED: sharded stage skipped under forced "
              f"8-device CPU: {sharded['sharded_skipped']}", file=sys.stderr)
        sys.exit(1)
    netstats, err = _run_stage(
        "netstats", timeout_s=300, force_cpu=True,
        extra_env={"BGT_BENCH_SMOKE": "1"},
    )
    if netstats is None:
        print(f"bench smoke FAILED (netstats stage): {err}", file=sys.stderr)
        sys.exit(1)
    uploads, err = _run_stage(
        "uploads", timeout_s=300, force_cpu=True,
        extra_env={"BGT_BENCH_SMOKE": "1"},
    )
    if uploads is None:
        print(f"bench smoke FAILED (uploads stage): {err}", file=sys.stderr)
        sys.exit(1)
    speculation, err = _run_stage(
        "speculation", timeout_s=540, force_cpu=True,
        extra_env={"BGT_BENCH_SMOKE": "1"},
    )
    if speculation is None:
        print(f"bench smoke FAILED (speculation stage): {err}",
              file=sys.stderr)
        sys.exit(1)
    trace, err = _run_stage(
        "trace", timeout_s=300, force_cpu=True,
        extra_env={"BGT_BENCH_SMOKE": "1"},
    )
    if trace is None:
        print(f"bench smoke FAILED (trace stage): {err}", file=sys.stderr)
        sys.exit(1)
    fleet, err = _run_stage(
        "fleet", timeout_s=540, force_cpu=True,
        extra_env={"BGT_BENCH_SMOKE": "1"},
    )
    if fleet is None:
        print(f"bench smoke FAILED (fleet stage): {err}", file=sys.stderr)
        sys.exit(1)
    print(json.dumps({"smoke": "ok", **result,
                      "sharded": {k: v for k, v in sharded.items()
                                  if k != "platform"},
                      "netstats": {k: v for k, v in netstats.items()
                                   if k != "platform"},
                      "uploads": {k: v for k, v in uploads.items()
                                  if k != "platform"},
                      "speculation": {k: v for k, v in speculation.items()
                                      if k != "platform"},
                      "trace": {k: v for k, v in trace.items()
                                if k != "platform"},
                      "fleet": {k: v for k, v in fleet.items()
                                if k != "platform"}}))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stage", choices=sorted(STAGES), default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="batched + sharded + netstats + uploads + "
                         "speculation + trace + fleet stages only, 1 rep, "
                         "all hard gates armed")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="with --stage trace: also write the validated "
                         "Chrome-trace JSON here (load in ui.perfetto.dev)")
    args = ap.parse_args()
    if args.stage:
        from bevy_ggrs_tpu.utils.platform import apply_platform_env

        apply_platform_env()
        if args.trace_out:
            os.environ["BGT_TRACE_OUT"] = args.trace_out
        print(json.dumps(STAGES[args.stage][0]()))
        return
    if args.smoke:
        smoke()
        return
    orchestrate()


if __name__ == "__main__":
    main()
