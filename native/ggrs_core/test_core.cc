/* Native unit tests for ggrs_core — frame math, wire round-trips, input
 * queues, and a two-session loopback game driven entirely in C++.
 * Build+run: make -C native test */

#include "ggrs_core.h"

#include <cassert>
#include <cstdio>
#include <cstring>
#include <vector>

static int failures = 0;
#define CHECK(cond)                                                         \
  do {                                                                      \
    if (!(cond)) {                                                          \
      printf("FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);                \
      failures++;                                                           \
    }                                                                       \
  } while (0)

static void test_session_lifecycle() {
  GgrsP2P *a = ggrs_p2p_create(2, 1, 0, 8, 1, 0, 60.0, 30.0);
  GgrsP2P *b = ggrs_p2p_create(2, 1, 0, 8, 1, 0, 60.0, 30.0);
  CHECK(a && b);
  uint16_t pa = ggrs_p2p_local_port(a), pb = ggrs_p2p_local_port(b);
  CHECK(ggrs_p2p_add_player(a, GGRS_LOCAL, 0, nullptr, 0) == GGRS_OK);
  CHECK(ggrs_p2p_add_player(a, GGRS_REMOTE, 1, "127.0.0.1", pb) == GGRS_OK);
  CHECK(ggrs_p2p_add_player(b, GGRS_REMOTE, 0, "127.0.0.1", pa) == GGRS_OK);
  CHECK(ggrs_p2p_add_player(b, GGRS_LOCAL, 1, nullptr, 0) == GGRS_OK);
  CHECK(ggrs_p2p_start(a) == GGRS_OK);
  CHECK(ggrs_p2p_start(b) == GGRS_OK);

  /* sync */
  for (int i = 0; i < 2000 && !(ggrs_p2p_state(a) == GGRS_RUNNING &&
                                ggrs_p2p_state(b) == GGRS_RUNNING); i++) {
    ggrs_p2p_poll(a);
    ggrs_p2p_poll(b);
  }
  CHECK(ggrs_p2p_state(a) == GGRS_RUNNING);
  CHECK(ggrs_p2p_state(b) == GGRS_RUNNING);

  /* run 120 interleaved frames */
  int32_t req[4096];
  uint8_t inp[4096];
  int nr, ni;
  int advances_a = 0, advances_b = 0;
  for (int f = 0; f < 120; f++) {
    GgrsP2P *ss[2] = {a, b};
    for (int s = 0; s < 2; s++) {
      ggrs_p2p_poll(ss[s]);
      uint8_t v = (uint8_t)(f & 0xF);
      int h = (s == 0) ? 0 : 1;
      CHECK(ggrs_p2p_add_local_input(ss[s], h, &v) == GGRS_OK);
      int rc = ggrs_p2p_advance(ss[s], req, 4096, inp, 4096, &nr, &ni);
      if (rc == GGRS_OK) {
        for (int i = 0; i < nr;) {
          if (req[i] == GGRS_REQ_ADVANCE) {
            (s == 0 ? advances_a : advances_b)++;
            i += 2 + 2;
          } else {
            i += 2;
          }
        }
      } else {
        CHECK(rc == GGRS_ERR_PREDICTION_THRESHOLD);
      }
    }
  }
  CHECK(advances_a >= 110);
  CHECK(advances_b >= 110);
  CHECK(ggrs_p2p_current_frame(a) >= 110);
  CHECK(ggrs_p2p_confirmed_frame(a) > 100);
  /* both sides fed each other: inputs for a confirmed frame must agree */
  ggrs_p2p_destroy(a);
  ggrs_p2p_destroy(b);
}

static void test_buffer_too_small() {
  GgrsP2P *a = ggrs_p2p_create(1, 1, 0, 8, 0, 0, 60.0, 30.0);
  CHECK(ggrs_p2p_add_player(a, GGRS_LOCAL, 0, nullptr, 0) == GGRS_OK);
  CHECK(ggrs_p2p_start(a) == GGRS_OK);
  uint8_t v = 1;
  CHECK(ggrs_p2p_add_local_input(a, 0, &v) == GGRS_OK);
  int32_t req[2];
  uint8_t inp[1];
  int nr, ni;
  CHECK(ggrs_p2p_advance(a, req, 2, inp, 1, &nr, &ni) ==
        GGRS_ERR_BUFFER_TOO_SMALL);
  ggrs_p2p_destroy(a);
}

static void test_invalid_usage() {
  GgrsP2P *a = ggrs_p2p_create(2, 1, 0, 8, 0, 0, 60.0, 30.0);
  CHECK(ggrs_p2p_add_player(a, GGRS_LOCAL, 7, nullptr, 0) ==
        GGRS_ERR_INVALID_REQUEST);
  CHECK(ggrs_p2p_start(a) == GGRS_ERR_INVALID_REQUEST); /* incomplete */
  uint8_t v = 0;
  CHECK(ggrs_p2p_add_local_input(a, 0, &v) != GGRS_OK); /* not started/local */
  ggrs_p2p_destroy(a);
}

int main() {
  test_invalid_usage();
  test_buffer_too_small();
  test_session_lifecycle();
  if (failures) {
    printf("%d FAILURES\n", failures);
    return 1;
  }
  printf("native tests OK\n");
  return 0;
}
