/* Native unit tests for ggrs_core — frame math, wire round-trips, input
 * queues, and a two-session loopback game driven entirely in C++.
 * Build+run: make -C native test */

#include "ggrs_core.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cassert>
#include <cstdio>
#include <cstring>
#include <random>
#include <vector>

static int failures = 0;
#define CHECK(cond)                                                         \
  do {                                                                      \
    if (!(cond)) {                                                          \
      printf("FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);                \
      failures++;                                                           \
    }                                                                       \
  } while (0)

static void test_session_lifecycle() {
  GgrsP2P *a = ggrs_p2p_create(2, 1, 0, 8, 1, 0, 60.0, 30.0);
  GgrsP2P *b = ggrs_p2p_create(2, 1, 0, 8, 1, 0, 60.0, 30.0);
  CHECK(a && b);
  uint16_t pa = ggrs_p2p_local_port(a), pb = ggrs_p2p_local_port(b);
  CHECK(ggrs_p2p_add_player(a, GGRS_LOCAL, 0, nullptr, 0) == GGRS_OK);
  CHECK(ggrs_p2p_add_player(a, GGRS_REMOTE, 1, "127.0.0.1", pb) == GGRS_OK);
  CHECK(ggrs_p2p_add_player(b, GGRS_REMOTE, 0, "127.0.0.1", pa) == GGRS_OK);
  CHECK(ggrs_p2p_add_player(b, GGRS_LOCAL, 1, nullptr, 0) == GGRS_OK);
  CHECK(ggrs_p2p_start(a) == GGRS_OK);
  CHECK(ggrs_p2p_start(b) == GGRS_OK);

  /* sync */
  for (int i = 0; i < 2000 && !(ggrs_p2p_state(a) == GGRS_RUNNING &&
                                ggrs_p2p_state(b) == GGRS_RUNNING); i++) {
    ggrs_p2p_poll(a);
    ggrs_p2p_poll(b);
  }
  CHECK(ggrs_p2p_state(a) == GGRS_RUNNING);
  CHECK(ggrs_p2p_state(b) == GGRS_RUNNING);

  /* run 120 interleaved frames */
  int32_t req[4096];
  uint8_t inp[4096];
  int nr, ni;
  int advances_a = 0, advances_b = 0;
  for (int f = 0; f < 120; f++) {
    GgrsP2P *ss[2] = {a, b};
    for (int s = 0; s < 2; s++) {
      ggrs_p2p_poll(ss[s]);
      uint8_t v = (uint8_t)(f & 0xF);
      int h = (s == 0) ? 0 : 1;
      CHECK(ggrs_p2p_add_local_input(ss[s], h, &v) == GGRS_OK);
      int rc = ggrs_p2p_advance(ss[s], req, 4096, inp, 4096, &nr, &ni);
      if (rc == GGRS_OK) {
        for (int i = 0; i < nr;) {
          if (req[i] == GGRS_REQ_ADVANCE) {
            (s == 0 ? advances_a : advances_b)++;
            i += 2 + 2;
          } else {
            i += 2;
          }
        }
      } else {
        CHECK(rc == GGRS_ERR_PREDICTION_THRESHOLD);
      }
    }
  }
  CHECK(advances_a >= 110);
  CHECK(advances_b >= 110);
  CHECK(ggrs_p2p_current_frame(a) >= 110);
  CHECK(ggrs_p2p_confirmed_frame(a) > 100);
  /* both sides fed each other: inputs for a confirmed frame must agree */
  ggrs_p2p_destroy(a);
  ggrs_p2p_destroy(b);
}

static void test_buffer_too_small() {
  GgrsP2P *a = ggrs_p2p_create(1, 1, 0, 8, 0, 0, 60.0, 30.0);
  CHECK(ggrs_p2p_add_player(a, GGRS_LOCAL, 0, nullptr, 0) == GGRS_OK);
  CHECK(ggrs_p2p_start(a) == GGRS_OK);
  uint8_t v = 1;
  CHECK(ggrs_p2p_add_local_input(a, 0, &v) == GGRS_OK);
  int32_t req[2];
  uint8_t inp[1];
  int nr, ni;
  CHECK(ggrs_p2p_advance(a, req, 2, inp, 1, &nr, &ni) ==
        GGRS_ERR_BUFFER_TOO_SMALL);
  ggrs_p2p_destroy(a);
}

static void test_invalid_usage() {
  GgrsP2P *a = ggrs_p2p_create(2, 1, 0, 8, 0, 0, 60.0, 30.0);
  CHECK(ggrs_p2p_add_player(a, GGRS_LOCAL, 7, nullptr, 0) ==
        GGRS_ERR_INVALID_REQUEST);
  CHECK(ggrs_p2p_start(a) == GGRS_ERR_INVALID_REQUEST); /* incomplete */
  uint8_t v = 0;
  CHECK(ggrs_p2p_add_local_input(a, 0, &v) != GGRS_OK); /* not started/local */
  ggrs_p2p_destroy(a);
}

static void test_packet_fuzz() {
  /* random bytes into the packet handler must never crash or corrupt.
   * The fuzzer socket IS the registered peer, so its garbage reaches the
   * parser (packets from unknown sources are dropped earlier). */
  int fd = ::socket(AF_INET, SOCK_DGRAM, 0);
  sockaddr_in me{};
  me.sin_family = AF_INET;
  me.sin_addr.s_addr = inet_addr("127.0.0.1");
  me.sin_port = 0;
  CHECK(bind(fd, (sockaddr *)&me, sizeof me) == 0);
  socklen_t mlen = sizeof me;
  getsockname(fd, (sockaddr *)&me, &mlen);
  uint16_t fuzz_port = ntohs(me.sin_port);

  GgrsP2P *a = ggrs_p2p_create(2, 2, 0, 8, 0, 10, 60.0, 30.0);
  uint16_t pa = ggrs_p2p_local_port(a);
  ggrs_p2p_add_player(a, GGRS_LOCAL, 0, nullptr, 0);
  ggrs_p2p_add_player(a, GGRS_REMOTE, 1, "127.0.0.1", fuzz_port);
  ggrs_p2p_start(a);

  sockaddr_in dst{};
  dst.sin_family = AF_INET;
  dst.sin_addr.s_addr = inet_addr("127.0.0.1");
  dst.sin_port = htons(pa);
  std::mt19937 rng(7);
  uint8_t buf[512];
  for (int i = 0; i < 5000; i++) {
    size_t len = rng() % sizeof buf;
    for (size_t j = 0; j < len; j++) buf[j] = (uint8_t)rng();
    if (rng() % 2) { buf[0] = 0xA7; buf[1] = 0x47; }  /* valid magic, evil body */
    if (len > 2 && rng() % 4 == 0) buf[2] = (uint8_t)(1 + rng() % 9); /* incl. DISC_NOTICE */
    (void)sendto(fd, buf, len, 0, (sockaddr *)&dst, sizeof dst);
    if (i % 50 == 0) ggrs_p2p_poll(a);
  }
  ggrs_p2p_poll(a);
  ::close(fd);
  /* session alive and well-behaved after the storm */
  CHECK(ggrs_p2p_state(a) == GGRS_SYNCHRONIZING || ggrs_p2p_state(a) == GGRS_RUNNING);
  int32_t handles[2];
  CHECK(ggrs_p2p_local_handles(a, handles, 2) == 1);
  ggrs_p2p_destroy(a);
}

static void test_spectator_follows_host() {
  /* all-native trio: host (with spectator) + peer + spectator client */
  GgrsP2P *host = ggrs_p2p_create(2, 1, 0, 8, 1, 0, 60.0, 30.0);
  GgrsP2P *peer = ggrs_p2p_create(2, 1, 0, 8, 1, 0, 60.0, 30.0);
  uint16_t ph = ggrs_p2p_local_port(host), pp = ggrs_p2p_local_port(peer);
  GgrsSpectator *spec =
      ggrs_spectator_create(2, 1, 0, "127.0.0.1", ph, 60.0, 30.0, 1);
  uint16_t ps = ggrs_spectator_local_port(spec);
  ggrs_p2p_add_player(host, GGRS_LOCAL, 0, nullptr, 0);
  ggrs_p2p_add_player(host, GGRS_REMOTE, 1, "127.0.0.1", pp);
  ggrs_p2p_add_player(host, GGRS_SPECTATOR, 2, "127.0.0.1", ps);
  ggrs_p2p_start(host);
  ggrs_p2p_add_player(peer, GGRS_REMOTE, 0, "127.0.0.1", ph);
  ggrs_p2p_add_player(peer, GGRS_LOCAL, 1, nullptr, 0);
  ggrs_p2p_start(peer);

  for (int i = 0; i < 4000; i++) {
    ggrs_p2p_poll(host);
    ggrs_p2p_poll(peer);
    ggrs_spectator_poll(spec);
    if (ggrs_p2p_state(host) == GGRS_RUNNING &&
        ggrs_p2p_state(peer) == GGRS_RUNNING &&
        ggrs_spectator_state(spec) == GGRS_RUNNING)
      break;
  }
  CHECK(ggrs_spectator_state(spec) == GGRS_RUNNING);

  int32_t req[4096];
  uint8_t inp[4096];
  int nr, ni;
  int spec_frames = 0;
  uint8_t last_spec_row[2] = {0, 0};
  for (int f = 0; f < 100; f++) {
    GgrsP2P *ss[2] = {host, peer};
    for (int s2 = 0; s2 < 2; s2++) {
      ggrs_p2p_poll(ss[s2]);
      uint8_t v = (uint8_t)((f + s2) & 0xF);
      ggrs_p2p_add_local_input(ss[s2], s2 == 0 ? 0 : 1, &v);
      ggrs_p2p_advance(ss[s2], req, 4096, inp, 4096, &nr, &ni);
    }
    ggrs_spectator_poll(spec);
    int rc = ggrs_spectator_advance(spec, req, 4096, inp, 4096, &nr, &ni);
    if (rc == GGRS_OK) {
      for (int i = 0; i < nr; i += 4) {
        CHECK(req[i] == GGRS_REQ_ADVANCE);
        spec_frames++;
      }
      if (ni >= 2) { last_spec_row[0] = inp[ni - 2]; last_spec_row[1] = inp[ni - 1]; }
    }
  }
  CHECK(spec_frames >= 60);
  /* the spectator replays the real inputs (frame-dependent pattern) */
  CHECK(last_spec_row[0] != 0 || last_spec_row[1] != 0);
  ggrs_spectator_destroy(spec);
  ggrs_p2p_destroy(host);
  ggrs_p2p_destroy(peer);
}

static void test_host_stall_liveness() {
  /* Attended-quiet accounting (mirrors tests/test_protocol_liveness.py):
   * a host stall longer than the disconnect timeout must NOT drop a live
   * peer — only attended silence counts.  Then genuinely killing one peer
   * must still disconnect it after ~timeout of attended polling. */
  GgrsP2P *a = ggrs_p2p_create(2, 1, 0, 8, 1, 0, 0.4, 0.2);
  GgrsP2P *b = ggrs_p2p_create(2, 1, 0, 8, 1, 0, 0.4, 0.2);
  uint16_t pa = ggrs_p2p_local_port(a), pb = ggrs_p2p_local_port(b);
  ggrs_p2p_add_player(a, GGRS_LOCAL, 0, nullptr, 0);
  ggrs_p2p_add_player(a, GGRS_REMOTE, 1, "127.0.0.1", pb);
  ggrs_p2p_add_player(b, GGRS_REMOTE, 0, "127.0.0.1", pa);
  ggrs_p2p_add_player(b, GGRS_LOCAL, 1, nullptr, 0);
  ggrs_p2p_start(a);
  ggrs_p2p_start(b);
  for (int i = 0; i < 2000 && !(ggrs_p2p_state(a) == GGRS_RUNNING &&
                                ggrs_p2p_state(b) == GGRS_RUNNING); i++) {
    ggrs_p2p_poll(a);
    ggrs_p2p_poll(b);
  }
  CHECK(ggrs_p2p_state(a) == GGRS_RUNNING);
  /* host stall: 2x the timeout with NO polling on either side */
  usleep(800 * 1000);
  ggrs_p2p_poll(a);
  ggrs_p2p_poll(b);
  int32_t kind, arg;
  uint64_t big, big2;
  char addrbuf[64];
  bool disconnected = false;
  while (ggrs_p2p_next_event(a, &kind, &arg, &big, &big2, addrbuf,
                             sizeof addrbuf))
    disconnected |= (kind == GGRS_EV_DISCONNECTED);
  CHECK(!disconnected); /* the stall must not read as remote silence */
  /* now kill b for real: poll only a at ~60 Hz until the timeout fires */
  for (int i = 0; i < 120 && !disconnected; i++) {
    usleep(16 * 1000);
    ggrs_p2p_poll(a);
    while (ggrs_p2p_next_event(a, &kind, &arg, &big, &big2, addrbuf,
                               sizeof addrbuf))
      disconnected |= (kind == GGRS_EV_DISCONNECTED);
  }
  CHECK(disconnected); /* attended silence still disconnects */
  ggrs_p2p_destroy(a);
  ggrs_p2p_destroy(b);
}

int main() {
  test_host_stall_liveness();
  test_spectator_follows_host();
  test_packet_fuzz();
  test_invalid_usage();
  test_buffer_too_small();
  test_session_lifecycle();
  if (failures) {
    printf("%d FAILURES\n", failures);
    return 1;
  }
  printf("native tests OK\n");
  return 0;
}
