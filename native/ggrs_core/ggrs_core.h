/* ggrs_core — native host runtime for bevy_ggrs_tpu.
 *
 * C API for the session/network core (the reference consumes this layer from
 * the native `ggrs` crate; SURVEY.md §2.3 reconstructs the surface).  The
 * simulation data plane stays in JAX on the TPU; this library owns the
 * latency-sensitive host path: non-blocking UDP, the wire protocol (format
 * shared with bevy_ggrs_tpu/session/protocol.py — the two implementations
 * interoperate on the wire), per-peer endpoint state machines, input queues
 * with PredictRepeatLast prediction, and the P2P advance/rollback decision.
 *
 * Request stream encoding returned by ggrs_p2p_advance:
 *   int32 records, one request after another:
 *     SAVE    -> [0, frame]
 *     LOAD    -> [1, frame]
 *     ADVANCE -> [2, frame, status[0..num_players-1]]
 *   each ADVANCE additionally appends num_players*input_size bytes to the
 *   input byte buffer, in handle order.
 */

#ifndef GGRS_CORE_H
#define GGRS_CORE_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

enum GgrsPlayerKind { GGRS_LOCAL = 0, GGRS_REMOTE = 1, GGRS_SPECTATOR = 2 };
enum GgrsState { GGRS_SYNCHRONIZING = 0, GGRS_RUNNING = 1 };
enum GgrsReq { GGRS_REQ_SAVE = 0, GGRS_REQ_LOAD = 1, GGRS_REQ_ADVANCE = 2 };
enum GgrsInputStatus {
  GGRS_INPUT_CONFIRMED = 0,
  GGRS_INPUT_PREDICTED = 1,
  GGRS_INPUT_DISCONNECTED = 2
};
enum GgrsErr {
  GGRS_OK = 0,
  GGRS_ERR_PREDICTION_THRESHOLD = -1,
  GGRS_ERR_NOT_SYNCHRONIZED = -2,
  GGRS_ERR_INVALID_REQUEST = -3,
  GGRS_ERR_BUFFER_TOO_SMALL = -4,
};
enum GgrsEventKind {
  GGRS_EV_SYNCHRONIZING = 0,
  GGRS_EV_SYNCHRONIZED = 1,
  GGRS_EV_DISCONNECTED = 2,
  GGRS_EV_INTERRUPTED = 3,
  GGRS_EV_RESUMED = 4,
  GGRS_EV_DESYNC = 5,
};

typedef struct GgrsP2P GgrsP2P;

/* lifecycle ---------------------------------------------------------------*/
GgrsP2P *ggrs_p2p_create(int num_players, int input_size, uint16_t local_port,
                         int max_prediction, int input_delay,
                         int desync_interval, double disconnect_timeout_s,
                         double disconnect_notify_s);
int ggrs_p2p_add_player(GgrsP2P *s, int kind, int handle, const char *ip,
                        uint16_t port);
int ggrs_p2p_start(GgrsP2P *s); /* validate player set, begin sync */
void ggrs_p2p_destroy(GgrsP2P *s);
uint16_t ggrs_p2p_local_port(GgrsP2P *s);

/* per-tick ----------------------------------------------------------------*/
void ggrs_p2p_poll(GgrsP2P *s); /* poll_remote_clients */
int ggrs_p2p_state(GgrsP2P *s);
int ggrs_p2p_add_local_input(GgrsP2P *s, int handle, const uint8_t *data);
int ggrs_p2p_advance(GgrsP2P *s, int32_t *req_buf, int req_cap,
                     uint8_t *input_buf, int input_cap, int *n_req_words,
                     int *n_input_bytes);

/* queries -----------------------------------------------------------------*/
int32_t ggrs_p2p_current_frame(GgrsP2P *s);
int32_t ggrs_p2p_confirmed_frame(GgrsP2P *s);
int ggrs_p2p_frames_ahead(GgrsP2P *s);
int ggrs_p2p_max_prediction(GgrsP2P *s);
int ggrs_p2p_num_players(GgrsP2P *s);
int ggrs_p2p_local_handles(GgrsP2P *s, int32_t *out, int cap);

/* events: returns 1 if an event was popped.  a/b/b2 meaning per kind:
 *  SYNCHRONIZING: a=count b=total; DESYNC: a=frame b=remote_checksum
 *  b2=local_checksum.  addr written as "ip:port" into addrbuf (>=64 bytes). */
int ggrs_p2p_next_event(GgrsP2P *s, int32_t *kind, int32_t *a, uint64_t *b,
                        uint64_t *b2, char *addrbuf, int addrcap);

/* desync detection: the TPU side pushes confirmed-frame checksums here */
void ggrs_p2p_push_checksum(GgrsP2P *s, int32_t frame, uint64_t checksum);

/* ---- spectator client session ------------------------------------------
 * Follows a host's confirmed all-player input stream; never predicts.
 * ggrs_spectator_advance fills one-or-more ADVANCE records (same encoding
 * as ggrs_p2p_advance, catch-up emits several) or returns
 * GGRS_ERR_PREDICTION_THRESHOLD while waiting for the next frame. */
typedef struct GgrsSpectator GgrsSpectator;
GgrsSpectator *ggrs_spectator_create(int num_players, int input_size,
                                     uint16_t local_port, const char *host_ip,
                                     uint16_t host_port,
                                     double disconnect_timeout_s,
                                     double disconnect_notify_s,
                                     int catchup_speed);
void ggrs_spectator_destroy(GgrsSpectator *s);
uint16_t ggrs_spectator_local_port(GgrsSpectator *s);
void ggrs_spectator_poll(GgrsSpectator *s);
int ggrs_spectator_state(GgrsSpectator *s);
int32_t ggrs_spectator_current_frame(GgrsSpectator *s);
int32_t ggrs_spectator_frames_behind(GgrsSpectator *s);
int ggrs_spectator_advance(GgrsSpectator *s, int32_t *req_buf, int req_cap,
                           uint8_t *input_buf, int input_cap,
                           int *n_req_words, int *n_input_bytes);
int ggrs_spectator_next_event(GgrsSpectator *s, int32_t *kind, int32_t *a,
                              uint64_t *b, uint64_t *b2, char *addrbuf,
                              int addrcap);

/* network stats for a remote handle */
int ggrs_p2p_stats(GgrsP2P *s, int handle, double *ping_ms, int *send_queue,
                   double *kbps_sent, int *local_frames_behind,
                   int *remote_frames_behind);

#ifdef __cplusplus
}
#endif

#endif /* GGRS_CORE_H */
