/* ggrs_core implementation — see ggrs_core.h for the API contract and
 * bevy_ggrs_tpu/session/protocol.py for the (shared) wire format. */

#include "ggrs_core.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <random>
#include <string>
#include <vector>

namespace {

/* ---- frame math (explicit i32 wraparound, matches utils/frames.py) ----- */
using Frame = int32_t;
constexpr Frame NULL_FRAME = -1;

static inline Frame frame_diff(Frame a, Frame b) {
  return (Frame)((uint32_t)a - (uint32_t)b);
}
static inline bool frame_lt(Frame a, Frame b) { return frame_diff(a, b) < 0; }
static inline bool frame_le(Frame a, Frame b) { return frame_diff(a, b) <= 0; }
static inline bool frame_gt(Frame a, Frame b) { return frame_diff(a, b) > 0; }

static double now_s() {
  using namespace std::chrono;
  return duration<double>(steady_clock::now().time_since_epoch()).count();
}

/* ---- wire format (little-endian; keep in sync with protocol.py) -------- */
constexpr uint16_t MAGIC = 0x47A7;
constexpr uint8_t T_SYNC_REQ = 1, T_SYNC_REP = 2, T_INPUT = 3, T_INPUT_ACK = 4,
                  T_QUAL_REQ = 5, T_QUAL_REP = 6, T_KEEP_ALIVE = 7,
                  T_CHECKSUM = 8, T_DISC_NOTICE = 9;
/* wire protocol version, carried in SYNC_REQ/SYNC_REP after the nonce; a
 * mismatched or missing version gets no reply, so mixed-version pairs stall
 * in the handshake instead of mis-parsing each other's input rows (mirrors
 * session/protocol.py PROTOCOL_VERSION — keep in sync) */
constexpr uint8_t PROTOCOL_VERSION = 1;
/* how long an adopted disconnect-consensus frame keeps rebroadcasting
 * (mirrors session/p2p.py DISC_NOTICE_REBROADCAST_S) */
constexpr double DISC_NOTICE_REBROADCAST_S = 1.5;
constexpr int NUM_SYNC_ROUNDTRIPS = 5;
constexpr double SYNC_RETRY_S = 0.06, QUALITY_INTERVAL_S = 0.2,
                 KEEP_ALIVE_S = 0.2;
/* max contribution of one inter-poll gap to the attended-quiet clock
 * (mirrors session/protocol.py ATTENDED_GAP_CAP_S: a host stall must not
 * read as remote silence and spuriously drop a live peer) */
constexpr double ATTENDED_GAP_CAP_S = 0.25;
constexpr int MAX_INPUTS_PER_PACKET = 64;
/* absolute bound on un-acked send history (frames; ~68 s at 60 fps).  The
 * ack-driven trim keeps these deques tiny normally, and a silent peer hits
 * the disconnect timeout — but a peer whose keepalives arrive while its acks
 * are lost one-way defeats that timeout; without this cap local_sent /
 * spectator_sent would grow unboundedly.  Oldest frames drop first. */
constexpr int MAX_UNACKED_FRAMES = 4096;

struct Writer {
  std::vector<uint8_t> buf;
  void u8(uint8_t v) { buf.push_back(v); }
  void u16(uint16_t v) { for (int i = 0; i < 2; i++) buf.push_back(v >> (8 * i)); }
  void u32(uint32_t v) { for (int i = 0; i < 4; i++) buf.push_back(v >> (8 * i)); }
  void u64(uint64_t v) { for (int i = 0; i < 8; i++) buf.push_back(v >> (8 * i)); }
  void i32(int32_t v) { u32((uint32_t)v); }
  void i8(int8_t v) { buf.push_back((uint8_t)v); }
  void bytes(const uint8_t *p, size_t n) { buf.insert(buf.end(), p, p + n); }
};

struct Reader {
  const uint8_t *p;
  size_t n, off = 0;
  bool ok = true;
  Reader(const uint8_t *p_, size_t n_) : p(p_), n(n_) {}
  bool need(size_t k) { if (off + k > n) { ok = false; return false; } return true; }
  uint8_t u8() { if (!need(1)) return 0; return p[off++]; }
  uint16_t u16() { if (!need(2)) return 0; uint16_t v = p[off] | p[off+1] << 8; off += 2; return v; }
  uint32_t u32() { if (!need(4)) return 0; uint32_t v = 0; for (int i = 3; i >= 0; i--) v = (v << 8) | p[off + i]; off += 4; return v; }
  uint64_t u64() { if (!need(8)) return 0; uint64_t v = 0; for (int i = 7; i >= 0; i--) v = (v << 8) | p[off + i]; off += 8; return v; }
  int32_t i32() { return (int32_t)u32(); }
  int8_t i8() { return (int8_t)u8(); }
};

/* ---- addresses --------------------------------------------------------- */
struct Addr {
  uint32_t ip = 0;  /* network order */
  uint16_t port = 0; /* host order */
  bool operator<(const Addr &o) const {
    return ip != o.ip ? ip < o.ip : port < o.port;
  }
  bool operator==(const Addr &o) const { return ip == o.ip && port == o.port; }
  std::string str() const {
    char b[64];
    struct in_addr a; a.s_addr = ip;
    snprintf(b, sizeof b, "%s:%u", inet_ntoa(a), (unsigned)port);
    return b;
  }
};

/* ---- non-blocking UDP socket ------------------------------------------- */
struct UdpSocket {
  int fd = -1;
  bool open(uint16_t port) {
    fd = ::socket(AF_INET, SOCK_DGRAM, 0);
    if (fd < 0) return false;
    int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = INADDR_ANY;
    sa.sin_port = htons(port);
    if (bind(fd, (sockaddr *)&sa, sizeof sa) < 0) { ::close(fd); fd = -1; return false; }
    return true;
  }
  uint16_t local_port() const {
    sockaddr_in sa{}; socklen_t len = sizeof sa;
    getsockname(fd, (sockaddr *)&sa, &len);
    return ntohs(sa.sin_port);
  }
  void send_to(const Addr &a, const uint8_t *p, size_t n) {
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = a.ip;
    sa.sin_port = htons(a.port);
    (void)sendto(fd, p, n, 0, (sockaddr *)&sa, sizeof sa);
  }
  /* returns bytes read or -1 when drained */
  int recv_from(Addr *from, uint8_t *buf, size_t cap) {
    sockaddr_in sa{}; socklen_t len = sizeof sa;
    ssize_t r = recvfrom(fd, buf, cap, 0, (sockaddr *)&sa, &len);
    if (r < 0) return -1;
    from->ip = sa.sin_addr.s_addr;
    from->port = ntohs(sa.sin_port);
    return (int)r;
  }
  ~UdpSocket() { if (fd >= 0) ::close(fd); }
};

/* ---- time sync (matches session/time_sync.py) -------------------------- */
struct TimeSync {
  std::deque<int> local_adv, remote_adv;
  static constexpr size_t WINDOW = 40;
  void note_local(int v) { local_adv.push_back(v); if (local_adv.size() > WINDOW) local_adv.pop_front(); }
  void note_remote(int v) { remote_adv.push_back(v); if (remote_adv.size() > WINDOW) remote_adv.pop_front(); }
  static double avg(const std::deque<int> &d) {
    if (d.empty()) return 0;
    double s = 0; for (int v : d) s += v;
    return s / d.size();
  }
  int local_advantage() const { return (int)(avg(local_adv) + (avg(local_adv) >= 0 ? 0.5 : -0.5)); }
  int frames_ahead() const {
    if (local_adv.empty() || remote_adv.empty()) return 0;
    double d = (avg(local_adv) - avg(remote_adv)) / 2.0;
    return (int)(d + (d >= 0 ? 0.5 : -0.5));
  }
};

/* ---- events ------------------------------------------------------------ */
struct Event {
  int32_t kind;
  int32_t a = 0;
  uint64_t b = 0;
  Addr addr;
  uint64_t b2 = 0; /* DESYNC: local checksum (b = remote) */
};

/* ---- per-peer endpoint (matches session/protocol.py PeerEndpoint) ------ */
struct Endpoint {
  Addr addr;
  UdpSocket *sock = nullptr;
  int input_size = 1;  /* bytes per frame the PEER streams to us */
  int state = GGRS_SYNCHRONIZING;
  uint32_t sync_nonce = 0;
  int sync_remaining = NUM_SYNC_ROUNDTRIPS;
  double last_sync_sent = 0, last_recv = 0, last_send = 0, last_quality = 0;
  double disconnect_timeout_s = 2.0, disconnect_notify_s = 0.5, created = 0;
  /* attended-quiet accounting (see session/protocol.py): silence accrues
   * per poll, each gap capped, so only time the host spent listening counts
   * toward the disconnect timeout */
  double quiet_s = 0, last_poll = 0;
  bool interrupted = false, disconnected = false;
  TimeSync time_sync;
  Frame last_acked = NULL_FRAME;        /* newest of OUR inputs peer has */
  Frame last_received_frame = NULL_FRAME; /* newest peer input we have (max) */
  /* highest CONTIGUOUSLY received frame — what we ack (see protocol.py);
   * anchored flag is separate: contig can legitimately equal -1 (== the
   * NULL sentinel) when the peer stream starts at frame 0 */
  Frame contig_received = NULL_FRAME;
  bool contig_anchored = false;
  bool have_stream_base = false;
  Frame stream_base = NULL_FRAME;       /* first frame of OUR outbound stream */
  int local_advantage = 0, remote_advantage = 0;
  double ping_s = 0;
  uint64_t bytes_sent = 0;
  int send_queue_len = 0;
  std::vector<Event> events;
  /* inbound inputs + checksums, drained by the session */
  std::vector<std::pair<Frame, std::vector<uint8_t>>> inbox;
  std::vector<std::pair<Frame, uint64_t>> checksum_inbox;
  Frame base_inbox = NULL_FRAME;  /* peer stream base, delivered once */
  bool have_base_inbox = false;

  void init(double now) { last_recv = now; created = now; last_poll = now; }

  void send(uint8_t type, const Writer &body) {
    Writer w;
    w.u16(MAGIC); w.u8(type);
    w.bytes(body.buf.data(), body.buf.size());
    bytes_sent += w.buf.size();
    last_send = now_s();
    sock->send_to(addr, w.buf.data(), w.buf.size());
  }

  void send_sync_request() {
    Writer b; b.u32(sync_nonce); b.u8(PROTOCOL_VERSION);
    last_sync_sent = now_s();
    send(T_SYNC_REQ, b);
  }

  void send_inputs(const std::deque<std::pair<Frame, std::vector<uint8_t>>> &pending) {
    /* redundant packets, chunked: slow receivers (late spectators) must
     * never see a truncation gap they cannot fill */
    if (!have_stream_base && !pending.empty()) {
      have_stream_base = true;
      stream_base = pending.front().first;
    }
    std::vector<const std::pair<Frame, std::vector<uint8_t>> *> out;
    for (auto &p : pending)
      if (last_acked == NULL_FRAME || frame_gt(p.first, last_acked)) out.push_back(&p);
    send_queue_len = (int)out.size();
    if (out.empty()) return;
    size_t limit = std::min(out.size(), (size_t)(4 * MAX_INPUTS_PER_PACKET));
    for (size_t c = 0; c < limit; c += MAX_INPUTS_PER_PACKET) {
      size_t end = std::min(c + (size_t)MAX_INPUTS_PER_PACKET, limit);
      Writer b;
      b.i32(out[c]->first);
      b.u16((uint16_t)(end - c));
      b.i32(contig_received);
      int adv = local_advantage; if (adv > 127) adv = 127; if (adv < -127) adv = -127;
      b.i8((int8_t)adv);
      b.i32(stream_base);
      for (size_t i = c; i < end; i++) b.bytes(out[i]->second.data(), out[i]->second.size());
      send(T_INPUT, b);
    }
  }

  void send_input_ack() { Writer b; b.i32(contig_received); send(T_INPUT_ACK, b); }

  void send_checksum(Frame f, uint64_t cs) {
    Writer b; b.i32(f); b.u64(cs); send(T_CHECKSUM, b);
  }

  std::deque<std::pair<int, Frame>> disc_notice_inbox;

  void send_disc_notice(int handle, Frame frame) {
    Writer b;
    b.u16((uint16_t)(int16_t)handle);
    b.i32(frame);
    send(T_DISC_NOTICE, b);
  }

  void note_ack(Frame ack) {
    if (ack != NULL_FRAME && (last_acked == NULL_FRAME || frame_gt(ack, last_acked)))
      last_acked = ack;
  }

  void handle(const uint8_t *data, size_t n) {
    if (disconnected) return; /* once disconnected, always disconnected:
                               * late packets must not mutate input queues */
    Reader r(data, n);
    if (r.u16() != MAGIC) return;
    uint8_t t = r.u8();
    last_recv = now_s();
    quiet_s = 0;
    last_poll = last_recv; /* the gap ending here held a packet */
    if (interrupted) { interrupted = false; events.push_back({GGRS_EV_RESUMED, 0, 0, addr}); }
    switch (t) {
      case T_SYNC_REQ: {
        uint32_t nonce = r.u32();
        uint8_t ver = r.u8();
        /* drop without replying on missing (pre-versioning 4-byte body) or
         * mismatched version: the mixed-version pair must stall, not run */
        if (!r.ok || ver != PROTOCOL_VERSION) break;
        Writer b; b.u32(nonce); b.u8(PROTOCOL_VERSION); send(T_SYNC_REP, b);
        break;
      }
      case T_SYNC_REP: {
        uint32_t nonce = r.u32();
        uint8_t ver = r.u8();
        if (!r.ok || ver != PROTOCOL_VERSION) break;
        if (state == GGRS_SYNCHRONIZING && nonce == sync_nonce) {
          sync_remaining--;
          sync_nonce = (uint32_t)(sync_nonce * 6364136223846793005ULL + 1ULL);
          events.push_back({GGRS_EV_SYNCHRONIZING,
                            NUM_SYNC_ROUNDTRIPS - sync_remaining,
                            (uint64_t)NUM_SYNC_ROUNDTRIPS, addr});
          if (sync_remaining <= 0) {
            state = GGRS_RUNNING;
            events.push_back({GGRS_EV_SYNCHRONIZED, 0, 0, addr});
          } else {
            send_sync_request();
          }
        }
        break;
      }
      case T_INPUT: {
        Frame start = r.i32();
        uint16_t count = r.u16();
        Frame ack = r.i32();
        int8_t adv = r.i8();
        Frame base = r.i32();
        if (!r.ok) break;
        note_ack(ack);
        time_sync.note_remote(adv);
        remote_advantage = adv;
        if (!contig_anchored) {
          contig_anchored = true;
          contig_received = base - 1;  /* anchor at the true stream start */
          base_inbox = base;
          have_base_inbox = true;
        }
        Frame end = NULL_FRAME;
        for (int i = 0; i < count; i++) {
          Frame f = start + i;
          if (!r.need(input_size)) break;
          const uint8_t *raw = r.p + r.off;
          r.off += input_size;
          end = f;
          if (frame_gt(f, contig_received)) {
            if (last_received_frame == NULL_FRAME || frame_gt(f, last_received_frame))
              last_received_frame = f;
            inbox.emplace_back(f, std::vector<uint8_t>(raw, raw + input_size));
          }
        }
        /* contiguous ranges only extend the mark when they connect to it */
        if (end != NULL_FRAME && !frame_gt(start, contig_received + 1) &&
            frame_gt(end, contig_received))
          contig_received = end;
        break;
      }
      case T_INPUT_ACK: {
        Frame ack = r.i32();
        if (r.ok) note_ack(ack);
        break;
      }
      case T_QUAL_REQ: {
        uint64_t ts = r.u64();
        int8_t adv = r.i8();
        if (!r.ok) break;
        time_sync.note_remote(adv);
        remote_advantage = adv;
        Writer b; b.u64(ts); send(T_QUAL_REP, b);
        break;
      }
      case T_QUAL_REP: {
        uint64_t ts = r.u64();
        if (!r.ok) break;
        double rtt = now_s() - (double)ts / 1e6;
        if (rtt > 0) ping_s = rtt;
        break;
      }
      case T_CHECKSUM: {
        Frame f = r.i32();
        uint64_t cs = r.u64();
        if (!r.ok) break;
        checksum_inbox.emplace_back(f, cs);
        break;
      }
      case T_DISC_NOTICE: {
        int handle = (int)(int16_t)r.u16();
        Frame f = r.i32();
        if (!r.ok) break;
        disc_notice_inbox.push_back({handle, f});
        break;
      }
      default: break; /* keepalive: recv timestamp update is enough */
    }
  }

  void poll() {
    double t = now_s();
    double gap = t - last_poll;
    if (gap < 0) gap = 0;
    last_poll = t;
    if (disconnected) return;
    double cap = std::min(ATTENDED_GAP_CAP_S, 0.5 * disconnect_timeout_s);
    quiet_s += std::min(gap, cap);
    if (state == GGRS_SYNCHRONIZING) {
      if (t - last_sync_sent >= SYNC_RETRY_S) send_sync_request();
      return;
    }
    if (t - last_quality >= QUALITY_INTERVAL_S) {
      last_quality = t;
      Writer b;
      b.u64((uint64_t)(t * 1e6));
      int adv = local_advantage; if (adv > 127) adv = 127; if (adv < -127) adv = -127;
      b.i8((int8_t)adv);
      send(T_QUAL_REQ, b);
    }
    if (t - last_send >= KEEP_ALIVE_S) {
      if (last_received_frame != NULL_FRAME) send_input_ack();
      else { Writer b; send(T_KEEP_ALIVE, b); }
    }
    double quiet = quiet_s;
    if (quiet >= disconnect_timeout_s) {
      disconnected = true;
      events.push_back({GGRS_EV_DISCONNECTED, 0, 0, addr});
    } else if (quiet >= disconnect_notify_s && !interrupted) {
      interrupted = true;
      events.push_back({GGRS_EV_INTERRUPTED,
                        (int32_t)(disconnect_timeout_s * 1000), 0, addr});
    }
  }
};

/* ---- input queue (matches session/input_queue.py) ---------------------- */
struct InputQueue {
  int input_size = 1, delay = 0;
  std::map<Frame, std::vector<uint8_t>, bool (*)(Frame, Frame)> inputs{frame_lt};
  std::map<Frame, std::vector<uint8_t>, bool (*)(Frame, Frame)> predictions{frame_lt};
  Frame last_confirmed = NULL_FRAME;
  Frame first_incorrect = NULL_FRAME;

  std::vector<uint8_t> def() const { return std::vector<uint8_t>(input_size, 0); }

  bool have_base = false;
  Frame base = NULL_FRAME;

  void set_base(Frame b) {
    have_base = true;
    base = b;
    recheck_contig();
  }

  void recheck_contig() {
    if (last_confirmed == NULL_FRAME && have_base && inputs.count(base))
      last_confirmed = base;
    while (last_confirmed != NULL_FRAME && inputs.count(last_confirmed + 1))
      last_confirmed = last_confirmed + 1;
  }

  Frame add_local(Frame frame, const uint8_t *v) {
    Frame eff = frame + delay;
    store(eff, v);
    return eff;
  }
  void add_remote(Frame frame, const uint8_t *v) { store(frame, v); }

  void store(Frame frame, const uint8_t *v) {
    if (last_confirmed != NULL_FRAME && frame_le(frame, last_confirmed)) return;
    if (inputs.count(frame)) return;
    std::vector<uint8_t> val(v, v + input_size);
    auto it = predictions.find(frame);
    if (it != predictions.end()) {
      if (it->second != val &&
          (first_incorrect == NULL_FRAME || frame_lt(frame, first_incorrect)))
        first_incorrect = frame;
      predictions.erase(it);
    }
    inputs[frame] = std::move(val);
    /* contiguous high-water mark, anchored at the stream base when known */
    if (last_confirmed == NULL_FRAME) {
      if (have_base && frame != base) { recheck_contig(); return; }
      last_confirmed = frame;
    }
    recheck_contig();
  }

  /* returns status */
  int input_for(Frame frame, uint8_t *out) {
    auto it = inputs.find(frame);
    if (it != inputs.end()) {
      memcpy(out, it->second.data(), input_size);
      return GGRS_INPUT_CONFIRMED;
    }
    std::vector<uint8_t> pred = def();
    if (last_confirmed != NULL_FRAME) {
      /* PredictRepeatLast: nearest confirmed input at or before `frame`;
       * frames before the first real input predict the DEFAULT input (must
       * match the python queue exactly — these early predictions are never
       * corrected, so any mismatch is a permanent cross-peer desync) */
      auto ub = inputs.upper_bound(frame);
      if (ub != inputs.begin()) { --ub; pred = ub->second; }
    }
    predictions[frame] = pred;
    memcpy(out, pred.data(), input_size);
    return GGRS_INPUT_PREDICTED;
  }

  const std::vector<uint8_t> *confirmed(Frame f) const {
    auto it = inputs.find(f);
    return it == inputs.end() ? nullptr : &it->second;
  }

  Frame take_first_incorrect() {
    Frame f = first_incorrect;
    first_incorrect = NULL_FRAME;
    return f;
  }

  /* disconnect-frame consensus adoption: drop real inputs newer than f and
   * pull the contiguity mark back (mirrors InputQueue.truncate_after) */
  void truncate_after(Frame f) {
    for (auto it = inputs.begin(); it != inputs.end();)
      it = frame_gt(it->first, f) ? inputs.erase(it) : std::next(it);
    if (last_confirmed != NULL_FRAME && frame_gt(last_confirmed, f)) {
      last_confirmed =
          (f != NULL_FRAME && inputs.count(f)) ? f : NULL_FRAME;
      recheck_contig();
    }
  }

  void gc(Frame before) {
    for (auto *m : {&inputs, &predictions})
      for (auto it = m->begin(); it != m->end();)
        it = frame_lt(it->first, before) ? m->erase(it) : std::next(it);
  }
};

}  // namespace

/* ---- the P2P session ---------------------------------------------------- */
struct GgrsP2P {
  int num_players = 2, input_size = 1;
  int max_prediction = 8, input_delay = 0, desync_interval = 0;
  double disconnect_timeout_s = 2.0, disconnect_notify_s = 0.5;
  UdpSocket sock;
  bool started = false;
  Frame current_frame = 0, confirmed = NULL_FRAME;
  std::vector<int> local_handles;
  std::map<int, Addr> remote_handle_addr;
  std::map<Addr, std::unique_ptr<Endpoint>> endpoints;
  std::map<Addr, std::unique_ptr<Endpoint>> spectator_endpoints;
  std::map<Addr, std::vector<int>> handles_of_addr;
  std::vector<Addr> spectator_addrs;
  /* confirmed all-player input rows streamed to spectators */
  std::deque<std::pair<Frame, std::vector<uint8_t>>> spectator_sent;
  Frame next_spectator_frame = 0;
  std::vector<InputQueue> queues;
  std::vector<Addr> disc_corrected; /* addrs whose disconnect was resolved */
  /* disconnect-frame consensus (mirrors session/p2p.py _disc_frame /
   * _disc_notices): handle -> adopted frame; handle -> (frame, until) */
  std::map<int, Frame> disc_frame;
  std::map<int, std::pair<Frame, double>> disc_notices;
  std::map<int, std::vector<uint8_t>> staged;
  std::deque<std::pair<Frame, std::vector<uint8_t>>> local_sent;
  std::deque<Event> events;
  std::map<Frame, uint64_t, bool (*)(Frame, Frame)> local_checksums{frame_lt};
  /* remote reports that arrived before our local checksum for that frame */
  std::map<Frame, std::vector<std::pair<Addr, uint64_t>>, bool (*)(Frame, Frame)>
      remote_checksums{frame_lt};
  std::mt19937 rng{std::random_device{}()};
};

extern "C" {

GgrsP2P *ggrs_p2p_create(int num_players, int input_size, uint16_t local_port,
                         int max_prediction, int input_delay,
                         int desync_interval, double disconnect_timeout_s,
                         double disconnect_notify_s) {
  auto *s = new GgrsP2P();
  s->num_players = num_players;
  s->input_size = input_size;
  s->max_prediction = max_prediction;
  s->input_delay = input_delay;
  s->desync_interval = desync_interval;
  s->disconnect_timeout_s = disconnect_timeout_s;
  s->disconnect_notify_s = disconnect_notify_s;
  if (!s->sock.open(local_port)) { delete s; return nullptr; }
  s->queues.resize(num_players);
  for (auto &q : s->queues) q.input_size = input_size;
  return s;
}

uint16_t ggrs_p2p_local_port(GgrsP2P *s) { return s->sock.local_port(); }

int ggrs_p2p_add_player(GgrsP2P *s, int kind, int handle, const char *ip,
                        uint16_t port) {
  if (kind == GGRS_LOCAL) {
    if (handle < 0 || handle >= s->num_players) return GGRS_ERR_INVALID_REQUEST;
    s->local_handles.push_back(handle);
    s->queues[handle].delay = s->input_delay;
    return GGRS_OK;
  }
  Addr a;
  a.ip = inet_addr(ip ? ip : "127.0.0.1");
  a.port = port;
  if (kind == GGRS_REMOTE) {
    if (handle < 0 || handle >= s->num_players) return GGRS_ERR_INVALID_REQUEST;
    s->remote_handle_addr[handle] = a;
    s->handles_of_addr[a].push_back(handle);
    return GGRS_OK;
  }
  if (kind == GGRS_SPECTATOR) {
    s->spectator_addrs.push_back(a);
    return GGRS_OK;
  }
  return GGRS_ERR_INVALID_REQUEST;
}

int ggrs_p2p_start(GgrsP2P *s) {
  size_t have = s->local_handles.size() + s->remote_handle_addr.size();
  if ((int)have != s->num_players) return GGRS_ERR_INVALID_REQUEST;
  /* wire rows pack local inputs in ascending-handle order (receivers unpack
   * via the sorted handles_of_addr) — sort so add_player order is free */
  std::sort(s->local_handles.begin(), s->local_handles.end());
  double t = now_s();
  for (auto &[addr, handles] : s->handles_of_addr) {
    auto ep = std::make_unique<Endpoint>();
    ep->addr = addr;
    ep->sock = &s->sock;
    ep->input_size = s->input_size * (int)handles.size();
    ep->sync_nonce = s->rng();
    ep->disconnect_timeout_s = s->disconnect_timeout_s;
    ep->disconnect_notify_s = s->disconnect_notify_s;
    ep->init(t);
    s->endpoints[addr] = std::move(ep);
  }
  for (auto &addr : s->spectator_addrs) {
    auto ep = std::make_unique<Endpoint>();
    ep->addr = addr;
    ep->sock = &s->sock;
    ep->input_size = s->input_size * s->num_players + s->num_players; /* inputs + status bytes */
    ep->sync_nonce = s->rng();
    ep->disconnect_timeout_s = s->disconnect_timeout_s;
    ep->disconnect_notify_s = s->disconnect_notify_s;
    ep->init(t);
    s->spectator_endpoints[addr] = std::move(ep);
  }
  s->started = true;
  return GGRS_OK;
}

void ggrs_p2p_destroy(GgrsP2P *s) { delete s; }

int ggrs_p2p_state(GgrsP2P *s) {
  for (auto &[a, ep] : s->endpoints)
    if (ep->state != GGRS_RUNNING && !ep->disconnected) return GGRS_SYNCHRONIZING;
  return GGRS_RUNNING;
}

/* GGPO-style min-rule adoption (mirrors P2PSession._adopt_disconnect):
 * keep real inputs up to the consensus frame, resim the tail under the
 * disconnect policy, rebroadcast.  Clamped at our confirmed frame (frames
 * below it may be pruned from the driver's ring); the residual race when a
 * survivor confirmed a frame another never received is caught by desync
 * detection. */
static void adopt_disconnect(GgrsP2P *s, int handle, Frame frame) {
  auto &q = s->queues[handle];
  Frame f = frame_le(frame, q.last_confirmed) ? frame : q.last_confirmed;
  if (s->confirmed != NULL_FRAME && frame_lt(f, s->confirmed))
    f = s->confirmed;
  auto it = s->disc_frame.find(handle);
  if (it != s->disc_frame.end() && !frame_lt(f, it->second)) return;
  s->disc_frame[handle] = f;
  q.truncate_after(f);
  Frame nxt = f + 1;
  if (frame_lt(nxt, s->current_frame) &&
      (q.first_incorrect == NULL_FRAME ||
       frame_lt(nxt, q.first_incorrect)))
    q.first_incorrect = nxt;
  s->disc_notices[handle] = {f, now_s() + DISC_NOTICE_REBROADCAST_S};
}

void ggrs_p2p_poll(GgrsP2P *s) {
  uint8_t buf[65536];
  Addr from;
  int n;
  while ((n = s->sock.recv_from(&from, buf, sizeof buf)) >= 0) {
    auto it = s->endpoints.find(from);
    if (it != s->endpoints.end()) { it->second->handle(buf, (size_t)n); continue; }
    auto st = s->spectator_endpoints.find(from);
    if (st != s->spectator_endpoints.end()) st->second->handle(buf, (size_t)n);
  }
  for (auto &[addr, ep] : s->spectator_endpoints) {
    ep->poll();
    for (auto &e : ep->events) s->events.push_back(e);
    ep->events.clear();
    ep->inbox.clear();
    ep->checksum_inbox.clear();
    if (ep->state == GGRS_RUNNING && !ep->disconnected)
      ep->send_inputs(s->spectator_sent);
  }
  for (auto &[addr, ep] : s->endpoints) {
    if (ep->last_received_frame != NULL_FRAME) {
      int adv = frame_diff(s->current_frame, ep->last_received_frame);
      ep->local_advantage = adv;
      ep->time_sync.note_local(adv);
    }
    ep->poll();
    /* drain endpoint state into the session */
    for (auto &e : ep->events) s->events.push_back(e);
    ep->events.clear();
    /* an endpoint marked disconnected — possibly by a T_DISC_NOTICE
     * processed EARLIER in this same poll — must not drain its inboxes
     * into the queues: re-adding inputs past the just-adopted consensus
     * frame would silently re-extend last_confirmed and desync us from
     * the other survivors (the python core is immune because
     * PeerEndpoint.handle() drops packets the instant the flag is set;
     * here the recv loop filled the inbox before the notice ran) */
    if (ep->disconnected) {
      ep->have_base_inbox = false;
      ep->inbox.clear();
      ep->checksum_inbox.clear();
      /* disc notices too: a dropped peer must not keep forcing consensus
       * adoptions below (same staleness as its queued inputs) */
      ep->disc_notice_inbox.clear();
    }
    if (ep->have_base_inbox) {
      ep->have_base_inbox = false;
      for (int h : s->handles_of_addr[addr])
        s->queues[h].set_base(ep->base_inbox);
    }
    for (auto &[f, raw] : ep->inbox) {
      auto &handles = s->handles_of_addr[addr];
      for (size_t i = 0; i < handles.size(); i++)
        s->queues[handles[i]].add_remote(f, raw.data() + i * s->input_size);
    }
    ep->inbox.clear();
    /* desync compare (or park until our local checksum exists) */
    for (auto &[f, remote_cs] : ep->checksum_inbox) {
      auto it = s->local_checksums.find(f);
      if (it == s->local_checksums.end())
        s->remote_checksums[f].emplace_back(addr, remote_cs);
      else if (it->second != remote_cs)
        s->events.push_back({GGRS_EV_DESYNC, f, remote_cs, addr, it->second});
    }
    ep->checksum_inbox.clear();
    for (auto &[h, f] : ep->disc_notice_inbox) {
      auto it2 = s->remote_handle_addr.find(h);
      if (it2 == s->remote_handle_addr.end() || it2->second == addr)
        continue; /* our handle, unknown, or a peer announcing itself */
      auto &dep = s->endpoints[it2->second];
      if (!dep->disconnected) {
        /* consistency over liveness: fast-propagate the drop, adopting
         * every handle of the dead peer from local knowledge first */
        dep->disconnected = true;
        dep->events.push_back({GGRS_EV_DISCONNECTED, 0, 0, it2->second});
        s->disc_corrected.push_back(it2->second);
        for (int hh : s->handles_of_addr[it2->second])
          adopt_disconnect(s, hh, s->queues[hh].last_confirmed);
      }
      adopt_disconnect(s, h, f);
    }
    ep->disc_notice_inbox.clear();
    if (ep->state == GGRS_RUNNING && !ep->disconnected)
      ep->send_inputs(s->local_sent);
  }
  /* a remote just hit the disconnect timeout: frames advanced on its served
   * predictions will never be corrected by the wire (late packets are
   * dropped), yet input_for now reports DISCONNECTED/zero for its handles.
   * Force the mismatch-rollback now, BEFORE compute_confirmed (which skips
   * disconnected remotes) can leapfrog the uncorrected frames and the ring
   * prunes the rollback target (mirrors P2PSession._force_disconnect_
   * correction).  Pre-stream-base predictions are permanently correct (the
   * served default IS the input on every peer) and stay untouched. */
  for (auto &[addr, ep] : s->endpoints) {
    if (!ep->disconnected) continue;
    bool seen = false;
    for (auto &a : s->disc_corrected) seen |= (a == addr);
    if (seen) continue;
    s->disc_corrected.push_back(addr);
    for (int h : s->handles_of_addr[addr])
      adopt_disconnect(s, h, s->queues[h].last_confirmed);
  }
  /* rebroadcast adopted consensus frames while their window is open
   * (notices ride lossy links; receipt is idempotent under the min rule) */
  if (!s->disc_notices.empty()) {
    double now = now_s();
    for (auto it = s->disc_notices.begin(); it != s->disc_notices.end();) {
      if (now >= it->second.second) {
        it = s->disc_notices.erase(it);
        continue;
      }
      for (auto &[a2, ep2] : s->endpoints)
        if (!ep2->disconnected && ep2->state == GGRS_RUNNING)
          ep2->send_disc_notice(it->first, it->second.first);
      ++it;
    }
  }
}

int ggrs_p2p_add_local_input(GgrsP2P *s, int handle, const uint8_t *data) {
  bool is_local = false;
  for (int h : s->local_handles) is_local |= (h == handle);
  if (!is_local) return GGRS_ERR_INVALID_REQUEST;
  if (ggrs_p2p_state(s) != GGRS_RUNNING) return GGRS_ERR_NOT_SYNCHRONIZED;
  s->staged[handle] = std::vector<uint8_t>(data, data + s->input_size);
  return GGRS_OK;
}

static Frame compute_confirmed(GgrsP2P *s) {
  Frame c = s->current_frame;
  for (auto &[h, addr] : s->remote_handle_addr) {
    auto &ep = s->endpoints[addr];
    if (ep->disconnected) continue;
    Frame lc = s->queues[h].last_confirmed;
    if (lc == NULL_FRAME || frame_lt(lc, c)) c = lc;
    if (c == NULL_FRAME) break;
  }
  return c;
}

int ggrs_p2p_advance(GgrsP2P *s, int32_t *req_buf, int req_cap,
                     uint8_t *input_buf, int input_cap, int *n_req_words,
                     int *n_input_bytes) {
  *n_req_words = 0;
  *n_input_bytes = 0;
  if (ggrs_p2p_state(s) != GGRS_RUNNING) return GGRS_ERR_NOT_SYNCHRONIZED;
  for (int h : s->local_handles)
    if (!s->staged.count(h)) return GGRS_ERR_INVALID_REQUEST;

  Frame new_confirmed = compute_confirmed(s);
  /* confirmed must not advance past a pending mispredicted frame — the
   * rollback target has to remain loadable from the driver's ring */
  for (auto &q : s->queues) {
    Frame fi = q.first_incorrect;
    if (fi != NULL_FRAME &&
        (new_confirmed == NULL_FRAME || frame_lt(fi, new_confirmed)))
      new_confirmed = fi;
  }
  if (frame_diff(s->current_frame, new_confirmed) > s->max_prediction) {
    s->staged.clear();
    return GGRS_ERR_PREDICTION_THRESHOLD;
  }

  /* commit + broadcast local inputs */
  Frame eff = NULL_FRAME;
  for (int h : s->local_handles)
    eff = s->queues[h].add_local(s->current_frame, s->staged[h].data());
  s->staged.clear();
  if (!s->local_handles.empty()) {
    std::vector<uint8_t> row;
    for (int h : s->local_handles) {
      const auto *v = s->queues[h].confirmed(eff);
      row.insert(row.end(), v->begin(), v->end());
    }
    s->local_sent.emplace_back(eff, std::move(row));
    for (auto &[a, ep] : s->endpoints)
      if (ep->state == GGRS_RUNNING && !ep->disconnected)
        ep->send_inputs(s->local_sent);
  }

  int rw = 0, ib = 0;
  auto emit_save = [&](Frame f) -> bool {
    if (rw + 2 > req_cap) return false;
    req_buf[rw++] = GGRS_REQ_SAVE;
    req_buf[rw++] = f;
    return true;
  };
  auto emit_load = [&](Frame f) -> bool {
    if (rw + 2 > req_cap) return false;
    req_buf[rw++] = GGRS_REQ_LOAD;
    req_buf[rw++] = f;
    return true;
  };
  auto emit_advance = [&](Frame f) -> bool {
    if (rw + 2 + s->num_players > req_cap) return false;
    if (ib + s->num_players * s->input_size > input_cap) return false;
    req_buf[rw++] = GGRS_REQ_ADVANCE;
    req_buf[rw++] = f;
    for (int h = 0; h < s->num_players; h++) {
      int status;
      auto it = s->remote_handle_addr.find(h);
      if (it != s->remote_handle_addr.end() && s->endpoints[it->second]->disconnected) {
        /* frames at/below the consensus frame keep their REAL confirmed
         * input (a deep rollback must reproduce the original sim); only
         * frames past it bake the disconnect policy */
        const std::vector<uint8_t> *v = s->queues[h].confirmed(f);
        if (v != nullptr) {
          memcpy(input_buf + ib, v->data(), s->input_size);
          status = GGRS_INPUT_CONFIRMED;
        } else {
          status = GGRS_INPUT_DISCONNECTED;
          memset(input_buf + ib, 0, s->input_size);
        }
      } else {
        status = s->queues[h].input_for(f, input_buf + ib);
      }
      req_buf[rw++] = status;
      ib += s->input_size;
    }
    return true;
  };

  /* rollback on misprediction */
  Frame first_incorrect = NULL_FRAME;
  for (auto &q : s->queues) {
    Frame f = q.take_first_incorrect();
    if (f != NULL_FRAME &&
        (first_incorrect == NULL_FRAME || frame_lt(f, first_incorrect)))
      first_incorrect = f;
  }
  bool rolled_back = false;
  if (first_incorrect != NULL_FRAME && frame_lt(first_incorrect, s->current_frame)) {
    if (!emit_load(first_incorrect)) return GGRS_ERR_BUFFER_TOO_SMALL;
    for (Frame i = first_incorrect; frame_lt(i, s->current_frame); i++) {
      if (!emit_advance(i)) return GGRS_ERR_BUFFER_TOO_SMALL;
      if (!emit_save(i + 1)) return GGRS_ERR_BUFFER_TOO_SMALL;
    }
    rolled_back = true;
  }

  s->confirmed = new_confirmed;

  /* gc */
  Frame horizon = s->confirmed - s->max_prediction - 2;
  for (auto &q : s->queues) q.gc(horizon);
  /* trim pending input history to the oldest ack across CONNECTED peers.
   * A connected peer that has not acked anything yet (last_acked ==
   * NULL_FRAME — still syncing, or all its acks were lost) blocks trimming
   * entirely: dropping frames it never saw would stall it forever.  With no
   * connected peers left the history has no consumer and is dropped.
   * (Matches session/p2p.py _min_ack.) */
  Frame acked = NULL_FRAME;
  bool keep_all = false, any_connected = false;
  for (auto &[a, ep] : s->endpoints) {
    if (ep->disconnected) continue;
    any_connected = true;
    if (ep->last_acked == NULL_FRAME) { keep_all = true; break; }
    if (acked == NULL_FRAME || frame_lt(ep->last_acked, acked))
      acked = ep->last_acked;
  }
  if (!any_connected)
    s->local_sent.clear();
  else if (!keep_all)
    while (!s->local_sent.empty() && acked != NULL_FRAME &&
           frame_le(s->local_sent.front().first, acked))
      s->local_sent.pop_front();
  while ((int)s->local_sent.size() > MAX_UNACKED_FRAMES)
    s->local_sent.pop_front();
  for (auto it = s->local_checksums.begin(); it != s->local_checksums.end();)
    it = frame_lt(it->first, horizon) ? s->local_checksums.erase(it) : std::next(it);
  for (auto it = s->remote_checksums.begin(); it != s->remote_checksums.end();)
    it = frame_lt(it->first, horizon) ? s->remote_checksums.erase(it) : std::next(it);

  if (!rolled_back && !emit_save(s->current_frame))
    return GGRS_ERR_BUFFER_TOO_SMALL;
  if (!emit_advance(s->current_frame)) return GGRS_ERR_BUFFER_TOO_SMALL;
  s->current_frame++;

  /* stream newly confirmed all-player input rows to spectators */
  if (!s->spectator_endpoints.empty() && s->confirmed != NULL_FRAME) {
    while (frame_le(s->next_spectator_frame, s->confirmed)) {
      Frame f = s->next_spectator_frame;
      std::vector<uint8_t> row;
      row.reserve((size_t)s->num_players * (s->input_size + 1));
      std::vector<uint8_t> stats;
      stats.reserve(s->num_players);
      for (int h = 0; h < s->num_players; h++) {
        const auto *v = s->queues[h].confirmed(f);
        if (v) {
          row.insert(row.end(), v->begin(), v->end());
          stats.push_back((uint8_t)GGRS_INPUT_CONFIRMED);
        } else {
          /* stream the status the HOST's sim used: DISCONNECTED for a
           * dead player's post-consensus frames, PREDICTED (default
           * input) for pre-stream-base frames */
          row.insert(row.end(), (size_t)s->input_size, 0);
          auto it = s->remote_handle_addr.find(h);
          bool disc = it != s->remote_handle_addr.end() &&
                      s->endpoints[it->second]->disconnected;
          stats.push_back((uint8_t)(disc ? GGRS_INPUT_DISCONNECTED
                                         : GGRS_INPUT_PREDICTED));
        }
      }
      row.insert(row.end(), stats.begin(), stats.end());
      s->spectator_sent.emplace_back(f, std::move(row));
      s->next_spectator_frame = f + 1;
    }
    /* same keep-all-until-every-connected-spectator-acks rule as the peer
     * history above: a late-syncing spectator must still be able to pull the
     * stream from its base */
    Frame acked = NULL_FRAME;
    bool keep_all = false, any_connected = false;
    for (auto &[a2, ep] : s->spectator_endpoints) {
      if (ep->disconnected) continue;
      any_connected = true;
      if (ep->last_acked == NULL_FRAME) { keep_all = true; break; }
      if (acked == NULL_FRAME || frame_lt(ep->last_acked, acked))
        acked = ep->last_acked;
    }
    if (!any_connected)
      s->spectator_sent.clear();
    else if (!keep_all) {
      while (!s->spectator_sent.empty() && acked != NULL_FRAME &&
             frame_le(s->spectator_sent.front().first, acked))
        s->spectator_sent.pop_front();
      /* hard cap: an ACKING spectator >8 chunks (~8.5 s at 60fps) behind
       * starts losing the oldest frames (it should have been catching up) */
      while ((int)s->spectator_sent.size() > 8 * MAX_INPUTS_PER_PACKET)
        s->spectator_sent.pop_front();
    }
    /* absolute bound, applied even while a connected spectator has acked
     * nothing (keepalives alive, acks lost one-way) — see MAX_UNACKED_FRAMES */
    while ((int)s->spectator_sent.size() > MAX_UNACKED_FRAMES)
      s->spectator_sent.pop_front();
  }
  *n_req_words = rw;
  *n_input_bytes = ib;
  return GGRS_OK;
}

int32_t ggrs_p2p_current_frame(GgrsP2P *s) { return s->current_frame; }
int32_t ggrs_p2p_confirmed_frame(GgrsP2P *s) { return s->confirmed; }
int ggrs_p2p_max_prediction(GgrsP2P *s) { return s->max_prediction; }
int ggrs_p2p_num_players(GgrsP2P *s) { return s->num_players; }

int ggrs_p2p_frames_ahead(GgrsP2P *s) {
  int m = 0;
  for (auto &[a, ep] : s->endpoints)
    if (!ep->disconnected) {
      int v = ep->time_sync.frames_ahead();
      if (v > m) m = v;
    }
  return m;
}

int ggrs_p2p_local_handles(GgrsP2P *s, int32_t *out, int cap) {
  int n = 0;
  for (int h : s->local_handles)
    if (n < cap) out[n++] = h;
  return n;
}

int ggrs_p2p_next_event(GgrsP2P *s, int32_t *kind, int32_t *a, uint64_t *b,
                        uint64_t *b2, char *addrbuf, int addrcap) {
  if (s->events.empty()) return 0;
  Event e = s->events.front();
  s->events.pop_front();
  *kind = e.kind;
  *a = e.a;
  *b = e.b;
  *b2 = e.b2;
  std::string str = e.addr.str();
  snprintf(addrbuf, addrcap, "%s", str.c_str());
  return 1;
}

void ggrs_p2p_push_checksum(GgrsP2P *s, int32_t frame, uint64_t checksum) {
  if (s->desync_interval <= 0) return;
  if (frame % s->desync_interval != 0) return;
  s->local_checksums[frame] = checksum;
  auto pit = s->remote_checksums.find(frame);
  if (pit != s->remote_checksums.end()) {
    for (auto &[addr, remote_cs] : pit->second)
      if (remote_cs != checksum)
        s->events.push_back({GGRS_EV_DESYNC, frame, remote_cs, addr, checksum});
    s->remote_checksums.erase(pit);
  }
  for (auto &[a, ep] : s->endpoints)
    if (ep->state == GGRS_RUNNING && !ep->disconnected)
      ep->send_checksum(frame, checksum);
}

/* ---- spectator client session ------------------------------------------ */

struct GgrsSpectator {
  int num_players = 2, input_size = 1, catchup_speed = 1;
  UdpSocket sock;
  Addr host;
  std::unique_ptr<Endpoint> ep;
  Frame current_frame = 0;
  std::map<Frame, std::vector<uint8_t>, bool (*)(Frame, Frame)> inputs{frame_lt};
  std::deque<Event> events;
  std::mt19937 rng{std::random_device{}()};
};

extern "C" {

GgrsSpectator *ggrs_spectator_create(int num_players, int input_size,
                                     uint16_t local_port, const char *host_ip,
                                     uint16_t host_port,
                                     double disconnect_timeout_s,
                                     double disconnect_notify_s,
                                     int catchup_speed) {
  auto *s = new GgrsSpectator();
  s->num_players = num_players;
  s->input_size = input_size;
  s->catchup_speed = catchup_speed;
  if (!s->sock.open(local_port)) { delete s; return nullptr; }
  s->host.ip = inet_addr(host_ip ? host_ip : "127.0.0.1");
  s->host.port = host_port;
  auto ep = std::make_unique<Endpoint>();
  ep->addr = s->host;
  ep->sock = &s->sock;
  ep->input_size = input_size * num_players + num_players; /* inputs + status bytes */
  ep->sync_nonce = s->rng();
  ep->disconnect_timeout_s = disconnect_timeout_s;
  ep->disconnect_notify_s = disconnect_notify_s;
  ep->init(now_s());
  s->ep = std::move(ep);
  return s;
}

void ggrs_spectator_destroy(GgrsSpectator *s) { delete s; }
uint16_t ggrs_spectator_local_port(GgrsSpectator *s) { return s->sock.local_port(); }
int ggrs_spectator_state(GgrsSpectator *s) { return s->ep->state; }
int32_t ggrs_spectator_current_frame(GgrsSpectator *s) { return s->current_frame; }

int32_t ggrs_spectator_frames_behind(GgrsSpectator *s) {
  if (s->ep->last_received_frame == NULL_FRAME) return 0;
  Frame d = frame_diff(s->ep->last_received_frame, s->current_frame);
  return d > 0 ? d : 0;
}

void ggrs_spectator_poll(GgrsSpectator *s) {
  uint8_t buf[65536];
  Addr from;
  int n;
  while ((n = s->sock.recv_from(&from, buf, sizeof buf)) >= 0)
    if (from == s->host) s->ep->handle(buf, (size_t)n);
  s->ep->poll();
  for (auto &e : s->ep->events) s->events.push_back(e);
  s->ep->events.clear();
  for (auto &[f, raw] : s->ep->inbox) s->inputs[f] = raw;
  s->ep->inbox.clear();
  s->ep->checksum_inbox.clear();
  if (s->ep->state == GGRS_RUNNING) s->ep->send_input_ack();
}

int ggrs_spectator_advance(GgrsSpectator *s, int32_t *req_buf, int req_cap,
                           uint8_t *input_buf, int input_cap,
                           int *n_req_words, int *n_input_bytes) {
  *n_req_words = 0;
  *n_input_bytes = 0;
  if (s->ep->state != GGRS_RUNNING) return GGRS_ERR_NOT_SYNCHRONIZED;
  if (!s->inputs.count(s->current_frame))
    return GGRS_ERR_PREDICTION_THRESHOLD;
  int n = 1;
  if (ggrs_spectator_frames_behind(s) > 2) n += s->catchup_speed > 0 ? s->catchup_speed : 0;
  int rw = 0, ib = 0;
  int row_inputs = s->num_players * s->input_size;
  for (int i = 0; i < n; i++) {
    auto it = s->inputs.find(s->current_frame);
    if (it == s->inputs.end()) break;
    if (rw + 2 + s->num_players > req_cap || ib + row_inputs > input_cap)
      return GGRS_ERR_BUFFER_TOO_SMALL;
    req_buf[rw++] = GGRS_REQ_ADVANCE;
    req_buf[rw++] = s->current_frame;
    for (int h = 0; h < s->num_players; h++) {
      /* per-player status streamed by the host (row tail; the endpoint
       * slicer only stores full input_size rows, so the tail is always
       * present — a fallback here would silently re-mark dead players
       * CONFIRMED, the exact bug the status stream closes) */
      req_buf[rw++] = (int32_t)it->second[row_inputs + h];
    }
    memcpy(input_buf + ib, it->second.data(), row_inputs);
    ib += row_inputs;
    s->inputs.erase(it);
    s->current_frame = s->current_frame + 1;
  }
  *n_req_words = rw;
  *n_input_bytes = ib;
  return GGRS_OK;
}

int ggrs_spectator_next_event(GgrsSpectator *s, int32_t *kind, int32_t *a,
                              uint64_t *b, uint64_t *b2, char *addrbuf,
                              int addrcap) {
  if (s->events.empty()) return 0;
  Event e = s->events.front();
  s->events.pop_front();
  *kind = e.kind;
  *a = e.a;
  *b = e.b;
  *b2 = e.b2;
  std::string str = e.addr.str();
  snprintf(addrbuf, addrcap, "%s", str.c_str());
  return 1;
}

} /* extern "C" */

int ggrs_p2p_stats(GgrsP2P *s, int handle, double *ping_ms, int *send_queue,
                   double *kbps_sent, int *local_frames_behind,
                   int *remote_frames_behind) {
  auto it = s->remote_handle_addr.find(handle);
  if (it == s->remote_handle_addr.end()) return GGRS_ERR_INVALID_REQUEST;
  auto &ep = s->endpoints[it->second];
  double elapsed = now_s() - ep->created;
  if (elapsed < 1e-6) elapsed = 1e-6;
  *ping_ms = ep->ping_s * 1e3;
  *send_queue = ep->send_queue_len;
  *kbps_sent = (double)ep->bytes_sent * 8 / 1000 / elapsed;
  *local_frames_behind = -ep->time_sync.local_advantage();
  *remote_frames_behind = -ep->remote_advantage;
  return GGRS_OK;
}

} /* extern "C" */
