#!/usr/bin/env python
"""Bench-history regression gate over the BENCH_r*.json trajectory.

Each bench round writes one ``BENCH_rNN.json`` record (``{"n", "cmd", "rc",
"tail", "parsed": {...}}`` — see bench.py).  This tool reads the whole
trajectory, compares the LATEST record's throughput metrics against the best
prior value of each metric, prints a per-stage delta table, and exits
nonzero when any metric regressed more than ``--threshold`` (default 10%).

Rules that keep the gate honest on this heterogeneous history:

- only records with ``rc == 0`` count (a crashed round proves nothing);
- only prior records on the SAME platform as the latest are compared
  (a CPU round "regressing" against a TPU round is not a regression);
- higher-is-better throughput metrics participate (``*fps*``,
  ``*per_sec*``, ``*speedup*``, ``*frames_per_dispatch*``, and the headline
  ``value``) — spreads, byte counts and percentages are reported by
  bench.py but not gated;
- LOWER-is-better upload-census metrics (``*uploads_per_tick*``,
  ``*dispatches_per_tick*``, ``*uploads_per_flush*`` from the ``uploads``
  stage, plus latency floors like ``rollback_service_p99_ms`` and
  ``migration_downtime_ms``) gate in the opposite direction: the latest is
  compared against the best (lowest) prior and an increase past the
  threshold fails — their table delta is printed as "goodness" (negative =
  got worse);
- the gate is SPREAD-AWARE: a throughput delta inside either record's own
  per-stage spread (bench.py ships ``(max-min)/median`` per stage, see
  ``stage_spreads``) is annotated "within spread" and not flagged — that is
  measured run-to-run wobble, not a regression — and ms-scale latency
  floors tolerate an absolute increase of ``_MS_FLOOR_SLACK`` ms whatever
  the ratio;
- metrics the latest record does not carry are skipped, not failed
  (stage sets grew over rounds — r01 had no batched stage).

``scripts/check.sh`` runs this with ``--warn-only`` (soft gate: the table
prints, regressions warn, the exit code stays 0) because single-shot bench
numbers on a shared 1-core host are noisy; CI trend enforcement should run
it bare after a reps>=5 bench run.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

# higher-is-better selector: any numeric parsed key matching one of these is
# a gated throughput metric ("value" is the headline resim fps);
# frames_per_dispatch is the megastep flatness ratio (~N when every flush
# retires as one dispatch — falling means the fused program split)
_METRIC_RE = re.compile(r"(fps|per_sec|speedup|ticks_per_sec|"
                        r"frames_per_dispatch)")
_EXCLUDE_RE = re.compile(r"(spread|bytes|pct|entities|depth|reps|lobbies)")

# LOWER-is-better floor metrics: the packed/megastep/input-queue upload
# censuses (bench.py stage_uploads) must hold at 1.0 per tick / per flush —
# an INCREASE past the threshold is the regression (a staging path grew an
# extra host->device upload or split a dispatch) — the speculation
# stage's rollback-servicing p99s (bench.py _speculation_service_arm),
# where an increase means rollback servicing got slower, and the fleet
# stage's live-migration downtime and SLO alert latency (bench.py
# stage_fleet — stall-to-fire for the induced heartbeat_liveness breach)
_FLOOR_RE = re.compile(r"(uploads_per_tick|dispatches_per_tick|"
                       r"uploads_per_flush|rollback_service_p99_ms|"
                       r"migration_downtime_ms|fleet_alert_latency_ms)")

# ms-scale floors carry scheduling jitter that dwarfs their absolute size
# (a 7ms -> 25ms migration downtime is +257% relative but meaningless);
# an increase within this many ms is never flagged, whatever the ratio
_MS_FLOOR_SLACK = 50.0


def load_records(dir: str) -> list:
    """All parsable ``BENCH_r*.json`` records in round order, as
    ``(round, parsed_dict)`` pairs; crashed (rc != 0) and malformed records
    are dropped with a note on stderr."""
    out = []
    for path in sorted(glob.glob(os.path.join(dir, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bench_history: skipping {path}: {e}", file=sys.stderr)
            continue
        if rec.get("rc", 0) != 0:
            print(f"bench_history: skipping {path}: rc={rec['rc']}",
                  file=sys.stderr)
            continue
        parsed = rec.get("parsed")
        if isinstance(parsed, dict):
            # record-level annotations (human notes on known noise, e.g. the
            # r04->r05 batched wobble) ride along; string values never enter
            # the numeric metric extractors
            if isinstance(rec.get("annotations"), list):
                parsed = dict(parsed, __annotations__=rec["annotations"])
            out.append((int(m.group(1)), parsed))
    return out


def _flatten(d: dict, prefix: str = "") -> dict:
    """Nested parsed dicts -> dotted flat keys (``stage_platforms.batched``)."""
    flat = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten(v, key + "."))
        else:
            flat[key] = v
    return flat


def throughput_metrics(parsed: dict) -> dict:
    """The gated higher-is-better numeric metrics of one parsed record."""
    out = {}
    for k, v in _flatten(parsed).items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if _EXCLUDE_RE.search(k) or _FLOOR_RE.search(k):
            continue
        if k == "value" or _METRIC_RE.search(k):
            out[k] = float(v)
    return out


def floor_metrics(parsed: dict) -> dict:
    """The gated LOWER-is-better census metrics of one parsed record."""
    out = {}
    for k, v in _flatten(parsed).items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if _FLOOR_RE.search(k):
            out[k] = float(v)
    return out


def _spread_for(flat: dict, metric: str) -> float:
    """Best-effort run-to-run spread fraction for the stage a metric belongs
    to, read from the record's own spread keys (bench.py ships every stage's
    ``(max-min)/median`` spread, duplicated under ``stage_spreads``).  0.0
    when the record carries no matching spread."""
    stage = metric.split(".")[0] if "." in metric else metric.split("_")[0]
    out = 0.0
    for k, v in flat.items():
        if "spread" not in k:
            continue
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        # abbreviated spread names still match their stage ("spread_canon"
        # covers canonical_mode_fps): compare stems prefix-wise
        stem = re.sub(r"spread|stage|[._]", "", k)
        stem_match = len(stem) >= 4 and (stage.startswith(stem)
                                         or stem.startswith(stage))
        if stage in k or stem_match or (metric == "value" and k == "spread"):
            out = max(out, float(v))
    return out


def compare(records: list, threshold: float) -> tuple:
    """Latest-vs-best-prior comparison.

    Returns ``(rows, regressions)`` where each row is ``(metric, best_prior,
    best_round, latest, delta_frac_or_None, note)``.  ``regressions`` lists
    the rows whose delta is below ``-threshold`` AND outside the measured
    noise: a throughput delta inside either record's own per-stage spread is
    annotated ``within spread`` instead of flagged (single-shot numbers on a
    shared host wobble; the spread is the measured wobble), and an ms-scale
    floor increase inside ``_MS_FLOOR_SLACK`` is annotated ``within ms
    slack`` (relative deltas on ~10ms latencies are jitter, not signal)."""
    latest_round, latest = records[-1]
    platform = latest.get("platform")
    latest_flat = _flatten(latest)
    priors = [
        (n, p) for n, p in records[:-1]
        if platform is None or p.get("platform") == platform
    ]
    rows, regressions = [], []
    for extract, lower_is_better in ((throughput_metrics, False),
                                     (floor_metrics, True)):
        latest_m = extract(latest)
        for metric in sorted(latest_m):
            best = best_round = best_parsed = None
            for n, p in priors:
                v = extract(p).get(metric)
                if v is None or v <= 0:
                    continue
                if best is None or (v < best if lower_is_better
                                    else v > best):
                    best, best_round, best_parsed = v, n, p
            if best is None:
                rows.append((metric, None, None, latest_m[metric], None, ""))
                continue
            # delta is always "goodness": negative = got worse, so the
            # single `< -threshold` regression test covers both directions
            delta = (latest_m[metric] - best) / best
            if lower_is_better:
                delta = -delta
            note = ""
            if delta < -threshold:
                if lower_is_better and metric.endswith("_ms") and (
                        latest_m[metric] - best <= _MS_FLOOR_SLACK):
                    note = "within ms slack"
                elif not lower_is_better:
                    noise = max(_spread_for(latest_flat, metric),
                                _spread_for(_flatten(best_parsed), metric))
                    if -delta <= noise:
                        note = "within spread"
            row = (metric, best, best_round, latest_m[metric], delta, note)
            rows.append(row)
            if delta < -threshold and not note:
                regressions.append(row)
    return (latest_round, platform, rows, regressions)


def print_table(latest_round: int, platform, rows: list,
                threshold: float) -> None:
    """The per-stage delta table (stdout)."""
    print(f"bench history: BENCH_r{latest_round:02d} (platform={platform}) "
          f"vs best prior same-platform record, threshold {threshold:.0%}")
    w = max((len(r[0]) for r in rows), default=6)
    print(f"  {'metric':<{w}}  {'best prior':>12}  {'latest':>12}  delta")
    for metric, best, best_round, latest, delta, note in rows:
        if delta is None:
            print(f"  {metric:<{w}}  {'-':>12}  {latest:>12.1f}  (new)")
            continue
        if note:
            flag = f"  ({note})"
        else:
            flag = "  << REGRESSION" if delta < -threshold else ""
        print(f"  {metric:<{w}}  {best:>9.1f}(r{best_round:02d})"
              f"  {latest:>12.1f}  {delta:+7.1%}{flag}")


def main(argv=None) -> int:
    """CLI entry point; returns the process exit code."""
    ap = argparse.ArgumentParser(
        description="compare the latest BENCH_r*.json against the best "
                    "prior record and gate on throughput regressions")
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json (default: .)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="regression fraction that fails the gate "
                         "(default: 0.10 = 10%%)")
    ap.add_argument("--warn-only", action="store_true",
                    help="print the table and warnings but always exit 0")
    args = ap.parse_args(argv)

    records = load_records(args.dir)
    if len(records) < 2:
        print("bench_history: fewer than two usable records — nothing to "
              "compare")
        return 0
    latest_round, platform, rows, regressions = compare(
        records, args.threshold
    )
    print_table(latest_round, platform, rows, args.threshold)
    for note in records[-1][1].get("__annotations__", []):
        print(f"  note: {note}")
    if not any(r[4] is not None for r in rows):
        print("bench_history: no same-platform prior record — no gate")
        return 0
    if regressions:
        names = ", ".join(r[0] for r in regressions)
        print(f"bench_history: {len(regressions)} metric(s) regressed more "
              f"than {args.threshold:.0%}: {names}", file=sys.stderr)
        return 0 if args.warn_only else 1
    print("bench_history: no regressions past threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
