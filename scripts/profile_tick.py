#!/usr/bin/env python
"""Capture a device profile of driver ticks — the perf-diagnosis tool.

    python scripts/profile_tick.py --mode synctest --entities 2000 \
        --ticks 50 --logdir /tmp/ggrs_trace

Runs warmup ticks (compiles outside the capture), then records `--ticks`
ticks under ``jax.profiler.trace``; view the trace with TensorBoard/XProf.
Alongside the device trace it prints a host-side wall-time split per
runner-tick from the drivers' phase timers (telemetry/phases.py): network
poll, session step (SyncTest checksum comparison lives here), input
staging, wave dispatch, readback harvest, rollback load, store/save, and
the unattributed residual — so host-bound vs device-bound is obvious at a
glance.  ``--trace-out`` additionally writes the profiled window as a
Chrome-trace JSON (telemetry/trace.py) loadable in ui.perfetto.dev.  This
is the tool that pins whether a slow driver is paying link round-trips
(docs/tpu_notes.md §3b) or real compute."""

import argparse
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

import numpy as np


def build_runner(mode: str, entities: int, check_distance: int):
    from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
    from bevy_ggrs_tpu.models import stress

    app = stress.make_app(entities, capacity=entities)
    if mode == "synctest":
        session = SyncTestSession(
            num_players=2, input_shape=(), input_dtype=np.uint8,
            check_distance=check_distance,
        )
        return [GgrsRunner(app, session)], lambda: None
    # p2p pair over the in-process channel network
    from bevy_ggrs_tpu import PlayerType, SessionBuilder, SessionState
    from bevy_ggrs_tpu.session.channel import ChannelNetwork

    net = ChannelNetwork(latency_hops=2)
    socks = [net.endpoint("a"), net.endpoint("b")]
    runners = []
    for i in range(2):
        app_i = stress.make_app(entities, capacity=entities)
        b = (SessionBuilder.for_app(app_i).with_input_delay(1)
             .with_disconnect_timeout(60.0).with_disconnect_notify_delay(30.0)
             .add_player(PlayerType.LOCAL, i)
             .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a"))
        runners.append(GgrsRunner(app_i, b.start_p2p_session(socks[i])))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING
               for r in runners):
            break
        time.sleep(0.001)
    if not all(r.session.current_state() == SessionState.RUNNING
               for r in runners):
        raise SystemExit("p2p pair never reached RUNNING — nothing to profile")
    return runners, net.deliver


def _phase_totals(runners):
    """Sum the runners' cumulative PhaseSet totals (scripts-side copy so a
    delta over the profiled window survives warmup accumulation)."""
    agg = {"wall": 0.0, "unattributed": 0.0, "phases": {}}
    for r in runners:
        t = r.stats()["phases"]
        agg["wall"] += t["wall_seconds"]
        agg["unattributed"] += t["unattributed_seconds"]
        for name, s in t["phase_seconds"].items():
            agg["phases"][name] = agg["phases"].get(name, 0.0) + s
    return agg


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("synctest", "p2p"), default="synctest")
    ap.add_argument("--entities", type=int, default=2000)
    ap.add_argument("--check-distance", type=int, default=7)
    ap.add_argument("--ticks", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=50)
    ap.add_argument("--logdir", default="/tmp/ggrs_trace")
    ap.add_argument("--telemetry-out", default=None, metavar="PATH",
                    help="enable telemetry and write the profiled ticks' "
                         "timeline (spans, rollbacks, dispatches) as JSONL")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="enable telemetry and write the profiled ticks as "
                         "Chrome-trace JSON (load in ui.perfetto.dev)")
    ap.add_argument("--phase-breakdown", action="store_true",
                    help="print per-phase p50/p95/p99 latency over the "
                         "profiled window (exact values from the flight "
                         "recorder; needs no telemetry)")
    args = ap.parse_args()

    import jax

    from bevy_ggrs_tpu import telemetry

    if args.telemetry_out or args.trace_out:
        telemetry.enable()

    runners, deliver = build_runner(args.mode, args.entities,
                                    args.check_distance)

    for _ in range(args.warmup):
        deliver()
        for r in runners:
            r.tick()

    if args.telemetry_out or args.trace_out:
        telemetry.reset()  # drop warmup events: export the profiled window only
    fr = telemetry.flight_recorder()
    if args.phase_breakdown or args.trace_out:
        # the ring must hold the whole profiled window (exact percentiles /
        # one trace slice per tick)
        fr.set_maxlen(max(fr.maxlen, args.ticks * len(runners) + 16))
        fr.clear()
    base = _phase_totals(runners)
    t0 = time.perf_counter()
    with runners[0].profile(args.logdir):
        for _ in range(args.ticks):
            deliver()
            for r in runners:
                r.tick()
        # device drain: on accelerators the per-phase numbers above measure
        # async SUBMISSION only — queued device compute is paid here
        t_drain = time.perf_counter()
        for r in runners:
            jax.block_until_ready(r.world)
        drain = time.perf_counter() - t_drain
    wall = time.perf_counter() - t0

    runner_ticks = args.ticks * len(runners)
    cur = _phase_totals(runners)
    print(f"platform: {jax.devices()[0].platform}")
    print(f"{args.ticks} ticks x {len(runners)} runner(s) in {wall:.3f}s -> "
          f"{args.ticks / wall:.1f} ticks/s "
          f"({runner_ticks / wall:.1f} runner-ticks/s)")
    attributed = 0.0
    for name in telemetry.PHASES:
        total = cur["phases"].get(name, 0.0) - base["phases"].get(name, 0.0)
        if total <= 0.0:
            continue
        attributed += total
        print(f"  {name:20s} {total * 1e3 / runner_ticks:8.3f} ms/runner-tick")
    unattr = cur["unattributed"] - base["unattributed"]
    print(f"  {'(unattributed host)':20s} "
          f"{unattr * 1e3 / runner_ticks:8.3f} ms/runner-tick")
    print(f"  {'(device drain)':20s} "
          f"{drain * 1e3 / runner_ticks:8.3f} ms/runner-tick")
    untimed = wall - attributed - unattr - drain
    print(f"  {'(outside ticks)':20s} "
          f"{untimed * 1e3 / runner_ticks:8.3f} ms/runner-tick  "
          f"(deliver/profiler overhead between ticks)")
    if args.phase_breakdown:
        print("per-phase latency over the profiled window (ms/tick, exact):")
        print(telemetry.format_phase_table(
            telemetry.phase_breakdown(fr.snapshot("tick"))
        ))
    # upload census: packed staging cost shows under stage_inputs above;
    # this is the denominator that says whether it bought the single-upload
    # shape (docs/dispatch_floor.md "Packed uploads")
    st0 = runners[0].stats()
    d = st0["device_dispatches"]
    print(f"upload census: {st0['host_uploads']} host uploads / {d} "
          f"dispatches = {st0['host_uploads'] / max(d, 1):.2f} per dispatch "
          f"(packed={st0['packed']}, "
          f"{st0['packed_upload_bytes']} packed bytes"
          + (f", megastep: {st0['megastep_dispatches']} fused chunks, "
             f"{st0['fused_ring_loads']} ring loads" if st0["megastep"]
             else "") + ")")
    print(f"device trace written to {args.logdir} (view with xprof/"
          f"tensorboard)")
    if args.telemetry_out:
        n = telemetry.export_jsonl(args.telemetry_out)
        print(f"telemetry timeline: {n} events -> {args.telemetry_out}")
    if args.trace_out:
        n = telemetry.write_trace(args.trace_out)
        print(f"chrome trace: {n} events -> {args.trace_out} "
              f"(load in ui.perfetto.dev)")


if __name__ == "__main__":
    main()
