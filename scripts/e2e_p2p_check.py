#!/usr/bin/env python
"""E2E smoke: two P2P runners over real loopback UDP sockets — drives the
full stack (builder, UDP transport, sync handshake, protocol, driver with
fused dispatch + donation, readback).  Exits nonzero on failure.

Usage: BGT_PLATFORM=cpu python scripts/e2e_p2p_check.py [--ticks 60]
"""

import argparse
import time

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

import numpy as np

from bevy_ggrs_tpu import (
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    UdpNonBlockingSocket,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.events import SessionState


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ticks", type=int, default=60)
    args = ap.parse_args()

    socks = [UdpNonBlockingSocket(0, host="127.0.0.1") for _ in range(2)]
    addrs = [("127.0.0.1", s.local_addr[1]) for s in socks]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder(input_shape=(), input_dtype=np.uint8)
            .with_num_players(2)
            .with_input_delay(2)
            .with_max_prediction_window(8)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, addrs[1 - i])
        )
        sess = b.start_p2p_session(socks[i])
        rng = np.random.default_rng(42 + i)
        runners.append(
            GgrsRunner(
                app,
                sess,
                read_inputs=lambda hs, r=rng: {
                    h: np.uint8(r.integers(0, 16)) for h in hs
                },
            )
        )

    t0 = time.time()
    while any(
        r.session.current_state() != SessionState.RUNNING for r in runners
    ):
        for r in runners:
            r.update(0.0)
        time.sleep(0.001)
        assert time.time() - t0 < 60, "sync handshake timed out"
    print(f"RUNNING after {time.time() - t0:.2f}s", flush=True)

    for tick in range(args.ticks):
        for r in runners:
            r.update(1 / 60)
        if tick % 20 == 0:
            print(f"tick {tick} frames {runners[0].frame} {runners[1].frame}",
                  flush=True)
    # staggered phase: peer 1 only ticks every 3rd host frame (with 3x the
    # delta), so peer 0 must PREDICT its inputs and roll back on arrival —
    # exercises Load + donated-dispatch + leading-save-from-stacked
    for tick in range(args.ticks):
        runners[0].update(1 / 60)
        if tick % 3 == 2:
            runners[1].update(3 / 60)
    for r in runners:
        r.finish()
    s0, s1 = runners[0].stats(), runners[1].stats()
    keys = ("ticks", "rollbacks", "device_dispatches", "frame", "confirmed")
    print("stats0:", {k: s0[k] for k in keys})
    print("stats1:", {k: s1[k] for k in keys})
    assert s0["frame"] > args.ticks // 2, "peer 0 did not advance"
    assert s1["frame"] > args.ticks // 2, "peer 1 did not advance"
    assert s0["rollbacks"] + s1["rollbacks"] > 0, (
        "staggered phase produced no rollbacks — prediction path unexercised"
    )
    c0 = runners[0].read_components(["pos"])
    moved = bool(np.abs(c0["pos"]).sum() > 0)
    print("pos readback:", c0["pos"].shape, "moved:", moved)
    assert moved
    print("E2E P2P OK")


if __name__ == "__main__":
    main()
