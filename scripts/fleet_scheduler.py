#!/usr/bin/env python
"""Fleet scheduler daemon: matchmaking, placement, migration, failover.

    python scripts/fleet_scheduler.py --port 3600 --metrics-port 9464

Workers (scripts/fleet_worker.py) register against the port; clients submit
lobbies with SUBMIT datagrams (bevy_ggrs_tpu.fleet.FleetClient).  The 5 s
reporting loop prints the federated ``/fleet`` snapshot (same schema the
HTTP endpoint serves — one schema for CLI and scrapers); with
``--metrics-port`` the registry is scrapable as Prometheus text plus the
``/fleet`` and ``/qos`` JSON routes (docs/observability.md "Fleet
federation & SLOs").  ``--status URL`` is a one-shot client mode: fetch a
running scheduler's ``/fleet`` JSON, pretty-print it, exit."""

import argparse
import json
import sys
import time
import urllib.request

sys.path.insert(0, ".")

from bevy_ggrs_tpu import telemetry
from bevy_ggrs_tpu.fleet import FleetScheduler, start_fleet_exporter


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=3600)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--worker-timeout", type=float, default=2.0,
                    help="heartbeat silence before a worker is declared "
                         "dead and its lobbies failed over (s)")
    ap.add_argument("--mem-budget-mb", type=int, default=512,
                    help="per-worker device-bytes admission budget")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus /metrics on this port")
    ap.add_argument("--metrics-host", default="127.0.0.1")
    ap.add_argument("--status", metavar="URL", default=None,
                    help="one-shot: fetch /fleet from a running scheduler's "
                         "metrics endpoint (host:port or full URL), "
                         "pretty-print, exit")
    args = ap.parse_args()
    if args.status is not None:
        url = args.status
        if "://" not in url:
            url = "http://" + url
        if not url.rstrip("/").endswith("/fleet"):
            url = url.rstrip("/") + "/fleet"
        with urllib.request.urlopen(url, timeout=5) as resp:
            snap = json.load(resp)
        json.dump(snap, sys.stdout, indent=2, sort_keys=True)
        print()
        return
    telemetry.enable()
    sched = FleetScheduler(
        host=args.host, port=args.port,
        worker_timeout_s=args.worker_timeout,
        mem_budget_bytes=args.mem_budget_mb * 1024 * 1024,
    )
    exporter = None
    if args.metrics_port is not None:
        exporter = start_fleet_exporter(
            sched.observer, port=args.metrics_port, host=args.metrics_host
        )
        print(f"metrics on http://{args.metrics_host}:{exporter.port}"
              f"/metrics (+ /fleet, /qos)", flush=True)
    print(f"fleet scheduler on {sched.local_addr}", flush=True)
    last_report = 0.0
    try:
        while True:
            sched.poll()
            now = time.monotonic()
            if now - last_report >= 5.0:
                last_report = now
                snap = sched.fleet_snapshot(tail=4)
                if snap["workers"] or snap["lobbies"]:
                    print(json.dumps(
                        {k: snap[k]
                         for k in ("schema", "workers", "lobbies", "alerts")}
                    ), flush=True)
            time.sleep(0.002)
    except KeyboardInterrupt:
        pass
    finally:
        sched.close()
        if exporter is not None:
            exporter.close()


if __name__ == "__main__":
    main()
