#!/usr/bin/env python
"""Fleet scheduler daemon: matchmaking, placement, migration, failover.

    python scripts/fleet_scheduler.py --port 3600 --metrics-port 9464

Workers (scripts/fleet_worker.py) register against the port; clients submit
lobbies with SUBMIT datagrams (bevy_ggrs_tpu.fleet.FleetClient).  The 5 s
reporting loop prints the placement snapshot and refreshes the ``fleet_*``
gauges; with ``--metrics-port`` the registry is scrapable as Prometheus
text (docs/observability.md "Fleet scheduling")."""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu import telemetry
from bevy_ggrs_tpu.fleet import FleetScheduler


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=3600)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--worker-timeout", type=float, default=2.0,
                    help="heartbeat silence before a worker is declared "
                         "dead and its lobbies failed over (s)")
    ap.add_argument("--mem-budget-mb", type=int, default=512,
                    help="per-worker device-bytes admission budget")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus /metrics on this port")
    ap.add_argument("--metrics-host", default="127.0.0.1")
    args = ap.parse_args()
    telemetry.enable()
    exporter = None
    if args.metrics_port is not None:
        exporter = telemetry.start_http_exporter(
            port=args.metrics_port, host=args.metrics_host
        )
        print(f"metrics on http://{args.metrics_host}:{exporter.port}"
              f"/metrics", flush=True)
    sched = FleetScheduler(
        host=args.host, port=args.port,
        worker_timeout_s=args.worker_timeout,
        mem_budget_bytes=args.mem_budget_mb * 1024 * 1024,
    )
    print(f"fleet scheduler on {sched.local_addr}", flush=True)
    last_report = 0.0
    try:
        while True:
            sched.poll()
            now = time.monotonic()
            if now - last_report >= 5.0:
                last_report = now
                snap = sched.snapshot()
                if snap["workers"] or snap["lobbies"]:
                    print(json.dumps(
                        {k: snap[k] for k in ("workers", "lobbies")}
                    ), flush=True)
            time.sleep(0.002)
    except KeyboardInterrupt:
        pass
    finally:
        sched.close()
        if exporter is not None:
            exporter.close()


if __name__ == "__main__":
    main()
