#!/usr/bin/env bash
# TPU measurement suite — run EARLY in a round while the TPU tunnel is
# healthy (see docs/tpu_notes.md §4 for why it may not be):
#   bash scripts/tpu_measure.sh | tee -a TPU_MEASUREMENTS.txt
#
# Crash-resilient by construction (round-3 postmortem): bench.py is staged —
# every metric lands in BENCH_PROGRESS.jsonl the moment it is measured, the
# orchestrator probes/recovers the tunnel between stages, and each auxiliary
# suite below runs under its own timeout so one wedge cannot void the rest.
set -uo pipefail
cd "$(dirname "$0")/.."

run() {  # run <name> <timeout-s> <cmd...>: never aborts the suite
  local name="$1" t="$2"; shift 2
  echo "== $name =="
  timeout "$t" "$@" || echo "[$name FAILED/TIMED OUT rc=$? — continuing]"
}

echo "== backend probe =="
if ! timeout 120 python -c "import jax; d=jax.devices(); print(d)"; then
  echo "TPU backend unusable — running ALL suites on CPU (bench.py still"
  echo "re-probes per stage and reclaims the TPU if the tunnel recovers)"
  export BGT_PLATFORM=cpu  # every suite below calls apply_platform_env
fi

# outer timeout must exceed bench.py's own worst case (stage timeouts sum to
# ~55 min; probe/retry overhead can roughly double a flaky run).  bench.py
# manages its own per-stage fallback/recovery, so it runs WITHOUT the
# CPU pin even when the suite-level probe failed.
run "headline bench (staged, incremental)" 7200 env -u BGT_PLATFORM python bench.py

run "criterion equivalents" 600 python benches/criterion_equiv.py --iters 100

run "end-to-end driver throughput" 1200 python benches/driver_bench.py

run "speculation payoff (lossy/jittery P2P)" 1200 \
  python benches/driver_bench.py --speculation-payoff

run "cross-backend checksum parity" 300 python scripts/parity_check.py

# writes the MULTICHIP record itself (empty-output runs are marked
# "skipped", never "ok" — see scripts/multichip_bench.py)
run "multichip dry run (8 devices)" 1000 \
  python scripts/multichip_bench.py --n-devices 8 --out MULTICHIP.json

run "program-variant stability" 600 python - <<'PYEOF'
from bevy_ggrs_tpu.ops.variant_probe import probe_program_variants
from bevy_ggrs_tpu.models import box_game, pong, crowd, stress, fixed_point
for name, mk in [("box_game", lambda: box_game.make_app(num_players=2)),
                 ("pong", pong.make_app),
                 ("crowd", lambda: crowd.make_app(n_per_team=64)),
                 ("stress", lambda: stress.make_app(1024, capacity=1024)),
                 ("fixed_point", fixed_point.make_app)]:
    print(f"{name:12s}:", probe_program_variants(mk(), trials=60, warmup_frames=8).summary())
PYEOF

run "example: box_game synctest" 300 \
  python examples/box_game_synctest.py --frames 120 --check-distance 3
run "example: particles synctest" 300 \
  python examples/particles_stress.py --rate 100 --synctest --frames 120 --check-distance 3

echo "ALL TPU MEASUREMENTS DONE"
