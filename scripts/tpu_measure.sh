#!/usr/bin/env bash
# One-shot TPU measurement suite — run FIRST THING in a round while the TPU
# tunnel is healthy (see docs/tpu_notes.md §4 for why it may not be):
#   bash scripts/tpu_measure.sh | tee TPU_MEASUREMENTS.txt
# Runs on the default (accelerator) backend; each step prints JSON/lines.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== backend probe =="
timeout 90 python -c "import jax; d=jax.devices(); print(d)" || {
  echo "TPU backend unusable — aborting (do NOT kill -9 while claimed)"; exit 1; }

echo "== headline bench (bench.py) =="
python bench.py

echo "== criterion equivalents =="
python benches/criterion_equiv.py --iters 100

echo "== end-to-end driver throughput =="
python benches/driver_bench.py

echo "== cross-backend checksum parity =="
python scripts/parity_check.py

echo "== program-variant stability on this backend =="
python - <<'PYEOF'
from bevy_ggrs_tpu.ops.variant_probe import probe_program_variants
from bevy_ggrs_tpu.models import box_game, pong, crowd, stress, fixed_point
for name, mk in [("box_game", lambda: box_game.make_app(num_players=2)),
                 ("pong", pong.make_app),
                 ("crowd", lambda: crowd.make_app(n_per_team=64)),
                 ("stress", lambda: stress.make_app(1024, capacity=1024)),
                 ("fixed_point", fixed_point.make_app)]:
    print(f"{name:12s}:", probe_program_variants(mk(), trials=60, warmup_frames=8).summary())
PYEOF

echo "== examples on device (quick) =="
python examples/box_game_synctest.py --frames 120 --check-distance 3
python examples/particles_stress.py --rate 100 --synctest --frames 120 --check-distance 3

echo "ALL TPU MEASUREMENTS DONE"
