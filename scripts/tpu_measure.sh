#!/usr/bin/env bash
# One-shot TPU measurement suite — run FIRST THING in a round while the TPU
# tunnel is healthy (see docs/tpu_notes.md §4 for why it may not be):
#   bash scripts/tpu_measure.sh | tee TPU_MEASUREMENTS.txt
# Runs on the default (accelerator) backend; each step prints JSON/lines.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== backend probe =="
timeout 90 python -c "import jax; d=jax.devices(); print(d)" || {
  echo "TPU backend unusable — aborting (do NOT kill -9 while claimed)"; exit 1; }

echo "== headline bench (bench.py) =="
python bench.py

echo "== criterion equivalents =="
python benches/criterion_equiv.py --iters 100

echo "== cross-backend checksum parity =="
python scripts/parity_check.py

echo "== examples on device (quick) =="
python examples/box_game_synctest.py --frames 120 --check-distance 3
python examples/particles_stress.py --rate 100 --synctest --frames 120 --check-distance 3

echo "ALL TPU MEASUREMENTS DONE"
