#!/usr/bin/env python
"""Standalone room/signaling server (the matchbox `matchbox_server`
analog): hosts rooms, pushes rosters, relays datagrams for peers that
cannot reach each other directly.

    python scripts/room_server.py --port 3536
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.session.room import RoomServer


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=3536)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="member silence timeout (s)")
    args = ap.parse_args()
    server = RoomServer(port=args.port, host=args.host,
                        member_timeout_s=args.timeout)
    print(f"room server on {server.local_addr}", flush=True)
    last_report = 0.0
    try:
        while True:
            server.poll()
            now = time.monotonic()
            if now - last_report >= 5.0:
                last_report = now
                rooms = {
                    room: sorted(members)
                    for room, members in server.rooms.items()
                }
                if rooms:
                    print(f"rooms: {rooms}", flush=True)
            time.sleep(0.002)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()


if __name__ == "__main__":
    main()
