#!/usr/bin/env python
"""Standalone room/signaling server (the matchbox `matchbox_server`
analog): hosts rooms, pushes rosters, relays datagrams for peers that
cannot reach each other directly.

    python scripts/room_server.py --port 3536

With ``--metrics-port`` the process also serves the telemetry registry as a
Prometheus text endpoint (``GET /metrics``) plus the lobby QoS snapshot as
JSON (``GET /qos`` — see docs/observability.md "Network & QoS") and a
bounded Chrome-trace export (``GET /trace`` — docs/observability.md
"Tracing & device memory"); the ``lobby_qos_score`` gauges are refreshed
in the 5 s reporting loop:

    python scripts/room_server.py --port 3536 --metrics-port 9464
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu import telemetry
from bevy_ggrs_tpu.session.room import RoomServer


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=3536)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="member silence timeout (s)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus /metrics on this port "
                         "(0 = any free port)")
    ap.add_argument("--metrics-host", default="127.0.0.1")
    ap.add_argument("--join-token", default=None,
                    help="shared-secret join token: JOINs not carrying it "
                         "are rejected with a reason (off by default; see "
                         "docs/architecture.md trust-model note)")
    args = ap.parse_args()
    exporter = None
    if args.metrics_port is not None:
        telemetry.enable()
        exporter = telemetry.start_http_exporter(
            port=args.metrics_port, host=args.metrics_host
        )
        print(
            f"metrics on http://{args.metrics_host}:{exporter.port}/metrics",
            flush=True,
        )
        print(
            f"qos on http://{args.metrics_host}:{exporter.port}/qos",
            flush=True,
        )
        print(
            f"trace on http://{args.metrics_host}:{exporter.port}/trace"
            f"  (Chrome-trace JSON, ?n= caps events; load in"
            f" ui.perfetto.dev)",
            flush=True,
        )
    server = RoomServer(port=args.port, host=args.host,
                        member_timeout_s=args.timeout,
                        join_token=args.join_token)
    print(f"room server on {server.local_addr}", flush=True)
    last_report = 0.0
    try:
        while True:
            server.poll()
            now = time.monotonic()
            if now - last_report >= 5.0:
                last_report = now
                rooms = {
                    room: sorted(members)
                    for room, members in server.rooms.items()
                }
                telemetry.gauge_set("room_count", len(rooms), "active rooms")
                telemetry.gauge_set(
                    "room_members",
                    sum(len(m) for m in rooms.values()),
                    "members across all rooms",
                )
                # keep the lobby_qos_score gauges warm for /metrics scrapes
                # (/qos recomputes on demand either way)
                telemetry.update_qos_gauges()
                if rooms:
                    print(f"rooms: {rooms}", flush=True)
            time.sleep(0.002)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        if exporter is not None:
            exporter.close()


if __name__ == "__main__":
    main()
