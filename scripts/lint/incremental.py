"""Incremental linting — the ``--changed`` fast path.

``python -m scripts.lint --changed`` lints only the python files ``git
diff`` reports against a base ref (default HEAD: unstaged + staged +
untracked), EXPANDED to every module that transitively imports one of
them, so interprocedural rules (BGT011/BGT063 chains resolve through the
importer) and per-file rules both see the same code they would in a full
run.  What a partial corpus structurally cannot support — the reverse
docs checks (BGT022/BGT031/BGT033/BGT051) and the stale-suppression
meta-rule (BGT005), which need the WHOLE repo to prove absence — is
turned off via ``Config.partial_corpus``; ``scripts/check.sh`` keeps the
authoritative full run.

The import graph is built the same way the purity call graph resolves
modules: stdlib AST only, dotted names mapped to repo-relative paths,
relative imports anchored at the importing file's package.  Conservative
by design: unresolvable imports simply add no edge, which can only make
the expansion smaller — never wrong for the files it does include.
"""

from __future__ import annotations

import ast
import subprocess
from pathlib import Path, PurePosixPath
from typing import Dict, Iterable, List, Set, Tuple

from .core import DEFAULT_PATHS, iter_py_files


def git_changed_files(root: Path, base: str = "HEAD") -> Set[str]:
    """Repo-relative posix paths of files changed vs ``base`` (worktree +
    index) plus untracked files; empty set when git is unavailable."""
    changed: Set[str] = set()
    for args in (
        ["git", "diff", "--name-only", base],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                args, cwd=root, capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return set()
        if res.returncode != 0:
            continue
        changed.update(
            line.strip() for line in res.stdout.splitlines() if line.strip()
        )
    return changed


def _module_candidates(dotted: str) -> List[str]:
    """Possible repo-relative paths for a dotted module name."""
    base = dotted.replace(".", "/")
    return [base + ".py", base + "/__init__.py"]


def _file_dotted(rel: str) -> str:
    """The dotted module name a repo-relative path is importable as."""
    p = PurePosixPath(rel)
    parts = list(p.parts)
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3]
    return ".".join(parts)


def _imports_of(rel: str, tree: ast.AST, known: Set[str]) -> Set[str]:
    """Repo-relative paths (from ``known``) that ``rel`` imports."""
    out: Set[str] = set()
    self_dotted = _file_dotted(rel)
    is_pkg = rel.endswith("__init__.py")

    def add_module(dotted: str) -> bool:
        for cand in _module_candidates(dotted):
            if cand in known:
                out.add(cand)
                return True
        return False

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                # `import a.b.c` binds a but loads a, a.b and a.b.c
                parts = a.name.split(".")
                for i in range(len(parts)):
                    add_module(".".join(parts[: i + 1]))
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                anchor = self_dotted.split(".")
                drop = node.level - 1 if is_pkg else node.level
                if drop:
                    anchor = anchor[: len(anchor) - drop]
                base = ".".join(
                    anchor + (node.module.split(".") if node.module else [])
                )
            if not base:
                continue
            add_module(base)
            for a in node.names:
                if a.name != "*":
                    add_module(f"{base}.{a.name}")
    return out


def build_import_graphs(
    root: Path,
) -> Tuple[Set[str], Dict[str, Set[str]], Dict[str, Set[str]]]:
    """``(corpus_rels, importer_rel -> deps, imported_rel -> importers)``
    over the default lint corpus (fixtures excluded, same as a full run)."""
    files = iter_py_files(DEFAULT_PATHS, root)
    rels: List[Tuple[str, Path]] = []
    for p in files:
        try:
            rel = p.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = p.as_posix()
        rels.append((rel, p))
    known = {rel for rel, _ in rels}
    forward: Dict[str, Set[str]] = {}
    reverse: Dict[str, Set[str]] = {}
    for rel, p in rels:
        try:
            tree = ast.parse(p.read_text())
        except (OSError, SyntaxError):
            continue
        for dep in _imports_of(rel, tree, known):
            if dep != rel:
                forward.setdefault(rel, set()).add(dep)
                reverse.setdefault(dep, set()).add(rel)
    return known, forward, reverse


def build_reverse_import_graph(root: Path) -> Tuple[Set[str], Dict[str, Set[str]]]:
    """``(corpus_rels, imported_rel -> {importer_rel, ...})`` over the
    default lint corpus (fixtures excluded, same as a full run)."""
    known, _forward, reverse = build_import_graphs(root)
    return known, reverse


def _closure(seed: Set[str], edges: Dict[str, Set[str]],
             seen: Set[str]) -> None:
    work = list(seed)
    while work:
        cur = work.pop()
        for nxt in edges.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                work.append(nxt)


def expand_dependents(changed: Iterable[str], root: Path) -> List[str]:
    """The changed .py files that exist in the lint corpus, plus every
    transitive importer — sorted repo-relative paths."""
    known, _forward, reverse = build_import_graphs(root)
    seed = {c for c in changed if c.endswith(".py") and c in known}
    seen = set(seed)
    _closure(seed, reverse, seen)
    return sorted(seen)


def expand_closure(changed: Iterable[str], root: Path,
                   graphs=None) -> List[str]:
    """Bidirectional slice: changed files, every transitive importer, and
    every transitive forward import of all of those.  The forward half is
    what interprocedural chain rules (BGT011/BGT063/BGT071) need when the
    *caller* changed: its witness chains resolve through callee modules
    the reverse closure alone would omit."""
    known, forward, reverse = graphs or build_import_graphs(root)
    seed = {c for c in changed if c.endswith(".py") and c in known}
    seen = set(seed)
    _closure(seed, reverse, seen)
    _closure(set(seen), forward, seen)
    return sorted(seen)


def changed_corpus(root: Path, base: str = "HEAD") -> Tuple[List[str], Set[str]]:
    """``(paths_to_lint, raw_changed_set)`` for the --changed CLI mode."""
    changed = git_changed_files(root, base=base)
    return expand_closure(changed, root), changed
