"""Dtype-promotion drift in sim arithmetic — BGT072.

A world component's dtype is part of the persisted contract: the
checkpoint schema digest (``snapshot/persist.py``) records
``path:dtype:shape`` per leaf, and ``load_world`` fails LOUDLY on any
leaf whose stored dtype differs from the live registry.  JAX's weak-type
promotion makes that failure trivially easy to manufacture: one bare
Python float literal (``pos * 0.5``) or one true division in arithmetic
on an int-declared component silently promotes the array to float — the
next ``save_world``/``load_world`` round-trip then dies on the exact
schema-digest mismatch this rule's finding predicts.

The check is file-local by design: each model module declares its own
components (``app.rollback_component("pos", (2,), jnp.int32)``), so the
name -> dtype-category map never crosses files and a ``pos`` that is
int32 in ``fixed_point.py`` but float32 in ``crowd.py`` cannot
cross-contaminate.  Only int-category components are hazardous — float
components absorb Python float literals without changing dtype.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Context, Finding, SourceFile, lint_pass, rule

rule(
    "BGT072", "dtype-promotion-drift",
    summary="float promotion of an int-declared component — the persisted "
            "schema digest (persist.py load_world) will fail on it",
)

_INT_DTYPES = frozenset({
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_",
})
_FLOAT_DTYPES = frozenset({"float16", "float32", "float64", "bfloat16"})
_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Mod, ast.Pow, ast.FloorDiv)


def _dtype_category(node: ast.AST) -> Optional[str]:
    """'int' / 'float' for a ``jnp.int32``-style dtype expression."""
    name = None
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    if name in _INT_DTYPES:
        return "int"
    if name in _FLOAT_DTYPES:
        return "float"
    return None


def _component_kinds(tree: ast.AST) -> Dict[str, str]:
    """name -> dtype category from this module's rollback_component
    declarations (conflicting redeclarations drop the name)."""
    kinds: Dict[str, str] = {}
    dropped: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "rollback_component"
                and len(node.args) >= 3
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        cat = _dtype_category(node.args[2])
        if cat is None:
            continue
        name = node.args[0].value
        if name in kinds and kinds[name] != cat:
            dropped.add(name)
        kinds[name] = cat
    for name in dropped:
        kinds.pop(name, None)
    return kinds


def _comp_name(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """The component name when ``node`` reads an int component: either
    ``<x>.comps["name"]`` directly or a local alias bound from one."""
    if (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "comps"
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)):
        return node.slice.value
    if isinstance(node, ast.Name):
        return aliases.get(node.id)
    return None


def check_dtype_drift(sf: SourceFile, kinds: Dict[str, str]) -> List[Finding]:
    out: List[Finding] = []
    int_comps = {n for n, c in kinds.items() if c == "int"}
    if not int_comps:
        return out

    for fn in (n for n in ast.walk(sf.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))):
        # local aliases: vel = world.comps["vel"]  (tuple unpacks too)
        aliases: Dict[str, str] = {}
        for n in ast.walk(fn):
            if not isinstance(n, ast.Assign):
                continue
            for t in n.targets:
                pairs = []
                if isinstance(t, ast.Name):
                    pairs = [(t, n.value)]
                elif isinstance(t, ast.Tuple) and isinstance(n.value, ast.Tuple) \
                        and len(t.elts) == len(n.value.elts):
                    pairs = list(zip(t.elts, n.value.elts))
                for tgt, val in pairs:
                    if isinstance(tgt, ast.Name):
                        name = _comp_name(val, {})
                        if name in int_comps:
                            aliases[tgt.id] = name

        def int_side(expr) -> Optional[str]:
            name = _comp_name(expr, aliases)
            return name if name in int_comps else None

        for n in ast.walk(fn):
            if not isinstance(n, ast.BinOp):
                continue
            name = int_side(n.left) or int_side(n.right)
            if name is None:
                continue
            if isinstance(n.op, ast.Div):
                out.append(Finding(
                    "BGT072", sf.rel, n.lineno,
                    f"true division of int component {name!r} promotes it "
                    "to float — the stored dtype drifts from its "
                    "rollback_component declaration and load_world's "
                    "schema-digest check (snapshot/persist.py) fails the "
                    "next checkpoint round-trip; use // or astype first",
                ))
                continue
            if isinstance(n.op, _ARITH_OPS):
                other = n.right if int_side(n.left) else n.left
                if isinstance(other, ast.Constant) and isinstance(
                        other.value, float):
                    out.append(Finding(
                        "BGT072", sf.rel, n.lineno,
                        f"bare float literal {other.value!r} in arithmetic "
                        f"on int component {name!r} weak-type-promotes the "
                        "result to float — the stored dtype drifts from "
                        "its rollback_component declaration and "
                        "load_world's schema-digest check "
                        "(snapshot/persist.py) fails the next checkpoint "
                        "round-trip; use an int literal or astype "
                        "explicitly",
                    ))
    return out


@lint_pass
def dtype_drift_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    out: List[Finding] = []
    for sf in ctx.files:
        if sf.tree is None or sf.is_test or not cfg.in_sim_code(sf.rel):
            continue
        kinds = _component_kinds(sf.tree)
        if kinds:
            out.extend(check_dtype_drift(sf, kinds))
    return out
