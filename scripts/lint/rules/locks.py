"""Blocking call under a held lock — BGT061.

The control-plane locks exist to protect microsecond-scale map mutations
(a metrics series write, a pending-table insert).  A blocking call made
while one is held — ``sock.recvfrom`` with a timeout, ``time.sleep``, a
``block_until_ready`` device sync, ``Thread.join`` — turns every other
thread that touches the lock into a hostage of that wait: the Prometheus
scrape thread stalls the tick loop, or worse, a join-under-lock deadlocks
against the thread it is joining.  The rule is scoped to the concurrency
modules (``config.CONCURRENCY_MODULES``) and keys on the call shape
(attribute names in ``config.BLOCKING_CALL_ATTRS``, dotted prefixes in
``config.BLOCKING_CALL_DOTTED``) — no type inference, which is the right
trade for a stdlib linter: the listed names are unambiguous in this
codebase (nothing else defines a ``recvfrom``).

Fix: copy what you need under the lock, drop it, then block — or
suppress with the reason the wait is bounded and the lock is private.
"""

from __future__ import annotations

from typing import List

from ..core import Context, Finding, lint_pass, rule
from .shared_state import scan_module

rule(
    "BGT061", "blocking-call-under-lock",
    summary="a blocking call (socket/sleep/subprocess/device-sync/join) "
            "made while a lock is held stalls every thread that shares it",
)


@lint_pass
def blocking_under_lock_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    out: List[Finding] = []
    for sf in ctx.files:
        if sf.tree is None or sf.is_test:
            continue
        if not cfg.in_concurrency_scope(sf.rel):
            continue
        mmap = scan_module(sf, cfg)
        for qual, fi in sorted(mmap.funcs.items()):
            for line, call_repr, held in fi.blocking:
                locks = ", ".join(sorted(held))
                out.append(Finding(
                    "BGT061", sf.rel, line,
                    f"blocking call under lock: {qual} calls "
                    f"{call_repr}(...) while holding {locks} — every "
                    "thread sharing that lock stalls for the full wait; "
                    "copy state under the lock, release it, then block",
                ))
    return out
