"""Tick-phase timer discipline (BGT020/BGT021) and the stale-catalog
meta-lint (BGT022).

The phase catalog is **extracted from the package source by AST literal
parsing** (``extract_phase_catalog``) — the lint must not import
``bevy_ggrs_tpu`` (that pulls jax), and the previous hand-mirrored copy in
``lint_imports.py`` was itself a determinism hazard for the lint: a new
phase added to ``telemetry/phases.py`` without updating the mirror would
have been flagged as a typo.  ``tests/test_phases.py`` keeps the identity
assertion as a regression guard.

Every ``.phase("<literal>")`` call in the drivers must name a catalog phase
(a typo would silently leak its time into ``unattributed_ms``) and must be
a ``with``-statement context expression (a bare call never runs
``__enter__``/``__exit__``, so it times nothing).  BGT022 closes the other
direction: a catalog phase no driver ever times is dead weight that skews
``unattributed_pct`` readers.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Set

from ..core import Context, Finding, lint_pass, rule

rule(
    "BGT020", "phase-name",
    summary=".phase() with a non-literal or non-catalog phase name",
)
rule(
    "BGT021", "phase-not-timed",
    summary=".phase() call outside a with-statement times nothing",
)
rule(
    "BGT022", "stale-phase-catalog",
    summary="a catalog phase is never timed by any driver",
)


def extract_phase_catalog(phases_path: Path) -> Optional[Set[str]]:
    """The ``PHASES = ("...", ...)`` tuple of telemetry/phases.py, read by
    AST literal parsing — no package import, no jax.  Returns None when the
    file or the assignment cannot be found (reported as BGT022 upstream)."""
    try:
        tree = ast.parse(phases_path.read_text(), filename=str(phases_path))
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            targets = [node.target.id]
            value = node.value
        else:
            continue
        if "PHASES" not in targets or value is None:
            continue
        if isinstance(value, (ast.Tuple, ast.List)):
            names = set()
            for elt in value.elts:
                if not (isinstance(elt, ast.Constant) and isinstance(elt.value, str)):
                    return None  # non-literal element: cannot trust the parse
                names.add(elt.value)
            return names
    return None


def check_phases(tree: ast.AST, catalog: Set[str]) -> list:
    """Return ``(line, message, used_name_or_None)`` for ``.phase(...)``
    misuse; well-formed sites contribute their name via the third slot so
    the caller can do the BGT022 reverse check."""
    problems = []
    used: Set[str] = set()
    with_exprs = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                with_exprs.add(id(item.context_expr))
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "phase"
        ):
            continue
        if (
            len(node.args) != 1
            or node.keywords
            or not isinstance(node.args[0], ast.Constant)
            or not isinstance(node.args[0].value, str)
        ):
            problems.append((
                node.lineno,
                "phase timer: .phase() takes one string literal "
                "(dynamic names defeat the catalog lint)",
                "BGT020",
            ))
            continue
        name = node.args[0].value
        used.add(name)
        if name not in catalog:
            problems.append((
                node.lineno,
                f"phase timer: {name!r} is not in the phase catalog "
                f"{sorted(catalog)} — its time would silently land "
                "in unattributed_ms (telemetry/phases.py)",
                "BGT020",
            ))
        if id(node) not in with_exprs:
            problems.append((
                node.lineno,
                f"phase timer: .phase({name!r}) must be a with-statement "
                "context expression — a bare call times nothing",
                "BGT021",
            ))
    return problems, used


@lint_pass
def phases_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    out: List[Finding] = []
    catalog = extract_phase_catalog(ctx.root / cfg.phases_module)
    if catalog is None:
        if cfg.project_checks:
            out.append(Finding(
                "BGT022", cfg.phases_module, 0,
                "could not extract the PHASES tuple by AST literal parsing "
                "— the catalog must stay a flat tuple of string literals "
                "so the lint can read it without importing jax",
            ))
        return out
    used_anywhere: Set[str] = set()
    drivers_seen: Set[str] = set()
    for sf in ctx.files:
        if sf.tree is None or not any(sf.rel.endswith(s) for s in cfg.phase_files):
            continue
        drivers_seen.add(sf.rel)
        problems, used = check_phases(sf.tree, catalog)
        used_anywhere |= used
        for line, msg, rid in problems:
            out.append(Finding(rid, sf.rel, line, msg))
    # the reverse (stale-catalog) check needs the FULL driver set in the
    # corpus — a partial-path run must not call a phase stale just because
    # the driver that times it was not linted
    if (cfg.project_checks and len(drivers_seen) == len(cfg.phase_files)
            and not getattr(cfg, "partial_corpus", False)):
        for name in sorted(catalog - used_anywhere):
            out.append(Finding(
                "BGT022", cfg.phases_module, 0,
                f"stale catalog: phase {name!r} is declared in PHASES but "
                "never timed by any driver "
                f"({', '.join(cfg.phase_files)}) — dead catalog entries "
                "skew unattributed_pct readers",
            ))
    return out
