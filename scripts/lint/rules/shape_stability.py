"""Data-dependent-shape ops in sim/rollback scope — BGT071.

Under ``jax.jit`` an array's *shape* is part of the program: an op whose
output shape depends on array *values* (``nonzero``, boolean-mask
indexing, ``concatenate`` over a dynamically-sized sequence, ``reshape``
to a data-derived size) either fails to trace or — when it sneaks through
host-side — recompiles the program once per distinct shape, a 10-50ms
cliff per tick that defeats every cached-program guarantee the engine
ships.  Inside sim/rollback scope (``models/``, ``ops/``) these ops are
hazards *by construction*; fixed-capacity masks (``jnp.where(mask, x,
y)``) are the sanctioned alternative.

Like the hot-loop purity rule (BGT011), the check is interprocedural:
a sim-scope function that *reaches* a data-dependent-shape op through
the package call graph is flagged at its call site with the full witness
chain, and a ``# bgt: ignore[BGT071]: reason`` on the direct (seed) line
sanctions every caller at once.  The runtime twin is the
``BGT_COMPILE_GUARD`` sentinel, which catches the recompiles this rule
cannot prove statically.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import Context, Finding, SourceFile, lint_pass, rule
from .purity import CallGraph, FuncKey

rule(
    "BGT071", "data-dependent-shape",
    summary="data-dependent-shape op in (or reachable from) sim/rollback "
            "scope — shapes must be value-independent under jit",
)

# calls whose RESULT shape depends on array values
_SHAPE_CALL_ATTRS = frozenset({
    "nonzero", "flatnonzero", "argwhere", "compress", "extract",
})
# jnp.unique is value-dependent unless given a static `size=`
_UNIQUE_ATTRS = frozenset({"unique"})
# calls producing boolean masks (subscripting with one is a gather of
# data-dependent length)
_MASK_CALLS = frozenset({
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "isnan", "isfinite", "isinf", "isclose", "equal", "not_equal",
    "greater", "greater_equal", "less", "less_equal",
})
# attribute calls that taint a reshape size as data-derived
_SIZE_TAINT_ATTRS = frozenset({"sum", "item", "count_nonzero"})
_CONCAT_NAMES = frozenset({
    "concatenate", "stack", "hstack", "vstack", "column_stack",
})


def _call_attr(node: ast.Call) -> Optional[str]:
    """Trailing attribute/name of a call target (``jnp.nonzero`` ->
    ``nonzero``, ``x.reshape`` -> ``reshape``)."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_mask_expr(node: ast.AST, mask_names: set) -> bool:
    if isinstance(node, (ast.Compare, ast.BoolOp)):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.Invert, ast.Not)):
        return _is_mask_expr(node.operand, mask_names)
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.BitAnd, ast.BitOr)):
        return (_is_mask_expr(node.left, mask_names)
                or _is_mask_expr(node.right, mask_names))
    if isinstance(node, ast.Name):
        return node.id in mask_names
    if isinstance(node, ast.Call):
        a = _call_attr(node)
        return a in _MASK_CALLS
    return False


def _size_is_data_derived(node: ast.AST) -> bool:
    """True when a reshape size expression contains a value read
    (``int(x.sum())``, ``mask.sum()``, ``n.item()``)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            a = _call_attr(n)
            if a in _SIZE_TAINT_ATTRS:
                return True
            if isinstance(n.func, ast.Name) and n.func.id == "int" and n.args:
                if any(isinstance(x, (ast.Call, ast.Subscript, ast.Attribute))
                       for x in ast.walk(n.args[0])):
                    return True
    return False


def _has_static_size_kw(node: ast.Call) -> bool:
    return any(k.arg == "size" for k in node.keywords)


def scan_shape_hazards(sf: SourceFile) -> List[Tuple[str, int, str]]:
    """``(qualname, line, description)`` for every data-dependent-shape
    op in the file, attributed to the innermost enclosing function
    (qualnames match the purity call graph's collector)."""
    out: List[Tuple[str, int, str]] = []

    def visit_fn(fn: ast.AST, qual: str) -> None:
        mask_names = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and isinstance(n.value, (
                    ast.Compare, ast.BoolOp)):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        mask_names.add(t.id)
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                a = _call_attr(n)
                if a in _SHAPE_CALL_ATTRS:
                    out.append((qual, n.lineno,
                                f"{a}() has a data-dependent result shape"))
                elif a in _UNIQUE_ATTRS and not _has_static_size_kw(n):
                    out.append((qual, n.lineno,
                                "unique() without a static size= has a "
                                "data-dependent result shape"))
                elif a == "where" and len(n.args) == 1 and not n.keywords:
                    out.append((qual, n.lineno,
                                "single-argument where() returns "
                                "data-dependent index arrays"))
                elif a == "reshape":
                    sizes = n.args
                    if len(sizes) == 1 and isinstance(sizes[0], (ast.Tuple,
                                                                 ast.List)):
                        sizes = sizes[0].elts
                    if any(_size_is_data_derived(s) for s in sizes):
                        out.append((qual, n.lineno,
                                    "reshape to a data-derived size"))
                elif a in _CONCAT_NAMES and n.args:
                    seq = n.args[0]
                    if isinstance(seq, (ast.Name, ast.GeneratorExp,
                                        ast.ListComp, ast.Starred)):
                        out.append((qual, n.lineno,
                                    f"{a}() over a dynamically-sized "
                                    "sequence — result length varies per "
                                    "call"))
            elif isinstance(n, ast.Subscript) and not isinstance(
                    n.ctx, ast.Store):
                sl = n.slice
                if _is_mask_expr(sl, mask_names):
                    out.append((qual, n.lineno,
                                "boolean-mask indexing selects a "
                                "data-dependent number of rows"))

    def walk(node, stack: Tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(stack + (child.name,))
                visit_fn(child, qual)
                walk(child, stack + (child.name,))
            elif isinstance(child, ast.ClassDef):
                walk(child, stack + (child.name,))
            else:
                walk(child, stack)

    walk(sf.tree, ())
    # hazards inside nested defs are attributed to BOTH quals by the
    # double-walk above; dedupe on (line, desc) keeping the innermost
    seen = {}
    for qual, line, desc in out:
        cur = seen.get((line, desc))
        if cur is None or len(qual) > len(cur):
            seen[(line, desc)] = qual
    return [(q, line, desc) for (line, desc), q in sorted(
        seen.items(), key=lambda kv: kv[0][0])]


@lint_pass
def shape_stability_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    out: List[Finding] = []

    # 1. direct scan: findings in sim scope, seeds everywhere
    seeds: Dict[FuncKey, Tuple[int, str]] = {}
    by_rel_hazards: Dict[str, List] = {}
    for sf in ctx.files:
        if sf.tree is None or sf.is_test:
            continue
        hazards = scan_shape_hazards(sf)
        if not hazards:
            continue
        by_rel_hazards[sf.rel] = hazards
        in_sim = cfg.in_sim_code(sf.rel)
        for qual, line, desc in hazards:
            sup = sf.suppressions.get(line, {})
            sanctioned = "BGT071" in sup
            if sanctioned:
                # seed-line sanction: the suppression stops propagation
                # to every caller, so it is live even when no finding
                # lands on the line itself (non-sim seed files)
                ctx.used_suppressions.add((sf.rel, line, "BGT071"))
            else:
                seeds[(sf.rel, qual)] = (line, desc)
            if in_sim:
                # sanctioned sim-scope hazards still emit — core marks
                # them suppressed, same contract as every other rule
                out.append(Finding(
                    "BGT071", sf.rel, line,
                    f"{desc} — inside sim/rollback scope shapes must be "
                    "value-independent under jit (fixed-capacity "
                    "jnp.where masks are the sanctioned form); every "
                    "distinct shape is a steady-state recompile the "
                    "BGT_COMPILE_GUARD sentinel would trip on",
                ))

    if not seeds:
        return out

    # 2. witness chains: sim-scope call sites reaching a non-sim seed
    graph = getattr(ctx, "_callgraph", None)
    if graph is None:
        graph = CallGraph(ctx)
        ctx._callgraph = graph

    # why[key] = ("seed", line, desc) | ("via", line, next_key)
    why: Dict[FuncKey, tuple] = {}
    edges_rev: Dict[FuncKey, List] = {}
    for key, res in graph.resolved.items():
        for line, tgt in res:
            edges_rev.setdefault(tgt.key, []).append((key, line))
    work = []
    for key, (line, desc) in seeds.items():
        if key in graph.funcs:
            why[key] = ("seed", line, desc)
            work.append(key)
    while work:
        key = work.pop()
        for caller_key, line in edges_rev.get(key, []):
            if caller_key not in why:
                why[caller_key] = ("via", line, key)
                work.append(caller_key)

    def chain(key: FuncKey) -> str:
        hops = []
        cur = key
        for _ in range(32):
            w = why.get(cur)
            if w is None:
                break
            if w[0] == "seed":
                hops.append(f"{cur[1]}() [{cur[0]}:{w[1]}] — {w[2]}")
                break
            hops.append(f"{cur[1]}() [{cur[0]}:{w[1]}]")
            cur = w[2]
        return " -> ".join(hops)

    for rel, mod in graph.by_rel.items():
        if not cfg.in_sim_code(rel):
            continue
        for fn in mod.funcs.values():
            for line, tgt in graph.resolved.get(fn.key, []):
                if tgt.key not in why:
                    continue
                # seeds in sim files already carry a direct finding at
                # the hazard line; chain findings cover the cross-file
                # case where the seed sits outside sim scope
                seed_key = tgt.key
                w = why[seed_key]
                while w[0] == "via":
                    seed_key = w[2]
                    w = why[seed_key]
                if cfg.in_sim_code(seed_key[0]):
                    continue
                out.append(Finding(
                    "BGT071", rel, line,
                    f"{fn.key[1]}() reaches a data-dependent-shape op: "
                    f"{chain(tgt.key)} — shapes must be value-independent "
                    "in sim/rollback scope; suppress at the seed line if "
                    "the shape set is provably bounded",
                ))
    return out
