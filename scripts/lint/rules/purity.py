"""Hot-loop purity — intra-function (BGT010), interprocedural (BGT011),
and the stale-allowlist meta-lint (BGT012).

The pipelined tick engine (docs/architecture.md "Tick pipeline") depends on
the hot loop never forcing a device->host sync: one stray
``block_until_ready`` / ``device_get`` / eager ``.to_int`` in the dispatch
path re-serializes host against device and silently voids the overlap, with
no test failing.  Forcing reads are allowed only inside the allowlisted
flush funnels (config.PURITY_ALLOW).

BGT010 is the original syntactic rule: forcing *syntax* outside an
allowlisted function of a covered file.  It is trivially defeated by a
one-line refactor — move the forcing read into a helper and call the
helper.  BGT011 closes that hole: it builds a call graph over the whole
package, seeds every function whose body contains forcing syntax, and
propagates the "forces device->host sync" effect backwards through call
edges, so a hot-loop function reaching a forcing helper N calls deep is
flagged *at the call site* with the full chain in the message.

Call-edge resolution is deliberately conservative (no type inference):

- ``f(...)``            -> same-module function, else a ``from x import f``
- ``self.m(...)``       -> method of the enclosing class (same module)
- ``mod.f(...)``        -> function of an imported module
- ``Cls.m(...)``        -> method of a same-module or imported class
- ``obj.m(...)``        -> *unique-name fallback*: resolves only when
  exactly one function/method in the package is named ``m`` (and the name
  is not on the common-method skip list) — this is what lets
  ``self._checks.try_host()`` resolve without type information.

A helper that contains forcing syntax only on a guarded non-blocking path
(reads after ``is_ready()``) is sanctioned by putting
``# bgt: ignore[BGT011]: <why>`` on the forcing line — that stops the
effect from seeding there, for every caller.  Allowlisted funnels never
propagate: calling ``checksum`` / ``_drain_inflight`` from hot code is the
design, not a leak.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import PurePosixPath
from typing import Dict, List, Optional, Set, Tuple

from ..core import Context, Finding, SourceFile, lint_pass, rule

rule(
    "BGT010", "hot-loop-purity",
    summary="forcing device->host read outside an allowlisted flush funnel",
)
rule(
    "BGT011", "interprocedural-purity",
    summary="hot-loop call reaches a device->host-forcing helper through the call graph",
)
rule(
    "BGT012", "stale-purity-allowlist",
    summary="PURITY_ALLOW names a function that no longer exists in its target file",
)

# receiver-less method names too generic for the unique-name fallback —
# a dict's .get or a socket's .send must never resolve to package code
_COMMON_METHOD_NAMES = frozenset({
    "get", "set", "put", "pop", "add", "append", "extend", "remove", "clear",
    "items", "keys", "values", "update", "copy", "join", "split", "strip",
    "read", "write", "close", "open", "send", "recv", "flush", "seek",
    "start", "stop", "run", "next", "sort", "index", "count", "insert",
    "encode", "decode", "format", "replace", "setdefault", "reshape",
    "astype", "tobytes", "item", "mean", "sum", "min", "max", "step",
})


# -- intra-function (BGT010) --------------------------------------------------


def check_purity(tree: ast.AST, allow: set,
                 attrs: frozenset = None, names: frozenset = None) -> list:
    """Return ``(line, message)`` for forcing reads outside ``allow``-listed
    functions (attribute accesses count even un-called: holding a bound
    ``.to_int`` and calling it later forces just the same)."""
    from ..config import PURITY_ATTRS, PURITY_NAMES

    attrs = PURITY_ATTRS if attrs is None else attrs
    names = PURITY_NAMES if names is None else names
    problems = []

    def walk(node, fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = node.name
        bad = None
        if isinstance(node, ast.Attribute) and node.attr in attrs:
            bad = f".{node.attr}"
        elif isinstance(node, ast.Name) and node.id in names:
            bad = node.id
        if bad is not None and fn not in allow:
            problems.append((
                node.lineno,
                f"hot-loop purity: {bad} in {fn or '<module>'}() — forcing "
                "device->host reads is allowed only in "
                f"{sorted(allow)} (see docs/architecture.md tick pipeline)",
            ))
        for child in ast.iter_child_nodes(node):
            walk(child, fn)

    walk(tree, None)
    return problems


# -- call graph (BGT011) ------------------------------------------------------

FuncKey = Tuple[str, str]  # (module rel path, qualname)


@dataclasses.dataclass
class _Func:
    key: FuncKey
    lineno: int
    cls: Optional[str]
    # (line, what) forcing syntax inside the body, minus BGT011-suppressed
    direct: List[Tuple[int, str]] = dataclasses.field(default_factory=list)
    # (line, raw call ref) — resolved after all modules are collected
    calls: List[Tuple[int, tuple]] = dataclasses.field(default_factory=list)

    @property
    def name(self) -> str:
        return self.key[1].rsplit(".", 1)[-1]


@dataclasses.dataclass
class _Module:
    rel: str
    dotted: str
    is_pkg: bool = False  # an __init__.py — anchors relative imports at itself
    funcs: Dict[str, _Func] = dataclasses.field(default_factory=dict)
    classes: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    # alias -> ("module", dotted) | ("symbol", dotted_module, symbol)
    imports: Dict[str, tuple] = dataclasses.field(default_factory=dict)


def _dotted(rel: str, package_parent: str) -> str:
    p = PurePosixPath(rel)
    if package_parent:
        try:
            p = p.relative_to(package_parent)
        except ValueError:
            pass
    parts = list(p.parts)
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3]  # strip .py
    return ".".join(parts)


def _resolve_import_module(cur_dotted: str, is_pkg: bool,
                           node: ast.ImportFrom) -> Optional[str]:
    """Absolute dotted module named by a possibly-relative ImportFrom.
    A plain module's level-1 anchor is its parent package; an
    ``__init__.py``'s is the package itself."""
    if node.level == 0:
        return node.module
    base = cur_dotted.split(".")
    drop = node.level - 1 if is_pkg else node.level
    if drop:
        base = base[:len(base) - drop]
    if not base and not node.module:
        return None
    return ".".join(base + (node.module.split(".") if node.module else []))


class _Collector(ast.NodeVisitor):
    """One module's functions, classes, imports and raw call refs."""

    def __init__(self, mod: _Module, sf: SourceFile, attrs, names,
                 used: Optional[set] = None):
        self.mod = mod
        self.sf = sf
        self.attrs = attrs
        self.names = names
        # (rel, line, rule) sink for consumed seed-line sanctions, so the
        # stale-suppression meta-rule (BGT005) knows they are load-bearing
        self.used = used if used is not None else set()
        self._stack: List[str] = []  # qualname segments
        self._cls: List[Optional[str]] = []

    # imports ---------------------------------------------------------------
    def visit_Import(self, node: ast.Import):
        for a in node.names:
            if a.asname:
                self.mod.imports[a.asname] = ("module", a.name)
            else:
                root = a.name.split(".")[0]
                self.mod.imports[root] = ("module", root)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        base = _resolve_import_module(self.mod.dotted, self.mod.is_pkg, node)
        if base is None:
            return
        for a in node.names:
            alias = a.asname or a.name
            # `from pkg import mod` is a module alias when pkg.mod exists;
            # the resolver decides at lookup time, so record both shapes
            self.mod.imports[alias] = ("symbol", base, a.name)

    # defs ------------------------------------------------------------------
    def _enter_func(self, node):
        qual = ".".join(self._stack + [node.name])
        cls = self._cls[-1] if self._cls else None
        fn = _Func(key=(self.mod.rel, qual), lineno=node.lineno, cls=cls)
        self.mod.funcs[qual] = fn
        if cls is not None and len(self._stack) == 1:
            self.mod.classes.setdefault(cls, set()).add(node.name)
        self._stack.append(node.name)
        self._cls.append(None)
        self._scan_body(node, fn)
        self.generic_visit(node)
        self._cls.pop()
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._enter_func(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef):
        self.mod.classes.setdefault(node.name, set())
        self._stack.append(node.name)
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()
        self._stack.pop()

    # body scan (only direct statements of this function, not nested defs) --
    def _scan_body(self, fnode, fn: _Func):
        def inner(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue  # nested defs get their own _Func
                self._scan_node(child, fn)
                inner(child)

        inner(fnode)

    def _scan_node(self, node, fn: _Func):
        # forcing syntax seeds the effect — unless the line carries a
        # BGT011 suppression (a sanctioned non-blocking guard)
        if isinstance(node, ast.Attribute) and node.attr in self.attrs:
            if "BGT011" not in self.sf.suppressions.get(node.lineno, {}):
                fn.direct.append((node.lineno, f".{node.attr}"))
            else:
                self.used.add((self.sf.rel, node.lineno, "BGT011"))
        elif isinstance(node, ast.Name) and node.id in self.names:
            if "BGT011" not in self.sf.suppressions.get(node.lineno, {}):
                fn.direct.append((node.lineno, node.id))
            else:
                self.used.add((self.sf.rel, node.lineno, "BGT011"))
        if not isinstance(node, ast.Call):
            return
        f = node.func
        if isinstance(f, ast.Name):
            fn.calls.append((node.lineno, ("bare", f.id)))
        elif isinstance(f, ast.Attribute):
            recv = f.value
            if isinstance(recv, ast.Name):
                if recv.id == "self":
                    fn.calls.append((node.lineno, ("self", f.attr)))
                else:
                    fn.calls.append((node.lineno, ("name_attr", recv.id, f.attr)))
            else:
                # dotted module path like pkg.mod.fn, or an arbitrary
                # expression receiver — keep the method name for the
                # unique-name fallback
                fn.calls.append((node.lineno, ("obj_attr", f.attr)))


class CallGraph:
    """Package-wide call graph with the "forces device->host sync" effect
    propagated backwards from every seeding site."""

    def __init__(self, ctx: Context):
        cfg = ctx.config
        self.cfg = cfg
        pkg_dir = cfg.package_dir
        package_parent = str(PurePosixPath(pkg_dir).parent)
        if package_parent == ".":
            package_parent = ""
        self.modules: Dict[str, _Module] = {}  # dotted -> module
        self.by_rel: Dict[str, _Module] = {}
        for sf in ctx.files:
            in_pkg = sf.rel == pkg_dir or sf.rel.startswith(pkg_dir + "/")
            if not in_pkg or sf.tree is None:
                continue
            mod = _Module(
                rel=sf.rel,
                dotted=_dotted(sf.rel, package_parent),
                is_pkg=sf.rel.endswith("__init__.py"),
            )
            _Collector(
                mod, sf, cfg.purity_attrs, cfg.purity_names,
                used=ctx.used_suppressions,
            ).visit(sf.tree)
            self.modules[mod.dotted] = mod
            self.by_rel[sf.rel] = mod
        # unique-name index over methods AND functions for the fallback
        self.by_name: Dict[str, List[_Func]] = {}
        self.funcs: Dict[FuncKey, _Func] = {}
        for mod in self.modules.values():
            for fn in mod.funcs.values():
                self.funcs[fn.key] = fn
                self.by_name.setdefault(fn.name, []).append(fn)
        self._propagate()

    # -- resolution ---------------------------------------------------------
    def _mod_func(self, mod: _Module, name: str) -> Optional[_Func]:
        return mod.funcs.get(name)

    def _class_method(self, mod: _Module, cls: str, meth: str) -> Optional[_Func]:
        return mod.funcs.get(f"{cls}.{meth}")

    def _module_attr(self, mod: _Module, attr: str):
        """Resolve ``mod.attr``: a def, a class, a submodule, or a
        re-exported name (an ``from .x import attr`` in the module —
        typically an ``__init__.py`` facade) chased one hop."""
        f = self._mod_func(mod, attr)
        if f is not None:
            return ("func", f)
        if attr in mod.classes:
            return ("class", mod, attr)
        inner = mod.imports.get(attr)
        if inner is None:
            return None
        if inner[0] == "module":
            target = self.modules.get(inner[1])
            return ("module", target) if target else None
        sub = self.modules.get(f"{inner[1]}.{inner[2]}")
        if sub is not None:
            return ("module", sub)
        src = self.modules.get(inner[1])
        if src is None:
            return None
        f = self._mod_func(src, inner[2])
        if f is not None:
            return ("func", f)
        if inner[2] in src.classes:
            return ("class", src, inner[2])
        return None

    def _follow_symbol(self, mod: _Module, alias: str):
        """What an imported alias refers to: ("module", _Module) or
        ("class", _Module, clsname) or ("func", _Func) or None."""
        entry = mod.imports.get(alias)
        if entry is None:
            return None
        if entry[0] == "module":
            target = self.modules.get(entry[1])
            return ("module", target) if target else None
        _, base, symbol = entry
        # `from pkg import mod` — pkg.mod is a module we know
        as_module = self.modules.get(f"{base}.{symbol}")
        if as_module is not None:
            return ("module", as_module)
        src = self.modules.get(base)
        if src is None:
            return None
        return self._module_attr(src, symbol)

    def _unique_by_name(self, name: str) -> Optional[_Func]:
        if name in _COMMON_METHOD_NAMES or name.startswith("__"):
            return None
        cands = self.by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def resolve(self, mod: _Module, caller: _Func, ref: tuple) -> Optional[_Func]:
        kind = ref[0]
        if kind == "bare":
            name = ref[1]
            f = self._mod_func(mod, name)
            if f is not None:
                return f
            sym = self._follow_symbol(mod, name)
            if sym and sym[0] == "func":
                return sym[1]
            return None
        if kind == "self":
            meth = ref[1]
            if caller.cls is not None:
                f = self._class_method(mod, caller.cls, meth)
                if f is not None:
                    return f
            return self._unique_by_name(meth)
        if kind == "name_attr":
            recv, attr = ref[1], ref[2]
            sym = self._follow_symbol(mod, recv)
            if sym is not None:
                if sym[0] == "module":
                    res = self._module_attr(sym[1], attr)
                    return res[1] if res and res[0] == "func" else None
                if sym[0] == "class":
                    return self._class_method(sym[1], sym[2], attr)
                if sym[0] == "func":
                    return None  # attribute of a function — not a call edge
            if recv in mod.classes:
                return self._class_method(mod, recv, attr)
            return self._unique_by_name(attr)
        if kind == "obj_attr":
            return self._unique_by_name(ref[1])
        return None

    # -- effect propagation -------------------------------------------------
    def _is_allowlisted(self, fn: _Func) -> bool:
        allow = self.cfg.purity_allowlist_for(fn.key[0])
        return allow is not None and fn.name in allow

    def _propagate(self):
        # why[key] = ("direct", line, what) | ("via", line, callee_key)
        self.why: Dict[FuncKey, tuple] = {}
        edges_rev: Dict[FuncKey, List[Tuple[_Func, int]]] = {}
        self.resolved: Dict[FuncKey, List[Tuple[int, _Func]]] = {}
        for mod in self.modules.values():
            for fn in mod.funcs.values():
                res = []
                for line, ref in fn.calls:
                    tgt = self.resolve(mod, fn, ref)
                    if tgt is None or tgt.key == fn.key:
                        continue
                    res.append((line, tgt))
                    edges_rev.setdefault(tgt.key, []).append((fn, line))
                self.resolved[fn.key] = res
        work = []
        for fn in self.funcs.values():
            if fn.direct and not self._is_allowlisted(fn):
                line, what = fn.direct[0]
                self.why[fn.key] = ("direct", line, what)
                work.append(fn.key)
        while work:
            key = work.pop()
            fn = self.funcs[key]
            if self._is_allowlisted(fn):
                continue  # sanctioned funnel: effect stops here
            for caller, line in edges_rev.get(key, []):
                if caller.key in self.why:
                    continue
                self.why[caller.key] = ("via", line, key)
                work.append(caller.key)

    def forces(self, key: FuncKey) -> bool:
        return key in self.why

    def chain(self, key: FuncKey) -> str:
        """Human-readable forcing chain ending at the direct site."""
        hops = []
        cur = key
        for _ in range(32):
            why = self.why.get(cur)
            if why is None:
                break
            if why[0] == "direct":
                rel, qual = cur
                hops.append(f"{qual}() forces via {why[2]} ({rel}:{why[1]})")
                break
            _, line, nxt = why
            hops.append(f"{cur[1]}() [{cur[0]}:{line}]")
            cur = nxt
        return " -> ".join(hops)


# -- passes -------------------------------------------------------------------


@lint_pass
def purity_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    out: List[Finding] = []

    # BGT010 — intra-function syntax, hot files only
    hot_files = []
    for sf in ctx.files:
        allow = cfg.purity_allowlist_for(sf.rel)
        if allow is None or sf.tree is None:
            continue
        hot_files.append((sf, allow))
        for line, msg in check_purity(
            sf.tree, allow, cfg.purity_attrs, cfg.purity_names
        ):
            out.append(Finding("BGT010", sf.rel, line, msg))

    # BGT011 — interprocedural: package call graph, report call sites in
    # hot files whose resolved callee transitively forces.  The graph is
    # stashed on ctx so later passes (BGT071 witness chains) reuse the
    # module/call-edge resolution instead of rebuilding it.
    graph = CallGraph(ctx)
    ctx._callgraph = graph
    for sf, allow in hot_files:
        mod = graph.by_rel.get(sf.rel)
        if mod is None:
            continue
        for fn in mod.funcs.values():
            if fn.name in allow:
                continue
            for line, tgt in graph.resolved.get(fn.key, []):
                if graph._is_allowlisted(tgt) or not graph.forces(tgt.key):
                    continue
                out.append(Finding(
                    "BGT011", sf.rel, line,
                    f"interprocedural purity: {fn.key[1]}() reaches a "
                    f"device->host-forcing helper: {graph.chain(tgt.key)} — "
                    "route through an allowlisted flush funnel or make the "
                    "helper non-blocking",
                ))

    # BGT012 — stale allowlist entries (AST lookup in the target file)
    if cfg.project_checks:
        for suffix, names in sorted(cfg.purity_allow.items()):
            target = ctx.by_suffix(suffix)
            if target is None:
                path = ctx.root / suffix
                if not path.exists():
                    out.append(Finding(
                        "BGT012", suffix, 0,
                        f"PURITY_ALLOW covers {suffix!r} but the file does "
                        "not exist — remove or update the entry "
                        "(scripts/lint/config.py)",
                    ))
                    continue
                # outside the linted path set: load directly
                from ..core import load_file

                target = load_file(path, ctx.root)
            if target.tree is None:
                continue
            defined = {
                n.name
                for n in ast.walk(target.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for fname in sorted(names - defined):
                out.append(Finding(
                    "BGT012", suffix, 0,
                    f"stale allowlist: PURITY_ALLOW[{suffix!r}] names "
                    f"{fname!r} but no such function exists in the file — "
                    "the allowlist rotted under a refactor "
                    "(scripts/lint/config.py)",
                ))
    return out
