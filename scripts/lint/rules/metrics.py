"""Metric-name <-> docs-catalog cross-check (BGT030/BGT031), ported from
the original ``lint_imports.py``.

Every metric the package/scripts register with a literal name must appear
in a ``| metric | ... |`` table of docs/observability.md, and every name
the docs catalog lists must still be registered somewhere — both
directions, so the catalog can neither rot nor silently under-document new
families.  Tests are excluded (they register throwaway names on purpose).
"""

from __future__ import annotations

import ast
import re
from typing import List

from ..core import Context, Finding, lint_pass, rule

rule(
    "BGT030", "undocumented-metric",
    summary="a metric registered in code has no docs/observability.md row",
)
rule(
    "BGT031", "stale-metric-doc",
    summary="a documented metric name is never registered in code",
)

# registry/shorthand entry points whose first positional arg is the name
_METRIC_REG_ATTRS = {
    "counter", "gauge", "histogram",
    "bind_counter", "bind_gauge", "bind_histogram", "gauge_set",
}
# telemetry-module shorthands; gated on the receiver being `telemetry` so
# unrelated `.count("x")` / `.observe(...)` methods never false-positive
_METRIC_TELEMETRY_ATTRS = {"count", "observe", "gauge_set"}
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]{2,}$")


def _attr_root(node: ast.Attribute):
    """Name at the root of a dotted/called access, e.g. ``registry().x`` or
    ``a.b.c`` -> ``registry`` / ``a`` (None when the root is not a name)."""
    inner = node.value
    while isinstance(inner, (ast.Attribute, ast.Call)):
        inner = inner.func if isinstance(inner, ast.Call) else inner.value
    return inner.id if isinstance(inner, ast.Name) else None


def collect_metric_names(tree: ast.AST) -> set:
    """Metric names registered with a string literal anywhere in ``tree``."""
    names = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr in _METRIC_TELEMETRY_ATTRS:
            if _attr_root(node.func) != "telemetry":
                continue
        elif attr not in _METRIC_REG_ATTRS:
            continue
        if not node.args:
            continue
        a0 = node.args[0]
        # a conditional name picks one of two literals (runner.py's
        # speculation hit/miss counter) — both are registered names
        cands = [a0.body, a0.orelse] if isinstance(a0, ast.IfExp) else [a0]
        for c in cands:
            if isinstance(c, ast.Constant) and isinstance(c.value, str) \
                    and _METRIC_NAME_RE.match(c.value):
                names.add(c.value)
    return names


def docs_metric_names(md_text: str) -> set:
    """Backticked names in the first column of every ``| metric | ... |``
    table in the docs catalog."""
    names = set()
    in_table = False
    for line in md_text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        if cells[0] == "metric":
            in_table = True
            continue
        if in_table and not set(cells[0]) <= set("-: "):
            names.update(re.findall(r"`([a-z][a-z0-9_]+)`", cells[0]))
    return names


@lint_pass
def metrics_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    if not cfg.project_checks:
        return []
    code_names = set()
    for sf in ctx.files:
        if sf.tree is None or sf.is_test or sf.is_fixture:
            continue
        code_names |= collect_metric_names(sf.tree)
    docs_path = ctx.root / cfg.metric_docs
    if not docs_path.exists():
        return [Finding("BGT031", cfg.metric_docs, 0, "metric catalog file missing")]
    doc_names = docs_metric_names(docs_path.read_text())
    out: List[Finding] = []
    for name in sorted(code_names - doc_names):
        out.append(Finding(
            "BGT030", cfg.metric_docs, 0,
            f"metric {name!r} is registered in code but missing from the "
            "docs catalog (add a `| metric | labels | meaning |` row)",
        ))
    # the reverse (stale-row) direction needs the FULL registration corpus —
    # a partial-path run must not call a row stale just because the file
    # that registers it was not linted (same guard as the BGT022 reverse
    # check); the package __init__ in the corpus is the full-run proxy
    full_corpus = (
        ctx.by_suffix(cfg.package_dir + "/__init__.py") is not None
        and not getattr(cfg, "partial_corpus", False)
    )
    if full_corpus:
        for name in sorted(doc_names - code_names):
            out.append(Finding(
                "BGT031", cfg.metric_docs, 0,
                f"metric {name!r} is documented in the catalog but never "
                "registered in code (stale row — remove or fix the name)",
            ))
    return out
