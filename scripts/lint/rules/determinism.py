"""Determinism-hazard rules for step/model/session code (BGT040-BGT044).

Every peer re-simulates bit-identically — that is the whole contract
(docs/determinism.md).  Any nondeterminism is a silent desync that SyncTest
can only catch at runtime, frames after the fact; these rules enforce the
property statically, at the program level:

- **BGT040 wall-clock**: ``time.time()`` / ``time.monotonic()`` inside sim
  code (models/, ops/) — frame-derived time is the only clock a step
  function may see (``StepCtx.time``).  ``perf_counter`` is deliberately
  allowed: it feeds telemetry, never state.
- **BGT041 unseeded RNG**: the process-global ``random`` module RNG and
  ``np.random`` module-level sampling share hidden state across call sites
  and peers; all randomness must flow from an explicit seed
  (``np.random.default_rng(seed)``, ``random.Random(seed)``, or the
  per-frame ``ctx.rng_key`` fold).
- **BGT042 set-iteration order**: iterating a ``set`` into ``sum()`` or an
  array constructor bakes hash order (PYTHONHASHSEED-dependent for str)
  into float accumulation order / array layout — sort first.
- **BGT043 host callbacks in jitted step code**: ``jax.debug.*`` /
  ``io_callback`` / ``pure_callback`` inside sim code round-trips to host
  mid-program — a sync leak at best, an ordering hazard under async
  dispatch at worst.
- **BGT044 frozen-world mutation**: in-place assignment into ``world``
  (``world.comps[...] = x``) bypasses the immutable-snapshot contract the
  save ring depends on; use ``dataclasses.replace``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..core import Context, Finding, SourceFile, lint_pass, rule

rule(
    "BGT040", "wall-clock-in-step",
    summary="wall-clock read inside sim code — use frame-derived ctx.time",
)
rule(
    "BGT041", "unseeded-rng",
    summary="process-global RNG use — derive all randomness from an explicit seed",
)
rule(
    "BGT042", "set-iteration-order",
    summary="set iteration feeding sum()/array construction bakes in hash order",
)
rule(
    "BGT043", "host-callback-in-step",
    summary="jax.debug/io_callback/pure_callback inside sim code",
)
rule(
    "BGT044", "frozen-world-mutation",
    summary="in-place mutation of the frozen world — use dataclasses.replace",
)

_WALL_CLOCK = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow", "datetime.date.today",
})
# seeded-constructor names exempt under numpy.random / random
_RNG_CTORS = frozenset({"default_rng", "Generator", "SeedSequence", "PCG64",
                        "Philox", "RandomState", "Random", "SystemRandom"})
_HOST_CALLBACKS = frozenset({
    "jax.experimental.io_callback", "jax.pure_callback",
    "jax.experimental.pure_callback",
})
_ARRAY_CTORS = frozenset({"asarray", "array", "stack", "concatenate",
                          "hstack", "vstack", "fromiter"})


def _alias_map(tree: ast.AST) -> Dict[str, str]:
    """name bound in this module -> dotted path it refers to
    (``np`` -> ``numpy``, ``getrandbits`` -> ``random.getrandbits``)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
                else:
                    aliases[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _dotted_path(func, aliases: Dict[str, str]) -> Optional[str]:
    """Fully-resolved dotted path of a call target, or None for anything
    that is not a plain Name/Attribute-of-Names chain."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    root = aliases.get(node.id, node.id)
    return ".".join([root] + list(reversed(parts)))


def _is_set_expr(node) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("set", "frozenset"):
        return True
    return False


def _iterates_a_set(arg) -> bool:
    """True when ``arg`` is a set expression or a comprehension whose
    outermost iterable is one."""
    if _is_set_expr(arg):
        return True
    if isinstance(arg, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
        return _is_set_expr(arg.generators[0].iter)
    return False


def _enclosing_functions(tree: ast.AST) -> Dict[int, str]:
    """id(node) -> name of the innermost enclosing function (for scoping
    wall-clock: module-level timing constants are not step code)."""
    owner: Dict[int, str] = {}

    def walk(node, fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = node.name
        owner[id(node)] = fn
        for child in ast.iter_child_nodes(node):
            walk(child, fn)

    walk(tree, None)
    return owner


def check_determinism(sf: SourceFile, in_sim: bool) -> List[Finding]:
    """All BGT04x findings for one file; BGT041/BGT042 run everywhere the
    pass is scoped, BGT040/BGT043/BGT044 only in sim code."""
    tree = sf.tree
    aliases = _alias_map(tree)
    owner = _enclosing_functions(tree) if in_sim else {}
    out: List[Finding] = []

    for node in ast.walk(tree):
        # BGT044: in-place mutation of the frozen world ------------------
        if in_sim and isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            flat = []
            for t in targets:
                flat.extend(t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t])
            for t in flat:
                root = t
                while isinstance(root, (ast.Attribute, ast.Subscript)):
                    root = root.value
                if isinstance(root, ast.Name) and root.id == "world" and root is not t:
                    out.append(Finding(
                        "BGT044", sf.rel, t.lineno,
                        "frozen-world mutation: assigning into `world` "
                        "in-place corrupts every snapshot sharing the "
                        "buffer — build the new state with "
                        "dataclasses.replace(world, ...)",
                    ))

        if not isinstance(node, ast.Call):
            continue
        path = _dotted_path(node.func, aliases)

        # BGT042: set iteration feeding order-sensitive accumulation -----
        consumer = None
        if isinstance(node.func, ast.Name) and node.func.id == "sum":
            consumer = "sum()"
        elif isinstance(node.func, ast.Attribute):
            if node.func.attr in _ARRAY_CTORS:
                consumer = f".{node.func.attr}()"
            elif node.func.attr == "join":
                consumer = ".join()"
        if consumer and node.args and _iterates_a_set(node.args[0]):
            out.append(Finding(
                "BGT042", sf.rel, node.lineno,
                f"set-iteration order: {consumer} over a set bakes hash "
                "order into the result (float accumulation order / array "
                "layout differ across peers under PYTHONHASHSEED) — "
                "iterate sorted(...) instead",
            ))

        if path is None:
            continue

        # BGT040: wall-clock in sim code ---------------------------------
        if in_sim and path in _WALL_CLOCK and owner.get(id(node)) is not None:
            out.append(Finding(
                "BGT040", sf.rel, node.lineno,
                f"wall-clock read: {path}() inside sim code desyncs peers "
                "— step functions may only see frame-derived time "
                "(StepCtx.time = frame / fps)",
            ))

        # BGT041: process-global RNG -------------------------------------
        parts = path.split(".")
        if parts[0] == "random" and len(parts) == 2:
            fn = parts[1]
            if fn not in _RNG_CTORS:
                out.append(Finding(
                    "BGT041", sf.rel, node.lineno,
                    f"unseeded RNG: random.{fn}() uses the process-global "
                    "generator — peers (and reruns) draw different "
                    "streams; use random.Random(seed) or the per-frame "
                    "ctx.rng_key fold",
                ))
            elif fn in ("Random", "RandomState") and not node.args:
                out.append(Finding(
                    "BGT041", sf.rel, node.lineno,
                    f"unseeded RNG: random.{fn}() without a seed argument "
                    "is nondeterministic across runs — pass an explicit "
                    "seed",
                ))
        elif len(parts) >= 2 and parts[0] == "numpy" and parts[1] == "random":
            fn = parts[-1]
            if fn not in _RNG_CTORS and len(parts) >= 3:
                out.append(Finding(
                    "BGT041", sf.rel, node.lineno,
                    f"unseeded RNG: np.random.{fn}() samples the legacy "
                    "module-global RNG — use np.random.default_rng(seed)",
                ))
            elif fn in ("default_rng", "RandomState") and not node.args:
                out.append(Finding(
                    "BGT041", sf.rel, node.lineno,
                    f"unseeded RNG: np.random.{fn}() without a seed is "
                    "OS-entropy seeded — pass the explicit seed param",
                ))

        # BGT043: host callbacks in jitted sim code ----------------------
        if in_sim and (
            path in _HOST_CALLBACKS
            or path.startswith("jax.debug.")
            or path.endswith(".io_callback")
            or path == "io_callback"
        ):
            out.append(Finding(
                "BGT043", sf.rel, node.lineno,
                f"host callback in sim code: {path}() round-trips "
                "device->host inside the jitted step — a sync leak that "
                "voids pipelining and an ordering hazard under async "
                "dispatch; strip it before shipping",
            ))
    return out


@lint_pass
def determinism_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    out: List[Finding] = []
    for sf in ctx.files:
        if sf.tree is None or sf.is_test:
            continue
        out.extend(check_determinism(sf, in_sim=cfg.in_sim_code(sf.rel)))
    return out
