"""Rule-id <-> docs-catalog cross-check (BGT050/BGT051).

docs/static-analysis.md carries the human-facing rule catalog (what each
rule catches, why it matters for determinism, how to suppress it).  The
registry in :mod:`..core` is the machine truth; this pass diffs the two in
both directions, the same way the metric<->docs lint works, so the catalog
can neither rot nor silently under-document a new rule.
"""

from __future__ import annotations

import re
from typing import List

from ..core import RULES, Context, Finding, lint_pass, rule

rule(
    "BGT050", "undocumented-rule",
    summary="a registered rule id has no docs/static-analysis.md row",
)
rule(
    "BGT051", "stale-rule-doc",
    summary="a documented rule id is not registered in the analyzer",
)

_RULE_ID_IN_DOCS = re.compile(r"`(BGT0\d\d)`")


def docs_rule_ids(md_text: str) -> set:
    """Rule ids named in the first column of every ``| rule | ... |`` table."""
    ids = set()
    in_table = False
    for line in md_text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        if cells[0] == "rule":
            in_table = True
            continue
        if in_table and not set(cells[0]) <= set("-: "):
            ids.update(_RULE_ID_IN_DOCS.findall(cells[0]))
    return ids


@lint_pass
def docs_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    if not cfg.project_checks:
        return []
    docs_path = ctx.root / cfg.rule_docs
    if not docs_path.exists():
        return [Finding(
            "BGT050", cfg.rule_docs, 0,
            "rule catalog file missing — every BGT0xx rule must be "
            "documented (id, what it catches, why, how to suppress)",
        )]
    doc_ids = docs_rule_ids(docs_path.read_text())
    out: List[Finding] = []
    for rid in sorted(set(RULES) - doc_ids):
        out.append(Finding(
            "BGT050", cfg.rule_docs, 0,
            f"rule {rid} ({RULES[rid].name}) is registered in the analyzer "
            "but missing from the docs catalog (add a `| rule | ... |` row)",
        ))
    for rid in sorted(doc_ids - set(RULES)):
        out.append(Finding(
            "BGT051", cfg.rule_docs, 0,
            f"rule {rid} is documented in the catalog but not registered "
            "in the analyzer (stale row — remove or fix the id)",
        ))
    return out
