"""Inconsistent lock acquisition order — BGT062.

Two locks taken as ``A then B`` on one code path and ``B then A`` on
another is the textbook ABBA deadlock, and it is invisible to every test
that doesn't lose the exact race.  The module scanner already records the
nesting order of textual lock paths per function (a ``with a:`` lexically
enclosing a ``with b:``, including multi-item ``with a, b:`` which
acquires left-to-right); this pass merges those orders module-wide and
flags every pair witnessed in both directions, naming both witness sites
so the fix — pick one canonical order and rewrite the minority site — is
mechanical.

Lock identity is the dotted source path (``self._lock``), same textual
witness as BGT060: two different objects that happen to share a spelling
could false-positive, but in this codebase lock spellings are unique per
class and the modules in scope are small; suppress with the aliasing
argument if that ever changes.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..core import Context, Finding, lint_pass, rule
from .shared_state import scan_module

rule(
    "BGT062", "inconsistent-lock-order",
    summary="two locks are acquired in opposite nesting orders on "
            "different code paths — the classic ABBA deadlock",
)


@lint_pass
def lock_order_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    out: List[Finding] = []
    for sf in ctx.files:
        if sf.tree is None or sf.is_test:
            continue
        if not cfg.in_concurrency_scope(sf.rel):
            continue
        mmap = scan_module(sf, cfg)
        # (A, B) -> [(qual, line)] witnesses of "A held when B acquired"
        orders: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
        for qual, fi in mmap.funcs.items():
            for outer, inner, line in fi.lock_orders:
                orders.setdefault((outer, inner), []).append((qual, line))
        reported = set()
        for (a, b), sites in sorted(orders.items()):
            if (b, a) not in orders or frozenset((a, b)) in reported:
                continue
            reported.add(frozenset((a, b)))
            qual, line = min(sites, key=lambda s: s[1])
            rqual, rline = min(orders[(b, a)], key=lambda s: s[1])
            out.append(Finding(
                "BGT062", sf.rel, line,
                f"inconsistent lock order: {qual} (line {line}) acquires "
                f"{a} then {b}, but {rqual} (line {rline}) acquires "
                f"{b} then {a} — pick one canonical order; two threads "
                "taking these paths concurrently deadlock",
            ))
    return out
