"""Cross-thread shared state — BGT060.

The fleet control plane (PR 11) and the telemetry exporter are the only
places this repo runs (or is one ``threading.Thread`` away from running)
concurrent code, and the determinism rules are blind to them: a mutable
attribute written from a scrape thread AND the tick loop with no common
lock is a data race that no SyncTest oracle will ever catch — it shows up
as a corrupted heartbeat or a torn metrics series once per ten thousand
scrapes.  BGT060 builds a per-class attribute/lock map over the modules
in ``config.CONCURRENCY_MODULES``:

- **background entry points** are detected (``threading.Thread(target=
  ...)`` targets, ``do_*`` methods of HTTP handler classes) or declared
  (``config.THREAD_ROOTS`` — cross-module entries like the Prometheus
  scrape threads calling straight into ``Gauge.set``);
- every function reachable from a background root is *background*; every
  function not reachable ONLY from thread-only roots is *foreground*
  (declared roots are public API, so they count as both);
- an attribute written (rebound or subscript-mutated through ``self.X``)
  from both worlds must hold one **common lock** — a ``with <lock>:``
  whose expression names the same dotted path — at every write site
  outside ``__init__`` (construction happens-before ``Thread.start``).

The lock witness is textual (``self._reg._lock`` == ``self._reg._lock``)
— no alias analysis, which is exactly as strong as the repo's lock idiom
(locks live on ``self``/one hop down and are acquired with ``with``).
Explicit ``.acquire()``/``.release()`` pairing is NOT modeled; rewrite to
``with`` or suppress with the protocol that replaces the lock.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from ..core import Context, Finding, SourceFile, lint_pass, rule

rule(
    "BGT060", "unlocked-shared-attribute",
    summary="attribute written from both a background thread and the "
            "foreground with no common lock held at every write site",
)

# a with-expression is a lock witness when its last path segment looks
# like one — matches the repo idiom (_lock on the registry, per-object
# locks named `lock`) without resolving types
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|mutex|rlock|cond|condition)$", re.I)


def dotted_path(node: ast.AST) -> Optional[str]:
    """``self._reg._lock`` -> that string; None for non-Name/Attribute."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_lock_expr(dotted: Optional[str]) -> bool:
    return bool(dotted) and bool(_LOCK_NAME_RE.search(dotted.rsplit(".", 1)[-1]))


@dataclasses.dataclass
class FuncInfo:
    """One function/method of a concurrency-scoped module."""

    qual: str  # dotted qualname (Cls.meth, Outer.__init__.Handler.do_GET)
    cls: Optional[str]  # nearest enclosing class name
    lineno: int
    # attr -> [(line, held_locks frozenset)] for writes through self.attr
    writes: Dict[str, List[Tuple[int, frozenset]]] = dataclasses.field(
        default_factory=dict
    )
    # local call refs: ("self", name) | ("bare", name) | ("attr", name)
    calls: List[tuple] = dataclasses.field(default_factory=list)
    # (outer_lock, inner_lock, line) nesting orders witnessed (BGT062)
    lock_orders: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list
    )
    # (line, call_repr, held_locks) blocking calls under a lock (BGT061)
    blocking: List[Tuple[int, str, frozenset]] = dataclasses.field(
        default_factory=list
    )


@dataclasses.dataclass
class ModuleMap:
    """Everything BGT060/061/062 need about one module."""

    funcs: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    # qualnames that are thread-only entry points (Thread targets, do_*)
    bg_only_roots: Set[str] = dataclasses.field(default_factory=set)
    handler_classes: Set[str] = dataclasses.field(default_factory=set)


def _first_self_attr(node: ast.AST) -> Optional[str]:
    """For a store target rooted at ``self``: the first attribute after it
    (``self.X`` and ``self.X[...]`` and ``self.X.Y = ...`` all -> X)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    chain: List[str] = []
    while isinstance(node, ast.Attribute):
        chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and chain:
        return chain[-1]
    return None


class _ModuleScanner(ast.NodeVisitor):
    """Collect per-function write/lock/call facts for one module."""

    def __init__(self, mmap: ModuleMap, blocking_attrs, blocking_dotted):
        self.mmap = mmap
        self.blocking_attrs = blocking_attrs
        self.blocking_dotted = blocking_dotted
        self._stack: List[str] = []
        self._cls: List[str] = []

    def visit_ClassDef(self, node: ast.ClassDef):
        base_names = {dotted_path(b) or "" for b in node.bases}
        if any(n.rsplit(".", 1)[-1].endswith("RequestHandler")
               for n in base_names):
            self.mmap.handler_classes.add(node.name)
        self._stack.append(node.name)
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()
        self._stack.pop()

    def _enter_func(self, node):
        qual = ".".join(self._stack + [node.name])
        cls = self._cls[-1] if self._cls else None
        fi = FuncInfo(qual=qual, cls=cls, lineno=node.lineno)
        self.mmap.funcs[qual] = fi
        if cls in self.mmap.handler_classes and node.name.startswith("do_"):
            self.mmap.bg_only_roots.add(qual)
        self._scan_body(node, fi)
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _enter_func

    # -- statement-level scan with a held-lock stack ------------------------
    def _scan_body(self, fnode, fi: FuncInfo):
        def scan(node, held: Tuple[str, ...]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue  # nested defs get their own FuncInfo
                inner_held = held
                if isinstance(child, ast.With):
                    for item in child.items:
                        d = dotted_path(item.context_expr)
                        if is_lock_expr(d):
                            for outer in inner_held:
                                if outer != d:
                                    fi.lock_orders.append(
                                        (outer, d, child.lineno)
                                    )
                            inner_held = inner_held + (d,)
                self._scan_stmt(child, fi, inner_held)
                scan(child, inner_held)

        scan(fnode, ())

    def _scan_stmt(self, node, fi: FuncInfo, held: Tuple[str, ...]):
        hset = frozenset(held)
        # writes through self.X (rebind, augmented, subscript/attr store)
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]):
                attr = _first_self_attr(el)
                if attr is not None:
                    fi.writes.setdefault(attr, []).append((node.lineno, hset))
        # calls: thread targets, local edges, blocking-under-lock
        if not isinstance(node, ast.Call):
            return
        d = dotted_path(node.func)
        if d is not None and d.rsplit(".", 1)[-1] == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    td = dotted_path(kw.value)
                    if td is not None:
                        self.mmap.bg_only_roots.add(
                            td[5:] if td.startswith("self.") else td
                        )
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                fi.calls.append(("self", node.func.attr))
            else:
                fi.calls.append(("attr", node.func.attr))
        elif isinstance(node.func, ast.Name):
            fi.calls.append(("bare", node.func.id))
        if held and self._is_blocking(node, d):
            fi.blocking.append((node.lineno, d or "<call>", hset))

    def _is_blocking(self, node: ast.Call, d: Optional[str]) -> bool:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in self.blocking_attrs:
            return True
        if d is None:
            return False
        return any(
            d == p or (p.endswith(".") and d.startswith(p))
            for p in self.blocking_dotted
        )


def scan_module(sf: SourceFile, cfg) -> ModuleMap:
    mmap = ModuleMap()
    _ModuleScanner(
        mmap, cfg.blocking_call_attrs, cfg.blocking_call_dotted
    ).visit(sf.tree)
    return mmap


def _resolve_local(mmap: ModuleMap, fi: FuncInfo, ref: tuple) -> Optional[str]:
    """Same-module call-edge resolution, mirroring the purity graph's
    conservative shapes (self method / module function / unique name)."""
    kind, name = ref
    if kind == "self" and fi.cls is not None:
        # nearest enclosing class wins; handles nested handler classes
        prefix = fi.qual.rsplit(".", 1)[0]
        cand = f"{prefix}.{name}"
        if cand in mmap.funcs:
            return cand
    if kind == "bare" and name in mmap.funcs:
        return name
    matches = [q for q, f in mmap.funcs.items()
               if q.rsplit(".", 1)[-1] == name]
    return matches[0] if len(matches) == 1 else None


def _closure(mmap: ModuleMap, roots: Set[str]) -> Set[str]:
    seen = set(r for r in roots if r in mmap.funcs)
    work = list(seen)
    while work:
        cur = work.pop()
        for ref in mmap.funcs[cur].calls:
            tgt = _resolve_local(mmap, mmap.funcs[cur], ref)
            if tgt is not None and tgt not in seen:
                seen.add(tgt)
                work.append(tgt)
    return seen


def partition(mmap: ModuleMap, declared_roots: Set[str]):
    """``(bg_funcs, fg_funcs)`` qualname sets.  Declared roots are public
    API (reached from BOTH worlds); detected thread targets / do_* are
    background-only.  Root spellings (``_scrape`` from a Thread target,
    ``Cls.meth`` from config) are matched against qualnames by dotted
    suffix."""

    def match(qual: str, roots) -> bool:
        return any(qual == r or qual.endswith("." + r) for r in roots)

    bg_only = {q for q in mmap.funcs if match(q, mmap.bg_only_roots)}
    declared = {q for q in mmap.funcs if match(q, declared_roots)}
    bg = _closure(mmap, bg_only | declared)
    fg_roots = {q for q in mmap.funcs if q not in bg_only or q in declared}
    fg = _closure(mmap, fg_roots)
    return bg, fg


def check_shared_state(sf: SourceFile, cfg) -> List[Finding]:
    mmap = scan_module(sf, cfg)
    bg, fg = partition(mmap, cfg.thread_roots_for(sf.rel))
    if not bg:
        return []  # no background entry points: nothing is concurrent
    out: List[Finding] = []
    # group write sites per (class, attr) across all that class's methods
    by_attr: Dict[Tuple[str, str], List[Tuple[str, int, frozenset]]] = {}
    for qual, fi in mmap.funcs.items():
        if fi.cls is None:
            continue
        for attr, sites in fi.writes.items():
            for line, held in sites:
                by_attr.setdefault((fi.cls, attr), []).append(
                    (qual, line, held)
                )
    for (cls, attr), sites in sorted(by_attr.items()):
        live = [s for s in sites
                if not s[0].rsplit(".", 1)[-1] == "__init__"]
        if not live:
            continue  # construction happens-before Thread.start
        bg_writers = sorted({q for q, _, _ in live if q in bg})
        fg_writers = sorted({q for q, _, _ in live if q in fg})
        if not bg_writers or not fg_writers:
            continue  # one world only: no race
        common = frozenset.intersection(*[h for _, _, h in live])
        if common:
            continue  # a shared lock witnesses every write
        line = min(l for _, l, _ in live)
        out.append(Finding(
            "BGT060", sf.rel, line,
            f"unlocked shared attribute: {cls}.{attr} is written from a "
            f"background thread ({', '.join(bg_writers)}) and the "
            f"foreground ({', '.join(fg_writers)}) with no common lock "
            "held at every write site — hold one `with <lock>:` around "
            "every write (or suppress with the protocol that orders them)",
        ))
    return out


@lint_pass
def shared_state_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    out: List[Finding] = []
    for sf in ctx.files:
        if sf.tree is None or sf.is_test:
            continue
        if not cfg.in_concurrency_scope(sf.rel):
            continue
        out.extend(check_shared_state(sf, cfg))
    return out
