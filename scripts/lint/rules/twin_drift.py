"""Solo/batched twin-drift — BGT073.

ROADMAP item 5 (collapse the ``GgrsRunner``/``BatchedRunner``
duplication) is blocked on nobody knowing precisely *which* paired
hot-path implementations have drifted.  This rule answers that
mechanically: ``scripts/lint/config.py`` declares the twin map — pairs
of ``file::Qual.name`` references with an expectation — and the pass
compares each pair after normalizing both ASTs:

- docstrings dropped, type annotations stripped,
- argument/local names renamed to positional placeholders in first-use
  order (``self`` and free/global names keep their spelling),
- string literals inside telemetry/phase calls (``span("...")``,
  ``.record("...")``, ``telemetry.count("...")``) blanked, so a
  ``"rollback"`` vs ``"batched_rollback"`` label is not drift.

``expect="sync"`` pairs must normalize identically — divergence is a
finding on the solo definition line.  ``expect="drift"`` pairs are the
documented duplication inventory; one that CONVERGES is also a finding
(promote it to sync so the map stays honest).  A reference naming a
missing function is map rot (same idea as BGT012).

Full project runs additionally emit ``LINT_twins.json`` — the
machine-readable duplication inventory (pair, status, similarity ratio,
line counts) that is the work-list for the ROADMAP-5 unification.
"""

from __future__ import annotations

import ast
import copy
import difflib
import json
from typing import Dict, List, Optional

from ..core import Context, Finding, lint_pass, rule

rule(
    "BGT073", "solo-batched-twin-drift",
    summary="declared solo/batched twin pair drifted (or a declared drift "
            "converged) — keep the twin map honest",
)

# calls whose string-literal args are labels, not semantics
_LABEL_CALL_ATTRS = frozenset({
    "span", "phase", "record", "count", "observe", "gauge_set", "inc",
    "observe_key", "set_key",
})


def _strip_labels_and_docs(fn: ast.AST) -> None:
    """In place: drop docstrings, annotations and label strings."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            node.returns = None
            if (node.body and isinstance(node.body[0], ast.Expr)
                    and isinstance(node.body[0].value, ast.Constant)
                    and isinstance(node.body[0].value.value, str)):
                node.body = node.body[1:] or [ast.Pass()]
        elif isinstance(node, ast.arg):
            node.annotation = None
        elif isinstance(node, ast.Call):
            attr = (node.func.attr if isinstance(node.func, ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name)
                    else None)
            if attr in _LABEL_CALL_ATTRS:
                for i, a in enumerate(node.args):
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        node.args[i] = ast.Constant(value="")
                for k in node.keywords:
                    if isinstance(k.value, ast.Constant) and isinstance(
                            k.value.value, str):
                        k.value = ast.Constant(value="")


def _rename_locals(fn: ast.AST) -> None:
    """In place: rename args + locally-bound names to placeholders in
    first-binding order; free (closure/global/builtin) names keep their
    spelling so cross-module references still have to match."""
    mapping: Dict[str, str] = {}

    def bind(name: str) -> None:
        if name not in mapping:
            mapping[name] = f"_v{len(mapping)}"

    for node in ast.walk(fn):
        if isinstance(node, ast.arg):
            bind(node.arg)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bind(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            bind(node.name)
    for node in ast.walk(fn):
        if isinstance(node, ast.arg) and node.arg in mapping:
            node.arg = mapping[node.arg]
        elif isinstance(node, ast.Name) and node.id in mapping:
            node.id = mapping[node.id]
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn and node.name in mapping:
            node.name = mapping[node.name]
        elif isinstance(node, ast.Global):
            node.names = [mapping.get(n, n) for n in node.names]
        elif isinstance(node, ast.Nonlocal):
            node.names = [mapping.get(n, n) for n in node.names]


def normalize_dump(fn: ast.AST) -> str:
    """Comparable dump of a function def (see module docstring)."""
    fn = copy.deepcopy(fn)
    fn.name = "_twin"
    fn.decorator_list = []
    _strip_labels_and_docs(fn)
    _rename_locals(fn)
    return ast.dump(fn, annotate_fields=False, include_attributes=False)


def find_qualname(tree: ast.AST, qual: str) -> Optional[ast.AST]:
    parts = qual.split(".")

    def descend(node, remaining):
        head, rest = remaining[0], remaining[1:]
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)) and child.name == head:
                if not rest:
                    return child if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) else None
                found = descend(child, rest)
                if found is not None:
                    return found
        return None

    return descend(tree, parts)


def _resolve(ctx: Context, ref: str):
    """``(sf, fn_node, rel, qual)`` for a ``file::Qual.name`` ref; the
    missing part is None."""
    rel, _, qual = ref.partition("::")
    sf = ctx.by_suffix(rel)
    if sf is None or sf.tree is None:
        return None, None, rel, qual
    return sf, find_qualname(sf.tree, qual), rel, qual


@lint_pass
def twin_drift_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    if getattr(cfg, "partial_corpus", False) or not cfg.twin_map:
        return []
    out: List[Finding] = []
    inventory: List[dict] = []
    corpus_complete = True

    for solo_ref, batch_ref, expect, note in cfg.twin_map:
        solo_sf, solo_fn, solo_rel, solo_qual = _resolve(ctx, solo_ref)
        batch_sf, batch_fn, batch_rel, batch_qual = _resolve(ctx, batch_ref)
        if solo_sf is None or batch_sf is None:
            # a twinned file missing from the corpus: not a full run
            corpus_complete = False
            continue
        rot = []
        if solo_fn is None:
            rot.append((solo_rel, solo_qual))
        if batch_fn is None:
            rot.append((batch_rel, batch_qual))
        if rot:
            for rel, qual in rot:
                out.append(Finding(
                    "BGT073", rel, 0,
                    f"twin map rot: {qual!r} no longer exists in {rel} — "
                    "the declared solo/batched pair "
                    f"({solo_ref} <-> {batch_ref}) rotted under a "
                    "refactor; update TWIN_MAP (scripts/lint/config.py)",
                ))
            inventory.append({
                "solo": solo_ref, "batched": batch_ref, "expect": expect,
                "status": "missing", "similarity": 0.0, "note": note,
            })
            continue
        dump_a = normalize_dump(solo_fn)
        dump_b = normalize_dump(batch_fn)
        in_sync = dump_a == dump_b
        similarity = 1.0 if in_sync else round(
            difflib.SequenceMatcher(None, dump_a, dump_b).ratio(), 3)
        if expect == "sync" and not in_sync:
            out.append(Finding(
                "BGT073", solo_rel, solo_fn.lineno,
                f"declared-sync twin drifted: {solo_qual} vs "
                f"{batch_ref} normalize differently (similarity "
                f"{similarity:.0%}) — re-align the implementations or "
                "re-declare the pair as drift in TWIN_MAP "
                "(scripts/lint/config.py)",
            ))
        elif expect == "drift" and in_sync:
            out.append(Finding(
                "BGT073", solo_rel, solo_fn.lineno,
                f"declared-drift twin converged: {solo_qual} and "
                f"{batch_ref} now normalize identically — promote the "
                "pair to expect=\"sync\" in TWIN_MAP so the "
                "duplication inventory stays honest",
            ))
        inventory.append({
            "solo": solo_ref, "batched": batch_ref, "expect": expect,
            "status": "in_sync" if in_sync else "drifted",
            "similarity": similarity,
            "solo_lines": _body_lines(solo_fn),
            "batched_lines": _body_lines(batch_fn),
            "note": note,
        })

    # the machine-readable ROADMAP-5 work-list, full project runs only
    twins_json = getattr(cfg, "twins_json", None)
    if cfg.project_checks and twins_json and corpus_complete:
        payload = {
            "version": 1,
            "generated_by": "scripts.lint BGT073 (twin_drift_pass)",
            "pairs": inventory,
            "drifted": sum(1 for p in inventory if p["status"] == "drifted"),
        }
        path = ctx.root / twins_json
        path.write_text(json.dumps(payload, indent=2) + "\n")
    return out


def _body_lines(fn: ast.AST) -> int:
    end = getattr(fn, "end_lineno", fn.lineno)
    return int(end - fn.lineno + 1)
