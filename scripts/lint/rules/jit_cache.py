"""jit cache-key hazards — BGT070.

Every hot-path guarantee the engine ships assumes XLA executables stay
*cached*: ``jax.jit`` keys its cache on the callable's identity plus the
static-argument values, so three Python-side patterns silently defeat it
and turn a 60Hz tick into a 10-50ms compile cliff:

- **fresh callable per call** — ``jax.jit(f)`` (or a lambda / local def /
  inline ``functools.partial``) created inside a function that runs per
  tick builds a NEW cache every call; nothing ever hits.  Sanctioned
  creation sites: module scope, ``make_*``/``build_*``/``init_*``
  factories (callers memoize the result), ``__init__`` bodies,
  ``@cached_property``/``@lru_cache`` bodies, keyed memo caches
  (``cache[key] = jax.jit(...)``) and lazy module singletons
  (``global _fn; _fn = jax.jit(...)``).
- **per-call-varying / non-literal static args** — a ``static_argnums``
  or ``static_argnames`` value that is not a literal cannot be proven
  call-stable; every distinct runtime value is a separate executable.
  Likewise an f-string, dict or other non-hashable literal fed through a
  ``functools.partial`` into ``jax.jit`` either crashes hashing or keys
  the cache on object identity (fresh per call).
- **mutable closed-over state** — a jitted local function that closes
  over a name the enclosing scope mutates (``state[k] = ...``,
  ``xs.append(...)``, augmented assignment) bakes the value at trace
  time: the mutation is invisible to later cached calls, a silent
  determinism drift no recompile ever fixes.

The runtime twin is the ``BGT_COMPILE_GUARD`` sentinel
(``bevy_ggrs_tpu/utils/compile_guard.py``): what this rule cannot prove
statically trips :class:`RecompileError` on the first steady-state
compile.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Context, Finding, SourceFile, lint_pass, rule
from .determinism import _alias_map, _dotted_path

rule(
    "BGT070", "jit-cache-key-hazard",
    summary="jit cache-key hazard: fresh callable, non-literal static args "
            "or mutable closed-over state",
)

_JIT_PATHS = frozenset({"jax.jit", "jax.experimental.jit"})
_PARTIAL_PATHS = frozenset({"functools.partial", "partial"})
# decorators whose body runs (at most) once per instance/process
_CACHING_DECOS = frozenset({
    "cached_property", "functools.cached_property", "property",
    "lru_cache", "functools.lru_cache", "cache", "functools.cache",
})
_MUTATOR_ATTRS = frozenset({
    "append", "extend", "add", "update", "pop", "popitem", "remove",
    "discard", "clear", "insert", "setdefault",
})


def _is_literal_static(node: ast.AST) -> bool:
    """True for static_argnums/static_argnames values jit can key stably
    AND whose value provably never varies between calls: int/str literals
    or tuples/lists of them."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, str))
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_literal_static(e) for e in node.elts)
    return False


def _decorator_paths(fn: ast.AST, aliases: Dict[str, str]) -> Set[str]:
    out: Set[str] = set()
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        dotted = _dotted_path(target, aliases)
        if dotted:
            out.add(dotted)
    return out


class _Scope:
    """One enclosing function: name, exemption status, mutated names."""

    def __init__(self, fn, aliases: Dict[str, str], cfg):
        self.fn = fn
        self.name = fn.name
        self.globals: Set[str] = {
            g for n in ast.walk(fn) if isinstance(n, (ast.Global,))
            for g in n.names
        }
        decos = _decorator_paths(fn, aliases)
        self.exempt = (
            fn.name == "__init__"
            or fn.name in cfg.jit_factory_allow
            or any(fn.name.startswith(p) for p in cfg.jit_factory_prefixes)
            or bool(decos & _CACHING_DECOS)
        )
        # names the function mutates in place (closure hazard targets)
        self.mutated: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.AugAssign) and isinstance(n.target, ast.Name):
                self.mutated.add(n.target.id)
            elif isinstance(n, (ast.Assign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Name):
                        self.mutated.add(t.value.id)
            elif (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _MUTATOR_ATTRS
                    and isinstance(n.func.value, ast.Name)):
                self.mutated.add(n.func.value.id)
        # local function defs (closure-hazard candidates for jit(Name))
        self.local_defs: Dict[str, ast.AST] = {
            n.name: n for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }


def _free_names(fn: ast.AST) -> Set[str]:
    """Names a function loads but never binds — its closure surface."""
    bound: Set[str] = {a.arg for a in fn.args.args}
    bound.update(a.arg for a in fn.args.kwonlyargs)
    bound.update(a.arg for a in fn.args.posonlyargs)
    if fn.args.vararg:
        bound.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        bound.add(fn.args.kwarg.arg)
    loads: Set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Name):
            if isinstance(n.ctx, ast.Store):
                bound.add(n.id)
            else:
                loads.add(n.id)
    return loads - bound


def _bad_partial_arg(call: ast.Call) -> Optional[str]:
    """A non-hashable / per-call-unstable argument inside a partial(...)
    feeding jit, or None."""
    for a in list(call.args) + [k.value for k in call.keywords]:
        if isinstance(a, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
            return "a mutable container literal"
        if isinstance(a, ast.JoinedStr):
            return "an f-string"
    return None


def check_jit_cache(sf: SourceFile, cfg) -> List[Finding]:
    out: List[Finding] = []
    aliases = _alias_map(sf.tree)

    # innermost enclosing _Scope per node, plus Assign context per call
    scopes: Dict[int, Optional[_Scope]] = {}
    assign_of: Dict[int, ast.Assign] = {}

    def walk(node, scope):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = _Scope(node, aliases, cfg)
        scopes[id(node)] = scope
        if isinstance(node, ast.Assign):
            v = node.value
            if isinstance(v, ast.Call):
                assign_of[id(v)] = node
        for child in ast.iter_child_nodes(node):
            walk(child, scope)

    walk(sf.tree, None)

    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted_path(node.func, aliases)
        if dotted not in _JIT_PATHS:
            continue
        scope = scopes.get(id(node))
        line = node.lineno

        # closure over mutated state: hazard regardless of creation site
        target = node.args[0] if node.args else None
        if (scope is not None and isinstance(target, ast.Name)
                and target.id in scope.local_defs):
            shared = _free_names(scope.local_defs[target.id]) & scope.mutated
            if shared:
                names = ", ".join(sorted(shared))
                out.append(Finding(
                    "BGT070", sf.rel, line,
                    f"jitted function {target.id!r} closes over {names} "
                    f"which {scope.name}() mutates in place — the traced "
                    "value is baked at compile time, so the mutation is "
                    "invisible to every later cached call (silent drift); "
                    "pass the state as an argument instead",
                ))
                continue

        if scope is None or scope.exempt:
            continue  # module scope / factory / memoized one-shot

        # non-literal static args: every distinct runtime value is a
        # separate executable — report the most specific hazard only
        bad_static = next(
            (k.arg for k in node.keywords
             if k.arg in ("static_argnums", "static_argnames")
             and not _is_literal_static(k.value)), None)
        if bad_static is not None:
            out.append(Finding(
                "BGT070", sf.rel, line,
                f"jit inside {scope.name}() with a non-literal "
                f"{bad_static} — the static value cannot be proven "
                "call-stable, so every distinct value recompiles; hoist "
                "the jit to a memoized factory keyed on the static value",
            ))
            continue
        bad_part = None
        if isinstance(target, ast.Call):
            tp = _dotted_path(target.func, aliases)
            if tp in _PARTIAL_PATHS:
                bad_part = _bad_partial_arg(target)
        if bad_part is not None:
            out.append(Finding(
                "BGT070", sf.rel, line,
                f"jit of a functools.partial carrying {bad_part} inside "
                f"{scope.name}() — the partial is rebuilt per call and "
                "its arguments defeat (or crash) the jit cache key; bake "
                "the value into a module-level program or a keyed factory",
            ))
            continue

        # memoized creation sites are sanctioned: cache[key] = jax.jit(...)
        # and the lazy `global _fn` singleton
        assign = assign_of.get(id(node))
        if assign is not None:
            if any(isinstance(t, ast.Subscript) for t in assign.targets):
                continue
            if any(isinstance(t, ast.Name) and t.id in scope.globals
                   for t in assign.targets):
                continue
        out.append(Finding(
            "BGT070", sf.rel, line,
            f"jit callable created inside {scope.name}() — a fresh jit "
            "misses the executable cache on every call (compile cliff "
            "mid-tick; the BGT_COMPILE_GUARD runtime twin raises "
            "RecompileError here); hoist to module scope, a "
            "make_*/build_* factory, or a keyed memo cache",
        ))
    return out


@lint_pass
def jit_cache_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    out: List[Finding] = []
    for sf in ctx.files:
        if sf.tree is None or sf.is_test:
            continue
        out.extend(check_jit_cache(sf, cfg))
    return out
