"""Rule passes — importing this package registers every rule and pass.

Each module declares its rule ids with :func:`..core.rule` at import time
and registers one or more :func:`..core.lint_pass` functions.  The id
blocks are stable API (baselines, suppression comments and the docs
catalog all key on them):

- ``BGT00x`` hygiene: unused imports, duplicate defs, syntax, bad ignores
- ``BGT01x`` hot-loop purity (intra + interprocedural + allowlist meta)
- ``BGT02x`` tick-phase timer discipline
- ``BGT03x`` metric-name and trace-kind <-> docs-catalog cross-checks
- ``BGT04x`` determinism hazards in step/model/session code
- ``BGT05x`` rule-id <-> docs-catalog cross-check
- ``BGT06x`` concurrency & transfer races in the control plane
- ``BGT07x`` recompilation & engine-drift: jit cache-key hazards,
  data-dependent shapes, dtype-promotion drift, solo/batched twin drift
"""

from . import imports  # noqa: F401
from . import purity  # noqa: F401
from . import phases  # noqa: F401
from . import metrics  # noqa: F401
from . import trace_kinds  # noqa: F401
from . import determinism  # noqa: F401
from . import docs  # noqa: F401
from . import shared_state  # noqa: F401
from . import locks  # noqa: F401
from . import lock_order  # noqa: F401
from . import transfer_race  # noqa: F401
from . import jit_cache  # noqa: F401
from . import shape_stability  # noqa: F401
from . import dtype_drift  # noqa: F401
from . import twin_drift  # noqa: F401
