"""Staging-rewrite and donation transfer races — BGT063.

The packed-upload path (docs/architecture.md "Upload staging") hands the
device an async view of host memory: ``jax.device_put(buf)`` returns
immediately and the DMA reads ``buf`` *later*.  Rewriting that buffer
before the transfer lands corrupts the in-flight upload — silently, on
device, with no host-side error — and the same hazard applies to arrays
donated via ``jax.jit(..., donate_argnums=...)``: after the donated call,
the caller's array aliases freed device memory.  SyncTest never catches
either (single-stepped runs always land before the rewrite); this rule
makes the ordering contract static, and the ``BGT_SANITIZE=1`` runtime
sanitizer (bevy_ggrs_tpu/utils/staging.py) enforces it dynamically.

Four detections, in increasing order of reach:

1. **guard files** (``config.TRANSFER_GUARD_FILES``): *any* un-barriered
   ``device_put`` — the staging funnel is exactly where every upload must
   either block or hand ownership to a rotation protocol, so an
   unbarriered site there is a finding by default and the protocol that
   makes it safe must be spelled out in a ``# bgt: ignore[BGT063]``
   reason.
2. **reused staging attrs**: a ``self.X`` that is (a) allocated from a
   pool factory (``np.empty``-family or ``.new_buffer``) and (b)
   subscript-rewritten somewhere in its class is a *reused* buffer;
   uploading it without a barrier races detection 2's rewrite sites.
3. **interprocedural**: a function that uploads its parameter
   un-barriered gives that parameter an "uploads async" effect; the
   effect propagates backwards through the package call graph (same
   resolution as BGT011), so passing a reused staging attr into a helper
   that uploads three calls deep is flagged at the call site with the
   full chain.
4. **donation**: a name bound from ``jax.jit(..., donate_argnums=N)``
   donates its N-th argument; any read of that argument after the call,
   with no rebinding in between, touches freed device memory.

A barrier is ``x.block_until_ready()`` on the bound result (or chained
directly on the call).  A ``# bgt: ignore[BGT063]: <why>`` on the
``device_put`` line sanctions the site for every caller — same seed-line
contract as BGT011 — and is tracked as load-bearing for BGT005.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ..core import Context, Finding, SourceFile, lint_pass, rule
from .purity import CallGraph, FuncKey

rule(
    "BGT063", "transfer-race",
    summary="a staging buffer or donated array can be rewritten/read "
            "before the async transfer that consumes it lands",
)


# -- per-function facts -------------------------------------------------------


@dataclasses.dataclass
class _TFunc:
    key: FuncKey
    cls: Optional[str]
    # un-barriered, un-suppressed device_put sites: (line, desc)
    uploads: List[Tuple[int, tuple]] = dataclasses.field(default_factory=list)
    # call sites with positional-arg descriptors: (line, ref, [desc, ...])
    calls: List[Tuple[int, tuple, list]] = dataclasses.field(
        default_factory=list
    )
    # donated-call reuse findings, pre-formatted: (line, message)
    donation_hits: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list
    )


def _strip_subscript(node: ast.AST) -> ast.AST:
    while isinstance(node, ast.Subscript):
        node = node.value
    return node


def _desc(node: ast.AST, params: Dict[str, int]) -> tuple:
    """What flows into an upload/call position, after peeling slices:
    ``self.X[...]`` -> ("self_attr", X); a parameter -> ("param", i)."""
    node = _strip_subscript(node)
    chain: List[str] = []
    while isinstance(node, ast.Attribute):
        chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        if node.id == "self" and chain:
            return ("self_attr", chain[-1])
        if not chain and node.id in params:
            return ("param", params[node.id])
    return ("other",)


def _is_device_put(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr == "device_put"
    return isinstance(f, ast.Name) and f.id == "device_put"


def _jit_donated_positions(call: ast.Call) -> Optional[Set[int]]:
    """donate_argnums of a ``jax.jit(...)`` call, literal only."""
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    if name != "jit":
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            out: Set[int] = set()
            for e in v.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, int)):
                    return None
                out.add(e.value)
            return out
    return None


class _TransferCollector(ast.NodeVisitor):
    """One module's upload sites, staging attrs, donation bindings and
    call-argument flow — qualnames mirror the purity collector so the
    shared CallGraph can resolve our refs."""

    def __init__(self, sf: SourceFile, cfg, used: set):
        self.sf = sf
        self.cfg = cfg
        self.used = used
        self.funcs: Dict[str, _TFunc] = {}
        # cls -> attrs allocated from a pool factory / subscript-rewritten
        self.factory_attrs: Dict[str, Set[str]] = {}
        self.written_attrs: Dict[str, Set[str]] = {}
        # donated bindings: bare name / self attr -> donated positions
        self.donated_names: Dict[str, Set[int]] = {}
        self.donated_self: Dict[str, Set[int]] = {}
        self._stack: List[str] = []
        self._cls: List[Optional[str]] = []

    def collect(self):
        # donation bindings first — a method may call a jitted self-attr
        # bound in __init__ further down the file
        for node in ast.walk(self.sf.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            pos = _jit_donated_positions(node.value)
            if pos is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.donated_names[t.id] = pos
                elif (isinstance(t, ast.Attribute)
                      and isinstance(t.value, ast.Name)
                      and t.value.id == "self"):
                    self.donated_self[t.attr] = pos
        self.visit(self.sf.tree)
        return self

    def reused_staging(self, cls: Optional[str]) -> Set[str]:
        if cls is None:
            return set()
        return (self.factory_attrs.get(cls, set())
                & self.written_attrs.get(cls, set()))

    # -- structure ----------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef):
        self._stack.append(node.name)
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()
        self._stack.pop()

    def _enter_func(self, node):
        qual = ".".join(self._stack + [node.name])
        cls = self._cls[-1] if self._cls else None
        fn = _TFunc(key=(self.sf.rel, qual), cls=cls)
        self.funcs[qual] = fn
        params = {
            a.arg: i for i, a in enumerate(
                [p for p in node.args.posonlyargs + node.args.args
                 if p.arg not in ("self", "cls")]
            )
        }
        self._scan_body(node, fn, params, cls)
        self._stack.append(node.name)
        self._cls.append(None)
        self.generic_visit(node)
        self._cls.pop()
        self._stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _enter_func

    # -- body scan ----------------------------------------------------------
    def _scan_body(self, fnode, fn: _TFunc, params: Dict[str, int],
                   cls: Optional[str]):
        uploads: List[Tuple[ast.Call, int, tuple, Optional[str]]] = []
        barriered_nodes: Set[int] = set()
        barriered_names: Set[str] = set()
        donated_calls: List[Tuple[int, str, str]] = []  # (line, var, fname)
        name_loads: List[Tuple[int, str]] = []
        name_stores: List[Tuple[int, str]] = []

        def scan(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                self._scan_stmt(
                    child, fn, params, cls, uploads, barriered_nodes,
                    barriered_names, donated_calls,
                )
                if isinstance(child, ast.Name):
                    if isinstance(child.ctx, ast.Load):
                        name_loads.append((child.lineno, child.id))
                    elif isinstance(child.ctx, ast.Store):
                        name_stores.append((child.lineno, child.id))
                scan(child)

        scan(fnode)

        # resolve barriers: a site survives only if neither the call node
        # nor its bound name ever hits block_until_ready
        for call, line, desc, bound in uploads:
            if id(call) in barriered_nodes:
                continue
            if bound is not None and bound in barriered_names:
                continue
            if "BGT063" in self.sf.suppressions.get(line, {}):
                # sanctioned upload: no finding, no effect — but the
                # suppression is load-bearing (BGT005 must not flag it)
                self.used.add((self.sf.rel, line, "BGT063"))
                continue
            fn.uploads.append((line, desc))

        # donation reuse: a read of the donated variable after the call
        # with no rebinding in between
        for call_line, var, fname in donated_calls:
            stores = sorted(l for l, n in name_stores
                            if n == var and l >= call_line)
            for load_line in sorted(l for l, n in name_loads
                                    if n == var and l > call_line):
                if any(call_line <= s <= load_line for s in stores):
                    break  # rebound before (or at) this read: safe again
                fn.donation_hits.append((
                    load_line,
                    f"donated-array reuse: {var!r} was donated to "
                    f"{fname}(...) on line {call_line} "
                    "(jax.jit donate_argnums) and is read here — after "
                    "donation the array aliases freed device memory; "
                    "rebind it from the call result or drop the donation",
                ))
                break  # one finding per donated call is enough

    def _scan_stmt(self, node, fn: _TFunc, params, cls,
                   uploads, barriered_nodes, barriered_names, donated_calls):
        # staging-attr classification (anywhere in the class, incl __init__)
        if isinstance(node, ast.Assign) and cls is not None:
            for t in node.targets:
                base = _strip_subscript(t)
                if (isinstance(t, ast.Subscript)
                        and isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"):
                    self.written_attrs.setdefault(cls, set()).add(base.attr)
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and isinstance(node.value, ast.Call)):
                    vf = node.value.func
                    fname = vf.attr if isinstance(vf, ast.Attribute) else (
                        vf.id if isinstance(vf, ast.Name) else None
                    )
                    if fname is not None and (
                        fname in self.cfg.staging_factory_names
                        or fname in self.cfg.staging_factory_attrs
                    ):
                        self.factory_attrs.setdefault(cls, set()).add(t.attr)
        if not isinstance(node, ast.Call):
            return
        f = node.func
        # barrier forms
        if isinstance(f, ast.Attribute) and f.attr == "block_until_ready":
            if isinstance(f.value, ast.Call):
                barriered_nodes.add(id(f.value))
            elif isinstance(f.value, ast.Name):
                barriered_names.add(f.value.id)
        # upload sites (bound name recovered from the enclosing assign by
        # the caller would be cleaner, but a parent-pointer walk is enough)
        if _is_device_put(node) and node.args:
            desc = _desc(node.args[0], params)
            uploads.append((node, node.lineno, desc, self._bound_name(node)))
        # donated-function invocations
        dpos = None
        fname = None
        if isinstance(f, ast.Name) and f.id in self.donated_names:
            dpos, fname = self.donated_names[f.id], f.id
        elif (isinstance(f, ast.Attribute)
              and isinstance(f.value, ast.Name) and f.value.id == "self"
              and f.attr in self.donated_self):
            dpos, fname = self.donated_self[f.attr], f"self.{f.attr}"
        if dpos is not None:
            for p in sorted(dpos):
                if p < len(node.args):
                    arg = _strip_subscript(node.args[p])
                    if isinstance(arg, ast.Name):
                        donated_calls.append((node.lineno, arg.id, fname))
        # call-argument flow for the interprocedural half (same ref shapes
        # as the purity collector, so CallGraph.resolve understands them)
        descs = [_desc(a, params) for a in node.args]
        if isinstance(f, ast.Name):
            fn.calls.append((node.lineno, ("bare", f.id), descs))
        elif isinstance(f, ast.Attribute):
            recv = f.value
            if isinstance(recv, ast.Name):
                if recv.id == "self":
                    fn.calls.append((node.lineno, ("self", f.attr), descs))
                else:
                    fn.calls.append(
                        (node.lineno, ("name_attr", recv.id, f.attr), descs)
                    )
            else:
                fn.calls.append((node.lineno, ("obj_attr", f.attr), descs))

    def _bound_name(self, call: ast.Call) -> Optional[str]:
        # `x = jax.device_put(...)` — found by locating the assign whose
        # value subtree contains the call, so conditional shapes like
        # `x = put(a, s) if s else put(a)` still bind (the tree is small,
        # so a parent scan per upload site is fine)
        for node in ast.walk(self.sf.tree):
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and any(n is call for n in ast.walk(node.value))):
                return node.targets[0].id
        return None


# -- interprocedural effect propagation --------------------------------------


class _TransferGraph:
    """Backward propagation of the "uploads param i un-barriered" effect
    over the purity call graph's resolution machinery."""

    def __init__(self, ctx: Context):
        cfg = ctx.config
        self.cfg = cfg
        self.graph = CallGraph(ctx)
        self.collectors: Dict[str, _TransferCollector] = {}
        self.tfuncs: Dict[FuncKey, _TFunc] = {}
        pkg = cfg.package_dir
        for sf in ctx.files:
            in_pkg = sf.rel == pkg or sf.rel.startswith(pkg + "/")
            if not in_pkg or sf.tree is None:
                continue
            col = _TransferCollector(sf, cfg, ctx.used_suppressions).collect()
            self.collectors[sf.rel] = col
            for fn in col.funcs.values():
                self.tfuncs[fn.key] = fn
        # effects[key] = {param index -> why}; why is
        # ("direct", line) | ("via", line, callee_key, callee_param)
        self.effects: Dict[FuncKey, Dict[int, tuple]] = {}
        for key, fn in self.tfuncs.items():
            for line, desc in fn.uploads:
                if desc[0] == "param":
                    self.effects.setdefault(key, {}) \
                        .setdefault(desc[1], ("direct", line))
        self._resolved: Dict[FuncKey, List[Tuple[int, FuncKey, list]]] = {}
        for key, fn in self.tfuncs.items():
            mod = self.graph.by_rel.get(key[0])
            caller = self.graph.funcs.get(key)
            if mod is None or caller is None:
                continue
            res = []
            for line, ref, descs in fn.calls:
                tgt = self.graph.resolve(mod, caller, ref)
                if tgt is not None and tgt.key != key:
                    res.append((line, tgt.key, descs))
            self._resolved[key] = res
        self._propagate()

    def _propagate(self):
        changed = True
        while changed:
            changed = False
            for key, fn in self.tfuncs.items():
                for line, tkey, descs in self._resolved.get(key, []):
                    teffects = self.effects.get(tkey)
                    if not teffects:
                        continue
                    for j, desc in enumerate(descs):
                        if j not in teffects or desc[0] != "param":
                            continue
                        mine = self.effects.setdefault(key, {})
                        if desc[1] not in mine:
                            mine[desc[1]] = ("via", line, tkey, j)
                            changed = True

    def chain(self, key: FuncKey, param: int) -> str:
        hops = []
        for _ in range(32):
            why = self.effects.get(key, {}).get(param)
            if why is None:
                break
            if why[0] == "direct":
                hops.append(
                    f"{key[1]}() uploads its arg un-barriered "
                    f"({key[0]}:{why[1]})"
                )
                break
            _, line, key2, param2 = why
            hops.append(f"{key[1]}() [{key[0]}:{line}]")
            key, param = key2, param2
        return " -> ".join(hops)


# -- pass ---------------------------------------------------------------------


@lint_pass
def transfer_race_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    tg = _TransferGraph(ctx)
    out: List[Finding] = []
    for rel, col in sorted(tg.collectors.items()):
        guard = cfg.is_transfer_guard_file(rel)
        for qual, fn in sorted(col.funcs.items()):
            staging = col.reused_staging(fn.cls)
            # direct un-barriered uploads
            for line, desc in fn.uploads:
                if guard:
                    out.append(Finding(
                        "BGT063", rel, line,
                        f"transfer race: {qual}() calls device_put without "
                        "a barrier in a staging funnel — the DMA reads the "
                        "host buffer later; block_until_ready the result, "
                        "or document the rotation protocol that delays the "
                        "rewrite in a suppression reason",
                    ))
                elif desc[0] == "self_attr" and desc[1] in staging:
                    out.append(Finding(
                        "BGT063", rel, line,
                        f"transfer race: {qual}() uploads the reused "
                        f"staging buffer self.{desc[1]} without a barrier "
                        "— this class subscript-rewrites that buffer, and "
                        "an un-landed upload still reads it; barrier the "
                        "result or rotate through a StagingQueue",
                    ))
            # interprocedural: reused staging attr flowing into an
            # uploading callee's effect position
            for line, tkey, descs in tg._resolved.get(fn.key, []):
                teffects = tg.effects.get(tkey, {})
                for j, desc in enumerate(descs):
                    if j not in teffects:
                        continue
                    if desc[0] == "self_attr" and desc[1] in staging:
                        out.append(Finding(
                            "BGT063", rel, line,
                            f"transfer race: {qual}() passes the reused "
                            f"staging buffer self.{desc[1]} into an "
                            "un-barriered upload path: "
                            f"{tg.chain(tkey, j)} — the buffer can be "
                            "rewritten before the DMA lands",
                        ))
            # donation reuse
            for line, msg in fn.donation_hits:
                out.append(Finding("BGT063", rel, line, msg))
    return out
