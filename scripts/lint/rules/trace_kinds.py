"""Trace-event-kind <-> docs-catalog cross-check (BGT032/BGT033).

Every trace event ``kind`` the package emits with a literal first argument
to a ``.record("...")`` call (the timeline's ``telemetry.record`` and the
flight recorder's ``fr.record`` share the signature) must appear in a
``| kind | ... |`` table of docs/observability.md ("Tracing & device
memory"), and every kind the catalog lists must still be emitted somewhere
— both directions, mirroring the metric catalog check (BGT030/BGT031).
The Chrome-trace exporter (telemetry/trace.py) routes events by kind, so
an uncataloged kind is one Perfetto consumers cannot interpret and a stale
row documents an instant that will never appear.

Unlike BGT030 (which reports against the docs file), the forward direction
here is reported AT THE EMISSION LINE — the fix is usually a docs row, but
the witness is the ``.record`` call, and a suppression belongs there when
a kind is deliberately private.  Tests are excluded (they record throwaway
kinds on purpose).
"""

from __future__ import annotations

import ast
import re
from typing import List, Tuple

from ..core import Context, Finding, lint_pass, rule

rule(
    "BGT032", "undocumented-trace-kind",
    summary="an emitted trace event kind has no docs/observability.md row",
)
rule(
    "BGT033", "stale-trace-kind-doc",
    summary="a documented trace event kind is never emitted in code",
)

_KIND_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def collect_trace_kinds(tree: ast.AST) -> List[Tuple[str, int]]:
    """``(kind, lineno)`` for every ``.record("literal", ...)`` call."""
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "record" and node.args):
            continue
        a0 = node.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str) \
                and _KIND_RE.match(a0.value):
            out.append((a0.value, node.lineno))
    return out


def docs_trace_kinds(md_text: str) -> set:
    """Backticked names in the first column of every ``| kind | ... |``
    table in the docs catalog (same parse as the metric tables, keyed on
    the ``kind`` header cell)."""
    names = set()
    in_table = False
    for line in md_text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        if cells[0] == "kind":
            in_table = True
            continue
        if in_table and not set(cells[0]) <= set("-: "):
            names.update(re.findall(r"`([a-z][a-z0-9_]+)`", cells[0]))
    return names


@lint_pass
def trace_kinds_pass(ctx: Context) -> List[Finding]:
    cfg = ctx.config
    if not cfg.project_checks:
        return []
    docs_path = ctx.root / cfg.metric_docs
    if not docs_path.exists():
        # BGT031 already reports the missing catalog file
        return []
    doc_kinds = docs_trace_kinds(docs_path.read_text())
    out: List[Finding] = []
    emitted = set()
    for sf in ctx.files:
        if sf.tree is None or sf.is_test:
            continue
        for kind, lineno in collect_trace_kinds(sf.tree):
            emitted.add(kind)
            if kind not in doc_kinds:
                out.append(Finding(
                    "BGT032", sf.rel, lineno,
                    f"trace event kind {kind!r} is emitted here but missing "
                    "from the docs catalog (add a `| kind | payload | "
                    "meaning |` row to docs/observability.md)",
                ))
    # the stale-row direction needs the FULL emission corpus — same guard
    # as BGT031: the package __init__ in the corpus is the full-run proxy
    full_corpus = (
        ctx.by_suffix(cfg.package_dir + "/__init__.py") is not None
        and not getattr(cfg, "partial_corpus", False)
    )
    if full_corpus:
        for kind in sorted(doc_kinds - emitted):
            out.append(Finding(
                "BGT033", cfg.metric_docs, 0,
                f"trace event kind {kind!r} is documented in the catalog "
                "but never emitted in code (stale row — remove or fix the "
                "name)",
            ))
    return out
