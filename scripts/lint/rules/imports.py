"""Hygiene rules: unused imports, duplicate definitions, syntax errors,
and malformed suppression comments (ported from the original
``scripts/lint_imports.py`` stdlib checker).
"""

from __future__ import annotations

import ast
from typing import List

from ..core import (
    RULES,
    Context,
    Finding,
    iter_suppression_origins,
    lint_pass,
    post_pass,
    rule,
)

rule(
    "BGT001", "unused-import",
    summary="an imported name is never referenced in the module",
)
rule(
    "BGT002", "duplicate-definition",
    summary="a def/class silently shadows an earlier same-scope binding",
)
rule(
    "BGT003", "syntax-error",
    summary="the file does not parse",
)
rule(
    "BGT004", "unknown-suppression",
    summary="a '# bgt: ignore[...]' comment names a rule id that does not exist",
)
rule(
    "BGT005", "stale-suppression",
    summary="a '# bgt: ignore[...]' comment whose rule no longer fires on "
            "any line it covers — the suppression inventory rotted",
)

# re-export / intentional-import conventions that must not be flagged
_ALLOW_UNUSED_IN = ("__init__.py",)


def _names_loaded(tree: ast.AST) -> set:
    """Every bare name and attribute-root referenced anywhere in the tree."""
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # walk to the root of a dotted access (os.path.join -> os)
            inner = node.value
            while isinstance(inner, ast.Attribute):
                inner = inner.value
            if isinstance(inner, ast.Name):
                used.add(inner.id)
    # names referenced inside string annotations / __all__ entries count
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)
    return used


def check_unused_imports(tree: ast.AST, source: str, allow_unused: bool = False):
    """``(line, message)`` pairs for imports nobody uses (pure helper —
    the old lint_imports API shape, reused by the shim)."""
    problems = []
    used = _names_loaded(tree)
    lines = source.splitlines()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue  # compiler directives, not bindings
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if "noqa" in line or allow_unused:
            continue
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name.split(".")[0]
            if bound not in used and bound != "_":
                problems.append(
                    (node.lineno, f"unused import: {alias.asname or alias.name}")
                )
    return problems


def check_duplicate_defs(tree: ast.AST):
    """``(line, message)`` pairs for same-scope def/class shadowing."""
    problems = []
    for scope in ast.walk(tree):
        if not isinstance(scope, (ast.Module, ast.ClassDef)):
            continue
        seen = {}
        for stmt in scope.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                # any decorator exempts: @property/@x.setter pairs,
                # @overload stacks, @pytest.fixture shadowing, ...
                if stmt.name in seen and not stmt.decorator_list:
                    problems.append(
                        (stmt.lineno,
                         f"duplicate definition of {stmt.name!r} "
                         f"(first at line {seen[stmt.name]})")
                    )
                seen[stmt.name] = stmt.lineno
    return problems


@lint_pass
def hygiene_pass(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for f in ctx.files:
        if f.syntax_error is not None:
            line, msg = f.syntax_error
            out.append(Finding("BGT003", f.rel, line, f"syntax error: {msg}"))
            continue
        for line, rid in f.unknown_ignores:
            out.append(Finding(
                "BGT004", f.rel, line,
                f"suppression names unknown rule id {rid!r} "
                "(typo? run --list-rules for the catalog)",
            ))
        allow_unused = f.path.name in _ALLOW_UNUSED_IN
        for line, msg in check_unused_imports(f.tree, f.source, allow_unused):
            out.append(Finding("BGT001", f.rel, line, msg))
        for line, msg in check_duplicate_defs(f.tree):
            out.append(Finding("BGT002", f.rel, line, msg))
    return out


@post_pass
def stale_suppression_pass(ctx: Context, findings: List[Finding]) -> List[Finding]:
    """BGT005 — the BGT012 stale-allowlist idea generalized to EVERY rule:
    an ignore comment is live only if its rule actually fired (and was
    suppressed) on a covered line this run, or a pass consumed it as a
    seed-line sanction (``ctx.used_suppressions`` — the BGT011/BGT063
    shape, where the sanction prevents the finding from ever existing).

    Skipped for partial corpora (``--changed``): a slice run cannot prove
    a project-level rule would not have fired."""
    cfg = ctx.config
    if getattr(cfg, "partial_corpus", False):
        return []
    hits = {(f.path, f.line, f.rule) for f in findings if f.suppressed}
    hits |= set(ctx.used_suppressions)
    out: List[Finding] = []
    for sf in ctx.files:
        if sf.syntax_error is not None:
            continue  # no rules ran: staleness is unknowable
        # ignore-syntax *examples* inside docstrings (this very framework
        # documents itself) are not suppressions — skip string-literal lines
        doc_lines: set = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                end = getattr(node, "end_lineno", None) or node.lineno
                doc_lines.update(range(node.lineno, end + 1))
        for origin, ids, _reason, targets in iter_suppression_origins(sf.source):
            if origin in doc_lines:
                continue
            for rid in ids:
                if rid not in RULES:
                    continue  # a BGT004 finding already covers the typo
                if rid == "BGT005":
                    continue  # self-referential: suppresses THIS rule here
                if any((sf.rel, t, rid) in hits for t in targets):
                    continue
                out.append(Finding(
                    "BGT005", sf.rel, origin,
                    f"stale suppression: {rid} no longer fires on any line "
                    "this comment covers — delete the ignore (or fix the "
                    "regression that was hiding behind it)",
                ))
    return out
