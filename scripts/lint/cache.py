"""Content-hash result cache — ``python -m scripts.lint --cache``.

The full run costs ~3s, and almost all of it is re-analyzing files that
have not changed since the last run.  This module keys a per-file result
cache (``.lint_cache.json`` at the repo root) on two content hashes:

* the **analyzer's own sources** (every ``scripts/lint/**/*.py``,
  config included) — any rule or config edit invalidates everything;
* each corpus file's **source hash** — an unchanged file's per-file
  findings and consumed seed-line sanctions are replayed from the cache.

Correctness is structural, not heuristic:

* Changed files are expanded to the **bidirectional** import closure
  (changed + transitive importers + transitive forward imports), so
  every interprocedural chain rule (BGT011/BGT063/BGT071) sees both the
  callers its findings land on and the callees its witness chains pass
  through.  The sliced pass families are exactly the per-file /
  chain-sound ones; their per-file findings are cacheable.
* Whole-corpus rule families (metrics/trace-kind/docs catalogs, phase
  discipline, concurrency scope, twin drift) run **fresh every time** —
  their inputs include files outside the python corpus (docs tables),
  so their findings are never cached.  So do the meta-rules (BGT005
  stale suppressions, BGT012 stale allowlist), which reason about the
  whole repo.
* A changed file *set* (add/delete/rename) or analyzer hash miss falls
  back to a plain full run and rebuilds the cache.

The agreement contract is the same as ``--changed``'s: a cached run
reports exactly what a full run would (test_lint.py proves it on a
mutated corpus).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from . import core
from .config import Config
from .core import (
    DEFAULT_PATHS,
    Context,
    Finding,
    SourceFile,
    apply_suppressions,
    iter_py_files,
    load_file,
    timed_passes,
)
from .incremental import _imports_of

CACHE_FILE = ".lint_cache.json"
CACHE_VERSION = 1

# pass families (module basenames) that are sound on a bidirectional
# slice: per-file rules plus the chain rules whose witnesses resolve
# within the closure
SLICE_PASS_MODULES = frozenset({
    "imports", "purity", "determinism", "transfer_race",
    "jit_cache", "shape_stability", "dtype_drift",
})
# families that must see the whole corpus (or non-python inputs like the
# docs catalogs) and therefore always run fresh
FULL_PASS_MODULES = frozenset({
    "phases", "metrics", "trace_kinds", "docs",
    "shared_state", "locks", "lock_order", "twin_drift",
})

# rules whose findings are a pure function of one file plus its import
# closure — the only ones a per-file cache entry may carry.  Everything
# else (whole-corpus catalogs, BGT005/BGT012 meta-rules) is recomputed
# on every cached run.
CACHED_RULES = frozenset({
    "BGT001", "BGT002", "BGT003", "BGT004",
    "BGT010", "BGT011",
    "BGT040", "BGT041", "BGT042", "BGT043", "BGT044",
    "BGT063",
    "BGT070", "BGT071", "BGT072",
})

_FINDING_KEYS = (
    "rule", "path", "line", "message", "suppressed", "suppress_reason",
)


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def ruleset_hash(root: Path) -> str:
    """One hash over every analyzer source file (config included)."""
    h = hashlib.sha256()
    lint_dir = Path(__file__).resolve().parent
    for p in sorted(lint_dir.rglob("*.py")):
        h.update(p.relative_to(lint_dir).as_posix().encode())
        h.update(b"\0")
        h.update(p.read_bytes())
        h.update(b"\0")
    return h.hexdigest()


def _graphs_from_files(
    files: List[SourceFile],
) -> Tuple[Dict[str, Set[str]], Dict[str, Set[str]]]:
    """(forward, reverse) import graphs from already-parsed sources — no
    second ast.parse over the corpus."""
    known = {sf.rel for sf in files}
    forward: Dict[str, Set[str]] = {}
    reverse: Dict[str, Set[str]] = {}
    for sf in files:
        if sf.tree is None:
            continue
        for dep in _imports_of(sf.rel, sf.tree, known):
            if dep != sf.rel:
                forward.setdefault(sf.rel, set()).add(dep)
                reverse.setdefault(dep, set()).add(sf.rel)
    return forward, reverse


def _bidirectional_closure(
    changed: Set[str],
    forward: Dict[str, Set[str]],
    reverse: Dict[str, Set[str]],
) -> Set[str]:
    seen = set(changed)
    for edges in (reverse, forward):
        work = list(seen)
        while work:
            cur = work.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    work.append(nxt)
    return seen


def _entry(findings: List[Finding], used, rel: str) -> dict:
    return {
        "findings": [
            {k: getattr(f, k) for k in _FINDING_KEYS}
            for f in findings
            if f.path == rel and f.rule in CACHED_RULES
        ],
        "used_suppressions": sorted(
            [line, rule_id]
            for (r, line, rule_id) in used
            if r == rel
        ),
    }


def _write_manifest(path: Path, ruleset: str, shas: Dict[str, str],
                    entries: Dict[str, dict]) -> None:
    payload = {
        "version": CACHE_VERSION,
        "ruleset": ruleset,
        "files": {
            rel: {"sha": shas[rel], **entries[rel]} for rel in sorted(shas)
        },
    }
    path.write_text(json.dumps(payload) + "\n")


def _full_rebuild(root: Path, cfg: Config, cache_path: Path,
                  ruleset: str, shas: Dict[str, str]):
    findings, files = core.run(None, root=root, config=cfg)
    ctx = core.LAST_CONTEXT
    used = ctx.used_suppressions if ctx is not None else set()
    entries = {rel: _entry(findings, used, rel) for rel in shas}
    _write_manifest(cache_path, ruleset, shas, entries)
    stats = {"mode": "rebuild", "analyzed": len(files), "reused": 0}
    return findings, files, stats


def cached_run(root: Path, config: Optional[Config] = None,
               cache_path: Optional[Path] = None):
    """Full-corpus results, reusing cached per-file findings for files
    whose content (and the analyzer's) is unchanged.  Returns
    ``(findings, files, stats)`` with findings identical to a plain
    ``run()`` over the default corpus."""
    import time

    from . import rules  # noqa: F401  (registration side effect)

    cfg = config or Config()
    cache_path = cache_path or root / CACHE_FILE
    ruleset = ruleset_hash(root)

    core.LAST_TIMINGS.clear()
    t0 = time.perf_counter()
    files = [load_file(p, root) for p in iter_py_files(DEFAULT_PATHS, root)]
    core.LAST_TIMINGS["load"] = time.perf_counter() - t0
    shas = {sf.rel: _sha(sf.source.encode()) for sf in files}

    manifest = None
    if cache_path.exists():
        try:
            manifest = json.loads(cache_path.read_text())
        except (OSError, ValueError):
            manifest = None
    if (manifest is None
            or manifest.get("version") != CACHE_VERSION
            or manifest.get("ruleset") != ruleset
            or set(manifest.get("files", ())) != set(shas)):
        return _full_rebuild(root, cfg, cache_path, ruleset, shas)

    cached = manifest["files"]
    changed = {rel for rel, sha in shas.items() if cached[rel]["sha"] != sha}
    forward, reverse = _graphs_from_files(files)
    slice_rels = (_bidirectional_closure(changed, forward, reverse)
                  if changed else set())

    # Run A — slice families over the bidirectional closure.  The slice
    # is a partial corpus by construction; project-level checks stay on
    # (BGT012 reads its targets from disk, so it is slice-safe).
    slice_files = [sf for sf in files if sf.rel in slice_rels]
    ctx_a = Context(
        root=root, files=slice_files,
        config=dataclasses.replace(cfg, partial_corpus=True),
    )
    passes_a = [p for p in core.PASSES
                if core._pass_label(p) in SLICE_PASS_MODULES]
    findings_a = timed_passes(ctx_a, passes_a, core.LAST_TIMINGS)

    # Run B — whole-corpus families, always fresh
    ctx_b = Context(root=root, files=files, config=cfg)
    passes_b = [p for p in core.PASSES
                if core._pass_label(p) in FULL_PASS_MODULES]
    findings_b = timed_passes(ctx_b, passes_b, core.LAST_TIMINGS)

    merged: List[Finding] = []
    used = set(ctx_a.used_suppressions) | set(ctx_b.used_suppressions)
    for rel, ent in cached.items():
        if rel in slice_rels:
            continue
        merged.extend(Finding(**fd) for fd in ent["findings"])
        used.update((rel, line, rid)
                    for line, rid in ent["used_suppressions"])
    for f in findings_a:
        if f.rule in CACHED_RULES:
            if f.path in slice_rels:
                merged.append(f)
        else:
            merged.append(f)  # BGT012-style: recomputed fully every run
    merged.extend(findings_b)
    apply_suppressions(merged, files)

    # post passes (BGT005) see the merged corpus-wide picture
    ctx_b.used_suppressions = used
    extra: List[Finding] = []
    t0 = time.perf_counter()
    for p in core.POST_PASSES:
        extra.extend(p(ctx_b, merged))
    core.LAST_TIMINGS["post"] = time.perf_counter() - t0
    apply_suppressions(extra, files)
    merged.extend(extra)
    merged.sort(key=lambda f: (f.path, f.line, f.rule))

    entries = {}
    for rel in shas:
        if rel in slice_rels:
            entries[rel] = _entry(findings_a, ctx_a.used_suppressions, rel)
        else:
            ent = cached[rel]
            entries[rel] = {
                "findings": ent["findings"],
                "used_suppressions": ent["used_suppressions"],
            }
    _write_manifest(cache_path, ruleset, shas, entries)

    stats = {
        "mode": "warm",
        "analyzed": len(slice_rels),
        "reused": len(files) - len(slice_rels),
    }
    return merged, files, stats
