"""Determinism-analyzer core: rule registry, findings, suppressions, baseline.

The framework is stdlib-only by design (the container forbids installs) and
never imports the package under analysis — everything is AST + text, so the
gate runs in milliseconds and cannot be poisoned by import-time side effects
(jax initialisation, device probes).

Concepts
--------
Rule
    A registered check with a stable ``BGT0xx`` id, a severity, and a
    one-line summary.  Rules are declared with :func:`rule` so the registry
    is the single source of truth — ``docs/static-analysis.md`` is
    cross-checked against it in both directions (rule ``BGT050``/``BGT051``),
    the same way the metric catalog lint works.

Pass
    A function that inspects the corpus and emits :class:`Finding`\\ s for
    one or more rules.  Passes are registered with :func:`lint_pass`; a pass
    sees the whole :class:`Context` so interprocedural analyses (the purity
    call graph) are first-class, not bolted on.

Suppression
    ``# bgt: ignore[BGT041]`` on the offending line (or on a comment line
    directly above it) waives that rule there.  A reason is encouraged:
    ``# bgt: ignore[BGT041]: handshake nonce, host-side only``.  Unknown
    rule ids inside an ignore comment are themselves a finding (``BGT004``)
    so typos cannot silently disable a gate.

Baseline
    ``--baseline FILE`` loads fingerprints (rule, path, message — line
    numbers excluded so pure line drift does not churn it) that are reported
    as suppressed instead of failing the gate; ``--write-baseline`` emits
    the file.  The repo itself carries **no** baseline: HEAD lints clean,
    and the knob exists for downstream forks adopting the analyzer.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

# directories never expanded when walking a path argument; the fixture
# corpus *must* trip rules, so it is only ever linted via explicit paths
# from the tests
EXCLUDE_DIR_NAMES = {"__pycache__", "lint_fixtures", ".git"}

DEFAULT_PATHS = ("bevy_ggrs_tpu", "tests", "scripts", "bench.py")

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Rule:
    """One registered check with a stable id."""

    id: str
    name: str
    severity: str
    summary: str


RULES: Dict[str, Rule] = {}
PASSES: List[Callable] = []
# post passes run AFTER the normal passes and suppression application —
# they see the (suppressed-marked) findings, so meta-rules like the
# stale-suppression check (BGT005) can reason about which suppressions
# actually did something this run
POST_PASSES: List[Callable] = []

_RULE_ID_RE = re.compile(r"^BGT0\d\d$")


def rule(id: str, name: str, severity: str = "error", summary: str = "") -> Rule:
    """Register a rule id; returns the :class:`Rule` (import-time use)."""
    if not _RULE_ID_RE.match(id):
        raise ValueError(f"rule id {id!r} must match BGT0xx")
    if id in RULES:
        raise ValueError(f"duplicate rule id {id}")
    if severity not in SEVERITIES:
        raise ValueError(f"severity {severity!r} not in {SEVERITIES}")
    r = Rule(id=id, name=name, severity=severity, summary=summary)
    RULES[id] = r
    return r


def lint_pass(fn: Callable) -> Callable:
    """Decorator: register ``fn(ctx) -> list[Finding]`` as an analysis pass."""
    PASSES.append(fn)
    return fn


def post_pass(fn: Callable) -> Callable:
    """Decorator: register ``fn(ctx, findings) -> list[Finding]`` to run
    after every normal pass and after suppressions were applied."""
    POST_PASSES.append(fn)
    return fn


@dataclasses.dataclass
class Finding:
    """One problem at one place.  ``fingerprint`` (rule, path, message)
    deliberately omits the line number so baselines survive unrelated
    edits above the finding."""

    rule: str
    path: str  # repo-root-relative posix path
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: str = ""

    @property
    def severity(self) -> str:
        return RULES[self.rule].severity

    def fingerprint(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "name": RULES[self.rule].name,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
        }


# -- suppression comments -----------------------------------------------------

_IGNORE_RE = re.compile(r"#\s*bgt:\s*ignore\[([A-Za-z0-9_,\s]+)\](?::\s*(.*))?")


def iter_suppression_origins(src: str):
    """Yield ``(origin_line, ids, reason, targets)`` per ignore comment.

    ``ids`` keeps unknown rule ids (the caller decides what to do with
    them); ``targets`` is every line the comment covers: its own physical
    line, and — when the comment is the *whole* line (standalone) — the
    rest of that comment block through the first code line below it, so a
    multi-line justification can sit above a long statement."""
    lines = src.splitlines()
    for lineno, line in enumerate(lines, start=1):
        m = _IGNORE_RE.search(line)
        if not m:
            continue
        reason = (m.group(2) or "").strip()
        ids = [t.strip() for t in m.group(1).split(",") if t.strip()]
        targets = [lineno]
        if line.strip().startswith("#"):
            # cover the comment block below plus the first code line
            nxt = lineno + 1
            while nxt <= len(lines) and lines[nxt - 1].strip().startswith("#"):
                targets.append(nxt)
                nxt += 1
            targets.append(nxt)
        yield lineno, ids, reason, targets


def parse_suppressions(src: str):
    """Map ``line -> {rule_id: reason}`` for every ``# bgt: ignore[...]``
    comment, plus ``(line, bad_id)`` pairs for unknown rule ids."""
    covers: Dict[int, Dict[str, str]] = {}
    unknown: List[Tuple[int, str]] = []
    for lineno, ids, reason, targets in iter_suppression_origins(src):
        for rid in ids:
            if rid not in RULES:
                unknown.append((lineno, rid))
                continue
            for t in targets:
                covers.setdefault(t, {})[rid] = reason
    return covers, unknown


# -- corpus -------------------------------------------------------------------


@dataclasses.dataclass
class SourceFile:
    """One parsed file of the corpus."""

    path: Path  # absolute
    rel: str  # repo-root-relative posix
    source: str
    tree: Optional[ast.AST]  # None on syntax error
    syntax_error: Optional[Tuple[int, str]]
    suppressions: Dict[int, Dict[str, str]]
    unknown_ignores: List[Tuple[int, str]]

    @property
    def is_test(self) -> bool:
        parts = Path(self.rel).parts
        return "tests" in parts and "lint_fixtures" not in parts

    @property
    def is_fixture(self) -> bool:
        return "lint_fixtures" in Path(self.rel).parts


@dataclasses.dataclass
class Context:
    """Everything a pass may look at: the parsed corpus plus repo root
    (for docs/package files outside the explicit path set) and the
    analysis configuration (overridable by fixture tests)."""

    root: Path
    files: List[SourceFile]
    config: "object" = None  # scripts.lint.config.Config, set by run()
    # (rel, line, rule_id) of suppressions a pass consumed WITHOUT leaving
    # a suppressed finding behind — seed-line sanctions like BGT011/BGT063,
    # which stop an effect from propagating.  The stale-suppression
    # meta-rule (BGT005) treats these as live.
    used_suppressions: set = dataclasses.field(default_factory=set)

    def by_suffix(self, suffix: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.rel.endswith(suffix):
                return f
        return None


def load_file(path: Path, root: Path) -> SourceFile:
    src = path.read_text()
    try:
        rel = path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        rel = path.as_posix()
    tree, err = None, None
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        err = (e.lineno or 0, e.msg or "syntax error")
    covers, unknown = parse_suppressions(src)
    return SourceFile(
        path=path, rel=rel, source=src, tree=tree, syntax_error=err,
        suppressions=covers, unknown_ignores=unknown,
    )


def iter_py_files(paths, root: Path) -> List[Path]:
    """Expand path arguments into a sorted list of .py files, skipping
    :data:`EXCLUDE_DIR_NAMES` during directory walks (an explicitly named
    file is always included — the fixture tests rely on that)."""
    files: List[Path] = []
    for p in paths:
        p = Path(p)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not EXCLUDE_DIR_NAMES & set(f.parts):
                    files.append(f)
        elif p.suffix == ".py" and p.exists():
            files.append(p)
    return files


# -- running ------------------------------------------------------------------


def apply_suppressions(findings: List[Finding], files: List[SourceFile]) -> None:
    by_rel = {f.rel: f for f in files}
    for fd in findings:
        sf = by_rel.get(fd.path)
        if sf is None:
            continue
        at = sf.suppressions.get(fd.line, {})
        if fd.rule in at:
            fd.suppressed = True
            fd.suppress_reason = at[fd.rule] or "(no reason given)"


def load_baseline(path: Path) -> set:
    data = json.loads(path.read_text())
    return {
        (e["rule"], e["path"], e["message"]) for e in data.get("findings", [])
    }


def write_baseline(path: Path, findings: List[Finding]) -> None:
    entries = [
        {"rule": f.rule, "path": f.path, "message": f.message}
        for f in findings
        if not f.suppressed
    ]
    path.write_text(json.dumps({"version": 1, "findings": entries}, indent=2))


# wall-seconds per pass family (module basename) from the most recent
# run()/cached run in this process — the `--timings` / time-budget CLI
# surface and what check.sh prints
LAST_TIMINGS: Dict[str, float] = {}
# the Context of the most recent run() — the result cache needs the
# consumed seed-line sanctions (ctx.used_suppressions) a plain
# (findings, files) return cannot carry
LAST_CONTEXT: Optional[Context] = None


def _pass_label(fn: Callable) -> str:
    return fn.__module__.rsplit(".", 1)[-1]


def timed_passes(ctx: Context, passes, timings: Dict[str, float]) -> List[Finding]:
    """Run ``passes`` over ``ctx`` accumulating wall time per pass family."""
    import time

    out: List[Finding] = []
    for p in passes:
        t0 = time.perf_counter()
        out.extend(p(ctx))
        label = _pass_label(p)
        timings[label] = timings.get(label, 0.0) + time.perf_counter() - t0
    return out


def run(paths=None, root: Optional[Path] = None, config=None) -> Tuple[List[Finding], List[SourceFile]]:
    """Run every registered pass over ``paths``; returns (findings, files)
    with line-level suppressions already applied (baseline is the CLI's
    job — library callers see everything)."""
    import time

    # rule/pass modules register themselves on import
    from . import rules  # noqa: F401  (registration side effect)
    from .config import Config

    global LAST_CONTEXT
    root = Path(root) if root is not None else _find_root()
    cfg = config or Config()
    LAST_TIMINGS.clear()
    t0 = time.perf_counter()
    files = [load_file(p, root) for p in iter_py_files(paths or DEFAULT_PATHS, root)]
    LAST_TIMINGS["load"] = time.perf_counter() - t0
    ctx = Context(root=root, files=files, config=cfg)
    LAST_CONTEXT = ctx
    findings = timed_passes(ctx, PASSES, LAST_TIMINGS)
    apply_suppressions(findings, files)
    # post passes (the stale-suppression meta-rule) see the suppressed-
    # marked findings; their own findings are suppressible too
    extra: List[Finding] = []
    t0 = time.perf_counter()
    for p in POST_PASSES:
        extra.extend(p(ctx, findings))
    LAST_TIMINGS["post"] = time.perf_counter() - t0
    apply_suppressions(extra, files)
    findings.extend(extra)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, files


def _find_root() -> Path:
    """The repo root: the directory holding ``bevy_ggrs_tpu`` — two levels
    up from this file (scripts/lint/core.py)."""
    return Path(__file__).resolve().parent.parent.parent


# -- CLI ----------------------------------------------------------------------


def _format_text(findings: List[Finding], show_suppressed: bool) -> List[str]:
    out = []
    for f in findings:
        if f.suppressed and not show_suppressed:
            continue
        flag = " [suppressed]" if f.suppressed else ""
        out.append(f"{f.path}:{f.line}: {f.rule} ({f.severity}){flag}: {f.message}")
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m scripts.lint",
        description="bevy_ggrs_tpu determinism analyzer / lint framework",
    )
    ap.add_argument("paths", nargs="*", help=f"files/dirs (default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--json", metavar="FILE", help="write a JSON report ('-' for stdout)")
    ap.add_argument("--baseline", metavar="FILE", help="fingerprints to tolerate")
    ap.add_argument("--write-baseline", metavar="FILE", help="write current findings as a baseline")
    ap.add_argument("--show-suppressed", action="store_true", help="also print suppressed findings")
    ap.add_argument("--list-rules", action="store_true", help="print the rule catalog and exit")
    ap.add_argument(
        "--changed", action="store_true",
        help="lint only files changed vs --changed-base plus their "
             "import-graph dependents (fast pre-commit path; check.sh "
             "keeps the authoritative full run)",
    )
    ap.add_argument(
        "--changed-base", metavar="REF", default="HEAD",
        help="git ref --changed diffs against (default: HEAD)",
    )
    ap.add_argument(
        "--cache", action="store_true",
        help="reuse per-file results from .lint_cache.json for files whose "
             "content hash (and the analyzer's own) is unchanged; "
             "whole-corpus rules always run fresh",
    )
    ap.add_argument(
        "--timings", action="store_true",
        help="print per-pass-family wall time after the run",
    )
    ap.add_argument(
        "--time-budget", metavar="SECONDS", type=float, default=None,
        help="warn when total lint wall time exceeds SECONDS (soft gate; "
             "see --time-budget-hard)",
    )
    ap.add_argument(
        "--time-budget-hard", action="store_true",
        help="exit nonzero when --time-budget is exceeded",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        from . import rules  # noqa: F401

        for r in sorted(RULES.values(), key=lambda r: r.id):
            print(f"{r.id}  {r.severity:7s}  {r.name}: {r.summary}")
        return 0

    if args.changed:
        from .config import Config
        from .incremental import changed_corpus

        paths, changed = changed_corpus(_find_root(), base=args.changed_base)
        if not paths:
            print("lint: --changed found no changed python files")
            return 0
        print(
            f"lint: --changed vs {args.changed_base}: {len(changed)} changed "
            f"file(s) -> {len(paths)} with dependents"
        )
        # a partial corpus cannot support the reverse (stale-entry) docs
        # checks or the stale-suppression meta-rule without false
        # positives; the full run in check.sh keeps those armed
        findings, _files = run(paths, config=Config(partial_corpus=True))
    elif args.cache and not args.paths:
        from .cache import cached_run

        findings, _files, stats = cached_run(_find_root())
        print(
            f"lint: cache {stats['mode']}: {stats['analyzed']} file(s) "
            f"analyzed, {stats['reused']} reused"
        )
    else:
        findings, _files = run(args.paths or None)

    if args.baseline:
        known = load_baseline(Path(args.baseline))
        for f in findings:
            if not f.suppressed and f.fingerprint() in known:
                f.suppressed = True
                f.suppress_reason = "baseline"
    if args.write_baseline:
        write_baseline(Path(args.write_baseline), findings)

    for line in _format_text(findings, args.show_suppressed):
        print(line)

    active = [f for f in findings if not f.suppressed]
    errors = [f for f in active if f.severity == "error"]
    suppressed = [f for f in findings if f.suppressed]

    if args.json:
        report = {
            "version": 1,
            "counts": {
                "findings": len(active),
                "errors": len(errors),
                "warnings": len(active) - len(errors),
                "suppressed": len(suppressed),
            },
            "findings": [f.as_dict() for f in findings],
            "rules": [dataclasses.asdict(r) for r in sorted(RULES.values(), key=lambda r: r.id)],
        }
        text = json.dumps(report, indent=2)
        if args.json == "-":
            print(text)
        else:
            Path(args.json).write_text(text + "\n")

    total = sum(LAST_TIMINGS.values())
    if args.timings:
        for label, secs in sorted(
                LAST_TIMINGS.items(), key=lambda kv: -kv[1]):
            print(f"lint-timing: {label:16s} {secs * 1e3:8.1f} ms")
        print(f"lint-timing: {'total':16s} {total * 1e3:8.1f} ms")

    print(
        f"lint: {len(RULES)} rules, {len(active)} findings "
        f"({len(errors)} errors, {len(suppressed)} suppressed)"
    )
    over_budget = args.time_budget is not None and total > args.time_budget
    if over_budget:
        print(
            f"lint: WARNING: wall time {total:.2f}s exceeded the "
            f"--time-budget of {args.time_budget:.2f}s"
            + ("" if args.time_budget_hard else " (soft gate; use "
               "--time-budget-hard to fail on this)")
        )
    if errors:
        return 1
    return 1 if (over_budget and args.time_budget_hard) else 0
