"""bevy_ggrs_tpu determinism analyzer — a stdlib-only static-analysis
framework with stable ``BGT0xx`` rule ids.

Usage::

    python -m scripts.lint [paths...] [--json FILE] [--baseline FILE]
    python -m scripts.lint --list-rules

The rule catalog lives in docs/static-analysis.md (cross-checked against
the registry in both directions by rule BGT050/BGT051).  Suppress a finding
with a ``bgt: ignore`` comment naming the rule id, on (or directly above)
the offending line — see the docs for the exact syntax.

``scripts/lint_imports.py`` is kept as a thin shim over this package so
pre-existing invocations and the test-suite mirrors keep working.
"""

from .core import (  # noqa: F401
    DEFAULT_PATHS,
    Finding,
    Rule,
    RULES,
    main,
    run,
)
from .config import Config  # noqa: F401
