"""Analysis configuration — the repo's allowlists and scopes in one place.

Fixture tests construct a :class:`Config` pointing at the corpus under
``tests/lint_fixtures/`` instead of the real drivers; everything else uses
the defaults below.  The allowlists themselves are meta-linted (``BGT012``:
every allowlisted function must still exist in its target file) so they
cannot rot silently when drivers are refactored.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Set, Tuple

# -- hot-loop purity ---------------------------------------------------------
# file (repo-relative posix suffix) -> functions allowed to force
# device->host reads.  These are the sanctioned flush funnels: calling one
# from hot-loop code is fine (that is their job); forcing *syntax* anywhere
# else in these files — or reaching a forcing helper through a call chain
# (BGT011) — is not.
PURITY_ALLOW: Dict[str, Set[str]] = {
    "bevy_ggrs_tpu/runner.py": {
        "checksum",               # user-facing flush point (property)
        "read_components",        # render readback (drains first)
        "_drain_inflight",        # THE blocking point the others share
        "_flush_session_checks",  # finish()/set_session flush
    },
    "bevy_ggrs_tpu/batch_runner.py": {
        "lobby_checksum",         # user-facing flush point
        "finish",                 # end-of-run flush
    },
    "bevy_ggrs_tpu/ops/batch.py": {
        "harvest_shards",         # per-device metrics probe (bench/dryrun
                                  # only — never called from the tick path)
    },
    "bevy_ggrs_tpu/session/p2p.py": {
        "check_now",              # finish()/set_session flush hook
        "_resolve_checksum",      # the one sanctioned force/peek funnel
    },
}

# attribute accesses that force (or can force) a device sync
PURITY_ATTRS = frozenset({"to_int", "block_until_ready", "device_get"})
# bare-name calls that force
PURITY_NAMES = frozenset({"checksum_to_int"})

# the package whose call graph the interprocedural pass builds
PACKAGE_DIR = "bevy_ggrs_tpu"

# -- tick-phase timer discipline ---------------------------------------------
# The catalog itself is extracted from telemetry/phases.py by AST literal
# parsing (no jax import) — see rules_phases.extract_phase_catalog.
PHASES_MODULE = "bevy_ggrs_tpu/telemetry/phases.py"
PHASE_FILES: Tuple[str, ...] = (
    "bevy_ggrs_tpu/runner.py",
    "bevy_ggrs_tpu/batch_runner.py",
)

# -- metric-name <-> docs-catalog cross-check --------------------------------
METRIC_DOCS = "docs/observability.md"

# -- rule-id <-> docs-catalog cross-check ------------------------------------
RULE_DOCS = "docs/static-analysis.md"

# -- concurrency scope (BGT060/061/062) --------------------------------------
# Threaded (or thread-adjacent) control-plane modules: the attribute/lock
# map and the blocking-under-lock / lock-order checks run only here.  The
# fleet modules are poll-driven single-threaded TODAY, but they are the
# modules a future thread would be added to — covering them now means the
# rule fires on the PR that adds the thread, not three PRs later.
CONCURRENCY_MODULES: Tuple[str, ...] = (
    "bevy_ggrs_tpu/fleet/worker.py",
    "bevy_ggrs_tpu/fleet/scheduler.py",
    "bevy_ggrs_tpu/fleet/protocol.py",
    "bevy_ggrs_tpu/fleet/observe.py",
    "bevy_ggrs_tpu/telemetry/metrics.py",
    "bevy_ggrs_tpu/telemetry/prometheus.py",
    "scripts/room_server.py",
)

# module suffix -> extra background-thread entry points ("Cls.method" /
# bare function qualnames).  Thread(target=...) functions and do_* methods
# of HTTP handler classes are detected automatically; this covers
# CROSS-module entries the per-module scan cannot see — the Prometheus
# exporter's scrape threads call straight into the metric mutators.
THREAD_ROOTS: Dict[str, Set[str]] = {
    "bevy_ggrs_tpu/telemetry/metrics.py": {
        "Counter.inc", "Gauge.set", "Gauge.set_key", "Gauge.inc",
        "Histogram.observe", "Histogram.observe_key",
        "_Metric.series", "MetricsRegistry._get_or_create",
        "MetricsRegistry.metrics", "MetricsRegistry.render_prometheus",
    },
    # the fleet exporter's scrape threads call the observer's read surface
    # (fleet/observe.py routes) while the scheduler poll thread ingests
    "bevy_ggrs_tpu/fleet/observe.py": {
        "FleetObserver.fleet_snapshot", "FleetObserver.fleet_qos",
        "FleetObserver.active_alerts", "FleetObserver.alert_history",
        "FleetObserver.window", "FleetObserver.rate",
    },
}

# calls that can block the holder of a lock (BGT061): attribute names
# (socket/array sync surface) and dotted prefixes (module calls)
BLOCKING_CALL_ATTRS = frozenset({
    "recvfrom", "recv", "accept", "connect", "sendall",
    "block_until_ready", "join",
})
BLOCKING_CALL_DOTTED: Tuple[str, ...] = (
    "time.sleep", "subprocess.", "select.select", "socket.create_connection",
)

# -- transfer-race scope (BGT063) --------------------------------------------
# files whose UNBARRIERED jax.device_put calls are findings by themselves
# (the staging funnels: every reused-buffer upload is routed through here,
# so an unbarriered upload inside one is the PR 8 hazard by construction).
# Elsewhere, an unbarriered upload only becomes a finding when a reused
# staging buffer provably flows into it through the call graph.
TRANSFER_GUARD_FILES: Tuple[str, ...] = (
    "bevy_ggrs_tpu/utils/staging.py",
)
# constructors whose result counts as a persistent host staging buffer
# when assigned to an attribute that is also subscript-written
STAGING_FACTORY_NAMES = frozenset({
    "empty", "zeros", "ones", "full", "frombuffer", "empty_like",
    "zeros_like",
})
STAGING_FACTORY_ATTRS = frozenset({"new_buffer", "new_batch_buffer"})

# -- jit cache-key hazards (BGT070) ------------------------------------------
# Functions allowed to create jit callables per call: factories that bake a
# program per (shape, config) and whose CALLERS memoize the result.  Name
# prefixes cover the repo's make_*/build_*/init_* convention; the explicit
# set covers one-off exceptions.  ``__init__``, ``@cached_property`` /
# ``@lru_cache`` bodies, keyed memo-cache assignments
# (``cache[key] = jax.jit(...)``) and lazy module singletons
# (``global _fn; _fn = jax.jit(...)``) are exempted structurally.
JIT_FACTORY_PREFIXES: Tuple[str, ...] = (
    "make_", "build_", "init_", "_make_", "_build_",
)
JIT_FACTORY_ALLOW: frozenset = frozenset()

# -- solo/batched twin map (BGT073) ------------------------------------------
# Declared duplicated hot-path implementations between the solo GgrsRunner
# and the batched/wave stack: ``("file::Qual.name", "file::Qual.name",
# expect, note)``.  expect="sync": the pair must stay identical after AST
# normalization (locals renamed, docstrings/phase labels stripped) — any
# divergence is a finding.  expect="drift": documented divergence, carried
# in LINT_twins.json as the work-list for the ROADMAP-5 unification; a
# drift pair that CONVERGES is also a finding (promote it to sync).
TWIN_MAP: Tuple[Tuple[str, str, str, str], ...] = (
    ("bevy_ggrs_tpu/runner.py::GgrsRunner.arm_compile_guard",
     "bevy_ggrs_tpu/batch_runner.py::BatchedRunner.arm_compile_guard",
     "sync", "compile-guard arming hook — kept bit-identical"),
    ("bevy_ggrs_tpu/runner.py::GgrsRunner.update",
     "bevy_ggrs_tpu/batch_runner.py::BatchedRunner.tick",
     "drift", "per-tick orchestration + phase wrapping"),
    ("bevy_ggrs_tpu/runner.py::GgrsRunner._report_mismatch",
     "bevy_ggrs_tpu/batch_runner.py::BatchedRunner._report_mismatch",
     "drift", "synctest mismatch forensics (batched adds the lobby index)"),
    ("bevy_ggrs_tpu/runner.py::GgrsRunner._flush_speculation",
     "bevy_ggrs_tpu/batch_runner.py::BatchedRunner._speculate_idle_lanes",
     "drift", "speculative-draft seam (solo drains, batched fills idle lanes)"),
    ("bevy_ggrs_tpu/runner.py::GgrsRunner._service_rollback",
     "bevy_ggrs_tpu/batch_runner.py::BatchedRunner._do_loads",
     "drift", "rollback servicing (solo per-request, batched fused wave)"),
    ("bevy_ggrs_tpu/runner.py::GgrsRunner._stage_packed_rows",
     "bevy_ggrs_tpu/batch_runner.py::BatchedRunner._do_runs",
     "drift", "packed input staging ahead of dispatch"),
    ("bevy_ggrs_tpu/runner.py::GgrsRunner.finish",
     "bevy_ggrs_tpu/batch_runner.py::BatchedRunner.finish",
     "drift", "end-of-run flush + session check drain"),
    ("bevy_ggrs_tpu/runner.py::GgrsRunner._note_dispatch_uploads",
     "bevy_ggrs_tpu/ops/batch.py::BucketedWaveExecutor._note_uploads",
     "sync", "host-upload census accounting"),
    ("bevy_ggrs_tpu/runner.py::GgrsRunner._note_compile",
     "bevy_ggrs_tpu/ops/batch.py::BucketedWaveExecutor._dispatch",
     "drift", "program-compile accounting (first-dispatch timing)"),
)
TWINS_JSON = "LINT_twins.json"

# -- determinism-hazard scopes -----------------------------------------------
# step/sim code: the only places wall-clock reads, jitted debug callbacks
# and frozen-world mutation are hazards *by construction* (session code
# legitimately reads monotonic clocks for timeouts — host-side only)
SIM_DIR_NAMES = frozenset({"models", "ops"})


def _in_sim_code(rel: str) -> bool:
    from pathlib import PurePosixPath

    return bool(SIM_DIR_NAMES & set(PurePosixPath(rel).parts))


@dataclasses.dataclass
class Config:
    """Overridable analysis configuration (defaults = this repo)."""

    purity_allow: Dict[str, Set[str]] = dataclasses.field(
        default_factory=lambda: {k: set(v) for k, v in PURITY_ALLOW.items()}
    )
    purity_attrs: frozenset = PURITY_ATTRS
    purity_names: frozenset = PURITY_NAMES
    package_dir: str = PACKAGE_DIR
    phases_module: str = PHASES_MODULE
    phase_files: Tuple[str, ...] = PHASE_FILES
    metric_docs: str = METRIC_DOCS
    rule_docs: str = RULE_DOCS
    # project-level cross-checks (metrics/docs/stale-allowlist) only make
    # sense against the real repo; fixture runs turn them off
    project_checks: bool = True
    concurrency_modules: Tuple[str, ...] = CONCURRENCY_MODULES
    thread_roots: Dict[str, Set[str]] = dataclasses.field(
        default_factory=lambda: {k: set(v) for k, v in THREAD_ROOTS.items()}
    )
    blocking_call_attrs: frozenset = BLOCKING_CALL_ATTRS
    blocking_call_dotted: Tuple[str, ...] = BLOCKING_CALL_DOTTED
    transfer_guard_files: Tuple[str, ...] = TRANSFER_GUARD_FILES
    staging_factory_names: frozenset = STAGING_FACTORY_NAMES
    staging_factory_attrs: frozenset = STAGING_FACTORY_ATTRS
    jit_factory_prefixes: Tuple[str, ...] = JIT_FACTORY_PREFIXES
    jit_factory_allow: frozenset = JIT_FACTORY_ALLOW
    twin_map: Tuple[Tuple[str, str, str, str], ...] = TWIN_MAP
    # repo-root-relative path the BGT073 duplication inventory is written
    # to on full project runs; None disables the write (fixture runs)
    twins_json: str = TWINS_JSON
    # True for `--changed` runs: the corpus is a changed-files slice, so
    # reverse (stale-entry) docs checks and the stale-suppression
    # meta-rule would false-positive on everything the slice omits
    partial_corpus: bool = False

    def in_concurrency_scope(self, rel: str) -> bool:
        return any(rel.endswith(suffix) for suffix in self.concurrency_modules)

    def thread_roots_for(self, rel: str) -> Set[str]:
        for suffix, roots in self.thread_roots.items():
            if rel.endswith(suffix):
                return roots
        return set()

    def is_transfer_guard_file(self, rel: str) -> bool:
        return any(rel.endswith(suffix) for suffix in self.transfer_guard_files)

    def purity_allowlist_for(self, rel: str):
        """The allowlist for ``rel`` if the purity rules cover it, else None."""
        for suffix, allow in self.purity_allow.items():
            if rel.endswith(suffix):
                return allow
        return None

    def in_sim_code(self, rel: str) -> bool:
        return _in_sim_code(rel)
