"""Analysis configuration — the repo's allowlists and scopes in one place.

Fixture tests construct a :class:`Config` pointing at the corpus under
``tests/lint_fixtures/`` instead of the real drivers; everything else uses
the defaults below.  The allowlists themselves are meta-linted (``BGT012``:
every allowlisted function must still exist in its target file) so they
cannot rot silently when drivers are refactored.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Set, Tuple

# -- hot-loop purity ---------------------------------------------------------
# file (repo-relative posix suffix) -> functions allowed to force
# device->host reads.  These are the sanctioned flush funnels: calling one
# from hot-loop code is fine (that is their job); forcing *syntax* anywhere
# else in these files — or reaching a forcing helper through a call chain
# (BGT011) — is not.
PURITY_ALLOW: Dict[str, Set[str]] = {
    "bevy_ggrs_tpu/runner.py": {
        "checksum",               # user-facing flush point (property)
        "read_components",        # render readback (drains first)
        "_drain_inflight",        # THE blocking point the others share
        "_flush_session_checks",  # finish()/set_session flush
    },
    "bevy_ggrs_tpu/batch_runner.py": {
        "lobby_checksum",         # user-facing flush point
        "finish",                 # end-of-run flush
    },
    "bevy_ggrs_tpu/ops/batch.py": {
        "harvest_shards",         # per-device metrics probe (bench/dryrun
                                  # only — never called from the tick path)
    },
    "bevy_ggrs_tpu/session/p2p.py": {
        "check_now",              # finish()/set_session flush hook
        "_resolve_checksum",      # the one sanctioned force/peek funnel
    },
}

# attribute accesses that force (or can force) a device sync
PURITY_ATTRS = frozenset({"to_int", "block_until_ready", "device_get"})
# bare-name calls that force
PURITY_NAMES = frozenset({"checksum_to_int"})

# the package whose call graph the interprocedural pass builds
PACKAGE_DIR = "bevy_ggrs_tpu"

# -- tick-phase timer discipline ---------------------------------------------
# The catalog itself is extracted from telemetry/phases.py by AST literal
# parsing (no jax import) — see rules_phases.extract_phase_catalog.
PHASES_MODULE = "bevy_ggrs_tpu/telemetry/phases.py"
PHASE_FILES: Tuple[str, ...] = (
    "bevy_ggrs_tpu/runner.py",
    "bevy_ggrs_tpu/batch_runner.py",
)

# -- metric-name <-> docs-catalog cross-check --------------------------------
METRIC_DOCS = "docs/observability.md"

# -- rule-id <-> docs-catalog cross-check ------------------------------------
RULE_DOCS = "docs/static-analysis.md"

# -- determinism-hazard scopes -----------------------------------------------
# step/sim code: the only places wall-clock reads, jitted debug callbacks
# and frozen-world mutation are hazards *by construction* (session code
# legitimately reads monotonic clocks for timeouts — host-side only)
SIM_DIR_NAMES = frozenset({"models", "ops"})


def _in_sim_code(rel: str) -> bool:
    from pathlib import PurePosixPath

    return bool(SIM_DIR_NAMES & set(PurePosixPath(rel).parts))


@dataclasses.dataclass
class Config:
    """Overridable analysis configuration (defaults = this repo)."""

    purity_allow: Dict[str, Set[str]] = dataclasses.field(
        default_factory=lambda: {k: set(v) for k, v in PURITY_ALLOW.items()}
    )
    purity_attrs: frozenset = PURITY_ATTRS
    purity_names: frozenset = PURITY_NAMES
    package_dir: str = PACKAGE_DIR
    phases_module: str = PHASES_MODULE
    phase_files: Tuple[str, ...] = PHASE_FILES
    metric_docs: str = METRIC_DOCS
    rule_docs: str = RULE_DOCS
    # project-level cross-checks (metrics/docs/stale-allowlist) only make
    # sense against the real repo; fixture runs turn them off
    project_checks: bool = True

    def purity_allowlist_for(self, rel: str):
        """The allowlist for ``rel`` if the purity rules cover it, else None."""
        for suffix, allow in self.purity_allow.items():
            if rel.endswith(suffix):
                return allow
        return None

    def in_sim_code(self, rel: str) -> bool:
        return _in_sim_code(rel)
