#!/usr/bin/env python
"""Fleet worker host process: registers with a scheduler and runs lobbies.

    python scripts/fleet_worker.py --scheduler 127.0.0.1:3600 \
        --worker-id w0 --capacity 4

One process = one worker = one accelerator's worth of lobby hosting.  The
worker polls forever: it accepts PLACE/DRAIN/RESUME/DROP commands, advances
hosted lobbies by a bounded frame budget per poll, ships confirmed
checkpoints back to the scheduler (the failover source), and heartbeats its
load/QoS stats.  ``BGT_PLATFORM``/``JAX_PLATFORMS`` select the backend
(bevy_ggrs_tpu/utils/platform.py).  The bench fleet stage spawns two of
these and SIGKILLs one mid-game (bench.py stage_fleet).

``--trace-out`` dumps this worker's Chrome trace periodically with an
atomic replace, so the file is valid JSON even if the process is
SIGKILLed mid-game — the bench fleet stage feeds the survivors' and the
victim's last dumps into the N-way ``merge_traces``."""

import argparse
import os
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

from bevy_ggrs_tpu import telemetry
from bevy_ggrs_tpu.fleet import FleetWorker


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scheduler", default="127.0.0.1:3600",
                    help="scheduler host:port")
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--capacity", type=int, default=4,
                    help="max concurrently hosted lobbies")
    ap.add_argument("--duration", type=float, default=None,
                    help="exit after this many seconds (default: run forever)")
    ap.add_argument("--ckpt-every", type=int, default=120,
                    help="confirmed-checkpoint shipping cadence (frames)")
    ap.add_argument("--step-budget", type=int, default=16,
                    help="max frames per lobby per poll")
    ap.add_argument("--pace-fps", type=float, default=0.0,
                    help="cap running lobbies to this realtime frame rate "
                         "(0 = unpaced)")
    ap.add_argument("--trace-out", default=None,
                    help="dump this worker's Chrome trace here periodically "
                         "(atomic replace — survives SIGKILL)")
    ap.add_argument("--trace-every", type=float, default=1.0,
                    help="trace dump cadence with --trace-out (s)")
    args = ap.parse_args()
    telemetry.enable()
    host, _, port = args.scheduler.rpartition(":")
    worker = FleetWorker(
        args.worker_id, (host or "127.0.0.1", int(port)),
        capacity=args.capacity, ckpt_every_frames=args.ckpt_every,
        step_budget=args.step_budget, pace_fps=args.pace_fps,
    )
    print(f"fleet worker {args.worker_id} on {worker.local_addr} -> "
          f"scheduler {args.scheduler}", flush=True)

    def _dump_trace() -> None:
        tmp = args.trace_out + ".tmp"
        telemetry.write_trace(tmp, process_name=f"worker:{args.worker_id}")
        os.replace(tmp, args.trace_out)

    try:
        if args.trace_out is None:
            worker.run(duration_s=args.duration)
        else:
            # manual run() loop so a reader always finds a complete trace
            # file, even after this process is SIGKILLed mid-game
            worker.register()
            t0 = time.monotonic()
            next_dump = t0 + args.trace_every
            while (args.duration is None
                   or time.monotonic() - t0 < args.duration):
                worker.poll()
                now = time.monotonic()
                if now >= next_dump:
                    next_dump = now + args.trace_every
                    _dump_trace()
                time.sleep(0.005)
    except KeyboardInterrupt:
        pass
    finally:
        if args.trace_out is not None:
            _dump_trace()
        worker.close()


if __name__ == "__main__":
    main()
