#!/usr/bin/env python
"""Fast lint gate for CI: unused imports, obvious bind errors, the
hot-loop purity rule, the phase-timer catalog, and the metric-name <->
docs-catalog cross-check (every registered metric must have a
docs/observability.md table row, and vice versa).

Prefers ``pyflakes`` when it is importable (full undefined-name analysis);
otherwise falls back to a stdlib-``ast`` checker that catches the highest
value class of drift in a growing codebase — imports nobody uses anymore —
plus duplicate function/class definitions in the same scope.  Zero
third-party dependencies by design (the container forbids installs).

The purity lint runs in BOTH modes: the pipelined tick engine
(docs/architecture.md "Tick pipeline") depends on the hot loop never forcing
a device->host sync — one stray ``block_until_ready`` / ``device_get`` /
eager ``.to_int`` in the dispatch path re-serializes host against device and
silently voids the overlap, with no test failing.  Forcing reads are allowed
only inside the allowlisted harvest/flush functions below.

    python scripts/lint_imports.py [paths...]   # default: package+tests+scripts
"""

import ast
import re
import sys
from pathlib import Path

DEFAULT_PATHS = ("bevy_ggrs_tpu", "tests", "scripts", "bench.py")

# re-export / intentional-import conventions that must not be flagged
_ALLOW_UNUSED_IN = ("__init__.py",)

# -- hot-loop purity --------------------------------------------------------
# file (path suffix) -> functions allowed to force device->host reads
PURITY_ALLOW = {
    "bevy_ggrs_tpu/runner.py": {
        "checksum",               # user-facing flush point (property)
        "read_components",        # render readback (drains first)
        "_drain_inflight",        # THE blocking point the others share
        "_flush_session_checks",  # finish()/set_session flush
    },
    "bevy_ggrs_tpu/batch_runner.py": {
        "lobby_checksum",         # user-facing flush point
        "finish",                 # end-of-run flush
    },
    "bevy_ggrs_tpu/ops/batch.py": {
        "harvest_shards",         # per-device metrics probe (bench/dryrun
                                  # only — never called from the tick path)
    },
    "bevy_ggrs_tpu/session/p2p.py": {
        "check_now",              # finish()/set_session flush hook
        "_resolve_checksum",      # the one sanctioned force/peek funnel
    },
}
# attribute accesses that force (or can force) a device sync
PURITY_ATTRS = {"to_int", "block_until_ready", "device_get"}
# bare-name calls that force
PURITY_NAMES = {"checksum_to_int"}

# -- tick-phase timer discipline --------------------------------------------
# Mirror of bevy_ggrs_tpu.telemetry.phases.PHASES (stdlib-only: importing
# the package pulls jax, which this gate must not do).  tests/test_phases.py
# asserts the two stay identical.  Every ``.phase("<literal>")`` call in the
# drivers must name a catalog phase (a typo would silently leak its time
# into unattributed_ms) and must be a ``with``-statement context expression
# (a bare call never runs __enter__/__exit__, so it times nothing).
PHASE_CATALOG = {
    "net_poll", "session_step", "stage_inputs", "wave_dispatch",
    "readback_harvest", "rollback_load", "store_save",
}
PHASE_FILES = ("bevy_ggrs_tpu/runner.py", "bevy_ggrs_tpu/batch_runner.py")

# -- metric-name <-> docs-catalog cross-check --------------------------------
# Every metric the package/scripts register with a literal name must appear
# in a `| metric | ... |` table of docs/observability.md, and every name the
# docs catalog lists must still be registered somewhere — both directions,
# so the catalog can neither rot nor silently under-document new families.
# Tests are excluded (they register throwaway names on purpose).
METRIC_CODE_PATHS = ("bevy_ggrs_tpu", "scripts", "bench.py")
METRIC_DOCS = "docs/observability.md"
# registry/shorthand entry points whose first positional arg is the name
_METRIC_REG_ATTRS = {
    "counter", "gauge", "histogram",
    "bind_counter", "bind_gauge", "bind_histogram", "gauge_set",
}
# telemetry-module shorthands; gated on the receiver being `telemetry` so
# unrelated `.count("x")` / `.observe(...)` methods never false-positive
_METRIC_TELEMETRY_ATTRS = {"count", "observe", "gauge_set"}
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]{2,}$")


def _attr_root(node: ast.Attribute):
    """Name at the root of a dotted/called access, e.g. ``registry().x`` or
    ``a.b.c`` -> ``registry`` / ``a`` (None when the root is not a name)."""
    inner = node.value
    while isinstance(inner, (ast.Attribute, ast.Call)):
        inner = inner.func if isinstance(inner, ast.Call) else inner.value
    return inner.id if isinstance(inner, ast.Name) else None


def collect_metric_names(tree: ast.AST) -> set:
    """Metric names registered with a string literal anywhere in ``tree``."""
    names = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr in _METRIC_TELEMETRY_ATTRS:
            if _attr_root(node.func) != "telemetry":
                continue
        elif attr not in _METRIC_REG_ATTRS:
            continue
        if not node.args:
            continue
        a0 = node.args[0]
        # a conditional name picks one of two literals (runner.py's
        # speculation hit/miss counter) — both are registered names
        cands = [a0.body, a0.orelse] if isinstance(a0, ast.IfExp) else [a0]
        for c in cands:
            if isinstance(c, ast.Constant) and isinstance(c.value, str) \
                    and _METRIC_NAME_RE.match(c.value):
                names.add(c.value)
    return names


def docs_metric_names(md_text: str) -> set:
    """Backticked names in the first column of every ``| metric | ... |``
    table in the docs catalog."""
    names = set()
    in_table = False
    for line in md_text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        if cells[0] == "metric":
            in_table = True
            continue
        if in_table and not set(cells[0]) <= set("-: "):
            names.update(re.findall(r"`([a-z][a-z0-9_]+)`", cells[0]))
    return names


def check_metric_docs(root: Path) -> list:
    """Both-direction diff between code-registered metric names and the
    docs/observability.md catalog; returns ``(path, message)`` problems."""
    code_names = set()
    for p in METRIC_CODE_PATHS:
        for f in _iter_files([root / p]):
            if "tests" in f.parts:
                continue
            try:
                tree = ast.parse(f.read_text(), filename=str(f))
            except SyntaxError:
                continue  # the import lint reports it
            code_names |= collect_metric_names(tree)
    docs_path = root / METRIC_DOCS
    if not docs_path.exists():
        return [(str(docs_path), "metric catalog file missing")]
    doc_names = docs_metric_names(docs_path.read_text())
    problems = []
    for name in sorted(code_names - doc_names):
        problems.append((
            str(docs_path),
            f"metric {name!r} is registered in code but missing from the "
            "docs catalog (add a `| metric | labels | meaning |` row)",
        ))
    for name in sorted(doc_names - code_names):
        problems.append((
            str(docs_path),
            f"metric {name!r} is documented in the catalog but never "
            "registered in code (stale row — remove or fix the name)",
        ))
    return problems


def _purity_allowlist(path: Path):
    """The allowlist for ``path`` if the purity lint covers it, else None."""
    posix = path.as_posix()
    for suffix, allow in PURITY_ALLOW.items():
        if posix.endswith(suffix):
            return allow
    return None


def check_purity(tree: ast.AST, allow: set) -> list:
    """Return ``(line, message)`` for forcing reads outside ``allow``-listed
    functions (attribute accesses count even un-called: holding a bound
    ``.to_int`` and calling it later forces just the same)."""
    problems = []

    def walk(node, fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = node.name
        bad = None
        if isinstance(node, ast.Attribute) and node.attr in PURITY_ATTRS:
            bad = f".{node.attr}"
        elif isinstance(node, ast.Name) and node.id in PURITY_NAMES:
            bad = node.id
        if bad is not None and fn not in allow:
            problems.append((
                node.lineno,
                f"hot-loop purity: {bad} in {fn or '<module>'}() — forcing "
                "device->host reads is allowed only in "
                f"{sorted(allow)} (see docs/architecture.md tick pipeline)",
            ))
        for child in ast.iter_child_nodes(node):
            walk(child, fn)

    walk(tree, None)
    return problems


def check_phases(tree: ast.AST) -> list:
    """Return ``(line, message)`` for ``.phase(...)`` misuse in a driver:
    a non-literal or non-catalog phase name, or a call that is not a
    ``with``-statement context expression (timing nothing)."""
    problems = []
    with_exprs = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                with_exprs.add(id(item.context_expr))
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "phase"
        ):
            continue
        if (
            len(node.args) != 1
            or node.keywords
            or not isinstance(node.args[0], ast.Constant)
            or not isinstance(node.args[0].value, str)
        ):
            problems.append((
                node.lineno,
                "phase timer: .phase() takes one string literal "
                "(dynamic names defeat the catalog lint)",
            ))
            continue
        name = node.args[0].value
        if name not in PHASE_CATALOG:
            problems.append((
                node.lineno,
                f"phase timer: {name!r} is not in the phase catalog "
                f"{sorted(PHASE_CATALOG)} — its time would silently land "
                "in unattributed_ms (telemetry/phases.py)",
            ))
        if id(node) not in with_exprs:
            problems.append((
                node.lineno,
                f"phase timer: .phase({name!r}) must be a with-statement "
                "context expression — a bare call times nothing",
            ))
    return problems


def _check_phases_file(path: Path) -> list:
    posix = path.as_posix()
    if not any(posix.endswith(s) for s in PHASE_FILES):
        return []
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError:
        return []  # the import lint reports the syntax error
    return check_phases(tree)


def _check_purity_file(path: Path) -> list:
    allow = _purity_allowlist(path)
    if allow is None:
        return []
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError:
        return []  # the import lint reports the syntax error
    return check_purity(tree, allow)


def _names_loaded(tree: ast.AST) -> set:
    """Every bare name and attribute-root referenced anywhere in the tree."""
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # walk to the root of a dotted access (os.path.join -> os)
            inner = node.value
            while isinstance(inner, ast.Attribute):
                inner = inner.value
            if isinstance(inner, ast.Name):
                used.add(inner.id)
    # names referenced inside string annotations / __all__ entries count
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)
    return used


def _check_file(path: Path) -> list:
    """Return ``(line, message)`` problems found in one file."""
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    problems = []
    used = _names_loaded(tree)
    allow_unused = path.name in _ALLOW_UNUSED_IN
    lines = src.splitlines()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue  # compiler directives, not bindings
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if "noqa" in line or allow_unused:
            continue
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name.split(".")[0]
            if bound not in used and bound != "_":
                problems.append(
                    (node.lineno, f"unused import: {alias.asname or alias.name}")
                )
    # duplicate top-level def/class bindings in the same scope shadow silently
    for scope in ast.walk(tree):
        if not isinstance(scope, (ast.Module, ast.ClassDef)):
            continue
        seen = {}
        for stmt in scope.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                # any decorator exempts: @property/@x.setter pairs,
                # @overload stacks, @pytest.fixture shadowing, ...
                if stmt.name in seen and not stmt.decorator_list:
                    problems.append(
                        (stmt.lineno,
                         f"duplicate definition of {stmt.name!r} "
                         f"(first at line {seen[stmt.name]})")
                    )
                seen[stmt.name] = stmt.lineno
    return problems


def _iter_files(paths) -> list:
    """Expand the path arguments into a sorted list of .py files."""
    files = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    return files


def main(argv) -> int:
    """Lint the given paths; return a non-zero exit code on any finding."""
    paths = argv[1:] or list(DEFAULT_PATHS)
    files = _iter_files(paths)
    # the purity + phase-timer lints run regardless of which import checker
    # is available
    pure_bad = 0
    for f in files:
        for lineno, msg in _check_purity_file(f):
            print(f"{f}:{lineno}: {msg}")
            pure_bad += 1
        for lineno, msg in _check_phases_file(f):
            print(f"{f}:{lineno}: {msg}")
            pure_bad += 1
    for where, msg in check_metric_docs(Path(__file__).resolve().parent.parent):
        print(f"{where}: {msg}")
        pure_bad += 1
    try:
        from pyflakes.api import checkPath
        from pyflakes.reporter import Reporter

        rep = Reporter(sys.stdout, sys.stderr)
        bad = sum(checkPath(str(f), rep) for f in files)
        print(f"lint (pyflakes + purity + phases + metrics): {len(files)} files, "
              f"{bad + pure_bad} problems")
        return 1 if bad + pure_bad else 0
    except ImportError:
        pass
    bad = 0
    for f in files:
        for lineno, msg in _check_file(f):
            print(f"{f}:{lineno}: {msg}")
            bad += 1
    print(f"lint (stdlib ast + purity + phases + metrics): {len(files)} files, "
          f"{bad + pure_bad} problems")
    return 1 if bad + pure_bad else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
