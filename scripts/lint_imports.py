#!/usr/bin/env python
"""Fast lint gate for CI: unused imports and obvious bind errors.

Prefers ``pyflakes`` when it is importable (full undefined-name analysis);
otherwise falls back to a stdlib-``ast`` checker that catches the highest
value class of drift in a growing codebase — imports nobody uses anymore —
plus duplicate function/class definitions in the same scope.  Zero
third-party dependencies by design (the container forbids installs).

    python scripts/lint_imports.py [paths...]   # default: package+tests+scripts
"""

import ast
import sys
from pathlib import Path

DEFAULT_PATHS = ("bevy_ggrs_tpu", "tests", "scripts", "bench.py")

# re-export / intentional-import conventions that must not be flagged
_ALLOW_UNUSED_IN = ("__init__.py",)


def _names_loaded(tree: ast.AST) -> set:
    """Every bare name and attribute-root referenced anywhere in the tree."""
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # walk to the root of a dotted access (os.path.join -> os)
            inner = node.value
            while isinstance(inner, ast.Attribute):
                inner = inner.value
            if isinstance(inner, ast.Name):
                used.add(inner.id)
    # names referenced inside string annotations / __all__ entries count
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)
    return used


def _check_file(path: Path) -> list:
    """Return ``(line, message)`` problems found in one file."""
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    problems = []
    used = _names_loaded(tree)
    allow_unused = path.name in _ALLOW_UNUSED_IN
    lines = src.splitlines()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue  # compiler directives, not bindings
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if "noqa" in line or allow_unused:
            continue
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name.split(".")[0]
            if bound not in used and bound != "_":
                problems.append(
                    (node.lineno, f"unused import: {alias.asname or alias.name}")
                )
    # duplicate top-level def/class bindings in the same scope shadow silently
    for scope in ast.walk(tree):
        if not isinstance(scope, (ast.Module, ast.ClassDef)):
            continue
        seen = {}
        for stmt in scope.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                # any decorator exempts: @property/@x.setter pairs,
                # @overload stacks, @pytest.fixture shadowing, ...
                if stmt.name in seen and not stmt.decorator_list:
                    problems.append(
                        (stmt.lineno,
                         f"duplicate definition of {stmt.name!r} "
                         f"(first at line {seen[stmt.name]})")
                    )
                seen[stmt.name] = stmt.lineno
    return problems


def _iter_files(paths) -> list:
    """Expand the path arguments into a sorted list of .py files."""
    files = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    return files


def main(argv) -> int:
    """Lint the given paths; return a non-zero exit code on any finding."""
    paths = argv[1:] or list(DEFAULT_PATHS)
    files = _iter_files(paths)
    try:
        from pyflakes.api import checkPath
        from pyflakes.reporter import Reporter

        rep = Reporter(sys.stdout, sys.stderr)
        bad = sum(checkPath(str(f), rep) for f in files)
        print(f"lint (pyflakes): {len(files)} files, {bad} problems")
        return 1 if bad else 0
    except ImportError:
        pass
    bad = 0
    for f in files:
        for lineno, msg in _check_file(f):
            print(f"{f}:{lineno}: {msg}")
            bad += 1
    print(f"lint (stdlib ast): {len(files)} files, {bad} problems")
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
