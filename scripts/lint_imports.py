#!/usr/bin/env python
"""Thin shim over the determinism analyzer (``scripts/lint/``).

The four checks that used to live here — unused imports, hot-loop purity,
the phase-timer catalog, and the metric<->docs cross-check — are now rules
``BGT001``/``BGT010``/``BGT02x``/``BGT03x`` of the framework, alongside
interprocedural purity (``BGT011``) and the determinism-hazard rules
(``BGT04x``).  See docs/static-analysis.md for the catalog.

This file keeps two things working unchanged:

- ``python scripts/lint_imports.py [paths...]`` — delegates to
  ``python -m scripts.lint`` with the same arguments and exit semantics;
- the module-level mirrors the test suite loads by file path
  (``PHASE_CATALOG``, ``check_phases``, ``check_purity``) — now backed by
  the framework, with the phase catalog extracted from
  ``telemetry/phases.py`` by AST literal parsing instead of a
  hand-maintained copy (tests/test_phases.py keeps the identity
  assertion as a regression guard).
"""

import sys
from pathlib import Path

_ROOT = Path(__file__).resolve().parent.parent
if str(_ROOT) not in sys.path:
    sys.path.insert(0, str(_ROOT))

from scripts.lint import main  # noqa: E402
from scripts.lint.config import PURITY_ALLOW, PHASES_MODULE  # noqa: E402,F401
from scripts.lint.rules.phases import (  # noqa: E402
    check_phases as _check_phases,
    extract_phase_catalog,
)
from scripts.lint.rules.purity import check_purity  # noqa: E402,F401

# extracted from the package source — no jax import, nothing to mirror
PHASE_CATALOG = extract_phase_catalog(_ROOT / PHASES_MODULE) or set()


def check_phases(tree):
    """Old-API adapter: ``(line, message)`` pairs against the extracted
    catalog (the framework's variant also reports which names were used)."""
    problems, _used = _check_phases(tree, PHASE_CATALOG)
    return [(line, msg) for line, msg, _rid in problems]


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
