#!/usr/bin/env bash
# One-command CI: native build, full test suite, bench + graft smoke.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== determinism analyzer (hard gate; JSON report next to bench artifacts) =="
# 30 rules: hygiene, intra- + interprocedural hot-loop purity, phase-timer
# discipline, metric/rule docs cross-checks, determinism hazards, the
# BGT06x concurrency/transfer-race block (shared-state locking, blocking-
# under-lock, lock ordering, staging/donation races), and the BGT07x
# recompilation/shape-stability/engine-drift block — see
# docs/static-analysis.md; scripts/lint_imports.py remains as a thin shim.
# `python -m scripts.lint --changed` is the fast pre-commit slice; --cache
# replays unchanged files from .lint_cache.json (agreement with the full
# run is tested), so this stays the authoritative gate at slice cost.
# --timings prints the per-rule-family wall-time table; the 10s budget is
# a soft gate (warns, exit 0) — add --time-budget-hard to enforce.
python -m scripts.lint --json LINT_report.json --cache --timings --time-budget 10

echo "== native build + tests =="
make -C native
make -C native test
make -C native asan

echo "== docs coverage =="
python scripts/docs_check.py

echo "== tests (CPU, 8 virtual devices) =="
python -m pytest tests/ -q

echo "== graft entry (CPU) =="
BGT_PLATFORM=cpu BGT_CPU_DEVICES=8 python - <<'EOF'
from bevy_ggrs_tpu.utils.platform import apply_platform_env
apply_platform_env()
import jax
import __graft_entry__ as g
fn, args = g.entry()
jax.block_until_ready(jax.jit(fn)(*args))
g.dryrun_multichip(8)
print("graft ok")
EOF

echo "== bench smoke (batched + sharded + netstats + uploads + speculation + trace + fleet stages, gates armed) =="
# the sharded stage runs under forced 8-virtual-device CPU and hard-fails
# unless per-device dispatches per tick are flat across lobby counts; the
# netstats stage hard-fails unless every rollback carries a blamed handle
# (sum(rollback_cause_total) == rollbacks_total), the sampler costs <1% of
# the tick, and /qos serves a usable lobby_qos_score; the speculation stage
# hard-fails unless cache-hit rollback servicing p99 is >=5x below the
# miss/resim path at a >50% hit rate with the steady census unchanged; the
# fleet stage runs a real 2-worker fleet and hard-fails on any desync after
# live migration or SIGKILL failover, a failover that did not resume from
# the last confirmed checkpoint, or an admission reject that is not
# wire-visible; the uploads stage additionally hard-fails unless the
# BGT_SANITIZE transfer sanitizer costs <2% of the packed tick armed and
# <1.5us disarmed; the uploads and speculation measured windows also run
# under the armed BGT_COMPILE_GUARD sentinel — any steady-state recompile
# raises RecompileError (runtime twin of lint BGT070/BGT071) and the
# disarmed notify() hook must stay <1.5us (one attribute check)
python bench.py --smoke

echo "== bench =="
python bench.py

echo "== bench history (soft gate: warns on >10% throughput regression) =="
# single-shot numbers on a shared host are noisy — the table and warnings
# print, the exit code stays 0; run without --warn-only to enforce
python scripts/bench_history.py --warn-only

echo "ALL CHECKS PASSED"
