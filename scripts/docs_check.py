#!/usr/bin/env python
"""Documentation coverage check — the `cargo doc` + #![warn(missing_docs)]
analog (reference CI, SURVEY §4.6): every module, public class, and public
function in bevy_ggrs_tpu must carry a docstring."""

import ast
import os

ROOT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "bevy_ggrs_tpu")


def check_file(path):
    problems = []
    tree = ast.parse(open(path).read())
    rel = os.path.relpath(path, os.path.dirname(ROOT))
    if not ast.get_docstring(tree) and os.path.basename(path) != "__init__.py":
        problems.append(f"{rel}: missing module docstring")
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            if node.name.startswith("_"):
                continue
            if not ast.get_docstring(node):
                problems.append(f"{rel}:{node.lineno}: {node.name} undocumented")
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if (
                        isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and not sub.name.startswith("_")
                        and not ast.get_docstring(sub)
                        # simple accessors named like the GGRS surface are
                        # documented at the class/PARITY level
                        and len(sub.body) > 1
                    ):
                        problems.append(
                            f"{rel}:{sub.lineno}: {node.name}.{sub.name} undocumented"
                        )
    return problems


def main():
    problems = []
    for root, _, files in os.walk(ROOT):
        for f in sorted(files):
            if f.endswith(".py"):
                problems += check_file(os.path.join(root, f))
    for p in problems:
        print(p)
    print(f"{len(problems)} documentation problems")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
