#!/usr/bin/env python
"""Multichip dry-run harness: run ``dryrun_multichip`` and write a
MULTICHIP-style JSON record with real per-device metrics.

Historically the MULTICHIP_r*.json records were written by a driver that
captured ``__graft_entry__.dryrun_multichip`` output — which printed
nothing, so every record carried ``"tail": ""`` yet still said
``"ok": true``.  An empty tail is indistinguishable from a run that did
nothing, so this harness enforces the honest rule:

    empty output  ->  {"ok": false, "skipped": true}   (NEVER ok)

``_dryrun_payload`` now prints one ``MULTICHIP_METRICS {json}`` line per
sharded program (canonical mesh + lobby-sharded wave executor, each with
per-device buffer residency); those lines are parsed out of the tail into
a structured ``metrics`` list.

Usage:
    python scripts/multichip_bench.py [--n-devices 8] [--out MULTICHIP.json]

Exit code 0 when the record is ok OR honestly skipped; 1 on rc != 0.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
METRICS_PREFIX = "MULTICHIP_METRICS "


def classify(rc: int, tail: str) -> dict:
    """The empty-tail rule, factored for unit testing: a record may be
    ``ok`` only when the run exited 0 AND produced output.  rc==0 with an
    empty tail means the run cannot prove it measured anything — mark it
    ``skipped``, never ``ok``."""
    has_output = bool(tail.strip())
    return {
        "rc": rc,
        "ok": rc == 0 and has_output,
        "skipped": rc == 0 and not has_output,
    }


def parse_metrics(tail: str) -> list:
    """Extract the structured MULTICHIP_METRICS lines from captured output
    (non-metrics lines stay in the tail verbatim)."""
    out = []
    for line in tail.splitlines():
        if line.startswith(METRICS_PREFIX):
            try:
                out.append(json.loads(line[len(METRICS_PREFIX):]))
            except json.JSONDecodeError:
                pass  # a torn line is tail noise, not a harness failure
    return out


def run(n_devices: int, timeout_s: int) -> dict:
    code = (
        "import __graft_entry__; "
        f"__graft_entry__.dryrun_multichip({n_devices})"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            cwd=ROOT, capture_output=True, text=True, timeout=timeout_s,
        )
        rc, tail = r.returncode, (r.stdout + r.stderr)[-4000:]
    except subprocess.TimeoutExpired as e:
        rc = 124
        tail = ((e.stdout or b"").decode(errors="replace")
                + (e.stderr or b"").decode(errors="replace"))[-4000:]
        tail += "\n[multichip_bench: TIMEOUT]"
    record = {"n_devices": n_devices, **classify(rc, tail), "tail": tail}
    record["metrics"] = parse_metrics(tail)
    return record


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-devices", type=int, default=8)
    ap.add_argument("--timeout", type=int, default=900)
    ap.add_argument("--out", default=None,
                    help="write the JSON record here (default: stdout only)")
    args = ap.parse_args()
    record = run(args.n_devices, args.timeout)
    text = json.dumps(record, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    return 0 if record["rc"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
