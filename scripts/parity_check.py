#!/usr/bin/env python
"""SyncTest checksum parity across backends (BASELINE.md metric 2).

Runs the same 12-frame fixed-point resim on the default device (TPU when
available) AND on the host CPU backend, and compares the 64-bit checksums
frame by frame.  Integer sim math -> must match EXACTLY.  Also reports the
float box_game checksums for observation (not guaranteed across backends).

Run from the repo root: python scripts/parity_check.py
"""

import sys

sys.path.insert(0, ".")

import numpy as np


def run_on(device, app_maker, k=12):
    import jax

    from bevy_ggrs_tpu.session.events import InputStatus
    from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

    app = app_maker()
    rng = np.random.default_rng(7)
    inputs = rng.integers(0, 16, (k, app.num_players)).astype(np.uint8)
    status = np.full((k, app.num_players), InputStatus.CONFIRMED, np.int8)
    with jax.default_device(device):
        world = jax.device_put(app.init_state(), device)
        _, _, checks = app.resim_fn(world, inputs, status, 0, -1)
        checks = np.asarray(checks)
    return [checksum_to_int(c) for c in checks]


def main():
    import jax

    from bevy_ggrs_tpu.models import box_game, fixed_point

    default_dev = jax.devices()[0]
    cpu_dev = jax.devices("cpu")[0]
    print(f"default backend: {default_dev.platform}, cpu: {cpu_dev.platform}")

    fp_default = run_on(default_dev, fixed_point.make_app)
    fp_cpu = run_on(cpu_dev, fixed_point.make_app)
    exact = fp_default == fp_cpu
    print(f"fixed-point parity ({default_dev.platform} vs cpu): "
          f"{'EXACT MATCH' if exact else 'MISMATCH'}")
    if not exact:
        for i, (a, b) in enumerate(zip(fp_default, fp_cpu)):
            if a != b:
                print(f"  frame {i+1}: {a:#018x} != {b:#018x}")

    bg_default = run_on(default_dev, box_game.make_app)
    bg_cpu = run_on(cpu_dev, box_game.make_app)
    print(f"float box_game parity (informational): "
          f"{'match' if bg_default == bg_cpu else 'differs (expected for f32 cross-backend)'}")
    return 0 if exact else 1


if __name__ == "__main__":
    raise SystemExit(main())
