#!/usr/bin/env python
"""Replay inspection tool — desync post-mortems from recorded input streams.

    python scripts/replay_tool.py info match.npz
    python scripts/replay_tool.py checksums match.npz --model box_game [--every 10]
    python scripts/replay_tool.py diff a.npz b.npz
    python scripts/replay_tool.py merge-reports peer_a.json peer_b.json

`checksums` re-simulates the recording deterministically and prints per-frame
checksums (compare outputs across builds/machines to locate a divergence
frame); `diff` compares two recordings' input streams (e.g. the two peers'
recordings of the same match — the first differing frame is where their
realities split); `merge-reports` frame-aligns two peers' desync forensics
reports (telemetry/forensics.py JSON files) and prints the first divergent
frame with both sides' rollback and phase context — run it FIRST, before
any re-simulation (docs/debugging-desyncs.md §0)."""

import argparse
import json
import sys

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

import numpy as np


def load(path):
    from bevy_ggrs_tpu.session.replay import InputRecorder

    return InputRecorder.load(path)


def cmd_info(args):
    rec = load(args.recording)
    frames = sorted(rec.frames)
    print(f"players:      {rec.num_players}")
    print(f"input shape:  {rec.input_shape} {rec.input_dtype}")
    print(f"frames:       {len(frames)}"
          + (f" ({frames[0]}..{frames[-1]})" if frames else ""))
    gaps = [f for f in range(frames[0], frames[-1]) if f not in rec.frames] if frames else []
    print(f"gaps:         {len(gaps)}" + (f" first at {gaps[0]}" if gaps else ""))


def cmd_checksums(args):
    from bevy_ggrs_tpu import GgrsRunner
    from bevy_ggrs_tpu.session.replay import ReplaySession
    from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int
    from bevy_ggrs_tpu import models

    from bevy_ggrs_tpu import telemetry

    if args.telemetry_out or args.trace_out:
        telemetry.enable()
    rec = load(args.recording)
    app = getattr(models, args.model).make_app(num_players=rec.num_players)
    # bit-faithful replay requires the recorded canonical program config
    app.canonical_depth = rec.canonical_depth
    app.canonical_branches = rec.canonical_branches
    runner = GgrsRunner(app, ReplaySession(rec))
    if args.phase_breakdown:
        fr = telemetry.flight_recorder()
        # size the ring to the whole replay so the percentiles are exact
        fr.set_maxlen(max(fr.maxlen, len(rec.frames) + 16))
        fr.clear()
    while not runner.session.finished:
        runner.tick()
        if runner.frame % args.every == 0:
            print(f"frame {runner.frame}: "
                  f"{checksum_to_int(runner._world_checksum):#018x}")
    print(f"final frame {runner.frame}: "
          f"{checksum_to_int(runner._world_checksum):#018x}")
    if args.phase_breakdown:
        print("per-phase latency over the replay (ms/tick, exact):")
        print(telemetry.format_phase_table(
            telemetry.phase_breakdown(fr.snapshot("tick"))
        ))
    if args.telemetry_out:
        n = telemetry.export_jsonl(args.telemetry_out)
        print(f"telemetry timeline: {n} events -> {args.telemetry_out}")
    if args.trace_out:
        n = telemetry.write_trace(args.trace_out)
        print(f"chrome trace: {n} events -> {args.trace_out} "
              f"(load in ui.perfetto.dev)")


def cmd_diff(args):
    a, b = load(args.a), load(args.b)
    frames = sorted(set(a.frames) | set(b.frames))
    diverged = False
    for f in frames:
        va, vb = a.frames.get(f), b.frames.get(f)
        if va is None or vb is None:
            print(f"frame {f}: only in {'b' if va is None else 'a'}")
            diverged = True
        elif not np.array_equal(va, vb):
            print(f"frame {f}: a={va.tolist()} b={vb.tolist()}")
            diverged = True
    print("recordings identical" if not diverged else "recordings DIFFER")
    return 1 if diverged else 0


def cmd_merge_reports(args):
    from bevy_ggrs_tpu.telemetry import merge_reports

    m = merge_reports(args.a, args.b)
    first = m["first_divergent_frame"]
    as_json = getattr(args, "json", False)
    if getattr(args, "trace_out", None):
        from bevy_ggrs_tpu.telemetry import merge_report_traces

        with open(args.a) as f:
            ra = json.load(f)
        with open(args.b) as f:
            rb = json.load(f)
        merged = merge_report_traces(ra, rb)
        with open(args.trace_out, "w") as f:
            json.dump(merged, f, default=repr)
        n = len(merged["traceEvents"])
        print(f"merged chrome trace: {n} events -> {args.trace_out} "
              f"(cross-peer flow arrows; load in ui.perfetto.dev)",
              file=sys.stderr if as_json else sys.stdout)
    if as_json:
        print(json.dumps(m, indent=2, default=repr))
        return 1 if first is not None else 0
    print(f"a: {m['a']}")
    print(f"b: {m['b']}")
    print(f"overlapping checksummed frames: {m['common_frames']}")
    if first is None:
        print("no divergent frame in the overlapping window — the split "
              "predates both reports' retained checksums; rerun with a "
              "denser DesyncDetection interval")
        return 0
    at = m["checksums_at_divergence"] or {}
    print(f"FIRST DIVERGENT FRAME: {first}")
    if at.get("a") is not None or at.get("b") is not None:
        print(f"  checksum a: {at.get('a'):#018x}" if at.get("a") is not None
              else "  checksum a: (absent)")
        print(f"  checksum b: {at.get('b'):#018x}" if at.get("b") is not None
              else "  checksum b: (absent)")
    if m["divergent_frames"]:
        tail = m["divergent_frames"][:8]
        print(f"  divergent frames ({len(m['divergent_frames'])}): {tail}"
              + (" ..." if len(m["divergent_frames"]) > 8 else ""))
    if m["component_diff"]:
        print(f"  diverged components: {', '.join(m['component_diff'])}")
    for side in ("a", "b"):
        rbs = [r for r in m["rollbacks"][side]
               if r.get("to_frame") is not None
               and abs(r["to_frame"] - first) <= 8]
        if rbs:
            print(f"  {side} rollbacks near frame {first}:")
            for r in rbs[-4:]:
                print(f"    -> {r.get('to_frame')} depth={r.get('depth')} "
                      f"handle={r.get('handle')} "
                      f"lateness={r.get('lateness')} "
                      f"kind={r.get('cause_kind')}")
        ctx = m["tick_context"][side]
        if ctx:
            print(f"  {side} tick context ({len(ctx)} entries):")
            for e in ctx[-4:]:
                print(f"    frame={e.get('frame')} "
                      f"wall_ms={e.get('wall_ms')} "
                      f"rollbacks={e.get('rollbacks')} "
                      f"phases={e.get('phases')}")
    return 1


def main():
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("info")
    p.add_argument("recording")
    p = sub.add_parser("checksums")
    p.add_argument("recording")
    p.add_argument("--model", default="box_game")
    p.add_argument("--every", type=int, default=10)
    p.add_argument("--telemetry-out", default=None, metavar="PATH",
                   help="enable telemetry and write the replay's timeline "
                        "(spans, rollbacks, dispatches) as JSONL")
    p.add_argument("--phase-breakdown", action="store_true",
                   help="print per-phase p50/p95/p99 latency over the "
                        "replay (exact values from the flight recorder; "
                        "needs no telemetry)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="enable telemetry and write the replay as Chrome-"
                        "trace JSON (load in ui.perfetto.dev)")
    p = sub.add_parser("diff")
    p.add_argument("a")
    p.add_argument("b")
    p = sub.add_parser(
        "merge-reports",
        help="frame-align two desync forensics reports; exit 1 on divergence",
    )
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("--json", action="store_true",
                   help="emit the merge result (first_divergent_frame, "
                        "component_diff, rollbacks, tick context) as JSON "
                        "on stdout instead of the text summary; exit codes "
                        "unchanged (1 on divergence)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write both reports' embedded trace slices as ONE "
                        "clock-aligned Chrome trace with cross-peer flow "
                        "arrows from the blamed peer's input send to the "
                        "victim's rollback (load in ui.perfetto.dev)")
    args = ap.parse_args()
    rc = {
        "info": cmd_info,
        "checksums": cmd_checksums,
        "diff": cmd_diff,
        "merge-reports": cmd_merge_reports,
    }[args.cmd](args)
    raise SystemExit(rc or 0)


if __name__ == "__main__":
    main()
