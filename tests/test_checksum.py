"""Checksum tests: determinism, sensitivity, slot-permutation invariance,
cross-type non-commutativity, custom hash fns, presence participation —
mirroring the properties of component_checksum.rs / resource_checksum.rs /
entity_checksum.rs in the reference."""

import dataclasses

import jax
import jax.numpy as jnp

from bevy_ggrs_tpu.snapshot import (
    Registry,
    checksum_to_int,
    spawn,
    despawn,
    insert_resource,
    remove_resource,
    world_checksum,
)


def make_reg():
    reg = Registry(16)
    reg.register_component("a", (2,), jnp.float32, checksum=True)
    reg.register_component("b", (2,), jnp.float32, checksum=True)
    reg.register_resource("r", jnp.float32(0.0), checksum=True)
    return reg


def cs(reg, w) -> int:
    return checksum_to_int(world_checksum(reg, w))


def test_deterministic():
    reg = make_reg()
    w = reg.init_state()
    w, _ = spawn(reg, w, {"a": jnp.array([1.0, 2.0])})
    assert cs(reg, w) == cs(reg, w)


def test_value_sensitivity():
    reg = make_reg()
    w = reg.init_state()
    w1, s = spawn(reg, w, {"a": jnp.array([1.0, 2.0])})
    w2 = dataclasses.replace(
        w1, comps={**w1.comps, "a": w1.comps["a"].at[s, 0].set(1.0000001)}
    )
    assert cs(reg, w1) != cs(reg, w2)


def test_cross_type_non_commutative():
    # same values in component a vs component b must differ
    reg = make_reg()
    w = reg.init_state()
    wa, _ = spawn(reg, w, {"a": jnp.array([3.0, 4.0])})
    wb, _ = spawn(reg, w, {"b": jnp.array([3.0, 4.0])})
    assert cs(reg, wa) != cs(reg, wb)


def test_slot_permutation_invariant():
    # two entities spawned in either slot order but with the same ids+values
    # hash identically (XOR fold keyed by rollback_id, not slot)
    reg = make_reg()
    w0 = reg.init_state()
    w0, s0 = spawn(reg, w0, {"a": jnp.array([1.0, 1.0])})
    w0, s1 = spawn(reg, w0, {"a": jnp.array([2.0, 2.0])})
    # manually construct the slot-swapped layout with identical identities
    w1 = dataclasses.replace(
        w0,
        comps={**w0.comps, "a": w0.comps["a"].at[jnp.array([0, 1])].set(
            w0.comps["a"][jnp.array([1, 0])]
        )},
        rollback_id=w0.rollback_id.at[jnp.array([0, 1])].set(
            w0.rollback_id[jnp.array([1, 0])]
        ),
    )
    assert cs(reg, w0) == cs(reg, w1)


def test_entity_part_catches_spawn_divergence():
    # no checksummed component differs, but entity counts do
    reg = Registry(8)
    reg.register_component("x", (), jnp.float32, checksum=False)
    w = reg.init_state()
    w1, _ = spawn(reg, w, {})
    assert cs(reg, w) != cs(reg, w1)


def test_despawn_marker_changes_checksum():
    reg = make_reg()
    w = reg.init_state()
    w, s = spawn(reg, w, {"a": jnp.array([1.0, 2.0])})
    w2 = despawn(reg, w, s, frame=1)
    assert cs(reg, w) != cs(reg, w2)  # active count changed


def test_resource_presence_participates():
    reg = Registry(4)
    reg.register_resource("score", jnp.int32(5), checksum=True)
    w = reg.init_state()
    w2 = remove_resource(reg, w, "score")
    assert cs(reg, w) != cs(reg, w2)
    w3 = insert_resource(reg, w2, "score", 5)
    assert cs(reg, w) == cs(reg, w3)


def test_custom_hash_fn():
    # quantizing hash: tiny (<1e-3) wobble hashes equal, large change differs
    reg = Registry(4)
    reg.register_component(
        "t",
        (2,),
        jnp.float32,
        checksum=True,
        hash_fn=lambda col: (col * 1000.0).astype(jnp.int32).astype(jnp.uint32),
    )
    w = reg.init_state()
    w, s = spawn(reg, w, {"t": jnp.array([1.0, 2.0])})
    w_wobble = dataclasses.replace(
        w, comps={"t": w.comps["t"].at[s, 0].set(1.0000002)}
    )
    w_far = dataclasses.replace(w, comps={"t": w.comps["t"].at[s, 0].set(1.5)})
    assert cs(reg, w) == cs(reg, w_wobble)
    assert cs(reg, w) != cs(reg, w_far)


def test_checksum_jittable_and_stable_under_jit():
    reg = make_reg()
    w = reg.init_state()
    w, _ = spawn(reg, w, {"a": jnp.array([1.0, 2.0]), "b": jnp.array([0.5, 0.5])})
    eager = checksum_to_int(world_checksum(reg, w))
    jitted = checksum_to_int(jax.jit(lambda w: world_checksum(reg, w))(w))
    assert eager == jitted


def test_checksum_avalanche_on_random_bit_flips():
    # property: flipping ANY single bit of present, checksummed state changes
    # the checksum (sum-fold after per-entity avalanche mixing)
    import numpy as np

    reg = make_reg()
    w = reg.init_state()
    w, _ = spawn(reg, w, {"a": jnp.array([1.5, -2.25]), "b": jnp.array([0.0, 9.0])})
    w, _ = spawn(reg, w, {"a": jnp.array([3.0, 4.0])})
    base = cs(reg, w)
    rng = np.random.default_rng(0)
    for _ in range(40):
        name = ("a", "b")[int(rng.integers(0, 2))]
        ent = int(rng.integers(0, 2))
        if name == "b" and ent == 1:
            continue  # entity 1 has no component b: flip would be invisible
        lane = int(rng.integers(0, 2))
        bit = np.uint32(1) << np.uint32(rng.integers(0, 32))
        col = np.asarray(w.comps[name]).copy()
        raw = col.view(np.uint32)
        raw[ent, lane] ^= bit
        w2 = dataclasses.replace(w, comps={**w.comps, name: jnp.asarray(col)})
        assert cs(reg, w2) != base, f"bit flip invisible: {name}[{ent},{lane}]"
