"""Smoke tests for the example CLIs (subprocess, CPU-pinned) — the analog of
the reference keeping its examples compiling in CI (SURVEY §4.6)."""

import os
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(args, timeout=240):
    env = dict(os.environ, BGT_PLATFORM="cpu")
    return subprocess.run(
        [sys.executable] + args, cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout,
    )


def test_box_game_synctest_example():
    r = run_example(["examples/box_game_synctest.py", "--frames", "60",
                     "--check-distance", "3"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "no mismatches" in r.stdout


def test_particles_example_synctest():
    r = run_example(["examples/particles_stress.py", "--rate", "10",
                     "--ttl", "20", "--synctest", "--check-distance", "2",
                     "--frames", "40"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "live particles" in r.stdout


def test_box_game_p2p_pair_example():
    import socket as so

    socks = [so.socket(so.AF_INET, so.SOCK_DGRAM) for _ in range(2)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    env = dict(os.environ, BGT_PLATFORM="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, "examples/box_game_p2p.py",
             "--local-port", str(ports[i]),
             "--players"] +
            (["local", f"127.0.0.1:{ports[1]}"] if i == 0
             else [f"127.0.0.1:{ports[0]}", "local"]) +
            ["--frames", "120"],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
            assert p.returncode == 0, out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert all("done at frame" in o for o in outs), outs[0][-500:]


def test_pong_example_synctest():
    r = run_example(["examples/pong_p2p.py", "--synctest", "--frames", "60",
                     "--check-distance", "2"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "score" in r.stdout


def test_crowd_multichip_example():
    env = dict(os.environ, BGT_PLATFORM="cpu", BGT_CPU_DEVICES="8")
    r = subprocess.run(
        [sys.executable, "examples/crowd_multichip.py",
         "--per-team", "256", "--frames", "16"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "speculative fan-out" in r.stdout


def test_spectator_cli_follows_host_pair():
    import socket as so

    socks = [so.socket(so.AF_INET, so.SOCK_DGRAM) for _ in range(3)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    env = dict(os.environ, BGT_PLATFORM="cpu")
    host = subprocess.Popen(
        [sys.executable, "examples/box_game_p2p.py",
         "--local-port", str(ports[0]),
         "--players", "local", f"127.0.0.1:{ports[1]}",
         "--spectators", f"127.0.0.1:{ports[2]}",
         "--frames", "120"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    peer = subprocess.Popen(
        [sys.executable, "examples/box_game_p2p.py",
         "--local-port", str(ports[1]),
         "--players", f"127.0.0.1:{ports[0]}", "local",
         "--frames", "120"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    spec = subprocess.Popen(
        [sys.executable, "examples/box_game_spectator.py",
         "--local-port", str(ports[2]),
         "--host", f"127.0.0.1:{ports[0]}",
         "--frames", "60"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        # generous timeouts: three interpreters jit-compiling concurrently
        # under full-suite CPU contention are slow to reach real-time pacing
        s_out, _ = spec.communicate(timeout=480)
        h_out, _ = host.communicate(timeout=120)
        p_out, _ = peer.communicate(timeout=120)
    finally:
        for p in (host, peer, spec):
            if p.poll() is None:
                p.kill()
    assert spec.returncode == 0, s_out[-2000:]
    assert "frame" in s_out
    assert host.returncode == 0, h_out[-2000:]


def test_box_game_room_example_pair():
    """Matchmaking flow end-to-end: room server process + two player
    processes that find each other by room name and finish with the SAME
    checksum (printed on the final line)."""
    import socket as so
    import re

    s = so.socket(so.AF_INET, so.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ, BGT_PLATFORM="cpu")
    server = subprocess.Popen(
        [sys.executable, "scripts/room_server.py", "--port", str(port),
         "--host", "127.0.0.1"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    procs = []
    outs = []
    try:
        for name in ("alice", "bob"):
            procs.append(subprocess.Popen(
                [sys.executable, "examples/box_game_room.py",
                 "--server", f"127.0.0.1:{port}", "--room", "smoke",
                 "--frames", "90", "--peer-id", name],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            ))
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
            assert p.returncode == 0, out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.kill()
    sums = [re.search(r"checksum (0x[0-9a-f]+)", o) for o in outs]
    assert all(sums), outs[0][-500:]
    assert sums[0].group(1) == sums[1].group(1)
