"""Full-mesh 3-peer P2P: every peer owns one handle and holds two remote
endpoints; confirmed frame is the min over both input streams and all three
simulations stay checksum-identical."""

import numpy as np

from bevy_ggrs_tpu import GgrsRunner, PlayerType, SessionBuilder, SessionState
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


def test_three_peer_full_mesh():
    net = ChannelNetwork(latency_hops=1, seed=3)
    names = ["p0", "p1", "p2"]
    socks = [net.endpoint(n) for n in names]
    keys = [box_game.keys_to_input(right=True), box_game.keys_to_input(up=True),
            box_game.keys_to_input(down=True)]
    runners = []
    for i in range(3):
        app = box_game.make_app(num_players=3)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .with_disconnect_timeout(60.0)
            .with_disconnect_notify_delay(30.0)
            .add_player(PlayerType.LOCAL, i)
        )
        for j in range(3):
            if j != i:
                b.add_player(PlayerType.REMOTE, j, names[j])
        session = b.start_p2p_session(socks[i])
        runners.append(
            GgrsRunner(app, session,
                       read_inputs=lambda hs, i=i: {h: keys[i] for h in hs})
        )

    import time

    for _ in range(500):
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    assert all(r.session.current_state() == SessionState.RUNNING for r in runners)

    for _ in range(80):
        net.deliver()
        for r in runners:
            r.update(DT)
    assert all(r.frame >= 70 for r in runners)

    # every player's motion visible on every peer
    for r in runners:
        pos = np.asarray(r.world.comps["pos"])
        assert pos[0, 0] > 1.9  # p0 held right
        assert r.session.confirmed_frame() > 50

    # 3-way checksum agreement at a frame all three still hold + confirmed
    f = None
    for _ in range(40):
        conf = min(r.session.confirmed_frame() for r in runners)
        shared = set(runners[0].ring.frames())
        for r in runners[1:]:
            shared &= set(r.ring.frames())
        shared = [fr for fr in shared if fr <= conf]
        if shared:
            f = max(shared)
            break
        net.deliver()
        min(runners, key=lambda r: r.frame).update(DT)
    assert f is not None
    sums = [checksum_to_int(r.ring.peek(f)[1]) for r in runners]
    assert sums[0] == sums[1] == sums[2], f"3-way desync at {f}: {sums}"
