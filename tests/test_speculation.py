"""Speculative fan-out: vmap over M predicted remote-input branches.

The capability the reference lacks (SURVEY §2.4 "Speculation"): instead of
predicting one input stream (PredictRepeatLast) and paying a rollback resim on
mispredict, evaluate M candidate futures in one ``jit(vmap(lax.scan(step)))``
call and select the branch matching the inputs that actually arrive."""

import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu import select_branch, slice_frame
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.events import InputStatus


def _status(k, p):
    return np.full((k, p), InputStatus.CONFIRMED, np.int8)


def test_selected_branch_matches_direct_resim():
    app = box_game.make_app(num_players=2)
    world = app.init_state()
    k, m = 4, 5
    # branch b: remote player holds input byte b; local player holds RIGHT
    candidates = [
        box_game.keys_to_input(),
        box_game.keys_to_input(left=True),
        box_game.keys_to_input(right=True),
        box_game.keys_to_input(up=True),
        box_game.keys_to_input(down=True),
    ]
    branches = np.zeros((m, k, 2), np.uint8)
    branches[:, :, 0] = box_game.keys_to_input(right=True)
    for b in range(m):
        branches[b, :, 1] = candidates[b]
    statuses = np.broadcast_to(_status(k, 2), (m, k, 2))

    finals, stacked, checks = app.speculate_fn(
        world, branches, statuses, 0, -1
    )
    # the "real" remote inputs turn out to be branch 3
    direct_final, _, direct_checks = app.resim_fn(
        world, branches[3], statuses[3], 0, -1
    )
    sel = select_branch(finals, 3)
    assert jnp.allclose(sel.comps["pos"], direct_final.comps["pos"])
    assert np.array_equal(np.asarray(checks[3]), np.asarray(direct_checks))
    # distinct branches genuinely diverge
    assert not np.array_equal(np.asarray(checks[0]), np.asarray(checks[3]))


def test_stacked_states_are_per_frame_saves():
    app = box_game.make_app(num_players=2)
    world = app.init_state()
    k = 3
    inputs = np.full((k, 2), box_game.keys_to_input(up=True), np.uint8)
    final, stacked, checks = app.resim_fn(world, inputs, _status(k, 2), 0, -1)
    # frame-by-frame singles must reproduce the stacked scan outputs
    w = world
    for i in range(k):
        w, cs = app.advance_fn(w, inputs[i], _status(1, 2)[0], i + 1, -1)
        assert np.array_equal(np.asarray(cs), np.asarray(checks[i]))
        si = slice_frame(stacked, i)
        assert jnp.allclose(w.comps["pos"], si.comps["pos"])
