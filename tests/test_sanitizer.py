"""BGT_SANITIZE transfer-race sanitizer: the seeded staging-reuse race is
caught with the sanitizer armed and (silently) missed without it, the
legitimate protocols (sync commit, StagingQueue rotation, recycle
rebinding) stay quiet, and violations are counted per rule.

The race seed mirrors the exact hazard the module docstring describes:
``StagingQueue.commit`` starts an async upload and does NOT block — a
rewrite of the same backing buffer before the matching ``acquire()`` is
the corruption BGT063 exists for.  The sanitizer's ledger is stamp-based
(commit stamps, acquire clears), so the test is deterministic even on a
CPU backend where the transfer itself lands instantly.
"""

import numpy as np
import pytest

from bevy_ggrs_tpu import telemetry
from bevy_ggrs_tpu.ops.packing import pack_prefix
from bevy_ggrs_tpu.utils import staging
from bevy_ggrs_tpu.utils.staging import (
    StagingQueue,
    TransferRaceError,
    TransferSanitizer,
)


@pytest.fixture(autouse=True)
def _sanitizer_off_after():
    yield
    staging.set_sanitize(False)
    telemetry.disable()
    telemetry.reset()


def _mk():
    return np.zeros((4, 32), dtype=np.int8)


def test_seeded_staging_reuse_race_caught_only_when_armed():
    # armed: rewriting the committed buffer before its acquire() raises
    staging.set_sanitize(True)
    q = StagingQueue(_mk, depth=2)
    buf = q.acquire()
    pack_prefix(buf, 0, 3)
    q.commit(buf[:3])
    with pytest.raises(TransferRaceError, match="in flight"):
        pack_prefix(buf, 1, 3)

    # disarmed (the default): the same seeded race passes silently
    staging.set_sanitize(False)
    q2 = StagingQueue(_mk, depth=2)
    b2 = q2.acquire()
    pack_prefix(b2, 0, 3)
    q2.commit(b2[:3])
    pack_prefix(b2, 1, 3)  # no raise: this is the silent corruption


def test_rotation_protocol_never_trips_the_sanitizer():
    staging.set_sanitize(True)
    q = StagingQueue(_mk, depth=2)
    for tick in range(8):
        buf = q.acquire()
        pack_prefix(buf, tick, 2)
        q.commit(buf[:3])


def test_sync_commit_allows_immediate_rewrite():
    staging.set_sanitize(True)
    buf = _mk()
    x = staging.commit(buf)
    assert np.array_equal(np.asarray(x), buf)
    pack_prefix(buf, 5, 1)  # commit() landed the transfer: no raise


def test_donation_guard_and_rebind():
    san = staging.set_sanitize(True)
    a, b = _mk(), _mk()
    san.guard_donated(a, "test")  # never donated: fine
    san.donate(a, "wave 0")
    with pytest.raises(TransferRaceError, match="donated"):
        san.guard_donated(a, "test")
    san.undonate(a)  # slot rebound from the call result
    san.guard_donated(a, "test")
    san.guard_donated(b, "test")


def test_donated_table_is_bounded():
    san = staging.set_sanitize(True)
    arrs = [np.zeros(1, np.int8) for _ in range(TransferSanitizer._DONATED_CAP + 8)]
    for i, a in enumerate(arrs):
        san.donate(a, f"wave {i}")
    assert len(san._donated) == TransferSanitizer._DONATED_CAP
    san.guard_donated(arrs[0], "test")  # oldest entries aged out
    with pytest.raises(TransferRaceError):
        san.guard_donated(arrs[-1], "test")


def test_violations_counted_per_rule():
    telemetry.enable()
    san = staging.set_sanitize(True)
    buf = _mk()
    san.begin(buf, "test upload")
    with pytest.raises(TransferRaceError):
        san.guard_write(buf, "test rewrite")
    san.donate(buf)
    with pytest.raises(TransferRaceError):
        san.guard_donated(buf, "test redispatch")
    assert san.violations == 2
    c = telemetry.registry().counter("sanitizer_violations_total", "")
    assert c.value(rule="staging_reuse") == 1
    assert c.value(rule="donated_reuse") == 1


def test_env_var_arms_the_default_sanitizer(monkeypatch):
    monkeypatch.setenv("BGT_SANITIZE", "1")
    assert TransferSanitizer().enabled
    monkeypatch.delenv("BGT_SANITIZE")
    assert not TransferSanitizer().enabled


def test_disabled_hooks_are_noops():
    san = TransferSanitizer(enabled=False)
    buf = _mk()
    san.begin(buf)
    san.guard_write(buf)
    san.donate(buf)
    san.guard_donated(buf)
    san.undonate(buf)
    assert san.violations == 0 and san._inflight == {} and san._donated == {}


def test_executor_recycle_donation_guard():
    """The batched executor's recycle path must (a) run clean under the
    sanitizer — every donated handle is rebound from the dispatch result —
    and (b) raise if a stale donated handle is re-dispatched."""
    from bevy_ggrs_tpu.models import stress
    from bevy_ggrs_tpu.ops.batch import BucketedWaveExecutor, stack_worlds

    staging.set_sanitize(True)
    M, K = 2, 4
    app = stress.make_app(32, capacity=32)
    ex = BucketedWaveExecutor(app, K, recycle_outputs=True)
    worlds = stack_worlds([app.init_state() for _ in range(M)])
    inputs = np.zeros((M, K, 2), np.uint8)
    status = np.zeros((M, K, 2), np.int8)
    starts = np.zeros((M,), np.int32)

    for _ in range(3):  # steady recycle: guard_donated then rebind, clean
        _b, finals, _stacked, _c = ex.run_wave(
            worlds, inputs, status, starts, [K] * M)
        worlds = finals

    key = ("exact_recycle", K)
    assert key in ex._prev_out
    stale = ex._prev_out[key]
    _b, worlds, _s, _c = ex.run_wave(worlds, inputs, status, starts, [K] * M)
    ex._prev_out[key] = stale  # reinsert handles the last wave donated
    with pytest.raises(TransferRaceError, match="donated"):
        ex.run_wave(worlds, inputs, status, starts, [K] * M)
