"""Hierarchy rollback — port of /root/reference/tests/hierarchy.rs:60-182:
3-level parent chains preserved across continuous rollback; child despawn
rolled back cleanly; recursive despawn takes the subtree."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu import App, GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.snapshot import (
    Registry,
    active_mask,
    despawn_recursive,
    despawn_where,
    spawn,
)


def make_chain_app(levels=3, chains=4, despawn_leaf_at=None, despawn_root_at=None):
    app = App(num_players=1, capacity=32, input_shape=(), input_dtype=np.uint8)
    app.register_hierarchy()
    app.rollback_component("depth", (), jnp.int32, checksum=True)
    app.rollback_component("age", (), jnp.int32, checksum=True)
    roots = []

    def step(world, ctx):
        m = active_mask(world) & world.has["age"]
        world = dataclasses.replace(
            world,
            comps={**world.comps,
                   "age": jnp.where(m, world.comps["age"] + 1, world.comps["age"])},
        )
        if despawn_leaf_at is not None:
            kill = m & (ctx.frame == despawn_leaf_at) & (
                world.comps["depth"] == levels - 1
            )
            world = despawn_where(app.reg, world, kill, ctx.frame)
        if despawn_root_at is not None:
            world = jax.lax.cond(
                ctx.frame == despawn_root_at,
                lambda w: despawn_recursive(app.reg, w, roots[0], ctx.frame),
                lambda w: w,
                world,
            )
        return world

    import jax

    def setup(world):
        for c in range(chains):
            parent = -1
            for d in range(levels):
                world, slot = spawn(
                    app.reg, world,
                    {Registry.PARENT: parent, "depth": d, "age": 0},
                )
                if d == 0:
                    roots.append(int(slot))
                parent = int(slot)
        return world

    app.set_step(step)
    app.set_setup(setup)
    return app


def run(app, ticks, check_distance=3):
    session = SyncTestSession(num_players=1, input_shape=(),
                              input_dtype=np.uint8, check_distance=check_distance)
    mismatches = []
    runner = GgrsRunner(app, session, on_mismatch=mismatches.append)
    for _ in range(ticks):
        runner.tick()
    return runner, mismatches


def test_three_level_chains_preserved():
    app = make_chain_app()
    runner, mismatches = run(app, 20)
    assert mismatches == []
    w = runner.world
    parent = np.asarray(w.comps[Registry.PARENT])
    depth = np.asarray(w.comps["depth"])
    alive = np.asarray(active_mask(w))
    for slot in range(12):
        assert alive[slot]
        if depth[slot] > 0:
            p = parent[slot]
            assert alive[p]
            assert depth[p] == depth[slot] - 1  # chain intact
    assert np.all(np.asarray(w.comps["age"])[:12] == 20)


def test_child_despawn_across_rollback():
    app = make_chain_app(despawn_leaf_at=8)
    runner, mismatches = run(app, 20)
    assert mismatches == []
    w = runner.world
    alive = np.asarray(active_mask(w))
    depth = np.asarray(w.comps["depth"])
    has = np.asarray(w.has["depth"])
    # leaves gone, inner nodes alive
    for slot in range(12):
        if has[slot] and alive[slot]:
            assert depth[slot] < 2
    assert sum(alive[:12]) == 8


def test_recursive_root_despawn_takes_subtree():
    app = make_chain_app(despawn_root_at=6)
    runner, mismatches = run(app, 20)
    assert mismatches == []
    w = runner.world
    alive = np.asarray(active_mask(w))
    # first chain (slots 0,1,2) fully gone, others intact
    assert not alive[0] and not alive[1] and not alive[2]
    assert alive[3] and alive[4] and alive[5]
