"""Pong: a complete game (paddles, ball despawn/respawn on goals, score
resource, serve delay) stays deterministic under continuous rollback."""

import numpy as np

from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import pong
from bevy_ggrs_tpu.snapshot import active_mask


def run_game(ticks, check_distance=3, p0_move=0, p1_move=0):
    app = pong.make_app()
    session = SyncTestSession(num_players=2, input_shape=(),
                              input_dtype=np.uint8,
                              check_distance=check_distance)
    mismatches = []
    runner = GgrsRunner(
        app, session,
        read_inputs=lambda hs: {0: np.uint8(p0_move), 1: np.uint8(p1_move)},
        on_mismatch=mismatches.append,
    )
    for _ in range(ticks):
        runner.tick()
    return runner, mismatches


def test_rally_scores_and_reserves():
    # player 1 hides at the top: balls served toward it eventually score
    runner, mismatches = run_game(650, p1_move=pong.UP)
    assert mismatches == []
    score = np.asarray(runner.world.res["score"])
    assert score.sum() >= 1, f"no goals after 650 frames: {score}"
    # ball lifecycle: at most one ball active at any time, and the serve
    # cycle keeps producing them
    kind = np.asarray(runner.world.comps["kind"])
    active = np.asarray(active_mask(runner.world))
    assert (active & (kind == pong.K_BALL)).sum() <= 1
    assert int(runner.world.next_id) >= 3  # paddles + at least one ball


def test_paddles_track_input():
    runner, mismatches = run_game(30, p0_move=pong.UP, p1_move=pong.DOWN)
    assert mismatches == []
    pos = np.asarray(runner.world.comps["pos"])
    assert pos[0, 1] > 0.3   # p0 moved up
    assert pos[1, 1] < -0.3  # p1 moved down
