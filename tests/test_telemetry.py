"""Telemetry subsystem tests — registry semantics, timeline ordering across
a forced rollback, desync forensics reports, the Prometheus exporter, and
the two hardening satellites that ride along (room same-addr rejoin, sync
handshake protocol versioning)."""

import dataclasses
import glob
import json
import struct
import time
import urllib.request

import pytest

from bevy_ggrs_tpu import telemetry
from tests.test_synctest import make_counter_app, make_runner


@pytest.fixture(autouse=True)
def _clean_telemetry():
    # the registry/timeline are process globals: isolate every test
    telemetry.disable()
    telemetry.reset()
    telemetry.configure_forensics(None)
    yield
    telemetry.disable()
    telemetry.reset()
    telemetry.configure_forensics(None)


# ---------------------------------------------------------------- registry


def test_counter_semantics_with_labels():
    telemetry.enable()
    telemetry.count("widgets_total", help="widgets")
    telemetry.count("widgets_total", 4, kind="blue")
    telemetry.count("widgets_total", kind="blue")
    c = telemetry.registry().counter("widgets_total", "widgets")
    assert c.value() == 1
    assert c.value(kind="blue") == 5
    snap = telemetry.registry().snapshot()
    assert snap["widgets_total"]["kind"] == "counter"
    assert snap["widgets_total"]["series"]["kind=blue"] == 5


def test_histogram_buckets_and_sum():
    telemetry.enable()
    for v in (0, 1, 1, 5, 100):
        telemetry.observe("depth", v, help="d", buckets=(0, 1, 4, 8))
    h = telemetry.registry().histogram("depth", "d", buckets=(0, 1, 4, 8))
    s = h.snapshot()
    assert s["count"] == 5
    assert s["sum"] == 107
    # per-bucket (non-cumulative); 100 overflows every bucket -> count only
    assert s["buckets"] == [1, 2, 0, 1]


def test_gauge_and_kind_conflict():
    telemetry.enable()
    telemetry.gauge_set("depth_now", 3, help="g")
    assert telemetry.registry().gauge("depth_now", "g").value() == 3
    with pytest.raises(TypeError):
        telemetry.registry().counter("depth_now", "not a gauge")


def test_disabled_is_noop():
    assert not telemetry.enabled()
    telemetry.count("never_total")
    telemetry.observe("never_hist", 1)
    telemetry.gauge_set("never_gauge", 1)
    telemetry.record("never_event")
    assert telemetry.registry().snapshot() == {}
    assert telemetry.timeline().tail(10) == []


def test_prometheus_rendering_cumulative():
    telemetry.enable()
    telemetry.count("ticks_total", 3, help="ticks")
    for v in (0, 2, 9):
        telemetry.observe("lat", v, help="lat", buckets=(1, 4))
    text = telemetry.registry().render_prometheus()
    assert "# TYPE ticks_total counter" in text
    assert "ticks_total 3" in text
    # cumulative le buckets ending in +Inf, plus _sum/_count
    assert 'lat_bucket{le="1"} 1' in text
    assert 'lat_bucket{le="4"} 2' in text
    assert 'lat_bucket{le="+Inf"} 3' in text
    assert "lat_sum 11" in text
    assert "lat_count 3" in text


# ---------------------------------------------- timeline across a rollback


def test_timeline_orders_rollbacks_and_spans():
    telemetry.enable()
    app = make_counter_app()
    runner, mismatches = make_runner(app, check_distance=2)
    for _ in range(8):
        runner.tick()
    assert not mismatches
    events = telemetry.timeline().tail(10_000)
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    rollbacks = telemetry.timeline().events("rollback")
    # check_distance=2 forces a load+resim every tick after warmup
    assert rollbacks, "synctest check_distance=2 must roll back"
    for ev in rollbacks:
        assert ev["to_frame"] < ev["from_frame"]
        assert ev["depth"] == ev["from_frame"] - ev["to_frame"]
    span_names = {e["name"] for e in telemetry.timeline().events("span")}
    assert {"SaveWorld", "LoadWorld", "AdvanceWorld"} <= span_names
    # summary() derives the headline numbers from the same run
    s = telemetry.summary()
    assert s["enabled"] and s["derived"]["rollbacks_total"] == len(rollbacks)


def test_export_jsonl_round_trips(tmp_path):
    telemetry.enable()
    telemetry.record("alpha", x=1)
    telemetry.record("beta", y="z")
    out = tmp_path / "tl.jsonl"
    n = telemetry.export_jsonl(str(out))
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert n == len(lines) == 2
    assert [l["kind"] for l in lines] == ["alpha", "beta"]


# -------------------------------------------------------------- forensics


def test_desync_report_on_injected_mismatch(tmp_path):
    telemetry.enable()
    telemetry.configure_forensics(str(tmp_path))
    app = make_counter_app()
    runner, mismatches = make_runner(app, check_distance=2)
    for _ in range(4):
        runner.tick()
    # corrupt checksummed state behind the session's back (negative control
    # pattern from test_synctest.py) -> re-simulated frames must disagree
    w = runner.world
    runner.world = dataclasses.replace(
        w, comps={**w.comps, "counter": w.comps["counter"] + 1000}
    )
    runner._world_checksum = app.checksum_fn(runner.world)
    for _ in range(6):
        runner.tick()
    assert mismatches
    reports = glob.glob(str(tmp_path / "desync_synctest_mismatch_*.json"))
    assert reports, "forensics dir configured -> a report must be written"
    rep = json.loads(open(reports[0]).read())
    assert rep["kind"] == "synctest_mismatch"
    assert rep["frames"]
    assert "counter" in rep["component_checksums"]
    assert "__entities__" in rep["component_checksums"]
    assert rep["timeline_tail"], "report embeds the recent timeline"
    assert telemetry.registry().counter(
        "checksum_mismatch_total", ""
    ).value(kind="synctest") > 0


def test_no_report_without_forensics_dir(tmp_path):
    telemetry.enable()
    assert telemetry.forensics_dir() is None
    assert telemetry.write_desync_report("synctest_mismatch") is None
    assert not list(tmp_path.iterdir())


# ------------------------------------------------------------- prometheus


def test_http_exporter_scrape():
    telemetry.enable()
    telemetry.count("scraped_total", 7, help="scrape me")
    exporter = telemetry.start_http_exporter(port=0)
    try:
        url = f"http://127.0.0.1:{exporter.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as resp:
            body = resp.read().decode()
            ctype = resp.headers["Content-Type"]
        assert ctype.startswith("text/plain; version=0.0.4")
        assert "scraped_total 7" in body
    finally:
        exporter.close()


# ------------------------------------------------- satellite: room rejoin


def test_same_addr_rejoin_into_full_room():
    """A socket that already holds a slot in a full room may re-join it
    under a new peer id: its own membership must not count against capacity."""
    from bevy_ggrs_tpu import RoomServer, RoomSocket, wait_for_players
    from bevy_ggrs_tpu.session import room as room_mod

    old_cap = room_mod.MAX_ROOM_MEMBERS
    room_mod.MAX_ROOM_MEMBERS = 1
    try:
        server = RoomServer(host="127.0.0.1")
        a = RoomSocket(server.local_addr, "solo", peer_id="old-name",
                       host="127.0.0.1")
        wait_for_players(a, 1, timeout_s=5.0, server=server)
        a.peer_id = "new-name"
        a._join()
        deadline = time.monotonic() + 3.0
        while (time.monotonic() < deadline
               and sorted(server.rooms.get("solo", {})) != ["new-name"]):
            server.poll()
            time.sleep(0.002)
        assert sorted(server.rooms["solo"]) == ["new-name"]
        assert len(server.rooms["solo"]) <= 1
        server.close()
        a.close()
    finally:
        room_mod.MAX_ROOM_MEMBERS = old_cap


# -------------------------------------- satellite: handshake versioning


def test_sync_handshake_rejects_versionless_peer():
    """A peer speaking the pre-version wire format (4-byte sync bodies) must
    stall in SYNCHRONIZING instead of mis-parsing — and a versioned REQ from
    it gets a versioned REP."""
    from bevy_ggrs_tpu import (
        GgrsRunner, PlayerType, SessionBuilder, SessionState,
        UdpNonBlockingSocket,
    )
    from bevy_ggrs_tpu.models import box_game
    from bevy_ggrs_tpu.session.protocol import (
        HDR, MAGIC, PROTOCOL_VERSION, S_SYNC_REP, S_SYNC_REQ,
        T_SYNC_REQ, T_SYNC_REP,
    )

    telemetry.enable()
    old_body = struct.Struct("<I")  # the pre-version sync body
    socks = [UdpNonBlockingSocket(0, host="127.0.0.1") for _ in range(2)]
    addrs = [("127.0.0.1", s.local_addr[1]) for s in socks]
    app = box_game.make_app(num_players=2)
    b = (SessionBuilder.for_app(app)
         .add_player(PlayerType.LOCAL, 0)
         .add_player(PlayerType.REMOTE, 1, addrs[1]))
    session = b.start_p2p_session(socks[0])
    runner = GgrsRunner(app, session)
    versioned_reps = []
    for _ in range(60):
        runner.update(0.0)
        for addr, data in socks[1].receive_all():
            magic, t = HDR.unpack_from(data)
            if t == T_SYNC_REQ:
                (nonce,) = old_body.unpack_from(data[HDR.size:])
                # reply in the OLD format: no version byte
                socks[1].send_to(
                    HDR.pack(MAGIC, T_SYNC_REP) + old_body.pack(nonce), addr
                )
            elif t == T_SYNC_REP:
                versioned_reps.append(S_SYNC_REP.unpack_from(data[HDR.size:]))
        time.sleep(0.001)
    # version-less REPs were dropped -> never synchronized
    assert session.current_state() == SessionState.SYNCHRONIZING
    assert telemetry.registry().counter(
        "handshake_version_mismatch_total", ""
    ).value(remote_version="none") > 0
    # a properly versioned REQ from the old peer's socket gets a versioned REP
    socks[1].send_to(
        HDR.pack(MAGIC, T_SYNC_REQ) + S_SYNC_REQ.pack(99, PROTOCOL_VERSION),
        addrs[0],
    )
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline and not any(
        n == 99 for n, _ in versioned_reps
    ):
        runner.update(0.0)
        for addr, data in socks[1].receive_all():
            magic, t = HDR.unpack_from(data)
            if t == T_SYNC_REP:
                versioned_reps.append(S_SYNC_REP.unpack_from(data[HDR.size:]))
        time.sleep(0.001)
    assert (99, PROTOCOL_VERSION) in versioned_reps
    for s in socks:
        s.close()
