"""Test config: force the JAX CPU backend with 8 virtual devices.

Tests run deterministic logic and mesh-sharding paths on a virtual 8-device
CPU mesh (no TPU needed); the benchmark (bench.py) runs on real hardware.
Must run before any jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
