"""Test config: force the JAX CPU backend with 8 virtual devices.

Tests run deterministic logic and mesh-sharding paths on a virtual 8-device
CPU mesh (no TPU needed); the benchmark (bench.py) runs on real hardware.

Note: the ambient environment may import jax at interpreter start (TPU tunnel
sitecustomize) with JAX_PLATFORMS already set, so env vars are too late —
update the jax config directly instead."""

import os

# jax < 0.5 has no jax_num_cpu_devices config option; the XLA flag is the
# portable spelling and is read at backend init (first device use), so
# setting it here is early enough even when jax was already imported
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older jax: the XLA_FLAGS fallback above covers it

import pytest


@pytest.fixture(scope="module")
def eight_devices():
    """The 8-virtual-device CPU mesh, verified — for the lobby-sharding
    tests (tests/test_sharded_wave.py), which are meaningless on fewer
    devices.  The XLA flag above only takes effect when it precedes backend
    init; if some earlier import already initialized a smaller backend
    (e.g. an ambient single-chip TPU sitecustomize), SKIP the module rather
    than fail it."""
    flag = "--xla_force_host_platform_device_count"
    assert flag in os.environ.get("XLA_FLAGS", ""), (
        "conftest did not force the XLA host device count"
    )
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip(
            f"backend initialized with {len(devices)} device(s); the XLA "
            "device-count flag was applied too late to provision 8"
        )
    return devices[:8]
