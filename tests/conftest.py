"""Test config: force the JAX CPU backend with 8 virtual devices.

Tests run deterministic logic and mesh-sharding paths on a virtual 8-device
CPU mesh (no TPU needed); the benchmark (bench.py) runs on real hardware.

Note: the ambient environment may import jax at interpreter start (TPU tunnel
sitecustomize) with JAX_PLATFORMS already set, so env vars are too late —
update the jax config directly instead."""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
