"""Test config: force the JAX CPU backend with 8 virtual devices.

Tests run deterministic logic and mesh-sharding paths on a virtual 8-device
CPU mesh (no TPU needed); the benchmark (bench.py) runs on real hardware.

Note: the ambient environment may import jax at interpreter start (TPU tunnel
sitecustomize) with JAX_PLATFORMS already set, so env vars are too late —
update the jax config directly instead."""

import os

# jax < 0.5 has no jax_num_cpu_devices config option; the XLA flag is the
# portable spelling and is read at backend init (first device use), so
# setting it here is early enough even when jax was already imported
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older jax: the XLA_FLAGS fallback above covers it
