"""SoA world-state tests: deterministic slot allocation, stable rollback ids
(RollbackOrdered semantics, /root/reference/src/snapshot/rollback.rs:62-99),
deferred despawn / resurrect-by-restore (src/snapshot/despawn.rs), hierarchy
recursive despawn, spawn_many determinism, component/resource presence."""

import jax
import jax.numpy as jnp

from bevy_ggrs_tpu.snapshot import (
    Registry,
    active_mask,
    active_count,
    spawn,
    spawn_many,
    despawn,
    despawn_recursive,
    despawn_confirmed,
    insert_component,
    remove_component,
    insert_resource,
    remove_resource,
)


def make_reg(cap=16):
    reg = Registry(cap)
    reg.register_component("pos", (2,), jnp.float32, checksum=True)
    reg.register_component("hp", (), jnp.int32, default=100)
    return reg


def test_spawn_assigns_monotonic_ids_and_first_free_slots():
    reg = make_reg()
    w = reg.init_state()
    slots = []
    for i in range(4):
        w, s = spawn(reg, w, {"pos": jnp.array([i, i], jnp.float32)})
        slots.append(int(s))
    assert slots == [0, 1, 2, 3]
    assert [int(w.rollback_id[s]) for s in slots] == [0, 1, 2, 3]
    assert int(w.next_id) == 4
    assert not bool(w.overflow)


def test_slot_reuse_keeps_order_monotonic():
    # RollbackOrdered never forgets: a reused slot gets a NEW, larger id
    reg = make_reg()
    w = reg.init_state()
    w, s0 = spawn(reg, w, {})
    w, s1 = spawn(reg, w, {})
    w = despawn(reg, w, s0, frame=0)
    w = despawn_confirmed(reg, w, confirmed=0)  # hard-free slot 0
    assert not bool(w.alive[0])
    w, s2 = spawn(reg, w, {})
    assert int(s2) == 0  # first free slot reused
    assert int(w.rollback_id[0]) == 2  # fresh id, never id 0 again


def test_despawn_is_deferred_and_disabling():
    reg = make_reg()
    w = reg.init_state()
    w, s = spawn(reg, w, {})
    w = despawn(reg, w, s, frame=5)
    # still allocated, but excluded from the active mask immediately
    assert bool(w.alive[int(s)])
    assert not bool(active_mask(w)[int(s)])
    # not confirmed yet -> stays allocated
    w2 = despawn_confirmed(reg, w, confirmed=4)
    assert bool(w2.alive[int(s)])
    # confirmed -> hard-freed
    w3 = despawn_confirmed(reg, w, confirmed=5)
    assert not bool(w3.alive[int(s)])
    assert int(w3.rollback_id[int(s)]) == -1


def test_resurrect_via_snapshot_restore():
    # marking after frame F is invisible in F's snapshot: restoring F IS the
    # EntityResurrect pass (despawn.rs:69-87)
    reg = make_reg()
    w = reg.init_state()
    w, s = spawn(reg, w, {})
    snapshot = w  # save at frame 3
    w = despawn(reg, w, s, frame=5)
    restored = snapshot  # rollback to frame 3
    assert bool(active_mask(restored)[int(s)])


def test_overflow_flag():
    reg = make_reg(cap=2)
    w = reg.init_state()
    w, _ = spawn(reg, w, {})
    w, _ = spawn(reg, w, {})
    assert not bool(w.overflow)
    w, _ = spawn(reg, w, {})
    assert bool(w.overflow)


def test_spawn_many_deterministic():
    reg = make_reg(cap=8)
    w = reg.init_state()
    w, _ = spawn(reg, w, {})  # occupy slot 0
    rows = jnp.arange(6, dtype=jnp.float32).reshape(3, 2)
    w = spawn_many(reg, w, {"pos": rows}, count=3)
    assert int(active_count(w)) == 4
    # rows land in ascending free slots 1,2,3 with ids 1,2,3
    assert [int(w.rollback_id[i]) for i in (1, 2, 3)] == [1, 2, 3]
    assert jnp.allclose(w.comps["pos"][1:4], rows)
    # count can be traced/partial
    w2 = spawn_many(reg, w, {"pos": rows}, count=2)
    assert int(active_count(w2)) == 6
    assert int(w2.next_id) == 6


def test_spawn_many_overflow():
    reg = make_reg(cap=4)
    w = reg.init_state()
    rows = jnp.zeros((6, 2), jnp.float32)
    w = spawn_many(reg, w, {"pos": rows}, count=6)
    assert int(active_count(w)) == 4
    assert bool(w.overflow)


def test_hierarchy_recursive_despawn():
    reg = make_reg()
    reg.register_hierarchy()
    w = reg.init_state()
    w, root = spawn(reg, w, {})
    w, mid = spawn(reg, w, {Registry.PARENT: root})
    w, leaf = spawn(reg, w, {Registry.PARENT: mid})
    w, other = spawn(reg, w, {})
    w = despawn_recursive(reg, w, root, frame=7)
    am = active_mask(w)
    assert not bool(am[int(root)])
    assert not bool(am[int(mid)])
    assert not bool(am[int(leaf)])
    assert bool(am[int(other)])


def test_component_presence():
    reg = make_reg()
    w = reg.init_state()
    w, s = spawn(reg, w, {"pos": jnp.zeros(2)})
    assert bool(w.has["pos"][int(s)])
    assert not bool(w.has["hp"][int(s)])
    w = insert_component(reg, w, s, "hp", 42)
    assert bool(w.has["hp"][int(s)])
    assert int(w.comps["hp"][int(s)]) == 42
    w = remove_component(reg, w, s, "hp")
    assert not bool(w.has["hp"][int(s)])


def test_resource_lifecycle():
    reg = make_reg()
    reg.register_resource("score", jnp.int32(0), present=False)
    w = reg.init_state()
    assert not bool(w.res_present["score"])
    w = insert_resource(reg, w, "score", 10)
    assert bool(w.res_present["score"])
    assert int(w.res["score"]) == 10
    w = remove_resource(reg, w, "score")
    assert not bool(w.res_present["score"])


def test_required_component_inserted_on_spawn():
    reg = Registry(4)
    reg.register_component("tag", (), jnp.int32, default=7, required=True)
    w = reg.init_state()
    w, s = spawn(reg, w, {})
    assert bool(w.has["tag"][int(s)])
    assert int(w.comps["tag"][int(s)]) == 7


def test_ops_are_jittable():
    reg = make_reg()

    @jax.jit
    def build(w):
        w, s = spawn(reg, w, {"pos": jnp.ones(2)})
        w = despawn(reg, w, s, frame=3)
        w = despawn_confirmed(reg, w, confirmed=3)
        return w

    w = build(reg.init_state())
    assert int(active_count(w)) == 0
    assert int(w.next_id) == 1


def test_cloned_entity_gets_fresh_id():
    # EntityCloner regression analog (/root/reference/src/snapshot/
    # rollback.rs:121-196): copying an entity's components into a new spawn
    # must mint a NEW rollback id, never alias the source's identity
    reg = make_reg()
    w = reg.init_state()
    w, src = spawn(reg, w, {"pos": jnp.array([3.0, 4.0]), "hp": 7})
    clone_comps = {
        "pos": w.comps["pos"][src],
        "hp": w.comps["hp"][src],
    }
    w, dup = spawn(reg, w, clone_comps)
    assert int(w.rollback_id[int(src)]) != int(w.rollback_id[int(dup)])
    assert int(w.rollback_id[int(dup)]) == 1
    assert jnp.allclose(w.comps["pos"][int(dup)], w.comps["pos"][int(src)])
