"""Multiple local players per peer: a 4-player game across 2 connections
(each endpoint streams 2 input rows per frame).  The reference supports 2-4
players with any local/remote split (box_game.rs:34-38)."""

import numpy as np
import pytest

from bevy_ggrs_tpu import GgrsRunner, PlayerType, SessionBuilder, SessionState
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


@pytest.mark.parametrize("native", [False, True])
def test_two_local_players_per_peer(native):
    if native:
        from bevy_ggrs_tpu.session.native import native_available

        if not native_available():
            pytest.skip("native core not built")
        import socket as so

        ports = []
        for _ in range(2):
            s = so.socket(so.AF_INET, so.SOCK_DGRAM)
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            s.close()
    else:
        net = ChannelNetwork()
        socks = [net.endpoint("A"), net.endpoint("B")]

    keys = [box_game.keys_to_input(right=True), box_game.keys_to_input(up=True),
            box_game.keys_to_input(left=True), box_game.keys_to_input(down=True)]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=4)
        # REGRESSION: declare local handles in descending order — the wire
        # row order must not depend on add_player order
        mine = [1, 0] if i == 0 else [3, 2]
        theirs = [2, 3] if i == 0 else [0, 1]
        b = SessionBuilder.for_app(app).with_input_delay(1)
        for h in mine:
            b.add_player(PlayerType.LOCAL, h)
        for h in theirs:
            if native:
                b.add_player(PlayerType.REMOTE, h, ("127.0.0.1", ports[1 - i]))
            else:
                b.add_player(PlayerType.REMOTE, h, "BA"[i == 1])
        if native:
            session = b.start_p2p_session_native(local_port=ports[i])
        else:
            session = b.start_p2p_session(socks[i])
        runners.append(
            GgrsRunner(app, session,
                       read_inputs=lambda hs: {h: keys[h] for h in hs})
        )
        assert sorted(session.local_player_handles()) == sorted(mine)

    import time

    for _ in range(400):
        if not native:
            net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    assert all(r.session.current_state() == SessionState.RUNNING for r in runners)

    for _ in range(60):
        if not native:
            net.deliver()
        for r in runners:
            r.update(DT)

    # every player's held direction moved their own cube on BOTH peers
    for r in runners:
        pos = np.asarray(r.world.comps["pos"])
        assert pos[0, 0] > 1.9  # p0 right (+x)
        assert pos[2, 0] < -1.9 + 2.0  # p2 left (-x from its spawn)
        assert r.frame >= 50
    # and the peers agree
    for _ in range(6):
        shared = sorted(set(runners[0].ring.frames()) & set(runners[1].ring.frames()))
        if shared:
            break
        if not native:
            net.deliver()
        (runners[0] if runners[0].frame <= runners[1].frame else runners[1]).update(DT)
    assert shared
    f = shared[-1]
    assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
        runners[1].ring.peek(f)[1]
    )
