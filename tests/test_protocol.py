"""Wire-protocol unit tests: endpoint handshake, input redundancy + ack,
quality/ping, keepalive/disconnect timers, checksum reports, and robustness
against malformed/truncated/alien packets."""

import time


from bevy_ggrs_tpu.session.events import (
    Disconnected,
    NetworkInterrupted,
    SessionState,
    Synchronized,
)
from bevy_ggrs_tpu.session.protocol import (
    HDR,
    MAGIC,
    MAX_INPUTS_PER_PACKET,
    PeerEndpoint,
    S_INPUT,
    T_CHECKSUM,
    T_KEEP_ALIVE,
)


def make_pair(input_size=1, **kw):
    """Two endpoints wired directly to each other's handle()."""
    a_out, b_out = [], []
    a = PeerEndpoint(send=a_out.append, input_size=input_size, rng_nonce=1,
                     addr="B", **kw)
    b = PeerEndpoint(send=b_out.append, input_size=input_size, rng_nonce=2,
                     addr="A", **kw)
    return a, b, a_out, b_out


def pump(a, b, a_out, b_out, rounds=10):
    for _ in range(rounds):
        a.poll()
        b.poll()
        for pkt in a_out:
            b.handle(pkt)
        a_out.clear()
        for pkt in b_out:
            a.handle(pkt)
        b_out.clear()


def test_sync_handshake_completes():
    a, b, ao, bo = make_pair()
    pump(a, b, ao, bo)
    assert a.state == SessionState.RUNNING
    assert b.state == SessionState.RUNNING
    assert any(isinstance(e, Synchronized) for e in a.events)
    assert any(isinstance(e, Synchronized) for e in b.events)


def test_input_redundancy_and_ack():
    a, b, ao, bo = make_pair()
    pump(a, b, ao, bo)
    got = []
    b.on_input = lambda f, raw: got.append((f, raw))
    pending = [(f, bytes([f])) for f in range(5)]
    a.send_inputs(pending)
    for pkt in ao:
        b.handle(pkt)
    ao.clear()
    assert got == [(f, bytes([f])) for f in range(5)]
    assert b.last_received_frame == 4
    b.send_input_ack()
    for pkt in bo:
        a.handle(pkt)
    bo.clear()
    assert a.last_acked == 4
    # next send excludes acked frames
    a.send_inputs(pending + [(5, b"\x05")])
    assert a.send_queue_len == 1


def test_quality_roundtrip_sets_ping():
    a, b, ao, bo = make_pair()
    pump(a, b, ao, bo)
    # force a quality report now
    a._last_quality_sent = 0.0
    a.poll()
    for pkt in ao:
        b.handle(pkt)
    ao.clear()
    for pkt in bo:
        a.handle(pkt)
    bo.clear()
    assert a.ping_s >= 0.0  # measured (tiny on loopback)


def test_checksum_report():
    a, b, ao, bo = make_pair()
    pump(a, b, ao, bo)
    got = []
    b.on_checksum = lambda f, cs: got.append((f, cs))
    a.send_checksum(42, 0xDEADBEEFCAFEBABE)
    for pkt in ao:
        b.handle(pkt)
    assert got == [(42, 0xDEADBEEFCAFEBABE)]


def test_disconnect_timers():
    a, b, ao, bo = make_pair(
        disconnect_timeout_s=0.12, disconnect_notify_start_s=0.04
    )
    pump(a, b, ao, bo)
    a.events.clear()
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline and not a.disconnected:
        a.poll()  # b never talks again
        time.sleep(0.01)
    kinds = [type(e) for e in a.events]
    assert NetworkInterrupted in kinds
    assert Disconnected in kinds


def test_malformed_packets_ignored():
    a, _, ao, _ = make_pair()
    a.handle(b"")  # empty
    a.handle(b"\x00")  # short
    a.handle(HDR.pack(0x1234, 3) + b"junk")  # wrong magic
    a.handle(HDR.pack(MAGIC, 99))  # unknown type
    a.handle(HDR.pack(MAGIC, T_CHECKSUM) + b"\x01")  # truncated body
    from bevy_ggrs_tpu.session.protocol import T_DISC_NOTICE

    seen = []
    a.on_disc_notice = lambda h, f: seen.append((h, f))
    a.handle(HDR.pack(MAGIC, T_DISC_NOTICE) + b"\x01")  # truncated notice
    a.handle(HDR.pack(MAGIC, T_DISC_NOTICE))  # empty notice body
    assert seen == []  # truncated notices never reach the session
    a.handle(HDR.pack(MAGIC, T_KEEP_ALIVE))
    assert a.state == SessionState.SYNCHRONIZING  # unaffected


def test_truncated_input_payload_safe():
    a, b, ao, bo = make_pair(input_size=4)
    pump(a, b, ao, bo)
    got = []
    b.on_input = lambda f, raw: got.append((f, raw))
    # claim 3 inputs but ship bytes for 1.5
    from bevy_ggrs_tpu.session.protocol import S_INPUT

    body = S_INPUT.pack(0, 3, -1, 0, 0) + b"\x01\x02\x03\x04\x05\x06"
    b.handle(HDR.pack(MAGIC, 3) + body)
    assert got == [(0, b"\x01\x02\x03\x04")]  # only the complete one


def test_chunk_loss_gap_refills():
    # >64 pending inputs -> 2 chunks; losing chunk 1 must NOT let the ack
    # leapfrog the gap: the receiver acks the contiguous mark, the sender
    # retransmits, and the gap fills
    a, b, ao, bo = make_pair()
    pump(a, b, ao, bo)
    got = {}
    b.on_input = lambda f, raw: got.setdefault(f, raw)
    n = MAX_INPUTS_PER_PACKET + 20
    pending = [(f, bytes([f % 251])) for f in range(n)]
    a.send_inputs(pending)
    packets = list(ao)
    ao.clear()
    assert len(packets) == 2
    b.handle(packets[1])  # chunk 1 lost; only chunk 2 arrives
    assert b.contig_received == -1  # gap: nothing contiguous yet
    b.send_input_ack()
    for pkt in bo:
        a.handle(pkt)
    bo.clear()
    assert a.last_acked == -1  # sender knows nothing landed contiguously
    # retransmission fills the gap
    a.send_inputs(pending)
    for pkt in ao:
        b.handle(pkt)
    ao.clear()
    assert sorted(got) == list(range(n))
    assert b.contig_received == n - 1
    b.send_input_ack()
    for pkt in bo:
        a.handle(pkt)
    assert a.last_acked == n - 1


def test_first_packets_lost_anchors_at_stream_base():
    # even if the receiver's FIRST seen packet is beyond the stream start,
    # the stream_base field keeps the ack anchored before the gap
    a, b, ao, bo = make_pair()
    pump(a, b, ao, bo)
    got = {}
    b.on_input = lambda f, raw: got.setdefault(f, raw)
    bases = []
    b.on_stream_base = bases.append
    n = MAX_INPUTS_PER_PACKET + 10
    pending = [(f + 5, bytes([f % 251])) for f in range(n)]  # stream starts at 5
    a.send_inputs(pending)
    packets = list(ao)
    ao.clear()
    b.handle(packets[1])  # first chunk lost entirely
    assert bases == [5]
    assert b.contig_received == 4  # anchored just below the true base
    a.send_inputs(pending)
    for pkt in ao:
        b.handle(pkt)
    assert b.contig_received == 5 + n - 1
