"""Tick-phase latency attribution tests — histogram percentiles, guarded
phase timers (disabled-path cost + enabled-path series), the always-on
flight recorder (ring bound, dump, desync embedding, reconciliation), the
bench-history regression gate, and the lint's mirrored phase catalog."""

import dataclasses
import importlib.util
import json
import os
import time

import pytest

from bevy_ggrs_tpu import telemetry
from tests.test_synctest import make_counter_app, make_runner


@pytest.fixture(autouse=True)
def _clean_telemetry():
    # registry/timeline/flight ring are process globals: isolate every test
    telemetry.disable()
    telemetry.reset()
    telemetry.configure_forensics(None)
    telemetry.configure_flight(maxlen=256, enabled=True)
    yield
    telemetry.disable()
    telemetry.reset()
    telemetry.configure_forensics(None)
    telemetry.configure_flight(maxlen=256, enabled=True)


# ------------------------------------------------- histogram percentiles


def test_percentile_from_buckets_uniform():
    telemetry.enable()
    h = telemetry.registry().histogram(
        "lat_ms", "l", buckets=telemetry.LATENCY_MS_BUCKETS
    )
    # 100 uniform values in (0, 10]: true p50 ~5, p95 ~9.5
    for i in range(1, 101):
        h.observe(i / 10.0)
    p50 = h.percentile(0.5)
    p95 = h.percentile(0.95)
    assert 4.0 <= p50 <= 6.0, p50
    assert 8.5 <= p95 <= 10.0, p95
    ps = h.percentiles()
    assert set(ps) == {"p50", "p95", "p99"}
    assert ps["p50"] == p50


def test_percentile_overflow_clamps_to_last_bound():
    telemetry.enable()
    h = telemetry.registry().histogram("big_ms", "b", buckets=(1.0, 2.0))
    h.observe(50.0)  # lands past every finite bucket
    assert h.percentile(0.5) == 2.0


def test_percentile_empty_series_is_none():
    telemetry.enable()
    h = telemetry.registry().histogram("empty_ms", "e", buckets=(1.0,))
    assert h.percentile(0.5) is None


def test_summary_derived_latency_percentiles():
    telemetry.enable()
    ps = telemetry.PhaseSet(owner="solo")
    for _ in range(5):
        ps.begin_tick()
        with ps.phase("wave_dispatch"):
            pass
        ps.end_tick(frame=1)
    derived = telemetry.summary()["derived"]["latency_ms"]
    assert "tick_phase_ms" in derived
    (key, row), = [
        (k, v) for k, v in derived["tick_phase_ms"].items()
        if "wave_dispatch" in k
    ]
    assert row["count"] == 5
    assert row["p50"] is not None and row["p50"] >= 0


# ------------------------------------------------------------ phase timers


def test_phase_timers_populate_histogram_series():
    telemetry.enable()
    ps = telemetry.PhaseSet(owner="solo")
    ps.begin_tick()
    with ps.phase("rollback_load"):
        time.sleep(0.001)
    ps.note_rollback(3)
    ps.end_tick(frame=7)
    h = telemetry.registry().histogram(
        "tick_phase_ms", "", buckets=telemetry.LATENCY_MS_BUCKETS
    )
    s = h.snapshot(phase="rollback_load", owner="solo")
    assert s["count"] == 1
    assert s["sum"] >= 1.0  # slept 1ms
    wall = telemetry.registry().histogram(
        "tick_wall_ms", "", buckets=telemetry.LATENCY_MS_BUCKETS
    ).snapshot(owner="solo")
    assert wall["count"] == 1


def test_phase_unknown_name_raises():
    ps = telemetry.PhaseSet()
    with pytest.raises(KeyError):
        ps.phase("made_up_phase")


def test_phase_totals_reconcile():
    ps = telemetry.PhaseSet(owner="solo")
    for _ in range(10):
        ps.begin_tick()
        with ps.phase("session_step"):
            pass
        with ps.phase("wave_dispatch"):
            pass
        ps.end_tick()
    t = ps.totals()
    assert t["ticks"] == 10
    # totals() rounds each part to 6 decimals, so compare with abs slack
    attributed = sum(t["phase_seconds"].values())
    assert attributed == pytest.approx(t["attributed_seconds"], abs=1e-5)
    assert t["wall_seconds"] == pytest.approx(
        t["attributed_seconds"] + t["unattributed_seconds"], abs=1e-5
    )


def test_phase_timers_disabled_path_is_cheap():
    # flight off + telemetry off: entering a phase must be one boolean
    # check. Bound the per-cycle cost generously (CI hosts are noisy) —
    # the point is catching an accidental perf_counter/dict hit on the
    # disabled path, which would cost 10x this bound.
    telemetry.configure_flight(enabled=False)
    ps = telemetry.PhaseSet(owner="solo")
    p1, p2 = ps.phase("net_poll"), ps.phase("wave_dispatch")
    n = 20000
    ps.begin_tick()
    assert ps._on is False
    t0 = time.perf_counter()
    for _ in range(n):
        with p1:
            pass
        with p2:
            pass
    dt = time.perf_counter() - t0
    per_cycle_us = dt / n * 1e6
    assert per_cycle_us < 20.0, f"{per_cycle_us:.2f}us per 2-phase cycle"
    # nothing was recorded anywhere
    ps.end_tick()
    assert ps.ticks == 0
    assert len(telemetry.flight_recorder()) == 0
    assert telemetry.registry().metrics() == []


def test_phase_timers_flight_only_no_registry_families():
    # telemetry disabled, flight on: entries land in the ring but the
    # registry must stay empty (no histogram families created)
    ps = telemetry.PhaseSet(owner="solo")
    ps.begin_tick()
    with ps.phase("store_save"):
        pass
    ps.end_tick(frame=3)
    assert telemetry.registry().metrics() == []
    entries = telemetry.flight_recorder().snapshot("tick")
    assert len(entries) == 1
    assert entries[0]["frame"] == 3
    assert "store_save" in entries[0]["phases"]


# -------------------------------------------------------- flight recorder


def test_flight_ring_bound_and_clear():
    fr = telemetry.flight_recorder()
    fr.set_maxlen(8)
    for i in range(20):
        fr.record("tick", i=i)
    assert len(fr) == 8
    assert [e["i"] for e in fr.snapshot()] == list(range(12, 20))
    fr.clear()
    assert len(fr) == 0


def test_flight_reconciliation_invariant():
    ps = telemetry.PhaseSet(owner="solo")
    for _ in range(5):
        ps.begin_tick()
        with ps.phase("wave_dispatch"):
            time.sleep(0.0005)
        with ps.phase("store_save"):
            pass
        ps.end_tick()
    for e in telemetry.flight_recorder().snapshot("tick"):
        total = sum(e["phases"].values()) + e["unattributed_ms"]
        # rounding each part to 4 decimals bounds the drift
        assert total == pytest.approx(e["wall_ms"], abs=0.01)


def test_dump_flight_record(tmp_path):
    fr = telemetry.flight_recorder()
    fr.record("tick", wall_ms=1.0)
    path = tmp_path / "flight.json"
    telemetry.dump_flight_record(str(path))
    data = json.loads(path.read_text())
    assert data["maxlen"] == fr.maxlen
    assert data["events"][0]["kind"] == "tick"


def test_flight_disabled_records_nothing():
    telemetry.configure_flight(enabled=False)
    fr = telemetry.flight_recorder()
    fr.record("tick", x=1)
    assert len(fr) == 0


def test_desync_report_embeds_flight_record(tmp_path):
    # telemetry NEVER enabled: the report's flight_record section must
    # still hold the recent tick history (the always-on black box)
    telemetry.configure_forensics(str(tmp_path))
    app = make_counter_app()
    runner, mismatches = make_runner(app, check_distance=2)
    for _ in range(6):
        runner.tick()
    w = runner.world
    runner.world = dataclasses.replace(
        w, comps={**w.comps, "counter": w.comps["counter"] + 1000}
    )
    runner._world_checksum = app.checksum_fn(runner.world)
    for _ in range(6):
        runner.tick()
    assert mismatches, "corruption never tripped the synctest comparison"
    reports = sorted(tmp_path.glob("desync_synctest_mismatch_*.json"))
    assert reports
    rep = json.loads(reports[0].read_text())
    flight = rep["flight_record"]
    ticks = [e for e in flight if e["kind"] == "tick"]
    assert ticks, "no tick entries in the embedded flight record"
    assert "phases" in ticks[-1] and "wall_ms" in ticks[-1]


def test_phase_breakdown_exact_percentiles():
    entries = [
        {"kind": "tick", "wall_ms": float(i), "unattributed_ms": 0.0,
         "phases": {"wave_dispatch": float(i)}}
        for i in range(1, 101)
    ]
    bd = telemetry.phase_breakdown(entries)
    assert bd["wave_dispatch"]["count"] == 100
    assert bd["wave_dispatch"]["p50"] == pytest.approx(50.5)
    assert bd["(wall)"]["p99"] == pytest.approx(99.01)
    table = telemetry.format_phase_table(bd)
    assert "wave_dispatch" in table and "p50" in table


# ------------------------------------------------------- timeline dropped


def test_timeline_dropped_counter_and_summary():
    telemetry.enable()
    tl = telemetry.Timeline(maxlen=4)
    for i in range(7):
        tl.record("ev", i=i)
    assert len(tl) == 4
    assert tl.dropped == 3
    c = telemetry.registry().counter("timeline_events_dropped_total", "")
    assert c.value() == 3
    tl.clear()
    assert tl.dropped == 0
    # the process-default timeline surfaces its own count in summary()
    assert "timeline_events_dropped" in telemetry.summary()


# -------------------------------------------------- prometheus escaping


def test_prometheus_label_value_escaping():
    telemetry.enable()
    telemetry.count("esc_total", peer='a"b\\c\nd')
    text = telemetry.registry().render_prometheus()
    assert 'peer="a\\"b\\\\c\\nd"' in text


def test_prometheus_histogram_exposition():
    telemetry.enable()
    ps = telemetry.PhaseSet(owner="solo")
    ps.begin_tick()
    with ps.phase("net_poll"):
        pass
    ps.end_tick()
    text = telemetry.registry().render_prometheus()
    assert 'tick_phase_ms_bucket{' in text
    assert 'le="+Inf"' in text
    assert "tick_phase_ms_sum{" in text
    assert "tick_phase_ms_count{" in text


# ------------------------------------------------------- runner wiring


def test_runner_stats_phases_and_compile():
    app = make_counter_app()
    runner, _ = make_runner(app, check_distance=2)
    for _ in range(10):
        runner.tick()
    st = runner.stats()
    assert st["phases"]["ticks"] > 0
    assert st["phases"]["unattributed_pct"] < 50.0
    assert "wave_dispatch" in st["phases"]["phase_seconds"]
    assert st["compile_ms"], "first dispatches were not timed"
    assert all(v > 0 for v in st["compile_ms"].values())


def test_packed_staging_attributed_to_stage_inputs():
    """The packed single-upload path must keep its host-side staging work
    (pack rows + synchronous commit) attributed under ``stage_inputs`` —
    and the totals must still reconcile (attributed + unattributed = wall),
    so the packing refactor cannot silently open an attribution hole."""
    app = make_counter_app()
    runner, mismatches = make_runner(app)
    for _ in range(12):
        runner.tick()
    assert mismatches == []
    st = runner.stats()
    assert st["packed"], "driver did not take the packed path"
    t = st["phases"]
    assert t["phase_seconds"].get("stage_inputs", 0.0) > 0.0
    attributed = sum(t["phase_seconds"].values())
    assert attributed == pytest.approx(t["attributed_seconds"], abs=1e-5)
    assert t["wall_seconds"] == pytest.approx(
        t["attributed_seconds"] + t["unattributed_seconds"], abs=1e-5
    )


# ------------------------------------------------------- bench history


def _load_bench_history():
    spec = importlib.util.spec_from_file_location(
        "bench_history",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_history.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_record(dir, n, parsed, rc=0):
    with open(os.path.join(dir, f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump({"n": n, "cmd": "bench", "rc": rc, "tail": "",
                   "parsed": parsed}, f)


def test_bench_history_detects_regression(tmp_path):
    bh = _load_bench_history()
    _write_record(tmp_path, 1, {"value": 1000.0, "platform": "cpu"})
    _write_record(tmp_path, 2, {"value": 800.0, "platform": "cpu"})
    assert bh.main(["--dir", str(tmp_path), "--threshold", "0.10"]) == 1
    assert bh.main(["--dir", str(tmp_path), "--threshold", "0.10",
                    "--warn-only"]) == 0
    # a looser threshold passes
    assert bh.main(["--dir", str(tmp_path), "--threshold", "0.25"]) == 0


def test_bench_history_compares_best_prior_same_platform(tmp_path):
    bh = _load_bench_history()
    _write_record(tmp_path, 1, {"value": 900.0, "platform": "cpu"})
    _write_record(tmp_path, 2, {"value": 90000.0, "platform": "tpu"})
    _write_record(tmp_path, 3, {"value": 880.0, "platform": "cpu"})
    # the tpu record must NOT count as the best prior for a cpu latest
    assert bh.main(["--dir", str(tmp_path), "--threshold", "0.10"]) == 0
    records = bh.load_records(str(tmp_path))
    _, _, rows, regs = bh.compare(records, 0.10)
    (row,) = [r for r in rows if r[0] == "value"]
    assert row[1] == 900.0 and row[2] == 1


def test_bench_history_skips_crashed_and_new_metrics(tmp_path):
    bh = _load_bench_history()
    _write_record(tmp_path, 1, {"value": 5000.0, "platform": "cpu"}, rc=1)
    _write_record(tmp_path, 2, {"value": 1000.0, "platform": "cpu"})
    _write_record(
        tmp_path, 3,
        {"value": 990.0, "brand_new_fps": 123.0, "platform": "cpu"},
    )
    # rc=1 record ignored (else value would regress 80%); new metric passes
    assert bh.main(["--dir", str(tmp_path), "--threshold", "0.10"]) == 0


def test_bench_history_excludes_non_throughput_keys():
    bh = _load_bench_history()
    metrics = bh.throughput_metrics({
        "value": 10.0, "spread": 0.5, "bytes_per_resim_frame": 720000,
        "pipeline_unattributed_pct": 3.0, "entities": 10000,
        "canonical_mode_fps": 5.0, "pipeline_speedup": 1.2,
        "tpu_fallback_to_cpu": True,
    })
    assert set(metrics) == {"value", "canonical_mode_fps",
                            "pipeline_speedup"}


def test_bench_history_upload_census_gates_on_increase(tmp_path):
    """The stage_uploads census metrics are LOWER-is-better: an extra
    upload per tick (1.0 -> 2.0) must fail the gate even while every
    throughput metric improves."""
    bh = _load_bench_history()
    assert set(bh.floor_metrics({
        "uploads_per_tick_packed": 1.0, "dispatches_per_tick_packed": 1.0,
        "megastep_uploads_per_flush": 1.0, "value": 10.0, "spread": 0.1,
    })) == {"uploads_per_tick_packed", "dispatches_per_tick_packed",
            "megastep_uploads_per_flush"}
    _write_record(tmp_path, 1, {"value": 1000.0,
                                "uploads_per_tick_packed": 1.0,
                                "platform": "cpu"})
    _write_record(tmp_path, 2, {"value": 1500.0,
                                "uploads_per_tick_packed": 2.0,
                                "platform": "cpu"})
    assert bh.main(["--dir", str(tmp_path), "--threshold", "0.10"]) == 1
    # holding the floor passes
    _write_record(tmp_path, 3, {"value": 1500.0,
                                "uploads_per_tick_packed": 1.0,
                                "platform": "cpu"})
    assert bh.main(["--dir", str(tmp_path), "--threshold", "0.10"]) == 0


def test_bench_history_megastep_flatness_is_higher_is_better(tmp_path):
    """megastep_frames_per_dispatch ~ N when flushes stay fused; a fall
    (the program splitting into multiple dispatches) is the regression."""
    bh = _load_bench_history()
    _write_record(tmp_path, 1, {"megastep_frames_per_dispatch": 8.0,
                                "platform": "cpu"})
    _write_record(tmp_path, 2, {"megastep_frames_per_dispatch": 4.0,
                                "platform": "cpu"})
    assert bh.main(["--dir", str(tmp_path), "--threshold", "0.10"]) == 1
    _write_record(tmp_path, 3, {"megastep_frames_per_dispatch": 8.0,
                                "platform": "cpu"})
    assert bh.main(["--dir", str(tmp_path), "--threshold", "0.10"]) == 0


# ------------------------------------------------------------- lint mirror


def test_lint_phase_catalog_matches_package():
    spec = importlib.util.spec_from_file_location(
        "lint_imports",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "lint_imports.py"),
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    assert lint.PHASE_CATALOG == set(telemetry.PHASES)


def test_lint_check_phases_flags_misuse():
    import ast

    spec = importlib.util.spec_from_file_location(
        "lint_imports",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "lint_imports.py"),
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    bad = ast.parse(
        "with ps.phase('not_a_phase'):\n    pass\n"
        "t = ps.phase('net_poll')\n"
        "with ps.phase(name):\n    pass\n"
    )
    msgs = [m for _, m in lint.check_phases(bad)]
    assert any("not in the phase catalog" in m for m in msgs)
    assert any("must be a with-statement" in m for m in msgs)
    assert any("one string literal" in m for m in msgs)
    good = ast.parse("with ps.phase('wave_dispatch'):\n    pass\n")
    assert lint.check_phases(good) == []
