"""Determinism-analyzer tests: fixture corpus, interprocedural regression,
and the repo-at-HEAD-lints-clean gate.

The corpus under ``tests/lint_fixtures/`` carries a true-positive, a
suppressed, and a clean fixture per rule; these tests parameterize over
them so the analyzer is tested like product code.  The interprocedural
test is the acceptance criterion for BGT011: the two-deep forcing chain
(``tick -> grab -> pull``) that the old intra-function ``check_purity``
provably misses (it returns no problems for ``hot.py``) is flagged at the
call site with the full witness chain.

No jax import anywhere in this module — the analyzer is stdlib-only and
so are its tests.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from scripts.lint import RULES, run  # noqa: E402
from scripts.lint.config import Config  # noqa: E402
from scripts.lint.core import (  # noqa: E402
    load_baseline,
    parse_suppressions,
    write_baseline,
)
from scripts.lint.rules.docs import docs_rule_ids  # noqa: E402
from scripts.lint.rules.metrics import (  # noqa: E402
    collect_metric_names,
    docs_metric_names,
)
from scripts.lint.rules.phases import extract_phase_catalog  # noqa: E402
from scripts.lint.rules.purity import check_purity  # noqa: E402
from scripts.lint.rules.trace_kinds import (  # noqa: E402
    collect_trace_kinds,
    docs_trace_kinds,
)

FIXTURES = ROOT / "tests" / "lint_fixtures"

# string-literal copies of the ignore syntax are assembled from halves so
# the analyzer's line-based comment scan never sees the pattern in THIS
# file's source
_IG = "# bgt: " + "ignore"


def lint_paths(paths, **overrides):
    """Run the framework over explicit fixture paths with a quiet config
    (project-level cross-checks off unless a test turns them on)."""
    overrides.setdefault("project_checks", False)
    cfg = Config(**overrides)
    findings, _files = run([str(p) for p in paths], root=ROOT, config=cfg)
    return findings


def only(findings, rule_id):
    return [f for f in findings if f.rule == rule_id]


# -- file-scoped rule triples -------------------------------------------------

# (rule id, fixture stem, expected positive count)
TRIPLES = [
    ("BGT001", "bgt001", 1),
    ("BGT002", "bgt002", 1),
    ("BGT041", "bgt041", 3),
    ("BGT042", "bgt042", 3),
    ("BGT040", "models/bgt040", 3),
    ("BGT043", "models/bgt043", 3),
    ("BGT044", "models/bgt044", 3),
    ("BGT005", "bgt005", 1),
    ("BGT070", "bgt070", 4),
    ("BGT071", "models/bgt071", 5),
    ("BGT072", "models/bgt072", 2),
]


@pytest.mark.parametrize("rule_id,stem,n_pos", TRIPLES,
                         ids=[t[0] for t in TRIPLES])
def test_fixture_positive_fires(rule_id, stem, n_pos):
    hits = only(lint_paths([FIXTURES / f"{stem}_positive.py"]), rule_id)
    assert len(hits) == n_pos, [f.as_dict() for f in hits]
    assert all(not f.suppressed for f in hits)
    assert all(f.severity == "error" for f in hits)


@pytest.mark.parametrize("rule_id,stem,n_pos", TRIPLES,
                         ids=[t[0] for t in TRIPLES])
def test_fixture_suppression_respected(rule_id, stem, n_pos):
    hits = only(lint_paths([FIXTURES / f"{stem}_suppressed.py"]), rule_id)
    assert hits, "the suppressed fixture must still trip the rule"
    assert all(f.suppressed for f in hits)
    assert all(f.suppress_reason for f in hits), \
        "fixture suppressions all carry a justification"


@pytest.mark.parametrize("rule_id,stem,n_pos", TRIPLES,
                         ids=[t[0] for t in TRIPLES])
def test_fixture_clean_is_clean(rule_id, stem, n_pos):
    assert only(lint_paths([FIXTURES / f"{stem}_clean.py"]), rule_id) == []


def test_bgt003_syntax_error():
    hits = only(lint_paths([FIXTURES / "bgt003_positive.py"]), "BGT003")
    assert len(hits) == 1 and not hits[0].suppressed


def test_bgt004_unknown_suppression_id():
    hits = only(lint_paths([FIXTURES / "bgt004_positive.py"]), "BGT004")
    assert len(hits) == 1
    assert "BGT999" in hits[0].message
    assert only(lint_paths([FIXTURES / "bgt004_clean.py"]), "BGT004") == []


# -- hot-loop purity: intra-function (BGT010) ---------------------------------

PURITY_CFG = dict(
    purity_allow={"lint_fixtures/purity/hot.py": {"sanctioned"}},
)


def test_bgt010_positive_suppressed_and_allowlisted():
    findings = lint_paths([FIXTURES / "purity" / "hot.py"], **PURITY_CFG)
    hits = only(findings, "BGT010")
    assert len(hits) == 2, [f.as_dict() for f in hits]
    live = [f for f in hits if not f.suppressed]
    assert len(live) == 1 and "tick" in live[0].message
    gone = [f for f in hits if f.suppressed]
    assert len(gone) == 1 and "also_bad" in gone[0].message
    # the allowlisted funnel's own .device_get is never flagged
    src = (FIXTURES / "purity" / "hot.py").read_text().splitlines()
    sanction_line = next(i for i, ln in enumerate(src, 1) if ".device_get" in ln)
    assert all(f.line != sanction_line for f in hits)


# -- hot-loop purity: interprocedural (BGT011) --------------------------------


def _interproc_paths(pkg):
    d = FIXTURES / pkg
    return [d / "__init__.py", d / "hot.py", d / "helpers.py", d / "leaf.py"]


def _interproc_cfg(pkg):
    return dict(
        package_dir=f"tests/lint_fixtures/{pkg}",
        purity_allow={f"lint_fixtures/{pkg}/hot.py": set()},
    )


def test_bgt011_catches_two_deep_chain_the_old_check_misses():
    """THE acceptance criterion: hot.py has no forcing syntax, so the old
    intra-function rule is blind to it; the call graph flags the call site
    with the full tick -> grab -> pull witness chain."""
    import ast

    hot = FIXTURES / "interproc" / "hot.py"
    assert check_purity(ast.parse(hot.read_text()), allow=set()) == [], \
        "the old intra-function check must provably miss this fixture"

    findings = lint_paths(_interproc_paths("interproc"),
                          **_interproc_cfg("interproc"))
    hits = only(findings, "BGT011")
    assert len(hits) == 1, [f.as_dict() for f in findings]
    f = hits[0]
    assert f.path.endswith("interproc/hot.py") and not f.suppressed
    # the message carries the whole chain down to the direct forcing site
    for fragment in ("tick", "grab", "pull", "block_until_ready", "leaf.py"):
        assert fragment in f.message, f.message
    # and no BGT010 anywhere: there is no forcing syntax in the hot file
    assert only(findings, "BGT010") == []


def test_bgt011_seed_line_suppression_sanctions_every_caller():
    findings = lint_paths(_interproc_paths("interproc_suppressed"),
                          **_interproc_cfg("interproc_suppressed"))
    assert only(findings, "BGT011") == [], \
        "suppressing at the seed (forcing) line must clear the whole chain"


def test_bgt011_clean_chain_is_clean():
    findings = lint_paths(_interproc_paths("interproc_clean"),
                          **_interproc_cfg("interproc_clean"))
    assert only(findings, "BGT011") == []


def test_bgt011_packed_staging_chain_flagged():
    """The packed single-upload hot path's exact shape: stage_packed_rows
    -> commit_staging -> upload, with the forcing (the synchronous staging
    commit that makes persistent-buffer reuse safe) two calls deep.  The
    analyzer must surface it at the driver call site with the full chain —
    the real bevy_ggrs_tpu/utils/staging.py commit is sanctioned at its
    seed line, and this fixture is what proves that sanction is load-
    bearing rather than the chain being invisible."""
    import ast

    hot = FIXTURES / "interproc_packed" / "hot.py"
    assert check_purity(ast.parse(hot.read_text()), allow=set()) == [], \
        "the intra-function check must provably miss the staging chain"

    findings = lint_paths(_interproc_paths("interproc_packed"),
                          **_interproc_cfg("interproc_packed"))
    hits = only(findings, "BGT011")
    assert len(hits) == 1, [f.as_dict() for f in findings]
    f = hits[0]
    assert f.path.endswith("interproc_packed/hot.py") and not f.suppressed
    for fragment in ("stage_packed_rows", "commit_staging", "upload",
                     "block_until_ready", "leaf.py"):
        assert fragment in f.message, f.message


def test_bgt011_packed_staging_clean_chain_is_clean():
    findings = lint_paths(_interproc_paths("interproc_packed_clean"),
                          **_interproc_cfg("interproc_packed_clean"))
    assert only(findings, "BGT011") == []


# -- stale-allowlist meta-lint (BGT012) ---------------------------------------


def test_bgt012_flags_rotted_allowlist_entry():
    findings = lint_paths(
        [FIXTURES / "purity" / "hot.py"],
        purity_allow={"lint_fixtures/purity/hot.py": {"sanctioned", "ghost_fn"}},
        project_checks=True,
    )
    hits = only(findings, "BGT012")
    assert len(hits) == 1 and "ghost_fn" in hits[0].message
    # existing entries are not flagged
    assert "sanctioned" not in hits[0].message


def test_bgt012_flags_missing_target_file():
    findings = lint_paths(
        [FIXTURES / "purity" / "hot.py"],
        purity_allow={"lint_fixtures/purity/gone.py": {"whatever"}},
        project_checks=True,
    )
    hits = only(findings, "BGT012")
    assert len(hits) == 1 and "does not exist" in hits[0].message


# -- tick-phase discipline (BGT020/021/022) -----------------------------------

PHASES_CFG = dict(
    phases_module="tests/lint_fixtures/phases/phases.py",
    phase_files=("lint_fixtures/phases/driver.py",),
    purity_allow={},
    project_checks=True,  # the reverse (stale-catalog) check needs it
)


def test_phase_rules_on_fixture_driver():
    findings = lint_paths([FIXTURES / "phases" / "driver.py"], **PHASES_CFG)
    bgt020 = only(findings, "BGT020")
    assert len(bgt020) == 2
    assert any("typo_phase" in f.message for f in bgt020)
    assert any("one string literal" in f.message for f in bgt020)
    bgt021 = only(findings, "BGT021")
    assert len(bgt021) == 1 and "checksum" in bgt021[0].message
    stale = only(findings, "BGT022")
    assert len(stale) == 1 and "never_timed" in stale[0].message


def test_phase_reverse_check_skipped_on_partial_corpus():
    """A partial-path run must not call a phase stale just because the
    driver that times it was not linted."""
    cfg = dict(PHASES_CFG)
    cfg["phase_files"] = ("lint_fixtures/phases/driver.py",
                          "lint_fixtures/phases/other_driver.py")
    findings = lint_paths([FIXTURES / "phases" / "driver.py"], **cfg)
    assert only(findings, "BGT022") == []


def test_extract_phase_catalog(tmp_path):
    cat = extract_phase_catalog(FIXTURES / "phases" / "phases.py")
    assert cat == {"inputs", "advance", "checksum", "never_timed"}
    assert extract_phase_catalog(tmp_path / "missing.py") is None
    bad = tmp_path / "dynamic.py"
    bad.write_text("PHASES = tuple(x for x in names)\n")
    assert extract_phase_catalog(bad) is None


def test_bgt022_on_unextractable_catalog(tmp_path):
    findings = lint_paths(
        [FIXTURES / "bgt001_clean.py"],
        phases_module="tests/lint_fixtures/phases/no_such_catalog.py",
        purity_allow={},
        project_checks=True,
    )
    hits = only(findings, "BGT022")
    assert len(hits) == 1 and "AST literal parsing" in hits[0].message


def test_real_catalog_extracts_and_matches_package():
    """The real telemetry/phases.py catalog must stay AST-extractable —
    that is the contract replacing the old hand-mirrored copy."""
    cat = extract_phase_catalog(ROOT / "bevy_ggrs_tpu/telemetry/phases.py")
    assert cat and "session_step" in cat


# -- metric and rule docs cross-checks (BGT03x / BGT05x) ----------------------


def test_metric_name_collection_and_docs_parse():
    import ast

    tree = ast.parse(
        "reg.counter('ticks_total')\n"
        "reg.bind_gauge('depth_now', lobby=3)\n"
        "telemetry.count('rollbacks_total')\n"
        "other.count('not_a_metric')\n"  # non-telemetry receiver: ignored
    )
    assert collect_metric_names(tree) == {
        "ticks_total", "depth_now", "rollbacks_total",
    }
    md = (
        "| metric | labels | meaning |\n"
        "|--------|--------|---------|\n"
        "| `ticks_total` | - | ticks |\n"
        "\nprose mentioning `not_in_a_table`\n"
    )
    assert docs_metric_names(md) == {"ticks_total"}


def test_bgt031_skipped_on_partial_corpus():
    """A single-file run must not call every documented metric stale just
    because the files registering them were not linted (the same guard as
    the BGT022 reverse check)."""
    findings = lint_paths([FIXTURES / "bgt001_clean.py"],
                          purity_allow={}, project_checks=True)
    assert only(findings, "BGT031") == []


def test_bgt030_and_bgt031_on_synthetic_tree(tmp_path):
    """Both directions fire against a synthetic repo root whose corpus IS
    complete (the package __init__ is linted)."""
    pkg = tmp_path / "bevy_ggrs_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(
        "def setup(reg):\n"
        "    reg.counter('undocumented_total')\n"
        "    reg.gauge('documented_now')\n"
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| metric | labels | meaning |\n"
        "|--------|--------|---------|\n"
        "| `documented_now` | - | fine |\n"
        "| `ghost_metric` | - | stale |\n"
    )
    cfg = Config(purity_allow={}, project_checks=True,
                 phases_module="no/such/phases.py")
    findings, _files = run([str(pkg / "__init__.py")], root=tmp_path,
                           config=cfg)
    b30 = only(findings, "BGT030")
    assert len(b30) == 1 and "undocumented_total" in b30[0].message
    b31 = only(findings, "BGT031")
    assert len(b31) == 1 and "ghost_metric" in b31[0].message


def test_trace_kind_collection_and_docs_parse():
    import ast

    tree = ast.parse(
        "telemetry.record('rollback', to_frame=3)\n"
        "fr.record('tick', frame=1)\n"
        "telemetry.record(kind, x=1)\n"       # dynamic: not collectable
        "recorder.append('not_a_record')\n"   # not a .record call
        "telemetry.record('Not_A_Kind')\n"    # fails the kind regex
    )
    assert collect_trace_kinds(tree) == [("rollback", 1), ("tick", 2)]
    md = (
        "| kind | source | meaning |\n"
        "|------|--------|---------|\n"
        "| `rollback` | runner | blamed rollback |\n"
        "\nprose mentioning `not_in_a_table`\n"
        "| metric | labels | meaning |\n"
        "|--------|--------|---------|\n"
        "| `ticks_total` | - | a METRIC table, not a kind table |\n"
    )
    assert docs_trace_kinds(md) == {"rollback"}


BGT032_CFG = dict(purity_allow={}, project_checks=True,
                  phases_module="no/such/phases.py")


def test_bgt032_fixture_triple():
    """The fixture triple runs against the REAL docs catalog (fixtures are
    not tests to this pass), so the positive's private kind fires and the
    clean fixture's catalogued ``rollback`` does not."""
    pos = only(lint_paths([FIXTURES / "bgt032_positive.py"], **BGT032_CFG),
               "BGT032")
    assert len(pos) == 1 and not pos[0].suppressed
    assert "zzz_private_event" in pos[0].message
    sup = only(lint_paths([FIXTURES / "bgt032_suppressed.py"], **BGT032_CFG),
               "BGT032")
    assert len(sup) == 1 and sup[0].suppressed and sup[0].suppress_reason
    assert only(lint_paths([FIXTURES / "bgt032_clean.py"], **BGT032_CFG),
                "BGT032") == []


def test_bgt033_skipped_on_partial_corpus():
    findings = lint_paths([FIXTURES / "bgt001_clean.py"],
                          purity_allow={}, project_checks=True)
    assert only(findings, "BGT033") == []


def test_bgt032_and_bgt033_on_synthetic_tree(tmp_path):
    """Both directions against a synthetic root with a complete corpus."""
    pkg = tmp_path / "bevy_ggrs_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(
        "def emit(telemetry):\n"
        "    telemetry.record('undocumented_kind', frame=1)\n"
        "    telemetry.record('documented_kind', frame=2)\n"
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| kind | source | meaning |\n"
        "|------|--------|---------|\n"
        "| `documented_kind` | pkg | fine |\n"
        "| `ghost_kind` | nowhere | stale |\n"
    )
    cfg = Config(purity_allow={}, project_checks=True,
                 phases_module="no/such/phases.py")
    findings, _files = run([str(pkg / "__init__.py")], root=tmp_path,
                           config=cfg)
    b32 = only(findings, "BGT032")
    assert len(b32) == 1 and "undocumented_kind" in b32[0].message
    assert b32[0].line == 2  # reported at the emission line
    b33 = only(findings, "BGT033")
    assert len(b33) == 1 and "ghost_kind" in b33[0].message


def test_rule_docs_catalog_matches_registry_exactly():
    """docs/static-analysis.md documents exactly the registered rule set —
    the human-readable half of the BGT050/BGT051 gate."""
    ids = docs_rule_ids((ROOT / "docs/static-analysis.md").read_text())
    assert ids == set(RULES)


# -- concurrency & transfer races (BGT06x) ------------------------------------

# same triple contract as TRIPLES, but each rule needs its fixture files
# pulled into the analyzer's scope (concurrency_modules / package_dir)
CONCUR_TRIPLES = [
    ("BGT060", "bgt060", 1),
    ("BGT061", "bgt061", 2),
    ("BGT062", "bgt062", 1),
    ("BGT063", "bgt063", 2),
]


def _concur_cfg(stem):
    if stem == "bgt063":
        return dict(package_dir="tests/lint_fixtures")
    return dict(concurrency_modules=(
        f"{stem}_positive.py", f"{stem}_suppressed.py", f"{stem}_clean.py",
    ))


@pytest.mark.parametrize("rule_id,stem,n_pos", CONCUR_TRIPLES,
                         ids=[t[0] for t in CONCUR_TRIPLES])
def test_concurrency_fixture_positive_fires(rule_id, stem, n_pos):
    hits = only(lint_paths([FIXTURES / f"{stem}_positive.py"],
                           **_concur_cfg(stem)), rule_id)
    assert len(hits) == n_pos, [f.as_dict() for f in hits]
    assert all(not f.suppressed for f in hits)
    assert all(f.severity == "error" for f in hits)


@pytest.mark.parametrize("rule_id,stem,n_pos", CONCUR_TRIPLES,
                         ids=[t[0] for t in CONCUR_TRIPLES])
def test_concurrency_fixture_suppression_respected(rule_id, stem, n_pos):
    hits = only(lint_paths([FIXTURES / f"{stem}_suppressed.py"],
                           **_concur_cfg(stem)), rule_id)
    assert hits, "the suppressed fixture must still trip the rule"
    assert all(f.suppressed for f in hits)
    assert all(f.suppress_reason for f in hits)


@pytest.mark.parametrize("rule_id,stem,n_pos", CONCUR_TRIPLES,
                         ids=[t[0] for t in CONCUR_TRIPLES])
def test_concurrency_fixture_clean_is_clean(rule_id, stem, n_pos):
    assert only(lint_paths([FIXTURES / f"{stem}_clean.py"],
                           **_concur_cfg(stem)), rule_id) == []


def test_bgt060_declared_thread_roots_engage_the_analysis():
    """No ``Thread(...)`` in the module: the analysis is vacuous until the
    entry point is declared in config.THREAD_ROOTS (the telemetry
    registry's situation — its scrape thread lives in scripts/)."""
    path = FIXTURES / "bgt060_roots.py"
    scope = dict(concurrency_modules=("bgt060_roots.py",))
    assert only(lint_paths([path], **scope), "BGT060") == []
    hits = only(lint_paths(
        [path], thread_roots={"bgt060_roots.py": {"Series.bump"}}, **scope,
    ), "BGT060")
    assert len(hits) == 1, [f.as_dict() for f in hits]
    assert "_vals" in hits[0].message


def test_bgt060_real_registry_locking_is_load_bearing(tmp_path):
    """Strip the metrics registry's ``with self._reg._lock:`` blocks and
    BGT060 must fire — proof the rule watches the real control plane and
    the repo's locking is what keeps HEAD clean."""
    src = (ROOT / "bevy_ggrs_tpu/telemetry/metrics.py").read_text()
    assert "with self._reg._lock:" in src
    stripped = src.replace("with self._reg._lock:", "if True:")
    mod = tmp_path / "metrics_unlocked.py"
    mod.write_text(stripped)
    from scripts.lint.config import THREAD_ROOTS
    hits = only(lint_paths(
        [mod],
        concurrency_modules=("metrics_unlocked.py",),
        thread_roots={
            "metrics_unlocked.py":
                THREAD_ROOTS["bevy_ggrs_tpu/telemetry/metrics.py"],
        },
    ), "BGT060")
    assert hits, "unlocked cross-thread registry writes must be flagged"
    assert only(lint_paths(
        [ROOT / "bevy_ggrs_tpu/telemetry/metrics.py"]), "BGT060") == []


def _transfer_paths(pkg):
    d = FIXTURES / pkg
    return [d / "__init__.py", d / "driver.py", d / "helper.py"]


def test_bgt063_interprocedural_chain_flagged():
    """The reused staging buffer flows into a helper that uploads it
    un-barriered one call away — flagged at the driver's call site with
    the chain down to the direct device_put."""
    findings = lint_paths(_transfer_paths("transfer"),
                          package_dir="tests/lint_fixtures/transfer")
    hits = only(findings, "BGT063")
    assert len(hits) == 1, [f.as_dict() for f in findings]
    f = hits[0]
    assert f.path.endswith("transfer/driver.py") and not f.suppressed
    for fragment in ("flush", "self.buf", "upload_rows", "un-barriered",
                     "helper.py"):
        assert fragment in f.message, f.message


def test_bgt063_seed_suppression_sanctions_every_caller():
    findings = lint_paths(
        _transfer_paths("transfer_suppressed"),
        package_dir="tests/lint_fixtures/transfer_suppressed",
    )
    assert only(findings, "BGT063") == [], \
        "suppressing at the seed (upload) line must clear the chain"
    # ...and the seed comment is load-bearing, not stale (BGT005)
    assert only(findings, "BGT005") == []


def test_bgt063_clean_chain_is_clean():
    findings = lint_paths(_transfer_paths("transfer_clean"),
                          package_dir="tests/lint_fixtures/transfer_clean")
    assert only(findings, "BGT063") == []


# -- incremental (--changed) slice --------------------------------------------


def test_expand_dependents_pulls_in_reverse_importers():
    from scripts.lint.incremental import expand_dependents

    out = expand_dependents({"bevy_ggrs_tpu/fleet/protocol.py"}, ROOT)
    assert "bevy_ggrs_tpu/fleet/protocol.py" in out
    # worker and scheduler import the protocol module; linting them is what
    # keeps cross-file rules honest on the slice
    assert "bevy_ggrs_tpu/fleet/worker.py" in out
    assert "bevy_ggrs_tpu/fleet/scheduler.py" in out


def test_expand_dependents_ignores_non_corpus_files():
    from scripts.lint.incremental import expand_dependents

    assert expand_dependents(
        {"docs/observability.md", "no/such/file.py"}, ROOT) == []


def test_changed_slice_agrees_with_full_run():
    """On the files it lints, a --changed slice must report exactly the
    full run's findings, minus the whole-repo reverse checks the partial
    corpus structurally cannot support."""
    from scripts.lint.incremental import expand_dependents

    PARTIAL_SKIPPED = {"BGT005", "BGT022", "BGT031", "BGT033", "BGT073"}
    slice_paths = expand_dependents(
        {"bevy_ggrs_tpu/fleet/protocol.py"}, ROOT)
    assert slice_paths

    def key(fs, paths):
        return sorted(
            (f.rule, f.path, f.line, f.suppressed)
            for f in fs
            if f.path in paths and f.rule not in PARTIAL_SKIPPED
        )

    sliced, _ = run(slice_paths, root=ROOT,
                    config=Config(partial_corpus=True))
    full, _ = run(None, root=ROOT, config=Config())
    in_slice = set(slice_paths)
    assert key(sliced, in_slice) == key(full, in_slice)


def test_changed_cli_exits_zero():
    res = subprocess.run(
        [sys.executable, "-m", "scripts.lint", "--changed"],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "--changed" in res.stdout


# -- suppression parsing ------------------------------------------------------


def test_parse_suppressions_same_line_and_block():
    src = (
        "x = compute()  " + _IG + "[BGT001]: same-line reason\n"
        + _IG + "[BGT042]: a standalone comment covers\n"
        "# the whole block below it\n"
        "y = sum(stuff)\n"
    )
    covers, unknown = parse_suppressions(src)
    assert unknown == []
    assert covers[1]["BGT001"] == "same-line reason"
    # the standalone comment on line 2 covers lines 2-4 (through the block
    # to the first code line)
    for line in (2, 3, 4):
        assert covers[line]["BGT042"] == "a standalone comment covers"
    assert "BGT042" not in covers.get(1, {})


def test_parse_suppressions_unknown_id_reported():
    src = "x = 1  " + _IG + "[BGT998, BGT001]\n"
    covers, unknown = parse_suppressions(src)
    assert unknown == [(1, "BGT998")]
    assert covers[1] == {"BGT001": ""}


# -- baseline round-trip ------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    findings = lint_paths([FIXTURES / "bgt041_positive.py"])
    live = [f for f in findings if not f.suppressed]
    assert live
    bl = tmp_path / "baseline.json"
    write_baseline(bl, findings)
    known = load_baseline(bl)
    assert {f.fingerprint() for f in live} == known
    # fingerprints are line-number-free on purpose
    assert all(len(fp) == 3 for fp in known)


# -- the gate itself ----------------------------------------------------------


def test_repo_at_head_lints_clean_and_json_report(tmp_path):
    """`python -m scripts.lint` exits 0 at HEAD and the JSON report has the
    documented shape — the exact invocation scripts/check.sh gates on."""
    report_path = tmp_path / "lint_report.json"
    res = subprocess.run(
        [sys.executable, "-m", "scripts.lint", "--json", str(report_path)],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    report = json.loads(report_path.read_text())
    assert report["version"] == 1
    assert report["counts"]["errors"] == 0
    assert report["counts"]["findings"] == 0
    assert {r["id"] for r in report["rules"]} == set(RULES)
    for f in report["findings"]:  # only suppressed ones remain at HEAD
        assert f["suppressed"] and f["suppress_reason"]
        assert {"rule", "name", "severity", "path", "line", "message"} \
            <= set(f)


def test_shim_cli_still_works():
    """`python scripts/lint_imports.py` (the pre-framework invocation)
    delegates to the framework with the same exit semantics."""
    res = subprocess.run(
        [sys.executable, "scripts/lint_imports.py"],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "lint:" in res.stdout


# -- recompilation & engine drift (BGT07x) ------------------------------------


def _shape_chain_paths(pkg):
    d = FIXTURES / pkg
    return [d / "__init__.py", d / "digest.py",
            d / "ops" / "__init__.py", d / "ops" / "hot.py"]


def test_bgt071_chain_flagged_at_sim_call_site():
    """The interprocedural acceptance shape: ops/hot.py has no hazard
    syntax, the jnp.stack over a dynamic sequence lives in non-sim
    digest.py — the chain finding lands at the sim-scope call site with
    the full witness path down to the seed."""
    findings = lint_paths(_shape_chain_paths("shape_chain"),
                          package_dir="tests/lint_fixtures/shape_chain")
    hits = only(findings, "BGT071")
    assert len(hits) == 1, [f.as_dict() for f in findings]
    f = hits[0]
    assert f.path.endswith("shape_chain/ops/hot.py") and not f.suppressed
    for fragment in ("tick", "fold_parts", "digest.py", "stack"):
        assert fragment in f.message, f.message


def test_bgt071_seed_sanction_clears_every_caller():
    findings = lint_paths(
        _shape_chain_paths("shape_chain_suppressed"),
        package_dir="tests/lint_fixtures/shape_chain_suppressed")
    assert only(findings, "BGT071") == [], \
        "suppressing at the seed (hazard) line must clear the whole chain"


# BGT073 twin pairs share the two fixture halves; each test declares its
# own map (the config is the rule's input surface)
_TWINS = "tests/lint_fixtures/twins"
_SOLO = f"{_TWINS}/solo.py::Solo"
_BATCH = f"{_TWINS}/batched.py::Batched"


def _twin_findings(twin_map):
    d = FIXTURES / "twins"
    return only(lint_paths([d / "solo.py", d / "batched.py"],
                           twin_map=twin_map, twins_json=None), "BGT073")


def test_bgt073_sync_pair_in_sync_is_clean():
    # different local names AND a different telemetry label string: both
    # must normalize away
    assert _twin_findings(
        ((f"{_SOLO}.drain", f"{_BATCH}.drain", "sync", "queue drain"),)
    ) == []


def test_bgt073_declared_sync_pair_drifted_fires():
    hits = _twin_findings(
        ((f"{_SOLO}.tally", f"{_BATCH}.tally", "sync", "input tally"),))
    assert len(hits) == 1 and hits[0].path.endswith("twins/solo.py")
    assert "declared-sync twin drifted" in hits[0].message
    assert "similarity" in hits[0].message


def test_bgt073_declared_drift_pair_converged_fires():
    hits = _twin_findings(
        ((f"{_SOLO}.ping", f"{_BATCH}.ping", "drift", "clock probe"),))
    assert len(hits) == 1
    assert "declared-drift twin converged" in hits[0].message


def test_bgt073_map_rot_fires():
    hits = _twin_findings(
        ((f"{_SOLO}.gone", f"{_BATCH}.drain", "sync", "rotted ref"),))
    assert len(hits) == 1
    assert "twin map rot" in hits[0].message and "gone" in hits[0].message


def test_bgt073_partial_corpus_is_silent():
    d = FIXTURES / "twins"
    findings = lint_paths(
        [d / "solo.py", d / "batched.py"],
        twin_map=((f"{_SOLO}.tally", f"{_BATCH}.tally", "sync", "t"),),
        twins_json=None, partial_corpus=True)
    assert only(findings, "BGT073") == []


def test_twins_json_inventory_written(tmp_path):
    """Full-project-run shape: project_checks on + twins_json set writes
    the ROADMAP-5 work-list with per-pair status and similarity."""
    import shutil

    d = tmp_path / "twins"
    d.mkdir()
    for name in ("solo.py", "batched.py"):
        shutil.copy(FIXTURES / "twins" / name, d / name)
    cfg = Config(
        project_checks=True, twins_json="out_twins.json",
        metric_docs="docs/observability.md",
        rule_docs="docs/static-analysis.md",
        twin_map=(
            ("twins/solo.py::Solo.drain", "twins/batched.py::Batched.drain",
             "sync", "queue drain"),
            ("twins/solo.py::Solo.tally", "twins/batched.py::Batched.tally",
             "drift", "input tally"),
        ),
    )
    run([str(d / "solo.py"), str(d / "batched.py")],
        root=tmp_path, config=cfg)
    payload = json.loads((tmp_path / "out_twins.json").read_text())
    assert payload["version"] == 1 and payload["drifted"] == 1
    by_solo = {p["solo"]: p for p in payload["pairs"]}
    assert by_solo["twins/solo.py::Solo.drain"]["status"] == "in_sync"
    assert by_solo["twins/solo.py::Solo.drain"]["similarity"] == 1.0
    tally = by_solo["twins/solo.py::Solo.tally"]
    assert tally["status"] == "drifted" and 0 < tally["similarity"] < 1
    assert tally["solo_lines"] >= 1 and tally["batched_lines"] >= 1


def test_repo_twin_map_references_resolve_and_inventory_is_emitted():
    """The REAL twin map: every declared pair resolves at HEAD (no rot)
    and the repo-root LINT_twins.json inventory carries >= 5 pairs."""
    findings, _files = run(None, root=ROOT, config=Config())
    assert only([f for f in findings if not f.suppressed], "BGT073") == []
    payload = json.loads((ROOT / "LINT_twins.json").read_text())
    assert len(payload["pairs"]) >= 5
    assert all(p["status"] in ("in_sync", "drifted") for p in payload["pairs"])


# -- content-hash result cache (--cache) --------------------------------------


def _norm_findings(findings):
    return sorted(
        (f.rule, f.path, f.line, f.message, f.suppressed) for f in findings
    )


def test_cache_cold_and_warm_agree_exactly_with_full_run(tmp_path):
    """Unlike --changed (which drops whole-repo reverse checks), --cache
    must reproduce the full run's findings EXACTLY — whole-corpus rules
    run fresh and per-file results replay from the manifest."""
    from scripts.lint.cache import cached_run

    cache = tmp_path / "cache.json"
    cold, _, stats = cached_run(ROOT, cache_path=cache)
    assert stats["mode"] == "rebuild" and stats["reused"] == 0
    warm, _, stats = cached_run(ROOT, cache_path=cache)
    assert stats["mode"] == "warm" and stats["analyzed"] == 0
    assert stats["reused"] > 0
    plain, _ = run(None, root=ROOT, config=Config())
    assert _norm_findings(warm) == _norm_findings(plain)
    assert _norm_findings(cold) == _norm_findings(plain)


def _mini_repo(tmp_path):
    pkg = tmp_path / "bevy_ggrs_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "util.py").write_text("def helper():\n    return 1\n")
    (pkg / "main.py").write_text(
        "import os\n\nfrom bevy_ggrs_tpu.util import helper\n\n\n"
        "def tick():\n    return helper()\n")
    return pkg


def test_cache_slices_on_mutation_and_still_agrees(tmp_path):
    """Mutating one file re-analyzes its bidirectional import closure
    (the file plus its importer) and the merged result matches a fresh
    full run; adding a file falls back to a rebuild."""
    from scripts.lint.cache import cached_run

    pkg = _mini_repo(tmp_path)
    cfg = Config(project_checks=False)
    cache = tmp_path / "cache.json"
    _f, _x, stats = cached_run(tmp_path, config=cfg, cache_path=cache)
    assert stats["mode"] == "rebuild"

    (pkg / "util.py").write_text("import sys\n\n\ndef helper():\n    return 1\n")
    warm, _x, stats = cached_run(tmp_path, config=cfg, cache_path=cache)
    assert stats["mode"] == "warm"
    assert stats["analyzed"] >= 2, "importer main.py must re-enter the slice"
    plain, _x = run(None, root=tmp_path, config=cfg)
    assert _norm_findings(warm) == _norm_findings(plain)
    assert any(f.rule == "BGT001" and f.path.endswith("util.py")
               for f in warm), "fresh finding on the mutated file"

    (pkg / "extra.py").write_text("X = 1\n")
    _f, _x, stats = cached_run(tmp_path, config=cfg, cache_path=cache)
    assert stats["mode"] == "rebuild", "a changed file SET rebuilds"


def test_cache_cli_timings_and_soft_time_budget(tmp_path):
    """--cache --timings prints the per-family wall-time table; an
    exceeded --time-budget warns but stays a soft gate (exit 0), and
    --time-budget-hard turns it into a failure."""
    res = subprocess.run(
        [sys.executable, "-m", "scripts.lint", "--cache", "--timings",
         "--time-budget", "0.001"],
        cwd=ROOT, capture_output=True, text=True, timeout=180,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "lint: cache" in res.stdout
    assert "lint-timing: total" in res.stdout
    assert "WARNING" in res.stdout and "soft" in res.stdout

    from scripts.lint.core import main as lint_main
    rc = lint_main(["--cache", "--time-budget", "0.001",
                    "--time-budget-hard"])
    assert rc == 1
