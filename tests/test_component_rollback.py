"""Strategy-parametrized component rollback tests — port of
/root/reference/tests/component_rollback.rs:36-231: every registered strategy
must round-trip component values through continuous SyncTest resimulation
with the value==frame-count invariant."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from bevy_ggrs_tpu import (
    App,
    CloneStrategy,
    CopyStrategy,
    GgrsRunner,
    QuantizeStrategy,
    ReflectStrategy,
    Strategy,
    SyncTestSession,
)
from bevy_ggrs_tpu.snapshot import active_mask, spawn


def make_app(strategy, dtype=jnp.int32):
    app = App(num_players=1, capacity=4, input_shape=(), input_dtype=np.uint8)
    app.rollback_component("v", (), dtype, checksum=(dtype == jnp.int32),
                           strategy=strategy)

    def step(world, ctx):
        m = active_mask(world) & world.has["v"]
        one = jnp.asarray(1, world.comps["v"].dtype)
        return dataclasses.replace(
            world,
            comps={"v": jnp.where(m, world.comps["v"] + one, world.comps["v"])},
        )

    def setup(world):
        world, _ = spawn(app.reg, world, {"v": 0})
        return world

    app.set_step(step)
    app.set_setup(setup)
    return app


def run(app, ticks=15, check_distance=3):
    session = SyncTestSession(
        num_players=1, input_shape=(), input_dtype=np.uint8,
        check_distance=check_distance,
    )
    mismatches = []
    runner = GgrsRunner(app, session, on_mismatch=mismatches.append)
    for _ in range(ticks):
        runner.tick()
    return runner, mismatches


@pytest.mark.parametrize(
    "strategy", [CopyStrategy, CloneStrategy, ReflectStrategy],
    ids=["copy", "clone", "reflect"],
)
def test_value_equals_frame_count(strategy):
    runner, mismatches = run(make_app(strategy))
    assert mismatches == []
    assert int(runner.world.comps["v"][0]) == 15


def test_custom_store_load_strategy():
    # value stored doubled, halved on load — the Strategy bijection contract
    # (/root/reference/src/snapshot/strategy.rs:22-40)
    s = Strategy(store=lambda a: a * 2, load=lambda a: a // 2)
    runner, mismatches = run(make_app(s))
    assert mismatches == []
    assert int(runner.world.comps["v"][0]) == 15


def test_quantize_strategy_float_state():
    # bf16 ring storage with the quantized column CHECKSUMMED: the stored
    # representation is canonical (advance round-trips store->load every
    # frame, ops/resim.advance), so the live pass and a resim from a
    # restored snapshot are bit-identical and SyncTest stays clean.
    # Regression: without the round-trip this mismatches by construction
    # (found by the particles --quantize synctest).
    app = App(num_players=1, capacity=4, input_shape=(), input_dtype=np.uint8)
    app.rollback_component("x", (), jnp.float32, strategy=QuantizeStrategy(),
                           checksum=True)
    app.rollback_component("n", (), jnp.int32, checksum=True)

    def step(world, ctx):
        m = active_mask(world)
        return dataclasses.replace(
            world,
            comps={
                "x": jnp.where(m & world.has["x"], world.comps["x"] * 1.001 + 0.01,
                               world.comps["x"]),
                "n": jnp.where(m & world.has["n"], world.comps["n"] + 1,
                               world.comps["n"]),
            },
        )

    def setup(world):
        # 0.3 is NOT bf16-exact: pins the initial-state canonicalization
        # (frame-0 snapshot must restore exactly the live starting state)
        world, _ = spawn(app.reg, world, {"x": 0.3, "n": 0})
        return world

    app.set_step(step)
    app.set_setup(setup)
    runner, mismatches = run(app)
    assert mismatches == []
    assert int(runner.world.comps["n"][0]) == 15
    assert float(runner.world.comps["x"][0]) > 0.3


def test_multiple_disjoint_component_types():
    # 3 types x N entities (the criterion bench shape, benches/bench.rs:69-95)
    app = App(num_players=1, capacity=64, input_shape=(), input_dtype=np.uint8)
    for name in ("a", "b", "c"):
        app.rollback_component(name, (), jnp.int32, checksum=True)

    def step(world, ctx):
        comps = dict(world.comps)
        m = active_mask(world)
        for name in ("a", "b", "c"):
            comps[name] = jnp.where(
                m & world.has[name], comps[name] + 1, comps[name]
            )
        return dataclasses.replace(world, comps=comps)

    def setup(world):
        for i in range(20):
            world, _ = spawn(app.reg, world, {("a", "b", "c")[i % 3]: 0})
        return world

    app.set_step(step)
    app.set_setup(setup)
    runner, mismatches = run(app, ticks=12)
    assert mismatches == []
    # only entities having each component advanced it
    for i, name in enumerate(("a", "b", "c")):
        col = runner.world.comps[name]
        has = runner.world.has[name]
        assert int(col[i]) == 12  # entity i has component name
        assert bool(has[i])
