"""Real-network multi-node-on-one-host tests: full apps with real loopback
UDP sockets in one process, interleaved updates — the reference's
tests/p2p.rs harness pattern (SURVEY §4.4).  Asserts the remote player's
input visibly moves their entity on the other peer, confirmed frames
advance, snapshots prune, peers stay checksum-identical, and the
P2P+spectator trio works."""

import time


from bevy_ggrs_tpu import (
    DesyncDetection,
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
    UdpNonBlockingSocket,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.events import DesyncDetected, Synchronized
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


def make_pair(input_delay=2, desync=DesyncDetection.OFF, max_prediction=8):
    """Two box_game apps + P2P sessions over loopback UDP (ephemeral ports)."""
    socks = [UdpNonBlockingSocket(0, host="127.0.0.1") for _ in range(2)]
    addrs = [("127.0.0.1", s.local_addr[1]) for s in socks]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(input_delay)
            .with_max_prediction_window(max_prediction)
            .with_desync_detection_mode(desync)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, addrs[1 - i])
        )
        session = b.start_p2p_session(socks[i])

        def read_inputs(handles, i=i):
            # each peer holds a distinct direction
            key = {0: "right", 1: "up"}[i]
            return {h: box_game.keys_to_input(**{key: True}) for h in handles}

        runners.append(GgrsRunner(app, session, read_inputs=read_inputs))
    return runners, socks


def interleave(runners, ticks, dt=DT, sleep=0.0):
    for _ in range(ticks):
        for r in runners:
            r.update(dt)
        if sleep:
            time.sleep(sleep)


def test_p2p_smoke_remote_input_moves_entity():
    runners, socks = make_pair()
    # sync phase: updates with zero accumulated sim time still poll
    for _ in range(200):
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    assert all(r.session.current_state() == SessionState.RUNNING for r in runners)
    assert any(isinstance(e, Synchronized) for r in runners for e in r.events)

    x0 = [float(r.world.comps["pos"][0, 0]) for r in runners]
    y0 = [float(r.world.comps["pos"][1, 1]) for r in runners]
    interleave(runners, 60)
    # player 0 (local on runner 0) held RIGHT: moved on BOTH peers
    assert float(runners[0].world.comps["pos"][0, 0]) > x0[0]
    assert float(runners[1].world.comps["pos"][0, 0]) > x0[1]
    # player 1 held UP (negative z in our model: up bit -> acc -z... check moved)
    assert float(runners[0].world.comps["pos"][1, 1]) != y0[0]
    assert float(runners[1].world.comps["pos"][1, 1]) != y0[1]
    assert runners[0].frame >= 50 and runners[1].frame >= 50
    # network stats populated after sustained traffic
    stats = runners[0].session.network_stats(1)
    assert stats.kbps_sent > 0
    for s in socks:
        s.close()


def test_p2p_confirmed_advances_and_snapshots_pruned():
    runners, socks = make_pair()
    for _ in range(200):
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    interleave(runners, 80)
    for r in runners:
        assert r.session.confirmed_frame() > 40
        assert len(r.ring) <= r.ring.depth
        assert all(f >= r.confirmed for f in r.ring.frames())
    for s in socks:
        s.close()


def test_p2p_peers_agree_on_confirmed_checksums():
    runners, socks = make_pair()
    for _ in range(200):
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    interleave(runners, 80)
    r0, r1 = runners
    got = None
    for _ in range(6):
        shared = sorted(set(r0.ring.frames()) & set(r1.ring.frames()))
        if shared:
            f = shared[-1]
            got = [checksum_to_int(r.ring.peek(f)[1]) for r in runners]
            break
        (r0 if r0.frame <= r1.frame else r1).update(DT)
    assert got is not None, "rings share no frame"
    assert got[0] == got[1]
    for s in socks:
        s.close()


def test_p2p_desync_detection_fires_on_divergence():
    import dataclasses

    runners, socks = make_pair(desync=DesyncDetection.on(5))
    for _ in range(200):
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    interleave(runners, 30)
    # corrupt checksummed state on peer 1 behind the session's back
    w = runners[1].world
    runners[1].world = dataclasses.replace(
        w, comps={**w.comps, "pos": w.comps["pos"] + 5.0}
    )
    runners[1]._world_checksum = runners[1].app.checksum_fn(runners[1].world)
    interleave(runners, 80, sleep=0.001)
    desyncs = [
        e for r in runners for e in r.events if isinstance(e, DesyncDetected)
    ]
    assert desyncs, "expected DesyncDetected after state divergence"
    for s in socks:
        s.close()


def test_p2p_stalls_without_remote():
    # peer 1 never runs -> peer 0 must stall at the prediction threshold
    socks = [UdpNonBlockingSocket(0, host="127.0.0.1") for _ in range(2)]
    addrs = [("127.0.0.1", s.local_addr[1]) for s in socks]
    app = box_game.make_app(num_players=2)
    b = (
        SessionBuilder.for_app(app)
        .with_max_prediction_window(4)
        .add_player(PlayerType.LOCAL, 0)
        .add_player(PlayerType.REMOTE, 1, addrs[1])
    )
    session = b.start_p2p_session(socks[0])
    runner = GgrsRunner(app, session)
    # complete the sync handshake manually from the silent peer's socket
    from bevy_ggrs_tpu.session.protocol import (
        HDR, MAGIC, PROTOCOL_VERSION, S_SYNC_REP, S_SYNC_REQ,
        T_SYNC_REQ, T_SYNC_REP,
    )

    for _ in range(100):
        runner.update(0.0)
        for addr, data in socks[1].receive_all():
            magic, t = HDR.unpack_from(data)
            if t == T_SYNC_REQ:
                nonce, _ver = S_SYNC_REQ.unpack_from(data[HDR.size:])
                socks[1].send_to(
                    HDR.pack(MAGIC, T_SYNC_REP)
                    + S_SYNC_REP.pack(nonce, PROTOCOL_VERSION),
                    addr,
                )
        if session.current_state() == SessionState.RUNNING:
            break
        time.sleep(0.001)
    assert session.current_state() == SessionState.RUNNING
    interleave([runner], 30)
    # advanced to the prediction limit then stalled
    assert runner.frame <= 5
    assert runner.stalled_frames > 0
    for s in socks:
        s.close()


def test_p2p_spectator_trio():
    socks = [UdpNonBlockingSocket(0, host="127.0.0.1") for _ in range(3)]
    addrs = [("127.0.0.1", s.local_addr[1]) for s in socks]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, addrs[1 - i])
        )
        if i == 0:  # host streams to the spectator
            b.add_player(PlayerType.SPECTATOR, 2, addrs[2])
        session = b.start_p2p_session(socks[i])

        def read_inputs(handles, i=i):
            return {h: box_game.keys_to_input(right=(i == 0)) for h in handles}

        runners.append(GgrsRunner(app, session, read_inputs=read_inputs))

    spec_app = box_game.make_app(num_players=2)
    spec_session = SessionBuilder.for_app(spec_app).start_spectator_session(
        addrs[0], socks[2]
    )
    spec_runner = GgrsRunner(spec_app, spec_session)
    everyone = runners + [spec_runner]

    for _ in range(300):
        for r in everyone:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in everyone):
            break
        time.sleep(0.001)
    assert spec_session.current_state() == SessionState.RUNNING
    interleave(everyone, 100)
    assert spec_runner.frame > 20
    # spectator replays the same world: player 0 moved right
    assert float(spec_runner.world.comps["pos"][0, 0]) > 1.9
    for s in socks:
        s.close()


def test_p2p_session_restart():
    # dropping the session resets driver state; a fresh session on fresh
    # sockets restarts cleanly from frame 0 (schedule_systems.rs:70-79)
    runners, socks = make_pair()
    for _ in range(200):
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    interleave(runners, 30)
    assert runners[0].frame >= 25
    for r in runners:
        r.set_session(None)
        r.update(1.0)  # no session: accumulator clears, nothing advances
        assert r.frame == 0
    for s in socks:
        s.close()
    runners2, socks2 = make_pair()
    for _ in range(200):
        for r in runners2:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners2):
            break
        time.sleep(0.001)
    interleave(runners2, 20)
    assert all(r.frame >= 15 for r in runners2)
    for s in socks2:
        s.close()


def test_spectator_catchup():
    """A lagging spectator replays 1 + catchup_speed confirmed frames per
    tick until it closes the gap (the reference's catchup behavior,
    /root/reference/tests/p2p.rs:202-260; spectator.py advance_frame)."""
    catchup = 3
    socks = [UdpNonBlockingSocket(0, host="127.0.0.1") for _ in range(3)]
    addrs = [("127.0.0.1", s.local_addr[1]) for s in socks]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, addrs[1 - i])
        )
        if i == 0:
            b.add_player(PlayerType.SPECTATOR, 2, addrs[2])
        session = b.start_p2p_session(socks[i])
        runners.append(
            GgrsRunner(
                app, session,
                read_inputs=lambda hs: {
                    h: box_game.keys_to_input(right=True) for h in hs
                },
            )
        )

    spec_app = box_game.make_app(num_players=2)
    spec_session = (
        SessionBuilder.for_app(spec_app)
        .with_catchup_speed(catchup)
        .start_spectator_session(addrs[0], socks[2])
    )
    assert spec_session.catchup_speed == catchup
    spec_runner = GgrsRunner(spec_app, spec_session)
    everyone = runners + [spec_runner]
    for _ in range(300):
        for r in everyone:
            r.update(0.0)
        if all(
            r.session.current_state() == SessionState.RUNNING for r in everyone
        ):
            break
        time.sleep(0.001)
    assert spec_session.current_state() == SessionState.RUNNING

    # lag the spectator: hosts advance 40 frames while it sits idle
    lag = 40
    interleave(runners, lag)
    spec_runner.update(0.0)  # drain the socket only (no sim tick)
    assert spec_session.frames_behind_host() > 2 * catchup

    # now tick everyone: while behind, each spectator tick must replay
    # 1 + catchup frames (host ticks add ~1 new confirmed frame each, so
    # the gap shrinks by ~catchup per tick until it closes)
    behind0 = spec_session.frames_behind_host()
    deltas = []
    for _ in range(lag):
        before = spec_runner.frame
        interleave(everyone, 1)
        deltas.append(spec_runner.frame - before)
        if spec_session.frames_behind_host() <= 2:
            break
    assert max(deltas) == 1 + catchup  # catchup rate honored while lagging
    assert spec_session.frames_behind_host() <= 2  # gap actually closed
    # and it closed at the catchup rate, not one-frame-at-a-time
    assert len(deltas) <= behind0 // catchup + 3
    # spectator replays the true world
    assert float(spec_runner.world.comps["pos"][0, 0]) > 1.9
    for s in socks:
        s.close()
