"""Device-resident N-tick megastep (ops/megastep.py + GgrsRunner(megastep=
True)): a whole coalesced flush — rollback load included, when its target is
still resident in the on-device snapshot ring — runs as ONE dispatch fed by
ONE packed upload.

Acceptance oracle: the per-tick packed driver.  The megastep program must be
bit-identical to it (SyncTest checksums + ring contents), fused ring loads
must actually engage under the SyncTest every-tick rollback cadence, and the
steady predicted P2P shape must hit the headline cost: N frames per update =
1 dispatch + 1 upload."""

import numpy as np
import pytest

from bevy_ggrs_tpu import (
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
    SpeculationConfig,
    SyncTestSession,
)
from bevy_ggrs_tpu.models import fixed_point, stress
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


def _drive(megastep, coalesce=1, ticks=36, chunk=1, check_distance=3):
    app = fixed_point.make_app()
    session = SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=check_distance, compare_interval=1,
    )
    t = [0]

    def read_inputs(handles):
        t[0] += 1
        return {h: np.uint8((t[0] * 7 + h * 3) & 0xF) for h in handles}

    runner = GgrsRunner(
        app, session, read_inputs=read_inputs,
        on_mismatch=lambda e: (_ for _ in ()).throw(e),
        coalesce_frames=coalesce, megastep=megastep,
    )
    done = 0
    while done < ticks:
        n = min(chunk, ticks - done)
        runner.update(n * DT)
        done += n
    runner.finish()
    return runner


def _assert_bit_identical(a, b):
    assert a.frame == b.frame
    assert a.checksum == b.checksum
    shared = sorted(set(a.ring.frames()) & set(b.ring.frames()))
    assert shared
    for f in shared:
        assert checksum_to_int(a.ring.peek(f)[1]) == checksum_to_int(
            b.ring.peek(f)[1]
        )


def test_megastep_synctest_bit_identical():
    """SyncTest rolls back EVERY tick, so each flush carries a Load — the
    fused device-ring select must restore bit-exactly what the host ring
    path restores."""
    ms = _drive(megastep=True)
    ref = _drive(megastep=False)
    _assert_bit_identical(ms, ref)
    st = ms.stats()
    assert st["megastep"] and st["fused_ring_loads"] > 0
    assert st["megastep_dispatches"] > 0
    # every megastep dispatch is fed by exactly one packed upload
    assert st["host_uploads"] == st["device_dispatches"]


def test_megastep_coalesced_bit_identical():
    """coalesce=8 chunks: one flush = Load + 8-frame catch-up in a single
    fixed-shape dispatch (SyncTest keeps interleaving loads, so dispatch
    count stays O(flushes), not O(frames))."""
    ms = _drive(megastep=True, coalesce=8, ticks=48, chunk=8,
                check_distance=8)
    ref = _drive(megastep=False, coalesce=8, ticks=48, chunk=8,
                 check_distance=8)
    _assert_bit_identical(ms, ref)
    st = ms.stats()
    assert st["fused_ring_loads"] > 0
    assert st["host_uploads"] == st["device_dispatches"]
    # SyncTest loads every tick, so fusion cannot beat the coalesced
    # reference here — but it must never dispatch MORE (the steady P2P
    # test below owns the 1-dispatch-per-N headline)
    assert st["device_dispatches"] <= ref.stats()["device_dispatches"]


def _p2p_pair(coalesce, megastep):
    from bevy_ggrs_tpu.session.channel import ChannelNetwork

    net = ChannelNetwork(seed=21)
    socks = [net.endpoint(f"m{i}") for i in range(2)]
    runners = []
    for i in range(2):
        app = fixed_point.make_app()
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(2)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"m{1 - i}")
        )
        session = b.start_p2p_session(socks[i])
        runners.append(GgrsRunner(
            app, session,
            # constant inputs: PredictRepeatLast is always right, so the
            # steady state has NO rollbacks — the pure megastep cadence
            read_inputs=lambda hs: {h: np.uint8(3) for h in hs},
            coalesce_frames=coalesce, megastep=megastep,
        ))
    for _ in range(500):
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING
               for r in runners):
            break
    assert all(r.session.current_state() == SessionState.RUNNING
               for r in runners)
    return net, runners


def test_megastep_steady_p2p_one_dispatch_per_n_ticks():
    """The headline number: N coalesced frames per host update cost exactly
    ONE dispatch fed by ONE upload once prediction holds."""
    N = 8
    net, runners = _p2p_pair(coalesce=N, megastep=True)
    # settle the startup transient (predictions confirmed, rings warm)
    for _ in range(6):
        net.deliver()
        for r in runners:
            r.update(N * DT)
    r0 = runners[0]
    rb0 = r0.rollbacks
    flushes = 10
    exact = 0
    for _ in range(flushes):
        d0, u0, f0 = (r0.device_dispatches, r0.stats()["host_uploads"],
                      r0.frame)
        net.deliver()
        for r in runners:
            r.update(N * DT)
        # float accumulator drift can make a flush owe N±1 frames; every
        # flush that owes exactly N must cost exactly 1 dispatch + 1 upload
        if r0.frame - f0 == N:
            assert r0.device_dispatches - d0 == 1
            assert r0.stats()["host_uploads"] - u0 == 1
            exact += 1
    # frame-advantage throttling makes a few flushes owe N±1; the
    # exactly-N shape (asserted 1+1 above) must still dominate
    assert exact >= flushes // 2
    assert r0.rollbacks == rb0  # constant inputs: prediction never misses
    # align the peers frame-for-frame and compare live checksums (the
    # fast-confirming peer prunes its ring too eagerly for row-level
    # comparison; bit-equality vs the host-ring driver is owned by the
    # SyncTest tests above)
    for _ in range(200):
        if runners[0].frame == runners[1].frame:
            break
        net.deliver()
        behind = min(runners, key=lambda r: r.frame)
        behind.update(DT)
    assert runners[0].frame == runners[1].frame
    assert runners[0].checksum == runners[1].checksum
    for r in runners:
        r.finish()


def test_megastep_p2p_with_rollbacks_matches_per_tick_driver():
    """Flipping inputs under channel latency: rollbacks land inside the
    coalesced flushes, exercising the fused ring-load path end-to-end; the
    megastep peer must stay bit-identical to its per-tick packed partner
    (cross-peer ring agreement is the oracle)."""
    from bevy_ggrs_tpu.session.channel import ChannelNetwork

    net = ChannelNetwork(latency_hops=3, seed=5)
    socks = [net.endpoint(f"x{i}") for i in range(2)]
    runners = []
    for i, (ms, co) in enumerate([(True, 4), (False, 1)]):
        app = fixed_point.make_app()
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"x{1 - i}")
        )
        session = b.start_p2p_session(socks[i])
        flip = [0]

        def read_inputs(hs, flip=flip, i=i):
            flip[0] += 1
            return {h: np.uint8((flip[0] // 5 + i) & 0x7) for h in hs}

        runners.append(GgrsRunner(
            app, session, read_inputs=read_inputs,
            coalesce_frames=co, megastep=ms,
        ))
    for _ in range(500):
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING
               for r in runners):
            break
    for step in range(120):
        net.deliver()
        runners[1].update(DT)
        if step % 4 == 3:
            runners[0].update(4 * DT)
    assert runners[0].rollbacks > 0, "latency never forced a rollback"
    # keep ticking in lockstep so both confirmation frontiers overtake some
    # mutually retained frames — speculative ring rows may legitimately
    # differ, only both-confirmed ones are the oracle
    from bevy_ggrs_tpu.utils.frames import frame_lt

    shared = []
    for _ in range(40):
        net.deliver()
        for r in runners:
            r.update(DT)
        horizon = min(r.confirmed for r in runners)
        shared = sorted(
            f for f in set(runners[0].ring.frames())
            & set(runners[1].ring.frames())
            if not frame_lt(horizon, f)
        )
        if shared:
            break
    assert shared
    for f in shared:
        assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
            runners[1].ring.peek(f)[1]
        )
    for r in runners:
        r.finish()


def test_megastep_construction_guards():
    app = fixed_point.make_app()
    sess = SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=3, compare_interval=1,
    )
    with pytest.raises(ValueError, match="mutually exclusive"):
        GgrsRunner(
            app, sess, megastep=True,
            speculation=SpeculationConfig(
                candidates_fn=lambda used: used[None], depth=1
            ),
        )
    capp = stress.make_app(64, capacity=64)
    capp.canonical_depth = 8
    capp.canonical_branches = 4
    with pytest.raises(ValueError, match="canonical_branches"):
        GgrsRunner(capp, SyncTestSession(
            num_players=2, input_shape=(), input_dtype=np.uint8,
            check_distance=3, compare_interval=1,
        ), megastep=True)
