"""Speculation equivalence soak: randomized games over a lossy network, a
hedging peer vs a plain peer, in BOTH dispatch modes (fast per-length
programs and the canonical-branched bit-determinism program).  Speculation
is a pure latency optimization — it must never change a single bit of
state, so the peers' checksums must agree exactly while the cache takes
real hits (the reference has no analog; SURVEY §2.4 "Speculation")."""

import time

import numpy as np
import pytest

from bevy_ggrs_tpu import (
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
    SpeculationConfig,
    pad_candidates,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


def _run_game(mode: str, seed: int, ticks: int = 250):
    """Two peers, random input streams, peer 0 hedging; returns the pair of
    runners after `ticks` jittered host ticks."""
    net = ChannelNetwork(latency_hops=2, loss=0.1, seed=seed, jitter_hops=2)
    socks = [net.endpoint("a"), net.endpoint("b")]
    rngs = [np.random.default_rng(1000 * seed + i) for i in range(2)]
    runners = []
    for i in range(2):
        if mode == "canonical-branched":
            app = box_game.make_app(num_players=2)
            app.canonical_depth = 10
            app.canonical_branches = 9  # lane 0 + all 8 hedge candidates
        else:
            app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .with_max_prediction_window(8)
            .with_disconnect_timeout(60.0)
            .with_disconnect_notify_delay(30.0)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a")
        )
        session = b.start_p2p_session(socks[i])
        spec = (
            SpeculationConfig(
                candidates_fn=pad_candidates(2, [1 - i], list(range(8))),
                depth=4,
            )
            if i == 0
            else None
        )

        def read_inputs(handles, i=i):
            # hold inputs for random stretches: realistic pad behavior that
            # both mispredicts (on flips) and rewards hedging (on holds)
            return {h: np.uint8(rngs[i].integers(0, 8)) for h in handles}

        runners.append(
            GgrsRunner(app, session, read_inputs=read_inputs, speculation=spec)
        )

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(
            r.session.current_state() == SessionState.RUNNING for r in runners
        ):
            break
        time.sleep(0.002)
    assert all(
        r.session.current_state() == SessionState.RUNNING for r in runners
    )

    dt_rng = np.random.default_rng(seed)
    for _ in range(ticks):
        net.deliver()
        for r in runners:
            r.update(DT * float(dt_rng.uniform(0.5, 1.5)))
    return net, runners


@pytest.mark.parametrize("mode", ["fast", "canonical-branched"])
@pytest.mark.parametrize("seed", [3, 11])
def test_hedging_peer_bit_identical_to_plain_peer(mode, seed):
    net, runners = _run_game(mode, seed)
    # both progressed well past the sync handshake
    assert all(r.frame > 100 for r in runners)
    # tick evenly until both rings hold a common frame, then compare its
    # CONFIRMED checksum (both peers' view of the same simulated moment)
    common_frames = ()
    for _ in range(120):
        net.deliver()
        for r in runners:
            r.update(DT)
        common_frames = sorted(
            set(runners[0].ring.frames()) & set(runners[1].ring.frames())
        )
        confirmed = min(r.confirmed for r in runners)
        common_frames = [f for f in common_frames if f <= confirmed]
        if common_frames:
            break
    assert common_frames, "peers' snapshot rings never overlapped"
    common = common_frames[-1]
    cs = [checksum_to_int(r.ring.peek(common)[1]) for r in runners]
    assert cs[0] == cs[1], (
        f"speculating and plain peers diverged at frame {common} "
        f"({mode}, seed {seed})"
    )
    # the soak is only meaningful if hedging actually engaged
    stats = runners[0].stats()
    assert stats["speculation_hits"] + stats["speculation_misses"] > 0
