"""Speculation-cache HBM budget: ``SpeculationConfig.max_cached_bytes``
bounds the device bytes pinned by hedge branches (the cache shares nothing
with the ring — ops/speculation.py memory note).  Oldest start frames evict
first; the newest entry always survives so speculation is never silently
disabled by an undersized budget."""

import numpy as np

from bevy_ggrs_tpu import GgrsRunner
from bevy_ggrs_tpu.models import stress
from bevy_ggrs_tpu.ops.speculation import SpeculationCache, SpeculationConfig


def _cache(n_entities, **cfg_kwargs):
    app = stress.make_app(n_entities, capacity=n_entities)
    config = SpeculationConfig(
        candidates_fn=lambda last: np.stack(
            [np.bitwise_xor(last, v) for v in (0, 1, 2, 3)]
        ),
        depth=2,
        **cfg_kwargs,
    )
    return app, SpeculationCache(app, config)


def _fill(app, cache, frames):
    world = app.init_state()
    used = np.zeros((2,), np.uint8)
    for f in frames:
        cache.speculate(world, f, used)
    return world


def test_budget_evicts_oldest_and_respects_cap():
    app, cache = _cache(4096, max_cached_frames=64)
    _fill(app, cache, [0])
    per_entry = cache.cached_bytes
    assert per_entry > 0
    # budget for ~2.5 entries: the third insert must evict frame 0
    cache.config.max_cached_bytes = int(per_entry * 2.5)
    _fill(app, cache, [1, 2, 3, 4])
    assert cache.cached_bytes <= cache.config.max_cached_bytes
    kept = sorted(cache._cache)
    assert kept == [3, 4]  # oldest-first eviction
    assert cache.bytes_evicted >= 3 * per_entry


def test_newest_entry_survives_undersized_budget():
    app, cache = _cache(4096, max_cached_frames=64, max_cached_bytes=1)
    _fill(app, cache, [0, 1])
    assert sorted(cache._cache) == [1]  # never empty, newest kept
    # and a lookup against the surviving entry still serves
    got = cache.lookup(1, np.zeros((2,), np.uint8))
    assert got is not None


def test_budget_under_live_driver_large_world():
    """Overflow behavior at large capacity: a 100k-entity world whose hedge
    entries dwarf a small budget must keep hedging each tick while holding
    at most one entry (a scripted session keeps every advance PREDICTED so
    the driver speculates every tick and the cache would otherwise grow to
    ``max_cached_frames`` 100k-world entries)."""
    from bevy_ggrs_tpu.session.events import InputStatus
    from bevy_ggrs_tpu.session.requests import AdvanceRequest, SaveCell, SaveRequest
    from bevy_ggrs_tpu.session import SessionState as _SS

    n = 100_000
    app = stress.make_app(n, capacity=n)

    class PredictingSession:
        """Every tick: save + advance with the remote input PREDICTED."""

        def __init__(self):
            self.frame = 0

        def num_players(self):
            return 2

        def max_prediction(self):
            return 8

        def confirmed_frame(self):
            return -1

        def current_state(self):
            return _SS.RUNNING

        def local_player_handles(self):
            return [0]

        def add_local_input(self, handle, value):
            pass

        def _on_cell_saved(self, frame, provider):
            pass

        def advance_frame(self):
            status = np.zeros((2,), np.int8)
            status[1] = InputStatus.PREDICTED
            reqs = [
                SaveRequest(self.frame, SaveCell(self, self.frame)),
                AdvanceRequest(np.zeros((2,), np.uint8), status),
            ]
            self.frame += 1
            return reqs

    runner = GgrsRunner(
        app, PredictingSession(),
        read_inputs=lambda hs: {h: np.uint8(0) for h in hs},
        speculation=SpeculationConfig(
            candidates_fn=lambda last: np.stack(
                [np.bitwise_xor(last, v) for v in (0, 1)]
            ),
            depth=1,
            max_cached_bytes=1,  # pathologically small on purpose
        ),
    )
    for _ in range(6):
        runner.tick()
    s = runner.stats()
    assert len(runner.spec_cache._cache) <= 1
    # the byte cap actually bit: entries were dropped for size
    assert runner.spec_cache.bytes_evicted > 0
    assert s["speculation_cached_bytes"] <= max(
        runner.spec_cache._entry_bytes.values(), default=0
    )
