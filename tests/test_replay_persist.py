"""Replay + persistence: a recorded session replays to bit-identical
checksums (including from a mid-session checkpoint), and world checkpoints
round-trip through disk exactly."""


import numpy as np

from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.replay import InputRecorder, ReplaySession
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int
from bevy_ggrs_tpu.snapshot.persist import load_world, save_world


def record_run(ticks=25):
    app = box_game.make_app(num_players=2)
    rec = InputRecorder.for_app(app)
    rng = np.random.default_rng(5)
    session = SyncTestSession(num_players=2, input_shape=(),
                              input_dtype=np.uint8, check_distance=2)
    runner = GgrsRunner(
        app, session,
        read_inputs=lambda hs: {h: np.uint8(rng.integers(0, 16)) for h in hs},
        on_advance=rec.on_advance,
    )
    for _ in range(ticks):
        runner.tick()
    return app, rec, runner


def test_replay_reproduces_checksum(tmp_path):
    app, rec, live = record_run()
    assert len(rec) >= 20
    path = str(tmp_path / "match.npz")
    rec.save(path)
    rec2 = InputRecorder.load(path)

    replay_app = box_game.make_app(num_players=2)
    replayer = GgrsRunner(replay_app, ReplaySession(rec2))
    while not replayer.session.finished:
        replayer.tick()
    live_cs = checksum_to_int(live.app.checksum_fn(live.world))
    # compare at the same frame: replay covers frames recorded as confirmed
    # (the live runner is a few frames ahead of its last confirmed record)
    target = replayer.frame
    entry = live.ring.peek(target)
    if entry is not None:
        assert checksum_to_int(entry[1]) == checksum_to_int(
            replayer._world_checksum
        )
    else:
        # fall back: re-simulate the live run deterministically to the same
        # frame via a fresh replay and compare those
        replayer2 = GgrsRunner(box_game.make_app(num_players=2), ReplaySession(rec2))
        while not replayer2.session.finished:
            replayer2.tick()
        assert checksum_to_int(replayer2._world_checksum) == checksum_to_int(
            replayer._world_checksum
        )


def test_world_checkpoint_roundtrip(tmp_path):
    app, rec, runner = record_run(ticks=10)
    path = str(tmp_path / "ckpt.npz")
    save_world(path, app.reg, runner.world, frame=runner.frame)
    restored, frame = load_world(path, app.reg)
    assert frame == runner.frame
    assert checksum_to_int(app.checksum_fn(restored)) == checksum_to_int(
        app.checksum_fn(runner.world)
    )


def test_replay_resumes_from_checkpoint(tmp_path):
    # record a full match; replay half, checkpoint, resume in a fresh runner;
    # final checksum must equal a straight full replay
    app, rec, _ = record_run(ticks=30)
    full = GgrsRunner(box_game.make_app(num_players=2), ReplaySession(rec))
    while not full.session.finished:
        full.tick()

    half = GgrsRunner(box_game.make_app(num_players=2), ReplaySession(rec))
    for _ in range(12):
        half.tick()
    path = str(tmp_path / "mid.npz")
    save_world(path, half.app.reg, half.world, frame=half.frame)

    resumed_app = box_game.make_app(num_players=2)
    world, frame = load_world(path, resumed_app.reg)
    resumed = GgrsRunner(
        resumed_app,
        ReplaySession(rec, start_frame=frame),
        initial_state=world,
    )
    resumed.frame = frame
    while not resumed.session.finished:
        resumed.tick()
    assert resumed.frame == full.frame
    assert checksum_to_int(resumed._world_checksum) == checksum_to_int(
        full._world_checksum
    )


def test_p2p_recording_has_no_gaps_and_replays(tmp_path):
    # P2P regression: correctly-predicted frames are never re-advanced, so a
    # recorder keeping only all-CONFIRMED advances had permanent gaps and the
    # replay spun forever at the first one.  Record from a real loopback-UDP
    # pair with varying inputs (mispredictions + rollbacks) and assert the
    # confirmed recording is gapless and replays to the live checksums.
    import time as _t

    from bevy_ggrs_tpu import (
        GgrsRunner as _R,
        PlayerType,
        SessionBuilder,
        SessionState,
        UdpNonBlockingSocket,
    )

    socks = [UdpNonBlockingSocket(0, host="127.0.0.1") for _ in range(2)]
    addrs = [("127.0.0.1", s.local_addr[1]) for s in socks]
    rngs = [np.random.default_rng(7), np.random.default_rng(11)]
    runners, recs = [], []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        rec = InputRecorder.for_app(app)
        session = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, addrs[1 - i])
            .start_p2p_session(socks[i])
        )
        runners.append(_R(
            app, session,
            read_inputs=lambda hs, i=i: {
                h: np.uint8(rngs[i].integers(0, 16)) for h in hs
            },
            on_advance=rec.on_advance,
            on_confirmed=rec.on_confirmed,
        ))
        recs.append(rec)
    for _ in range(200):
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        _t.sleep(0.001)
    for _ in range(60):
        for r in runners:
            r.update(1.0 / 60.0)
    rec = recs[0]
    final = rec.final_frames()
    assert len(final) >= 30  # confirmed stream was captured, not just gaps
    keys = sorted(final)
    assert keys == list(range(keys[0], keys[-1] + 1))  # gapless
    path = str(tmp_path / "p2p.npz")
    rec.save(path)
    replayer = GgrsRunner(box_game.make_app(num_players=2),
                          ReplaySession(InputRecorder.load(path)))
    guard = 0
    while not replayer.session.finished:
        replayer.tick()
        guard += 1
        assert guard < 10 * len(final), "replay failed to finish (gap?)"
    entry = runners[0].ring.peek(replayer.frame)
    if entry is not None:
        assert checksum_to_int(entry[1]) == checksum_to_int(
            replayer._world_checksum
        )
    for s in socks:
        s.close()


def test_checkpoint_rejects_registry_mismatch(tmp_path):
    import pytest

    app, _, runner = record_run(ticks=3)
    path = str(tmp_path / "ckpt.npz")
    save_world(path, app.reg, runner.world)
    other = box_game.make_app(num_players=2)
    other.rollback_component("extra", (), np.int32)
    with pytest.raises(ValueError):
        load_world(path, other.reg)


def test_checkpoint_records_schema_digest_and_extras(tmp_path):
    # v2 checkpoints carry the registry schema + digest and named extras;
    # the round-trip preserves frame, digest, and extra payloads exactly
    from bevy_ggrs_tpu.snapshot.persist import (
        load_checkpoint, registry_schema, schema_digest,
    )

    app, _, runner = record_run(ticks=5)
    path = str(tmp_path / "ckpt.npz")
    tail = np.arange(6, dtype=np.int64)
    save_world(path, app.reg, runner.world, frame=runner.frame,
               extras={"tail_frames": tail})
    z = np.load(path, allow_pickle=False)
    assert str(z["__schema_digest__"]) == schema_digest(app.reg)
    rows = registry_schema(app.reg)
    assert rows and all(r.count(":") >= 2 for r in rows)
    ck = load_checkpoint(path, app.reg)
    assert ck.frame == runner.frame
    np.testing.assert_array_equal(ck.extras["tail_frames"], tail)
    assert checksum_to_int(app.checksum_fn(ck.world)) == checksum_to_int(
        app.checksum_fn(runner.world)
    )


def test_checkpoint_schema_error_names_drifted_leaves(tmp_path):
    # the mismatch diagnostic must name the drifted leaves, not just count
    import pytest

    app, _, runner = record_run(ticks=3)
    path = str(tmp_path / "ckpt.npz")
    save_world(path, app.reg, runner.world)
    other = box_game.make_app(num_players=2)
    other.rollback_component("shield_timer", (), np.int32)
    with pytest.raises(ValueError, match="shield_timer"):
        load_world(path, other.reg)


def test_checkpoint_dtype_mismatch_loud_unless_allow_cast(tmp_path):
    # dtype drift changes bits: rejected by default, bridged by allow_cast
    import jax.numpy as jnp
    import pytest

    from bevy_ggrs_tpu.app import App

    def build(dtype):
        a = App(num_players=1, capacity=4, input_shape=(),
                input_dtype=np.uint8)
        a.rollback_component("val", (), dtype, checksum=True)
        a.set_step(lambda w, ctx: w)
        return a

    a32 = build(jnp.int32)
    w = a32.init_state()
    path = str(tmp_path / "d.npz")
    save_world(path, a32.reg, w, frame=7)

    a16 = build(jnp.int16)
    with pytest.raises(ValueError, match="val"):
        load_world(path, a16.reg)
    world, frame = load_world(path, a16.reg, allow_cast=True)
    assert frame == 7
    assert np.asarray(world.comps["val"]).dtype == np.int16


def test_v1_checkpoint_dtype_mismatch_is_loud_per_leaf(tmp_path):
    # v1 files have no schema to compare, so the per-leaf dtype check is
    # the only guard — it must fail loudly too (the seed silently cast)
    import jax
    import pytest

    app, _, runner = record_run(ticks=3)
    leaves, _ = jax.tree.flatten(runner.world)
    path = str(tmp_path / "v1.npz")
    payload = {
        f"leaf_{i}": np.asarray(x).astype(np.float64)
        if np.asarray(x).dtype == np.float32 else np.asarray(x)
        for i, x in enumerate(leaves)
    }
    np.savez_compressed(path, __version__=1, __frame__=3,
                        __n_leaves__=len(leaves), **payload)
    with pytest.raises(ValueError, match="dtype"):
        load_world(path, app.reg)
    world, frame = load_world(path, app.reg, allow_cast=True)
    assert frame == 3
