"""Packed single-upload staging (ops/packing.py): host pack / device unpack
roundtrip, bit-equality of the packed resim path against the three-upload
reference on solo / canonical / batched / sharded drivers, and the upload
census the bench "uploads" stage gates on (steady tick = ONE host->device
upload feeding ONE fused dispatch)."""

import jax
import numpy as np

from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import fixed_point, stress
from bevy_ggrs_tpu.ops.packing import (
    PREFIX_BYTES,
    PackedSpec,
    pack_prefix,
    pack_row,
    repeat_last_row,
    unpack_seq,
)
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

# ----------------------------------------------------- pack/unpack roundtrip


def _roundtrip(spec, k, rng):
    if np.issubdtype(spec.input_dtype, np.floating):
        inputs = rng.standard_normal(
            (k, spec.players, *spec.input_shape)
        ).astype(spec.input_dtype)
    else:
        info = np.iinfo(spec.input_dtype)
        inputs = rng.integers(
            info.min, info.max, (k, spec.players, *spec.input_shape),
            dtype=spec.input_dtype, endpoint=True,
        )
    status = rng.integers(0, 3, (k, spec.players), dtype=np.int8)
    buf = spec.new_buffer(k)
    pack_prefix(buf, start_frame=1234, n_real=k, has_load=1, load_slot=5)
    for i in range(k):
        pack_row(spec, buf, i, inputs[i], status[i])
    out = jax.jit(lambda p: unpack_seq(spec, p))(buf)
    got_inputs, got_status, start, n_real, has_load, load_slot = out
    np.testing.assert_array_equal(np.asarray(got_inputs), inputs)
    np.testing.assert_array_equal(np.asarray(got_status), status)
    assert int(start) == 1234 and int(n_real) == k
    assert int(has_load) == 1 and int(load_slot) == 5


def test_roundtrip_scalar_uint8():
    _roundtrip(PackedSpec.from_parts(2, (), np.uint8), 5,
               np.random.default_rng(0))


def test_roundtrip_multibyte_vector_dtypes():
    # multi-byte itemsizes exercise the reshape-before-bitcast path
    rng = np.random.default_rng(1)
    _roundtrip(PackedSpec.from_parts(3, (4,), np.int16), 3, rng)
    _roundtrip(PackedSpec.from_parts(2, (2, 2), np.float32), 4, rng)


def test_prefix_is_negative_frame_safe():
    # wrapped frames are negative int32s; the .view write must roundtrip them
    spec = PackedSpec.from_parts(2, (), np.uint8)
    buf = spec.new_buffer(1)
    pack_prefix(buf, start_frame=-7, n_real=1)
    pack_row(spec, buf, 0, np.zeros(2, np.uint8), np.zeros(2, np.int8))
    _, _, start, _, _, _ = jax.jit(lambda p: unpack_seq(spec, p))(buf)
    assert int(start) == -7


def test_repeat_last_row_pads_with_final_real_row():
    spec = PackedSpec.from_parts(2, (), np.uint8)
    buf = spec.new_buffer(6)
    for i in range(3):
        pack_row(spec, buf, i, np.full(2, 10 + i, np.uint8),
                 np.zeros(2, np.int8))
    repeat_last_row(buf, 3, 6)
    for row in range(4, 7):  # padded payload rows 3..5 live at indices 4..6
        np.testing.assert_array_equal(buf[row], buf[3])


def test_width_is_prefix_and_word_aligned():
    spec = PackedSpec.from_parts(1, (), np.uint8)  # payload 2 < prefix 16
    assert spec.width >= PREFIX_BYTES and spec.width % 4 == 0
    big = PackedSpec.from_parts(4, (5,), np.float32)  # payload 84
    assert big.width == 84  # already word-aligned


# -------------------------------------- solo driver: packed == three-upload


def _synctest_driver(app_fn, packed, ticks=36, **kw):
    app = app_fn()
    session = SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=3, compare_interval=1,
    )
    t = [0]

    def read_inputs(handles):
        t[0] += 1
        return {h: np.uint8((t[0] * 7 + h * 3) & 0xF) for h in handles}

    runner = GgrsRunner(
        app, session, read_inputs=read_inputs,
        on_mismatch=lambda e: (_ for _ in ()).throw(e),
        packed=packed, **kw,
    )
    for _ in range(ticks):
        runner.tick()
    runner.finish()
    return runner


def _assert_bit_identical(a, b):
    assert a.frame == b.frame
    assert a.checksum == b.checksum
    shared = sorted(set(a.ring.frames()) & set(b.ring.frames()))
    assert shared
    for f in shared:
        assert checksum_to_int(a.ring.peek(f)[1]) == checksum_to_int(
            b.ring.peek(f)[1]
        )


def test_packed_solo_bit_identical_to_unpacked():
    packed = _synctest_driver(fixed_point.make_app, packed=True)
    plain = _synctest_driver(fixed_point.make_app, packed=False)
    assert packed.packed and not plain.packed
    _assert_bit_identical(packed, plain)


def test_packed_upload_census_one_per_dispatch():
    packed = _synctest_driver(fixed_point.make_app, packed=True)
    st = packed.stats()
    # the tentpole invariant: every fused dispatch fed by EXACTLY one upload
    assert st["host_uploads"] == st["device_dispatches"]
    assert st["packed_upload_bytes"] > 0
    plain = _synctest_driver(fixed_point.make_app, packed=False)
    stp = plain.stats()
    assert stp["host_uploads"] == 3 * stp["device_dispatches"]
    assert stp["packed_upload_bytes"] == 0


def test_packed_canonical_bit_identical():
    def make_canonical():
        app = stress.make_app(64, capacity=64)
        app.canonical_depth = 8
        return app

    packed = _synctest_driver(make_canonical, packed=True)
    plain = _synctest_driver(make_canonical, packed=False)
    assert packed.packed  # canonical_depth keeps a packed program
    _assert_bit_identical(packed, plain)
    st = packed.stats()
    assert st["host_uploads"] == st["device_dispatches"]


def test_packed_mode_matrix_without_packed_program():
    # canonical_branches mode ships no packed program.  The default
    # (packed=None) degrades to the three-upload path; an EXPLICIT
    # packed=True raises the mode-matrix ValueError instead of silently
    # excluding itself (docs/architecture.md)
    import pytest

    app = stress.make_app(64, capacity=64)
    app.canonical_depth = 8
    app.canonical_branches = 4
    assert app.packed_resim_fn is None
    runner = _synctest_driver(lambda: app, packed=None, ticks=12)
    assert runner.packed is False
    assert runner.stats()["host_uploads"] > 0  # census still counts
    with pytest.raises(ValueError, match="packed program"):
        _synctest_driver(lambda: app, packed=True, ticks=0)


# -------------------------------------------------- batched / sharded waves


def _drive_batched(packed, m=3, ticks=24, mesh=None):
    from bevy_ggrs_tpu import BatchedRunner

    app = fixed_point.make_app()
    t = [0]

    def read_inputs(lobby, handles):
        rng = np.random.default_rng(1000 * lobby + t[0])
        return {h: np.uint8(rng.integers(0, 16)) for h in handles}

    sessions = [
        SyncTestSession(num_players=2, input_shape=(), input_dtype=np.uint8,
                        check_distance=2, compare_interval=1)
        for _ in range(m)
    ]
    br = BatchedRunner(app, sessions, read_inputs=read_inputs,
                       packed=packed, mesh=mesh)
    sums = [[] for _ in range(m)]
    for _ in range(ticks):
        br.tick()
        t[0] += 1
        for b in range(m):
            sums[b].append(br.lobby_checksum(b))
    br.finish()  # SyncTest oracle: raises on any batched-restore mismatch
    return br, sums


def test_batched_packed_bit_identical_to_unpacked():
    a, a_sums = _drive_batched(packed=True)
    b, b_sums = _drive_batched(packed=False)
    assert a.stats()["packed"] and not b.stats()["packed"]
    assert a_sums == b_sums
    ea, eb = a.exec.stats(), b.exec.stats()
    assert ea["host_uploads"] == ea["wave_dispatches"]
    assert eb["host_uploads"] >= 3 * eb["wave_dispatches"]
    assert ea["packed_upload_bytes"] > 0
    assert eb["packed_upload_bytes"] == 0


def test_sharded_packed_bit_identical_to_unpacked(eight_devices):
    from bevy_ggrs_tpu.parallel import make_lobby_mesh

    # M=6 on D=8: two permanent pad lanes ride the packed buffer too
    mesh = make_lobby_mesh(len(eight_devices))
    a, a_sums = _drive_batched(packed=True, m=6, ticks=18, mesh=mesh)
    b, b_sums = _drive_batched(packed=False, m=6, ticks=18, mesh=mesh)
    assert a_sums == b_sums
    ea = a.exec.stats()
    assert ea["host_uploads"] == ea["wave_dispatches"]
    assert ea["packed_upload_bytes"] > 0
