"""Port of the GgrsSnapshots unit-test battery
(/root/reference/src/snapshot/mod.rs:369-512): eviction at depth,
rollback-discards-newer, same-frame replace, confirm-prunes, empty confirm,
missing-frame error, and i32 wraparound in both directions."""

import pytest

from bevy_ggrs_tpu.snapshot import SnapshotRing, MissingSnapshotError
from bevy_ggrs_tpu.utils.frames import I32_MAX, I32_MIN, wrap_i32


def test_push_and_peek():
    r = SnapshotRing(depth=8)
    for f in range(5):
        r.push(f, f * 10)
    assert len(r) == 5
    assert r.frames() == [4, 3, 2, 1, 0]
    assert r.peek(2) == 20
    assert r.peek(99) is None
    assert r.latest() == 40
    assert r.latest_frame() == 4


def test_eviction_at_depth():
    r = SnapshotRing(depth=3)
    for f in range(10):
        r.push(f, f)
    assert len(r) == 3
    assert r.frames() == [9, 8, 7]


def test_set_depth_trims_oldest():
    r = SnapshotRing(depth=8)
    for f in range(6):
        r.push(f, f)
    r.set_depth(2)
    assert r.frames() == [5, 4]
    r.set_depth(8)  # growing keeps contents
    assert r.frames() == [5, 4]


def test_same_frame_replace():
    r = SnapshotRing(depth=8)
    r.push(3, "a")
    r.push(3, "b")
    assert len(r) == 1
    assert r.peek(3) == "b"


def test_push_evicts_newer_and_equal():
    # pushing frame 2 after 0..4 evicts 2,3,4 (frames >= new frame)
    r = SnapshotRing(depth=8)
    for f in range(5):
        r.push(f, f)
    r.push(2, "new")
    assert r.frames() == [2, 1, 0]
    assert r.peek(2) == "new"


def test_rollback_discards_newer():
    r = SnapshotRing(depth=8)
    for f in range(6):
        r.push(f, f * 10)
    got = r.rollback(3)
    assert got == 30
    assert r.frames() == [3, 2, 1, 0]


def test_rollback_missing_frame_raises():
    r = SnapshotRing(depth=8)
    for f in range(3):
        r.push(f, f)
    with pytest.raises(MissingSnapshotError):
        r.rollback(99)
    # like the reference panic path, everything newer was consumed
    assert len(r) == 0


def test_confirm_prunes_older():
    r = SnapshotRing(depth=8)
    for f in range(6):
        r.push(f, f)
    r.confirm(3)
    # keeps the confirmed frame itself (still loadable)
    assert r.frames() == [5, 4, 3]


def test_confirm_on_empty_is_noop():
    r = SnapshotRing(depth=8)
    r.confirm(100)
    assert len(r) == 0


def test_wraparound_forward():
    # frames crossing I32_MAX -> I32_MIN: the wrapped frame is NEWER
    r = SnapshotRing(depth=8)
    f0 = I32_MAX - 1
    seq = [f0, wrap_i32(f0 + 1), wrap_i32(f0 + 2), wrap_i32(f0 + 3)]
    assert seq[2] == I32_MIN  # sanity: we actually wrapped
    for f in seq:
        r.push(f, f)
    assert len(r) == 4  # no spurious eviction at the wrap boundary
    assert r.frames() == list(reversed(seq))
    r.confirm(seq[2])
    assert r.frames() == [seq[3], seq[2]]


def test_wraparound_rollback():
    r = SnapshotRing(depth=8)
    f0 = I32_MAX
    seq = [f0, wrap_i32(f0 + 1), wrap_i32(f0 + 2)]
    for f in seq:
        r.push(f, f)
    got = r.rollback(seq[0])
    assert got == f0
    assert r.frames() == [f0]


def test_wraparound_push_evicts_across_boundary():
    # after pushing wrapped (newer) frames, re-pushing the pre-wrap frame
    # must evict the wrapped ones (they are >= it in wrapped order... they are
    # newer, so pushing the OLD frame evicts nothing newer? No: push evicts
    # frames >= new frame — wrapped frames are newer, hence evicted).
    r = SnapshotRing(depth=8)
    seq = [I32_MAX - 1, I32_MAX, I32_MIN, I32_MIN + 1]
    for f in seq:
        r.push(f, f)
    r.push(I32_MAX, "redo")
    assert r.frames() == [I32_MAX, I32_MAX - 1]
    assert r.peek(I32_MAX) == "redo"
