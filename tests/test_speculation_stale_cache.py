"""Regression: speculative branches hedged from predicted states must be
invalidated when a rollback corrects those states (runner._load calls
SpeculationCache.invalidate_after).  Before the fix, a deep rollback could
look up an entry whose *inputs* matched but whose base state was a stale
prediction, silently desyncing the speculating peer — caught by the
randomized soak (test_speculation_soak.py); this file pins the minimal
deterministic schedule that reproduced it (diverged at the second rollback
with 2 cache hits)."""

import numpy as np

from bevy_ggrs_tpu import GgrsRunner, SpeculationConfig, pad_candidates
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.events import InputStatus
from bevy_ggrs_tpu.session.requests import (
    AdvanceRequest,
    LoadRequest,
    SaveRequest,
    SaveCell,
)
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int


class _ScriptedSession:
    """Minimal session double: the test feeds request lists directly."""

    def __init__(self):
        self.conf = -1

    def max_prediction(self):
        return 8

    def rollback_window(self):
        return 8

    def confirmed_frame(self):
        return self.conf

    def _on_cell_saved(self, frame, provider):
        pass


def _mk(spec):
    app = box_game.make_app(num_players=2)
    r = GgrsRunner(app, read_inputs=lambda hs: {}, speculation=spec)
    r.session = _ScriptedSession()
    return r


def test_rollback_invalidates_branches_from_predicted_states():
    spec = SpeculationConfig(
        candidates_fn=pad_candidates(2, [1], list(range(8))),
        depth=4,
        max_cached_frames=16,  # keep old edges alive so stale hits can occur
    )
    a = _mk(spec)  # speculating
    b = _mk(None)  # plain reference

    rng = np.random.default_rng(0)
    true_inp = {}

    def tin(f):
        if f not in true_inp:
            true_inp[f] = rng.integers(0, 8, size=2).astype(np.uint8)
        return true_inp[f]

    def adv(f, predicted_from=None):
        inp = tin(f).copy()
        st = np.full((2,), InputStatus.CONFIRMED, np.int8)
        if predicted_from is not None:
            inp[1] = tin(predicted_from)[1]  # repeat-last prediction
            st[1] = InputStatus.PREDICTED
        return AdvanceRequest(inp, st)

    def batch(reqs, confirmed):
        for r in (a, b):
            r.session.conf = confirmed
            r._handle_requests(list(reqs))

    def assert_rings_agree(tag):
        for f in set(a.ring.frames()) & set(b.ring.frames()):
            ca = checksum_to_int(a.ring.peek(f)[1])
            cb = checksum_to_int(b.ring.peek(f)[1])
            assert ca == cb, f"diverged at frame {f} ({tag})"

    conf, last_real, cur = -1, 0, 0
    for t in range(1, 120):
        if cur - last_real < 8:  # prediction-threshold stall bound
            batch(
                [SaveRequest(cur, SaveCell(a.session, cur)),
                 adv(cur + 1, predicted_from=last_real)],
                conf,
            )
            cur += 1
            assert_rings_agree(f"live tick {t}")
        if t % 3 == 0:
            j = int(rng.integers(1, 4))
            newconf = min(last_real + j, cur - 1)
            if newconf > last_real:
                target, k = last_real, cur - last_real
                reqs = [LoadRequest(target)]
                for i in range(1, k + 1):
                    f = target + i
                    pf = None if f <= newconf else newconf
                    reqs.append(adv(f, predicted_from=pf))
                    reqs.append(SaveRequest(f, SaveCell(a.session, f)))
                batch(reqs, target)  # confirmed trails the load target
                last_real = conf = newconf
                assert_rings_agree(f"rollback tick {t}")

    # the scenario must actually exercise the cache to mean anything
    assert a.spec_cache.hits >= 1
