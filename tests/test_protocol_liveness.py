"""Liveness accounting regressions (the round-4 donation postmortem).

The disconnect timeout must measure remote silence *while the host was
listening* — not wall-clock gaps fabricated by the host's own stalls.  A
jit compile of a new program variant (e.g. the donated resim fn, compiled
one tick after the plain one) stalls the host for seconds; round 4's driver
read that as remote silence, spuriously disconnected a live peer, let
``_compute_confirmed`` leapfrog the peer's uncorrected predictions, and
then crashed with MissingSnapshotError when the peer's late (live!) packets
demanded a rollback below the pruned ring.  Reference failure model:
/root/reference/src (ggrs protocol's disconnect_timeout semantics).
"""


from bevy_ggrs_tpu import GgrsRunner, PlayerType, SessionBuilder, SessionState
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session import protocol
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.session.events import Disconnected, NetworkInterrupted
from bevy_ggrs_tpu.utils.frames import NULL_FRAME


def _make_ep(monkeypatch, timeout=2.0, notify=0.5):
    clock = {"t": 100.0}
    monkeypatch.setattr(protocol, "now_s", lambda: clock["t"])
    ep = protocol.PeerEndpoint(
        send=lambda b: None,
        input_size=1,
        rng_nonce=1,
        disconnect_timeout_s=timeout,
        disconnect_notify_start_s=notify,
        addr="peer",
    )
    ep.state = SessionState.RUNNING
    return ep, clock


def _keepalive_packet():
    return protocol.HDR.pack(protocol.MAGIC, protocol.T_KEEP_ALIVE)


def test_host_stall_does_not_disconnect_live_peer(monkeypatch):
    ep, clock = _make_ep(monkeypatch, timeout=2.0)
    # several host stalls far longer than the timeout, each followed by a
    # packet from the (live) peer: no gap may read as remote silence
    for _ in range(5):
        clock["t"] += 10.0  # host frozen (compile/GC); peer was alive
        ep.poll()
        assert not ep.disconnected
        ep.handle(_keepalive_packet())
        assert ep._quiet_s == 0.0
    assert not ep.disconnected
    assert not any(isinstance(e, Disconnected) for e in ep.events)


def test_single_stall_cannot_trip_even_short_timeouts(monkeypatch):
    ep, clock = _make_ep(monkeypatch, timeout=0.25, notify=0.08)
    clock["t"] += 30.0
    ep.poll()
    assert not ep.disconnected  # one gap contributes at most timeout/2


def test_attended_silence_still_disconnects(monkeypatch):
    ep, clock = _make_ep(monkeypatch, timeout=2.0, notify=0.5)
    # host polls at 60 Hz, peer genuinely silent
    interrupted_at = None
    for i in range(400):
        clock["t"] += 1.0 / 60.0
        ep.poll()
        if interrupted_at is None and ep.interrupted:
            interrupted_at = i
        if ep.disconnected:
            break
    assert interrupted_at is not None  # NetworkInterrupted precedes
    assert ep.disconnected
    kinds = [type(e) for e in ep.events]
    assert kinds.index(NetworkInterrupted) < kinds.index(Disconnected)
    # attended silence ~= wall time at a sane poll rate: fires near 2 s
    assert 110 <= i <= 140


def test_disconnected_endpoint_drops_late_packets(monkeypatch):
    ep, clock = _make_ep(monkeypatch, timeout=0.5, notify=0.1)
    for _ in range(300):
        clock["t"] += 1.0 / 60.0
        ep.poll()
        if ep.disconnected:
            break
    assert ep.disconnected
    before_recv = ep._last_recv
    seen = []
    ep.on_input = lambda f, raw: seen.append(f)
    ep.handle(_keepalive_packet())
    assert ep._last_recv == before_recv  # packet ignored entirely
    assert ep.disconnected
    assert seen == []


def _latency_pair(latency_hops=3):
    net = ChannelNetwork(latency_hops=latency_hops, seed=5)
    socks = [net.endpoint("a0"), net.endpoint("a1")]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"a{1 - i}")
        )
        session = b.start_p2p_session(socks[i])

        def read_inputs(handles, i=i):
            key = {0: "right", 1: "down"}[i]
            return {h: box_game.keys_to_input(**{key: True}) for h in handles}

        runners.append(GgrsRunner(app, session, read_inputs=read_inputs))
    return net, runners


def test_disconnect_forces_correction_of_served_predictions():
    """When a peer is dropped, frames advanced on its predicted inputs must
    be rolled back and resimulated with the DISCONNECTED input policy BEFORE
    the confirmed frame may pass them (else the ring prunes the rollback
    target — the round-4 crash)."""
    net, runners = _latency_pair(latency_hops=3)
    for _ in range(300):
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(
            r.session.current_state() == SessionState.RUNNING for r in runners
        ):
            break
    # run a few real frames so predictions for peer1's inputs are served
    for _ in range(6):
        net.deliver()
        for r in runners:
            r.update(1.0 / 60.0)
    s0 = runners[0].session
    remote_h = [h for h in s0.queues if h not in s0.local_handles][0]
    q = s0.queues[remote_h]
    assert q._predictions  # latency > delay: predictions outstanding
    # peer1 hits the timeout (simulated — the flag is what poll sets)
    ep = s0.endpoints[s0.remote_handle_addr[remote_h]]
    ep.disconnected = True
    s0.poll_remote_clients()
    assert q.first_incorrect != NULL_FRAME  # correction forced
    # the survivor keeps running; the forced rollback must find its snapshot
    before = runners[0].frame
    for _ in range(30):
        runners[0].update(1.0 / 60.0)
    assert runners[0].frame > before + 20


def test_disconnect_of_never_heard_stream_forces_no_correction():
    """If NOTHING of a peer's input stream ever arrived (no stream base, no
    inputs), every served prediction was the default input — exactly what
    the disconnect policy substitutes — so the correction must not fire: a
    status-only rollback would CREATE divergence against peers that saw
    more of the stream."""
    net, runners = _latency_pair(latency_hops=3)
    for _ in range(300):
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(
            r.session.current_state() == SessionState.RUNNING for r in runners
        ):
            break
    # no game ticks yet: the remote stream has not started
    s0 = runners[0].session
    remote_h = [h for h in s0.queues if h not in s0.local_handles][0]
    q = s0.queues[remote_h]
    assert q._base is None and q.last_confirmed == NULL_FRAME
    ep = s0.endpoints[s0.remote_handle_addr[remote_h]]
    ep.disconnected = True
    s0.poll_remote_clients()
    assert q.first_incorrect == NULL_FRAME  # no correction forced
    # and the survivor advances alone without crashing
    for _ in range(30):
        runners[0].update(1.0 / 60.0)
    assert runners[0].frame >= 25
