"""Rollback SERVICING through the speculation seam (runner._service_rollback):
repeated hedged rollbacks under speculation + pipeline + packed must stay
bit-identical to the plain sync unpacked driver; the SyncTest oracle (all
inputs CONFIRMED -> drafts never fire) exercises the all-miss path and the
``rollback_service_ms{path=miss}`` histogram; ``invalidate_after`` keeps the
cache sound (and the devmem registry reconciled) across a mid-speculation
disconnect rollback; plus the solo rows of the strict mode matrix and the
device-resident input-queue satellite's bit-equality + census."""

import numpy as np
import pytest

from bevy_ggrs_tpu import GgrsRunner, SyncTestSession, telemetry
from bevy_ggrs_tpu.models import box_game, fixed_point
from bevy_ggrs_tpu.ops.speculation import SpeculationConfig, pad_candidates
from bevy_ggrs_tpu.session.requests import (
    LoadRequest,
    RollbackCause,
    SaveCell,
    SaveRequest,
)
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int
from tests.test_packed import _assert_bit_identical, _synctest_driver
from tests.test_speculative_runner import ScriptedSession, adv

RIGHT = box_game.keys_to_input(right=True)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _save(session, f):
    return SaveRequest(f, SaveCell(session, f))


def make_rounds_script(session, correcteds):
    """R rounds of (predicted advance -> corrected rollback): every odd tick
    rolls back two frames and re-advances with the real remote input."""
    ticks = []
    f = 0
    for corrected in correcteds:
        actual = [RIGHT, corrected]
        ticks.append([_save(session, f), adv([RIGHT, 0], predicted=True)])
        ticks.append([
            LoadRequest(f), adv(actual), _save(session, f + 1),
            adv(actual, predicted=True),
        ])
        f += 2
    return ticks


def _run_rounds(speculation, correcteds, **kw):
    app = box_game.make_app(num_players=2)
    session = ScriptedSession([])
    session.script = make_rounds_script(session, correcteds)
    runner = GgrsRunner(app, session, speculation=speculation, **kw)
    for _ in range(2 * len(correcteds)):
        runner.tick()
    return runner


def test_repeated_hedged_rollbacks_bit_identical_to_sync_unpacked():
    correcteds = [1, 2, 9, 5]
    spec = SpeculationConfig(
        candidates_fn=pad_candidates(2, [1], list(range(16))), depth=4
    )
    r_spec = _run_rounds(spec, correcteds, pipeline=True, packed=True)
    r_plain = _run_rounds(None, correcteds, pipeline=False, packed=False)
    assert r_spec.spec_cache.hits == len(correcteds)
    assert r_spec.frame == r_plain.frame == 2 * len(correcteds)
    np.testing.assert_array_equal(
        np.asarray(r_spec.world.comps["pos"]),
        np.asarray(r_plain.world.comps["pos"]),
    )
    assert checksum_to_int(r_spec._world_checksum) == checksum_to_int(
        r_plain._world_checksum
    )
    for f in sorted(r_plain.session.saved):
        assert r_spec.session.saved[f]() == r_plain.session.saved[f]()


def test_synctest_oracle_with_speculation_is_all_miss():
    # SyncTest emits CONFIRMED statuses only, so drafts never fire — every
    # structural-resim LoadRequest goes through lookup (miss) and the miss
    # servicing path, and the oracle proves it restores bit-exactly
    telemetry.enable()
    spec = SpeculationConfig(
        candidates_fn=pad_candidates(2, [1], list(range(16))), depth=4
    )
    r_spec = _synctest_driver(
        lambda: box_game.make_app(num_players=2), packed=True,
        speculation=spec,
    )
    r_plain = _synctest_driver(
        lambda: box_game.make_app(num_players=2), packed=False
    )
    _assert_bit_identical(r_spec, r_plain)
    assert r_spec.spec_cache.hits == 0
    assert r_spec.spec_cache.misses > 0
    h = telemetry.registry().histogram("rollback_service_ms")
    assert h.percentile(0.5, path="miss") is not None
    assert h.percentile(0.5, path="hit") is None


def test_invalidate_after_mid_speculation_disconnect():
    app = box_game.make_app(num_players=2)
    session = ScriptedSession([])
    actual = [RIGHT, 7]  # NOT hedged below -> the disconnect load misses
    session.script = [
        [_save(session, 0), adv([RIGHT, 0], predicted=True)],
        [_save(session, 1), adv([RIGHT, 0], predicted=True)],
        [
            LoadRequest(0, cause=RollbackCause(handle=1, lateness=2,
                                               kind="disconnect")),
            adv(actual), _save(session, 1), adv(actual), _save(session, 2),
            adv(actual),
        ],
    ]
    spec = SpeculationConfig(
        candidates_fn=pad_candidates(2, [1], [0, 1, 2, 3]), depth=4
    )
    runner = GgrsRunner(app, session, speculation=spec)
    runner.tick()
    runner.tick()
    cache = runner.spec_cache
    assert set(cache._cache) == {0, 1}  # one branch set per predicted tick
    runner.tick()  # disconnect-consensus rollback to 0
    # entries hedged from the now-superseded frame-1 prediction are gone;
    # the frame-0 set (base state unchanged by the load) survives
    assert set(cache._cache) == {0}
    assert cache.misses >= 1
    # devmem row tracks the post-invalidation footprint exactly, and the
    # registry reconciles against live arrays (satellite: no stale bytes)
    from bevy_ggrs_tpu.telemetry import devmem

    assert devmem.snapshot()[cache._devmem_owner] == cache.cached_bytes
    devmem.census(strict=True)


def test_solo_mode_matrix():
    app = box_game.make_app(num_players=2)
    sess = SyncTestSession(num_players=2)
    with pytest.raises(ValueError, match="input_queue"):
        GgrsRunner(app, sess, packed=False, input_queue=True)
    spec = SpeculationConfig(candidates_fn=pad_candidates(2, [1], [1]))
    with pytest.raises(ValueError, match="mutually exclusive"):
        GgrsRunner(box_game.make_app(num_players=2),
                   SyncTestSession(num_players=2),
                   megastep=True, speculation=spec)


def test_input_queue_bit_identical_and_census():
    q = _synctest_driver(fixed_point.make_app, packed=True, input_queue=True)
    plain = _synctest_driver(fixed_point.make_app, packed=False)
    _assert_bit_identical(q, plain)
    st = q.stats()
    assert st["input_queue"] is True
    # the steady census is untouched: one upload per fused dispatch, the
    # rotation only moves the transfer-safety block off the critical path
    assert st["host_uploads"] == st["device_dispatches"]
    assert st["staging_deferred_blocks"] + st["staging_landed_free"] > 0
