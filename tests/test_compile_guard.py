"""BGT_COMPILE_GUARD steady-state recompile sentinel: armed compiles
raise :class:`RecompileError` naming owner and kind and count into
``recompiles_steady_total``; disabled/disarmed guards are no-ops; and the
e2e half — the exact per-call-varying-static-arg toy runner that BGT070
flags statically (tests/lint_fixtures/bgt070_e2e.py) — trips the armed
``watch_jax`` guard at runtime on the SAME site.

The guard mirrors the ``BGT_SANITIZE`` transfer sanitizer's shape:
env-enabled, starts disarmed so warmup compiles pass, one attribute
check per compile event when off.
"""

import importlib.util
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from bevy_ggrs_tpu import telemetry  # noqa: E402
from bevy_ggrs_tpu.utils import compile_guard  # noqa: E402
from bevy_ggrs_tpu.utils.compile_guard import (  # noqa: E402
    CompileGuard,
    RecompileError,
    set_compile_guard,
)

E2E_FIXTURE = ROOT / "tests" / "lint_fixtures" / "bgt070_e2e.py"


@pytest.fixture(autouse=True)
def _guard_off_after():
    yield
    set_compile_guard(False)
    telemetry.disable()
    telemetry.reset()


def test_env_var_enables_the_guard(monkeypatch):
    monkeypatch.setenv("BGT_COMPILE_GUARD", "1")
    assert CompileGuard().enabled
    monkeypatch.delenv("BGT_COMPILE_GUARD")
    assert not CompileGuard().enabled


def test_disabled_guard_never_arms_and_notify_is_a_noop():
    g = set_compile_guard(False)
    assert g.arm() is False and not g.armed
    compile_guard.notify("solo", "plain:d4", 12.0)  # must not raise
    assert g.steady_compiles == []


def test_enabled_but_disarmed_guard_passes_warmup_compiles():
    set_compile_guard(True)
    compile_guard.notify("batched", "exact:k8", 40.0)  # warmup: no raise
    assert compile_guard.guard().steady_compiles == []


def test_armed_guard_trips_with_owner_kind_and_counter():
    telemetry.enable()
    g = set_compile_guard(True)
    assert g.arm() is True
    with pytest.raises(RecompileError) as ei:
        compile_guard.notify("batched", "padded:k8", 12.5)
    assert ei.value.owner == "batched" and ei.value.kind == "padded:k8"
    assert "BGT070" in str(ei.value) and "BGT071" in str(ei.value)
    assert g.steady_compiles == [("batched", "padded:k8", 12.5)]
    c = telemetry.registry().counter("recompiles_steady_total", "")
    assert c.value(owner="batched") == 1


def test_disarm_returns_to_warmup_behavior():
    g = set_compile_guard(True)
    g.arm()
    g.disarm()
    compile_guard.notify("solo", "branched:d2", 1.0)
    assert g.steady_compiles == []


def test_runner_arm_methods_delegate_to_the_guard():
    """Both runners expose arm_compile_guard(); it returns False when the
    guard is disabled (engine code may call it unconditionally) and True
    once enabled.  The methods touch no runner state, so a bare instance
    is enough — no session construction needed."""
    from bevy_ggrs_tpu.batch_runner import BatchedRunner
    from bevy_ggrs_tpu.runner import GgrsRunner

    set_compile_guard(False)
    for cls in (GgrsRunner, BatchedRunner):
        inst = object.__new__(cls)
        assert inst.arm_compile_guard() is False
    set_compile_guard(True)
    for cls in (GgrsRunner, BatchedRunner):
        inst = object.__new__(cls)
        assert inst.arm_compile_guard() is True
        compile_guard.guard().disarm()


# -- e2e: the BGT070 site trips both halves -----------------------------------


def _load_toy():
    spec = importlib.util.spec_from_file_location("bgt070_e2e", E2E_FIXTURE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_e2e_lint_flags_the_toy_runner_site():
    from scripts.lint import run as lint_run
    from scripts.lint.config import Config

    findings, _files = lint_run(
        [str(E2E_FIXTURE)], root=ROOT, config=Config(project_checks=False))
    hits = [f for f in findings if f.rule == "BGT070"]
    assert len(hits) == 1, [f.as_dict() for f in findings]
    assert "static_argnums" in hits[0].message
    jit_line = next(
        i for i, ln in enumerate(E2E_FIXTURE.read_text().splitlines(), 1)
        if "jax.jit" in ln)
    assert hits[0].line == jit_line


def test_e2e_armed_watch_jax_guard_trips_on_the_same_site():
    """Runtime half: warmup tick compiles freely; after arming with
    watch_jax, the next tick's fresh-wrapper compile (the per-call-varying
    static arg BGT070 flagged) raises RecompileError attributed to jax."""
    import jax.numpy as jnp

    toy = _load_toy()
    x = jnp.arange(4.0)
    toy.tick(x, 2.0)  # warmup: guard disarmed, compile passes

    g = set_compile_guard(True)
    assert g.arm(watch_jax=True) is True
    with pytest.raises(RecompileError) as ei:
        toy.tick(x, 3.0)
    assert ei.value.owner == "jax"
    assert g.steady_compiles and g.steady_compiles[0][0] == "jax"
