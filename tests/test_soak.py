"""Soak: P2P over a lossy, laggy virtual network with churny inputs — input
redundancy and rollback must keep both peers in checksum agreement."""

import numpy as np
import pytest

from bevy_ggrs_tpu import GgrsRunner, PlayerType, SessionBuilder, SessionState
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


@pytest.mark.parametrize("loss,latency,jitter", [(0.15, 1, 0), (0.05, 3, 0), (0.3, 2, 0), (0.1, 1, 4)])
def test_lossy_network_stays_in_sync(loss, latency, jitter):
    net = ChannelNetwork(latency_hops=latency, loss=loss, seed=42, jitter_hops=jitter)
    socks = [net.endpoint("a"), net.endpoint("b")]
    rngs = [np.random.default_rng(100 + i) for i in range(2)]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(2)
            .with_max_prediction_window(8)
            # generous timeout: in-suite jit compiles stall the loop for
            # seconds; a one-sided fake disconnect legitimately diverges sims
            .with_disconnect_timeout(60.0)
            .with_disconnect_notify_delay(30.0)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a")
        )
        session = b.start_p2p_session(socks[i])

        def read_inputs(handles, i=i):
            return {h: np.uint8(rngs[i].integers(0, 16)) for h in handles}

        runners.append(GgrsRunner(app, session, read_inputs=read_inputs))

    import time

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.002)
    assert all(r.session.current_state() == SessionState.RUNNING for r in runners)

    # jittered host ticks: dt varies per peer per tick (uneven frame pacing
    # exercises the accumulator + time-sync paths alongside loss/reorder)
    dt_rng = np.random.default_rng(7)
    for _ in range(200):
        net.deliver()
        for r in runners:
            r.update(DT * float(dt_rng.uniform(0.5, 1.5)))
    # both made progress despite loss
    assert all(r.frame >= 150 for r in runners)
    # compare only at a frame both peers have CONFIRMED (a frame still inside
    # a pending rollback window may legitimately hold a predicted state until
    # the correction lands on the next tick)
    f = None
    for _ in range(40):
        conf = min(r.session.confirmed_frame() for r in runners)
        shared = [
            fr
            for fr in set(runners[0].ring.frames()) & set(runners[1].ring.frames())
            if fr <= conf
        ]
        if shared:
            f = max(shared)
            break
        net.deliver()
        (runners[0] if runners[0].frame <= runners[1].frame else runners[1]).update(DT)
    assert f is not None, "no shared confirmed frame found"
    assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
        runners[1].ring.peek(f)[1]
    ), f"desync at confirmed frame {f} under loss={loss} latency={latency} jitter={jitter}"
