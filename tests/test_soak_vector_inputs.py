"""Lossy-network soak with multi-byte (int16[2]) inputs: input rows larger
than a byte exercise packet payload slicing and redundancy across chunk
boundaries; peers must still agree at confirmed frames."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu import App, GgrsRunner, PlayerType, SessionBuilder, SessionState
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.snapshot import active_mask, spawn
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


def make_stick_app():
    # canonical_depth: this model's arithmetic (int->float scale + add) hits
    # XLA program-variant rounding differences (FMA/fusion), so cross-peer
    # bit-determinism REQUIRES the single fixed-length program
    # (docs/determinism.md); without it this soak desyncs ~75% of runs.
    app = App(num_players=2, capacity=4, input_shape=(2,), input_dtype=np.int16,
              canonical_depth=12)
    app.rollback_component("pos", (2,), jnp.float32, checksum=True)
    app.rollback_component("handle", (), jnp.int32, checksum=True)

    def step(world, ctx):
        h = world.comps["handle"]
        m = active_mask(world) & world.has["handle"]
        stick = ctx.inputs.astype(jnp.float32) / 1000.0
        delta = stick[jnp.clip(h, 0, 1)]
        pos = world.comps["pos"] + jnp.where(m[:, None], delta, 0.0)
        return dataclasses.replace(world, comps={**world.comps, "pos": pos})

    def setup(world):
        for h in range(2):
            world, _ = spawn(app.reg, world, {"pos": np.zeros(2), "handle": h})
        return world

    app.set_step(step)
    app.set_setup(setup)
    return app


def test_vector_inputs_survive_loss_and_reorder():
    net = ChannelNetwork(latency_hops=2, loss=0.2, jitter_hops=3, seed=11)
    socks = [net.endpoint("a"), net.endpoint("b")]
    rngs = [np.random.default_rng(i) for i in range(2)]
    runners = []
    for i in range(2):
        app = make_stick_app()
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(2)
            .with_disconnect_timeout(60.0)
            .with_disconnect_notify_delay(30.0)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a")
        )
        session = b.start_p2p_session(socks[i])
        runners.append(
            GgrsRunner(
                app, session,
                read_inputs=lambda hs, i=i: {
                    h: rngs[i].integers(-500, 500, 2).astype(np.int16) for h in hs
                },
            )
        )

    import time

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.002)
    assert all(r.session.current_state() == SessionState.RUNNING for r in runners)

    for _ in range(150):
        net.deliver()
        for r in runners:
            r.update(DT)
    assert all(r.frame >= 100 for r in runners)

    f = None
    for _ in range(40):
        conf = min(r.session.confirmed_frame() for r in runners)
        shared = [
            fr
            for fr in set(runners[0].ring.frames()) & set(runners[1].ring.frames())
            if fr <= conf
        ]
        if shared:
            f = max(shared)
            break
        net.deliver()
        (runners[0] if runners[0].frame <= runners[1].frame else runners[1]).update(DT)
    assert f is not None
    assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
        runners[1].ring.peek(f)[1]
    )
    # and motion actually happened (inputs flowed)
    assert float(np.abs(np.asarray(runners[0].world.comps["pos"])).max()) > 0.1
