"""Crowd model: reduction-heavy step stays deterministic under SyncTest and
produces identical checksums sharded vs single-device (fixed reduction
structure -> fixed float summation order per sharding... verified empirically
on the CPU mesh; see model docstring for the cross-backend caveat)."""

import numpy as np

from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import crowd
from bevy_ggrs_tpu.models.box_game import keys_to_input


def test_crowd_synctest_clean():
    app = crowd.make_app(n_per_team=64, num_teams=2)
    session = SyncTestSession(num_players=2, input_shape=(),
                              input_dtype=np.uint8, check_distance=3)
    mismatches = []
    runner = GgrsRunner(
        app, session,
        read_inputs=lambda hs: {h: keys_to_input(right=(h == 0)) for h in hs},
        on_mismatch=mismatches.append,
    )
    for _ in range(20):
        runner.tick()
    assert mismatches == []
    # team 0 steered right: its centroid moved right of team 1's
    pos = np.asarray(runner.world.comps["pos"])
    team = np.asarray(runner.world.comps["team"])
    assert pos[team == 0, 0].mean() > pos[team == 1, 0].mean()


def test_crowd_flocks_toward_centroid():
    app = crowd.make_app(n_per_team=64, num_teams=2)
    session = SyncTestSession(num_players=2, input_shape=(),
                              input_dtype=np.uint8, check_distance=0)
    runner = GgrsRunner(app, session)
    spread0 = np.asarray(runner.world.comps["pos"]).std()
    for _ in range(60):
        runner.tick()
    spread1 = np.asarray(runner.world.comps["pos"])[
        np.asarray(runner.world.alive)
    ].std()
    assert spread1 < spread0  # cohesion pulled the flock together
