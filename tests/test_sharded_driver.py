"""Distributed driver: a FULL synctest session over an entity-sharded world
on the 8-device CPU mesh must be bit-identical to the unsharded run.

tests/test_parallel.py proves the sharded *ops* match; this drives the whole
stack — session protocol, fused request dispatch, snapshot ring with lazy
slices, rollback loads — with every component column sharded across the
mesh's "data" axis (the SURVEY §2.4 tensor-parallel row, taken end-to-end)."""

import numpy as np

from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import stress
from bevy_ggrs_tpu.parallel import make_mesh, make_sharded_resim_fn, shard_world


def _drive(shard: bool, ticks: int = 24, n_entities: int = 512):
    app = stress.make_app(n_entities, capacity=n_entities)
    session = SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=3, compare_interval=1,
    )
    mismatches = []
    kwargs = {}
    if shard:
        mesh = make_mesh(n_data=8, n_spec=1)
        # swap the driver's dispatch for the mesh-sharded program and start
        # from a device-mesh-placed world; everything else is unchanged
        app.__dict__["resim_fn"] = make_sharded_resim_fn(app, mesh)
        kwargs["initial_state"] = shard_world(app, mesh, app.init_state())
    rng = np.random.default_rng(7)
    runner = GgrsRunner(
        app, session,
        read_inputs=lambda hs: {h: np.uint8(rng.integers(0, 8)) for h in hs},
        on_mismatch=mismatches.append,
        **kwargs,
    )
    checksums = []
    for _ in range(ticks):
        runner.tick()
        checksums.append(runner.checksum)
    runner.finish()
    return checksums, mismatches, runner


def test_sharded_driver_bit_identical_to_single_device():
    cs_single, mm_single, _ = _drive(shard=False)
    cs_sharded, mm_sharded, runner = _drive(shard=True)
    assert mm_single == [] and mm_sharded == []
    assert cs_single == cs_sharded, "sharded driver diverged from unsharded"
    # the sharded world really is distributed across the mesh
    col = runner.world.comps["pos"]
    assert len(col.sharding.device_set) == 8
