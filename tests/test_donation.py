"""Buffer-donation correctness: the driver's donating dispatch must be
bit-identical to the plain one, and the ring must survive rollbacks when the
donated pre-state's leading save was served from the previous dispatch's
stacked saves (GgrsRunner._run_batch donation notes)."""

import jax
import numpy as np

from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import fixed_point, stress
from bevy_ggrs_tpu.session.events import InputStatus


def _run_driver(app_factory, enable_donation, ticks=40, check_distance=4):
    app = app_factory()
    session = SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=check_distance, compare_interval=1,
    )
    rng = np.random.default_rng(11)
    checks = []
    runner = GgrsRunner(
        app, session,
        read_inputs=lambda hs: {h: np.uint8(rng.integers(0, 16)) for h in hs},
        on_mismatch=lambda e: (_ for _ in ()).throw(e),
    )
    runner.enable_donation = enable_donation
    for _ in range(ticks):
        runner.tick()
        checks.append(runner.checksum)
    runner.finish()
    return checks


def test_donated_op_bit_identical_to_plain():
    app = stress.make_app(512, capacity=512)
    inputs = np.zeros((8, 2), np.uint8)
    status = np.full((8, 2), InputStatus.CONFIRMED, np.int8)
    w1 = app.init_state()
    w2 = app.init_state()
    f1, s1, c1 = app.resim_fn(w1, inputs, status, 0)
    f2, s2, c2 = app.resim_fn_donated(w2, inputs, status, 0)
    assert np.array_equal(np.asarray(c1), np.asarray(c2))
    assert np.array_equal(
        np.asarray(f1.comps["pos"]), np.asarray(f2.comps["pos"])
    )


def test_donation_consumes_input_state():
    app = stress.make_app(128, capacity=128)
    inputs = np.zeros((4, 2), np.uint8)
    status = np.full((4, 2), InputStatus.CONFIRMED, np.int8)
    w = app.init_state()
    leaf = jax.tree.leaves(w.comps)[0]
    app.resim_fn_donated(w, inputs, status, 0)
    assert leaf.is_deleted()


def test_driver_checksums_identical_with_and_without_donation():
    # SyncTest rolls back check_distance frames EVERY tick, so this drives
    # the full Load + leading-Save + donated-dispatch cycle continuously
    factory = lambda: stress.make_app(256, capacity=256)
    with_donation = _run_driver(factory, True)
    without = _run_driver(factory, False)
    assert with_donation == without


def test_driver_donation_fixed_point_model():
    factory = fixed_point.make_app
    with_donation = _run_driver(factory, True, ticks=30, check_distance=5)
    without = _run_driver(factory, False, ticks=30, check_distance=5)
    assert with_donation == without


def test_donation_disabled_under_speculation():
    """Speculation retains pre-dispatch state across the dispatch; the
    driver must never route through the donating fn then."""
    from bevy_ggrs_tpu.ops.speculation import SpeculationConfig

    app = stress.make_app(128, capacity=128)
    session = SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=2, compare_interval=1,
    )
    runner = GgrsRunner(
        app, session,
        speculation=SpeculationConfig(
            candidates_fn=lambda last: np.stack([last, last ^ 1])
        ),
    )
    for _ in range(10):
        runner.tick()  # would raise on a deleted array if donation leaked
    runner.finish()


def test_donation_p2p_under_latency():
    """Round-4 regression shape: a P2P pair over a 3-hop-latency channel
    with flipping inputs forces real rollbacks while the donation path is
    active.  Round 4 shipped this red — the donated fn's compile stall
    tripped the wall-clock disconnect timeout, the 'dead' peer's late
    packets demanded a rollback below the pruned ring, and the driver
    crashed (MissingSnapshotError).  Guards both the attended-quiet
    liveness accounting and ring integrity on the donating dispatch path."""
    from bevy_ggrs_tpu import PlayerType, SessionBuilder, SessionState
    from bevy_ggrs_tpu.models import box_game
    from bevy_ggrs_tpu.session.channel import ChannelNetwork
    from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

    net = ChannelNetwork(latency_hops=3, seed=3)
    socks = [net.endpoint("d0"), net.endpoint("d1")]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"d{1 - i}")
        )
        session = b.start_p2p_session(socks[i])

        def read_inputs(handles, i=i):
            key = {0: "right", 1: "down"}[i]
            return {h: box_game.keys_to_input(**{key: True}) for h in handles}

        r = GgrsRunner(app, session, read_inputs=read_inputs)
        assert r.enable_donation  # the default — this test exists to cover it
        runners.append(r)

    def drive(ticks, dt=1.0 / 60.0):
        for _ in range(ticks):
            net.deliver()
            for r in runners:
                r.update(dt)

    drive(300, dt=0.0)
    assert all(
        r.session.current_state() == SessionState.RUNNING for r in runners
    )
    flip = [0]

    def flipping(handles):
        flip[0] += 1
        return {
            h: box_game.keys_to_input(right=(flip[0] // 5) % 2 == 0)
            for h in handles
        }

    runners[0].read_inputs = flipping
    drive(120)
    # the shape exercised what it claims to: donation fired, rollbacks ran,
    # and no endpoint was (spuriously) dropped
    assert all(r.donated_dispatches > 0 for r in runners)
    assert all(r.rollbacks > 0 for r in runners)
    for r in runners:
        assert all(
            not ep.disconnected for ep in r.session.endpoints.values()
        )
    assert all(r.frame >= 100 for r in runners)
    for _ in range(6):
        shared = sorted(
            set(runners[0].ring.frames()) & set(runners[1].ring.frames())
        )
        if shared:
            break
        drive(1)
    assert shared
    f = shared[-1]
    assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
        runners[1].ring.peek(f)[1]
    )
