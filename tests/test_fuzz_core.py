"""Model-based randomized tests: SnapshotRing and InputQueue against naive
reference models under thousands of random operations (the property version
of the reference's hand-written unit batteries)."""

import numpy as np
import pytest

from bevy_ggrs_tpu.session.input_queue import InputQueue
from bevy_ggrs_tpu.session.events import InputStatus
from bevy_ggrs_tpu.snapshot.ring import MissingSnapshotError, SnapshotRing
from bevy_ggrs_tpu.utils.frames import NULL_FRAME, frame_ge, frame_lt


class NaiveRing:
    """Spec model: ordered list of (frame, value), wrapping-frame order."""

    def __init__(self, depth):
        self.items = []  # ascending by wrapped order
        self.depth = depth

    def push(self, frame, value):
        self.items = [it for it in self.items if frame_lt(it[0], frame)]
        self.items.append((frame, value))
        self.items = self.items[-self.depth:]

    def confirm(self, frame):
        self.items = [it for it in self.items if frame_ge(it[0], frame)]

    def rollback(self, frame):
        keep = [it for it in self.items if not frame_lt(frame, it[0])]
        for f, v in keep:
            if f == frame:
                self.items = keep
                return v
        self.items = []
        raise KeyError(frame)

    def frames(self):
        return [f for f, _ in reversed(self.items)]


@pytest.mark.parametrize("seed", range(5))
def test_ring_matches_model(seed):
    rng = np.random.default_rng(seed)
    ring = SnapshotRing(depth=6)
    model = NaiveRing(6)
    frame = rng.integers(-(2**31), 2**31 - 100)
    for _ in range(2000):
        op = rng.integers(0, 10)
        if op < 6:  # push a newer frame (usual save pattern)
            frame = int(np.int32(frame + rng.integers(1, 3)))
            v = int(rng.integers(0, 1 << 30))
            ring.push(frame, v)
            model.push(frame, v)
        elif op < 7 and model.items:  # re-push an existing frame (replace)
            f = model.items[int(rng.integers(0, len(model.items)))][0]
            v = int(rng.integers(0, 1 << 30))
            ring.push(f, v)
            model.push(f, v)
            frame = f
        elif op < 8 and model.items:  # confirm some stored frame
            f = model.items[int(rng.integers(0, len(model.items)))][0]
            ring.confirm(f)
            model.confirm(f)
        elif op < 9 and model.items:  # rollback to a stored frame
            f = model.items[int(rng.integers(0, len(model.items)))][0]
            assert ring.rollback(f) == model.rollback(f)
            frame = f
        else:  # rollback to a missing frame: both must fail and empty
            f = int(np.int32(frame + 1000))
            with pytest.raises(MissingSnapshotError):
                ring.rollback(f)
            with pytest.raises(KeyError):
                model.rollback(f)
        assert ring.frames() == model.frames(), f"divergence after op {op}"


@pytest.mark.parametrize("seed", range(5))
def test_input_queue_matches_model(seed):
    rng = np.random.default_rng(100 + seed)
    q = InputQueue(input_shape=(), input_dtype=np.uint8, delay=0)
    inputs = {}  # frame -> value (spec model)
    served = {}  # frame -> predicted value we handed out
    first_incorrect = None
    cursor = 0
    for _ in range(3000):
        op = rng.integers(0, 10)
        if op < 4:  # serve a read at/ahead of the cursor
            f = cursor + int(rng.integers(0, 6))
            v, st = q.input_for(f)
            if f in inputs:
                assert st == InputStatus.CONFIRMED and int(v) == inputs[f]
            else:
                assert st == InputStatus.PREDICTED
                # PredictRepeatLast: nearest stored frame at/below f, else 0
                below = [g for g in inputs if g <= f]
                expect = inputs[max(below)] if below else 0
                assert int(v) == expect
                served[f] = int(v)
            cursor = max(cursor, f)
        elif op < 9:  # a (possibly redundant) input arrives in order
            nxt = max(inputs) + 1 if inputs else 0
            f = int(rng.integers(max(nxt - 3, 0), nxt + 1))  # redundancy
            val = int(rng.integers(0, 4))
            q.add_remote(f, np.uint8(val))
            if f >= nxt:  # model: only new frames accepted
                inputs[f] = val
                if f in served and served[f] != val:
                    if first_incorrect is None or f < first_incorrect:
                        first_incorrect = f
                served.pop(f, None)
        else:  # take/compare first incorrect
            got = q.take_first_incorrect()
            expect = NULL_FRAME if first_incorrect is None else first_incorrect
            assert got == expect
            first_incorrect = None
    assert q.last_confirmed == (max(inputs) if inputs else NULL_FRAME)


@pytest.mark.parametrize("seed", range(5))
def test_input_queue_out_of_order_matches_model(seed):
    """Out-of-order arrivals (reordered/refilled chunks): last_confirmed must
    be the CONTIGUOUS high-water mark anchored at the stream base."""
    rng = np.random.default_rng(200 + seed)
    base = int(rng.integers(0, 5))
    q = InputQueue(input_shape=(), input_dtype=np.uint8)
    q.set_base(base)
    truth = {}  # frame -> value, arrival in any order
    pending = list(rng.permutation(np.arange(base, base + 60)))
    while pending:
        # deliver a random prefix chunk (simulates packet ranges landing oo)
        take = int(rng.integers(1, 5))
        for _ in range(min(take, len(pending))):
            f = int(pending.pop())
            v = int(rng.integers(0, 7))
            q.add_remote(f, np.uint8(v))
            truth.setdefault(f, v)
        # model: contiguous mark from base
        lc = base - 1
        while lc + 1 in truth:
            lc += 1
        expect = lc if lc >= base else -1
        assert q.last_confirmed == expect
    # everything delivered: fully contiguous
    assert q.last_confirmed == base + 59
    for f, v in truth.items():
        got = q.confirmed_input(f)
        assert got is not None and int(got) == v
