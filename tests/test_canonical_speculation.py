"""Canonical-branched speculation: hedging + bit-determinism together.

The same lossy/reordered vector-input scenario that desyncs under
per-length programs must stay in sync when BOTH peers dispatch the one
canonical [branches, depth] program — with one peer actively hedging (cache
hits) and the other running dummy lanes."""

import dataclasses
import time

import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu import (
    App,
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
    SpeculationConfig,
)
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.snapshot import active_mask, spawn
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0
B, K = 4, 12


def make_app():
    app = App(num_players=2, capacity=4, input_shape=(), input_dtype=np.uint8,
              canonical_depth=K, canonical_branches=B)
    app.rollback_component("pos", (2,), jnp.float32, checksum=True)
    app.rollback_component("handle", (), jnp.int32, checksum=True)

    def step(world, ctx):
        h = world.comps["handle"]
        m = active_mask(world) & world.has["handle"]
        v = ctx.inputs.astype(jnp.float32) / 7.0 - 1.0  # division: FMA-bait
        delta = jnp.stack([v, -v], axis=-1)[jnp.clip(h, 0, 1)]
        pos = world.comps["pos"] + jnp.where(m[:, None], delta, 0.0)
        return dataclasses.replace(world, comps={**world.comps, "pos": pos})

    def setup(world):
        for h in range(2):
            world, _ = spawn(app.reg, world, {"pos": np.zeros(2), "handle": h})
        return world

    app.set_step(step)
    app.set_setup(setup)
    return app


def test_hedged_and_plain_peers_stay_bit_identical():
    net = ChannelNetwork(latency_hops=3, loss=0.1, jitter_hops=2, seed=5)
    socks = [net.endpoint("a"), net.endpoint("b")]
    runners = []
    for i in range(2):
        app = make_app()
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .with_disconnect_timeout(60.0)
            .with_disconnect_notify_delay(30.0)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a")
        )
        session = b.start_p2p_session(socks[i])
        # only peer 0 hedges; peer 1 runs the same program with dummy lanes
        spec = (
            SpeculationConfig(
                candidates_fn=lambda used: np.arange(B - 1, dtype=np.uint8)[
                    :, None
                ].repeat(2, axis=1),
            )
            if i == 0
            else None
        )
        tick = [0]

        def read_inputs(handles, i=i, tick=tick):
            tick[0] += 1
            val = (tick[0] // 6) % 3  # cycles 0,1,2 — hedged by candidates
            return {h: np.uint8(val) for h in handles}

        runners.append(
            GgrsRunner(app, session, read_inputs=read_inputs, speculation=spec)
        )

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.002)
    assert all(r.session.current_state() == SessionState.RUNNING for r in runners)

    for _ in range(150):
        net.deliver()
        for r in runners:
            r.update(DT)

    s0 = runners[0].stats()
    assert s0["rollbacks"] > 0
    assert s0["speculation_hits"] > 0, f"hedging never hit: {s0}"

    # bit-identical at confirmed frames despite asymmetric hedging
    f = None
    for _ in range(40):
        conf = min(r.session.confirmed_frame() for r in runners)
        shared = [
            fr
            for fr in set(runners[0].ring.frames()) & set(runners[1].ring.frames())
            if fr <= conf
        ]
        if shared:
            f = max(shared)
            break
        net.deliver()
        (runners[0] if runners[0].frame <= runners[1].frame else runners[1]).update(DT)
    assert f is not None
    assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
        runners[1].ring.peek(f)[1]
    ), "hedged peer diverged from plain peer"
