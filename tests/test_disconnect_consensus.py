"""Disconnect-frame consensus (GGPO-style): when a peer dies mid-game, the
survivors may have received DIFFERENT amounts of its input stream.  Each
survivor announces the last real frame it holds (T_DISC_NOTICE) and all
adopt the MINIMUM, truncating richer knowledge and resimulating everything
past the consensus frame under the disconnect policy — so the survivors'
simulations stay bit-identical after the death.  Also covers the
_inputs_for fix: a deep rollback spanning PRE-disconnect frames must
replay the dead player's real confirmed inputs, not zeros.

All timing here runs on a VIRTUAL protocol clock (monkeypatched now_s):
timeouts, notice-rebroadcast windows, and detection latencies advance one
frame per driven tick, so the tests are deterministic and immune to the
wall-clock starvation (jit compiles, loaded CI boxes) that made earlier
versions flaky."""

import numpy as np
import pytest

from bevy_ggrs_tpu import (
    DesyncDetection,
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session import p2p as p2p_mod
from bevy_ggrs_tpu.session import protocol
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.session.events import DesyncDetected, Disconnected
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int
from bevy_ggrs_tpu.utils.frames import NULL_FRAME

DT = 1.0 / 60.0


@pytest.fixture
def vclock(monkeypatch):
    """Virtual protocol clock: every endpoint timer (sync retries,
    keepalives, attended-quiet disconnect timers, notice rebroadcast)
    advances only when a test drives it."""
    c = {"t": 1000.0}
    monkeypatch.setattr(protocol, "now_s", lambda: c["t"])
    monkeypatch.setattr(p2p_mod, "now_s", lambda: c["t"])
    return c


def _trio(seed, latency=1, loss=0.0, timeout=0.6):
    net = ChannelNetwork(latency_hops=latency, loss=loss, seed=seed)
    names = ["s0", "s1", "s2"]
    socks = [net.endpoint(n) for n in names]
    rngs = [np.random.default_rng(500 + 10 * seed + i) for i in range(3)]
    runners = []
    for i in range(3):
        app = box_game.make_app(num_players=3)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .with_max_prediction_window(8)
            .with_disconnect_timeout(timeout)
            .with_disconnect_notify_delay(timeout / 3)
            .with_desync_detection_mode(DesyncDetection.on(5))
            .add_player(PlayerType.LOCAL, i)
        )
        for j in range(3):
            if j != i:
                b.add_player(PlayerType.REMOTE, j, names[j])
        session = b.start_p2p_session(socks[i])

        def read_inputs(handles, i=i):
            return {h: np.uint8(rngs[i].integers(0, 16)) for h in handles}

        runners.append(GgrsRunner(app, session, read_inputs=read_inputs))
    return net, runners


def _drive(vclock, net, runners, ticks, dt=DT):
    for _ in range(ticks):
        vclock["t"] += DT
        net.deliver()
        for r in runners:
            r.update(dt)


def _sync(vclock, net, runners, max_ticks=3000):
    for _ in range(max_ticks):
        vclock["t"] += DT
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(
            r.session.current_state() == SessionState.RUNNING for r in runners
        ):
            return True
    return False


def _confirmed_agreement(survivors, drive, attempts=120):
    """Newest mutually-held, mutually-confirmed ring frame must agree."""
    for _ in range(attempts):
        conf = min(r.session.confirmed_frame() for r in survivors)
        shared = set(survivors[0].ring.frames())
        for r in survivors[1:]:
            shared &= set(r.ring.frames())
        shared = [f for f in shared if f <= conf]
        if shared:
            f = max(shared)
            cs = [checksum_to_int(r.ring.peek(f)[1]) for r in survivors]
            return f, cs
        drive()
    return None, None


@pytest.mark.parametrize("seed,kill_tick,loss", [
    (1, 45, 0.0),
    (2, 60, 0.1),
    (3, 53, 0.2),
])
def test_survivors_converge_after_mid_game_death(vclock, seed, kill_tick, loss):
    net, runners = _trio(seed, latency=1, loss=loss)
    assert _sync(vclock, net, runners)
    # play with all three, then peer 2 dies abruptly (process-death analog:
    # no LEAVE, packets just stop)
    _drive(vclock, net, runners, kill_tick)
    survivors = runners[:2]
    # survivors keep ticking; the virtual clock carries the attended-quiet
    # timeout (0.6 s = 36 ticks of silence)
    saw_disc = [False, False]
    for _ in range(600):
        _drive(vclock, net, survivors, 1)
        for i, r in enumerate(survivors):
            saw_disc[i] = saw_disc[i] or any(
                isinstance(e, Disconnected) for e in r.events
            )
        if all(saw_disc):
            break
    assert all(saw_disc), "survivors never dropped the dead peer"

    _drive(vclock, net, survivors, 120)
    cf = [r.session._disc_frame.get(2) for r in survivors]
    assert all(c is not None for c in cf), cf

    # both made clean progress past the death
    assert all(r.frame >= kill_tick + 60 for r in survivors)

    def drive():
        _drive(vclock, net, survivors, 1)

    f, cs = _confirmed_agreement(survivors, drive)
    assert f is not None, "survivors share no confirmed frame"
    # bit-identical is the normal outcome — and cf values may DIFFER while
    # still harmless: the confirmed-floor clamp can adopt a frame above
    # last_confirmed, where the queue holds nothing, so both survivors
    # bake identical DISCONNECTED/zero inputs anyway.
    if cs[0] != cs[1]:
        # genuinely divergent (the documented residual race: one survivor
        # confirmed a frame of the dead stream the other never received):
        # the desync-detection backstop MUST surface it, never silent
        assert cf[0] != cf[1], (
            f"desync at frame {f} with EQUAL consensus frames {cf}: {cs}"
        )
        saw_desync = False
        for _ in range(900):
            drive()
            for r in survivors:
                saw_desync = saw_desync or any(
                    isinstance(e, DesyncDetected) for e in r.events
                )
            if saw_desync:
                break
        assert saw_desync, (
            f"split {cf} diverged at frame {f} but no DesyncDetected"
        )


def test_notice_fast_propagates_disconnect(vclock):
    """A survivor that learns of a death via T_DISC_NOTICE drops the dead
    peer immediately (consistency over liveness) instead of waiting out its
    own timeout — proven by giving survivor 1 a 600 s timer it never gets
    to use: only the notice from survivor 0 (0.6 s timer) can be the
    trigger.  Both then hold the SAME consensus frame and stay
    checksum-identical."""
    net, runners = _trio(seed=9, timeout=0.6)
    assert _sync(vclock, net, runners)
    s0, s1 = runners[0].session, runners[1].session
    for ep in s1.endpoints.values():
        ep.disconnect_timeout_s = 600.0  # s1 can only learn via the notice
    _drive(vclock, net, runners, 20)
    # peer 2 dies for real (never updated again)
    survivors = runners[:2]
    ticks_to_disc = None
    for t in range(1200):
        _drive(vclock, net, survivors, 1)
        if s1.endpoints["s2"].disconnected:
            ticks_to_disc = t
            break
    assert ticks_to_disc is not None
    # s0's timer is 36 ticks of virtual silence; the notice reaches s1
    # within a few more — far under the 36000-tick timer s1 would need
    assert ticks_to_disc < 120, ticks_to_disc
    _drive(vclock, net, survivors, 60)
    assert s1._disc_frame.get(2) is not None
    assert s1._disc_frame.get(2) == s0._disc_frame.get(2)

    def drive():
        _drive(vclock, net, survivors, 1)

    f, cs = _confirmed_agreement(survivors, drive)
    assert f is not None
    assert cs[0] == cs[1], f"survivors desynced at frame {f}: {cs}"


def test_deep_rollback_replays_real_inputs_of_dead_peer(vclock):
    """_inputs_for regression: after a disconnect, frames AT OR BEFORE the
    consensus frame must resimulate with the dead player's real confirmed
    inputs — a rollback spanning them used to zero them out and desync the
    survivor from its own ring."""
    net, runners = _trio(seed=5, latency=2)
    assert _sync(vclock, net, runners)
    _drive(vclock, net, runners, 30)
    s0 = runners[0].session
    cf = s0._disc_frame.get(2, None)
    assert cf is None  # nobody dead yet
    # record what the sim used for a confirmed frame of peer 2
    probe = s0.queues[2].last_confirmed
    assert probe != NULL_FRAME
    real = np.array(s0.queues[2].confirmed_input(probe), copy=True)
    # peer 2 dies; survivor adopts
    s0.endpoints["s2"].disconnected = True
    s0.poll_remote_clients()
    adopted = s0._disc_frame.get(2)
    assert adopted is not None
    from bevy_ggrs_tpu.session.events import InputStatus

    # pre-consensus frames: real input, CONFIRMED status
    if probe <= adopted:
        inputs, status = s0._inputs_for(probe)
        assert np.array_equal(inputs[2], real)
        assert status[2] == InputStatus.CONFIRMED
    # post-consensus frames: zeros, DISCONNECTED status
    inputs, status = s0._inputs_for(adopted + 3)
    assert status[2] == InputStatus.DISCONNECTED
    assert not np.any(inputs[2])


def test_notice_adopts_all_handles_of_multi_handle_peer():
    """A T_DISC_NOTICE names ONE handle, but the dead peer may own several:
    marking it disconnected must adopt a consensus frame for EVERY handle
    from local knowledge (the announcer's notices for the other handles may
    be lost within their rebroadcast window)."""
    net = ChannelNetwork()
    app = box_game.make_app(num_players=4)
    b = (
        SessionBuilder.for_app(app)
        .with_input_delay(1)
        .add_player(PlayerType.LOCAL, 0)
        .add_player(PlayerType.REMOTE, 1, "X")  # X owns handles 1 AND 2
        .add_player(PlayerType.REMOTE, 2, "X")
        .add_player(PlayerType.REMOTE, 3, "Y")
    )
    s = b.start_p2p_session(net.endpoint("me"))
    cb = s._make_on_disc_notice("Y")  # announcer is the OTHER peer
    cb(1, 5)  # notice about one of X's handles only
    assert s.endpoints["X"].disconnected
    assert 1 in s._disc_frame
    assert 2 in s._disc_frame  # the un-noticed handle adopted too
    assert not s.endpoints["Y"].disconnected


def test_spectator_replays_host_statuses_after_death(vclock):
    """The host streams the per-player STATUS its own sim used alongside
    the inputs: after a peer dies, the spectator must replay the dead
    handle as DISCONNECTED (not CONFIRMED zeros) and stay bit-identical
    to the host — closing the status-sensitivity gap for models that
    branch on InputStatus."""
    from bevy_ggrs_tpu.session.events import InputStatus

    net = ChannelNetwork(latency_hops=1, seed=21)
    names = ["h0", "h1"]
    socks = [net.endpoint(n) for n in names]
    spec_sock = net.endpoint("spec")
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .with_disconnect_timeout(0.6)
            .with_disconnect_notify_delay(0.2)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, names[1 - i])
        )
        if i == 0:
            b.add_player(PlayerType.SPECTATOR, 2, "spec")
        session = b.start_p2p_session(socks[i])
        runners.append(GgrsRunner(
            app, session,
            read_inputs=lambda hs, i=i: {
                h: box_game.keys_to_input(right=(i == 0), down=(i == 1))
                for h in hs
            },
        ))
    spec_app = box_game.make_app(num_players=2)
    spec_session = (
        SessionBuilder.for_app(spec_app)
        .with_catchup_speed(4)
        .start_spectator_session("h0", spec_sock)
    )
    spec_runner = GgrsRunner(spec_app, spec_session)
    everyone = runners + [spec_runner]
    for _ in range(3000):
        vclock["t"] += DT
        net.deliver()
        for r in everyone:
            r.update(0.0)
        if all(
            r.session.current_state() == SessionState.RUNNING for r in everyone
        ):
            break
    assert all(
        r.session.current_state() == SessionState.RUNNING for r in everyone
    )
    for _ in range(30):
        vclock["t"] += DT
        net.deliver()
        for r in everyone:
            r.update(DT)
    # peer h1 dies; host + spectator keep ticking
    alive = [runners[0], spec_runner]
    for _ in range(300):
        vclock["t"] += DT
        net.deliver()
        for r in alive:
            r.update(DT)
        if runners[0].session.endpoints["h1"].disconnected:
            break
    assert runners[0].session.endpoints["h1"].disconnected
    cf = runners[0].session._disc_frame.get(1)
    assert cf is not None
    for _ in range(120):
        vclock["t"] += DT
        net.deliver()
        for r in alive:
            r.update(DT)
    # a post-consensus row received by the spectator carries DISCONNECTED
    rows = {
        f: st for f, (_, st) in spec_session._inputs.items() if f > cf + 1
    }
    if not rows:
        # all consumed: look at what it WILL receive next
        for _ in range(30):
            vclock["t"] += DT
            net.deliver()
            runners[0].update(DT)
            spec_session.poll_remote_clients()
            rows = {
                f: st
                for f, (_, st) in spec_session._inputs.items()
                if f > cf + 1
            }
            if rows:
                break
    assert rows, "spectator received no post-consensus rows"
    f, st = max(rows.items())
    assert st[1] == InputStatus.DISCONNECTED, (f, st)
    assert st[0] == InputStatus.CONFIRMED
    # and the spectator's world matches the host's, frame for frame: the
    # solo host prunes its ring to one frame and the spectator trails a
    # constant couple of frames, so compare against a recorded history of
    # the host's live checksums instead of ring overlap
    host_cs = {}
    matched = 0
    last_spec = None
    for _ in range(60):
        host_cs[runners[0].frame] = runners[0].checksum
        if spec_runner.frame != last_spec:
            last_spec = spec_runner.frame
            if last_spec in host_cs:
                assert spec_runner.checksum == host_cs[last_spec], (
                    last_spec,
                    hex(spec_runner.checksum),
                    hex(host_cs[last_spec]),
                )
                matched += 1
        vclock["t"] += DT
        net.deliver()
        for r in alive:
            r.update(DT)
    assert matched >= 10, f"only {matched} spectator frames verified"
