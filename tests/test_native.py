"""Native C++ core tests: native<->native box_game over loopback UDP, and
wire interop — a NATIVE peer playing a PYTHON peer must converge to
identical confirmed checksums (same protocol, same prediction semantics)."""

import time

import numpy as np
import pytest

from bevy_ggrs_tpu import (
    DesyncDetection,
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
    UdpNonBlockingSocket,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.native import native_available
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native ggrs_core not built"
)

DT = 1.0 / 60.0


def assert_checksums_agree(r0, r1):
    """Align the two runners (confirmed ~ current on loopback, so rings can
    be offset by one frame) and compare checksums for a shared frame."""
    got = None
    for _ in range(6):
        shared = sorted(set(r0.ring.frames()) & set(r1.ring.frames()))
        if shared:
            f = shared[-1]
            got = (
                f,
                checksum_to_int(r0.ring.peek(f)[1]),
                checksum_to_int(r1.ring.peek(f)[1]),
            )
            break
        behind = r0 if r0.frame <= r1.frame else r1
        behind.update(DT)
    assert got is not None, "rings share no frame"
    _, c0, c1 = got
    assert c0 == c1, f"checksum divergence at frame {got[0]}"


def sync_all(runners, max_iters=400):
    for _ in range(max_iters):
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            return True
        time.sleep(0.001)
    return False


def interleave(runners, ticks):
    for _ in range(ticks):
        for r in runners:
            r.update(DT)


def make_native_runner(i, my_port, peer_port, input_delay=2):
    app = box_game.make_app(num_players=2)
    b = (
        SessionBuilder.for_app(app)
        .with_input_delay(input_delay)
        .add_player(PlayerType.LOCAL, i)
        .add_player(PlayerType.REMOTE, 1 - i, ("127.0.0.1", peer_port))
    )
    session = b.start_p2p_session_native(local_port=my_port)

    def read_inputs(handles, i=i):
        key = {0: "right", 1: "up"}[i]
        return {h: box_game.keys_to_input(**{key: True}) for h in handles}

    return GgrsRunner(app, session, read_inputs=read_inputs)


def free_ports(n):
    import socket as so

    socks = [so.socket(so.AF_INET, so.SOCK_DGRAM) for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def test_native_vs_native_smoke():
    p0, p1 = free_ports(2)
    r0 = make_native_runner(0, p0, p1)
    r1 = make_native_runner(1, p1, p0)
    assert sync_all([r0, r1])
    x0 = float(r1.world.comps["pos"][0, 0])
    interleave([r0, r1], 60)
    assert r0.frame >= 50 and r1.frame >= 50
    # remote input visible on the other peer
    assert float(r1.world.comps["pos"][0, 0]) > x0
    # confirmed checksums agree
    assert_checksums_agree(r0, r1)


def test_native_vs_python_wire_interop():
    p_native, p_python = free_ports(2)
    r_native = make_native_runner(0, p_native, p_python)

    app = box_game.make_app(num_players=2)
    sock = UdpNonBlockingSocket(p_python, host="0.0.0.0")
    b = (
        SessionBuilder.for_app(app)
        .with_input_delay(2)
        .add_player(PlayerType.LOCAL, 1)
        .add_player(PlayerType.REMOTE, 0, ("127.0.0.1", p_native))
    )
    session = b.start_p2p_session(sock)
    r_python = GgrsRunner(
        app, session,
        read_inputs=lambda hs: {h: box_game.keys_to_input(up=True) for h in hs},
    )
    assert sync_all([r_native, r_python])
    interleave([r_native, r_python], 80)
    assert r_native.frame >= 60 and r_python.frame >= 60
    assert min(
        r_native.session.confirmed_frame(), r_python.session.confirmed_frame()
    ) > 30
    assert_checksums_agree(r_native, r_python)
    sock.close()


def test_native_desync_detection():
    import dataclasses

    p0, p1 = free_ports(2)
    runners = []
    for i, (mine, theirs) in enumerate([(p0, p1), (p1, p0)]):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_desync_detection_mode(DesyncDetection.on(5))
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, ("127.0.0.1", theirs))
        )
        session = b.start_p2p_session_native(local_port=mine)
        runners.append(GgrsRunner(app, session))
    assert sync_all(runners)
    interleave(runners, 30)
    w = runners[1].world
    runners[1].world = dataclasses.replace(
        w, comps={**w.comps, "pos": w.comps["pos"] + 3.0}
    )
    runners[1]._world_checksum = runners[1].app.checksum_fn(runners[1].world)
    from bevy_ggrs_tpu.session.events import DesyncDetected

    for _ in range(120):
        interleave(runners, 5)
        time.sleep(0.002)
        desyncs = [
            e for r in runners for e in r.events if isinstance(e, DesyncDetected)
        ]
        if desyncs:
            break
    assert desyncs
    # the native event carries BOTH checksums (GgrsEvent::DesyncDetected
    # surface, reference examples/stress_tests/particles.rs:299-314)
    for e in desyncs:
        assert e.local_checksum is not None
        assert e.remote_checksum is not None
        assert e.local_checksum != e.remote_checksum


def test_native_stall_without_remote():
    p0, p1 = free_ports(2)
    r0 = make_native_runner(0, p0, p1, input_delay=0)
    # fake peer: reply to sync requests only, never send inputs
    from bevy_ggrs_tpu.session.protocol import (
        HDR, MAGIC, PROTOCOL_VERSION, S_SYNC_REP, S_SYNC_REQ,
        T_SYNC_REQ, T_SYNC_REP,
    )

    sock = UdpNonBlockingSocket(p1, host="0.0.0.0")
    for _ in range(200):
        r0.update(0.0)
        for addr, data in sock.receive_all():
            magic, t = HDR.unpack_from(data)
            if t == T_SYNC_REQ:
                nonce, _ver = S_SYNC_REQ.unpack_from(data[HDR.size:])
                sock.send_to(
                    HDR.pack(MAGIC, T_SYNC_REP)
                    + S_SYNC_REP.pack(nonce, PROTOCOL_VERSION),
                    addr,
                )
        if r0.session.current_state() == SessionState.RUNNING:
            break
        time.sleep(0.001)
    assert r0.session.current_state() == SessionState.RUNNING
    interleave([r0], 30)
    assert r0.frame <= 9  # max_prediction 8 + initial frame
    assert r0.stalled_frames > 0
    sock.close()


def test_native_host_python_spectator():

    p0, p1, p_spec = free_ports(3)
    # native host (streams to the spectator) + native peer
    app0 = box_game.make_app(num_players=2)
    b0 = (
        SessionBuilder.for_app(app0)
        .with_input_delay(1)
        .add_player(PlayerType.LOCAL, 0)
        .add_player(PlayerType.REMOTE, 1, ("127.0.0.1", p1))
        .add_player(PlayerType.SPECTATOR, 2, ("127.0.0.1", p_spec))
    )
    r0 = GgrsRunner(
        app0, b0.start_p2p_session_native(local_port=p0),
        read_inputs=lambda hs: {h: box_game.keys_to_input(right=True) for h in hs},
    )
    r1 = make_native_runner(1, p1, p0, input_delay=1)

    spec_app = box_game.make_app(num_players=2)
    spec_sock = UdpNonBlockingSocket(p_spec, host="0.0.0.0")
    spec_session = SessionBuilder.for_app(spec_app).start_spectator_session(
        ("127.0.0.1", p0), spec_sock
    )
    r_spec = GgrsRunner(spec_app, spec_session)
    everyone = [r0, r1, r_spec]
    assert sync_all(everyone)
    interleave(everyone, 100)
    assert r_spec.frame > 20
    assert float(r_spec.world.comps["pos"][0, 0]) > 1.9  # replayed movement
    spec_sock.close()


def test_native_spectator_follows_python_host():
    from bevy_ggrs_tpu import SessionBuilder as SB

    p_host, p_peer, p_spec = free_ports(3)
    # python host streaming to a NATIVE spectator; python remote peer
    app0 = box_game.make_app(num_players=2)
    sock0 = UdpNonBlockingSocket(p_host, host="0.0.0.0")
    b0 = (
        SB.for_app(app0)
        .with_input_delay(1)
        .add_player(PlayerType.LOCAL, 0)
        .add_player(PlayerType.REMOTE, 1, ("127.0.0.1", p_peer))
        .add_player(PlayerType.SPECTATOR, 2, ("127.0.0.1", p_spec))
    )
    r0 = GgrsRunner(
        app0, b0.start_p2p_session(sock0),
        read_inputs=lambda hs: {h: box_game.keys_to_input(right=True) for h in hs},
    )
    app1 = box_game.make_app(num_players=2)
    sock1 = UdpNonBlockingSocket(p_peer, host="0.0.0.0")
    b1 = (
        SB.for_app(app1)
        .with_input_delay(1)
        .add_player(PlayerType.REMOTE, 0, ("127.0.0.1", p_host))
        .add_player(PlayerType.LOCAL, 1)
    )
    r1 = GgrsRunner(app1, b1.start_p2p_session(sock1))

    spec_app = box_game.make_app(num_players=2)
    spec_session = SB.for_app(spec_app).start_spectator_session_native(
        ("127.0.0.1", p_host), local_port=p_spec
    )
    r_spec = GgrsRunner(spec_app, spec_session)
    everyone = [r0, r1, r_spec]
    assert sync_all(everyone)
    interleave(everyone, 100)
    assert r_spec.frame > 20
    assert float(r_spec.world.comps["pos"][0, 0]) > 1.9
    sock0.close()
    sock1.close()


def test_native_spectator_catchup():
    """Lag a NATIVE spectator behind a python host, then assert it closes
    the gap at 1 + catchup_speed frames per tick (mirrors
    test_p2p.py::test_spectator_catchup; C++ side: ggrs_spectator_advance's
    catch-up loop, /root/reference/tests/p2p.rs:202-260 for the pattern)."""
    from bevy_ggrs_tpu import SessionBuilder as SB

    catchup = 3
    p_host, p_peer, p_spec = free_ports(3)
    app0 = box_game.make_app(num_players=2)
    sock0 = UdpNonBlockingSocket(p_host, host="0.0.0.0")
    b0 = (
        SB.for_app(app0)
        .with_input_delay(1)
        .add_player(PlayerType.LOCAL, 0)
        .add_player(PlayerType.REMOTE, 1, ("127.0.0.1", p_peer))
        .add_player(PlayerType.SPECTATOR, 2, ("127.0.0.1", p_spec))
    )
    r0 = GgrsRunner(
        app0, b0.start_p2p_session(sock0),
        read_inputs=lambda hs: {h: box_game.keys_to_input(right=True) for h in hs},
    )
    app1 = box_game.make_app(num_players=2)
    sock1 = UdpNonBlockingSocket(p_peer, host="0.0.0.0")
    b1 = (
        SB.for_app(app1)
        .with_input_delay(1)
        .add_player(PlayerType.REMOTE, 0, ("127.0.0.1", p_host))
        .add_player(PlayerType.LOCAL, 1)
    )
    r1 = GgrsRunner(app1, b1.start_p2p_session(sock1))

    spec_app = box_game.make_app(num_players=2)
    spec_session = (
        SB.for_app(spec_app)
        .with_catchup_speed(catchup)
        .start_spectator_session_native(("127.0.0.1", p_host), local_port=p_spec)
    )
    r_spec = GgrsRunner(spec_app, spec_session)
    everyone = [r0, r1, r_spec]
    assert sync_all(everyone)

    lag = 40
    interleave([r0, r1], lag)
    r_spec.update(0.0)  # drain only
    assert spec_session.frames_behind_host() > 2 * catchup

    behind0 = spec_session.frames_behind_host()
    deltas = []
    for _ in range(lag):
        before = r_spec.frame
        interleave(everyone, 1)
        deltas.append(r_spec.frame - before)
        if spec_session.frames_behind_host() <= 2:
            break
    assert max(deltas) == 1 + catchup
    assert spec_session.frames_behind_host() <= 2
    assert len(deltas) <= behind0 // catchup + 3
    assert float(r_spec.world.comps["pos"][0, 0]) > 1.9
    sock0.close()
    sock1.close()


def test_native_three_peer_disconnect_consensus():
    """C++ core parity for the disconnect-frame consensus: a 3-peer native
    full mesh loses one peer mid-game; both survivors drop it (one possibly
    via the notice), keep advancing, and stay checksum-identical at
    mutually confirmed ring frames."""
    from bevy_ggrs_tpu import SessionBuilder as SB
    from bevy_ggrs_tpu.session.events import Disconnected
    from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

    ports = free_ports(3)
    runners = []
    for i in range(3):
        app = box_game.make_app(num_players=3)
        b = (
            SB.for_app(app)
            .with_input_delay(1)
            .with_max_prediction_window(8)
            .with_disconnect_timeout(0.6)
            .with_disconnect_notify_delay(0.2)
            .add_player(PlayerType.LOCAL, i)
        )
        for j in range(3):
            if j != i:
                b.add_player(PlayerType.REMOTE, j, ("127.0.0.1", ports[j]))
        session = b.start_p2p_session_native(local_port=ports[i])
        rng = np.random.default_rng(70 + i)
        runners.append(GgrsRunner(
            app, session,
            read_inputs=lambda hs, r=rng: {
                h: np.uint8(r.integers(0, 16)) for h in hs
            },
        ))
    assert sync_all(runners)
    for _ in range(60):
        interleave(runners, 1)
        time.sleep(0.001)
    # peer 2 dies abruptly
    survivors = runners[:2]
    saw = [False, False]
    deadline = time.monotonic() + 12.0
    while time.monotonic() < deadline:
        for i, r in enumerate(survivors):
            r.update(DT)
            saw[i] = saw[i] or any(
                isinstance(e, Disconnected) for e in r.events
            )
        if all(saw):
            break
        time.sleep(0.004)
    assert all(saw), "survivors never dropped the dead peer"
    before = [r.frame for r in survivors]
    for _ in range(150):
        for r in survivors:
            r.update(DT)
        time.sleep(0.001)
    assert all(
        r.frame >= b + 100 for r, b in zip(survivors, before)
    ), [r.frame for r in survivors]
    f = None
    for _ in range(60):
        conf = min(r.session.confirmed_frame() for r in survivors)
        shared = [
            fr
            for fr in set(survivors[0].ring.frames())
            & set(survivors[1].ring.frames())
            if fr <= conf
        ]
        if shared:
            f = max(shared)
            break
        for r in survivors:
            r.update(DT)
    assert f is not None, "no mutually confirmed ring frame"
    cs = [checksum_to_int(r.ring.peek(f)[1]) for r in survivors]
    assert cs[0] == cs[1], f"native survivors desynced at frame {f}: {cs}"
