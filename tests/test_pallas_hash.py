"""Pallas checksum kernel: must produce BIT-IDENTICAL checksums to the jnp
reference implementation (same per-entity fold, exact uint32 block sums).
Runs in interpret mode on the CPU test mesh; compiles natively on TPU."""

import jax
import numpy as np
import pytest

from bevy_ggrs_tpu.models import stress, particles
from bevy_ggrs_tpu.ops.pallas_hash import world_checksum_pallas
from bevy_ggrs_tpu.snapshot.checksum import world_checksum


@pytest.mark.parametrize("n", [100, 512, 1000])
def test_pallas_matches_jnp_checksum(n):
    app = stress.make_app(n_entities=n, capacity=n)
    w = app.init_state()
    ref = np.asarray(world_checksum(app.reg, w))
    got = np.asarray(world_checksum_pallas(app.reg, w, interpret=True))
    assert np.array_equal(ref, got)


def test_pallas_matches_with_masks_and_resources():
    app = particles.make_app(rate=16, ttl=8, capacity=300)
    w = app.init_state()
    # run a few frames so masks/ids/resources are non-trivial
    inputs = np.zeros((4, 2), np.uint8)
    status = np.zeros((4, 2), np.int8)
    w, _, _ = app.resim_fn(w, inputs, status, 0)
    ref = np.asarray(world_checksum(app.reg, w))
    got = np.asarray(world_checksum_pallas(app.reg, w, interpret=True))
    assert np.array_equal(ref, got)


def test_pallas_jittable():
    app = stress.make_app(n_entities=256, capacity=256)
    w = app.init_state()
    fn = jax.jit(lambda w: world_checksum_pallas(app.reg, w, interpret=True))
    assert np.array_equal(np.asarray(fn(w)), np.asarray(world_checksum(app.reg, w)))
