"""Determinism oracles: the fixed-point model is bit-exact and
checksum-stable across jit/eager, device counts, and (via scripts/
parity_check.py on real hardware) across CPU/TPU backends."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import fixed_point
from bevy_ggrs_tpu.session.events import InputStatus
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int


def _inputs(k, p=2):
    rng = np.random.default_rng(7)
    return (
        rng.integers(0, 16, (k, p)).astype(np.uint8),
        np.full((k, p), InputStatus.CONFIRMED, np.int8),
    )


def test_fixed_point_synctest_clean():
    app = fixed_point.make_app()
    session = SyncTestSession(num_players=2, input_shape=(),
                              input_dtype=np.uint8, check_distance=5)
    mismatches = []
    rng = np.random.default_rng(3)
    runner = GgrsRunner(
        app, session,
        read_inputs=lambda hs: {h: np.uint8(rng.integers(0, 16)) for h in hs},
        on_mismatch=mismatches.append,
    )
    for _ in range(30):
        runner.tick()
    assert mismatches == []
    assert int(jnp.abs(runner.world.comps["vel"]).max()) > 0  # actually moved


def test_fixed_point_eager_vs_jit_bit_exact():
    app = fixed_point.make_app()
    world = app.init_state()
    inputs, status = _inputs(8)
    from bevy_ggrs_tpu.ops.resim import resim

    eager = resim(app.reg, app.step, world, inputs, status, 0, app.retention,
                  app.fps, 0)
    jitted = app.resim_fn(world, inputs, status, 0, -1)
    assert np.array_equal(np.asarray(eager[2]), np.asarray(jitted[2]))
    assert np.array_equal(
        np.asarray(eager[0].comps["pos"]), np.asarray(jitted[0].comps["pos"])
    )


def test_fixed_point_checksum_stable_across_runs():
    app = fixed_point.make_app()
    inputs, status = _inputs(12)
    cs = []
    for _ in range(2):
        world = app.init_state()
        _, _, checks = app.resim_fn(world, inputs, status, 0, -1)
        cs.append(checksum_to_int(np.asarray(checks)[-1]))
    assert cs[0] == cs[1]
    # the value is pinned so any cross-backend run can compare against it:
    # scripts/parity_check.py recomputes this on the TPU backend
    assert cs[0] != 0


def test_canonical_mode_is_segmentation_stable():
    """Program-variant rounding regression: under canonical_depth, any
    segmentation of the same frame sequence is bit-identical (without it,
    the k=1 vs k=8 programs measurably differ on this arithmetic)."""
    import sys

    sys.path.insert(0, "tests")
    from test_soak_vector_inputs import make_stick_app

    app = make_stick_app()  # canonical_depth enabled
    rng = np.random.default_rng(2)
    inputs = rng.integers(-500, 500, (8, 2, 2)).astype(np.int16)
    status = np.zeros((8, 2), np.int8)

    w_one = app.init_state()
    for i in range(8):  # eight 1-frame dispatches
        w_one, _, _ = app.resim_fn(w_one, inputs[i:i+1], status[i:i+1], i)
    w_all, _, _ = app.resim_fn(app.init_state(), inputs, status, 0)  # one 8-frame
    w_mix = app.init_state()
    for i, k in ((0, 3), (3, 5)):  # mixed segmentation
        w_mix, _, _ = app.resim_fn(w_mix, inputs[i:i+k], status[i:i+k], i)

    a = np.asarray(w_one.comps["pos"])
    b = np.asarray(w_all.comps["pos"])
    c = np.asarray(w_mix.comps["pos"])
    assert np.array_equal(a, b) and np.array_equal(b, c)
