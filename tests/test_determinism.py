"""Determinism oracles: the fixed-point model is bit-exact and
checksum-stable across jit/eager, device counts, and (via scripts/
parity_check.py on real hardware) across CPU/TPU backends."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from bevy_ggrs_tpu import GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import fixed_point
from bevy_ggrs_tpu.session.events import InputStatus
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int


def _inputs(k, p=2):
    rng = np.random.default_rng(7)
    return (
        rng.integers(0, 16, (k, p)).astype(np.uint8),
        np.full((k, p), InputStatus.CONFIRMED, np.int8),
    )


def test_fixed_point_synctest_clean():
    app = fixed_point.make_app()
    session = SyncTestSession(num_players=2, input_shape=(),
                              input_dtype=np.uint8, check_distance=5)
    mismatches = []
    rng = np.random.default_rng(3)
    runner = GgrsRunner(
        app, session,
        read_inputs=lambda hs: {h: np.uint8(rng.integers(0, 16)) for h in hs},
        on_mismatch=mismatches.append,
    )
    for _ in range(30):
        runner.tick()
    assert mismatches == []
    assert int(jnp.abs(runner.world.comps["vel"]).max()) > 0  # actually moved


def test_fixed_point_eager_vs_jit_bit_exact():
    app = fixed_point.make_app()
    world = app.init_state()
    inputs, status = _inputs(8)
    from bevy_ggrs_tpu.ops.resim import resim

    eager = resim(app.reg, app.step, world, inputs, status, 0, app.retention,
                  app.fps, 0)
    jitted = app.resim_fn(world, inputs, status, 0, -1)
    assert np.array_equal(np.asarray(eager[2]), np.asarray(jitted[2]))
    assert np.array_equal(
        np.asarray(eager[0].comps["pos"]), np.asarray(jitted[0].comps["pos"])
    )


def test_fixed_point_checksum_stable_across_runs():
    app = fixed_point.make_app()
    inputs, status = _inputs(12)
    cs = []
    for _ in range(2):
        world = app.init_state()
        _, _, checks = app.resim_fn(world, inputs, status, 0, -1)
        cs.append(checksum_to_int(np.asarray(checks)[-1]))
    assert cs[0] == cs[1]
    # the value is pinned so any cross-backend run can compare against it:
    # scripts/parity_check.py recomputes this on the TPU backend
    assert cs[0] != 0


def test_canonical_mode_is_segmentation_stable():
    """Program-variant rounding regression: under canonical_depth, any
    segmentation of the same frame sequence is bit-identical (without it,
    the k=1 vs k=8 programs measurably differ on this arithmetic)."""
    import sys

    sys.path.insert(0, "tests")
    from test_soak_vector_inputs import make_stick_app

    app = make_stick_app()  # canonical_depth enabled
    rng = np.random.default_rng(2)
    inputs = rng.integers(-500, 500, (8, 2, 2)).astype(np.int16)
    status = np.zeros((8, 2), np.int8)

    w_one = app.init_state()
    for i in range(8):  # eight 1-frame dispatches
        w_one, _, _ = app.resim_fn(w_one, inputs[i:i+1], status[i:i+1], i)
    w_all, _, _ = app.resim_fn(app.init_state(), inputs, status, 0)  # one 8-frame
    w_mix = app.init_state()
    for i, k in ((0, 3), (3, 5)):  # mixed segmentation
        w_mix, _, _ = app.resim_fn(w_mix, inputs[i:i+k], status[i:i+k], i)

    a = np.asarray(w_one.comps["pos"])
    b = np.asarray(w_all.comps["pos"])
    c = np.asarray(w_mix.comps["pos"])
    assert np.array_equal(a, b) and np.array_equal(b, c)


def _fma_bait_app(**app_kw):
    """Float model whose per-resim-length XLA programs bait the fuser into
    different FMA contractions — the variant probe's intended prey."""
    from bevy_ggrs_tpu import App
    from bevy_ggrs_tpu.snapshot import active_mask, spawn

    app = App(num_players=2, capacity=4, input_shape=(2,),
              input_dtype=np.int16, **app_kw)
    app.rollback_component("pos", (2,), jnp.float32, checksum=True)
    app.rollback_component("handle", (), jnp.int32, checksum=True)

    def step(world, ctx):
        h = world.comps["handle"]
        m = active_mask(world) & world.has["handle"]
        stick = ctx.inputs.astype(jnp.float32) / 1000.0
        delta = stick[jnp.clip(h, 0, 1)]
        pos = world.comps["pos"] + jnp.where(m[:, None], delta, 0.0)
        return dataclasses.replace(world, comps={**world.comps, "pos": pos})

    def setup(world):
        for h in range(2):
            world, _ = spawn(app.reg, world, {"pos": np.zeros(2), "handle": h})
        return world

    app.set_step(step)
    app.set_setup(setup)
    return app


def test_variant_probe_passes_stable_models():
    from bevy_ggrs_tpu import probe_program_variants

    # integer model: stable by construction
    rep = probe_program_variants(fixed_point.make_app(), trials=20,
                                 warmup_frames=4)
    assert rep.stable, rep.summary()

    # ...and canonical mode makes even the FMA-bait float model stable by
    # construction (every length runs the one program; the probe then
    # trivially passes)
    rep2 = probe_program_variants(_fma_bait_app(canonical_depth=8),
                                  trials=20, warmup_frames=4)
    assert rep2.stable, rep2.summary()


@pytest.mark.xfail(
    strict=False,
    reason="whether XLA actually fuses the bait differently per resim "
    "length depends on backend and compiler version — on some CPU builds "
    "every length compiles to bit-identical programs and the probe "
    "(correctly) reports stable; the probe's detection machinery is "
    "covered by the stable-model assertions either way",
)
def test_variant_probe_flags_the_fma_bait_model():
    from bevy_ggrs_tpu import probe_program_variants

    rep = probe_program_variants(_fma_bait_app(), trials=40, warmup_frames=4)
    assert not rep.stable
    assert rep.first_example is not None


def test_fixed_point_golden_checksum():
    """Cross-round determinism anchor: the integer model's checksum for a
    pinned input sequence is an exact constant.  If a change to the hash,
    world layout, frame semantics, or model breaks this, it breaks replay
    and cross-peer compatibility with earlier builds — change it knowingly
    (and note it in NOTES.md) or not at all."""
    app = fixed_point.make_app()
    rng = np.random.default_rng(7)
    inputs = rng.integers(0, 16, (12, 2)).astype(np.uint8)
    status = np.zeros((12, 2), np.int8)
    _, _, checks = app.resim_fn(app.init_state(), inputs, status, 0)
    assert checksum_to_int(np.asarray(checks)[-1]) == 0x5898EBD39DB5B0DC
