"""Input queue unit tests: delay, PredictRepeatLast, first-incorrect
detection, redundancy dedup, gap prediction."""


from bevy_ggrs_tpu.session.input_queue import InputQueue
from bevy_ggrs_tpu.session.events import InputStatus
from bevy_ggrs_tpu.utils.frames import NULL_FRAME


def test_local_delay():
    q = InputQueue(delay=3)
    eff = q.add_local(0, 7)
    assert eff == 3
    v, st = q.input_for(3)
    assert int(v) == 7 and st == InputStatus.CONFIRMED
    # frames before the delayed input predict default (0)
    v, st = q.input_for(1)
    assert int(v) == 0 and st == InputStatus.PREDICTED


def test_predict_repeat_last():
    q = InputQueue()
    q.add_remote(0, 5)
    v, st = q.input_for(4)
    assert int(v) == 5 and st == InputStatus.PREDICTED


def test_first_incorrect_detection():
    q = InputQueue()
    q.add_remote(0, 5)
    # serve predictions for frames 1..3 (all predict 5)
    for f in (1, 2, 3):
        q.input_for(f)
    q.add_remote(1, 5)  # matches prediction -> no misprediction
    assert q.first_incorrect == NULL_FRAME
    q.add_remote(2, 9)  # differs -> first incorrect = 2
    q.add_remote(3, 9)  # also differs, but 2 stays first
    assert q.first_incorrect == 2
    assert q.take_first_incorrect() == 2
    assert q.first_incorrect == NULL_FRAME


def test_duplicate_and_old_inputs_ignored():
    q = InputQueue()
    q.add_remote(5, 1)
    q.add_remote(3, 9)  # stale redundancy, ignored
    assert q.last_confirmed == 5
    assert q.confirmed_input(3) is None


def test_inputs_since_for_redundant_packets():
    q = InputQueue()
    for f in range(4):
        q.add_remote(f, f * 10)
    got = q.inputs_since(1)
    assert [f for f, _ in got] == [2, 3]


def test_gc():
    q = InputQueue()
    for f in range(10):
        q.add_remote(f, f)
    q.gc(7)
    assert q.confirmed_input(6) is None
    assert q.confirmed_input(7) is not None
