"""Tick coalescing: a host update that owes N sim frames flushes all N
ticks' requests through one _handle_requests call, fusing consecutive
advances into a single k=N dispatch (GgrsRunner(coalesce_frames=N)).

Correctness bar: the session layer is driver-cadence-independent, so a
coalesced driver must produce bit-identical state to the per-tick driver
for variant-stable models — and fewer device dispatches.  The ring prune
must happen AFTER request processing: with coalescing, an early tick's
rollback target can sit below a later tick's confirmed frame."""

import numpy as np

from bevy_ggrs_tpu import GgrsRunner, PlayerType, SessionBuilder, SessionState, SyncTestSession
from bevy_ggrs_tpu.models import box_game, fixed_point
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


def _synctest_driver(coalesce, ticks=36, chunk=1, pipeline=True,
                     before_finish=None):
    app = fixed_point.make_app()
    session = SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=3, compare_interval=1,
    )
    t = [0]

    def read_inputs(handles):
        # deterministic per-frame stream, independent of flush cadence
        t[0] += 1
        return {h: np.uint8((t[0] * 7 + h * 3) & 0xF) for h in handles}

    runner = GgrsRunner(
        app, session, read_inputs=read_inputs,
        on_mismatch=lambda e: (_ for _ in ()).throw(e),
        coalesce_frames=coalesce, pipeline=pipeline,
    )
    done = 0
    while done < ticks:
        n = min(chunk, ticks - done)
        runner.update(n * DT)  # n due frames in one host update
        done += n
    if before_finish is not None:
        before_finish(runner)
    runner.finish()
    return runner


def test_coalesced_synctest_bit_identical_and_fewer_dispatches():
    plain = _synctest_driver(coalesce=1, chunk=1)
    fused = _synctest_driver(coalesce=4, chunk=4)
    assert fused.frame == plain.frame
    assert fused.checksum == plain.checksum  # bit-exact (fixed-point model)
    # ring contents agree frame-for-frame wherever both retain them
    shared = sorted(set(plain.ring.frames()) & set(fused.ring.frames()))
    assert shared
    for f in shared:
        assert checksum_to_int(plain.ring.peek(f)[1]) == checksum_to_int(
            fused.ring.peek(f)[1]
        )
    # the point of the feature: 4-frame chunks collapse into fewer dispatches
    assert fused.device_dispatches < plain.device_dispatches
    assert fused.ticks == plain.ticks


def test_coalesced_pipelined_bit_identical_without_forced_readbacks():
    """coalesce>1 composed with the tick pipeline: the async checksum
    readback must keep up with fused k>1 dispatches — bit-equal to the
    synchronous per-tick driver with ZERO forced (blocking) pulls during
    the run (finish() drains are excluded from the window)."""
    from bevy_ggrs_tpu.snapshot.lazy import readback_stats

    sync = _synctest_driver(coalesce=1, chunk=1, pipeline=False)
    before = readback_stats()
    window = {}
    piped = _synctest_driver(
        coalesce=4, chunk=4, pipeline=True,
        before_finish=lambda r: window.update(readback_stats()),
    )
    assert window["forced"] - before["forced"] == 0
    assert piped.frame == sync.frame
    assert piped.checksum == sync.checksum
    shared = sorted(set(sync.ring.frames()) & set(piped.ring.frames()))
    assert shared
    for f in shared:
        assert checksum_to_int(sync.ring.peek(f)[1]) == checksum_to_int(
            piped.ring.peek(f)[1]
        )


def test_coalesce_frames_one_is_the_reference_cadence():
    a = _synctest_driver(coalesce=1, chunk=1)
    b = _synctest_driver(coalesce=1, chunk=4)  # multiple due frames, cap 1
    assert b.checksum == a.checksum
    assert b.device_dispatches == a.device_dispatches


def _latency_pair(coalesce):
    net = ChannelNetwork(latency_hops=3, seed=11)
    socks = [net.endpoint("c0"), net.endpoint("c1")]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"c{1 - i}")
        )
        session = b.start_p2p_session(socks[i])

        def read_inputs(handles, i=i):
            key = {0: "right", 1: "down"}[i]
            return {h: box_game.keys_to_input(**{key: True}) for h in handles}

        runners.append(
            GgrsRunner(app, session, read_inputs=read_inputs,
                       coalesce_frames=coalesce)
        )
    return net, runners


def test_coalesced_p2p_catchup_under_latency():
    """The catch-up shape the feature exists for: one peer periodically
    falls 4 frames behind and catches up in a single coalesced update
    while rollbacks from channel latency land in the same flushes.  The
    prune-after-processing ordering is what keeps the early ticks' Load
    targets alive here."""
    net, runners = _latency_pair(coalesce=4)
    for _ in range(300):
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(
            r.session.current_state() == SessionState.RUNNING for r in runners
        ):
            break
    flip = [0]

    def flipping(handles):
        flip[0] += 1
        return {
            h: box_game.keys_to_input(right=(flip[0] // 5) % 2 == 0)
            for h in handles
        }

    runners[0].read_inputs = flipping
    # runner 1 ticks every host update; runner 0 only every 4th, owing 4
    for step in range(120):
        net.deliver()
        runners[1].update(DT)
        if step % 4 == 3:
            runners[0].update(4 * DT)
    assert all(r.frame >= 100 for r in runners)
    assert any(r.rollbacks > 0 for r in runners)
    # coalescing actually batched: runner 0 advanced ~120 frames in ~30 flushes
    assert runners[0].device_dispatches < runners[0].frame // 2
    shared = None
    for _ in range(8):
        shared = sorted(
            set(runners[0].ring.frames()) & set(runners[1].ring.frames())
        )
        if shared:
            break
        net.deliver()
        runners[1].update(DT)
        runners[0].update(DT)
    assert shared
    f = shared[-1]
    assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
        runners[1].ring.peek(f)[1]
    )


def test_coalesce_guardrails():
    """Construction-time validation: coalescing deeper than the SyncTest
    comparison-cell GC horizon would silently thin the determinism oracle;
    canonical apps cannot pad a rollback + catch-up run past their fixed
    depth.  Both must fail loudly at set_session, not mid-run."""
    import pytest

    app = fixed_point.make_app()
    sess = SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=3, compare_interval=1,
    )
    # horizon = 3 + 1 + 2 = 6: cap 6 ok, 7 rejected
    GgrsRunner(app, sess, coalesce_frames=6)
    with pytest.raises(ValueError, match="comparison-cell horizon"):
        GgrsRunner(app, SyncTestSession(
            num_players=2, input_shape=(), input_dtype=np.uint8,
            check_distance=3, compare_interval=1,
        ), coalesce_frames=7)

    from bevy_ggrs_tpu.models import stress

    capp = stress.make_app(64, capacity=64)
    capp.canonical_depth = 8
    sess2 = SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=4,  # window 4; 4 + coalesce 5 > depth 8
    )
    with pytest.raises(ValueError, match="canonical_depth"):
        GgrsRunner(capp, sess2, coalesce_frames=5)
