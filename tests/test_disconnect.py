"""Failure handling: a silent peer triggers NetworkInterrupted then
Disconnected (within configured timeouts), after which the surviving peer
keeps simulating with DISCONNECTED input status for the dead player —
the reference's failure model (SURVEY §5.3)."""

import time


from bevy_ggrs_tpu import GgrsRunner, PlayerType, SessionBuilder, SessionState
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.session.events import (
    Disconnected,
    InputStatus,
    NetworkInterrupted,
)

DT = 1.0 / 60.0


def test_peer_disconnect_survivor_continues():
    net = ChannelNetwork()
    socks = [net.endpoint("p0"), net.endpoint("p1")]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .with_disconnect_timeout(0.25)
            .with_disconnect_notify_delay(0.08)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"p{1 - i}")
        )
        session = b.start_p2p_session(socks[i])
        runners.append(
            GgrsRunner(
                app, session,
                read_inputs=lambda hs: {h: box_game.keys_to_input(right=True)
                                        for h in hs},
            )
        )
    for _ in range(300):
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
    for _ in range(20):
        net.deliver()
        for r in runners:
            r.update(DT)
    frame_at_death = runners[0].frame

    # peer 1 dies; keep driving peer 0 in real time until events fire
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline:
        net.deliver()
        runners[0].update(DT)
        if any(isinstance(e, Disconnected) for e in runners[0].events):
            break
        time.sleep(0.01)
    kinds = [type(e) for e in runners[0].events]
    assert NetworkInterrupted in kinds
    assert Disconnected in kinds

    # survivor stalls at most briefly, then advances freely (no remote inputs
    # needed once the peer is disconnected)
    before = runners[0].frame
    for _ in range(30):
        runners[0].update(DT)
    assert runners[0].frame > before + 20
    assert runners[0].frame > frame_at_death
    # dead player's input arrives as DISCONNECTED status
    inputs, status = runners[0].session._inputs_for(runners[0].frame - 1)
    assert status[1] == InputStatus.DISCONNECTED
