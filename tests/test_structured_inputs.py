"""Non-scalar input types: the Config::Input analog supports any POD shape
(the reference requires Input: PartialEq+Serialize+Default+Copy; here any
fixed-shape numpy dtype).  Exercises packing through the wire protocol and
through SyncTest."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu import App, GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.session.input_queue import InputQueue
from bevy_ggrs_tpu.snapshot import active_mask, spawn


def test_vector_input_synctest():
    # input = int16[2] stick axes
    app = App(num_players=2, capacity=4, input_shape=(2,), input_dtype=np.int16)
    app.rollback_component("pos", (2,), jnp.float32, checksum=True)
    app.rollback_component("handle", (), jnp.int32, checksum=True)

    def step(world, ctx):
        h = world.comps["handle"]
        m = active_mask(world) & world.has["handle"]
        stick = ctx.inputs.astype(jnp.float32) / 100.0  # [P, 2]
        delta = stick[jnp.clip(h, 0, ctx.inputs.shape[0] - 1)]
        pos = world.comps["pos"] + jnp.where(m[:, None], delta, 0.0)
        return dataclasses.replace(world, comps={**world.comps, "pos": pos})

    def setup(world):
        for h in range(2):
            world, _ = spawn(app.reg, world, {"pos": np.zeros(2), "handle": h})
        return world

    app.set_step(step)
    app.set_setup(setup)

    session = SyncTestSession(num_players=2, input_shape=(2,),
                              input_dtype=np.int16, check_distance=3)
    mismatches = []
    runner = GgrsRunner(
        app, session,
        read_inputs=lambda hs: {
            h: np.array([100 if h == 0 else 0, 50], np.int16) for h in hs
        },
        on_mismatch=mismatches.append,
    )
    for _ in range(20):
        runner.tick()
    assert mismatches == []
    assert abs(float(runner.world.comps["pos"][0, 0]) - 20.0) < 1e-4
    assert abs(float(runner.world.comps["pos"][1, 0])) < 1e-6
    assert abs(float(runner.world.comps["pos"][1, 1]) - 10.0) < 1e-4


def test_vector_input_queue_roundtrip():
    q = InputQueue(input_shape=(2,), input_dtype=np.int16, delay=1)
    eff = q.add_local(4, np.array([7, -3], np.int16))
    assert eff == 5
    v, st = q.input_for(5)
    assert v.tolist() == [7, -3]
