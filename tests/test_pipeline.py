"""Pipelined tick engine: async checksum readback (harvest vs forced),
late-landing checksum providers feeding p2p desync detection, sync-mode
zero-deep semantics, persistent staging reuse, and the bench/lint support
surfaces that guard the pipeline (trimmed-mean aggregation, hot-loop purity
lint)."""

import ast
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bevy_ggrs_tpu import (
    DesyncDetection,
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
    SyncTestSession,
)
from bevy_ggrs_tpu.models import box_game, stress
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.session.events import DesyncDetected
from bevy_ggrs_tpu.snapshot.lazy import (
    BatchChecks,
    ReadbackQueue,
    readback_stats,
    wrap_single_checksum,
)

DT = 1.0 / 60.0


def _stats_delta(before, after=None):
    after = after if after is not None else readback_stats()
    return {k: after[k] - before[k] for k in ("harvested", "forced")}


# -- BatchChecks / ReadbackQueue units --------------------------------------


def _device_batch(values):
    """uint32[k, 2] device array from a list of (hi, lo) pairs."""
    return jnp.asarray(np.asarray(values, np.uint32))


def test_harvest_collects_landed_copy_without_forcing():
    batch = BatchChecks(_device_batch([(1, 2)]))
    rbq = ReadbackQueue()
    rbq.start(batch)
    jax.block_until_ready(batch._dev)  # the copy has certainly landed
    before = readback_stats()
    assert rbq.harvest() >= 1
    delta = _stats_delta(before)
    assert delta["harvested"] >= 1 and delta["forced"] == 0
    assert batch.ref(0).to_int() == (1 << 32) | 2  # cached, still no force


def test_pull_pending_counts_unstarted_batch_as_forced():
    batch = BatchChecks(_device_batch([(3, 4)]))
    before = readback_stats()
    BatchChecks.pull_pending()
    delta = _stats_delta(before)
    assert delta["forced"] >= 1
    assert batch.ref(0).to_int() == (3 << 32) | 4


def test_checksum_ref_peek_converges_and_matches_call():
    ref = wrap_single_checksum(jnp.asarray(np.asarray([7, 9], np.uint32)))
    got = None
    for _ in range(1000):
        got = ref.peek()
        if got is not None:
            break
    assert got == (7 << 32) | 9
    assert ref() == got  # __call__ is to_int; now a cached read


def test_host_backed_provider_needs_no_async_surface():
    # spec-cache / test stubs hand plain numpy to BatchChecks — the harvest
    # path must adopt them without an is_ready/copy_to_host_async surface
    batch = BatchChecks(np.asarray([[5, 6]], np.uint32))
    assert ReadbackQueue().harvest() >= 1
    assert batch.ref(0).peek() == (5 << 32) | 6


# -- SyncTest: pipeline on/off bit-equality and sync-mode semantics ----------


def _synctest_checks(pipeline, ticks=30):
    app = stress.make_app(128, capacity=128)
    rng = np.random.default_rng(5)
    runner = GgrsRunner(
        app,
        SyncTestSession(num_players=2, check_distance=2, compare_interval=1),
        read_inputs=lambda hs: {h: np.uint8(rng.integers(0, 16)) for h in hs},
        on_mismatch=lambda e: (_ for _ in ()).throw(e),
        pipeline=pipeline,
    )
    checks = []
    for _ in range(ticks):
        runner.tick()
        checks.append(runner.checksum)
    runner.finish()
    return checks


def test_pipeline_on_off_checksums_bit_identical():
    assert _synctest_checks(True) == _synctest_checks(False)


def test_sync_mode_forces_readbacks_every_tick():
    before = readback_stats()
    _synctest_checks(False, ticks=10)
    assert _stats_delta(before)["forced"] >= 10


def test_pipeline_default_on_and_counted_in_stats():
    app = stress.make_app(64, capacity=64)
    runner = GgrsRunner(app, SyncTestSession(num_players=2))
    assert runner.pipeline is True
    assert runner.stats()["pipeline_degrades"] == 0
    runner.finish()


# -- p2p over deterministic channel ------------------------------------------


def _channel_pair(pipeline=True, desync=DesyncDetection.on(1), packed=True):
    net = ChannelNetwork(seed=7)
    socks = [net.endpoint(f"p{i}") for i in range(2)]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(2)
            .with_desync_detection_mode(desync)
            .with_eager_checksums(not pipeline)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"p{1 - i}")
        )
        session = b.start_p2p_session(socks[i])
        runners.append(GgrsRunner(
            app, session,
            read_inputs=lambda hs: {
                h: box_game.keys_to_input(right=True) for h in hs
            },
            pipeline=pipeline,
            packed=packed,
        ))
    for _ in range(500):
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING
               for r in runners):
            break
    assert all(r.session.current_state() == SessionState.RUNNING
               for r in runners)
    return net, runners


def _interleave(net, runners, ticks):
    for _ in range(ticks):
        net.deliver()
        for r in runners:
            r.update(DT)


def test_pipelined_p2p_steady_state_never_forces():
    net, runners = _channel_pair(pipeline=True)
    _interleave(net, runners, 20)  # settle the startup transient
    before = readback_stats()
    _interleave(net, runners, 60)
    delta = _stats_delta(before)
    assert delta["forced"] == 0
    assert delta["harvested"] > 0
    desyncs = [e for r in runners for e in r.events
               if isinstance(e, DesyncDetected)]
    assert not desyncs
    for r in runners:
        r.finish()


class _LateWrongRef:
    """Checksum provider whose async copy 'lands' only after ``late`` polls —
    and then reports a corrupted value."""

    def __init__(self, value, late):
        self.value = value
        self.polls = 0
        self.late = late

    def peek(self):
        self.polls += 1
        return None if self.polls <= self.late else self.value

    def __call__(self):
        return self.value


def test_late_checksum_still_desyncs_at_the_right_frame():
    """Satellite (c): a local checksum that resolves k polls after the frame
    is confirmed must still be published, compared, and fire DesyncDetected
    carrying THAT frame — late readbacks delay detection, never drop it."""
    net, runners = _channel_pair(pipeline=True)
    _interleave(net, runners, 10)
    sess = runners[1].session
    target = {}
    orig = sess._on_cell_saved

    def corrupting_hook(frame, provider):
        if not target and frame % 2 == 0:
            target["frame"] = frame
            target["ref"] = _LateWrongRef(value=0x0BAD_C0DE, late=6)
            orig(frame, target["ref"])
        else:
            orig(frame, provider)

    sess._on_cell_saved = corrupting_hook
    _interleave(net, runners, 80)
    assert "frame" in target, "hook never saw a save"
    assert target["ref"].polls > 6, "provider was never re-polled after None"
    desyncs = [e for r in runners for e in r.events
               if isinstance(e, DesyncDetected)]
    assert desyncs, "late-landing corrupted checksum produced no desync"
    assert {e.frame for e in desyncs} == {target["frame"]}
    for r in runners:
        r.finish()


def test_real_divergence_detected_with_pipelining_on():
    net, runners = _channel_pair(pipeline=True, desync=DesyncDetection.on(2))
    _interleave(net, runners, 20)
    w = runners[1].world
    runners[1].world = dataclasses.replace(
        w, comps={**w.comps, "pos": w.comps["pos"] + 5.0}
    )
    runners[1]._world_checksum = runners[1].app.checksum_fn(runners[1].world)
    _interleave(net, runners, 80)
    desyncs = [e for r in runners for e in r.events
               if isinstance(e, DesyncDetected)]
    assert desyncs, "expected DesyncDetected after state divergence"
    for r in runners:
        r.finish()


# -- runner integration: staging reuse, read_components ---------------------


def test_persistent_staging_buffer_is_reused():
    # default (packed) path: one persistent int8 buffer carries every upload
    net, runners = _channel_pair(pipeline=True, desync=DesyncDetection.OFF)
    _interleave(net, runners, 10)
    buf = runners[0]._stage_packed
    assert buf is not None
    _interleave(net, runners, 10)
    assert runners[0]._stage_packed is buf, (
        "solo-runner staging must reuse its persistent buffer, not "
        "reallocate per tick"
    )
    assert runners[0]._stage_inputs is None  # unpacked staging never ran
    for r in runners:
        r.finish()


def test_persistent_staging_buffer_is_reused_unpacked():
    net, runners = _channel_pair(
        pipeline=True, desync=DesyncDetection.OFF, packed=False
    )
    _interleave(net, runners, 10)
    buf = runners[0]._stage_inputs
    assert buf is not None
    _interleave(net, runners, 10)
    assert runners[0]._stage_inputs is buf, (
        "solo-runner staging must reuse its persistent buffer, not "
        "reallocate per tick"
    )
    for r in runners:
        r.finish()


def test_read_components_drains_inflight_window():
    net, runners = _channel_pair(pipeline=True, desync=DesyncDetection.OFF)
    _interleave(net, runners, 15)
    r = runners[0]
    out = r.read_components(["pos"])
    assert np.array_equal(out["pos"], np.asarray(r.world.comps["pos"]))
    assert "__active__" in out
    for r in runners:
        r.finish()


# -- support surfaces: bench aggregation, purity lint ------------------------


def test_trimmed_mean_drops_single_outlier():
    bench = pytest.importorskip("bench")
    samples = [100.0, 101.0, 99.0, 250.0]  # one contention-mauled rep
    val, spread, spread_raw = bench._trimmed_mean_spread(samples)
    assert val == pytest.approx(100.5)
    assert spread < 0.03
    assert spread_raw > 1.0  # the outlier stays visible in the raw spread
    # below 4 reps there is nothing to trim
    val3, _, _ = bench._trimmed_mean_spread([1.0, 2.0, 3.0])
    assert val3 == pytest.approx(2.0)


def test_purity_lint_flags_forcing_read_outside_allowlist():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "lint_imports", Path(__file__).parent.parent / "scripts/lint_imports.py"
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)

    bad = ast.parse(
        "def hot_loop(ref):\n"
        "    return ref.to_int()\n"
        "def sanctioned(ref):\n"
        "    return ref.to_int()\n"
    )
    problems = lint.check_purity(bad, allow={"sanctioned"})
    assert len(problems) == 1
    assert problems[0][0] == 2  # the hot_loop line, not the allowlisted one
