"""Mesh-sharding tests on the virtual 8-device CPU mesh: sharded resim and
speculation must produce bit-identical checksums to single-device runs."""

import jax
import numpy as np

from bevy_ggrs_tpu.models import particles, box_game
from bevy_ggrs_tpu.parallel import (
    make_mesh,
    make_sharded_resim_fn,
    make_sharded_speculate_fn,
    shard_world,
)
from bevy_ggrs_tpu.session.events import InputStatus


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_sharded_resim_matches_single_device():
    app = particles.make_app(rate=8, ttl=16, capacity=256)
    world = app.init_state()
    k = 4
    inputs = np.zeros((k, 2), np.uint8)
    status = np.full((k, 2), InputStatus.CONFIRMED, np.int8)

    _, _, checks_single = app.resim_fn(world, inputs, status, 0, -1)

    mesh = make_mesh(n_data=8, n_spec=1)
    sharded = make_sharded_resim_fn(app, mesh)
    _, _, checks_sharded = sharded(world, inputs, status, 0, -1)

    assert np.array_equal(np.asarray(checks_single), np.asarray(checks_sharded))


def test_sharded_speculation_matches_single_device():
    app = box_game.make_app(num_players=2, capacity=16)
    world = app.init_state()
    k, m = 4, 4
    branches = np.zeros((m, k, 2), np.uint8)
    for b in range(m):
        branches[b, :, 1] = b
    statuses = np.full((m, k, 2), InputStatus.CONFIRMED, np.int8)

    _, _, checks_single = app.speculate_fn(world, branches, statuses, 0, -1)

    mesh = make_mesh(n_data=2, n_spec=4)
    spec = make_sharded_speculate_fn(app, mesh)
    _, _, checks_sharded = spec(world, branches, statuses, 0, -1)

    assert np.array_equal(np.asarray(checks_single), np.asarray(checks_sharded))


def test_shard_world_places_on_mesh():
    app = particles.make_app(rate=8, ttl=16, capacity=256)
    world = app.init_state()
    mesh = make_mesh(n_data=8, n_spec=1)
    w = shard_world(app, mesh, world)
    shard_devs = {s.device for s in w.comps["pos"].addressable_shards}
    assert len(shard_devs) == 8


def test_sharded_canonical_branched_matches_single_device():
    import sys

    sys.path.insert(0, "tests")
    from test_canonical_speculation import make_app, B, K

    from bevy_ggrs_tpu.parallel import make_sharded_canonical_fn

    app = make_app()
    world = app.init_state()
    rng = np.random.default_rng(3)
    ib = rng.integers(0, 3, (B, K, 2)).astype(np.uint8)
    sb = np.zeros((B, K, 2), np.int8)
    n_real = np.full((B,), K, np.int32)

    _, _, checks_single = app.branched_fn(world, ib, sb, 0, n_real)

    mesh = make_mesh(n_data=2, n_spec=4)
    sharded = make_sharded_canonical_fn(app, mesh)
    _, _, checks_sharded = sharded(world, ib, sb, 0, n_real)

    assert np.array_equal(np.asarray(checks_single), np.asarray(checks_sharded))
