"""Device-sharded many-worlds executor: lobbies across the mesh.

The acceptance oracle is the unsharded path — ``ShardedWaveExecutor`` must
be BIT-identical to ``BucketedWaveExecutor`` on identical waves (stacked
states AND checksums), and ``BatchedRunner(mesh=...)`` must reproduce the
unsharded runner's checksums tick-for-tick with the SyncTest oracle green.
Runs on the conftest-forced 8-virtual-device CPU mesh (``eight_devices``)."""

import os
import sys

import numpy as np
import pytest

from bevy_ggrs_tpu import BatchedRunner, SyncTestSession, telemetry
from bevy_ggrs_tpu.batch_runner import ShardPlanner
from bevy_ggrs_tpu.models import fixed_point, stress
from bevy_ggrs_tpu.ops.batch import (
    BucketedWaveExecutor,
    ShardedWaveExecutor,
    stack_worlds,
)
from bevy_ggrs_tpu.parallel import make_lobby_mesh
from bevy_ggrs_tpu.session.events import InputStatus

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import multichip_bench  # noqa: E402  (scripts/ is not a package)


def _wave(app, m, k_max, seed=0):
    """A deterministic [m, k_max] staging wave: worlds, inputs, status."""
    rng = np.random.default_rng(seed)
    worlds = stack_worlds([app.init_state() for _ in range(m)])
    inputs = rng.integers(0, 16, (m, k_max, app.num_players)).astype(np.uint8)
    status = np.full((m, k_max, app.num_players), InputStatus.CONFIRMED,
                     np.int8)
    return worlds, inputs, status


def _tree_equal(a, b):
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


# -- executor-level bit-equality -------------------------------------------

@pytest.mark.parametrize("m,ks", [
    (8, [8] * 8),            # exact wave, M == D
    (16, [8] * 16),          # exact wave, M == 2D
    (12, [3, 8, 1, 5, 8, 2, 7, 4, 8, 1, 6, 8]),  # ragged, M not div by D
    (13, [5] * 13),          # non-power-of-two M, M not div by D
    (6, [2, 7, 1, 4, 8, 3]),  # ragged, M < D
], ids=["exact-m8", "exact-m16", "ragged-m12", "uniform-m13", "ragged-m6"])
@pytest.mark.parametrize("app_factory", [
    lambda: stress.make_app(64, capacity=64),
    fixed_point.make_app,
], ids=["stress", "fixed_point"])
def test_sharded_wave_bit_equality(eight_devices, app_factory, m, ks):
    """Sharded vs unsharded on the identical wave: same bucket choice, and
    bit-equal finals, stacked snapshots, and checksum rows."""
    k_max = 8
    app = app_factory()
    mesh = make_lobby_mesh(len(eight_devices))
    ref = BucketedWaveExecutor(app, k_max)
    sh = ShardedWaveExecutor(app, k_max, mesh)
    worlds, inputs, status = _wave(app, m, k_max)
    starts = np.arange(m, dtype=np.int32) * 3

    rb, rf, rs, rc = ref.run_wave(worlds, inputs, status, starts, ks)
    sb, sf, ss, sc = sh.run_wave(worlds, inputs, status, starts, ks)

    assert sb == rb
    assert _tree_equal(sf, rf), "finals diverged"
    assert _tree_equal(ss, rs), "stacked snapshots diverged"
    assert np.array_equal(np.asarray(sc), np.asarray(rc)), "checksums diverged"


def test_sharded_wave_mixed_depth_sequence(eight_devices):
    """Several consecutive waves of different bucket depths (program-cache
    reuse across waves) stay bit-equal, with finals threaded wave to wave."""
    app = stress.make_app(64, capacity=64)
    mesh = make_lobby_mesh(8)
    ref = BucketedWaveExecutor(app, 8)
    sh = ShardedWaveExecutor(app, 8, mesh)
    m = 12
    worlds, inputs, status = _wave(app, m, 8)
    rw = sw = worlds
    for tick, ks in enumerate([[1] * m, [4, 2, 1, 4, 3, 4, 1, 2, 4, 4, 1, 3],
                               [8] * m, [1] * m]):
        starts = np.full((m,), tick * 8, np.int32)
        _, rw, _, rc = ref.run_wave(rw, inputs, status, starts, ks)
        _, sw, _, sc = sh.run_wave(sw, inputs, status, starts, ks)
        assert np.array_equal(np.asarray(sc), np.asarray(rc)), f"tick {tick}"
    assert _tree_equal(sw, rw)


def test_sharded_executor_bookkeeping(eight_devices):
    """pad_lobbies math, recycle refusal, and per-device harvest census."""
    app = stress.make_app(64, capacity=64)
    mesh = make_lobby_mesh(8)
    sh = ShardedWaveExecutor(app, 8, mesh)
    assert [sh.pad_lobbies(m) for m in (1, 7, 8, 9, 16, 17)] == \
        [8, 8, 8, 16, 16, 24]
    with pytest.raises(ValueError, match="recycle_outputs"):
        ShardedWaveExecutor(app, 8, mesh, recycle_outputs=True)

    worlds, inputs, status = _wave(app, 16, 8)
    _, finals, _, _ = sh.run_wave(worlds, inputs, status,
                                  np.zeros(16, np.int32), [8] * 16)
    census = sh.harvest_shards(finals)
    assert census["n_devices"] == 8
    assert census["devices_touched"] == 8
    assert all(v > 0 for v in census["buffers_per_device"].values())
    assert sh.stats()["shard_devices"] == 8


# -- telemetry --------------------------------------------------------------

def test_sharded_telemetry_counters(eight_devices):
    """sharded_wave_dispatches / shard_program_compiles counters and the
    shard_imbalance_ratio gauge flow through the BoundMetric path."""
    telemetry.disable()
    telemetry.reset()
    telemetry.enable()
    try:
        app = stress.make_app(64, capacity=64)
        sh = ShardedWaveExecutor(app, 8, make_lobby_mesh(8))
        worlds, inputs, status = _wave(app, 8, 8)
        sh.run_wave(worlds, inputs, status, np.zeros(8, np.int32), [8] * 8)
        sh.run_wave(worlds, inputs, status, np.zeros(8, np.int32),
                    [4, 8, 1, 2, 8, 3, 5, 6])
        reg = telemetry.registry()
        assert reg.counter("sharded_wave_dispatches_total").value() == 2
        # exact + padded program at bucket 8
        assert reg.counter("shard_program_compiles_total").value() == 2

        planner = ShardPlanner(12, 4)
        plan = planner.plan([8, 8, 8, 0, 0, 0, 0, 0, 0, 0, 0, 0])
        # shard 0 holds all 3 active lobbies of 3 total -> 3 * 4 / 3 = 4.0
        assert plan["imbalance_ratio"] == pytest.approx(4.0)
        assert reg.gauge("shard_imbalance_ratio").value() == pytest.approx(4.0)
        balanced = planner.plan([1] * 12)
        assert balanced["imbalance_ratio"] == pytest.approx(1.0)
        assert planner.max_imbalance == pytest.approx(4.0)
    finally:
        telemetry.disable()
        telemetry.reset()


# -- driver level -----------------------------------------------------------

def _lobby_inputs(lobby, tick, handles):
    rng = np.random.default_rng(1000 * lobby + tick)
    return {h: np.uint8(rng.integers(0, 16)) for h in handles}


def _run_driver(app_factory, m, ticks, mesh):
    app = app_factory()
    t = [0]

    def read_inputs(lobby, handles):
        return _lobby_inputs(lobby, t[0], handles)

    sessions = [
        SyncTestSession(num_players=2, input_shape=(), input_dtype=np.uint8,
                        check_distance=2, compare_interval=1)
        for _ in range(m)
    ]
    br = BatchedRunner(app, sessions, read_inputs=read_inputs, mesh=mesh)
    sums = [[] for _ in range(m)]
    for _ in range(ticks):
        br.tick()
        t[0] += 1
        for b in range(m):
            sums[b].append(br.lobby_checksum(b))
    br.finish()  # SyncTest oracle: raises on any restore mismatch
    return br, sums


def test_batched_runner_sharded_matches_unsharded(eight_devices):
    """M=6 lobbies (not divisible by D=8, so two permanent pad lanes) with
    rollbacks: checksums AND dispatch counts must match the unsharded
    runner exactly — sharding may not cost extra dispatches per tick."""
    factory = lambda: stress.make_app(64, capacity=64)
    M, TICKS = 6, 18
    ref, ref_sums = _run_driver(factory, M, TICKS, mesh=None)
    sh, sh_sums = _run_driver(factory, M, TICKS, mesh=make_lobby_mesh(8))
    assert sh_sums == ref_sums
    assert sh.device_dispatches == ref.device_dispatches
    assert isinstance(sh.exec, ShardedWaveExecutor)
    s = sh.stats()["sharded"]
    assert s["devices"] == 8 and s["pad_lanes"] == 2
    assert s["waves_planned"] > 0


def test_batched_runner_single_device_fallback():
    """A 1-device mesh (or a 1-device backend) must fall back to the plain
    BucketedWaveExecutor — no shard_map, no planner, no pad lanes."""
    app = stress.make_app(64, capacity=64)
    br = BatchedRunner(
        app,
        [SyncTestSession(num_players=2, input_shape=(),
                         input_dtype=np.uint8, check_distance=2,
                         compare_interval=1)],
        read_inputs=lambda lobby, handles: {h: np.uint8(0) for h in handles},
        mesh=make_lobby_mesh(1),
    )
    assert not isinstance(br.exec, ShardedWaveExecutor)
    assert br.planner is None
    assert "sharded" not in br.stats()
    br.tick()
    br.finish()


# -- multichip harness rule -------------------------------------------------

def test_multichip_empty_tail_is_skipped_never_ok():
    """The MULTICHIP record rule: rc==0 with EMPTY output must be skipped,
    never ok (the regression this PR fixes: every historical record carried
    ok=true with tail='')."""
    assert multichip_bench.classify(0, "") == {
        "rc": 0, "ok": False, "skipped": True,
    }
    assert multichip_bench.classify(0, "  \n ") == {
        "rc": 0, "ok": False, "skipped": True,
    }
    assert multichip_bench.classify(0, "MULTICHIP_METRICS {}") == {
        "rc": 0, "ok": True, "skipped": False,
    }
    # a failure is a failure, not a skip, output or not
    assert multichip_bench.classify(1, "") == {
        "rc": 1, "ok": False, "skipped": False,
    }
    assert multichip_bench.classify(124, "partial") == {
        "rc": 124, "ok": False, "skipped": False,
    }


def test_multichip_metrics_parse():
    tail = (
        'noise line\n'
        'MULTICHIP_METRICS {"program": "canonical", "wall_secs": 1.0}\n'
        'MULTICHIP_METRICS not-json\n'
        'MULTICHIP_METRICS {"program": "sharded_wave", "lobbies": 16}\n'
    )
    metrics = multichip_bench.parse_metrics(tail)
    assert [m["program"] for m in metrics] == ["canonical", "sharded_wave"]
