"""BatchedRunner (many-worlds server driver): M lobbies through one fused
dispatch per wave must match M independent GgrsRunners checksum-for-checksum,
with the SyncTest oracle green inside the batch (proving the batched
save/load/ring plumbing restores exactly what it saved)."""

import numpy as np
import pytest

from bevy_ggrs_tpu import BatchedRunner, GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import fixed_point, stress


def _session(check_distance=4):
    return SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=check_distance, compare_interval=1,
    )


def _lobby_inputs(lobby, tick, handles):
    rng = np.random.default_rng(1000 * lobby + tick)
    return {h: np.uint8(rng.integers(0, 16)) for h in handles}


def _solo_checksums(app_factory, lobby, ticks, check_distance=4):
    app = app_factory()
    t = [0]

    def read_inputs(handles):
        out = _lobby_inputs(lobby, t[0], handles)
        t[0] += 1
        return out

    runner = GgrsRunner(app, _session(check_distance), read_inputs=read_inputs)
    out = []
    for _ in range(ticks):
        runner.tick()
        out.append(runner.checksum)
    runner.finish()
    return out


@pytest.mark.parametrize("app_factory", [
    lambda: stress.make_app(128, capacity=128),
    fixed_point.make_app,
], ids=["stress", "fixed_point"])
def test_batched_runner_matches_independent_runners(app_factory):
    M, TICKS = 3, 25
    app = app_factory()
    tcount = [0]

    def read_inputs(lobby, handles):
        # same per-(lobby, tick) stream the solo runners consume
        return _lobby_inputs(lobby, tcount[0], handles)

    br = BatchedRunner(app, [_session() for _ in range(M)],
                       read_inputs=read_inputs)
    batched = [[] for _ in range(M)]
    for _ in range(TICKS):
        br.tick()
        tcount[0] += 1
        for b in range(M):
            batched[b].append(br.lobby_checksum(b))
    br.finish()  # SyncTest oracle: raises on any batched-restore mismatch

    for b in range(M):
        solo = _solo_checksums(app_factory, b, TICKS)
        assert batched[b] == solo, f"lobby {b} diverged from its solo run"


def test_batched_runner_dispatch_count():
    """The whole point: M lobbies per tick must cost O(waves) dispatches,
    not O(M) — the warmed-up synctest shape is 3 dispatches/tick (one fused
    load wave + two run waves), and `device_dispatches` now counts load and
    store waves too, so a per-lobby fallback would blow the bound."""
    M, TICKS = 8, 12
    app = stress.make_app(64, capacity=64)
    br = BatchedRunner(app, [_session(check_distance=3) for _ in range(M)],
                       read_inputs=_lobby_inputs_tickless)
    for _ in range(TICKS):
        br.tick()
    br.finish()
    s = br.stats()
    assert s["device_dispatches"] <= 3 * TICKS, s
    assert s["fallback_loads"] == 0, s
    assert all(f == TICKS for f in s["frames"]), s


def test_batched_runner_dispatches_flat_in_lobby_count():
    """O(1)-dispatch acceptance shape: the same lockstep workload at M=4 and
    M=16 must cost the SAME number of device dispatches per tick."""
    per_m = {}
    for m in (4, 16):
        app = stress.make_app(64, capacity=64)
        br = BatchedRunner(app, [_session(check_distance=2) for _ in range(m)],
                           read_inputs=_lobby_inputs_tickless)
        for _ in range(10):
            br.tick()
        br.finish()
        per_m[m] = br.stats()["device_dispatches"]
    assert per_m[4] == per_m[16], per_m


def test_bucketed_executor_buckets_and_counters():
    """Bucket selection, compile caching and dispatch counters: repeated
    same-shape waves must reuse programs (compile count stays flat)."""
    from bevy_ggrs_tpu.ops.batch import BucketedWaveExecutor, bucket_sizes

    assert bucket_sizes(12) == (1, 2, 4, 8, 12)
    assert bucket_sizes(8) == (1, 2, 4, 8)
    assert bucket_sizes(1) == (1,)

    M, K = 3, 5
    app = stress.make_app(32, capacity=32)
    from bevy_ggrs_tpu.ops.batch import stack_worlds

    worlds = stack_worlds([app.init_state() for _ in range(M)])
    ex = BucketedWaveExecutor(app, K)
    assert ex.bucket_for(1) == 1 and ex.bucket_for(3) == 4
    assert ex.bucket_for(5) == 5
    with pytest.raises(ValueError):
        ex.bucket_for(6)

    inputs = np.zeros((M, K, 2), np.uint8)
    status = np.zeros((M, K, 2), np.int8)
    starts = np.zeros((M,), np.int32)
    # lockstep k=1 wave -> exact bucket-1 program
    bucket, _f, stacked, checks = ex.run_wave(worlds, inputs, status, starts,
                                              [1, 1, 1])
    assert bucket == 1 and checks.shape == (M, 2)
    # ragged wave (k_hot=3) -> padded bucket-4 program
    bucket, _f, _s, checks = ex.run_wave(worlds, inputs, status, starts,
                                         [3, 0, 1])
    assert bucket == 4 and checks.shape == (M * 4, 2)
    compiles = ex.compile_count
    for _ in range(3):  # same shapes again: no new programs
        ex.run_wave(worlds, inputs, status, starts, [1, 1, 1])
        ex.run_wave(worlds, inputs, status, starts, [3, 0, 1])
    st = ex.stats()
    assert ex.compile_count == compiles, st
    assert st["bucket_hist"][1] == 4 and st["bucket_hist"][4] == 4
    assert st["wave_dispatches"] == 8


def test_bucketed_executor_exact_matches_padded():
    """The exact (unmasked) full-wave program must be bit-identical to the
    padded program at the same depth for a variant-stable sim — the executor
    switches between them by wave shape."""
    from bevy_ggrs_tpu.ops.batch import BucketedWaveExecutor, stack_worlds

    M, K = 2, 4
    app = stress.make_app(64, capacity=64)
    worlds = stack_worlds([app.init_state() for _ in range(M)])
    rng = np.random.default_rng(7)
    inputs = rng.integers(0, 16, size=(M, K, 2), dtype=np.uint8)
    status = np.zeros((M, K, 2), np.int8)
    starts = np.zeros((M,), np.int32)
    ex = BucketedWaveExecutor(app, K)
    _b, f_exact, s_exact, c_exact = ex.run_wave(
        worlds, inputs, status, starts, [K] * M
    )
    # force the padded program by making one lane ragged, then rerun the
    # SAME full wave through the padded builder directly
    from bevy_ggrs_tpu.ops.batch import make_batched_padded_fn

    padded = make_batched_padded_fn(app, K, unroll=ex.unroll,
                                    fused_checksums=ex.fused_checksums)
    f_pad, s_pad, c_pad = padded(worlds, inputs, status, starts,
                                 np.full((M,), K, np.int32))
    import jax

    for a, b in zip(jax.tree.leaves(f_exact), jax.tree.leaves(f_pad)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(c_exact), np.asarray(c_pad))
    for a, b in zip(jax.tree.leaves(s_exact), jax.tree.leaves(s_pad)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _lobby_inputs_tickless(lobby, handles):
    rng = np.random.default_rng(lobby)
    return {h: np.uint8(rng.integers(0, 16)) for h in handles}


def test_batched_runner_mixed_source_loads_match_solo():
    """Partial-fusion load coverage: per-lobby check_distance/compare_interval
    make every load wave MIXED — lobby 0 rolls back 4 frames (older ring
    rows), lobby 1 rolls back 2 (a different, more recent stacked buffer),
    lobby 2 only loads every other tick (so some waves it doesn't load at
    all) — and the whole wave must still be served by ONE fused gather and
    stay bit-identical to three independent GgrsRunners."""
    configs = [dict(check_distance=4, compare_interval=1),
               dict(check_distance=2, compare_interval=1),
               dict(check_distance=3, compare_interval=2)]
    TICKS = 25

    def make_session(cfg):
        return SyncTestSession(
            num_players=2, input_shape=(), input_dtype=np.uint8, **cfg,
        )

    app = fixed_point.make_app()  # input-sensitive: a wrong restore desyncs
    tcount = [0]

    def read_inputs(lobby, handles):
        return _lobby_inputs(lobby, tcount[0], handles)

    br = BatchedRunner(app, [make_session(c) for c in configs],
                       read_inputs=read_inputs)

    # spy the load waves to prove they were mixed (partial participation)
    load_waves = []
    orig_do_loads = br._do_loads

    def spying_do_loads(wave_ops, *args):
        n = sum(1 for op in wave_ops
                if op is not None and op.load_frame is not None)
        if n:
            load_waves.append(n)
        return orig_do_loads(wave_ops, *args)

    br._do_loads = spying_do_loads

    batched = [[] for _ in configs]
    for _ in range(TICKS):
        br.tick()
        tcount[0] += 1
        for b in range(len(configs)):
            batched[b].append(br.lobby_checksum(b))
    br.finish()  # SyncTest oracle across every lobby

    s = br.stats()
    assert s["fallback_loads"] == 0, s  # every load wave was fused
    assert s["fused_loads"] > 0, s
    # the mix really happened: some waves had loads from only PART of the
    # lobbies (lobby 2 skips every other tick)
    assert any(0 < n < len(configs) for n in load_waves), load_waves

    for b, cfg in enumerate(configs):
        solo_app = fixed_point.make_app()
        t = [0]

        def solo_inputs(handles, _b=b, _t=t):
            out = _lobby_inputs(_b, _t[0], handles)
            _t[0] += 1
            return out

        runner = GgrsRunner(solo_app, make_session(cfg),
                            read_inputs=solo_inputs)
        solo = []
        for _ in range(TICKS):
            runner.tick()
            solo.append(runner.checksum)
        runner.finish()
        assert batched[b] == solo, f"lobby {b} diverged from its solo run"


def test_batched_runner_p2p_pair_in_one_batch():
    """Both peers of ONE P2P game hosted as two lanes of the same batch —
    the in-process server shape.  They must sync, advance, and agree."""
    from bevy_ggrs_tpu import PlayerType, SessionBuilder, SessionState
    from bevy_ggrs_tpu.session.channel import ChannelNetwork

    app = stress.make_app(64, capacity=64)
    net = ChannelNetwork(latency_hops=1)
    sessions = []
    for i in range(2):
        b = (SessionBuilder(input_shape=(), input_dtype=np.uint8)
             .with_num_players(2).with_input_delay(1)
             .add_player(PlayerType.LOCAL, i)
             .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a"))
        sessions.append(b.start_p2p_session(net.endpoint("a" if i == 0 else "b")))

    def read_inputs(lobby, handles):
        return {h: np.uint8((lobby * 7 + h * 3) & 0xF) for h in handles}

    br = BatchedRunner(app, sessions, read_inputs=read_inputs)
    for _ in range(400):
        net.deliver()
        br.tick()
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            break
    assert all(s.current_state() == SessionState.RUNNING for s in sessions)
    for _ in range(60):
        net.deliver()
        br.tick()
    s = br.stats()
    assert min(s["frames"]) > 40, s
    # both lanes simulate the same game from the same inputs: once both
    # peers have confirmed a frame, their checksums for it must agree —
    # compare live checksums at equal frames
    if s["frames"][0] == s["frames"][1]:
        assert br.lobby_checksum(0) == br.lobby_checksum(1)


def test_batched_runner_non_identity_fused_saves_match_solo():
    """Non-identity strategies flow through the ONE-dispatch vmapped
    store_state save path (and the fused load applies load_state): quantized
    bf16 ring storage under batched SyncTest with mixed per-lobby rollback
    depths must restore exactly and stay bit-identical to solo runners (the
    per-frame store->load canonicalization absorbs any sub-bf16 float
    drift, so the comparison is exact)."""
    import dataclasses

    import jax.numpy as jnp

    from bevy_ggrs_tpu import App, QuantizeStrategy
    from bevy_ggrs_tpu.snapshot import active_mask, spawn

    def make_qapp():
        app = App(num_players=1, capacity=4, input_shape=(),
                  input_dtype=np.uint8)
        app.rollback_component("x", (), jnp.float32,
                               strategy=QuantizeStrategy(), checksum=True)
        app.rollback_component("n", (), jnp.int32, checksum=True)

        def step(world, ctx):
            m = active_mask(world)
            return dataclasses.replace(world, comps={
                "x": jnp.where(m & world.has["x"],
                               world.comps["x"] * 1.001 + 0.01,
                               world.comps["x"]),
                "n": jnp.where(m & world.has["n"], world.comps["n"] + 1,
                               world.comps["n"]),
            })

        def setup(world):
            world, _ = spawn(app.reg, world, {"x": 0.3, "n": 0})
            return world

        app.set_step(step)
        app.set_setup(setup)
        return app

    def make_sess(cd):
        return SyncTestSession(num_players=1, input_shape=(),
                               input_dtype=np.uint8, check_distance=cd,
                               compare_interval=1)

    cds = [3, 2, 3]
    TICKS = 15
    br = BatchedRunner(
        make_qapp(), [make_sess(cd) for cd in cds],
        read_inputs=lambda lobby, handles: {h: np.uint8(0) for h in handles},
    )
    batched = [[] for _ in cds]
    for _ in range(TICKS):
        br.tick()
        for b in range(len(cds)):
            batched[b].append(br.lobby_checksum(b))
    br.finish()  # SyncTest oracle: fused-stored rows must restore exactly
    s = br.stats()
    assert s["fallback_loads"] == 0, s

    for b, cd in enumerate(cds):
        runner = GgrsRunner(
            make_qapp(), make_sess(cd),
            read_inputs=lambda handles: {h: np.uint8(0) for h in handles},
        )
        solo = []
        for _ in range(TICKS):
            runner.tick()
            solo.append(runner.checksum)
        runner.finish()
        assert batched[b] == solo, f"lobby {b} diverged from its solo run"


def test_batched_runner_rejects_canonical_mode():
    app = stress.make_app(64, capacity=64)
    app.canonical_depth = 8
    with pytest.raises(ValueError):
        BatchedRunner(app, [_session()])


def test_batched_runner_staggered_p2p_rollback_waves():
    """The realistic server shape: several independent P2P games in ONE
    batch, each over a channel with a DIFFERENT latency/jitter, with
    flipping inputs — rollback waves hit different lobbies on different
    ticks, so load waves are partial (some lanes load while others
    advance), exercising the scatter-load fallback rather than the
    lockstep fused path the SyncTest tests cover.  Correctness oracle:
    an INPUT-SENSITIVE model (fixed_point — the stress model's step
    ignores inputs and would make this vacuous) whose two lanes per game
    must be checksum-identical at every mutually CONFIRMED ring frame
    (frames above confirmed may legitimately differ: one lane saved them
    with the remote input still predicted)."""
    from bevy_ggrs_tpu import PlayerType, SessionBuilder, SessionState
    from bevy_ggrs_tpu.session.channel import ChannelNetwork
    from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

    GAMES = 3
    app = fixed_point.make_app()
    nets, sessions = [], []
    for g in range(GAMES):
        net = ChannelNetwork(
            latency_hops=1 + g, jitter_hops=g, seed=100 + g
        )
        nets.append(net)
        for i in range(2):
            b = (SessionBuilder(input_shape=(), input_dtype=np.uint8)
                 .with_num_players(2).with_input_delay(1)
                 .with_max_prediction_window(8)
                 .add_player(PlayerType.LOCAL, i)
                 .add_player(PlayerType.REMOTE, 1 - i,
                             f"g{g}b" if i == 0 else f"g{g}a"))
            sessions.append(
                b.start_p2p_session(net.endpoint(f"g{g}a" if i == 0 else f"g{g}b"))
            )

    tick_no = [0]

    def read_inputs(lobby, handles):
        game = lobby // 2
        # different flip periods per game => mispredictions at different ticks
        on = (tick_no[0] // (4 + 2 * game)) % 2 == 0
        return {h: np.uint8(0x3 if on else 0xC) for h in handles}

    br = BatchedRunner(app, sessions, read_inputs=read_inputs)

    # record load-wave participation to prove waves were PARTIAL
    wave_profile = []
    orig_do_loads = br._do_loads

    def spying_do_loads(wave_ops, *args):
        n_load = sum(
            1 for op in wave_ops
            if op is not None and op.load_frame is not None
        )
        if n_load:
            wave_profile.append(n_load)
        return orig_do_loads(wave_ops, *args)

    br._do_loads = spying_do_loads

    def drive(n):
        for _ in range(n):
            tick_no[0] += 1
            for net in nets:
                net.deliver()
            br.tick()

    for _ in range(400):
        for net in nets:
            net.deliver()
        br.tick()
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            break
    assert all(s.current_state() == SessionState.RUNNING for s in sessions)
    drive(120)

    s = br.stats()
    assert min(s["frames"]) > 80, s
    assert br.rollbacks > 0
    # staggered: at least one load wave covered SOME but not ALL lanes
    assert wave_profile, "no rollback waves at all"
    assert any(n < 2 * GAMES for n in wave_profile), wave_profile
    # every game's two lanes agree at every mutually confirmed ring frame
    from bevy_ggrs_tpu.utils.frames import frame_le

    for g in range(GAMES):
        a, b = 2 * g, 2 * g + 1
        compared = 0
        for _ in range(8):
            conf = min(br.confirmed[a], br.confirmed[b])
            shared = [
                f for f in set(br.rings[a].frames()) & set(br.rings[b].frames())
                if frame_le(f, conf)
            ]
            if shared:
                break
            drive(1)
        assert shared, f"game {g}: no mutually confirmed ring frame"
        for f in sorted(shared):
            ca = checksum_to_int(br.rings[a].peek(f)[1])
            cb = checksum_to_int(br.rings[b].peek(f)[1])
            assert ca == cb, f"game {g} desynced at frame {f}"
            compared += 1
        assert compared > 0
