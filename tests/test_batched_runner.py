"""BatchedRunner (many-worlds server driver): M lobbies through one fused
dispatch per wave must match M independent GgrsRunners checksum-for-checksum,
with the SyncTest oracle green inside the batch (proving the batched
save/load/ring plumbing restores exactly what it saved)."""

import numpy as np
import pytest

from bevy_ggrs_tpu import BatchedRunner, GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import fixed_point, stress


def _session(check_distance=4):
    return SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8,
        check_distance=check_distance, compare_interval=1,
    )


def _lobby_inputs(lobby, tick, handles):
    rng = np.random.default_rng(1000 * lobby + tick)
    return {h: np.uint8(rng.integers(0, 16)) for h in handles}


def _solo_checksums(app_factory, lobby, ticks, check_distance=4):
    app = app_factory()
    t = [0]

    def read_inputs(handles):
        out = _lobby_inputs(lobby, t[0], handles)
        t[0] += 1
        return out

    runner = GgrsRunner(app, _session(check_distance), read_inputs=read_inputs)
    out = []
    for _ in range(ticks):
        runner.tick()
        out.append(runner.checksum)
    runner.finish()
    return out


@pytest.mark.parametrize("app_factory", [
    lambda: stress.make_app(128, capacity=128),
    fixed_point.make_app,
], ids=["stress", "fixed_point"])
def test_batched_runner_matches_independent_runners(app_factory):
    M, TICKS = 3, 25
    app = app_factory()
    tcount = [0]

    def read_inputs(lobby, handles):
        # same per-(lobby, tick) stream the solo runners consume
        return _lobby_inputs(lobby, tcount[0], handles)

    br = BatchedRunner(app, [_session() for _ in range(M)],
                       read_inputs=read_inputs)
    batched = [[] for _ in range(M)]
    for _ in range(TICKS):
        br.tick()
        tcount[0] += 1
        for b in range(M):
            batched[b].append(br.lobby_checksum(b))
    br.finish()  # SyncTest oracle: raises on any batched-restore mismatch

    for b in range(M):
        solo = _solo_checksums(app_factory, b, TICKS)
        assert batched[b] == solo, f"lobby {b} diverged from its solo run"


def test_batched_runner_dispatch_count():
    """The whole point: M lobbies per tick must cost O(waves) dispatches,
    not O(M) — synctest shape is 2 waves (live + resim) once warmed up."""
    M, TICKS = 8, 12
    app = stress.make_app(64, capacity=64)
    br = BatchedRunner(app, [_session(check_distance=3) for _ in range(M)],
                       read_inputs=_lobby_inputs_tickless)
    for _ in range(TICKS):
        br.tick()
    br.finish()
    s = br.stats()
    assert s["device_dispatches"] <= 2 * TICKS, s
    assert all(f == TICKS for f in s["frames"]), s


def _lobby_inputs_tickless(lobby, handles):
    rng = np.random.default_rng(lobby)
    return {h: np.uint8(rng.integers(0, 16)) for h in handles}


def test_batched_runner_p2p_pair_in_one_batch():
    """Both peers of ONE P2P game hosted as two lanes of the same batch —
    the in-process server shape.  They must sync, advance, and agree."""
    from bevy_ggrs_tpu import PlayerType, SessionBuilder, SessionState
    from bevy_ggrs_tpu.session.channel import ChannelNetwork

    app = stress.make_app(64, capacity=64)
    net = ChannelNetwork(latency_hops=1)
    sessions = []
    for i in range(2):
        b = (SessionBuilder(input_shape=(), input_dtype=np.uint8)
             .with_num_players(2).with_input_delay(1)
             .add_player(PlayerType.LOCAL, i)
             .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a"))
        sessions.append(b.start_p2p_session(net.endpoint("a" if i == 0 else "b")))

    def read_inputs(lobby, handles):
        return {h: np.uint8((lobby * 7 + h * 3) & 0xF) for h in handles}

    br = BatchedRunner(app, sessions, read_inputs=read_inputs)
    for _ in range(400):
        net.deliver()
        br.tick()
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            break
    assert all(s.current_state() == SessionState.RUNNING for s in sessions)
    for _ in range(60):
        net.deliver()
        br.tick()
    s = br.stats()
    assert min(s["frames"]) > 40, s
    # both lanes simulate the same game from the same inputs: once both
    # peers have confirmed a frame, their checksums for it must agree —
    # compare live checksums at equal frames
    if s["frames"][0] == s["frames"][1]:
        assert br.lobby_checksum(0) == br.lobby_checksum(1)


def test_batched_runner_rejects_canonical_mode():
    app = stress.make_app(64, capacity=64)
    app.canonical_depth = 8
    with pytest.raises(ValueError):
        BatchedRunner(app, [_session()])


def test_batched_runner_staggered_p2p_rollback_waves():
    """The realistic server shape: several independent P2P games in ONE
    batch, each over a channel with a DIFFERENT latency/jitter, with
    flipping inputs — rollback waves hit different lobbies on different
    ticks, so load waves are partial (some lanes load while others
    advance), exercising the scatter-load fallback rather than the
    lockstep fused path the SyncTest tests cover.  Correctness oracle:
    an INPUT-SENSITIVE model (fixed_point — the stress model's step
    ignores inputs and would make this vacuous) whose two lanes per game
    must be checksum-identical at every mutually CONFIRMED ring frame
    (frames above confirmed may legitimately differ: one lane saved them
    with the remote input still predicted)."""
    from bevy_ggrs_tpu import PlayerType, SessionBuilder, SessionState
    from bevy_ggrs_tpu.session.channel import ChannelNetwork
    from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

    GAMES = 3
    app = fixed_point.make_app()
    nets, sessions = [], []
    for g in range(GAMES):
        net = ChannelNetwork(
            latency_hops=1 + g, jitter_hops=g, seed=100 + g
        )
        nets.append(net)
        for i in range(2):
            b = (SessionBuilder(input_shape=(), input_dtype=np.uint8)
                 .with_num_players(2).with_input_delay(1)
                 .with_max_prediction_window(8)
                 .add_player(PlayerType.LOCAL, i)
                 .add_player(PlayerType.REMOTE, 1 - i,
                             f"g{g}b" if i == 0 else f"g{g}a"))
            sessions.append(
                b.start_p2p_session(net.endpoint(f"g{g}a" if i == 0 else f"g{g}b"))
            )

    tick_no = [0]

    def read_inputs(lobby, handles):
        game = lobby // 2
        # different flip periods per game => mispredictions at different ticks
        on = (tick_no[0] // (4 + 2 * game)) % 2 == 0
        return {h: np.uint8(0x3 if on else 0xC) for h in handles}

    br = BatchedRunner(app, sessions, read_inputs=read_inputs)

    # record load-wave participation to prove waves were PARTIAL
    wave_profile = []
    orig_do_loads = br._do_loads

    def spying_do_loads(wave_ops):
        n_load = sum(
            1 for op in wave_ops
            if op is not None and op.load_frame is not None
        )
        if n_load:
            wave_profile.append(n_load)
        return orig_do_loads(wave_ops)

    br._do_loads = spying_do_loads

    def drive(n):
        for _ in range(n):
            tick_no[0] += 1
            for net in nets:
                net.deliver()
            br.tick()

    for _ in range(400):
        for net in nets:
            net.deliver()
        br.tick()
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            break
    assert all(s.current_state() == SessionState.RUNNING for s in sessions)
    drive(120)

    s = br.stats()
    assert min(s["frames"]) > 80, s
    assert br.rollbacks > 0
    # staggered: at least one load wave covered SOME but not ALL lanes
    assert wave_profile, "no rollback waves at all"
    assert any(n < 2 * GAMES for n in wave_profile), wave_profile
    # every game's two lanes agree at every mutually confirmed ring frame
    from bevy_ggrs_tpu.utils.frames import frame_le

    for g in range(GAMES):
        a, b = 2 * g, 2 * g + 1
        compared = 0
        for _ in range(8):
            conf = min(br.confirmed[a], br.confirmed[b])
            shared = [
                f for f in set(br.rings[a].frames()) & set(br.rings[b].frames())
                if frame_le(f, conf)
            ]
            if shared:
                break
            drive(1)
        assert shared, f"game {g}: no mutually confirmed ring frame"
        for f in sorted(shared):
            ca = checksum_to_int(br.rings[a].peek(f)[1])
            cb = checksum_to_int(br.rings[b].peek(f)[1])
            assert ca == cb, f"game {g} desynced at frame {f}"
            compared += 1
        assert compared > 0
