"""Multi-host validation: a genuine 2-process jax.distributed mesh (4 CPU
devices per process, gloo as the DCN stand-in) runs the sharded resim and
produces bit-identical checksums on every rank AND identical to a
single-process run of the same 8-device topology (integer model)."""

import os
import re
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 4)
    rank = int(sys.argv[1]); port = sys.argv[2]
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{{port}}",
                               num_processes=2, process_id=rank)
    sys.path.insert(0, {repo!r})
    import numpy as np
    from bevy_ggrs_tpu.models import fixed_point
    from bevy_ggrs_tpu.parallel import multihost, make_sharded_resim_fn
    from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

    mesh = multihost.make_multihost_mesh(n_spec=2)
    assert len(jax.devices()) == 8
    assert multihost.process_count() == 2
    app = fixed_point.make_app(capacity=16)
    world = app.init_state()
    rng = np.random.default_rng(7)
    inputs = rng.integers(0, 16, (8, 2)).astype(np.uint8)
    status = np.zeros((8, 2), np.int8)
    _, _, checks = make_sharded_resim_fn(app, mesh)(world, inputs, status, 0)
    print(f"RESULT rank={{rank}} checksum={{checksum_to_int(np.asarray(checks)[-1]):#x}}",
          flush=True)
    """
).format(repo=REPO)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
def test_two_process_distributed_mesh(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(rank), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={k: v for k, v in os.environ.items()
                 if k not in ("JAX_PLATFORMS",)},
        )
        for rank in (0, 1)
    ]
    sums = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        assert p.returncode == 0, out[-2000:]
        m = re.search(r"RESULT rank=\d+ checksum=(0x[0-9a-f]+)", out)
        assert m, out[-2000:]
        sums.append(int(m.group(1), 16))
    assert sums[0] == sums[1], "ranks disagree"

    # same topology single-process: the integer model must match exactly

    from bevy_ggrs_tpu.models import fixed_point
    from bevy_ggrs_tpu.parallel import make_mesh, make_sharded_resim_fn
    from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

    mesh = make_mesh(n_data=4, n_spec=2)
    app = fixed_point.make_app(capacity=16)
    world = app.init_state()
    rng = np.random.default_rng(7)
    inputs = rng.integers(0, 16, (8, 2)).astype(np.uint8)
    status = np.zeros((8, 2), np.int8)
    _, _, checks = make_sharded_resim_fn(app, mesh)(world, inputs, status, 0)
    local = checksum_to_int(np.asarray(checks)[-1])
    assert local == sums[0], "multi-process differs from single-process"
