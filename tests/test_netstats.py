"""Network-layer observability: NetStatsSampler, rollback-cause
attribution, QoS scoring, and cross-peer forensics merge
(telemetry/netstats.py, telemetry/qos.py, forensics.merge_reports)."""

import json

import numpy as np
import pytest

from bevy_ggrs_tpu import (
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
    telemetry,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.session.events import NetworkStats
from bevy_ggrs_tpu.session.requests import LoadRequest
from bevy_ggrs_tpu.session.synctest import SyncTestSession
from bevy_ggrs_tpu.session.time_sync import TimeSync
from bevy_ggrs_tpu.telemetry.netstats import NetStatsSampler
from bevy_ggrs_tpu.telemetry.qos import qos_score, qos_snapshot

DT = 1.0 / 60.0


@pytest.fixture(autouse=True)
def _telemetry():
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.disable()
    telemetry.reset()


class _FakeSession:
    """Minimal session surface for sampler unit tests."""

    def __init__(self, stats_by_handle):
        self.stats_by_handle = stats_by_handle
        self.calls = 0

    def remote_player_handles(self):
        return sorted(self.stats_by_handle)

    def network_stats(self, handle):
        self.calls += 1
        return self.stats_by_handle[handle]

    def frames_ahead(self):
        return 2


# -- sampler ----------------------------------------------------------------


def test_sampler_disabled_is_one_boolean_check():
    s = _FakeSession({1: NetworkStats(ping_ms=10.0)})
    sampler = NetStatsSampler(s, every=0)
    assert not sampler.enabled
    for _ in range(100):
        sampler.poll()
    # the disabled path returns before even counting polls: no counter
    # bump, no session traffic, no registry traffic
    assert sampler._n == 0
    assert s.calls == 0
    assert sampler.samples == 0
    assert "netstats_samples_total" not in telemetry.registry().snapshot()


def test_sampler_cadence_and_families():
    s = _FakeSession({
        1: NetworkStats(ping_ms=42.0, send_queue_len=3, kbps_sent=8.5,
                        local_frames_behind=2, remote_frames_behind=-1),
    })
    sampler = NetStatsSampler(s, every=5)
    for _ in range(25):
        sampler.poll()
    assert sampler.samples == 5
    snap = telemetry.registry().snapshot()
    assert snap["peer_send_queue"]["series"]["handle=1"] == 3
    assert snap["peer_kbps"]["series"]["handle=1"] == 8.5
    behind = snap["peer_frames_behind"]["series"]
    assert behind["handle=1,side=local"] == 2
    assert behind["handle=1,side=remote"] == -1
    # no per-endpoint TimeSync on the fake: falls back to session-wide
    # frames_ahead, warmup reads 0 (treated as warmed)
    assert snap["frame_advantage"]["series"]["handle=1"] == 2
    assert snap["time_sync_warmup"]["series"]["handle=1"] == 0
    ping = snap["peer_ping_ms"]["series"]["handle=1"]
    assert ping["count"] == 5 and ping["sum"] == pytest.approx(5 * 42.0)
    assert snap["netstats_samples_total"]["series"][""] == 5


def test_sampler_skips_non_live_silently():
    s = _FakeSession({
        0: NetworkStats(is_live=False),
        1: NetworkStats(ping_ms=5.0),
    })
    sampler = NetStatsSampler(s, every=1)
    sampler.poll()
    series = telemetry.registry().snapshot()["peer_ping_ms"]["series"]
    assert "handle=1" in series and "handle=0" not in series


def test_sampler_env_cadence(monkeypatch):
    monkeypatch.setenv("BGT_NETSTATS_EVERY", "7")
    assert NetStatsSampler(_FakeSession({})).every == 7
    monkeypatch.setenv("BGT_NETSTATS_EVERY", "0")
    assert not NetStatsSampler(_FakeSession({})).enabled
    monkeypatch.setenv("BGT_NETSTATS_EVERY", "junk")
    assert NetStatsSampler(_FakeSession({})).every == 60


# -- zeroed NetworkStats (is_live) ------------------------------------------


def _p2p_pair(latency_hops=0, seed=1, delay=1):
    net = ChannelNetwork(latency_hops=latency_hops, seed=seed)
    socks = [net.endpoint("peer0"), net.endpoint("peer1")]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(delay)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"peer{1 - i}")
        )
        session = b.start_p2p_session(socks[i])
        runners.append(
            GgrsRunner(app, session, read_inputs=lambda hs: {
                h: box_game.keys_to_input() for h in hs
            })
        )
    return net, runners


def _sync(net, runners, ticks=300):
    for _ in range(ticks):
        net.deliver()
        for r in runners:
            r.update(0.0)
    assert all(
        r.session.current_state() == SessionState.RUNNING for r in runners
    )


def test_network_stats_zeroed_for_non_live_handles():
    net, runners = _p2p_pair()
    s = runners[0].session
    # local handle: no endpoint behind it -> zeroed, not an exception
    st = s.network_stats(0)
    assert not st.is_live and st.ping_ms == 0.0 and st.send_queue_len == 0
    # unknown handle
    assert not s.network_stats(99).is_live
    # live remote handle
    assert s.network_stats(1).is_live
    # disconnected endpoint -> back to zeroed
    addr = s.remote_handle_addr[1]
    s.endpoints[addr].disconnected = True
    assert not s.network_stats(1).is_live
    assert s.time_sync_for(1) is None
    assert s.remote_player_handles() == [1]


# -- rollback-cause attribution ---------------------------------------------


def test_p2p_attribution_blames_remote_and_sums_match():
    net, runners = _p2p_pair(latency_hops=3)
    _sync(net, runners)
    flip = [0]

    def read_inputs(handles):
        flip[0] += 1
        on = (flip[0] // 7) % 2 == 0
        return {h: box_game.keys_to_input(right=on) for h in handles}

    for r in runners:
        r.read_inputs = read_inputs
        r._netstats = NetStatsSampler(r.session, every=8)
    for _ in range(120):
        net.deliver()
        for r in runners:
            r.update(DT)
    snap = telemetry.registry().snapshot()
    total = sum(snap["rollbacks_total"]["series"].values())
    causes = snap["rollback_cause_total"]["series"]
    assert total > 0, "latency + flipping inputs must force rollbacks"
    # the attribution invariant: every rollback carries a cause
    assert sum(causes.values()) == total
    # p2p mispredictions blame the REMOTE peer (each runner blames the
    # other's handle — both appear because both processes share a registry)
    assert set(causes) <= {"handle=0", "handle=1"}
    # lateness histogram rides the same labels
    lat = snap["input_lateness_frames"]["series"]
    assert sum(v["count"] for v in lat.values()) == total
    assert all(v["sum"] >= v["count"] for v in lat.values())  # >= 1 frame late
    # the sampler populated the per-peer families along the way
    assert "peer_ping_ms" in snap and "netstats_samples_total" in snap
    # flight ring carries the blamed entries even for always-on consumers
    rb_entries = telemetry.flight_recorder().snapshot("rollback")
    assert rb_entries and all(
        e.get("handle") in (0, 1) and e.get("lateness", 0) >= 1
        for e in rb_entries
    )


def test_synctest_rollbacks_attributed_as_resim():
    s = SyncTestSession(num_players=1, check_distance=2)
    causes = []
    for _ in range(6):
        s.add_local_input(0, np.uint8(0))
        for r in s.advance_frame():
            if isinstance(r, LoadRequest):
                causes.append(r.cause)
    assert causes, "check_distance>0 must emit structural rollbacks"
    for c in causes:
        assert c is not None
        assert c.handle == "resim" and c.kind == "resim"
        assert c.lateness == 2 and not c.mismatch


def test_causeless_load_attributed_to_unknown():
    net, runners = _p2p_pair()
    _sync(net, runners)
    r = runners[0]
    for _ in range(4):
        net.deliver()
        for x in runners:
            x.update(DT)
    target = max(r.ring.frames())
    r._load(target, None)  # legacy/replay path: no cause attached
    snap = telemetry.registry().snapshot()
    causes = snap["rollback_cause_total"]["series"]
    total = sum(snap["rollbacks_total"]["series"].values())
    assert causes.get("handle=unknown", 0) >= 1
    assert sum(causes.values()) == total


# -- TimeSync warmup ---------------------------------------------------------


def test_time_sync_warmup_and_one_sided_estimate():
    ts = TimeSync()
    assert not ts.warmed_up()
    assert ts.frames_ahead() == 0  # no data at all
    for f in range(10):
        ts.note_local(f + 4, f)  # consistently 4 ahead locally
    assert not ts.warmed_up()  # remote window still empty...
    assert ts.frames_ahead() == 2  # ...but the local view shows through
    ts.note_remote(-4)
    assert ts.warmed_up()
    assert ts.frames_ahead() == 4  # (4 - (-4)) / 2


# -- QoS ---------------------------------------------------------------------


def test_qos_score_monotone_and_bounded():
    base = qos_score(0, 0, 0, 0)
    assert base == 100.0
    # strictly monotone decreasing along every axis, from any point
    pts = [(0, 0, 0, 0), (60, 0.1, 0.01, 10.0), (300, 1.0, 0.5, 100.0)]
    for p in pts:
        s0 = qos_score(*p)
        for axis in range(4):
            worse = list(p)
            worse[axis] = worse[axis] * 2 + 1
            assert qos_score(*worse) < s0
        assert 0.0 < s0 <= 100.0
    # negative (bogus) samples clamp instead of inflating the score
    assert qos_score(-50, 0, 0, 0) == 100.0


def test_qos_snapshot_reads_registry_and_serves_json():
    import urllib.request

    telemetry.count("rollbacks_total", 5)
    telemetry.count("ticks_total", 100)
    telemetry.count("readback_forced_total", 1)
    telemetry.count("readback_harvested_total", 9)
    snap = qos_snapshot()
    d = snap["lobbies"]["default"]
    assert d["inputs"]["rollback_rate"] == pytest.approx(0.05)
    assert d["inputs"]["forced_readback_rate"] == pytest.approx(0.1)
    assert 0 < d["score"] < 100
    ex = telemetry.start_http_exporter(port=0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{ex.port}/qos", timeout=10
        ).read()
        served = json.loads(body)
        assert served["lobby_qos_score"]["default"] == d["score"]
        assert served["scales"]["worst_ping_ms"] > 0
        # the endpoint refreshed the gauge for the next /metrics scrape
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{ex.port}/metrics", timeout=10
        ).read().decode()
        assert "lobby_qos_score" in text
    finally:
        ex.close()


def test_qos_per_lobby_scores():
    telemetry.count("ticks_total", 100)
    telemetry.count("rollbacks_total", 2, lobby=0)
    telemetry.count("rollbacks_total", 40, lobby=1)
    snap = qos_snapshot()
    assert set(snap["lobby_qos_score"]) == {"0", "1"}
    assert snap["lobby_qos_score"]["0"] > snap["lobby_qos_score"]["1"]


# -- cross-peer forensics merge ----------------------------------------------


def _write_report(tmp_path, name, checksums, comp, flight):
    p = tmp_path / name
    telemetry.write_desync_report(
        "p2p_desync", frames=[max(checksums)], path=str(p),
        checksums=checksums,
    )
    rep = json.loads(p.read_text())
    rep["component_checksums"] = comp
    rep["flight_record"] = flight
    p.write_text(json.dumps(rep))
    return str(p)


def test_merge_reports_first_divergent_frame(tmp_path):
    a = _write_report(
        tmp_path, "a.json",
        {8: 100, 9: 101, 10: 102, 11: 103},
        {"position": 1, "velocity": 2},
        [{"kind": "tick", "frame": 9, "wall_ms": 1.5},
         {"kind": "rollback", "to_frame": 9, "depth": 2, "handle": 1,
          "lateness": 2, "cause_kind": "misprediction"}],
    )
    b = _write_report(
        tmp_path, "b.json",
        {9: 101, 10: 999, 11: 998, 12: 997},
        {"position": 1, "velocity": 7},
        [{"kind": "tick", "frame": 10, "wall_ms": 1.1}],
    )
    m = telemetry.merge_reports(a, b)
    assert m["first_divergent_frame"] == 10
    assert m["divergent_frames"] == [10, 11]
    assert m["common_frames"] == 3  # frames 9, 10, 11
    assert m["checksums_at_divergence"] == {"a": 102, "b": 999}
    assert m["component_diff"] == ["velocity"]
    assert m["rollbacks"]["a"][0]["handle"] == 1
    # tick context windows around the divergent frame
    assert [e["frame"] for e in m["tick_context"]["a"]] == [9]
    assert [e["frame"] for e in m["tick_context"]["b"]] == [10]


def test_merge_reports_agreeing_windows(tmp_path):
    cs = {5: 1, 6: 2}
    a = _write_report(tmp_path, "a.json", cs, None, [])
    b = _write_report(tmp_path, "b.json", cs, None, [])
    m = telemetry.merge_reports(a, b)
    # overlap agrees -> fall back to the detector-flagged frames (both
    # reports flagged max(cs) here)
    assert m["first_divergent_frame"] == 6
    assert m["divergent_frames"] == []


def test_desync_report_carries_frame_checksums(tmp_path):
    p = tmp_path / "r.json"
    telemetry.write_desync_report(
        "p2p_desync", frames=[3], path=str(p), checksums={3: 7, 4: 8},
    )
    rep = json.loads(p.read_text())
    assert rep["checksums"] == {"3": 7, "4": 8}


def test_merge_reports_cli(tmp_path, capsys):
    import scripts.replay_tool as rt

    a = _write_report(tmp_path, "a.json", {1: 10, 2: 20}, None, [])
    b = _write_report(tmp_path, "b.json", {1: 10, 2: 21}, None, [])

    class Args:
        pass

    args = Args()
    args.a, args.b = a, b
    rc = rt.cmd_merge_reports(args)
    out = capsys.readouterr().out
    assert rc == 1
    assert "FIRST DIVERGENT FRAME: 2" in out
