"""Fleet telemetry federation: series rings, SLO burn/hysteresis/dedup
semantics, heartbeat digest suppression over loopback, the federated HTTP
surface (``/fleet`` + fleet-wide ``/qos`` + ``worker=``-labeled
``/metrics``), and the N-way trace merge with fleet wire-event alignment
and migration flow arrows."""

import json
import time
import urllib.request

import pytest

from bevy_ggrs_tpu import telemetry
from bevy_ggrs_tpu.fleet import (
    FleetObserver,
    FleetScheduler,
    FleetWorker,
    SLO,
    start_fleet_exporter,
)
from bevy_ggrs_tpu.fleet import protocol as P
from bevy_ggrs_tpu.telemetry.trace import merge_traces, validate_chrome_trace


def _hb(qos_by_lobby, frame=0):
    """Synthetic worker heartbeat stats carrying the given lobby QoS map."""
    return {
        "capacity": 4,
        "lobbies": {lid: {"frame": frame, "state": "running"}
                    for lid in qos_by_lobby},
        "lobby_qos_score": dict(qos_by_lobby),
        "shard_imbalance_ratio": 1.0,
        "device_resident_bytes": 1024,
    }


@pytest.fixture()
def tel():
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.disable()
    telemetry.reset()


# -- series rings -----------------------------------------------------------


def test_series_ring_window_rate_and_bounds():
    from bevy_ggrs_tpu.fleet.observe import SeriesRing

    r = SeriesRing(capacity=4)
    assert r.last() is None and r.window(10.0) == [] \
        and r.rate(10.0) is None
    for i in range(6):  # overflows the 4-slot ring
        r.add(float(i), float(i * 10))
    assert len(r) == 4
    assert r.last() == (5.0, 50.0)
    # window is tail-referenced when now is omitted
    assert r.window(1.0) == [(4.0, 40.0), (5.0, 50.0)]
    assert r.window(0.5, now=5.0) == [(5.0, 50.0)]
    # rate: (50-20)/(5-2) over the full retained window
    assert r.rate(10.0) == pytest.approx(10.0)
    assert r.rate(0.5, now=5.0) is None  # one sample spans no interval
    assert r.tail(2) == [[4.0, 40.0], [5.0, 50.0]]


def test_observer_window_and_rate_query_surface(tel):
    obs = FleetObserver(slos=[])
    obs.ingest_heartbeat("w0", _hb({"L0": 80.0}, frame=0), now=0.0)
    obs.ingest_heartbeat("w0", _hb({"L0": 80.0}, frame=60), now=1.0)
    assert obs.window("lobby", "L0", "frame", 10.0, now=1.0) == \
        [(0.0, 0.0), (1.0, 60.0)]
    # frame rate == fps of the hosted lobby, derivable at the scheduler
    assert obs.rate("lobby", "L0", "frame", 10.0, now=1.0) == \
        pytest.approx(60.0)
    assert obs.rate("worker", "w0", "qos_floor", 10.0, now=1.0) == \
        pytest.approx(0.0)
    assert obs.window("worker", "nope", "qos_floor", 10.0) == []


# -- SLO burn semantics ------------------------------------------------------


def test_qos_slo_fires_only_after_sustained_breach(tel):
    slo = SLO("qos_floor", "qos_floor", 50.0,
              burn_window_s=1.0, resolve_window_s=1.0)
    obs = FleetObserver(slos=[slo])
    # one bad sample is NOT an incident
    obs.ingest_heartbeat("w0", _hb({"L0": 10.0}), now=0.0)
    assert obs.evaluate(0.0) == []  # breach observed, burn window not met
    obs.ingest_heartbeat("w0", _hb({"L0": 90.0}), now=0.4)
    assert obs.evaluate(0.4) == []  # recovered: burn clock resets
    assert obs.active_alerts() == []

    # a sustained breach fires exactly once
    fired = []
    for t in (2.0, 2.5, 3.0, 3.5, 4.0):
        obs.ingest_heartbeat("w0", _hb({"L0": 10.0}), now=t)
        fired += obs.evaluate(t)
    assert [e.state for e in fired] == ["fire"]
    ev = fired[0]
    assert (ev.slo_id, ev.subject, ev.signal) == \
        ("qos_floor", "L0", "qos_floor")
    assert ev.t == 3.0  # burn window satisfied a full 1.0s after 2.0
    assert len(obs.active_alerts()) == 1

    # hysteresis: recovery must stay clean for resolve_window_s
    resolved = []
    for t in (5.0, 5.5, 6.0):
        obs.ingest_heartbeat("w0", _hb({"L0": 90.0}), now=t)
        resolved += obs.evaluate(t)
    assert [e.state for e in resolved] == ["resolve"]
    assert resolved[0].t == 6.0
    assert obs.active_alerts() == []
    # the counter carries one fire and one resolve, never one per tick
    series = telemetry.summary()["metrics"]["fleet_alerts_total"]["series"]
    assert series == {"slo=qos_floor,state=fire": 1,
                      "slo=qos_floor,state=resolve": 1}


def test_liveness_slo_fire_and_resolve(tel):
    obs = FleetObserver()  # default slos: liveness gap 1.5s
    obs.ingest_liveness("w0", now=0.0)
    assert obs.evaluate(1.0) == []  # gap 1.0 < 1.5
    fired = obs.evaluate(2.0)  # gap 2.0 > 1.5 — the gap IS the sustain
    assert [(e.slo_id, e.state) for e in fired] == \
        [("heartbeat_liveness", "fire")]
    assert fired[0].value == pytest.approx(2.0)
    # dedup: further breaching ticks emit nothing
    assert obs.evaluate(2.5) == []
    assert obs.evaluate(3.0) == []
    # heartbeat returns; resolve only after a clean resolve window
    obs.ingest_liveness("w0", now=3.2)
    assert obs.evaluate(3.3) == []
    resolved = obs.evaluate(4.4)
    assert [(e.slo_id, e.state) for e in resolved] == \
        [("heartbeat_liveness", "resolve")]
    history = obs.alert_history()
    assert [a["state"] for a in history] == ["fire", "resolve"]


def test_migration_downtime_slo_event_triggered(tel):
    obs = FleetObserver()  # default ceiling 2000 ms
    obs.note_migration("L0", 120.0, now=0.0)
    assert obs.evaluate(0.1) == []  # under the ceiling
    obs.note_migration("L0", 3500.0, now=5.0)
    fired = obs.evaluate(5.0)  # one blown ceiling IS the incident
    assert [(e.slo_id, e.subject, e.state) for e in fired] == \
        [("migration_downtime", "L0", "fire")]
    assert fired[0].value == pytest.approx(3500.0)
    # the event ages out of breach, then hysteresis resolves
    assert obs.evaluate(5.5) == []
    resolved = []
    for t in (6.5, 7.5, 8.5):
        resolved += obs.evaluate(t)
    assert [e.state for e in resolved] == ["resolve"]


def test_forget_worker_force_resolves_active_alerts(tel):
    obs = FleetObserver()
    obs.ingest_liveness("w0", now=0.0)
    assert len(obs.evaluate(5.0)) == 1  # liveness fire
    emitted = obs.forget_worker("w0", now=6.0)
    assert [(e.slo_id, e.state) for e in emitted] == \
        [("heartbeat_liveness", "resolve")]
    assert obs.active_alerts() == []
    assert obs.evaluate(7.0) == []  # the dead worker never alerts again


# -- heartbeat digest suppression -------------------------------------------


def test_protocol_heartbeat_seq_roundtrip():
    msg = P.decode(P.encode_heartbeat_seq("w0", 77, "ab12cd34ef56ab78"))
    assert msg is not None and msg.kind == P.T_HEARTBEAT_SEQ
    assert (msg.a, msg.seq, msg.b) == ("w0", 77, "ab12cd34ef56ab78")
    # digest is canonical: key order does not matter, values do
    s1 = {"capacity": 2, "lobbies": {"a": {"frame": 1}}}
    s2 = {"lobbies": {"a": {"frame": 1}}, "capacity": 2}
    assert P.stats_digest(s1) == P.stats_digest(s2)
    assert P.stats_digest(s1) != P.stats_digest(
        {"capacity": 2, "lobbies": {"a": {"frame": 2}}})
    # round-trip stable: digesting the decoded JSON matches the original
    hb = P.decode(P.encode_heartbeat("w0", s1))
    assert P.stats_digest(hb.obj) == P.stats_digest(s1)


def test_heartbeat_suppression_over_loopback(tel):
    sched = FleetScheduler(worker_timeout_s=30.0)
    w = FleetWorker("w0", sched.local_addr, capacity=1, heartbeat_s=0.02)
    try:
        w.register()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and "w0" not in sched.workers:
            sched.poll()
            w.poll()
            time.sleep(0.002)
        assert "w0" in sched.workers
        counter = telemetry.registry().counter(
            "fleet_heartbeat_suppressed_total", "")
        # idle worker -> unchanged stats -> seq-only liveness heartbeats
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and counter.value() < 5:
            sched.poll()
            w.poll()
            time.sleep(0.002)
        assert counter.value() >= 5
        wi = sched.workers["w0"]
        # the scheduler accepted them: digest pinned to the held stats,
        # liveness fresh even though no full payload arrived recently
        assert wi.stats_digest == P.stats_digest(wi.stats)
        assert time.monotonic() - wi.last_seen < 1.0
        # and the observer's gap series kept sampling on liveness beats
        gaps = sched.observer.window("worker", "w0", "heartbeat_gap_ms",
                                     span_s=60.0)
        assert len(gaps) >= 5
    finally:
        w.close()
        sched.close()


# -- federated HTTP surface --------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        body = r.read()
        return r.headers.get("Content-Type", ""), body


def test_fleet_exporter_routes_and_one_schema(tel):
    obs = FleetObserver(slos=[])
    obs.ingest_heartbeat("wA", _hb({"L0": 91.0, "L1": 33.0}), now=1.0,
                         assigned_slots=2)
    obs.ingest_heartbeat("wB", _hb({"L2": 55.0}), now=1.1, assigned_slots=1)
    obs.set_topology({"workers": {"wA": {"capacity": 4},
                                  "wB": {"capacity": 4}},
                      "lobbies": {}, "events": []})
    exp = start_fleet_exporter(obs, port=0, worst_n=2)
    try:
        base = f"http://127.0.0.1:{exp.port}"
        ctype, body = _get(base + "/fleet")
        assert "json" in ctype
        fleet = json.loads(body)
        assert fleet["schema"] == "fleet/v1"
        assert set(fleet["workers"]) == {"wA", "wB"}
        assert fleet["workers"]["wA"]["capacity"] == 4  # topology merged in
        assert fleet["workers"]["wA"]["series"]["assigned_slots"] == [[1.0, 2.0]]
        assert fleet["lobbies"]["L1"]["worker"] == "wA"
        # ONE schema: the HTTP payload is the CLI payload
        snap = obs.fleet_snapshot()
        assert set(snap) == set(fleet)
        assert set(snap["workers"]) == set(fleet["workers"])
        # fleet-wide /qos overrides the single-process route: worst-first
        _, body = _get(base + "/qos")
        qos = json.loads(body)
        assert qos["schema"] == "fleet-qos/v1"
        assert [r["lobby"] for r in qos["worst_lobbies"]] == ["L1", "L2"]
        assert qos["worst_lobbies"][0]["worker"] == "wA"
        # federated /metrics: worker-labeled gauges in one scrape
        _, body = _get(base + "/metrics")
        text = body.decode("utf-8")
        assert 'fleet_worker_qos_floor{worker="wA"}' in text
        assert 'fleet_worker_qos_floor{worker="wB"}' in text
        assert 'fleet_lobby_qos_score{lobby="L1",worker="wA"}' in text
    finally:
        exp.close()


def test_fleet_snapshot_serves_alerts(tel):
    obs = FleetObserver()
    obs.ingest_liveness("w0", now=0.0)
    obs.evaluate(5.0)  # liveness fire
    snap = obs.fleet_snapshot(now=5.0)
    active = snap["alerts"]["active"]
    assert [(a["slo_id"], a["subject"], a["state"]) for a in active] == \
        [("heartbeat_liveness", "w0", "fire")]
    assert snap["alerts"]["recent"][-1]["state"] == "fire"
    assert obs.fleet_qos()["active_alerts"] == active


# -- N-way trace merge -------------------------------------------------------


def _instant(name, ts, **args):
    return {"name": name, "ph": "i", "ts": ts, "pid": 1, "tid": 1,
            "s": "t", "cat": "timeline", "args": args}


def _meta(pid, label):
    return {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": label}}


def test_merge_traces_three_way_fleet_alignment():
    # scheduler clock is the reference; workers run on shifted clocks and
    # share NO tick frames with it — alignment must come from the matched
    # fleet_wire send/completion pairs
    gap_us = 150.0      # true CKPT -> RESUME_OK downtime on a shared clock
    drop_delay = 50.0   # wA's loosest pair
    place_delay = 30.0  # each worker's tightest pair: the alignment error
    sched = {"traceEvents": [
        _meta(1, "scheduler"),
        _instant("fleet_wire", 1000.0, op="PLACE", lid="L1", track="scheduler"),
        _instant("fleet_wire", 2000.0, op="PLACE", lid="L2", track="scheduler"),
        _instant("fleet_wire", 5000.0, op="CKPT", lid="L1", track="scheduler"),
        _instant("fleet_wire", 5010.0, op="RESUME", lid="L1", track="scheduler"),
        _instant("fleet_wire", 9000.0, op="DROP", lid="L1", track="scheduler"),
        _instant("fleet_alert", 9500.0, slo="migration_downtime",
                 subject="L1", state="fire", track="scheduler"),
    ], "metadata": {"part": "sched"}}
    # worker A (migration source): clock +500000us ahead of the scheduler
    wa = {"traceEvents": [
        _meta(1, "worker:wA"),
        _instant("fleet_wire", 501000.0 + place_delay, op="PLACE_OK",
                 lid="L1", track="worker:wA"),
        _instant("fleet_wire", 509000.0 + drop_delay, op="DROP_RECV",
                 lid="L1", track="worker:wA"),
    ], "metadata": {"part": "wA"}}
    # worker B (migration destination): clock +100000us ahead; its own
    # PLACE_OK pins its clock to within place_delay, so the migration
    # completion keeps its true relative position
    wb = {"traceEvents": [
        _meta(1, "worker:wB"),
        _instant("fleet_wire", 102000.0 + place_delay, op="PLACE_OK",
                 lid="L2", track="worker:wB"),
        _instant("fleet_wire", 100000.0 + 5000.0 + gap_us, op="RESUME_OK",
                 lid="L1", track="worker:wB"),
    ], "metadata": {"part": "wB"}}

    merged = merge_traces(sched, wa, wb)
    assert validate_chrome_trace(merged) == []
    evs = merged["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert len(pids) == 3  # one lane per participant
    md = merged["metadata"]
    assert md["participants"] == 3 and len(md["parts"]) == 3
    assert md["aligned_frames"] == 0  # no tick frames — wire-pair path

    by_op = {(e["args"]["op"], e["args"]["lid"]): e for e in evs
             if e.get("ph") == "i" and e["name"] == "fleet_wire"}
    # completions landed after their sends on the merged clock, within
    # the alignment error bound (the smallest send->completion delay)
    for lid in ("L1", "L2"):
        assert by_op[("PLACE_OK", lid)]["ts"] >= by_op[("PLACE", lid)]["ts"]
    assert by_op[("RESUME_OK", "L1")]["ts"] >= by_op[("RESUME", "L1")]["ts"]
    assert by_op[("DROP_RECV", "L1")]["ts"] - by_op[("DROP", "L1")]["ts"] \
        <= drop_delay

    # the migration arrow: CKPT (scheduler pid) -> RESUME_OK (worker pid),
    # spanning the downtime gap up to the alignment error
    flows = [e for e in evs if e.get("cat") == "fleet_flow"]
    mig = [e for e in flows if e["name"] == "migration"]
    assert len(mig) == 2
    start = next(e for e in mig if e["ph"] == "s")
    end = next(e for e in mig if e["ph"] == "f")
    assert start["id"] == end["id"] and start["pid"] != end["pid"]
    span = end["ts"] - start["ts"]
    assert span > 0 and abs(span - gap_us) <= place_delay
    # both placements draw cross-pid PLACE->PLACE_OK arrows too
    place = [e for e in flows if e["name"] == "place"]
    assert len(place) == 4
    place_starts = {e["id"]: e for e in place if e["ph"] == "s"}
    for e in place:
        if e["ph"] == "f":
            assert e["pid"] != place_starts[e["id"]]["pid"]

    # the alert instant stays on the reference clock, inside the incident
    alert = next(e for e in evs if e.get("ph") == "i"
                 and e["name"] == "fleet_alert")
    assert alert["ts"] == 9500.0
    assert alert["pid"] == by_op[("CKPT", "L1")]["pid"]


def test_merge_traces_two_peer_metadata_still_carries_ab():
    a = {"traceEvents": [_instant("fleet_wire", 10.0, op="PLACE", lid="x",
                                  track="scheduler")],
         "metadata": {"who": "a"}}
    b = {"traceEvents": [_instant("fleet_wire", 20.0, op="PLACE_OK", lid="x",
                                  track="worker:w")],
         "metadata": {"who": "b"}}
    merged = merge_traces(a, b)
    md = merged["metadata"]
    assert md["a"] == {"who": "a"} and md["b"] == {"who": "b"}
    assert md["participants"] == 2
    assert validate_chrome_trace(merged) == []
