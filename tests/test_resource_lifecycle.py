"""Resource lifecycle under rollback — port of
/root/reference/tests/resource_lifecycle.rs:27-175: insert/remove a resource
mid-session while a checksummed always-present FrameLog witness proves the
sim stays deterministic; entity-reference remapping is exercised via a
resource holding a slot reference (the MapEntities analog — slot ids stay
valid across rollback by construction)."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu import App, GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.snapshot import (
    insert_resource,
    spawn,
)


def test_resource_insert_remove_mid_session():
    app = App(num_players=1, capacity=4, input_shape=(), input_dtype=np.uint8)
    app.rollback_resource("frame_log", jnp.int32(0), checksum=True)
    app.rollback_resource("score", jnp.int32(0), checksum=True, present=False)

    def step(world, ctx):
        # witness: always-present log advances every frame
        world = dataclasses.replace(
            world, res={**world.res, "frame_log": world.res["frame_log"] + 1}
        )
        # score exists only for frames 5..10: insert/remove driven by sim time
        in_window = (ctx.frame >= 5) & (ctx.frame < 10)
        present = world.res_present["score"]
        world = dataclasses.replace(
            world,
            res={**world.res, "score": jnp.where(
                in_window, world.res["score"] + 10, world.res["score"]
            )},
            res_present={**world.res_present, "score": in_window},
        )
        return world

    app.set_step(step)
    session = SyncTestSession(num_players=1, input_shape=(),
                              input_dtype=np.uint8, check_distance=3)
    mismatches = []
    runner = GgrsRunner(app, session, on_mismatch=mismatches.append)
    for _ in range(20):
        runner.tick()
    assert mismatches == []
    assert int(runner.world.res["frame_log"]) == 20
    assert not bool(runner.world.res_present["score"])  # removed after frame 10


def test_resource_with_entity_reference_survives_rollback():
    # the MapEntities analog: a resource holds a slot reference; slots are
    # stable across snapshot restore, so the reference stays valid
    # (cf. /root/reference/src/snapshot/resource_map.rs + the AtomicBool
    # was-called probe at tests/resource_lifecycle.rs:128-175)
    app = App(num_players=1, capacity=8, input_shape=(), input_dtype=np.uint8)
    app.rollback_component("hp", (), jnp.int32, checksum=True)
    app.rollback_resource("target_slot", jnp.int32(-1), checksum=True)

    def step(world, ctx):
        # damage whatever the resource points at
        t = world.res["target_slot"]
        valid = t >= 0
        hp = world.comps["hp"]
        hp = jnp.where(valid, hp.at[jnp.clip(t, 0, 7)].add(-1), hp)
        return dataclasses.replace(world, comps={"hp": hp})

    def setup(world):
        world, s0 = spawn(app.reg, world, {"hp": 100})
        world, s1 = spawn(app.reg, world, {"hp": 100})
        world = insert_resource(app.reg, world, "target_slot", s1)
        return world

    app.set_step(step)
    app.set_setup(setup)
    session = SyncTestSession(num_players=1, input_shape=(),
                              input_dtype=np.uint8, check_distance=4)
    mismatches = []
    runner = GgrsRunner(app, session, on_mismatch=mismatches.append)
    for _ in range(10):
        runner.tick()
    assert mismatches == []
    assert int(runner.world.comps["hp"][1]) == 90  # referenced entity damaged
    assert int(runner.world.comps["hp"][0]) == 100  # other untouched
