"""Deterministic time — port of /root/reference/tests/time.rs:18-49 and the
GgrsTime semantics (src/time.rs:63-87): simulation time = frame / fps,
identical under resimulation, restarting from zero on session restart."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu import App, GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.snapshot import active_mask, spawn


def make_app(fps=60):
    app = App(num_players=1, capacity=4, fps=fps, input_shape=(),
              input_dtype=np.uint8)
    app.rollback_component("t", (), jnp.float32, checksum=False)
    app.rollback_component("dt_sum", (), jnp.float32, checksum=False)
    app.rollback_component("n", (), jnp.int32, checksum=True)

    def step(world, ctx):
        m = active_mask(world)
        return dataclasses.replace(
            world,
            comps={
                "t": jnp.where(m, ctx.time_seconds, world.comps["t"]),
                "dt_sum": jnp.where(m, world.comps["dt_sum"] + ctx.delta_seconds,
                                    world.comps["dt_sum"]),
                "n": jnp.where(m, world.comps["n"] + 1, world.comps["n"]),
            },
        )

    def setup(world):
        world, _ = spawn(app.reg, world, {})
        return world

    app.set_step(step)
    app.set_setup(setup)
    return app


def session():
    return SyncTestSession(num_players=1, input_shape=(), input_dtype=np.uint8,
                           check_distance=2)


def test_ggrs_time_is_frame_over_fps():
    app = make_app(fps=60)
    mismatches = []
    runner = GgrsRunner(app, session(), on_mismatch=mismatches.append)
    for _ in range(30):
        runner.tick()
    assert mismatches == []
    assert abs(float(runner.world.comps["t"][0]) - 30 / 60) < 1e-6
    assert abs(float(runner.world.comps["dt_sum"][0]) - 30 / 60) < 1e-4


def test_time_restarts_with_session():
    # session restart: time rebuilds from zero (src/time.rs:79-86 behavior)
    app = make_app()
    runner = GgrsRunner(app, session())
    for _ in range(10):
        runner.tick()
    t_before = float(runner.world.comps["t"][0])
    assert t_before > 0.1
    runner.set_session(session())
    runner.world = app.init_state()
    runner._world_checksum = app.checksum_fn(runner.world)
    for _ in range(3):
        runner.tick()
    assert abs(float(runner.world.comps["t"][0]) - 3 / 60) < 1e-6


def test_accumulator_respects_fps():
    app = make_app(fps=30)
    runner = GgrsRunner(app, session())
    runner.update(1.0)  # one second -> 30 frames at 30 fps
    assert runner.frame == 30
