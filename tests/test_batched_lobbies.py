"""Many-worlds: M lobbies batched into one dispatch must be bit-identical
to M independent single-lobby runs (vmap lane independence), including
per-lobby spawn/despawn and independent frame clocks."""

import jax
import numpy as np

from bevy_ggrs_tpu.models import particles, stress
from bevy_ggrs_tpu.ops.batch import (
    make_batched_resim_fn,
    stack_worlds,
    unstack_world,
)
from bevy_ggrs_tpu.session.events import InputStatus


def _inputs(rng, m, k, players):
    return rng.integers(0, 8, size=(m, k, players)).astype(np.uint8)


def test_batched_lobbies_bit_identical_to_independent_runs():
    M, K, P = 4, 6, 2
    app = stress.make_app(256, capacity=256)
    rng = np.random.default_rng(11)
    inputs = _inputs(rng, M, K, P)
    status = np.full((M, K, P), InputStatus.CONFIRMED, np.int8)
    # distinct per-lobby clocks: lobbies are not in lockstep
    starts = np.array([0, 7, 100, 1000], np.int32)

    worlds = [app.init_state() for _ in range(M)]
    batched = stack_worlds(worlds)
    bfn = make_batched_resim_fn(app)
    finals_b, stacked_b, checks_b = bfn(batched, inputs, status, starts)

    for b in range(M):
        one, _, checks = app.resim_fn(
            worlds[b], inputs[b], status[b], int(starts[b])
        )
        assert np.array_equal(np.asarray(checks), np.asarray(checks_b)[b]), (
            f"lobby {b} diverged from its independent run"
        )
        solo = unstack_world(finals_b, b)
        for a, c in zip(jax.tree.leaves(solo), jax.tree.leaves(one)):
            assert np.array_equal(np.asarray(a), np.asarray(c))


def test_batched_lobbies_with_spawns():
    # particles spawn entities every frame from a rollback RNG resource —
    # slot allocation must stay per-lobby deterministic under vmap
    M, K = 3, 4
    app = particles.make_app(rate=4, ttl=8, capacity=128)
    rng = np.random.default_rng(3)
    inputs = _inputs(rng, M, K, 2)
    status = np.full((M, K, 2), InputStatus.CONFIRMED, np.int8)
    starts = np.array([0, 5, 31], np.int32)

    worlds = [app.init_state() for _ in range(M)]
    bfn = make_batched_resim_fn(app)
    _, _, checks_b = bfn(stack_worlds(worlds), inputs, status, starts)
    for b in range(M):
        _, _, checks = app.resim_fn(
            worlds[b], inputs[b], status[b], int(starts[b])
        )
        assert np.array_equal(np.asarray(checks), np.asarray(checks_b)[b])
