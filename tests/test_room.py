"""Room matchmaking transport (the matchbox/WebRTC analog): peers join a
room on a signaling server, learn each other's peer ids, and play a full
P2P session addressed BY PEER ID — direct (STUN-style) and relayed
(TURN-style) data planes, roster pruning, and the deterministic handle
assignment convention.  Reference contract: /root/reference/README.md:79
(matchbox pairing)."""

import time

import pytest

from bevy_ggrs_tpu import (
    GgrsRunner,
    PlayerType,
    RoomServer,
    RoomSocket,
    SessionBuilder,
    SessionState,
    assign_handles,
    wait_for_players,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


def _room_pair(mode, room="game-1"):
    server = RoomServer(host="127.0.0.1")
    addr = server.local_addr
    socks = [
        RoomSocket(addr, room, peer_id=f"peer-{i}", mode=mode,
                   host="127.0.0.1")
        for i in range(2)
    ]
    for s in socks:
        wait_for_players(s, 2, timeout_s=5.0, server=server)
    return server, socks


def test_join_roster_and_handle_assignment():
    server, socks = _room_pair("direct")
    for s in socks:
        assert s.players() == ["peer-0", "peer-1"]
        # every peer derives the identical handle map with no coordination
        assert assign_handles(s) == {0: "peer-0", 1: "peer-1"}
    server.close()
    for s in socks:
        s.close()


def test_datagrams_by_peer_id_direct_and_relay():
    for mode in ("direct", "relay"):
        server, socks = _room_pair(mode, room=f"dgram-{mode}")
        socks[0].send_to(b"hello", "peer-1")
        got = []
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and not got:
            server.poll()
            got = socks[1].receive_all()
            time.sleep(0.002)
        assert got == [("peer-0", b"hello")], (mode, got)
        # unknown destination: dropped silently (UDP semantics)
        socks[0].send_to(b"void", "peer-9")
        server.poll()
        server.close()
        for s in socks:
            s.close()


def test_member_timeout_prunes_roster():
    # timeout intentionally SHORTER than the ping interval: the live peer
    # also gets pruned at first, and must self-heal via re-JOIN while the
    # silent one stays gone
    server = RoomServer(host="127.0.0.1", member_timeout_s=0.3)
    addr = server.local_addr
    a = RoomSocket(addr, "prune", peer_id="alive", host="127.0.0.1")
    b = RoomSocket(addr, "prune", peer_id="doomed", host="127.0.0.1")
    wait_for_players(a, 2, timeout_s=5.0, server=server)
    # b goes silent; a keeps pinging
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        server.poll()
        a.receive_all()
        if a.players() == ["alive"]:
            break
        time.sleep(0.02)
    assert a.players() == ["alive"]
    server.close()
    a.close()
    b.close()


@pytest.mark.parametrize("mode", ["direct", "relay"])
def test_p2p_session_over_room_socket(mode):
    """The full drop-in: SessionBuilder players addressed by peer id over a
    RoomSocket; handshake, play, rollback-capable agreement."""
    server, socks = _room_pair(mode, room=f"p2p-{mode}")
    runners = []
    for i, sock in enumerate(socks):
        handles = assign_handles(sock)
        app = box_game.make_app(num_players=2)
        b = SessionBuilder.for_app(app).with_input_delay(1)
        for h, peer in handles.items():
            if peer == sock.peer_id:
                b.add_player(PlayerType.LOCAL, h)
            else:
                b.add_player(PlayerType.REMOTE, h, peer)
        session = b.start_p2p_session(sock)

        def read_inputs(hs, i=i):
            key = {0: "right", 1: "down"}[i]
            return {h: box_game.keys_to_input(**{key: True}) for h in hs}

        runners.append(GgrsRunner(app, session, read_inputs=read_inputs))

    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        server.poll()
        for r in runners:
            r.update(0.0)
        if all(
            r.session.current_state() == SessionState.RUNNING for r in runners
        ):
            break
        time.sleep(0.002)
    assert all(
        r.session.current_state() == SessionState.RUNNING for r in runners
    )

    for _ in range(120):
        server.poll()
        for r in runners:
            r.update(DT)
    assert all(r.frame >= 100 for r in runners)
    shared = sorted(set(runners[0].ring.frames()) & set(runners[1].ring.frames()))
    if not shared:
        for _ in range(3):
            server.poll()
            for r in runners:
                r.update(DT)
        shared = sorted(
            set(runners[0].ring.frames()) & set(runners[1].ring.frames())
        )
    assert shared
    f = shared[-1]
    assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
        runners[1].ring.peek(f)[1]
    )
    # remote input actually arrived (player moved on the OTHER peer's world)
    assert float(runners[0].world.comps["pos"][1, 1]) > 0.5
    server.close()
    for s in socks:
        s.close()


def test_room_socket_fuzz_resilience():
    """Garbage at both the server and the socket must never crash or
    corrupt the roster (untrusted UDP input, same posture as the session
    protocol fuzz test)."""
    import random
    import socket as so

    server, socks = _room_pair("direct", room="fuzz")
    fz = so.socket(so.AF_INET, so.SOCK_DGRAM)
    fz.bind(("127.0.0.1", 0))
    rng = random.Random(7)
    targets = [server.local_addr, socks[0].local_addr]
    for i in range(2000):
        n = rng.randrange(0, 128)
        buf = bytes(rng.randrange(256) for _ in range(n))
        if rng.random() < 0.5 and n >= 3:
            buf = b"\xa7\x52" + buf[2:]  # valid magic, evil body
        fz.sendto(buf, targets[i % 2])
        if i % 100 == 0:
            server.poll()
            socks[0].receive_all()
    server.poll()
    for s in socks:
        s.receive_all()
    assert socks[0].players() == ["peer-0", "peer-1"]
    # data plane still works after the storm
    socks[0].send_to(b"after", "peer-1")
    got = []
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline and not got:
        server.poll()
        got = socks[1].receive_all()
        time.sleep(0.002)
    assert got == [("peer-0", b"after")]
    fz.close()
    server.close()
    for s in socks:
        s.close()


def test_room_member_cap_and_socket_move():
    """Server hardening: a room never exceeds MAX_ROOM_MEMBERS (the roster
    count is one wire byte — overflow used to crash the server), and a
    socket re-JOINing a different room MOVES: its old membership dies
    immediately so pruning it can never orphan the live registration."""
    import socket as so
    import struct as st

    from bevy_ggrs_tpu.session.room import (
        MAX_ROOM_MEMBERS,
        ROOM_MAGIC,
        _HDR,
        _JOIN,
        _pack_str,
    )

    server = RoomServer(host="127.0.0.1")
    addr = server.local_addr
    flood = so.socket(so.AF_INET, so.SOCK_DGRAM)
    flood.bind(("127.0.0.1", 0))
    for i in range(MAX_ROOM_MEMBERS + 200):
        pkt = _HDR.pack(ROOM_MAGIC, _JOIN) + _pack_str("big") + _pack_str(f"p{i}")
        flood.sendto(pkt, addr)
        if i % 50 == 0:
            server.poll()
    server.poll()  # must not raise (the old crash was bytes([256]))
    assert len(server.rooms["big"]) <= MAX_ROOM_MEMBERS
    flood.close()

    a = RoomSocket(addr, "first", peer_id="mover", host="127.0.0.1")
    wait_for_players(a, 1, timeout_s=5.0, server=server)
    assert "first" in server.rooms
    # same socket joins another room: membership moves, old room empties
    a.room = "second"
    a._join()
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline:
        server.poll()
        a.receive_all()
        if "first" not in server.rooms and "second" in server.rooms:
            break
        time.sleep(0.01)
    assert "first" not in server.rooms
    assert sorted(server.rooms["second"]) == ["mover"]
    server.close()
    a.close()


def test_forged_control_packets_are_ignored():
    """Source-address validation: rosters/relays must come from the server,
    direct data from the roster address — a forged ROSTER would otherwise
    hijack the data plane wholesale."""
    import socket as so
    import struct as st

    from bevy_ggrs_tpu.session.room import ROOM_MAGIC, _HDR, _pack_str

    server, socks = _room_pair("direct", room="spoof")
    atk = so.socket(so.AF_INET, so.SOCK_DGRAM)
    atk.bind(("127.0.0.1", 0))
    # forged roster pointing peer-1 at the attacker
    evil = (_HDR.pack(ROOM_MAGIC, 2) + _pack_str("spoof") + bytes([1])
            + _pack_str("peer-1") + _pack_str("127.0.0.1")
            + st.pack("<H", atk.getsockname()[1]))
    atk.sendto(evil, socks[0].local_addr)
    time.sleep(0.05)
    before = dict(socks[0].roster)
    socks[0].receive_all()
    assert socks[0].roster == before  # forged roster rejected
    # forged direct DATA claiming to be peer-1 from the attacker's addr
    fake = _HDR.pack(ROOM_MAGIC, 3) + _pack_str("peer-1") + b"evil"
    atk.sendto(fake, socks[0].local_addr)
    time.sleep(0.05)
    got = socks[0].receive_all()
    assert ("peer-1", b"evil") not in got
    # forged FWD not from the server: also dropped
    fwd = _HDR.pack(ROOM_MAGIC, 5) + _pack_str("peer-1") + b"evil2"
    atk.sendto(fwd, socks[0].local_addr)
    time.sleep(0.05)
    got = socks[0].receive_all()
    assert all(payload != b"evil2" for _, payload in got)
    # the legit plane still works
    socks[1].send_to(b"legit", "peer-0")
    got = []
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline and not got:
        server.poll()
        got = socks[0].receive_all()
        time.sleep(0.002)
    assert got == [("peer-1", b"legit")]
    atk.close()
    server.close()
    for s in socks:
        s.close()


def test_move_to_full_room_keeps_old_membership():
    """A JOIN rejected for capacity must not deregister the mover from its
    previous room."""
    from bevy_ggrs_tpu.session import room as room_mod

    old_cap = room_mod.MAX_ROOM_MEMBERS
    room_mod.MAX_ROOM_MEMBERS = 1
    try:
        server = RoomServer(host="127.0.0.1")
        addr = server.local_addr
        a = RoomSocket(addr, "origin", peer_id="mover", host="127.0.0.1")
        blocker = RoomSocket(addr, "fullroom", peer_id="resident",
                             host="127.0.0.1")
        wait_for_players(a, 1, timeout_s=5.0, server=server)
        wait_for_players(blocker, 1, timeout_s=5.0, server=server)
        a.room = "fullroom"
        a._join()
        for _ in range(20):
            server.poll()
            time.sleep(0.005)
        assert sorted(server.rooms["fullroom"]) == ["resident"]
        assert sorted(server.rooms["origin"]) == ["mover"]  # still seated
        server.close()
        a.close()
        blocker.close()
    finally:
        room_mod.MAX_ROOM_MEMBERS = old_cap


def test_join_token_matching_clients_pair_up():
    server = RoomServer(host="127.0.0.1", join_token="s3cret")
    addr = server.local_addr
    socks = [
        RoomSocket(addr, "locked", peer_id=f"peer-{i}", host="127.0.0.1",
                   join_token="s3cret")
        for i in range(2)
    ]
    for s in socks:
        assert wait_for_players(s, 2, timeout_s=5.0, server=server) == [
            "peer-0", "peer-1"
        ]
    server.close()
    for s in socks:
        s.close()


def test_join_token_mismatch_rejected_with_reason():
    server = RoomServer(host="127.0.0.1", join_token="s3cret")
    addr = server.local_addr
    s = RoomSocket(addr, "locked", peer_id="intruder", host="127.0.0.1",
                   join_token="wrong")
    with pytest.raises(PermissionError, match="bad join token"):
        wait_for_players(s, 1, timeout_s=5.0, server=server)
    assert server.rooms.get("locked") in (None, {})
    server.close()
    s.close()


def test_join_token_absent_client_rejected_by_token_server():
    # a pre-token client sends no trailing token field; a token-requiring
    # server must still refuse it (empty != configured token)
    server = RoomServer(host="127.0.0.1", join_token="s3cret")
    addr = server.local_addr
    s = RoomSocket(addr, "locked", peer_id="legacy", host="127.0.0.1")
    with pytest.raises(PermissionError, match="bad join token"):
        wait_for_players(s, 1, timeout_s=5.0, server=server)
    server.close()
    s.close()


def test_token_client_compatible_with_tokenless_server():
    # forward compat: the trailing token field is ignored by servers that
    # never configured one
    server = RoomServer(host="127.0.0.1")
    addr = server.local_addr
    s = RoomSocket(addr, "open", peer_id="newcli", host="127.0.0.1",
                   join_token="s3cret")
    assert wait_for_players(s, 1, timeout_s=5.0, server=server) == ["newcli"]
    server.close()
    s.close()
