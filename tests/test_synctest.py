"""SyncTest integration tests — the workhorse layer (SURVEY §4.3): a full
app + session + driver, continuously re-simulating ``check_distance`` frames
every tick so rollback correctness is exercised by construction.  Ports the
reference patterns: value==frame-count invariant, negative-control injected
non-determinism (tests/synctest.rs:83-125), despawn-across-rollback (:59-75),
snapshot pruning after confirm (:129-153)."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from bevy_ggrs_tpu import App, GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.snapshot import active_count, active_mask, despawn_where, spawn


def make_counter_app(despawn_at=None, retention=8):
    # retention: despawn-retirement horizon; slots free at frame despawn+retention
    app = App(num_players=1, capacity=4, input_shape=(), input_dtype=np.uint8,
              retention=retention)
    app.rollback_component("counter", (), jnp.int32, checksum=True)

    def step(world, ctx):
        mask = active_mask(world) & world.has["counter"]
        cnt = jnp.where(mask, world.comps["counter"] + 1, world.comps["counter"])
        world = dataclasses.replace(world, comps={**world.comps, "counter": cnt})
        if despawn_at is not None:
            kill = mask & (ctx.frame == despawn_at)
            world = despawn_where(app.reg, world, kill, ctx.frame)
        return world

    def setup(world):
        world, _ = spawn(app.reg, world, {"counter": 0})
        return world

    app.set_step(step)
    app.set_setup(setup)
    return app


def make_runner(app, check_distance=2, **kw):
    session = SyncTestSession(
        num_players=app.num_players,
        input_shape=app.input_shape,
        input_dtype=app.input_dtype,
        check_distance=check_distance,
    )
    mismatches = []
    runner = GgrsRunner(
        app, session, on_mismatch=mismatches.append, **kw
    )
    return runner, mismatches


@pytest.mark.parametrize("check_distance", [0, 2, 7])
def test_counter_equals_frame_count(check_distance):
    app = make_counter_app()
    runner, mismatches = make_runner(app, check_distance)
    for _ in range(20):
        runner.tick()
    assert mismatches == []
    assert runner.frame == 20
    assert int(runner.world.comps["counter"][0]) == 20


def test_negative_control_detects_injected_nondeterminism():
    # the reference proves its detector fires by injecting non-determinism
    # (tests/synctest.rs:83-125); here: poke checksummed state behind the
    # session's back mid-run
    app = make_counter_app()
    runner, mismatches = make_runner(app, check_distance=3)
    for _ in range(10):
        runner.tick()
    assert mismatches == []
    runner.world = dataclasses.replace(
        runner.world,
        comps={**runner.world.comps, "counter": runner.world.comps["counter"] + 1000},
    )
    runner._world_checksum = app.checksum_fn(runner.world)
    for _ in range(6):
        runner.tick()
    assert len(mismatches) >= 1


def test_despawn_across_rollback():
    app = make_counter_app(despawn_at=10, retention=8)
    runner, mismatches = make_runner(app, check_distance=3)
    for _ in range(15):
        runner.tick()
    # entity disabled immediately, still allocated within the retention window
    assert int(active_count(runner.world)) == 0
    for _ in range(10):
        runner.tick()
    assert mismatches == []
    # past frame despawn_at + retention -> slot hard-freed
    assert not bool(runner.world.alive[0])


def test_snapshot_pruning_after_confirm():
    app = make_counter_app()
    runner, _ = make_runner(app, check_distance=2)
    for _ in range(30):
        runner.tick()
    assert len(runner.ring) <= runner.ring.depth
    # everything older than the confirmed frame was pruned
    assert all(f >= runner.confirmed for f in runner.ring.frames())


def test_non_checksummed_component_still_rolls_back():
    app = App(num_players=1, capacity=4, input_shape=(), input_dtype=np.uint8)
    app.rollback_component("cs", (), jnp.int32, checksum=True)
    app.rollback_component("plain", (), jnp.int32, checksum=False)

    def step(world, ctx):
        m = active_mask(world)
        return dataclasses.replace(
            world,
            comps={
                "cs": jnp.where(m, world.comps["cs"] + 1, world.comps["cs"]),
                "plain": jnp.where(m, world.comps["plain"] + 2, world.comps["plain"]),
            },
        )

    def setup(world):
        world, _ = spawn(app.reg, world, {"cs": 0, "plain": 0})
        return world

    app.set_step(step)
    app.set_setup(setup)
    runner, mismatches = make_runner(app, check_distance=2)
    for _ in range(12):
        runner.tick()
    assert mismatches == []
    assert int(runner.world.comps["plain"][0]) == 24


def test_box_game_synctest_moves_player():
    app = box_game.make_app(num_players=2)

    def read_inputs(handles):
        return {h: box_game.keys_to_input(right=(h == 0)) for h in handles}

    session = SyncTestSession(
        num_players=2, input_shape=(), input_dtype=np.uint8, check_distance=2
    )
    mismatches = []
    runner = GgrsRunner(
        app, session, read_inputs=read_inputs, on_mismatch=mismatches.append
    )
    x0 = float(runner.world.comps["pos"][0, 0])
    for _ in range(30):
        runner.tick()
    assert mismatches == []
    assert float(runner.world.comps["pos"][0, 0]) > x0  # player 0 moved right
    # player 1 (no input) only drifts by friction: vel stays 0
    assert float(jnp.abs(runner.world.comps["vel"][1]).max()) == 0.0


def test_input_delay_shifts_effect():
    app = box_game.make_app(num_players=1, capacity=4)
    session = SyncTestSession(
        num_players=1, input_shape=(), input_dtype=np.uint8,
        check_distance=0, input_delay=5,
    )
    runner = GgrsRunner(
        app,
        session,
        read_inputs=lambda hs: {h: box_game.keys_to_input(right=True) for h in hs},
    )
    for _ in range(3):
        runner.tick()
    # inputs delayed by 5 frames: nothing has moved yet
    assert float(jnp.abs(runner.world.comps["vel"][0]).max()) == 0.0
    for _ in range(10):
        runner.tick()
    assert float(runner.world.comps["vel"][0, 0]) > 0.0


def test_accumulator_runs_multiple_frames_per_update():
    app = make_counter_app()
    runner, _ = make_runner(app, check_distance=1)
    runner.update(5.5 / 60.0)  # one big host tick -> 5 ggrs frames
    assert runner.frame == 5


def test_session_restart_resets_driver():
    app = make_counter_app()
    runner, _ = make_runner(app, check_distance=2)
    for _ in range(10):
        runner.tick()
    assert runner.frame == 10
    runner.set_session(
        SyncTestSession(num_players=1, input_shape=(), input_dtype=np.uint8,
                        check_distance=2)
    )
    assert runner.frame == 0
    assert len(runner.ring) == 0
    for _ in range(4):
        runner.tick()
    assert runner.frame == 4


# -- deferred comparison (compare_interval > 1; the accelerator default) ----
# The CPU auto default is 1 (prompt), so these pin the deferred path
# explicitly: batching, the widened cell GC horizon, check_now, and the
# runner's end-of-run / session-swap flush.


def _deferred_runner(interval, check_distance=3):
    app = make_counter_app()
    session = SyncTestSession(
        num_players=1, input_shape=(), input_dtype=np.uint8,
        check_distance=check_distance, compare_interval=interval,
    )
    mismatches = []
    runner = GgrsRunner(app, session, on_mismatch=mismatches.append)
    return runner, session, mismatches


def _inject_divergence(runner):
    runner.world = dataclasses.replace(
        runner.world,
        comps={**runner.world.comps,
               "counter": runner.world.comps["counter"] + 1000},
    )
    runner._world_checksum = runner.app.checksum_fn(runner.world)


def test_deferred_compare_batches_and_still_detects():
    runner, session, mismatches = _deferred_runner(interval=8)
    for _ in range(10):
        runner.tick()
    assert mismatches == []
    _inject_divergence(runner)
    bad_frame = runner.frame
    # detection is deferred but must land within one compare interval, and
    # the widened cell GC horizon must keep the frames alive until compared
    for i in range(session.compare_interval() + session.check_distance + 2):
        runner.tick()
        if mismatches:
            break
    assert mismatches, "deferred comparison never fired"
    assert any(f >= bad_frame - session.check_distance
               for f in mismatches[0].mismatched_frames)


def test_check_now_forces_pending_comparisons():
    runner, session, mismatches = _deferred_runner(interval=64)
    for _ in range(10):
        runner.tick()
    _inject_divergence(runner)
    for _ in range(session.check_distance + 1):
        runner.tick()  # divergent resim saves recorded, not yet compared
    assert mismatches == []  # interval=64: nothing compared yet
    with pytest.raises(Exception):
        session.check_now()


def test_runner_finish_flushes_deferred_comparisons():
    runner, session, mismatches = _deferred_runner(interval=64)
    for _ in range(10):
        runner.tick()
    _inject_divergence(runner)
    for _ in range(session.check_distance + 1):
        runner.tick()
    assert mismatches == []
    runner.finish()  # end-of-run flush routes to on_mismatch
    assert mismatches


def test_session_swap_flushes_deferred_comparisons():
    runner, session, mismatches = _deferred_runner(interval=64)
    for _ in range(10):
        runner.tick()
    _inject_divergence(runner)
    for _ in range(session.check_distance + 1):
        runner.tick()
    assert mismatches == []
    runner.set_session(None)  # replacing the session must not drop checks
    assert mismatches
