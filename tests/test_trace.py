"""Frame-lifecycle tracing: Chrome-trace export, cross-peer flow
correlation, and device-memory accounting (telemetry/trace.py,
telemetry/devmem.py)."""

import gc
import json
import time

import pytest

from bevy_ggrs_tpu import (
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
    telemetry,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.telemetry import devmem

DT = 1.0 / 60.0


@pytest.fixture(autouse=True)
def _telemetry():
    telemetry.reset()
    telemetry.enable()
    telemetry.configure_flight(enabled=True)
    yield
    telemetry.configure_flight(enabled=True)  # module default
    telemetry.disable()
    telemetry.reset()


def _p2p_pair(latency_hops=0, seed=1, delay=1):
    net = ChannelNetwork(latency_hops=latency_hops, seed=seed)
    socks = [net.endpoint("peer0"), net.endpoint("peer1")]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(delay)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"peer{1 - i}")
        )
        session = b.start_p2p_session(socks[i])
        runners.append(
            GgrsRunner(app, session, read_inputs=lambda hs: {
                h: box_game.keys_to_input() for h in hs
            })
        )
    return net, runners


def _sync(net, runners, ticks=300):
    for _ in range(ticks):
        net.deliver()
        for r in runners:
            r.update(0.0)
    assert all(
        r.session.current_state() == SessionState.RUNNING for r in runners
    )


def _run_flipping(net, runners, ticks=120):
    """Induced-late-input workload: flipping inputs under link latency
    force attributable mispredictions (the test_netstats recipe)."""
    flip = [0]

    def read_inputs(handles):
        flip[0] += 1
        on = (flip[0] // 7) % 2 == 0
        return {h: box_game.keys_to_input(right=on) for h in handles}

    for r in runners:
        r.read_inputs = read_inputs
    for _ in range(ticks):
        net.deliver()
        for r in runners:
            r.update(DT)


# -- flow correlation (the acceptance-criteria scenario) ---------------------


def test_p2p_flow_links_rollback_to_blamed_input_send():
    net, runners = _p2p_pair(latency_hops=3)
    _sync(net, runners)
    telemetry.timeline().clear()
    telemetry.flight_recorder().clear()
    _run_flipping(net, runners)

    trace = telemetry.chrome_trace()
    assert telemetry.validate_chrome_trace(trace) == []
    links = telemetry.flows(trace)
    assert links, "latency + flipping inputs must produce flow arrows"

    snap = telemetry.registry().snapshot()
    causes = snap["rollback_cause_total"]["series"]
    total = sum(snap["rollbacks_total"]["series"].values())
    assert total > 0 and sum(causes.values()) == total
    for fl in links:
        send, rb = fl["send"], fl["rollback"]
        # the arrow points from the send of exactly the blamed frame...
        assert send["frame"] == rb["to_frame"]
        # ...by the peer owning the blamed handle...
        assert rb["handle"] in send["handles"]
        # ...with the same lateness the attribution counters saw
        assert rb["lateness"] >= 1
        assert f"handle={rb['handle']}" in causes
    # flows anchor on real rollbacks: never more arrows than rollbacks
    assert len(links) <= total
    # and the lateness histogram rode the same labels
    lat = snap["input_lateness_frames"]["series"]
    assert sum(v["count"] for v in lat.values()) == total


def test_flow_pairs_validate_and_stamp_ids():
    net, runners = _p2p_pair(latency_hops=3)
    _sync(net, runners)
    telemetry.timeline().clear()
    telemetry.flight_recorder().clear()
    _run_flipping(net, runners, ticks=80)
    trace = telemetry.chrome_trace()
    evs = trace["traceEvents"]
    starts = [e for e in evs if e.get("ph") == "s"]
    ends = [e for e in evs if e.get("ph") == "f"]
    assert len(starts) == len(ends) == len(telemetry.flows(trace))
    for e in ends:
        assert e["bp"] == "e"  # bind to enclosing slice (Perfetto arrows)
    assert telemetry.validate_chrome_trace(trace) == []


# -- merged cross-peer traces -------------------------------------------------


def _fake_report(pid_epoch, *, rollback=None, input_send=None, addr="peer"):
    """A minimal forensics report: tick flight entries frames 5..10 on a
    private clock epoch, plus optional rollback/input_send events."""
    flight = [
        {"kind": "tick", "frame": f, "wall_ms": 1.0,
         "t": pid_epoch + f * 0.016, "seq": f}
        for f in range(5, 11)
    ]
    timeline = []
    if rollback is not None:
        flight.append(dict(rollback, kind="rollback",
                           t=pid_epoch + 10 * 0.016, seq=99))
    if input_send is not None:
        timeline.append(dict(input_send, kind="input_send",
                             t=pid_epoch + input_send["frame"] * 0.016,
                             seq=50))
    return {"kind": "p2p_desync", "addr": addr,
            "flight_record": flight, "timeline_tail": timeline}


def test_merge_report_traces_cross_peer_flow_and_clock_alignment():
    victim = _fake_report(
        1000.0, addr="victim",
        rollback={"to_frame": 7, "from_frame": 10, "depth": 3,
                  "handle": 1, "lateness": 2, "cause_kind": "misprediction"},
    )
    blamed = _fake_report(
        5000.0, addr="blamed",
        input_send={"frame": 7, "handles": [1], "size": 8},
    )
    merged = telemetry.merge_report_traces(victim, blamed)
    assert telemetry.validate_chrome_trace(merged) == []
    assert merged["metadata"]["merged"] is True
    assert merged["metadata"]["aligned_frames"] == 6  # frames 5..10

    links = telemetry.flows(merged)
    assert len(links) == 1
    fl = links[0]
    assert fl["rollback"]["handle"] == 1
    assert fl["rollback"]["lateness"] == 2
    assert fl["send"]["frame"] == fl["rollback"]["to_frame"] == 7

    # the arrow crosses processes, and b's clock was shifted onto a's
    evs = merged["traceEvents"]
    pids = {e.get("pid") for e in evs if e.get("ph") == "i"}
    assert len(pids) == 2
    by_frame = {}
    for e in evs:
        if e.get("ph") == "X" and e.get("name") == "tick":
            by_frame.setdefault(e["args"]["frame"], []).append(e)
    for f, ticks in by_frame.items():
        assert len(ticks) == 2
        assert abs(ticks[0]["ts"] - ticks[1]["ts"]) < 1.0  # clock-aligned us


def test_merge_requires_cross_pid_no_self_blame():
    # a single report merged with an empty one: the victim's own
    # input_send must NOT pair with its own rollback in the merged view
    solo = _fake_report(
        0.0,
        rollback={"to_frame": 7, "handle": 1, "lateness": 2},
        input_send={"frame": 7, "handles": [1], "size": 8},
    )
    merged = telemetry.merge_report_traces(solo, _fake_report(50.0))
    assert telemetry.flows(merged) == []
    # ...but the in-process single-peer trace does pair them (local view)
    single = telemetry.trace_from_report(solo)
    assert len(telemetry.flows(single)) == 1


# -- device-memory accounting -------------------------------------------------


def test_devmem_reconciles_with_snapshot_ring():
    net, runners = _p2p_pair()
    _sync(net, runners)
    for _ in range(30):
        net.deliver()
        for r in runners:
            r.update(DT)
    r = runners[0]
    owner = r._devmem_tag + "/snapshot_ring"
    snap = devmem.snapshot()
    assert r._world_nbytes > 0
    assert snap[owner] == len(r.ring.frames()) * r._world_nbytes
    # the gauge mirrors the registry row exactly
    g = telemetry.registry().gauge("device_resident_bytes", "")
    assert g.value(owner=owner) == snap[owner]
    # summary carries the live-residency line
    s = telemetry.summary()
    assert s["device_resident_bytes"][owner] == snap[owner]
    assert s["device_resident_total_bytes"] == sum(snap.values())
    # census: registered bytes are a subset of live jax allocations
    c = devmem.census()
    assert c["registered_bytes"] == sum(snap.values())
    if c["live_bytes"] is not None:
        assert c["live_bytes"] >= snap[owner]
        assert c["unregistered_bytes"] >= 0


def test_devmem_rows_die_with_the_runner():
    net, runners = _p2p_pair()
    _sync(net, runners)
    tag = runners[0]._devmem_tag
    assert any(o.startswith(tag + "/") for o in devmem.snapshot())
    del runners
    gc.collect()
    assert not any(o.startswith(tag + "/") for o in devmem.snapshot())


def test_devmem_note_works_with_telemetry_off():
    telemetry.disable()
    devmem.note("offline/buf", 4096)
    assert devmem.snapshot()["offline/buf"] == 4096
    assert devmem.total() == 4096
    # no gauge family was created while disabled
    assert "device_resident_bytes" not in telemetry.registry().snapshot()
    # re-enable: the next note lands on the gauge (generation-checked)
    telemetry.enable()
    devmem.note("offline/buf", 8192)
    g = telemetry.registry().gauge("device_resident_bytes", "")
    assert g.value(owner="offline/buf") == 8192


# -- ring truncation accounting (satellite a) ---------------------------------


def test_timeline_drop_and_flight_eviction_exact_counts():
    tl = telemetry.timeline()
    old_maxlen = tl.maxlen
    try:
        tl.set_maxlen(8)
        for i in range(20):
            telemetry.record("stall", frame=i)
        assert len(tl) == 8
        assert tl.dropped == 12

        telemetry.configure_flight(maxlen=4)
        fr = telemetry.flight_recorder()
        for i in range(10):
            fr.record("tick", frame=i, wall_ms=0.1)
        assert len(fr) == 4
        assert fr.evictions == 6

        s = telemetry.summary()
        assert s["timeline_events_dropped"] == 12
        assert s["flight_record_evictions"] == 6
        md = telemetry.chrome_trace()["metadata"]
        assert md["timeline_events_dropped"] == 12
        assert md["flight_record_evictions"] == 6
    finally:
        tl.set_maxlen(old_maxlen)
        telemetry.configure_flight(maxlen=256)


# -- disabled paths (satellite e) ---------------------------------------------


def test_disabled_recording_is_sub_microsecond():
    telemetry.disable()
    telemetry.configure_flight(enabled=False)
    n = 20000
    best = float("inf")
    for _ in range(3):  # best-of-3 damps scheduler noise
        t0 = time.perf_counter()
        for i in range(n):
            telemetry.record("stall", frame=i)
        best = min(best, (time.perf_counter() - t0) / n * 1e6)
    assert best < 1.0, f"disabled record() costs {best:.3f}us/call"
    assert len(telemetry.timeline()) == 0


def test_trace_is_empty_but_valid_when_disabled():
    telemetry.disable()
    telemetry.configure_flight(enabled=False)
    telemetry.timeline().clear()
    telemetry.flight_recorder().clear()
    telemetry.record("stall", frame=1)  # must not land anywhere
    trace = telemetry.chrome_trace()
    assert telemetry.validate_chrome_trace(trace) == []
    assert all(e["ph"] == "M" for e in trace["traceEvents"])
    assert trace["metadata"]["timeline_events_dropped"] == 0
    json.dumps(trace)  # serializable as-is


# -- surfaces: write_trace, /trace endpoint, replay_tool --------------------


def test_write_trace_roundtrip(tmp_path):
    telemetry.record("stall", frame=3)
    telemetry.flight_recorder().record("tick", frame=3, wall_ms=0.5)
    p = tmp_path / "t.json"
    n = telemetry.write_trace(str(p))
    loaded = json.loads(p.read_text())
    assert len(loaded["traceEvents"]) == n
    assert telemetry.validate_chrome_trace(loaded) == []
    names = {e["name"] for e in loaded["traceEvents"]}
    assert {"tick", "stall"} <= names


def test_trace_endpoint_serves_bounded_json():
    import urllib.request

    fr = telemetry.flight_recorder()
    for i in range(40):
        telemetry.record("stall", frame=i)
        fr.record("tick", frame=i, wall_ms=0.2)
    ex = telemetry.start_http_exporter(port=0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{ex.port}/trace?n=10", timeout=10
        ).read()
        trace = json.loads(body)
        assert telemetry.validate_chrome_trace(trace) == []
        ticks = [e for e in trace["traceEvents"]
                 if e.get("ph") == "X" and e["name"] == "tick"]
        assert len(ticks) == 10  # the ?n= cap bounds each source's tail
    finally:
        ex.close()


def test_replay_tool_merge_reports_json_and_trace_out(tmp_path, capsys):
    import scripts.replay_tool as rt

    def write(name, checksums, frames=None):
        p = tmp_path / name
        telemetry.write_desync_report(
            "p2p_desync", path=str(p), checksums=checksums,
            frames=[max(checksums)] if frames is None else frames,
        )
        return str(p)

    class Args:
        pass

    args = Args()
    args.a = write("a.json", {1: 10, 2: 20})
    args.b = write("b.json", {1: 10, 2: 21})
    args.json = True
    args.trace_out = str(tmp_path / "merged_trace.json")
    rc = rt.cmd_merge_reports(args)
    out = capsys.readouterr().out
    assert rc == 1  # divergence keeps exit code 1 under --json
    m = json.loads(out)  # stdout is pure JSON (trace note went to stderr)
    assert m["first_divergent_frame"] == 2
    trace = json.loads((tmp_path / "merged_trace.json").read_text())
    assert telemetry.validate_chrome_trace(trace) == []

    # agreeing windows (and no commonly-flagged frame): exit 0, pure JSON
    args2 = Args()
    args2.a = write("c.json", {5: 1}, frames=[])
    args2.b = write("d.json", {5: 1}, frames=[])
    args2.json = True
    args2.trace_out = None
    rc = rt.cmd_merge_reports(args2)
    out = capsys.readouterr().out
    assert rc == 0
    assert json.loads(out)["first_divergent_frame"] is None
