"""Speculation under real network rollbacks: with 3-hop latency and 1-frame
delay, predictions mispredict whenever inputs flip; a hedging runner must
(a) hit its branch cache and (b) stay bit-identical to a non-hedging peer."""


from bevy_ggrs_tpu import (
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
    SpeculationConfig,
    pad_candidates,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


def test_speculating_peer_agrees_with_plain_peer():
    net = ChannelNetwork(latency_hops=3, seed=9)
    socks = [net.endpoint("a"), net.endpoint("b")]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .with_disconnect_timeout(60.0)
            .with_disconnect_notify_delay(30.0)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, "b" if i == 0 else "a")
        )
        session = b.start_p2p_session(socks[i])
        # only peer 0 hedges: its remote (player 1) flips between two inputs
        spec = (
            SpeculationConfig(
                candidates_fn=pad_candidates(2, [1], list(range(16))), depth=4
            )
            if i == 0
            else None
        )
        tick_counter = [0]

        def read_inputs(handles, i=i, tick_counter=tick_counter):
            tick_counter[0] += 1
            on = (tick_counter[0] // 5) % 2 == 0  # flip every 5 frames
            key = {0: "right", 1: "up"}[i]
            return {h: box_game.keys_to_input(**{key: on}) for h in handles}

        runners.append(
            GgrsRunner(app, session, read_inputs=read_inputs, speculation=spec)
        )

    import time

    for _ in range(400):
        net.deliver()
        for r in runners:
            r.update(0.0)
        if all(r.session.current_state() == SessionState.RUNNING for r in runners):
            break
        time.sleep(0.001)
    for _ in range(120):
        net.deliver()
        for r in runners:
            r.update(DT)

    s0 = runners[0].stats()
    assert s0["rollbacks"] > 0, "latency should have forced rollbacks"
    assert s0["speculation_hits"] > 0, f"no cache hits: {s0}"
    # checksum agreement at a confirmed frame both peers still hold
    f = None
    for _ in range(40):
        conf = min(r.session.confirmed_frame() for r in runners)
        shared = [
            fr
            for fr in set(runners[0].ring.frames()) & set(runners[1].ring.frames())
            if fr <= conf
        ]
        if shared:
            f = max(shared)
            break
        net.deliver()
        (runners[0] if runners[0].frame <= runners[1].frame else runners[1]).update(DT)
    assert f is not None
    assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
        runners[1].ring.peek(f)[1]
    )
