"""Pluggable transport: the same P2P session stack over an in-process
channel network (the matchbox/WebRTC-analog socket swap) with deterministic
latency — forces real predictions and rollbacks without real sockets."""


from bevy_ggrs_tpu import GgrsRunner, PlayerType, SessionBuilder, SessionState
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.channel import ChannelNetwork
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


def make_runners(latency_hops=0, loss=0.0):
    net = ChannelNetwork(latency_hops=latency_hops, loss=loss, seed=1)
    socks = [net.endpoint("peer0"), net.endpoint("peer1")]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(1)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, f"peer{1 - i}")
        )
        session = b.start_p2p_session(socks[i])

        def read_inputs(handles, i=i):
            key = {0: "right", 1: "down"}[i]
            return {h: box_game.keys_to_input(**{key: True}) for h in handles}

        runners.append(GgrsRunner(app, session, read_inputs=read_inputs))
    return net, runners


def drive(net, runners, ticks, dt=DT):
    for _ in range(ticks):
        net.deliver()
        for r in runners:
            r.update(dt)


def test_channel_p2p_runs_and_agrees():
    net, runners = make_runners()
    drive(net, runners, 300, dt=0.0)  # sync
    assert all(r.session.current_state() == SessionState.RUNNING for r in runners)
    drive(net, runners, 60)
    assert all(r.frame >= 50 for r in runners)
    shared = sorted(set(runners[0].ring.frames()) & set(runners[1].ring.frames()))
    if not shared:
        drive(net, runners, 1)
        shared = sorted(set(runners[0].ring.frames()) & set(runners[1].ring.frames()))
    assert shared
    f = shared[-1]
    assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
        runners[1].ring.peek(f)[1]
    )


def test_channel_p2p_with_latency_forces_rollbacks():
    # 3-hop latency > 1-frame input delay: predictions will be wrong whenever
    # inputs change, exercising the rollback path deterministically
    net, runners = make_runners(latency_hops=3)
    drive(net, runners, 300, dt=0.0)
    assert all(r.session.current_state() == SessionState.RUNNING for r in runners)

    # alternate inputs so predictions mispredict
    flip = [0]

    def read_inputs(handles):
        flip[0] += 1
        on = (flip[0] // 7) % 2 == 0
        return {h: box_game.keys_to_input(right=on) for h in handles}

    runners[0].read_inputs = read_inputs
    drive(net, runners, 120)
    assert all(r.frame >= 100 for r in runners)
    # both peers still agree wherever their rings overlap
    for _ in range(6):
        shared = sorted(set(runners[0].ring.frames()) & set(runners[1].ring.frames()))
        if shared:
            break
        drive(net, runners, 1)
    assert shared
    f = shared[-1]
    assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
        runners[1].ring.peek(f)[1]
    )
