"""BGT032 suppressed: the same uncataloged kind, waived at the emission
site with a reason."""


def leak(telemetry):
    # bgt: ignore[BGT032]: scratch event for a local repro session
    telemetry.record("zzz_private_event", frame=1)
