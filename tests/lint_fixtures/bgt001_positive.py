"""BGT001 positive: an import nobody uses."""
import os
import json

print(json.dumps({}))
