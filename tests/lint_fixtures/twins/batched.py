"""Twin fixtures, batched half: ``drain`` matches the solo half after
normalization (different local names + telemetry label), ``tally`` has
genuinely drifted, ``ping`` is identical (a declared drift that
converged)."""


class Batched:
    def drain(self, queue):
        drained = []
        while queue:
            drained.append(queue.pop())
        self._t.count("batched_drain_total")
        return drained

    def tally(self, xs):
        return sum(xs)

    def ping(self):
        return self._clock.now()
