"""Twin fixtures, solo half (see test_lint.py's BGT073 tests)."""


class Solo:
    def drain(self, q):
        out = []
        while q:
            out.append(q.pop())
        self._t.count("drain_total")
        return out

    def tally(self, xs):
        total = 0
        for x in xs:
            total += x
        return total

    def ping(self):
        return self._clock.now()
