"""BGT062 positive: ``credit`` nests a_lock -> b_lock, ``debit`` nests
b_lock -> a_lock — the classic ABBA deadlock, witnessed at both sites."""

import threading


class Ledger:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
        self.a = 0
        self.b = 0
        self._thread = threading.Thread(target=self.debit, daemon=True)

    def credit(self):
        with self.a_lock:
            with self.b_lock:
                self.a += 1
                self.b -= 1

    def debit(self):
        with self.b_lock:
            with self.a_lock:
                self.b += 1
                self.a -= 1
