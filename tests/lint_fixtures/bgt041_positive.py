"""BGT041 positive: process-global RNG in all three shapes."""
import random
import numpy as np


def jitter():
    a = random.random()
    b = np.random.uniform(0.0, 1.0)
    rng = np.random.default_rng()
    return a + b + rng.uniform()
