"""BGT002 clean: decorated pairs are exempt by design."""


class C:
    @property
    def v(self):
        return self._v

    @v.setter
    def v(self, x):
        self._v = x
