"""BGT061 positive: socket recv and a sleep, both while the lock is held
— every thread sharing ``self._lock`` stalls for the full wait."""

import socket
import threading
import time


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._thread = threading.Thread(target=self.poll, daemon=True)

    def poll(self):
        with self._lock:
            data, addr = self._sock.recvfrom(65536)
            time.sleep(0.01)
            self._pending.append((data, addr))
