"""BGT002 positive: a redefinition silently shadows the first."""


def advance(x):
    return x + 1


def advance(x):
    return x + 2
