def pull(ref):
    return ref
