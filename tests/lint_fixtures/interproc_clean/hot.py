"""Clean driver: the helper chain never forces."""
from .helpers import grab


def tick(ref):
    return grab(ref)
