from .leaf import pull


def grab(ref):
    return pull(ref)
