"""BGT061 suppressed: a blocking call under a lock with a (fixture)
bounded-wait justification."""

import threading
import time


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []
        self._thread = threading.Thread(target=self.poll, daemon=True)

    def poll(self):
        with self._lock:
            # bgt: ignore[BGT061]: fixture — 1ms bounded settle, the lock
            # is private to this object and never shared with the tick loop
            time.sleep(0.001)
            self._pending.clear()
