"""BGT062 clean: both paths acquire in the one canonical order."""

import threading


class Ledger:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
        self._thread = threading.Thread(target=self.debit, daemon=True)

    def credit(self):
        with self.a_lock:
            with self.b_lock:
                pass

    def debit(self):
        with self.a_lock:
            with self.b_lock:
                pass
