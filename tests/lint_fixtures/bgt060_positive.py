"""BGT060 positive: ``_series`` is written from the scrape thread
(``Thread(target=self._scrape)``) AND the foreground tick loop with no
common lock — the lock exists but neither writer holds it."""

import threading


class Registry:
    def __init__(self):
        self._series = {}
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._scrape, daemon=True)

    def _scrape(self):
        self._series["scrape"] = 1

    def tick(self):
        self._series["tick"] = 2
