def broken(:
    pass
