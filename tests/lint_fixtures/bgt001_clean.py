"""BGT001 clean: every import is used."""
import json

print(json.dumps({}))
