import jax


def upload_rows(rows):
    return jax.device_put(rows)
