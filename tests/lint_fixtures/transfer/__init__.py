"""BGT063 interprocedural positive: driver passes a reused staging
buffer into a helper that uploads it un-barriered."""
