"""BGT005 suppressed: the stale BGT042 ignore is itself waived with a
justified BGT005 suppression on the same origin line (a deliberate
keep-for-now, e.g. mid-refactor)."""


def total(values):
    # bgt: ignore[BGT042, BGT005]: kept during the sort refactor — the set
    # path returns next PR and the justification should survive with it
    return sum(sorted(values))
