"""BGT060 clean: every cross-thread write of ``_series`` holds the SAME
lock (``self._lock``) — the textual common-lock witness."""

import threading


class Registry:
    def __init__(self):
        self._series = {}
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._scrape, daemon=True)

    def _scrape(self):
        with self._lock:
            self._series["scrape"] = 1

    def tick(self):
        with self._lock:
            self._series["tick"] = 2
