"""BGT041 clean: all randomness derives from explicit seeds."""
import random
import numpy as np


def sample(seed: int):
    rng = np.random.default_rng(seed)
    r = random.Random(seed)
    return rng.uniform(), r.random()
