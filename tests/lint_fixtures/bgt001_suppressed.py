"""BGT001 suppressed: kept import with a justification."""
import os  # bgt: ignore[BGT001]: re-exported for plugin discovery
import json

print(json.dumps({}))
