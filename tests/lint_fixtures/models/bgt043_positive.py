"""BGT043 positive: host callbacks inside sim code."""
import jax
from jax.experimental import io_callback


def step(world, x):
    jax.debug.print("x={}", x)
    io_callback(print, None, x)
    jax.pure_callback(lambda v: v, x, x)
    return world
