"""BGT044 positive: in-place mutation of the frozen world."""


def step(world, x):
    world.pos = x
    world.comps["pos"] = x
    world.vel += x
    return world
