"""BGT040 positive: wall-clock reads inside sim-code functions."""
import time
import datetime


def step(world):
    t = time.time()
    m = time.monotonic()
    now = datetime.datetime.now()
    return t + m + now.timestamp()
