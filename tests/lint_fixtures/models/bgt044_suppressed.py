"""BGT044 suppressed: a sanctioned scratch-field write."""


def step(world, x):
    # bgt: ignore[BGT044]: scratch cache field, excluded from snapshots
    world._scratch = x
    return world
