"""BGT072 clean: int-preserving and explicitly-cast arithmetic."""
import jax.numpy as jnp


def register(app):
    app.rollback_component("ammo", (1,), jnp.int32)
    app.rollback_component("heat", (1,), jnp.float32)


def step(world):
    ammo = world.comps["ammo"]
    halved = ammo // 2
    scaled = ammo.astype(jnp.float32) * 0.5
    heat = world.comps["heat"] * 0.9
    return halved, scaled, heat
