"""BGT071 clean: fixed-capacity forms of every flagged op."""
import jax.numpy as jnp


def masked_damage(w):
    mask = w.hp > 0
    return jnp.sum(jnp.where(mask, w.dmg, 0))


def top_teams(w):
    return jnp.unique(w.team, size=8, fill_value=-1)


def to_grid(x):
    return x.reshape(4, -1)


def pair_rows(a, b):
    return jnp.stack([a, b])
