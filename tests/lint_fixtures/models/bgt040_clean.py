"""BGT040 clean: frame-derived time + perf_counter (allowed)."""
import time


def step(world, ctx):
    elapsed = time.perf_counter()  # profiling clock: deliberately allowed
    return world, ctx.frame / 60.0, elapsed
