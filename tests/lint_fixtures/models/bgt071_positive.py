"""BGT071 true positives — data-dependent result shapes in sim scope."""
import jax.numpy as jnp


def live_indices(w):
    return jnp.nonzero(w.alive)


def gather_alive(w):
    mask = w.hp > 0
    return w.pos[mask]


def unique_teams(w):
    return jnp.unique(w.team)


def hit_coords(w):
    return jnp.where(w.hits)


def merge_rows(rows):
    return jnp.concatenate(rows)
