"""BGT072 true positives — float promotion of int-declared components."""
import jax.numpy as jnp


def register(app):
    app.rollback_component("ammo", (1,), jnp.int32)
    app.rollback_component("heat", (1,), jnp.float32)


def step(world):
    ammo = world.comps["ammo"]
    half = ammo / 2
    decay = world.comps["ammo"] - 0.5
    return half, decay
