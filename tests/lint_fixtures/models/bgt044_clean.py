"""BGT044 clean: new state via dataclasses.replace."""
import dataclasses


def step(world, x):
    return dataclasses.replace(world, pos=world.pos + x)
