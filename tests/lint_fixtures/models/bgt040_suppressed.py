"""BGT040 suppressed: a justified host-side timing read."""
import time


def profile_step(world):
    # bgt: ignore[BGT040]: host-side profiling only, value never enters state
    return time.time()
