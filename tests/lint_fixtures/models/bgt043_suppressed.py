"""BGT043 suppressed: debug print kept behind a justification."""
import jax


def step(world, x):
    # bgt: ignore[BGT043]: temporary diagnostic, stripped by jit in prod config
    jax.debug.print("x={}", x)
    return world
