"""BGT071 with a justified seed-line suppression."""
import jax.numpy as jnp


def checksum_lanes(parts):
    return jnp.concatenate(parts)  # bgt: ignore[BGT071]: lane count is fixed by the registry at startup, never data-dependent
