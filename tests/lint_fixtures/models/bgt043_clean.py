"""BGT043 clean: no host callbacks in the step."""


def step(world, x):
    return world, x + 1
