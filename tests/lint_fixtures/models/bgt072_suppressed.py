"""BGT072 with a justified line suppression."""
import jax.numpy as jnp


def register(app):
    app.rollback_component("charge", (1,), jnp.int32)


def hud_scale(world):
    return world.comps["charge"] * 0.25  # bgt: ignore[BGT072]: display-only rescale on a host copy, never written back to the world
