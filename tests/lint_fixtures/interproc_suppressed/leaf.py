def pull(ref):
    # bgt: ignore[BGT011]: guarded — only called after readiness is polled
    return ref.block_until_ready()
