"""Same driver; the chain is sanctioned at the seed line in leaf.py."""
from .helpers import grab


def tick(ref):
    return grab(ref)
