from .leaf import upload


def commit_staging(buf):
    return upload(buf)
