"""Packed-staging driver shape: the per-tick dispatch path stages rows
into a persistent packed buffer and commits it through a helper — no
forcing syntax in this file, the chain hides in the staging commit."""
from .helpers import commit_staging


def stage_packed_rows(buf, k):
    return commit_staging(buf[:k + 1])
