def upload(buf):
    x = buf.device_put_result()
    return x.block_until_ready()
