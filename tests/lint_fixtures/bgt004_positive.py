"""BGT004 positive: a typo'd rule id in an ignore comment."""
X = 1  # bgt: ignore[BGT999]
