"""BGT063 clean: the upload barriers its bound result before returning,
and the donated name is rebound from the call result before any read."""

import jax
import numpy as np

step = jax.jit(lambda w: w + 1, donate_argnums=0)


class Stager:
    def __init__(self):
        self.buf = np.zeros((8, 4), dtype=np.float32)

    def pack(self, rows):
        for i, r in enumerate(rows):
            self.buf[i] = r

    def upload(self):
        x = jax.device_put(self.buf)
        x.block_until_ready()
        return x


def advance(world):
    out = step(world)
    world = out
    return out + world
