"""BGT041 suppressed: justified host-side-only global draw."""
import random


def nonce():
    # bgt: ignore[BGT041]: handshake nonce — host-side protocol only
    return random.getrandbits(32)
