"""BGT062 suppressed: the ABBA pair waived with a (fixture) argument that
the two paths can never run concurrently."""

import threading


class Ledger:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
        self._thread = threading.Thread(target=self.debit, daemon=True)

    def credit(self):
        with self.a_lock:
            # bgt: ignore[BGT062]: fixture — credit only runs before the
            # debit thread starts (single-phase handoff, pretend)
            with self.b_lock:
                pass

    def debit(self):
        with self.b_lock:
            with self.a_lock:
                pass
