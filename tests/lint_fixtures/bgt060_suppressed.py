"""BGT060 suppressed: same unlocked cross-thread write, waived with a
(fixture) protocol justification at the reporting write site."""

import threading


class Registry:
    def __init__(self):
        self._series = {}
        self._thread = threading.Thread(target=self._scrape, daemon=True)

    def _scrape(self):
        # bgt: ignore[BGT060]: fixture — single-writer epoch protocol, the
        # tick loop only writes before start() (pretend)
        self._series["scrape"] = 1

    def tick(self):
        self._series["tick"] = 2
