"""The driver: no forcing syntax anywhere in this file."""
from .helpers import grab


def tick(ref):
    return grab(ref)
