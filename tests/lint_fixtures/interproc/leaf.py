def pull(ref):
    return ref.block_until_ready()
