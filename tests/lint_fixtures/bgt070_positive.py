"""BGT070 true positives — one function per jit cache-key hazard shape."""
import functools

import jax


def _impl(x, axis):
    return x.sum(axis)


def tick_fresh(x):
    fn = jax.jit(_impl)  # fresh callable per call: nothing ever hits
    return fn(x, 0)


def tick_static(x, axes):
    fn = jax.jit(_impl, static_argnums=axes)  # non-literal static args
    return fn(x, 0)


def tick_partial(x, n):
    fn = jax.jit(functools.partial(_impl, opts={"n": n}))  # dict via partial
    return fn(x)


def tick_closure(xs):
    state = []

    def body(x):
        return x + len(state)

    fn = jax.jit(body)  # closes over `state`, which this scope mutates
    state.append(1)
    return fn(xs)
