"""BGT042 positive: set iteration feeding order-sensitive sinks."""
import numpy as np


def accumulate(names):
    total = sum(w for w in {1.5, 2.5, 3.5})
    arr = np.asarray({0.1, 0.2})
    tag = ",".join(set(names))
    return total, arr, tag
