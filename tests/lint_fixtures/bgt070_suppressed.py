"""BGT070 with a justified line suppression."""
import jax


def _impl(x, axis):
    return x.sum(axis)


def probe(x):
    fn = jax.jit(_impl)  # bgt: ignore[BGT070]: one-shot diagnostic probe — rebuilding the program per run is the point
    return fn(x, 0)
