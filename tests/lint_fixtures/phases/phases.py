"""Fixture phase catalog (AST-extracted by the lint, never imported)."""

PHASES = (
    "inputs",
    "advance",
    "checksum",
    "never_timed",
)
