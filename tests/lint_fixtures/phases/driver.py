"""Fixture driver exercising BGT020/BGT021 against phases.py's catalog."""


class _T:
    def phase(self, name):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def run(t: _T, dynamic: str):
    with t.phase("inputs"):
        pass
    with t.phase("advance"):
        pass
    with t.phase("typo_phase"):      # BGT020: not in the catalog
        pass
    with t.phase(dynamic):           # BGT020: not one string literal
        pass
    t.phase("checksum")              # BGT021: bare call, times nothing
