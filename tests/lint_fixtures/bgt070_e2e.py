"""A toy runner with a per-call-varying static argument: the exact site
BGT070 flags statically AND the armed compile guard trips at runtime
(tests/test_compile_guard.py drives both halves)."""
import jax

_STATIC_ARGS = (1,)


def _impl(x, scale):
    return x * scale


def tick(x, scale):
    fn = jax.jit(_impl, static_argnums=_STATIC_ARGS)
    return fn(x, scale)
