"""BGT063 interprocedural suppressed: the helper's seed-line sanction
kills the effect, so the driver's call site is clean too."""
