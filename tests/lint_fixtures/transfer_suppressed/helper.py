import jax


def upload_rows(rows):
    # bgt: ignore[BGT063]: fixture — every caller fences before the next
    # rewrite (pretend rotation protocol), sanctioned for all callers
    return jax.device_put(rows)
