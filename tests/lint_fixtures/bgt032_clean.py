"""BGT032 clean: only catalogued kinds (docs/observability.md "Tracing &
device memory" lists ``rollback``), plus non-literal and non-record calls
the collector must ignore."""


def fine(telemetry, recorder, kind):
    telemetry.record("rollback", to_frame=3, handle=1)
    telemetry.record(kind, x=1)  # dynamic kind: not collectable
    recorder.append("zzz_private_event")  # not a .record call
