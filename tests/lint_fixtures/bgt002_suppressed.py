"""BGT002 suppressed."""


def advance(x):
    return x + 1


# bgt: ignore[BGT002]: intentional platform-specific override below
def advance(x):
    return x + 2
