"""BGT063 positive: one staging race (reused ``self.buf`` uploaded with
no barrier) and one donation race (``world`` read after being donated)."""

import jax
import numpy as np

step = jax.jit(lambda w: w + 1, donate_argnums=0)


class Stager:
    def __init__(self):
        self.buf = np.zeros((8, 4), dtype=np.float32)

    def pack(self, rows):
        for i, r in enumerate(rows):
            self.buf[i] = r

    def upload(self):
        return jax.device_put(self.buf)


def advance(world):
    out = step(world)
    return out + world
