"""BGT063 suppressed: the staging upload carries a seed-line protocol
sanction (kills the finding AND the effect, tracked as load-bearing);
the donation reuse is waived at the read site."""

import jax
import numpy as np

step = jax.jit(lambda w: w + 1, donate_argnums=0)


class Stager:
    def __init__(self):
        self.buf = np.zeros((8, 4), dtype=np.float32)

    def pack(self, rows):
        for i, r in enumerate(rows):
            self.buf[i] = r

    def upload(self):
        # bgt: ignore[BGT063]: fixture — rotation protocol, pack() only
        # rewrites this buffer after the caller's fence (pretend)
        return jax.device_put(self.buf)


def advance(world):
    out = step(world)
    # bgt: ignore[BGT063]: fixture — `world` is a host-side copy here, the
    # donated device buffer is not aliased (pretend)
    return out + world
