"""BGT042 clean: sorted() pins the order before accumulation."""
import numpy as np


def accumulate(names):
    total = sum(sorted({1.5, 2.5, 3.5}))
    arr = np.asarray(sorted({0.1, 0.2}))
    tag = ",".join(sorted(set(names)))
    return total, arr, tag
