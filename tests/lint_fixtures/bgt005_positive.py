"""BGT005 positive: the ignore below names a rule (BGT042) that never
fires on the line it covers — a rotted suppression."""


def total(values):
    # bgt: ignore[BGT042]: stale — the set-iteration sum was refactored away
    return sum(sorted(values))
