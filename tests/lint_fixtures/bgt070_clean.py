"""BGT070 clean: every sanctioned jit creation site."""
import jax


def _impl(x, axis):
    return x.sum(axis)


_step = jax.jit(_impl)  # module scope

_cache = {}
_fn = None


def make_step(axis):
    # factory prefix: callers memoize the result
    return jax.jit(lambda x: x.sum(axis))


def step_for(k):
    fn = _cache.get(k)
    if fn is None:
        fn = _cache[k] = jax.jit(lambda x: x + k)  # keyed memo cache
    return fn


def get_step():
    global _fn
    if _fn is None:
        _fn = jax.jit(_impl)  # lazy module singleton
    return _fn


class Runner:
    def __init__(self):
        self.fn = jax.jit(_impl)  # once per instance
