"""BGT005 clean: the ignore is load-bearing — BGT042 really fires on the
covered line (and is suppressed), so the comment is not stale."""


def total():
    # bgt: ignore[BGT042]: fixture — deliberate set-iteration sum
    return sum({1.0, 2.0, 3.0})
