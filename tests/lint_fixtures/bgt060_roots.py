"""Declared-root fixture: NO ``threading.Thread`` is constructed in this
module — the scrape thread lives elsewhere and calls ``Series.bump``
directly, so thread-safety analysis only sees it when the method is
declared in ``config.THREAD_ROOTS`` (the ``telemetry/metrics.py``
situation).  Without the declaration the module is vacuously clean."""


class Series:
    def __init__(self):
        self._vals = {}

    def bump(self, key):
        self._vals[key] = self._vals.get(key, 0) + 1

    def tick(self, key):
        self._vals[key] = 0
