"""Sim-scope driver: no shape-hazard syntax anywhere in this file."""
from ..digest import fold_parts


def tick(world):
    return fold_parts(world)
