"""Non-sim helper: the data-dependent shape lives HERE, not in the
sim-scope driver that reaches it."""
import jax.numpy as jnp


def fold_parts(parts):
    return jnp.stack(parts)
