"""Clean packed-staging driver: the commit helper hands the buffer to the
dispatch without blocking anywhere in the chain."""
from .helpers import commit_staging


def stage_packed_rows(buf, k):
    return commit_staging(buf[:k + 1])
