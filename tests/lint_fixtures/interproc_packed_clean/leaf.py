def upload(buf):
    return buf
