"""BGT010 fixtures: forcing syntax in and out of the allowlist."""


def tick(ref):
    return ref.block_until_ready()


def also_bad(ref):
    # bgt: ignore[BGT010]: guarded non-blocking poll in the real code
    return ref.to_int()


def sanctioned(ref):
    return ref.device_get()
