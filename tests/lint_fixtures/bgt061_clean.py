"""BGT061 clean: copy state under the lock, release it, THEN block."""

import threading
import time


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []
        self._thread = threading.Thread(target=self.poll, daemon=True)

    def poll(self):
        with self._lock:
            drained = list(self._pending)
            self._pending.clear()
        time.sleep(0.01)
        return drained
