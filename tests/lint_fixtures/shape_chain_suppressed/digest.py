"""Non-sim helper with the hazard sanctioned at its seed line."""
import jax.numpy as jnp


def fold_parts(parts):
    return jnp.stack(parts)  # bgt: ignore[BGT071]: part count is fixed by the registry, never data-dependent
