"""Sim-scope driver: identical to shape_chain/ops/hot.py — the seed
sanction in digest.py must clear the chain finding here."""
from ..digest import fold_parts


def tick(world):
    return fold_parts(world)
