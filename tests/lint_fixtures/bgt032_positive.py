"""BGT032 true positive: emits a trace kind the docs catalog does not
list (the fixture run points at the real docs/observability.md)."""


def leak(telemetry):
    telemetry.record("zzz_private_event", frame=1)
