"""BGT042 suppressed: order provably irrelevant (exact ints)."""


def count(flags):
    # bgt: ignore[BGT042]: exact integer sum — order cannot change the value
    return sum(set(flags))
