import numpy as np

from .helper import upload_rows


class PackedStager:
    def __init__(self):
        self.buf = np.zeros((16, 8), dtype=np.float32)

    def pack(self, rows):
        k = 0
        for r in rows:
            self.buf[k] = r
            k += 1
        return k

    def flush(self, k):
        return upload_rows(self.buf[:k])
