import jax


def upload_rows(rows):
    x = jax.device_put(rows)
    x.block_until_ready()
    return x
