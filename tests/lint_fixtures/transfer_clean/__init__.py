"""BGT063 interprocedural clean: the helper barriers its upload, so no
effect propagates to the driver."""
