"""BGT004 clean: a well-formed suppression of a real rule."""
import os  # bgt: ignore[BGT001]: intentional
