"""Driver-integrated speculation: a scripted session forces a depth-1
rollback; with speculation enabled the corrected first frame must be served
from the branch cache and produce EXACTLY the state a plain resim produces."""

import numpy as np

from bevy_ggrs_tpu import GgrsRunner, SessionState
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.ops.speculation import SpeculationConfig, pad_candidates
from bevy_ggrs_tpu.session.events import InputStatus
from bevy_ggrs_tpu.session.requests import AdvanceRequest, LoadRequest, SaveCell, SaveRequest
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int


class ScriptedSession:
    """Minimal session emitting a fixed request script (one entry per tick)."""

    def __init__(self, script, num_players=2):
        self.script = list(script)
        self._num_players = num_players
        self.tick_idx = 0
        self.saved = {}

    def num_players(self):
        return self._num_players

    def max_prediction(self):
        return 8

    def confirmed_frame(self):
        return -1

    def current_state(self):
        return SessionState.RUNNING

    def local_player_handles(self):
        return [0]

    def add_local_input(self, handle, value):
        pass

    def advance_frame(self):
        reqs = self.script[self.tick_idx]
        self.tick_idx += 1
        return reqs

    def _on_cell_saved(self, frame, provider):
        self.saved[frame] = provider


def adv(inputs, predicted=False):
    status = np.zeros((2,), np.int8)
    if predicted:
        status[1] = InputStatus.PREDICTED
    return AdvanceRequest(np.asarray(inputs, np.uint8), status)


def make_script(session_holder, corrected):
    RIGHT = box_game.keys_to_input(right=True)
    UP = box_game.keys_to_input(up=True)
    predicted = [RIGHT, 0]  # remote predicted idle
    actual = [RIGHT, corrected]

    def save(f):
        return SaveRequest(f, SaveCell(session_holder[0], f))

    tick1 = [save(0), adv(predicted, predicted=True)]
    # real remote input arrives, differs -> rollback to 0, resim, live frame
    tick2 = [LoadRequest(0), adv(actual), save(1), adv([RIGHT, corrected], predicted=True)]
    return [tick1, tick2]


def run_script(speculation):
    app = box_game.make_app(num_players=2)
    corrected = box_game.keys_to_input(up=True)
    session = ScriptedSession([])
    session.script = make_script([session], corrected)
    runner = GgrsRunner(app, session, speculation=speculation)
    runner.tick()
    runner.tick()
    return runner


def test_cache_hit_matches_plain_resim():
    spec = SpeculationConfig(
        candidates_fn=pad_candidates(2, [1], list(range(16)))
    )
    r_spec = run_script(spec)
    r_plain = run_script(None)
    assert r_spec.spec_cache.hits == 1
    assert r_spec.frame == r_plain.frame == 2
    assert np.array_equal(
        np.asarray(r_spec.world.comps["pos"]), np.asarray(r_plain.world.comps["pos"])
    )
    assert checksum_to_int(r_spec._world_checksum) == checksum_to_int(
        r_plain._world_checksum
    )
    # the re-saved frame-1 checksum (served from cache) matches too
    assert r_spec.session.saved[1]() == r_plain.session.saved[1]()


def test_cache_miss_on_unhedged_input():
    # candidates only cover values 0..3; actual correction is UP|RIGHT = 9
    app = box_game.make_app(num_players=2)
    session = ScriptedSession([])
    session.script = make_script([session], np.uint8(9))
    spec = SpeculationConfig(candidates_fn=pad_candidates(2, [1], [0, 1, 2, 3]))
    runner = GgrsRunner(app, session, speculation=spec)
    runner.tick()
    runner.tick()
    assert runner.spec_cache.hits == 0
    assert runner.spec_cache.misses >= 1
    assert runner.frame == 2  # still correct via plain resim


def make_deep_script(session, corrected, depth):
    """Depth-``depth`` rollback: live-advance ``depth`` predicted frames, then
    the real (constant) remote input arrives for all of them."""
    RIGHT = box_game.keys_to_input(right=True)
    predicted = [RIGHT, 0]
    actual = [RIGHT, corrected]

    def save(f):
        return SaveRequest(f, SaveCell(session, f))

    ticks = []
    for f in range(depth):
        ticks.append([save(f), adv(predicted, predicted=True)])
    rollback = [LoadRequest(0)]
    for f in range(depth):
        rollback += [adv(actual), save(f + 1)]
    rollback.append(adv(actual, predicted=True))
    ticks.append(rollback)
    return ticks


def run_deep(speculation, depth=3):
    app = box_game.make_app(num_players=2)
    corrected = box_game.keys_to_input(up=True)
    session = ScriptedSession([])
    session.script = make_deep_script(session, corrected, depth)
    runner = GgrsRunner(app, session, speculation=speculation)
    for _ in range(depth + 1):
        runner.tick()
    return runner


def test_depth_k_cache_serves_whole_rollback():
    depth = 3
    spec = SpeculationConfig(
        candidates_fn=pad_candidates(2, [1], list(range(16))), depth=4
    )
    r_spec = run_deep(spec, depth)
    r_plain = run_deep(None, depth)
    assert r_spec.spec_cache.hits >= 1
    assert r_spec.frame == r_plain.frame == depth + 1
    # the whole catch-up was served from one cached branch: the final tick
    # dispatched only the live frame, not the depth-frame resim
    assert r_spec.device_dispatches < r_plain.device_dispatches
    assert np.array_equal(
        np.asarray(r_spec.world.comps["pos"]), np.asarray(r_plain.world.comps["pos"])
    )
    assert checksum_to_int(r_spec._world_checksum) == checksum_to_int(
        r_plain._world_checksum
    )
    for f in range(1, depth + 1):
        assert r_spec.session.saved[f]() == r_plain.session.saved[f]()
