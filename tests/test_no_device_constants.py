"""Static guard: no module-level jnp/jax.numpy constant assignments in the
package.  Pre-existing device arrays captured by jitted functions become
per-call parameter buffers — a measured ~4 ms/dispatch slow path through the
TPU tunnel (docs/tpu_notes.md §1).  Constants must be numpy scalars/arrays
or created during tracing."""

import ast
import os

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "bevy_ggrs_tpu")


def _is_jnp_call(node) -> bool:
    """True for jnp.<anything>(...) / jax.numpy.<...>(...) expressions."""
    if isinstance(node, ast.Call):
        f = node.func
        parts = []
        while isinstance(f, ast.Attribute):
            parts.append(f.attr)
            f = f.value
        if isinstance(f, ast.Name):
            parts.append(f.id)
        parts.reverse()
        if parts and parts[0] in ("jnp",):
            return True
        if len(parts) >= 2 and parts[0] == "jax" and parts[1] == "numpy":
            return True
    return False


def test_no_module_level_jnp_constants():
    offenders = []
    for root, _, files in os.walk(PKG):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            tree = ast.parse(open(path).read())
            for node in tree.body:  # module level only
                targets = []
                if isinstance(node, ast.Assign):
                    targets = [node.value]
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets = [node.value]
                for value in targets:
                    for sub in ast.walk(value):
                        if _is_jnp_call(sub):
                            offenders.append(
                                f"{os.path.relpath(path, PKG)}:{node.lineno}"
                            )
    assert not offenders, (
        "module-level jnp constants (TPU dispatch poison, tpu_notes.md §1): "
        + ", ".join(offenders)
    )
