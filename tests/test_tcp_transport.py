"""Second production transport: framed TCP (the reference's
matchbox-WebRTC drop-in analog, /root/reference/README.md:79).  Same
loopback two-apps-one-process harness as tests/test_p2p.py, swapping only
the socket — the sessions must not care.  Includes the simultaneous-dial
case (both peers' sync requests fire immediately, so both dial; the
lower-listen-address connection must win on both sides)."""

import time

import numpy as np
import pytest

from bevy_ggrs_tpu import (
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SessionState,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.transport import TcpNonBlockingSocket
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int

DT = 1.0 / 60.0


def _make_pair(input_delay=2):
    socks = [TcpNonBlockingSocket(0, host="127.0.0.1") for _ in range(2)]
    addrs = [("127.0.0.1", s.local_addr[1]) for s in socks]
    runners = []
    for i in range(2):
        app = box_game.make_app(num_players=2)
        b = (
            SessionBuilder.for_app(app)
            .with_input_delay(input_delay)
            .with_disconnect_timeout(60.0)
            .with_disconnect_notify_delay(30.0)
            .add_player(PlayerType.LOCAL, i)
            .add_player(PlayerType.REMOTE, 1 - i, addrs[1 - i])
        )
        session = b.start_p2p_session(socks[i])

        def read_inputs(handles, i=i):
            key = {0: "right", 1: "up"}[i]
            return {h: box_game.keys_to_input(**{key: True}) for h in handles}

        runners.append(GgrsRunner(app, session, read_inputs=read_inputs))
    return runners, socks


def _sync(runners, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for r in runners:
            r.update(0.0)
        if all(
            r.session.current_state() == SessionState.RUNNING for r in runners
        ):
            return True
        time.sleep(0.002)
    return False


def test_p2p_pair_over_tcp():
    runners, socks = _make_pair()
    assert _sync(runners), "TCP peers never reached RUNNING"
    for _ in range(120):
        for r in runners:
            r.update(DT)
        time.sleep(0.0005)
    try:
        assert all(r.frame >= 100 for r in runners)
        # remote input visibly moved the other player's entity on each peer
        for i, r in enumerate(runners):
            comps = r.read_components(["pos"])
            pos = np.asarray(comps["pos"])
            assert abs(pos[1 - i]).max() > 0.0, (
                f"peer {i} never saw remote movement"
            )
        # peers agree bit-for-bit at a common ring frame
        common = sorted(
            set(runners[0].ring.frames()) & set(runners[1].ring.frames())
        )
        conf = min(r.confirmed for r in runners)
        common = [f for f in common if f <= conf]
        assert common, "no common confirmed snapshot to compare"
        f = common[-1]
        assert checksum_to_int(runners[0].ring.peek(f)[1]) == checksum_to_int(
            runners[1].ring.peek(f)[1]
        )
    finally:
        for s in socks:
            s.close()


def test_simultaneous_dial_converges():
    a = TcpNonBlockingSocket(0, host="127.0.0.1")
    b = TcpNonBlockingSocket(0, host="127.0.0.1")
    addr_a = ("127.0.0.1", a.local_addr[1])
    addr_b = ("127.0.0.1", b.local_addr[1])
    # both dial each other in the same instant
    a.send_to(b"from-a-1", addr_b)
    b.send_to(b"from-b-1", addr_a)
    got_a, got_b = [], []
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and (len(got_a) < 2 or len(got_b) < 2):
        got_a.extend(a.receive_all())
        got_b.extend(b.receive_all())
        a.send_to(b"from-a-2", addr_b)
        b.send_to(b"from-b-2", addr_a)
        time.sleep(0.002)
    try:
        msgs_a = {m for _, m in got_a}
        msgs_b = {m for _, m in got_b}
        assert b"from-b-2" in msgs_a
        assert b"from-a-2" in msgs_b
        # all traffic keyed by the peer's LISTEN address, not ephemeral ports
        assert all(addr == addr_b for addr, _ in got_a)
        assert all(addr == addr_a for addr, _ in got_b)
    finally:
        a.close()
        b.close()


def test_framing_survives_arbitrary_fragmentation():
    # datagrams must reassemble regardless of how TCP fragments the stream
    import random

    from bevy_ggrs_tpu.session.transport import _TcpConn

    rng = random.Random(5)
    msgs = [bytes([rng.randrange(256)]) * rng.randrange(1, 300)
            for _ in range(200)]
    stream = b"".join(
        TcpNonBlockingSocket._frame(m, TcpNonBlockingSocket._DATA)
        for m in msgs
    )
    sock_holder = TcpNonBlockingSocket(0, host="127.0.0.1")
    conn = _TcpConn.__new__(_TcpConn)
    conn.rbuf = bytearray()
    got = []
    i = 0
    while i < len(stream):
        n = rng.randrange(1, 97)  # arbitrary fragment sizes incl. tiny
        conn.rbuf.extend(stream[i:i + n])
        i += n
        got.extend(p for t, p in sock_holder._pop_frames(conn.rbuf)
                   if t == TcpNonBlockingSocket._DATA)
    sock_holder.close()
    assert got == msgs


def test_oversized_datagram_rejected():
    with pytest.raises(ValueError):
        TcpNonBlockingSocket._frame(b"x" * (1 << 20))
