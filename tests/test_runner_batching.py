"""Property test for the driver's core optimization: fused batch execution
of a request stream must be bit-identical to naive one-request-at-a-time
execution, across randomized valid save/load/advance scripts."""

import numpy as np
import pytest

from bevy_ggrs_tpu import GgrsRunner, SessionState
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session.requests import AdvanceRequest, LoadRequest, SaveCell, SaveRequest
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int
from bevy_ggrs_tpu.snapshot.ring import SnapshotRing


class ScriptSession:
    """Feeds pre-built request lists (one per tick)."""

    def __init__(self, scripts):
        self.scripts = scripts
        self.i = 0
        self.saved = {}

    def num_players(self):
        return 2

    def max_prediction(self):
        return 8

    def confirmed_frame(self):
        return -1  # never confirm: keeps every load target legal up to depth

    def current_state(self):
        return SessionState.RUNNING

    def local_player_handles(self):
        return []

    def add_local_input(self, handle, value):
        pass

    def advance_frame(self):
        reqs = self.scripts[self.i]
        self.i += 1
        return reqs

    def _on_cell_saved(self, frame, provider):
        self.saved.setdefault(frame, []).append(provider)


def gen_scripts(rng, ticks):
    """Random valid request scripts + the session shells to bind cells to."""
    sess_a, sess_b = ScriptSession([]), ScriptSession([])

    def build(sess):
        scripts = []
        frame = 0
        ring_frames = []  # mirror of what the driver's ring will hold
        depth = 10
        for _ in range(ticks):
            reqs = []
            n_ops = rng.integers(1, 6)
            for _ in range(n_ops):
                op = rng.integers(0, 10)
                if op < 2:  # save current frame
                    reqs.append(SaveRequest(frame, SaveCell(sess, frame)))
                    ring_frames = [f for f in ring_frames if f < frame]
                    ring_frames.append(frame)
                    ring_frames = ring_frames[-depth:]
                elif op < 4 and ring_frames:  # rollback to a stored frame
                    t = int(ring_frames[rng.integers(0, len(ring_frames))])
                    reqs.append(LoadRequest(t))
                    ring_frames = [f for f in ring_frames if f <= t]
                    frame = t
                else:  # advance with random inputs
                    inputs = rng.integers(0, 16, 2).astype(np.uint8)
                    status = np.zeros(2, np.int8)
                    reqs.append(AdvanceRequest(inputs, status))
                    frame += 1
            scripts.append(reqs)
        return scripts

    rng_state = rng.bit_generator.state
    sess_a.scripts = build(sess_a)
    rng.bit_generator.state = rng_state  # identical script for the twin
    sess_b.scripts = build(sess_b)
    return sess_a, sess_b


class NaiveRunner:
    """One device call per request — the semantic reference."""

    def __init__(self, app, session):
        self.app = app
        self.session = session
        self.world = app.init_state()
        self.cs = app.checksum_fn(self.world)
        self.ring = SnapshotRing(depth=10)
        self.frame = 0

    def tick(self):
        for r in self.session.advance_frame():
            if isinstance(r, SaveRequest):
                self.ring.push(r.frame, (self.world, self.cs))
                r.cell.save(r.frame, lambda cs=self.cs: checksum_to_int(cs))
            elif isinstance(r, LoadRequest):
                self.world, self.cs = self.ring.rollback(r.frame)
                self.frame = r.frame
            else:
                self.frame += 1
                self.world, self.cs = self.app.advance_fn(
                    self.world, r.inputs, r.status, self.frame
                )


@pytest.mark.parametrize("seed", range(4))
def test_batched_equals_naive(seed):
    rng = np.random.default_rng(400 + seed)
    sess_batched, sess_naive = gen_scripts(rng, ticks=12)

    app1 = box_game.make_app(num_players=2)
    batched = GgrsRunner(app1, sess_batched)
    app2 = box_game.make_app(num_players=2)
    naive = NaiveRunner(app2, sess_naive)

    for t in range(12):
        batched.tick()
        naive.tick()
        assert batched.frame == naive.frame, f"frame drift at tick {t}"
        assert checksum_to_int(batched._world_checksum) == checksum_to_int(
            naive.cs
        ), f"world checksum drift at tick {t}"
        assert batched.ring.frames() == naive.ring.frames(), f"ring drift at {t}"
    # every recorded save cell agrees
    for f in sess_naive.saved:
        a = [p() if callable(p) else p for p in sess_batched.saved[f]]
        b = [p() if callable(p) else p for p in sess_naive.saved[f]]
        assert a == b, f"saved checksums differ at frame {f}"
