"""BatchedRunner speculation: draft waves hedge predicted transitions into
the lanes the active bucket left idle, and a LoadRequest whose corrected run
was fully hedged is served from the branch cache — bit-identical to a plain
(speculation-less) batched run of the same script — while partial/unhedged
corrections fall back to the fused-load miss path.  Plus the strict
ValueError mode matrix (docs/architecture.md)."""

import numpy as np
import pytest

from bevy_ggrs_tpu import BatchedRunner
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.ops.speculation import SpeculationConfig, pad_candidates
from bevy_ggrs_tpu.session.requests import LoadRequest, SaveCell, SaveRequest
from tests.test_speculative_runner import ScriptedSession, adv


def _rollback_script(holder, corrected):
    """Tick 1: save(0) + predicted advance.  Tick 2: the real remote input
    arrives -> rollback to 0, corrected resim frame, live frame."""
    RIGHT = box_game.keys_to_input(right=True)
    predicted = [RIGHT, 0]
    actual = [RIGHT, corrected]

    def save(f):
        return SaveRequest(f, SaveCell(holder[0], f))

    tick1 = [save(0), adv(predicted, predicted=True)]
    tick2 = [LoadRequest(0), adv(actual), save(1),
             adv(actual, predicted=True)]
    return [tick1, tick2]


def _run_pair(speculation, corrected):
    """Two lobbies: lobby 0 runs the rollback script, lobby 1 stays idle —
    its lane is the spare capacity the draft wave fills."""
    app = box_game.make_app(num_players=2)
    s0 = ScriptedSession([])
    s0.script = _rollback_script([s0], corrected)
    s1 = ScriptedSession([[], []])
    br = BatchedRunner(app, [s0, s1], speculation=speculation)
    br.tick()
    br.tick()
    return br


def _spec(values, depth=4):
    return SpeculationConfig(
        candidates_fn=pad_candidates(2, [1], values), depth=depth
    )


def test_batched_cache_hit_matches_plain_run():
    corrected = box_game.keys_to_input(up=True)
    br_spec = _run_pair(_spec([corrected]), corrected)
    br_plain = _run_pair(None, corrected)
    st = br_spec.stats()["speculation"]
    assert st["hits"] == 1 and st["misses"] == 0
    assert st["draft_waves"] >= 1 and st["draft_lanes_filled"] >= 1
    assert st["cache_served_frames"] == 2  # corrected frame + live frame
    assert br_spec.frames == br_plain.frames == [2, 0]
    assert br_spec.lobby_checksum(0) == br_plain.lobby_checksum(0)
    np.testing.assert_array_equal(
        np.asarray(br_spec.lobby_world(0).comps["pos"]),
        np.asarray(br_plain.lobby_world(0).comps["pos"]),
    )
    # the re-saved frame-1 checksum (a LazySlice into the branch stack on the
    # hit path, a batch ref on the plain path) matches bit-exactly
    assert br_spec.sessions[0].saved[1]() == br_plain.sessions[0].saved[1]()


def test_batched_cache_miss_on_unhedged_input_falls_back():
    corrected = np.uint8(9)  # UP|RIGHT — not among the hedged values
    br_spec = _run_pair(_spec([0, 1, 2, 3]), corrected)
    br_plain = _run_pair(None, corrected)
    st = br_spec.stats()["speculation"]
    assert st["hits"] == 0 and st["misses"] >= 1
    assert br_spec.frames == br_plain.frames == [2, 0]
    assert br_spec.lobby_checksum(0) == br_plain.lobby_checksum(0)
    assert br_spec.sessions[0].saved[1]() == br_plain.sessions[0].saved[1]()


def test_batched_speculation_mode_matrix():
    app = box_game.make_app(num_players=2)
    spec = _spec([1])
    with pytest.raises(ValueError, match="packed=True"):
        BatchedRunner(app, [ScriptedSession([])], packed=False,
                      speculation=spec)
    with pytest.raises(ValueError, match="k_max"):
        BatchedRunner(app, [ScriptedSession([])], k_max=2,
                      speculation=_spec([1], depth=8))

    import dataclasses

    import jax.numpy as jnp

    from bevy_ggrs_tpu import App, QuantizeStrategy
    from bevy_ggrs_tpu.snapshot import active_mask, spawn

    qapp = App(num_players=1, capacity=4, input_shape=(),
               input_dtype=np.uint8)
    qapp.rollback_component("x", (), jnp.float32,
                            strategy=QuantizeStrategy(), checksum=True)

    def step(world, ctx):
        m = active_mask(world)
        return dataclasses.replace(world, comps={
            "x": jnp.where(m & world.has["x"], world.comps["x"] + 1.0,
                           world.comps["x"]),
        })

    def setup(world):
        world, _ = spawn(qapp.reg, world, {"x": 0.5})
        return world

    qapp.set_step(step)
    qapp.set_setup(setup)
    with pytest.raises(ValueError, match="identity snapshot"):
        BatchedRunner(
            qapp, [ScriptedSession([], num_players=1)],
            speculation=SpeculationConfig(
                candidates_fn=pad_candidates(1, [0], [1])
            ),
        )
