"""End-to-end i32 frame wraparound: a session running across the I32_MAX ->
I32_MIN boundary must keep simulating, rolling back, checksumming, and
pruning cleanly (the reference handles this in its snapshot ring,
mod.rs:159-163 + tests; its despawn path left it as a TODO, despawn.rs:134 —
here wrapping compares cover despawn too)."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu import App, GgrsRunner, SyncTestSession
from bevy_ggrs_tpu.snapshot import active_count, active_mask, despawn_where, spawn
from bevy_ggrs_tpu.utils.frames import I32_MAX, frame_add, wrap_i32


def make_app(despawn_at=None, retention=6):
    app = App(num_players=1, capacity=4, input_shape=(), input_dtype=np.uint8,
              retention=retention)
    app.rollback_component("counter", (), jnp.int32, checksum=True)

    def step(world, ctx):
        m = active_mask(world) & world.has["counter"]
        cnt = jnp.where(m, world.comps["counter"] + 1, world.comps["counter"])
        world = dataclasses.replace(world, comps={**world.comps, "counter": cnt})
        if despawn_at is not None:
            kill = m & (ctx.frame == jnp.int32(despawn_at))
            world = despawn_where(app.reg, world, kill, ctx.frame)
        return world

    def setup(world):
        world, _ = spawn(app.reg, world, {"counter": 0})
        return world

    app.set_step(step)
    app.set_setup(setup)
    return app


def run(app, start_frame, ticks, check_distance=3):
    session = SyncTestSession(
        num_players=1, input_shape=(), input_dtype=np.uint8,
        check_distance=check_distance, initial_frame=start_frame,
    )
    mismatches = []
    runner = GgrsRunner(app, session, on_mismatch=mismatches.append)
    for _ in range(ticks):
        runner.tick()
    return runner, mismatches


def test_session_crosses_i32_boundary():
    start = I32_MAX - 5
    runner, mismatches = run(make_app(), start, ticks=15)
    assert mismatches == []
    assert int(runner.world.comps["counter"][0]) == 15
    assert runner.frame == frame_add(start, 15)
    assert runner.frame < 0  # we really did wrap
    # ring stayed bounded and ordered under wrapping compares
    assert len(runner.ring) <= runner.ring.depth


def test_retention_guard_uses_session_rollback_window():
    # retention must cover the session's ACTUAL rollback window: for SyncTest
    # that is check_distance (not max_prediction, which defaults larger) —
    # cd=3 with retention=6 is valid, cd=7 with retention=6 is not
    import pytest

    app = make_app(retention=6)
    session = SyncTestSession(num_players=1, input_shape=(),
                              input_dtype=np.uint8, check_distance=7)
    with pytest.raises(ValueError, match="rollback window"):
        GgrsRunner(app, session)
    # P2P-shaped sessions validate against max_prediction
    assert SyncTestSession(num_players=1, check_distance=3).rollback_window() == 3


def test_despawn_across_boundary():
    # mark for despawn right before the wrap; retirement fires after it
    start = I32_MAX - 3
    despawn_at = wrap_i32(I32_MAX - 1)
    runner, mismatches = run(make_app(despawn_at=despawn_at, retention=6),
                             start, ticks=14)
    assert mismatches == []
    assert int(active_count(runner.world)) == 0
    assert not bool(runner.world.alive[0])  # freed on the far side of the wrap
