"""Fleet scheduler subsystem: wire protocol round-trips, checkpoint
chunking, lobby bit-determinism across checkpoint/restore, and the full
scheduler/worker control loop over loopback UDP — placement, wire-visible
admission rejects, live migration bit-equality, and failover from the last
confirmed checkpoint."""

import time

import numpy as np
import pytest

from bevy_ggrs_tpu import telemetry
from bevy_ggrs_tpu.fleet import (
    ChunkAssembler,
    FleetClient,
    FleetScheduler,
    FleetWorker,
    LobbySim,
    LobbySpec,
    checksum_hex,
    chunk_checkpoint,
    decode,
)
from bevy_ggrs_tpu.fleet import protocol as P

SMALL = dict(app="stress_soa", entities=32, seed=9)


# -- protocol ---------------------------------------------------------------


def test_protocol_roundtrips():
    cases = [
        (P.encode_register("w0", 7), P.T_REGISTER,
         lambda m: (m.a, m.total) == ("w0", 7)),
        (P.encode_heartbeat("w0", {"capacity": 2}), P.T_HEARTBEAT,
         lambda m: m.obj == {"capacity": 2}),
        (P.encode_place("l0", {"app": "stress_soa"}), P.T_PLACE,
         lambda m: m.obj["app"] == "stress_soa"),
        (P.encode_place_ok("l0", 42), P.T_PLACE_OK, lambda m: m.frame == 42),
        (P.encode_drain("l0", 99), P.T_DRAIN, lambda m: m.frame == 99),
        (P.encode_ckpt_ack("l0", 10), P.T_CKPT_ACK, lambda m: m.frame == 10),
        (P.encode_resume("l0", 10, {"seed": 1}), P.T_RESUME,
         lambda m: (m.frame, m.obj) == (10, {"seed": 1})),
        (P.encode_resume_ok("l0", 10), P.T_RESUME_OK, lambda m: m.frame == 10),
        (P.encode_drop("l0"), P.T_DROP, lambda m: m.a == "l0"),
        (P.encode_submit("l0", {"entities": 3}), P.T_SUBMIT,
         lambda m: m.obj == {"entities": 3}),
        (P.encode_submit_ok("l0", "w1"), P.T_SUBMIT_OK, lambda m: m.b == "w1"),
        (P.encode_reject("l0", "capacity"), P.T_REJECT,
         lambda m: m.b == "capacity"),
        (P.encode_done("l0", 600, "ab" * 8), P.T_DONE,
         lambda m: (m.frame, m.b) == (600, "ab" * 8)),
    ]
    for data, kind, check in cases:
        msg = decode(data)
        assert msg is not None and msg.kind == kind and msg.a[0] in "wl"
        assert check(msg), kind


def test_protocol_drops_malformed():
    assert decode(b"") is None
    assert decode(b"\x00\x01\x02") is None  # wrong magic
    # truncated register: header + type but no payload
    from bevy_ggrs_tpu.session.room import ROOM_MAGIC, _HDR

    assert decode(_HDR.pack(ROOM_MAGIC, P.T_REGISTER)) is None
    assert decode(_HDR.pack(ROOM_MAGIC, 250)) is None  # unknown type


def test_chunk_assembler_out_of_order_and_supersede():
    blob = bytes(range(256)) * 600  # > 4 chunks
    grams = chunk_checkpoint("l0", 5, blob)
    assert len(grams) > 2
    asm = ChunkAssembler()
    msgs = [decode(g) for g in grams]
    # out of order: all but the first, then the first
    for m in msgs[1:]:
        assert asm.offer(m) is None
    assert asm.offer(msgs[0]) == blob
    # a newer frame's chunks supersede a stale partial for the same lobby
    asm2 = ChunkAssembler()
    asm2.offer(msgs[0])
    newer = [decode(g) for g in chunk_checkpoint("l0", 6, blob)]
    for m in newer[:-1]:
        assert asm2.offer(m) is None
    assert asm2.offer(newer[-1]) == blob
    assert asm2.pending() == []


# -- lobby determinism ------------------------------------------------------


def test_lobby_checkpoint_restore_bit_equality():
    # the migration invariant: straight run == run split by a checkpoint/
    # restore at an awkward (non-chunk-aligned) frame, bit for bit
    spec = LobbySpec(lobby_id="l0", target_frames=90, **SMALL)
    control = LobbySim(spec)
    control.run_to(90)
    a = LobbySim(spec)
    a.run_to(37)
    b = LobbySim.restore(spec, a.checkpoint_bytes())
    assert b.frame == 37
    b.run_to(90)
    assert b.checksum() == control.checksum()


def test_lobby_external_input_tail_rides_checkpoint():
    # external-mode lobbies advance only through queued inputs; the
    # unsimulated tail must survive the checkpoint or the resumed lobby
    # would stall (or worse, desync on regenerated inputs)
    spec = LobbySpec(lobby_id="e0", app="box_game", target_frames=20,
                     input_mode="external")
    sim = LobbySim(spec)
    for f in range(1, 11):
        sim.submit_input(f, np.full(
            (sim.app.num_players, *sim.app.input_shape), f,
            sim.app.input_dtype,
        ))
    sim.step(6)
    assert sim.frame == 6
    restored = LobbySim.restore(spec, sim.checkpoint_bytes())
    assert restored.frame == 6
    assert sorted(restored.pending) == [7, 8, 9, 10]
    restored.step(20)
    assert restored.frame == 10  # only the shipped tail was simulatable
    # and the tail produced the same state as never migrating at all
    sim.step(20)
    assert sim.frame == 10
    assert restored.checksum() == sim.checksum()
    with pytest.raises(ValueError):
        restored.submit_input(3, np.zeros(
            (restored.app.num_players, *restored.app.input_shape),
            restored.app.input_dtype,
        ))


# -- scheduler/worker over loopback UDP ------------------------------------


def _pump(sched, workers, n=1, sleep=0.002):
    for _ in range(n):
        sched.poll()
        for w in workers:
            w.poll()
        time.sleep(sleep)


def _pump_until(sched, workers, cond, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        _pump(sched, workers)
        if cond():
            return True
    return False


@pytest.fixture()
def fleet():
    telemetry.reset()
    telemetry.enable()
    sched = FleetScheduler(worker_timeout_s=30.0)  # no spurious deaths
    workers = [
        FleetWorker(f"w{i}", sched.local_addr, capacity=2,
                    ckpt_every_frames=25)
        for i in range(2)
    ]
    for w in workers:
        w.register()
    assert _pump_until(sched, workers, lambda: len(sched.workers) == 2, 10)
    yield sched, workers
    for w in workers:
        w.close()
    sched.close()
    telemetry.disable()


def test_fleet_live_migration_bit_equality(fleet):
    sched, workers = fleet
    spec = LobbySpec(lobby_id="mig", target_frames=300, **SMALL)
    ok, wid = sched.submit(spec)
    assert ok
    rec = sched.lobbies["mig"]
    assert _pump_until(sched, workers, lambda: rec.state == "running", 10)
    assert sched.migrate("mig")
    assert _pump_until(
        sched, workers,
        lambda: rec.state == "running" and rec.worker_id != wid, 30,
    ), "migration did not complete"
    assert _pump_until(sched, workers, lambda: rec.state == "done", 30)
    control = LobbySim(spec)
    control.run_to(300)
    assert rec.final_checksum == checksum_hex(control.checksum())
    series = telemetry.summary()["metrics"]["lobby_migrations_total"]["series"]
    assert series.get("outcome=ok") == 1
    hist = telemetry.summary()["metrics"].get("migration_downtime_ms")
    assert hist is not None  # downtime was observed


def test_fleet_admission_reject_is_wire_visible(fleet):
    sched, workers = fleet
    for i in range(4):  # 2 workers x capacity 2
        ok, _ = sched.submit(
            LobbySpec(lobby_id=f"fill{i}", target_frames=10_000, **SMALL)
        )
        assert ok
    # in-process verdict
    ok, reason = sched.submit(LobbySpec(lobby_id="over", **SMALL))
    assert not ok and reason == "capacity"
    # wire verdict: a FleetClient must receive the REJECT datagram
    import threading

    cli = FleetClient(sched.local_addr)
    stop = threading.Event()

    def pumper():
        while not stop.is_set():
            _pump(sched, workers)

    t = threading.Thread(target=pumper)
    t.start()
    try:
        got = cli.submit(LobbySpec(lobby_id="over2", **SMALL), timeout_s=10)
    finally:
        stop.set()
        t.join()
        cli.close()
    assert got is None and cli.last_reject == "capacity"
    series = telemetry.summary()["metrics"]["admission_rejects_total"]["series"]
    assert series.get("reason=capacity", 0) >= 2


def test_fleet_failover_from_confirmed_checkpoint(fleet):
    sched, workers = fleet
    # long enough that the survivor's restore-compile stall cannot get IT
    # declared dead, short enough that the test stays snappy
    sched.worker_timeout_s = 2.0
    spec = LobbySpec(lobby_id="vic", target_frames=1200, **SMALL)
    ok, _ = sched.submit(spec)
    assert ok
    rec = sched.lobbies["vic"]
    # run until a confirmed checkpoint is in hand but the game is not over
    assert _pump_until(
        sched, workers,
        lambda: rec.ckpt_blob is not None and rec.state == "running", 20,
    )
    assert rec.frame < 1200
    victim = next(w for w in workers if w.worker_id == rec.worker_id)
    survivor = next(w for w in workers if w is not victim)
    victim.close()
    assert _pump_until(sched, [survivor], lambda: rec.state == "done", 60), \
        f"no failover completion (state={rec.state})"
    control = LobbySim(spec)
    control.run_to(1200)
    assert rec.final_checksum == checksum_hex(control.checksum())
    series = telemetry.summary()["metrics"]["lobby_migrations_total"]["series"]
    assert series.get("outcome=failover") == 1


def test_scheduler_placement_is_bytes_and_slot_aware():
    # greedy placement prefers the emptier worker; memory budget rejects
    # with the wire-visible "memory" reason before slots run out
    telemetry.reset()
    sched = FleetScheduler(worker_timeout_s=30.0,
                           mem_budget_bytes=40 * 1024)
    w = FleetWorker("w0", sched.local_addr, capacity=8)
    w.register()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not sched.workers:
            sched.poll()
            w.poll()
            time.sleep(0.002)
        assert "w0" in sched.workers
        # stress_soa(32 entities): 6 float32 cols + bookkeeping ~ a few KB
        ok, _ = sched.submit(LobbySpec(lobby_id="a", **SMALL))
        assert ok
        big = LobbySpec(lobby_id="b", app="stress_soa", entities=4096, seed=1)
        ok, reason = sched.submit(big)
        assert not ok and reason == "memory"
    finally:
        w.close()
        sched.close()


# -- assembler fuzz ---------------------------------------------------------


def test_chunk_assembler_fuzz_reorder_dup_interleave():
    """Adversarial UDP delivery: random reorder, duplicated chunks, and two
    frames' streams interleaved for one lobby.  The invariant is bit-exact
    or nothing — every completion must equal the true blob for exactly the
    frame whose chunk completed it, and keys never mix frames."""
    rng = np.random.default_rng(1234)
    blobs = {
        5: bytes(rng.integers(0, 256, size=90_000, dtype=np.uint8)),
        6: bytes(rng.integers(0, 256, size=70_000, dtype=np.uint8)),
    }
    msgs = {
        f: [decode(g) for g in chunk_checkpoint("l0", f, blob)]
        for f, blob in blobs.items()
    }
    for trial in range(20):
        stream = [m for f in blobs for m in msgs[f]]
        # duplicate a few chunks, then shuffle the whole delivery order
        dups = rng.choice(len(stream), size=4, replace=False)
        stream += [stream[i] for i in dups]
        rng.shuffle(stream)
        asm = ChunkAssembler()
        done = set()
        for m in stream:
            out = asm.offer(m)
            if out is not None:
                # every completion is bit-exact for the completing frame (a
                # re-delivered full set may complete again — that is the
                # re-ship-until-acked contract, and it must stay bit-exact)
                assert out == blobs[m.frame], trial
                done.add(m.frame)
        # frame 6 always completes (nothing supersedes it); frame 5 may
        # have been legitimately dropped by a later frame-6 arrival
        assert 6 in done, trial
        assert {k[0] for k in asm.pending()} <= {"l0"}


def test_chunk_assembler_truncated_then_completed():
    blob = bytes(range(256)) * 300
    msgs = [decode(g) for g in chunk_checkpoint("l0", 9, blob)]
    assert len(msgs) >= 3
    asm = ChunkAssembler()
    for m in msgs[:-1]:  # truncated delivery: hold the last chunk back
        assert asm.offer(m) is None
    assert asm.pending() == [("l0", 9)]
    assert asm.offer(msgs[-1]) == blob  # the retry lands: bit-exact join
    assert asm.pending() == []


# -- malformed datagram accounting ------------------------------------------


def test_malformed_datagrams_counted_and_logged_once_per_peer(caplog):
    import logging
    import socket

    telemetry.reset()
    telemetry.enable()
    P._malformed_peers.clear()
    sched = FleetScheduler(worker_timeout_s=30.0)
    w = FleetWorker("w0", sched.local_addr, capacity=1)
    src = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        counter = telemetry.registry().counter(
            "fleet_malformed_datagrams_total", "")
        with caplog.at_level(logging.WARNING,
                             logger="bevy_ggrs_tpu.fleet.protocol"):
            for _ in range(3):
                src.sendto(b"\x00garbage", sched.local_addr)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and counter.value() < 3:
                sched.poll()
                time.sleep(0.002)
            assert counter.value() == 3
            # the worker's drain counts through the same funnel
            src.sendto(b"\xff" * 5, w.local_addr)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and counter.value() < 4:
                w.poll()
                time.sleep(0.002)
            assert counter.value() == 4
        warnings = [r for r in caplog.records
                    if "malformed" in r.getMessage()]
        # 4 dropped datagrams, ONE log line per peer (same source socket)
        assert len(warnings) == 1, [r.getMessage() for r in warnings]
    finally:
        src.close()
        w.close()
        sched.close()
        telemetry.disable()
        P._malformed_peers.clear()
