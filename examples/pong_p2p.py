#!/usr/bin/env python
"""Pong over P2P (or synctest with --synctest): a complete game on the
framework, with optional speculative rollback hedging (--speculate).

    python examples/pong_p2p.py --synctest --frames 600
    python examples/pong_p2p.py --local-port 8081 --players local 127.0.0.1:8082
    python examples/pong_p2p.py --local-port 8082 --players 127.0.0.1:8081 local
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

import numpy as np

from bevy_ggrs_tpu import (
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    SpeculationConfig,
    UdpNonBlockingSocket,
    pad_candidates,
)
from bevy_ggrs_tpu.models import pong


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--synctest", action="store_true")
    ap.add_argument("--check-distance", type=int, default=5)
    ap.add_argument("--local-port", type=int, default=0)
    ap.add_argument("--players", nargs="*", default=["local", "local"])
    ap.add_argument("--frames", type=int, default=1200)
    ap.add_argument("--speculate", action="store_true",
                    help="hedge predicted remote inputs (branch cache)")
    ap.add_argument("--canonical", action="store_true",
                    help="bit-determinism program (docs/determinism.md)")
    args = ap.parse_args()

    # --canonical: bit-determinism program (docs/determinism.md); with
    # --speculate the program gains fixed hedge lanes (canonical_branches)
    app = pong.make_app(canonical_depth=10 if args.canonical else None)
    if args.speculate:
        if not args.canonical:
            app.canonical_depth = 10
        app.canonical_branches = 4  # lane 0 real + 3 hedge candidates
    b = SessionBuilder.for_app(app).with_input_delay(1)

    def read_inputs(handles):
        # demo AI: each paddle chases the ball
        pos = runner.read_components(["pos", "kind"])
        kind = pos["kind"]
        balls = (kind == pong.K_BALL) & pos["__active__"]
        ball_y = float(pos["pos"][balls, 1][0]) if balls.any() else 0.0
        out = {}
        for h in handles:
            my_y = float(pos["pos"][h, 1])
            if ball_y > my_y + 0.2:
                out[h] = np.uint8(pong.UP)
            elif ball_y < my_y - 0.2:
                out[h] = np.uint8(pong.DOWN)
            else:
                out[h] = np.uint8(0)
        return out

    speculation = (
        SpeculationConfig(candidates_fn=pad_candidates(2, [1], [0, 1, 2]), depth=4)
        if args.speculate
        else None
    )

    if args.synctest or all(p == "local" for p in args.players):
        session = b.with_check_distance(args.check_distance).start_synctest_session()
        runner = GgrsRunner(
            app, session, read_inputs=read_inputs,
            on_mismatch=lambda e: (_ for _ in ()).throw(SystemExit(f"MISMATCH: {e}")),
        )
        for _ in range(args.frames):
            runner.tick()
            if pong.winner(runner.world) >= 0:
                break
    else:
        sock = UdpNonBlockingSocket(args.local_port)
        for handle, spec in enumerate(args.players):
            if spec == "local":
                b.add_player(PlayerType.LOCAL, handle)
            else:
                host, port = spec.rsplit(":", 1)
                b.add_player(PlayerType.REMOTE, handle, (host, int(port)))
        session = b.start_p2p_session(sock)
        runner = GgrsRunner(app, session, read_inputs=read_inputs,
                            speculation=speculation,
                            on_event=lambda e: print(f"event: {e}"))
        last = time.perf_counter()
        while runner.frame < args.frames and pong.winner(runner.world) < 0:
            now = time.perf_counter()
            runner.update(now - last)
            last = now
            time.sleep(0.001)

    score = np.asarray(runner.world.res["score"])
    w = pong.winner(runner.world)
    print(f"frame {runner.frame}: score {score[0]}-{score[1]}"
          + (f" — player {w} wins!" if w >= 0 else ""))
    print(f"stats: {runner.stats()}")


if __name__ == "__main__":
    main()
