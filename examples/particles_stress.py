#!/usr/bin/env python
"""particles stress CLI — port of
/root/reference/examples/stress_tests/particles.rs: P2P (or synctest)
session spawning --rate particles/frame with rollback-able seeded RNG and
full-state checksums; desync panic/continue flags."""

import argparse
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

from bevy_ggrs_tpu import (
    DesyncDetection,
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    UdpNonBlockingSocket,
)
from bevy_ggrs_tpu.models import particles
from bevy_ggrs_tpu.snapshot import active_count


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rate", type=int, default=100, help="particles per frame")
    ap.add_argument("--ttl", type=int, default=120)
    ap.add_argument("--synctest", action="store_true")
    ap.add_argument("--check-distance", type=int, default=7)
    ap.add_argument("--local-port", type=int, default=0)
    ap.add_argument("--players", nargs="*", default=["local", "local"])
    ap.add_argument("--frames", type=int, default=600)
    ap.add_argument("--continue-after-desync", action="store_true")
    ap.add_argument("--quantize", action="store_true",
                    help="store float snapshots as bf16 (the strategy A/B "
                         "knob; the reference's --reflect analog)")
    args = ap.parse_args()

    app = particles.make_app(rate=args.rate, ttl=args.ttl,
                             num_players=max(len(args.players), 1),
                             quantize=args.quantize)
    b = SessionBuilder.for_app(app).with_num_players(app.num_players)

    def on_event(e):
        print(f"event: {e}")
        from bevy_ggrs_tpu.session.events import DesyncDetected

        if isinstance(e, DesyncDetected) and not args.continue_after_desync:
            raise SystemExit(f"DESYNC: {e}")

    if args.synctest or all(p == "local" for p in args.players):
        session = b.with_check_distance(args.check_distance).start_synctest_session()
        runner = GgrsRunner(app, session,
                            on_mismatch=lambda e: on_event(e))
    else:
        sock = UdpNonBlockingSocket(args.local_port)
        b.with_desync_detection_mode(DesyncDetection.on(10)).with_input_delay(2)
        for handle, spec in enumerate(args.players):
            if spec == "local":
                b.add_player(PlayerType.LOCAL, handle)
            else:
                host, port = spec.rsplit(":", 1)
                b.add_player(PlayerType.REMOTE, handle, (host, int(port)))
        session = b.start_p2p_session(sock)
        runner = GgrsRunner(app, session, on_event=on_event)

    t0 = time.perf_counter()
    last = t0
    for _ in range(args.frames):
        now = time.perf_counter()
        runner.update(max(now - last, 1.0 / app.fps))
        last = now
    dt = time.perf_counter() - t0
    n = int(active_count(runner.world))
    print(f"{runner.frame} frames, {n} live particles, {dt:.2f}s "
          f"({runner.frame / dt:.0f} fps incl. resim)")


if __name__ == "__main__":
    main()
